"""Host-side tokenizers.

Parity with the reference's three tokenizer paths (build_components.py:265-300):
  - GPT-2 BPE via tiktoken                  (build_components.py:278)
  - LLaMA-2 sentencepiece wrapper           (Models/Llama/Llama2.py:12-28)
  - LLaMA-3 tiktoken BPE over Meta's
    tokenizer.model + reserved specials     (Models/Llama/Llama3.py:14-51)

Tokenization never touches the device; these stay plain Python. All wrappers
expose the same small interface: ``encode(text, allowed_special=...)``,
``decode(ids)``, ``.vocab_size``, ``.eos_id``.

Because training environments may be offline, ``build_tokenizer`` degrades
gracefully: if a tokenizer's assets are unavailable it raises a clear error,
and a deterministic ``ByteTokenizer`` is provided for tests/smoke runs.
"""

from __future__ import annotations

import os
from typing import Iterable, List, Optional, Sequence


class ByteTokenizer:
    """Deterministic offline tokenizer: raw UTF-8 bytes + special tokens.

    Used by tests and `--debug` smoke runs so the full pipeline works with
    zero network egress. Ids 0-255 are bytes; specials get ids >= 256.
    """

    def __init__(self, specials: Sequence[str] = ("<|endoftext|>",)):
        self.specials = {s: 256 + i for i, s in enumerate(specials)}
        self._specials_by_id = {v: k for k, v in self.specials.items()}
        self.vocab_size = 256 + len(self.specials)
        self.eos_id = self.specials.get("<|endoftext|>", 256)

    def encode(self, text: str, allowed_special: Optional[Iterable[str]] = None
               ) -> List[int]:
        """Bulk UTF-8 encode with allowed specials spliced in.

        Segments on the allowed special tokens with one regex pass and
        bulk-encodes the text between them — the original per-character
        Python loop took minutes per MB, which stalled real corpus runs
        (100MB+ shards) in the step-count pre-pass."""
        import re

        allowed = set(allowed_special or self.specials)
        pattern = "|".join(re.escape(s) for s in self.specials
                           if s in allowed)
        if not pattern:
            return list(text.encode("utf-8"))
        out: List[int] = []
        pos = 0
        for m in re.finditer(pattern, text):
            out.extend(text[pos:m.start()].encode("utf-8"))
            out.append(self.specials[m.group(0)])
            pos = m.end()
        out.extend(text[pos:].encode("utf-8"))
        return out

    def decode(self, ids: Sequence[int]) -> str:
        parts: List[bytes] = []
        for t in ids:
            t = int(t)
            if t in self._specials_by_id:
                parts.append(self._specials_by_id[t].encode("utf-8"))
            elif 0 <= t < 256:
                parts.append(bytes([t]))
            # ids outside the byte+special range (e.g. sampled from an
            # untrained model with a larger vocab) decode to nothing
        return b"".join(parts).decode("utf-8", errors="replace")


class GPT2Tokenizer:
    """GPT-2 BPE via tiktoken (reference build_components.py:278)."""

    def __init__(self):
        import tiktoken

        self._enc = tiktoken.get_encoding("gpt2")
        self.vocab_size = self._enc.n_vocab
        self.eos_id = self._enc.eot_token            # 50256

    def encode(self, text: str, allowed_special: Optional[Iterable[str]] = None
               ) -> List[int]:
        allowed = set(allowed_special or {"<|endoftext|>"})
        return self._enc.encode(text, allowed_special=allowed)

    def decode(self, ids: Sequence[int]) -> str:
        return self._enc.decode(list(int(i) for i in ids))


class Llama2Tokenizer:
    """SentencePiece wrapper (reference Models/Llama/Llama2.py:12-28)."""

    def __init__(self, model_path: str):
        import sentencepiece as spm

        if not os.path.exists(model_path):
            raise FileNotFoundError(
                f"LLaMA-2 sentencepiece model not found at {model_path}")
        self._sp = spm.SentencePieceProcessor(model_file=model_path)
        self.vocab_size = self._sp.vocab_size()
        self.eos_id = self._sp.eos_id()              # 2

    def encode(self, text: str, allowed_special: Optional[Iterable[str]] = None
               ) -> List[int]:
        return self._sp.encode(text)

    def decode(self, ids: Sequence[int]) -> str:
        return self._sp.decode(list(int(i) for i in ids))


LLAMA3_SPLIT_PATTERN = (
    r"(?i:'s|'t|'re|'ve|'m|'ll|'d)|[^\r\n\p{L}\p{N}]?\p{L}+|\p{N}{1,3}|"
    r" ?[^\s\p{L}\p{N}]+[\r\n]*|\s*[\r\n]+|\s+(?!\S)|\s+"
)


class Llama3Tokenizer:
    """tiktoken BPE over Meta's ``tokenizer.model`` + 256 reserved specials
    (reference Models/Llama/Llama3.py:14-51)."""

    def __init__(self, model_path: str):
        import tiktoken
        from tiktoken.load import load_tiktoken_bpe

        if not os.path.exists(model_path):
            raise FileNotFoundError(
                f"LLaMA-3 tokenizer.model not found at {model_path}")
        mergeable = load_tiktoken_bpe(model_path)
        num_base = len(mergeable)               # 128000 for Meta's model
        # Meta's exact special-token id layout: 256 specials fill ids
        # num_base .. num_base+255, with the named ones interleaved among
        # the reserved slots (so all ids stay < vocab_size = 128256).
        ordered = [
            "<|begin_of_text|>",                # 128000
            "<|end_of_text|>",                  # 128001
            "<|reserved_special_token_0|>",
            "<|reserved_special_token_1|>",
            "<|reserved_special_token_2|>",
            "<|reserved_special_token_3|>",
            "<|start_header_id|>",              # 128006
            "<|end_header_id|>",                # 128007
            "<|reserved_special_token_4|>",
            "<|eot_id|>",                       # 128009
        ] + [f"<|reserved_special_token_{i}|>" for i in range(5, 251)]
        specials = {tok: num_base + i for i, tok in enumerate(ordered)}
        self._enc = tiktoken.Encoding(
            name=os.path.basename(model_path),
            pat_str=LLAMA3_SPLIT_PATTERN,
            mergeable_ranks=mergeable,
            special_tokens=specials,
        )
        self.vocab_size = 128_256
        self.eos_id = specials["<|end_of_text|>"]    # 128001

    def encode(self, text: str, bos: bool = False, eos: bool = False,
               allowed_special: Optional[Iterable[str]] = None) -> List[int]:
        ids = self._enc.encode(
            text, allowed_special=set(allowed_special or
                                      self._enc.special_tokens_set))
        if bos:
            ids = [self._enc.encode_single_token("<|begin_of_text|>")] + ids
        if eos:
            ids = ids + [self.eos_id]
        return ids

    def decode(self, ids: Sequence[int]) -> str:
        return self._enc.decode(list(int(i) for i in ids))


# (repo_id, filename) for each LLaMA family's tokenizer asset — the same
# repos the reference pulls from behind its rank barriers
# (build_components.py:265-300); llama3* keep Meta's original BPE file.
HF_TOKENIZER_ASSETS = {
    "llama2": ("meta-llama/Llama-2-7b", "tokenizer.model"),
    "llama3": ("meta-llama/Meta-Llama-3-8B", "original/tokenizer.model"),
    "llama3_1": ("meta-llama/Llama-3.1-8B", "original/tokenizer.model"),
    "llama3_2": ("meta-llama/Llama-3.2-1B", "original/tokenizer.model"),
}


def fetch_tokenizer_asset(model: str,
                          cache_dir: str = "hf_checkpoints") -> str:
    """Download (cache-if-exists) the tokenizer asset for a LLaMA family.

    Local-only side effects — on multi-host runs the coordinator calls this
    BEFORE the shared barrier and every process re-resolves from the
    populated cache afterwards (same dance as weights/fetch.py's
    ``download_hf_weights``).
    """
    if model not in HF_TOKENIZER_ASSETS:
        raise ValueError(f"No tokenizer asset mapping for model '{model}'")
    repo_id, filename = HF_TOKENIZER_ASSETS[model]
    from huggingface_hub import hf_hub_download

    from building_llm_from_scratch_tpu.utils.retry import with_retries

    # bounded retry (3 attempts, backoff + jitter): transient hub failures
    # recover; 404/gated errors re-raise immediately (utils/retry.py)
    return with_retries(
        lambda: hf_hub_download(repo_id=repo_id, filename=filename,
                                cache_dir=cache_dir),
        describe=f"download {repo_id}/{filename}")


def build_tokenizer(model: str, tokenizer_path: Optional[str] = None,
                    fallback_byte: bool = False,
                    cache_dir: str = "hf_checkpoints"):
    """Tokenizer factory (reference build_components.py:265-300).

    LLaMA tokenizer assets auto-download from HF hub when ``tokenizer_path``
    is not given (cache-if-exists), so ``--model llama3_2 --load_weights``
    runs as one command the way the reference does. ``tokenizer_path``
    remains the offline override; ``fallback_byte=True`` (debug/smoke runs)
    degrades to the ByteTokenizer on any failure.
    """
    if fallback_byte and model != "GPT2" and tokenizer_path is None:
        # debug/smoke runs must not touch the network at all: without this
        # short-circuit an offline --byte_tokenizer run would block on hub
        # DNS/connect timeouts before degrading
        return ByteTokenizer()

    def _asset_path() -> str:
        if tokenizer_path is not None:
            return tokenizer_path
        try:
            return fetch_tokenizer_asset(model, cache_dir=cache_dir)
        except Exception as e:
            raise FileNotFoundError(
                f"{model} tokenizer assets unavailable: hub download "
                f"failed ({type(e).__name__}); pass --tokenizer_path to a "
                "local tokenizer.model for offline runs") from e

    try:
        if model == "GPT2":
            return GPT2Tokenizer()
        if model == "llama2":
            return Llama2Tokenizer(_asset_path())
        if model in ("llama3", "llama3_1", "llama3_2"):
            return Llama3Tokenizer(_asset_path())
    except Exception:
        if fallback_byte:
            return ByteTokenizer()
        raise
    raise ValueError(f"Unknown model '{model}'")
