"""Host-side data pipeline (reference: datautils/)."""

from building_llm_from_scratch_tpu.data.tokenizers import (
    ByteTokenizer,
    GPT2Tokenizer,
    Llama2Tokenizer,
    Llama3Tokenizer,
    build_tokenizer,
)
from building_llm_from_scratch_tpu.data.pretrain import (
    PretrainDataset,
    PretrainLoader,
    TokenCache,
    make_windows,
)
from building_llm_from_scratch_tpu.data.prefetch import Prefetcher
from building_llm_from_scratch_tpu.data.instruct import (
    InstructionDataset,
    InstructLoader,
    collate_batch,
    format_input,
    format_input_phi,
)

__all__ = [
    "ByteTokenizer",
    "GPT2Tokenizer",
    "Llama2Tokenizer",
    "Llama3Tokenizer",
    "build_tokenizer",
    "Prefetcher",
    "PretrainDataset",
    "PretrainLoader",
    "TokenCache",
    "make_windows",
    "InstructionDataset",
    "InstructLoader",
    "collate_batch",
    "format_input",
    "format_input_phi",
]
