"""Host/device overlap: a bounded background-thread batch prefetcher.

The synchronous step loop serializes three phases per step: build the next
numpy batch on the host, transfer it host->device, then dispatch the jitted
step. The device idles through the first two. ``Prefetcher`` moves them off
the critical path: a worker thread pulls batches from the underlying
iterator, performs the sharded device placement itself (``place_fn`` — the
trainer passes ``MeshPlan.shard_batch`` /
``jax.make_array_from_process_local_data`` wiring), and keeps up to
``depth`` already-placed batches in a bounded queue. With ``depth >= 2``
the H2D DMA for batch k+1 overlaps the device step for batch k and the
consumer's ``data_wait`` collapses to queue-pop time.

Contracts (the trainer and the resume machinery depend on all of them):

  - **Exact order.** One worker, one FIFO queue: batches arrive in the
    source iterator's order, bit-identical to the synchronous path. The
    PR-1 data-cursor resume therefore keeps working — callers apply the
    skip-count fast-forward (``itertools.islice``) BEFORE wrapping the
    iterator, so the queue only ever fills with batches that will train.
  - **No leaked threads.** ``close()`` (idempotent, also the context-
    manager exit) signals the worker, drains the queue so a blocked
    ``put`` wakes, and joins. The trainer closes in a ``finally`` so a
    GracefulStopper stop, a watchdog halt, or any exception unwinding the
    epoch tears the worker down.
  - **Exceptions propagate.** A worker-side exception (tokenizer error,
    OOM in placement, ...) is captured and re-raised at the consumer's
    next ``__next__`` — never swallowed, never hung.
  - **Telemetry.** ``stalls`` counts pops that found the queue empty while
    the worker was still producing (the genuinely host-starved case;
    the initial fill is excluded), ``fill_sum``/``pops`` give the mean
    queue depth — the trainer turns counter deltas into the per-window
    ``prefetch_stall`` / ``prefetch_fill_ratio`` metrics fields.

``place_in_worker=False`` keeps the queue host-side and applies
``place_fn`` at pop time instead: the forced-host-platform CPU backend
CHECK-aborts when multi-device placement races in-flight donated steps
(see ``Trainer._flush_metrics``'s round-4 note), so the trainer only
places from the worker thread when the backend is a real accelerator or
the run is single-device. The host-side work (read/tokenize/window/
shuffle/collate) still overlaps either way.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Iterable, Iterator, Optional

from building_llm_from_scratch_tpu.utils.logging import setup_logger

logger = setup_logger(__name__)

#: Queue sentinel: the worker finished the source iterator (or died — then
#: ``_exc`` is set). A plain object() so user batches can never collide.
_DONE = object()


class Prefetcher:
    """Iterate ``source`` through a bounded background-thread queue.

    Parameters
    ----------
    source:
        Any iterable of batches (numpy tuples, dicts, ...).
    depth:
        Max batches in flight (queue capacity), >= 1. Depth 2 is classic
        double buffering; 3 adds slack for jittery per-batch host time.
    place_fn:
        Optional transform applied exactly once per batch (the trainer's
        device placement). Where it runs is ``place_in_worker``.
    place_in_worker:
        True (default): ``place_fn`` runs on the worker thread, so the
        queue holds already-placed device batches and the H2D transfer
        overlaps the device step. False: the queue holds host batches and
        ``place_fn`` runs at pop time (see module docstring).
    name:
        Thread-name suffix for stack dumps (obs/stall.py flight recorder).
    """

    def __init__(self, source: Iterable[Any], depth: int = 2, *,
                 place_fn: Optional[Callable[[Any], Any]] = None,
                 place_in_worker: bool = True, name: str = "prefetch"):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self.depth = depth
        self._place_fn = place_fn
        self._place_in_worker = place_in_worker
        self._src = iter(source)
        self._q: "queue.Queue[Any]" = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        # worker -> consumer handshake state: _exc is written by the
        # worker and read by the consumer after the _DONE sentinel; the
        # lock makes the pair safe against a concurrent close() too
        # (previously close() was check-then-set racy from a second
        # thread — caught by graft-lint GL031 once annotated)
        self._lock = threading.Lock()
        self._exc: Optional[BaseException] = None   # guarded-by: _lock
        self._closed = False                        # guarded-by: _lock
        self._finished = False
        # telemetry counters (read by the trainer at logging cadence)
        self.stalls = 0
        self.pops = 0
        self.fill_sum = 0
        self._thread = threading.Thread(target=self._fill, daemon=True,
                                        name=f"{name}-worker")
        self._thread.start()

    # -- worker --------------------------------------------------------

    def _put(self, item: Any) -> bool:
        """Bounded put that stays responsive to ``close()``: a worker
        blocked forever in ``Queue.put`` on a full queue could never be
        joined. Returns False when cancelled."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _fill(self) -> None:
        try:
            for item in self._src:
                if self._stop.is_set():
                    return
                if self._place_fn is not None and self._place_in_worker:
                    item = self._place_fn(item)
                if not self._put(item):
                    return
        except BaseException as e:          # noqa: BLE001 — re-raised at pop
            with self._lock:
                self._exc = e
        finally:
            # always terminate the stream: the consumer's blocking get()
            # must wake whether the source ended, raised, or was cancelled
            self._put(_DONE)

    # -- consumer ------------------------------------------------------

    def __iter__(self) -> Iterator[Any]:
        return self

    def __next__(self) -> Any:
        if self._finished:
            raise StopIteration
        qsize = self._q.qsize()
        # a pop that finds the queue empty while the worker is still
        # producing a real batch = the host can't keep up (prefetch_stall).
        # Two exclusions: the FIRST pop (initial fill is startup latency,
        # not steady-state starvation) and a pop whose wait turns out to be
        # for the end-of-stream sentinel (nothing was starved — the source
        # is simply done, and counting it would make the final pop of every
        # healthy epoch race a spurious stall).
        would_stall = qsize == 0 and self.pops > 0
        item = self._q.get()
        if item is _DONE:
            self._finished = True
            self._thread.join(timeout=5.0)
            with self._lock:
                exc, self._exc = self._exc, None
            if exc is not None:
                raise exc
            raise StopIteration
        if would_stall:
            self.stalls += 1
        self.fill_sum += qsize
        self.pops += 1
        if self._place_fn is not None and not self._place_in_worker:
            item = self._place_fn(item)
        return item

    # -- lifecycle / introspection ------------------------------------

    def qsize(self) -> int:
        return self._q.qsize()

    @property
    def alive(self) -> bool:
        return self._thread.is_alive()

    def counters(self) -> dict:
        """Snapshot of the telemetry counters (trainer computes window
        deltas between snapshots)."""
        return {"stalls": self.stalls, "pops": self.pops,
                "fill_sum": self.fill_sum}

    def close(self) -> None:
        """Cancel and join the worker. Idempotent — atomically so: two
        threads racing close() (epoch teardown vs an unwinding caller)
        elect exactly one to drain and join. Safe mid-iteration
        (preemption stop, watchdog halt, exception unwind)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self._finished = True
        self._stop.set()
        # drain so a worker blocked in put() (full queue) cycles its
        # timeout and sees the stop flag promptly
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        self._thread.join(timeout=10.0)
        if self._thread.is_alive():          # pragma: no cover — deadlock aid
            logger.warning("Prefetch worker did not join within 10s; "
                           "leaving daemon thread to die with the process.")

    def __enter__(self) -> "Prefetcher":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False
