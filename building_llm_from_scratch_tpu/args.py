"""CLI flag surface + cross-flag validation.

Parity with the reference ``args.py`` (args.py:38-99 flags, :8-35 checks),
re-targeted at TPU hardware:

  - ``--run_type single_chip|multi_chip`` replaces single_gpu/multi_gpu;
  - the three wrapper flags (--use_fsdp / --use_zero_opt and their
    exclusivity check, args.py:25-32) become ONE ``--shard_mode``
    {dp,fsdp,zero1,tp,tp_fsdp} — mutually exclusive by construction;
  - ``--mixed_precision`` accepts the full reference policy table
    (datautils/mixed_precision.py:41-46) incl. bf16_hybrid;
  - TPU/offline additions: --tokenizer_path, --weights_dir,
    --byte_tokenizer, --tp, --target_context_length, --resume_from,
    --profile, --seed;
  - fault tolerance (training/resilience.py): --resume auto|off|<dir>,
    --keep_ckpts, --watchdog/--loss_spike_factor/--watchdog_window;
  - observability (obs/): --metrics_jsonl structured-telemetry sink,
    --log_every metrics cadence decoupled from eval, --stall_timeout
    per-host hung-step flight recorder, --compile_cache_dir persistent
    XLA compilation cache (with hit/miss telemetry).
"""

from __future__ import annotations

import argparse
import os
import warnings

from building_llm_from_scratch_tpu.configs import MODEL_PARAMS_MAPPING
from building_llm_from_scratch_tpu.parallel.sharding import SHARD_MODES


def check_dependencies(need_hf: bool = False) -> None:
    """Import-probe for required libraries (reference req_libraries.py:6-47).

    Core deps (jax/optax/numpy) raise with install hints; asset-fetch deps
    (tiktoken/huggingface_hub/safetensors) only when the run needs them.
    """
    core = {"jax": "jax", "optax": "optax", "numpy": "numpy"}
    fetch = {"huggingface_hub": "huggingface_hub"}
    for mod, pkg in core.items():
        try:
            __import__(mod)
        except ImportError:
            raise ImportError(
                f"Please install '{pkg}' with `pip install {pkg}`")
    if need_hf:
        for mod, pkg in fetch.items():
            try:
                __import__(mod)
            except ImportError:
                raise ImportError(
                    f"Please install '{pkg}' with `pip install {pkg}` "
                    "(needed for --load_weights)")


def perform_checks(args) -> None:
    """Cross-flag validation (reference args.py:8-35)."""
    if not args.warnings:
        warnings.filterwarnings("ignore")

    # serve mode decodes and finetune_fleet reads per-job record files
    # (--fleet_jobs) — only the classic train pipeline discovers its
    # corpus from --data_dir
    if args.mode == "train" and not os.path.exists(args.data_dir):
        raise FileNotFoundError(
            f"Data directory '{args.data_dir}' does not exist.")

    if args.mode == "serve":
        if not (args.serve_prompts or args.serve_port):
            raise ValueError(
                "--mode serve needs a workload: --serve_prompts "
                "<requests.jsonl> and/or --serve_port <port>.")
        if args.serve_prompts and not os.path.isfile(args.serve_prompts):
            raise FileNotFoundError(
                f"--serve_prompts '{args.serve_prompts}' does not exist.")
        if args.serve_slots < 1:
            raise ValueError("--serve_slots must be >= 1.")
        if args.serve_replicas < 1:
            raise ValueError("--serve_replicas must be >= 1.")
        if args.serve_workers < 0:
            raise ValueError("--serve_workers must be >= 0 "
                             "(0 = in-process serving).")
        if args.serve_workers > 0:
            if args.serve_replicas > 1:
                raise ValueError(
                    "--serve_workers and --serve_replicas are two fleet "
                    "tiers of the same thing: pick in-process replicas "
                    "(--serve_replicas) OR supervised worker processes "
                    "(--serve_workers), not both.")
            if args.load_weights:
                raise ValueError(
                    "--serve_workers cannot --load_weights: workers "
                    "rebuild params from the spec (seed-deterministic "
                    "init or --init_params_from an exported artifact).")
            if args.use_lora:
                raise ValueError(
                    "--serve_workers with LoRA: pass adapters via "
                    "--serve_adapters artifacts, not --use_lora.")
        if args.serve_tp < 1:
            raise ValueError("--serve_tp must be >= 1 (devices per "
                             "replica; 1 = unsharded).")
        if args.serve_sp < 1:
            raise ValueError("--serve_sp must be >= 1 (devices the "
                             "prefill chunk is sequence-sharded over; "
                             "1 = unsharded).")
        if args.serve_max_prompt < 0:
            raise ValueError("--serve_max_prompt must be >= 0 "
                             "(0 = auto: slot capacity / sp).")
        if args.serve_sp > 1:
            if args.serve_workers:
                raise ValueError(
                    "--serve_sp > 1 cannot ride --serve_workers: each "
                    "worker process sees its own device set; run "
                    "seq-sharded replicas in-process "
                    "(--serve_replicas) instead.")
            chunk = args.serve_prefill_chunk or 64
            if chunk % args.serve_sp != 0:
                raise ValueError(
                    f"--serve_prefill_chunk {chunk} must divide evenly "
                    f"over --serve_sp {args.serve_sp} devices: every "
                    "device owns an equal token slice of the chunk.")
        if args.serve_max_queue < 1:
            raise ValueError("--serve_max_queue must be >= 1.")
        if args.serve_max_new_tokens < 1:
            raise ValueError("--serve_max_new_tokens must be >= 1.")
        if args.serve_max_top_k < 1:
            raise ValueError("--serve_max_top_k must be >= 1.")
        if args.serve_max_len < 0:
            raise ValueError("--serve_max_len must be >= 0 (0 = model "
                             "context length).")
        if args.drain_timeout <= 0:
            raise ValueError("--drain_timeout must be > 0 seconds.")
        if args.serve_tick_timeout < 0:
            raise ValueError("--serve_tick_timeout must be >= 0 "
                             "(0 disables the supervisor).")
        if args.serve_max_restarts < 0:
            raise ValueError("--serve_max_restarts must be >= 0.")
        if args.serve_deadline_s < 0:
            raise ValueError("--serve_deadline_s must be >= 0 "
                             "(0 = no default deadline).")
        if args.serve_metrics_every < 0:
            raise ValueError("--serve_metrics_every must be >= 0 "
                             "(0 disables the tick cadence rows).")
        if args.serve_adapter_slots < 0:
            raise ValueError("--serve_adapter_slots must be >= 0 "
                             "(0 = sized to the listed adapters).")
        if args.serve_prefill_chunk < 0:
            raise ValueError("--serve_prefill_chunk must be >= 0 "
                             "(0 = monolithic bucketed prefill).")
        if args.serve_prefix_budget_mb <= 0:
            raise ValueError("--serve_prefix_budget_mb must be > 0.")
        if args.serve_kv_page_tokens < 1:
            raise ValueError("--serve_kv_page_tokens must be >= 1.")
        if args.serve_kv_paged == "on":
            chunk = args.serve_prefill_chunk or 64
            if chunk % args.serve_kv_page_tokens != 0:
                raise ValueError(
                    f"--serve_prefill_chunk {chunk} must be a whole "
                    f"number of pages (--serve_kv_page_tokens "
                    f"{args.serve_kv_page_tokens}): chunk scatters land "
                    "on page boundaries.")
            if args.serve_tp > 1:
                raise ValueError(
                    "--serve_kv_paged on cannot combine with "
                    "--serve_tp > 1: the shared page pool has no "
                    "heads-sharded placement (use replicas instead).")
        if args.serve_spec_k < 0:
            raise ValueError("--serve_spec_k must be >= 0 "
                             "(0 disables speculative decoding).")
        if args.serve_adapters:
            from building_llm_from_scratch_tpu.serving.frontend import (
                parse_adapter_specs,
            )

            specs = parse_adapter_specs(args.serve_adapters)
            if 0 < args.serve_adapter_slots < len(specs):
                raise ValueError(
                    f"--serve_adapter_slots {args.serve_adapter_slots} "
                    f"cannot hold the {len(specs)} adapters listed in "
                    "--serve_adapters.")
            for name, path in specs.items():
                if not os.path.isfile(path):
                    raise FileNotFoundError(
                        f"--serve_adapters '{name}': artifact '{path}' "
                        "does not exist.")
    else:
        # every serve flag, not just the workload pair: a non-default
        # value outside serve mode is a mistyped/missing --mode serve,
        # not a flag to silently drop
        stray = [f"--{name}" for name, default in (
            ("serve_prompts", None), ("serve_port", 0),
            ("serve_out", None), ("serve_slots", 8),
            ("serve_max_queue", 64), ("serve_max_new_tokens", 128),
            ("serve_max_len", 0), ("serve_max_top_k", 64),
            ("serve_host", "127.0.0.1"), ("drain_timeout", 30.0),
            ("serve_tick_timeout", 0.0), ("serve_max_restarts", 3),
            ("serve_deadline_s", 0.0), ("serve_metrics_every", 32),
            ("serve_adapters", None), ("serve_adapter_slots", 0),
            ("serve_prefix_cache", "off"), ("serve_prefill_chunk", 0),
            ("serve_kv_quant", "model"), ("serve_prefix_budget_mb", 256.0),
            ("serve_kv_paged", "off"), ("serve_kv_page_tokens", 16),
            ("serve_spec_k", 0), ("serve_replicas", 1), ("serve_tp", 1),
            ("serve_sp", 1), ("serve_max_prompt", 0),
            ("serve_workers", 0),
        ) if getattr(args, name) != default]
        if stray:
            raise ValueError(
                f"{', '.join(stray)} require --mode serve.")

    if args.mode == "finetune_fleet":
        from building_llm_from_scratch_tpu.serving.frontend import (
            parse_adapter_specs,
        )

        if not args.fleet_jobs:
            raise ValueError(
                "--mode finetune_fleet needs --fleet_jobs "
                "name=records.json[,name=records.json...].")
        specs = parse_adapter_specs(args.fleet_jobs, flag="--fleet_jobs")
        for name, path in specs.items():
            if not os.path.isfile(path):
                raise FileNotFoundError(
                    f"--fleet_jobs '{name}': records file '{path}' does "
                    "not exist.")
        if args.fleet_rows_per_job < 1:
            raise ValueError("--fleet_rows_per_job must be >= 1.")
        if args.fleet_capacity < 0:
            raise ValueError("--fleet_capacity must be >= 0 "
                             "(0 = one slot per listed job).")
        # capacity 0 resolves to one slot per listed job — the blow-up
        # guard must cover that path too, not just an explicit value
        effective_capacity = args.fleet_capacity or len(specs)
        if effective_capacity > 64:
            raise ValueError(
                f"a fused batch of {effective_capacity} job slots "
                "(--fleet_capacity, or one per --fleet_jobs entry when "
                "unset) is almost certainly a mistake — it multiplies "
                "the fused batch; cap --fleet_capacity at <= 64 and let "
                "extra jobs queue for freed slots.")
        if args.lora_rank < 1:
            raise ValueError("--lora_rank must be >= 1.")
        if args.finetune:
            raise ValueError(
                "--mode finetune_fleet IS instruction finetuning; drop "
                "--finetune (job data comes from --fleet_jobs).")
        if args.use_lora:
            raise ValueError(
                "--mode finetune_fleet manages its own stacked adapter "
                "pool; drop --use_lora (--lora_rank/--lora_alpha still "
                "apply).")
        if args.save_adapter:
            raise ValueError(
                "--mode finetune_fleet exports one artifact per job into "
                "--fleet_export_dir; --save_adapter is the solo-run "
                "export.")
    else:
        stray_fleet = [f"--{name}" for name, default in (
            ("fleet_jobs", None), ("fleet_rows_per_job", 4),
            ("fleet_capacity", 0), ("fleet_export_dir", None),
            ("fleet_style", "alpaca"),
        ) if getattr(args, name) != default]
        if stray_fleet:
            raise ValueError(
                f"{', '.join(stray_fleet)} require --mode finetune_fleet.")

    if args.num_params not in MODEL_PARAMS_MAPPING.get(args.model, []):
        raise ValueError(
            f"Unsupported model configuration: {args.model} with "
            f"{args.num_params}. Supported sizes: "
            f"{MODEL_PARAMS_MAPPING.get(args.model, [])}")

    # analog of "FSDP requires multi-GPU" (args.py:25-26): a sharded mode on
    # a single chip is a no-op at best
    if args.run_type == "single_chip" and args.shard_mode != "dp":
        raise ValueError(
            f"--shard_mode {args.shard_mode} requires --run_type multi_chip.")

    if args.tp > 1 and args.shard_mode not in ("tp", "tp_fsdp", "pp"):
        raise ValueError(
            "--tp > 1 requires --shard_mode tp, tp_fsdp or pp.")
    if args.shard_mode in ("tp", "tp_fsdp") and args.tp < 2:
        raise ValueError(
            f"--shard_mode {args.shard_mode} requires --tp >= 2.")

    # bf16_hybrid's explicit reduce-dtype step covers dp/fsdp/zero1
    # (round-4 VERDICT weak #4); tp's activation psums live inside the
    # GSPMD forward where the reduce dtype cannot be controlled, so the
    # combination is rejected at flag time instead of degrading mid-run.
    # (fp16 stays allowed with tp: its reduce dtype EQUALS its compute
    # dtype, so the GSPMD step's reduction already honors the policy.)
    if (args.mixed_precision == "bf16_hybrid"
            and args.shard_mode in ("tp", "tp_fsdp")):
        raise ValueError(
            f"--mixed_precision bf16_hybrid is not supported "
            f"with --shard_mode {args.shard_mode} (dp/fsdp/zero1 only): "
            "tensor-parallel activation reductions run under GSPMD, which "
            "would silently ignore the policy's reduce dtype.")

    if args.shard_mode != "pp" and (args.pp != 0
                                    or args.pp_micro is not None):
        raise ValueError(
            "--pp/--pp_micro only take effect with --shard_mode pp.")
    if args.shard_mode == "pp":
        if args.pp_micro is None:
            args.pp_micro = 8
        if args.pp_micro < 1:
            raise ValueError("--pp_micro must be >= 1.")
        if args.pp < 0:
            raise ValueError("--pp must be >= 0 (0 = one stage/device).")
        # GPT-2 (dropout 0.1) composes with pp since round 4: the schedule
        # folds (micro, data, stage, layer) into the mask PRNG
        # (parallel/pipeline.py)
        if args.mixed_precision in ("fp16", "bf16_hybrid"):
            raise ValueError(
                "--shard_mode pp supports --mixed_precision bf16/fp32 only "
                "(no loss-scaling state; the pipelined loss owns its psum "
                "dtypes).")
        if args.data_type == "fp16":
            raise ValueError(
                "--shard_mode pp does not support fp16 (the pipelined loss "
                "has no loss-scaling state yet); use bf16.")
        # pp x tp composes since round 5 (Megatron psums inside the stage
        # body, parallel/pipeline.py); pp x sp still does not
        if args.sp > 1:
            raise ValueError("--shard_mode pp does not compose with --sp.")
        if args.batch_size % args.pp_micro != 0:
            raise ValueError(
                f"--batch_size {args.batch_size} must be divisible by "
                f"--pp_micro {args.pp_micro}.")

    if args.grad_accum < 1:
        raise ValueError("--grad_accum must be >= 1.")
    if args.grad_accum > 1:
        if args.batch_size % args.grad_accum:
            raise ValueError(
                f"--batch_size {args.batch_size} must be divisible by "
                f"--grad_accum {args.grad_accum}.")
        if args.shard_mode == "pp":
            raise ValueError(
                "--grad_accum does not compose with --shard_mode pp "
                "(pipeline microbatching is --pp_micro).")
        if args.mixed_precision == "bf16_hybrid":
            raise ValueError(
                "--grad_accum does not compose with --mixed_precision "
                "bf16_hybrid (the explicit reduce-dtype step does not "
                "accumulate).")

    if args.sp > 1:
        if args.run_type != "multi_chip":
            raise ValueError("--sp > 1 requires --run_type multi_chip.")
        # GPT-2 (attention dropout) composes with --sp since round 4: the
        # ring schedule folds shard indices into the mask PRNG
        # (ops/ring_attention.py), and --mixed_precision bf16_hybrid
        # composes via the seq-mapped explicit-psum step
        # (train_step.make_sharded_train_step).

    if args.finetune and args.dataset == "gutenberg":
        raise ValueError(
            "--finetune requires an instruction dataset (--dataset alpaca).")
    if not args.finetune and args.dataset == "alpaca":
        raise ValueError(
            "--dataset alpaca requires --finetune.")

    if args.use_lora and args.lora_rank < 1:
        raise ValueError("--lora_rank must be >= 1.")
    if args.save_adapter and not args.use_lora:
        raise ValueError("--save_adapter requires --use_lora (there is "
                         "no adapter to export otherwise).")
    if args.save_adapter and args.mode == "serve":
        raise ValueError("--save_adapter is a training-mode export.")

    # fp16 params with a non-fp16 policy would bypass the loss scaler and
    # silently underflow gradients (round-2 VERDICT weak #4); fp16 alone is
    # fine — build_components synthesizes the fp16 scaling policy for it
    if args.data_type == "fp16" and args.mixed_precision not in (None, "fp16"):
        raise ValueError(
            "--data_type fp16 requires --mixed_precision fp16 (or unset); "
            f"got --mixed_precision {args.mixed_precision}.")

    from building_llm_from_scratch_tpu.ops.attention import AVAILABLE_IMPLS

    if args.attn_impl not in AVAILABLE_IMPLS:
        raise ValueError(
            f"--attn_impl {args.attn_impl} is not implemented yet; "
            f"options: {AVAILABLE_IMPLS}")

    if args.resume_from is not None and not os.path.isdir(args.resume_from):
        raise FileNotFoundError(
            f"--resume_from checkpoint '{args.resume_from}' does not exist.")
    if args.resume not in ("auto", "off") and not os.path.isdir(args.resume):
        raise FileNotFoundError(
            f"--resume checkpoint '{args.resume}' does not exist "
            "(expected 'auto', 'off', or a checkpoint directory).")
    if args.keep_ckpts < 0:
        raise ValueError("--keep_ckpts must be >= 0 (0 keeps all).")
    if args.prefetch < 0:
        raise ValueError("--prefetch must be >= 0 (0 disables).")
    if args.log_every < 0:
        raise ValueError("--log_every must be >= 0 (0 = eval cadence).")
    if args.stall_timeout < 0:
        raise ValueError("--stall_timeout must be >= 0 (0 disables).")
    if args.loss_spike_factor <= 1.0:
        raise ValueError("--loss_spike_factor must be > 1.")
    if args.watchdog_window < 1:
        raise ValueError("--watchdog_window must be >= 1.")
    if args.init_params_from is not None:
        if args.load_weights:
            raise ValueError(
                "--init_params_from and --load_weights are mutually "
                "exclusive (local export vs HF hub).")
        if args.resume_from is not None:
            raise ValueError(
                "--init_params_from and --resume_from are mutually "
                "exclusive: resume restores the FULL train state and "
                "would silently discard the .npz params.")
        if not os.path.isfile(args.init_params_from):
            raise FileNotFoundError(
                f"--init_params_from '{args.init_params_from}' does not "
                "exist.")

    check_dependencies(need_hf=(args.load_weights and not args.weights_dir))


def get_args(argv=None):
    """Parse + validate CLI flags (reference args.py:38-99)."""
    parser = argparse.ArgumentParser(
        prog="building_llm_from_scratch_tpu",
        description="TPU-native Large Language Model Training Configuration")

    # Run mode
    parser.add_argument("--mode", type=str, default="train",
                        choices=["train", "serve", "finetune_fleet"],
                        help="'train' (default): the pretrain/finetune "
                             "pipeline. 'serve': the continuous-batching "
                             "decode engine (serving/) — load or init the "
                             "model per the usual model flags, then serve "
                             "--serve_prompts JSONL and/or an HTTP "
                             "endpoint on --serve_port. 'finetune_fleet': "
                             "fused multi-LoRA finetuning (training/"
                             "lora_fusion.py) — k tenants' jobs from "
                             "--fleet_jobs train through ONE base "
                             "forward/backward, each exporting a "
                             "--serve_adapters-loadable artifact the "
                             "moment it finishes.")

    # Dataset and I/O paths
    parser.add_argument("--data_dir", type=str, default="data",
                        help="Path to the dataset directory.")
    parser.add_argument("--output_dir", type=str, default="model_checkpoints",
                        help="Directory to save model checkpoints.")

    # Serving (--mode serve; serving/ package)
    parser.add_argument("--serve_replicas", type=int, default=1,
                        help="Scale-out serving (serving/router.py): run "
                             "this many DecodeEngine replicas behind one "
                             "router with deadline-aware dispatch, "
                             "adapter-affinity + prefix-affinity routing "
                             "and rolling drain. Each replica gets its "
                             "own --serve_tp device slice (disjoint when "
                             "the device pool allows) and its own "
                             "adapter registry. 1 = the historical "
                             "single-engine path (no router object).")
    parser.add_argument("--serve_workers", type=int, default=0,
                        help="Cross-process fleet (serving/fleet.py): run "
                             "this many supervised worker PROCESSES, each "
                             "a full replica engine behind the unix-socket "
                             "RPC transport with its own metrics JSONL. "
                             "Workers are independently killable: the "
                             "supervisor detects death (heartbeat + "
                             "pipe-EOF), re-dispatches the dead worker's "
                             "queued requests onto survivors and restarts "
                             "the process with bounded backoff. 0 = "
                             "in-process serving (the historical paths). "
                             "Mutually exclusive with --serve_replicas.")
    parser.add_argument("--serve_tp", type=int, default=1,
                        help="Tensor-parallel degree per serving replica: "
                             "the decode/prefill/verify program family "
                             "runs with NamedSharding'd weights and "
                             "heads-sharded slot KV over a (1,1,tp) "
                             "mesh (Megatron rules, "
                             "parallel/sharding.py). 1 = unsharded.")
    parser.add_argument("--serve_sp", type=int, default=1,
                        help="Sequence-parallel prefill degree per serving "
                             "replica: chunked prefill shards each chunk's "
                             "tokens across a (1,sp,tp) mesh's seq axis so "
                             "a prompt larger than one device's pane "
                             "admits (the admission ceiling lifts to "
                             "pane x sp). Decode stays on the existing "
                             "programs; results are bit-identical to "
                             "unsharded. Implies --serve_prefill_chunk 64 "
                             "when unset; composes with --serve_tp and "
                             "--serve_kv_paged. 1 = unsharded.")
    parser.add_argument("--serve_max_prompt", type=int, default=0,
                        help="Per-DEVICE prefill pane in prompt tokens: "
                             "the admission ceiling is "
                             "min(max_len-1, pane x sp), so it lifts "
                             "with --serve_sp. 0 = auto "
                             "(slot capacity / sp). Prompts beyond the "
                             "ceiling get a typed rejection (HTTP 413).")
    parser.add_argument("--serve_slots", type=int, default=8,
                        help="Decode slots: the fixed batch rows the "
                             "engine keeps full (one XLA decode program "
                             "regardless of traffic).")
    parser.add_argument("--serve_max_queue", type=int, default=64,
                        help="Bounded request queue capacity; submissions "
                             "beyond it are rejected (HTTP 429) — "
                             "backpressure instead of unbounded memory.")
    parser.add_argument("--serve_port", type=int, default=0,
                        help="Serve a minimal stdlib HTTP endpoint on this "
                             "port (POST /generate, GET /healthz). "
                             "0 disables.")
    parser.add_argument("--serve_host", type=str, default="127.0.0.1",
                        help="Bind address for --serve_port. Loopback by "
                             "default — the endpoint is unauthenticated; "
                             "pass 0.0.0.0 to expose it deliberately.")
    parser.add_argument("--serve_prompts", type=str, default=None,
                        help="JSONL request file: one {'prompt': ..., "
                             "'max_new_tokens': ..., 'temperature': ..., "
                             "'top_k': ..., 'seed': ...} per line; "
                             "results are written as JSONL to "
                             "--serve_out (default stdout).")
    parser.add_argument("--serve_out", type=str, default=None,
                        help="Path for the JSONL results of "
                             "--serve_prompts (default stdout).")
    parser.add_argument("--serve_max_new_tokens", type=int, default=128,
                        help="Default per-request token budget when a "
                             "request does not specify max_new_tokens.")
    parser.add_argument("--serve_max_top_k", type=int, default=64,
                        help="Largest per-request top_k the compiled "
                             "decode program supports (static top-k "
                             "capacity); requests above it are rejected "
                             "with a 400.")
    parser.add_argument("--serve_max_len", type=int, default=0,
                        help="Per-slot KV capacity (prompt + generated); "
                             "0 (default) uses the model context length. "
                             "Smaller values cut the cache footprint "
                             "when serving short sequences.")
    parser.add_argument("--drain_timeout", type=float, default=30.0,
                        help="Graceful-drain budget on SIGTERM/SIGINT in "
                             "--mode serve: admission closes immediately, "
                             "in-flight (and queued) requests get this "
                             "many seconds to finish, the remainder fail "
                             "with reason 'preempted'. Completed JSONL "
                             "results are already on disk either way.")
    parser.add_argument("--serve_tick_timeout", type=float, default=0.0,
                        help="Fault supervisor: if one decode tick makes "
                             "no progress for this many seconds, dump a "
                             "flight record (all thread stacks + device "
                             "memory), fail the in-flight requests, and "
                             "restart the decode loop with bounded "
                             "exponential backoff (queued requests are "
                             "kept; the compiled programs survive, so a "
                             "restart costs zero recompiles). 0 disables.")
    parser.add_argument("--serve_max_restarts", type=int, default=3,
                        help="Supervisor restart budget: after this many "
                             "decode-loop restarts the engine fails "
                             "loudly instead of flapping.")
    parser.add_argument("--serve_deadline_s", type=float, default=0.0,
                        help="Default per-request deadline (seconds from "
                             "submission) applied when a request carries "
                             "no 'deadline_s' of its own: expired "
                             "requests are shed from the queue (HTTP "
                             "504) and admission rejects up front when "
                             "the backlog already predicts a miss (HTTP "
                             "429 + Retry-After). 0 = no default.")
    parser.add_argument("--serve_adapters", type=str, default=None,
                        help="Multi-tenant LoRA serving: comma-separated "
                             "name=path pairs of adapter artifacts "
                             "(--save_adapter npz files) loaded into the "
                             "engine's device-resident adapter pool. "
                             "Requests pick one with their 'adapter' "
                             "field; base-model traffic co-batches with "
                             "any adapter mix in the ONE compiled decode "
                             "program.")
    parser.add_argument("--serve_adapter_slots", type=int, default=0,
                        help="Static adapter-pool capacity (rows) for "
                             "--serve_adapters; hot-loads beyond it are "
                             "refused. 0 = number of listed adapters + 1 "
                             "spare hot-load row.")
    parser.add_argument("--serve_metrics_every", type=int, default=32,
                        help="Engine metrics cadence in decode ticks: "
                             "each cadence writes one metrics row with "
                             "the decode rate, occupancy/queue gauges "
                             "and the per-tick phase breakdown "
                             "(admit/prefill/decode_dispatch/host_fetch/"
                             "sample_commit/callback_detok) to "
                             "--metrics_jsonl. 0 disables.")
    parser.add_argument("--serve_prefix_cache", type=str, default="off",
                        choices=["on", "off"],
                        help="KV prefix caching (serving/kvcache.py): "
                             "requests sharing a prompt prefix (system "
                             "prompts) reuse its KV panes instead of "
                             "recomputing the prefix forward pass; "
                             "per-adapter namespaced, LRU-evicted under "
                             "--serve_prefix_budget_mb. Implies chunked "
                             "prefill (--serve_prefill_chunk, default 64 "
                             "when unset).")
    parser.add_argument("--serve_prefill_chunk", type=int, default=0,
                        help="Chunked prefill: split prompt prefill into "
                             "fixed chunks of this many tokens, "
                             "interleaved with decode ticks — bounds the "
                             "per-tick prefill stall a long prompt "
                             "inflicts on co-resident requests, and "
                             "replaces the per-bucket prefill programs "
                             "with ONE compiled chunk program. 0 = "
                             "monolithic bucketed prefill (historical "
                             "behavior).")
    parser.add_argument("--serve_kv_quant", type=str, default="model",
                        choices=["model", "int8"],
                        help="Slot KV-cache dtype policy: 'model' stores "
                             "KV in the model dtype; 'int8' quantizes on "
                             "append (per-position per-head scales, "
                             "dequantized inside decode attention) — "
                             "halves KV data bytes per slot, so ~2x "
                             "--serve_slots fits the same HBM at a small "
                             "documented accuracy tolerance.")
    parser.add_argument("--serve_prefix_budget_mb", type=float,
                        default=256.0,
                        help="Prefix-store byte budget (MiB of device "
                             "memory for cached prefix KV panes); least-"
                             "recently-used entries evict past it.")
    parser.add_argument("--serve_kv_paged", type=str, default="off",
                        choices=["on", "off"],
                        help="Paged KV cache (serving/kvcache.py): slot "
                             "KV lives in fixed-size pages drawn from a "
                             "shared pool, addressed through a per-slot "
                             "page table that rides the compiled "
                             "programs as data. Prefix hits become "
                             "shared refcounted page-table entries (zero "
                             "copy), freed pages recycle across "
                             "requests, and admission checks free PAGES "
                             "(oversubscription), not free slots. "
                             "Implies chunked prefill "
                             "(--serve_prefill_chunk, default 64 when "
                             "unset). 'off' keeps the contiguous layout "
                             "byte-identical to prior releases.")
    parser.add_argument("--serve_kv_page_tokens", type=int, default=16,
                        help="Tokens per KV page when --serve_kv_paged "
                             "on: small pages waste less on short tails "
                             "but grow the table/gather width; the "
                             "prefill chunk must be a whole number of "
                             "pages. Ignored when paging is off.")
    parser.add_argument("--serve_spec_k", type=int, default=0,
                        help="Speculative decoding draft length: each "
                             "tick an n-gram drafter proposes this many "
                             "tokens per slot from the slot's own "
                             "history and ONE compiled verify program "
                             "scores all k+1 positions — a slot commits "
                             "1..k+1 tokens per tick, attacking TPOT "
                             "itself. k is static (zero recompiles at "
                             "any acceptance rate); engine tokens are "
                             "bit-identical to spec-off. Per-request "
                             "opt-out via the 'spec': false field. "
                             "0 disables (default).")

    # Fused multi-LoRA finetuning (--mode finetune_fleet;
    # training/lora_fusion.py)
    parser.add_argument("--fleet_jobs", type=str, default=None,
                        help="Fleet jobs as comma-separated name="
                             "records.json pairs (Alpaca-format JSON per "
                             "tenant). Each job trains its own LoRA "
                             "adapter through the ONE fused step and "
                             "exports <fleet_export_dir>/<name>.npz at "
                             "ITS completion.")
    parser.add_argument("--fleet_rows_per_job", type=int, default=4,
                        help="Batch rows each job contributes per fused "
                             "step (the fused batch is capacity x this).")
    parser.add_argument("--fleet_capacity", type=int, default=0,
                        help="Static job slots in the fused step (jobs "
                             "beyond it queue and hot-join as slots "
                             "free, with zero recompiles). 0 = one slot "
                             "per listed job.")
    parser.add_argument("--fleet_export_dir", type=str, default=None,
                        help="Directory for per-job adapter artifacts "
                             "(default <output_dir>/adapters).")
    parser.add_argument("--fleet_style", type=str, default="alpaca",
                        choices=["alpaca", "plain"],
                        help="Job prompt template: 'alpaca' (the "
                             "reference instruction template) or 'plain' "
                             "(bare instruction+output — for tiny-"
                             "context --debug runs where the template "
                             "alone would overflow the context and zero "
                             "every loss weight).")

    # Training configuration
    parser.add_argument("--n_epochs", type=int, default=2,
                        help="Number of training epochs.")
    parser.add_argument("--batch_size", type=int, default=4,
                        help="PER-PROCESS batch size for training. "
                             "Exception: under --shard_mode pp this is the "
                             "GLOBAL batch — the stage axis maps over "
                             "hosts, so every process feeds the same rows.")
    parser.add_argument("--grad_accum", type=int, default=1,
                        help="Gradient-accumulation microbatches per step: "
                             "the batch is split into this many microbatches "
                             "scanned inside the jitted step (activation "
                             "memory of one microbatch, exact full-batch "
                             "numerics). Beyond reference parity.")
    parser.add_argument("--lr", type=float, default=5e-4,
                        help="Base (peak) learning rate.")
    parser.add_argument("--warmup_steps", type=int, default=10,
                        help="Number of warmup steps.")
    parser.add_argument("--initial_lr", type=float, default=1e-5,
                        help="Initial learning rate before warmup.")
    parser.add_argument("--min_lr", type=float, default=1e-6,
                        help="Minimum learning rate.")

    # Host/device overlap (data/prefetch.py, training/async_checkpoint.py)
    parser.add_argument("--prefetch", type=int, default=2,
                        help="Batch-prefetch depth: a background thread "
                             "keeps this many already-transferred device "
                             "batches queued so the H2D copy for batch "
                             "k+1 overlaps the step for batch k (2 = "
                             "double buffering). Exact batch order and "
                             "cursor resume are preserved. 0 disables "
                             "(strict synchronous path, e.g. for "
                             "debugging).")
    parser.add_argument("--async_ckpt", type=str, default="off",
                        choices=["on", "off"],
                        help="Write periodic checkpoints on a background "
                             "thread: the step loop pays only the host "
                             "snapshot, the shard/manifest/commit I/O "
                             "overlaps training. Exit-path checkpoints "
                             "(final/interrupted) still block until "
                             "durable. Multi-host runs fall back to "
                             "synchronous saves.")
    parser.add_argument("--tokenizer_cache_dir", type=str, default=None,
                        help="Persist per-file token-id caches here "
                             "(.npz): relaunches (the preemption-resume "
                             "loop) skip re-tokenizing the corpus. "
                             "In-memory tokenize-once caching is always "
                             "on regardless.")

    # Logging & Evaluation
    parser.add_argument("--print_sample_iter", type=int, default=10,
                        help="Steps between printing sample outputs.")
    parser.add_argument("--eval_freq", type=int, default=10,
                        help="Evaluation frequency (in steps).")
    parser.add_argument("--save_ckpt_freq", type=int, default=100,
                        help="Checkpoint save frequency (in steps).")

    # Observability (obs/)
    parser.add_argument("--metrics_jsonl", type=str, default=None,
                        help="Write structured run telemetry (header + "
                             "per-cadence metrics + typed events) to this "
                             "JSONL file (coordinator process only). "
                             "Render with scripts/summarize_metrics.py.")
    parser.add_argument("--log_every", type=int, default=0,
                        help="Steps between throughput/MFU/memory metric "
                             "lines, decoupled from the (expensive) eval "
                             "loop. 0 (default) logs at --eval_freq "
                             "cadence, the historical behavior.")
    parser.add_argument("--compile_cache_dir", type=str, default=None,
                        help="Enable JAX's persistent compilation cache at "
                             "this directory: relaunches (the preemption-"
                             "resume loop) skip XLA compiles. The compile "
                             "telemetry event records cache hit/miss and "
                             "entry counts.")
    parser.add_argument("--stall_timeout", type=float, default=0.0,
                        help="Opt-in per-host stall detector: if no train "
                             "step completes within this many seconds (or "
                             "10x the rolling median step time — floored "
                             "at 30s so eval/checkpoint cadence work "
                             "never false-fires — whichever is sooner), "
                             "dump all Python thread stacks + device "
                             "memory stats to the log. Strictly "
                             "host-local (no collectives — safe when a "
                             "peer is hung in a psum). 0 disables.")

    # Model Configuration
    parser.add_argument("--model", type=str, default="GPT2",
                        choices=list(MODEL_PARAMS_MAPPING),
                        help="Target model architecture.")
    parser.add_argument("--num_params", type=str, default="124M",
                        help="Model size identifier.")
    parser.add_argument("--load_weights", action="store_true",
                        help="Load pretrained HF weights.")
    parser.add_argument("--weights_dir", type=str, default=None,
                        help="Local directory holding the pretrained "
                             "checkpoint files (offline alternative to the "
                             "HF-hub download).")
    parser.add_argument("--init_params_from", type=str, default=None,
                        help="Initialize model params from a local .npz "
                             "export written by a previous run "
                             "(model_pg_final.npz) — e.g. SFT on top of "
                             "your own pretrained model, fully offline.")
    parser.add_argument("--debug", action="store_true",
                        help="Use a small model for debugging purposes.")
    parser.add_argument("--target_context_length", type=int, default=1024,
                        help="Clamp LLaMA context to this length with RoPE "
                             "theta rescale (reference behavior); 0 keeps "
                             "the native context.")

    # Hardware / precision / parallelism
    parser.add_argument("--run_type", type=str, default="single_chip",
                        choices=["single_chip", "multi_chip"],
                        help="Run on one chip or shard over the mesh.")
    parser.add_argument("--shard_mode", type=str, default="dp",
                        choices=list(SHARD_MODES) + ["pp"],
                        help="Parallelism strategy over the device mesh "
                             "(replaces --use_fsdp/--use_zero_opt); 'pp' = "
                             "GPipe-style pipeline over all devices.")
    parser.add_argument("--pp", type=int, default=0,
                        help="Pipeline stage count for --shard_mode pp "
                             "(0 = one stage per device; with fewer stages "
                             "the data axis absorbs the rest).")
    parser.add_argument("--pp_micro", type=int, default=None,
                        help="Microbatches per step for --shard_mode pp "
                             "(default 8).")
    parser.add_argument("--tp", type=int, default=1,
                        help="Tensor-parallel degree (model mesh axis).")
    parser.add_argument("--sp", type=int, default=1,
                        help="Sequence-parallel degree (seq mesh axis; "
                             "ring attention for long contexts).")
    parser.add_argument("--use_actv_ckpt", action="store_true",
                        help="Enable activation checkpointing (jax.remat).")
    parser.add_argument("--data_type", type=str, default="fp32",
                        choices=["fp32", "fp16", "bf16"],
                        help="Model precision data type.")
    parser.add_argument("--mixed_precision", type=str, default=None,
                        choices=["fp16", "bf16", "bf16_hybrid", "fp32"],
                        help="Mixed-precision policy (param/compute/reduce "
                             "dtypes; reference FSDP MixedPrecision table).")
    parser.add_argument("--attn_impl", type=str, default="auto",
                        choices=["auto", "xla", "flash", "pallas", "fused"],
                        help="Attention implementation (fused = in-house "
                             "pallas flash kernel with in-kernel dropout; "
                             "auto picks it on TPU).")

    # Fine-tuning & Dataset
    parser.add_argument("--finetune", action="store_true",
                        help="Enable instruction-finetuning mode.")
    parser.add_argument("--dataset", type=str, default="gutenberg",
                        choices=["gutenberg", "alpaca"],
                        help="Dataset name.")

    # LoRA
    parser.add_argument("--use_lora", action="store_true",
                        help="Enable LoRA fine-tuning.")
    parser.add_argument("--lora_rank", type=int, default=64,
                        help="LoRA rank.")
    parser.add_argument("--lora_alpha", type=float, default=32,
                        help="LoRA alpha.")
    parser.add_argument("--save_adapter", type=str, default=None,
                        help="After a --use_lora run, export the trained "
                             "adapter as a standalone npz artifact "
                             "(A/B tree + rank/alpha + base-config "
                             "fingerprint) loadable by --serve_adapters "
                             "— the finetune -> multi-tenant-serving "
                             "hand-off.")

    # Tokenizer (TPU/offline additions)
    parser.add_argument("--tokenizer_path", type=str, default=None,
                        help="Local tokenizer asset (sentencepiece/BPE "
                             "model file) for LLaMA tokenizers.")
    parser.add_argument("--byte_tokenizer", action="store_true",
                        help="Fall back to the offline ByteTokenizer "
                             "(debug/smoke runs).")

    # Run management / fault tolerance
    parser.add_argument("--resume_from", type=str, default=None,
                        help="Resume training from a checkpoint directory.")
    parser.add_argument("--resume", type=str, default="auto",
                        help="'auto' (default): resume from the latest "
                             "VALID checkpoint in --output_dir (manifest + "
                             "per-shard size/sha256 checks; corrupt "
                             "checkpoints fall back to the previous valid "
                             "one) — a preempted job relaunches with its "
                             "original command; 'off': always start fresh; "
                             "or an explicit checkpoint dir.")
    parser.add_argument("--keep_ckpts", type=int, default=0,
                        help="Retention GC: keep at most N step-tagged "
                             "checkpoints (model_pg_<step>), pruning the "
                             "oldest after each save. 'interrupted'/'final' "
                             "checkpoints are never pruned. 0 keeps all.")
    parser.add_argument("--watchdog", type=str, default="on",
                        choices=["on", "off"],
                        help="Loss anomaly watchdog: halt with a diagnostic "
                             "on non-finite train loss or a spike above "
                             "--loss_spike_factor x the running median "
                             "(bf16/fp32 runs; fp16 already skips bad steps "
                             "via loss scaling).")
    parser.add_argument("--loss_spike_factor", type=float, default=10.0,
                        help="Watchdog spike threshold as a multiple of the "
                             "running median train loss.")
    parser.add_argument("--watchdog_window", type=int, default=50,
                        help="Steps in the watchdog's running-median "
                             "window.")
    parser.add_argument("--profile", action="store_true",
                        help="Capture a jax.profiler trace of the first "
                             "training steps into <output_dir>/profile.")
    parser.add_argument("--profile_steps", type=int, default=10,
                        help="Number of steps to profile with --profile.")
    parser.add_argument("--seed", type=int, default=123,
                        help="Global random seed.")

    # Warnings & Logs
    parser.add_argument("--warnings", action="store_true",
                        help="Enable Python warnings.")

    args = parser.parse_args(argv)
    perform_checks(args)
    return args


if __name__ == "__main__":
    parsed = get_args()
    print("Arguments parsed and validated successfully:")
    for k, v in vars(parsed).items():
        print(f"  {k}: {v}")
