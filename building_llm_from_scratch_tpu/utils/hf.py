"""HuggingFace Hub authentication (reference utils.py:196-213).

Reads the access token from ``config_hf.json`` (same file name/key as the
reference, ``{"HF_ACCESS_TOKEN": "..."}``) and logs into the hub — needed
for the gated meta-llama weight/tokenizer downloads. Failures are logged,
not raised, matching the reference (runs with local assets don't need it).
"""

from __future__ import annotations

import json

from building_llm_from_scratch_tpu.utils.logging import setup_logger

logger = setup_logger(__name__)


def login_hf(config_path: str = "config_hf.json") -> bool:
    """Log into HF hub with the token from ``config_path``; True on success."""
    try:
        with open(config_path, "r", encoding="utf-8") as f:
            config = json.load(f)
        access_token = config.get("HF_ACCESS_TOKEN", None)
        assert access_token, "HF_ACCESS_TOKEN not found in config."

        from huggingface_hub import login

        login(token=access_token)
        logger.info("Logged into Hugging Face Hub.")
        return True
    except FileNotFoundError:
        logger.error("'%s' not found. Copy config_hf.json.example to "
                     "config_hf.json and fill in your access token (the "
                     "real file is gitignored).", config_path)
    except Exception as e:  # noqa: BLE001 — parity: log, don't crash
        logger.error("Error logging into Hugging Face: %s", e)
    return False
