"""Per-module console loggers (reference: logger.py:4-42).

Same behavior: named loggers, DEBUG level, timestamped format,
duplicate-handler guard, no propagation. Additionally process-index aware:
on multi-host TPU runs only process 0 emits below-WARNING records by
default (replacing the reference's ``rank == 0`` gating scattered through
train.py) — N hosts otherwise print N interleaved copies of every INFO
line. Set ``BLLM_LOG_ALL_HOSTS=1`` to see every host (debugging a single
wedged worker).

The gating is a lazy handler filter, NOT an import-time ``process_index``
call: these loggers are created at module import, long before
``jax.distributed.initialize``, and asking jax for a process index would
initialize the backend prematurely. The filter only consults distributed
state that already exists; with none, it assumes single-process (where
process 0 is everyone).
"""

from __future__ import annotations

import logging
import os
import sys

_FORMAT = "%(asctime)s - %(name)s - %(levelname)s - %(message)s"


def _coordinator_if_known() -> bool:
    """True unless this process is provably a non-coordinator. Never
    initializes jax (see module docstring)."""
    if sys.modules.get("jax") is None:
        return True
    try:
        from jax._src import distributed

        pid = getattr(distributed.global_state, "process_id", None)
        if pid is not None:
            return pid == 0
    except Exception:
        pass
    return True


class _CoordinatorFilter(logging.Filter):
    """Drop below-WARNING records on non-coordinator processes (the
    process-0 INFO gating the module docstring always promised).
    ``BLLM_LOG_ALL_HOSTS=1`` disables the gate for debugging."""

    def filter(self, record: logging.LogRecord) -> bool:
        if record.levelno >= logging.WARNING:
            return True
        if os.environ.get("BLLM_LOG_ALL_HOSTS"):
            return True
        return _coordinator_if_known()


def setup_logger(name: str, level: int | None = None) -> logging.Logger:
    """Get/create a named logger.

    ``level`` is applied whenever passed explicitly; when omitted, the
    DEBUG default applies only to a logger that has no level yet — a
    repeat default call no longer clobbers a level an earlier explicit
    call chose.
    """
    logger = logging.getLogger(name)
    if level is not None:
        logger.setLevel(level)
    elif logger.level == logging.NOTSET:
        logger.setLevel(logging.DEBUG)
    if not logger.handlers:
        handler = logging.StreamHandler(sys.stdout)
        handler.setFormatter(logging.Formatter(_FORMAT))
        handler.addFilter(_CoordinatorFilter())
        logger.addHandler(handler)
    logger.propagate = False
    return logger


def is_coordinator() -> bool:
    """True on the process that should do host-side IO (rank-0 analog)."""
    import jax

    return jax.process_index() == 0
