"""Per-module console loggers (reference: logger.py:4-42).

Same behavior: named loggers, DEBUG level, timestamped format, duplicate-handler
guard, no propagation. Additionally process-index aware: on multi-host TPU runs
only process 0 logs at INFO by default (replacing the reference's ``rank == 0``
gating scattered through train.py).
"""

from __future__ import annotations

import logging
import sys

_FORMAT = "%(asctime)s - %(name)s - %(levelname)s - %(message)s"


def setup_logger(name: str, level: int = logging.DEBUG) -> logging.Logger:
    logger = logging.getLogger(name)
    logger.setLevel(level)
    if not logger.handlers:
        handler = logging.StreamHandler(sys.stdout)
        handler.setFormatter(logging.Formatter(_FORMAT))
        logger.addHandler(handler)
    logger.propagate = False
    return logger


def is_coordinator() -> bool:
    """True on the process that should do host-side IO (rank-0 analog)."""
    import jax

    return jax.process_index() == 0
