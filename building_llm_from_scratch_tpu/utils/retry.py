"""Bounded retry with exponential backoff + jitter for remote fetches.

HF hub downloads (weights/fetch.py, data/tokenizers.py) run on shared
infrastructure where transient 5xx/connection-reset failures are routine —
on a multi-host TPU pod one flaky fetch otherwise kills the whole job at
startup. The policy here: up to ``attempts`` tries, exponential backoff
with full jitter (decorrelates the retry stampede across pod hosts), and a
hard distinction between RETRYABLE errors (connection/timeout/5xx/429) and
DEFINITIVE ones (404 not-found, gated/auth failures) which re-raise
immediately — retrying a typo'd repo name three times just hides the real
error for a minute.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Optional, TypeVar

from building_llm_from_scratch_tpu.utils.logging import setup_logger

logger = setup_logger(__name__)

T = TypeVar("T")

# Exception class names that mean "the asset does not exist / you may not
# have it" — matched by name across the MRO so huggingface_hub (and
# requests/urllib3 underneath it) never needs to be importable here.
_DEFINITIVE_NAMES = {
    "RepositoryNotFoundError",
    "EntryNotFoundError",
    "RevisionNotFoundError",
    "GatedRepoError",
    "HFValidationError",
}

_RETRYABLE_NAMES = {
    "ConnectionError",
    "ConnectTimeout",
    "ReadTimeout",
    "Timeout",
    "ChunkedEncodingError",
    "ProtocolError",
    "IncompleteRead",
    "RemoteDisconnected",
    "URLError",
    "SSLError",
}

_RETRYABLE_STATUS = {408, 425, 429}


def is_retryable_fetch_error(exc: BaseException) -> bool:
    """Classify a fetch failure: True for transient network conditions,
    False for definitive answers (404/gated/invalid-repo) where a retry
    only delays the real error message."""
    names = {c.__name__ for c in type(exc).__mro__}
    if names & _DEFINITIVE_NAMES:
        return False
    status = getattr(getattr(exc, "response", None), "status_code", None)
    if status is not None:
        return status in _RETRYABLE_STATUS or 500 <= int(status) <= 599
    if names & _RETRYABLE_NAMES:
        return True
    if isinstance(exc, (ConnectionError, TimeoutError)):
        return True
    # socket-level failures surface as OSError; local filesystem problems
    # (missing file, permissions) are NOT transient
    if isinstance(exc, OSError) and not isinstance(
            exc, (FileNotFoundError, PermissionError, IsADirectoryError,
                  NotADirectoryError)):
        return True
    return False


def with_retries(fn: Callable[[], T], *, attempts: int = 3,
                 base_delay: float = 1.0, max_delay: float = 30.0,
                 is_retryable: Callable[[BaseException], bool]
                 = is_retryable_fetch_error,
                 describe: str = "remote fetch",
                 sleep: Optional[Callable[[float], None]] = None,
                 rng: Callable[[], float] = random.random) -> T:
    """Call ``fn`` with up to ``attempts`` tries.

    Non-retryable errors and the final attempt's error re-raise unchanged
    (the caller's error handling sees the original exception). Between
    retryable failures, sleeps ``base_delay * 2^attempt`` capped at
    ``max_delay``, plus up to 100% jitter. ``sleep``/``rng`` are injectable
    for tests.
    """
    if attempts < 1:
        raise ValueError(f"attempts must be >= 1, got {attempts}")
    for attempt in range(attempts):
        try:
            return fn()
        except Exception as e:
            if attempt == attempts - 1 or not is_retryable(e):
                raise
            delay = min(max_delay, base_delay * (2 ** attempt))
            delay += rng() * delay
            # structured telemetry (obs/metrics.py): imported lazily so the
            # retry helper stays importable with zero obs dependencies
            from building_llm_from_scratch_tpu.obs.metrics import emit_event

            emit_event("retry", describe=describe,
                       error=f"{type(e).__name__}: {e}",
                       attempt=attempt + 1, attempts=attempts,
                       delay_s=round(delay, 2))
            logger.warning(
                "%s failed (%s: %s); retrying in %.1fs (attempt %d/%d)",
                describe, type(e).__name__, e, delay, attempt + 1, attempts)
            # resolved at call time so tests can stub the module's clock
            (sleep if sleep is not None else time.sleep)(delay)
    raise AssertionError("unreachable")
