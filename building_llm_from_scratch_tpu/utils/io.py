"""Host-side file IO helpers (reference: utils.py:89-101)."""

from __future__ import annotations

import json
import os
from typing import Any, List, Tuple


def read_text_file(path: str) -> str:
    with open(path, "r", encoding="utf-8") as f:
        return f.read()


def read_json_file(path: str) -> Any:
    with open(path, "r", encoding="utf-8") as f:
        return json.load(f)


def discover_training_files(data_dir: str) -> Tuple[List[str], List[str]]:
    """Walk ``data_dir`` collecting .txt (pretrain) and .json (finetune) files.

    Reference: main.py:68-78 (os.walk discovery).
    Returns (txt_files, json_files), both sorted for determinism.
    """
    txt, js = [], []
    for root, _dirs, files in os.walk(data_dir):
        for fname in files:
            p = os.path.join(root, fname)
            if fname.endswith(".txt"):
                txt.append(p)
            elif fname.endswith(".json"):
                js.append(p)
    return sorted(txt), sorted(js)
