"""Parameter counting and device-memory accounting.

Reference equivalents:
  - count_params / static 4N-Adam estimate  (utils.py:112-129)
  - dynamic param+grad+buffer estimate      (utils.py:131-144)
  - CUDA peak-memory tracking               (utils.py:149-166)

On TPU the peak-stat source is ``device.memory_stats()`` (HBM view); on CPU
test runs stats may be unavailable and we degrade gracefully.
"""

from __future__ import annotations

import sys
from typing import Any, Dict, Optional

import jax
import numpy as np

from building_llm_from_scratch_tpu.configs import DTYPE_BYTES


def count_params(params: Any) -> int:
    """Total number of scalar parameters in a pytree."""
    leaves = jax.tree_util.tree_leaves(params)
    return int(sum(np.prod(l.shape) if hasattr(l, "shape") else 1 for l in leaves))


def estimate_memory_static(n_params: int, dtype: str = "fp32",
                           optimizer: str = "adamw") -> float:
    """Static memory estimate in GB using the 4N Adam rule
    (params + grads + Adam m/v), reference utils.py:112-129."""
    mult = 4 if optimizer == "adamw" else 2
    return mult * n_params * DTYPE_BYTES[dtype] / 1024**3


def estimate_memory_dynamic(n_params: int, n_trainable: int,
                            dtype: str = "fp32") -> float:
    """Dynamic params+grads estimate in GB (reference utils.py:131-144:
    parameters + gradients-for-trainables + buffers; this framework keeps
    no torch-style buffers — RoPE/mask constants live in the jit program)."""
    return (n_params + n_trainable) * DTYPE_BYTES[dtype] / 1024**3


def host_rss_bytes() -> Optional[int]:
    """This process's resident set size in bytes, or None when
    undeterminable. Host-RAM growth (data pipeline buffers, checkpoint
    staging, metric accumulation) is invisible to ``device.memory_stats``
    — a leaking input pipeline OOMs the HOST first. Reads /proc (Linux,
    the TPU VM case) and falls back to getrusage peak-RSS elsewhere."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    try:
        import resource

        peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        # linux reports KiB, macOS bytes
        return peak * 1024 if sys.platform != "darwin" else peak
    except Exception:
        return None


def device_memory_stats(device: Optional[jax.Device] = None) -> Dict[str, int]:
    """Best-effort HBM stats for one device (bytes)."""
    device = device or jax.local_devices()[0]
    try:
        stats = device.memory_stats()
    except Exception:
        return {}
    return {k: v for k, v in (stats or {}).items() if isinstance(v, int)}


def log_device_memory(logger, prefix: str = "") -> None:
    """Log peak/in-use HBM per local device (reference utils.py:158-166)."""
    for d in jax.local_devices():
        stats = device_memory_stats(d)
        if not stats:
            # remote/tunnel backends expose no live stats; fall back to the
            # size of this process's live arrays on the device — an in-use
            # floor, not a peak
            # sum the actual shard bytes resident on THIS device: dividing
            # global nbytes by the device count undercounts replicated
            # arrays (each replica holds the FULL buffer)
            live = sum(
                s.data.nbytes
                for x in jax.live_arrays()
                if getattr(x, "sharding", None) is not None
                and d in x.sharding.device_set
                for s in x.addressable_shards
                if s.device == d) / 1024**3
            logger.info("%s%s: live stats unavailable; live jax.Arrays "
                        "hold >= %.2fGB", prefix, d, live)
            continue
        in_use = stats.get("bytes_in_use", 0) / 1024**3
        peak = stats.get("peak_bytes_in_use", 0) / 1024**3
        limit = stats.get("bytes_limit", 0) / 1024**3
        logger.info("%s%s: in_use=%.2fGB peak=%.2fGB limit=%.2fGB",
                    prefix, d, in_use, peak, limit)
