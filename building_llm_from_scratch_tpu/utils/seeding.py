"""Reproducibility (reference: utils.py:55-66).

The reference seeds python/numpy/torch and flips cudnn to deterministic. In
JAX, randomness is explicit: we seed python/numpy for host-side shuffling and
hand back a root ``jax.random.PRNGKey`` that all device-side randomness
(dropout, sampling, init) descends from.
"""

from __future__ import annotations

import random

import numpy as np


def set_seed(seed: int = 123):
    """Seed host-side RNGs and return the root JAX PRNG key."""
    import jax

    random.seed(seed)
    np.random.seed(seed)
    return jax.random.PRNGKey(seed)
