"""Reproducibility (reference: utils.py:55-66).

The reference seeds python/numpy/torch and flips cudnn to deterministic. In
JAX, randomness is explicit: we seed python/numpy for host-side shuffling and
hand back a root ``jax.random.PRNGKey`` that all device-side randomness
(dropout, sampling, init) descends from.
"""

from __future__ import annotations

import random

import numpy as np


def configure_default_prng():
    """Switch JAX's default PRNG from threefry to ``rbg`` on TPU.

    Threefry keygen dominates dropout cost on TPU: GPT2-124M bf16 bs8
    ctx1024 train steps measured 33.9k tok/s/chip under threefry vs 57.4k
    under rbg (v5e-1, 2026-07) — the T^2 attention-dropout masks hash
    millions of counters per step. ``rbg`` (XLA RngBitGenerator) is the
    standard TPU-production choice; streams derived via fold_in remain
    statistically sound for dropout. Called from the runtime entry points
    (main, bench) — never on library import, so embedding applications keep
    control of their own JAX config.
    """
    import jax

    if jax.default_backend() == "tpu":
        jax.config.update("jax_default_prng_impl", "rbg")


def set_seed(seed: int = 123):
    """Seed host-side RNGs and return the root JAX PRNG key."""
    import jax

    random.seed(seed)
    np.random.seed(seed)
    return jax.random.PRNGKey(seed)
