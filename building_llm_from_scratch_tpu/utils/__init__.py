"""Cross-cutting utilities (reference: utils.py, logger.py)."""

from building_llm_from_scratch_tpu.utils.logging import setup_logger
from building_llm_from_scratch_tpu.utils.io import read_text_file, read_json_file
from building_llm_from_scratch_tpu.utils.seeding import set_seed
from building_llm_from_scratch_tpu.utils.memory import (
    count_params,
    estimate_memory_static,
    device_memory_stats,
    log_device_memory,
)

__all__ = [
    "setup_logger",
    "read_text_file",
    "read_json_file",
    "set_seed",
    "count_params",
    "estimate_memory_static",
    "device_memory_stats",
    "log_device_memory",
]
