"""Dual-axis loss plot -> losses.pdf (reference: utils.py:171-191)."""

from __future__ import annotations

import os
from typing import Sequence


def plot_losses(epochs_seen: Sequence[float], tokens_seen: Sequence[int],
                train_losses: Sequence[float], val_losses: Sequence[float],
                output_dir: str, filename: str = "losses.pdf") -> str:
    """Plot train/val loss vs epochs (bottom axis) and tokens seen (top axis)."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, ax1 = plt.subplots()
    ax1.plot(epochs_seen, train_losses, label="Training loss")
    ax1.plot(epochs_seen, val_losses, linestyle="-.", label="Validation loss")
    ax1.set_xlabel("Epochs")
    ax1.set_ylabel("Loss")
    ax1.legend(loc="upper right")

    ax2 = ax1.twiny()
    ax2.plot(tokens_seen, train_losses, alpha=0)  # align top axis to tokens
    ax2.set_xlabel("Tokens seen")

    fig.tight_layout()
    os.makedirs(output_dir, exist_ok=True)
    out = os.path.join(output_dir, filename)
    plt.savefig(out)
    plt.close(fig)
    return out
