"""The telemetry schema registry: every event kind, span shape, and
phase/segment table the JSONL sink may emit — declared ONCE, here.

Before this module existed the schema lived in three places at once: the
emitting call sites (``MetricLogger.event(...)`` kwargs scattered over a
dozen modules), ``obs/trace.py``'s rendering tables, and a pinned fallback
copy inside ``scripts/summarize_metrics.py``. PR 7's review caught exactly
the failure mode that layout invites — a drift-prone private copy of
``TICK_PHASES`` — so consumers now import from here and the GL04x
telemetry lint (``analysis/telemetry.py``) checks every ``.event(...)``
call site against this registry: adding a field or an event kind without
declaring it is a lint failure, not a review catch.

Stdlib-only and import-free (no jax, no numpy): the static analyzer, the
renderer script and the trace exporter all load it without touching the
accelerator stack.

To register a new event kind:

  1. add an ``EventSpec`` to ``EVENTS`` below (required fields are the
     ones every emission must carry; ``open_fields=True`` admits dynamic
     payloads like ``watchdog_halt``'s health context);
  2. emit it with ``get_metrics().event("kind", ...)`` /
     ``obs.metrics.emit_event`` — ``scripts/lint_graft.py`` verifies the
     call site against the spec;
  3. if the trace exporter should render it, add it to
     ``INCIDENT_EVENTS`` / ``REQUEST_EVENTS`` (subsets of the registry —
     test-asserted).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, FrozenSet, List

#: Bump when a row type or a load-bearing field changes meaning. The
#: ``header`` row carries it; consumers key parsing decisions on it.
SCHEMA_VERSION = 13         # v13: long-context tier — prefill_shard
                            # tick phase (seq-sharded chunk prefill,
                            # --serve_sp), serve_warmup gains
                            # sp / prompt_pane_tokens / max_prompt,
                            # request_done gains long_prompt
                            # (v12: paged KV cache — page_admit /
                            # page_share / page_release /
                            # page_pool_exhausted events (serving page
                            # pool: refcounted shared pages + page-table
                            # attention), serve_warmup gains
                            # kv_paged / page_tokens / pool_pages
                            # (v11: memory observatory — memory_snapshot /
                            # memory_pressure / memory_drift events
                            # (obs/memory.py MemoryLedger: byte-exact
                            # component ledger + drift/pressure
                            # detection), request_done gains
                            # kv_bytes_peak + prefix_bytes_saved
                            # (v10: fleet observatory — clock_sync /
                            # incident_snapshot events, worker_request +
                            # rpc span roots (worker-side trees stamped
                            # with pid/incarnation), worker_* events
                            # rendered on the incidents trace track)
                            # (v9: cross-process fleet — worker_spawn /
                            # worker_heartbeat_missed / worker_dead /
                            # worker_restart / pane_handoff events
                            # serving/fleet.py supervision + prefix-
                            # pane handoff over the RPC transport)
                            # (v8: scale-out serving — serve_fleet /
                            # replica_drain / replica_restart /
                            # router_redispatch events, `replica` label
                            # on engine-scoped events + span rows,
                            # `router` request-span child)

#: JSONL row discriminators (the ``type`` field).
ROW_TYPES = ("header", "metrics", "health", "event", "span")

#: Engine tick phases, in within-tick order (serving/engine.py accumulates
#: wall-clock per phase and logs the sums at its metrics cadence as
#: ``tick_<phase>_s`` fields; /metrics exports ``tick_<phase>_seconds``).
#: ``prefix_copy`` is the KV memory engine's pane traffic (prefix-hit
#: copies + post-prefill pane extraction, serving/kvcache.py).
#: ``draft`` is the speculative drafter's host-side proposal time
#: (serving/spec.py; identically 0 on spec-off engines).
#: ``prefill_shard`` is chunk prefill on a sequence-sharded mesh
#: (``--serve_sp``): the same chunk pump, booked under its own phase so
#: the long-context share of tick wall is visible (identically 0 on
#: non-sp engines, like ``draft``).
TICK_PHASES = ("admit", "prefix_copy", "prefill", "prefill_shard", "draft",
               "decode_dispatch", "host_fetch", "sample_commit",
               "callback_detok")

#: Trainer StepTimeline segments (``<segment>_s`` fields of training
#: cadence metrics rows; obs/timeline.py owns the measurement).
TRAIN_SEGMENTS = ("data_wait", "dispatch", "host_fetch", "eval", "sample",
                  "checkpoint")

#: Event kinds rendered as instants on the trace's incidents track.
#: The worker-process lifecycle kinds joined in v10 so the fleet
#: exporter (obs/fleetview.py) and the single-file exporter render the
#: same death/restart instants without a second table.
#: ``memory_pressure``/``memory_drift`` joined in v11: a near-OOM
#: crossing or a ledger leak is an incident the timeline must show next
#: to the tick phases (``memory_snapshot`` is NOT here — it renders as a
#: counter track, not an instant).
INCIDENT_EVENTS = ("engine_restart", "drain", "serve_error", "stall",
                   "watchdog_halt", "preemption_signal", "preemption_stop",
                   "checkpoint_fallback", "serve_warmup",
                   "worker_spawn", "worker_heartbeat_missed", "worker_dead",
                   "worker_restart", "pane_handoff", "incident_snapshot",
                   "memory_pressure", "memory_drift")

#: Request-lifecycle event kinds pinned to the request's own trace track.
REQUEST_EVENTS = ("request_done", "request_rejected", "request_shed",
                  "request_expired", "request_failed")

#: Lifecycle event kinds that open the serving section of the renderer
#: even when zero requests completed (incident runs). Worker-process
#: births/deaths qualify: a fleet run where a worker died before any
#: request finished is exactly an incident file the section must explain.
SERVING_LIFECYCLE_EVENTS = ("engine_restart", "drain", "serve_error",
                            "worker_spawn", "worker_dead", "worker_restart")

#: Root span names the ``span`` row type may carry (one tree per row).
#: ``request`` is the router-side tree (one per request, emitted at the
#: terminal outcome whatever it was — worker_dead included).
#: ``worker_request`` is the worker-process-side view of the same
#: request (same ``request_id``, stamped with pid/incarnation).
#: ``rpc`` is one server-side RPC handle (method + request_id), so the
#: merged timeline can show client wait vs server handle per hop.
SPAN_NAMES = ("request", "worker_request", "rpc")

#: Child span names under a ``request`` root, in lifecycle order.
#: ``router`` (fleet dispatch hop, serving/router.py) only appears on
#: routed requests — single-engine span trees are unchanged.
REQUEST_SPAN_PHASES = ("router", "queued", "prefill", "decode")


@dataclass(frozen=True)
class EventSpec:
    """Declared shape of one ``event`` row kind.

    ``required``: every emission must carry these fields. ``optional``:
    fields an emission may carry. ``open_fields``: the payload includes
    dynamic keys (health context, stats dicts) — unknown fields are then
    legal, but the declared ones still document the stable core.
    """

    name: str
    required: FrozenSet[str] = frozenset()
    optional: FrozenSet[str] = frozenset()
    open_fields: bool = False
    doc: str = ""

    def known_fields(self) -> FrozenSet[str]:
        return self.required | self.optional | ALWAYS_ALLOWED_FIELDS


#: Fields every event row may carry regardless of kind (``event()`` adds
#: ``step`` itself; ``type``/``time``/``event`` are the row envelope).
ALWAYS_ALLOWED_FIELDS = frozenset({"step", "type", "time", "event"})


def _spec(name: str, required=(), optional=(), open_fields=False,
          doc: str = "") -> EventSpec:
    return EventSpec(name, frozenset(required), frozenset(optional),
                     open_fields, doc)


_EVENT_LIST: List[EventSpec] = [
    # -- run lifecycle ----------------------------------------------------
    _spec("components_built",
          optional=("model", "n_params", "est_train_mem_gb",
                    "flops_per_token_analytic", "shard_mode",
                    "load_weights", "prefetch", "async_ckpt",
                    "tokenizer_cache"),
          doc="model/optimizer/loader built; records the run's shape"),
    _spec("run_complete", optional=("tokens_seen", "final_train_loss"),
          doc="training main() reached its normal end"),
    # -- fetch / retry ----------------------------------------------------
    _spec("hf_fetch", required=("repo",),
          optional=("files", "bytes", "cached", "seconds"),
          doc="HF hub download (downloaded vs cached bytes split)"),
    _spec("retry", required=("describe",),
          optional=("error", "attempt", "attempts", "delay_s"),
          doc="bounded-retry attempt (utils/retry.py)"),
    _spec("tokenize_cache", required=("file", "source"),
          optional=("tokens", "seconds"),
          doc="TokenCache hit/encode (source: memory|disk|encoded)"),
    # -- compile telemetry ------------------------------------------------
    _spec("compile", required=("label",),
          optional=("compile_seconds", "lower_seconds",
                    "backend_compile_seconds", "executable_device_count",
                    "flops", "flops_per_device", "transcendentals",
                    "bytes_accessed", "memory", "n_compiles",
                    "tokens_per_step", "hbm_capacity_bytes",
                    "hbm_budget_frac", "cache_dir", "cache_entries",
                    "cache_hit"),
          doc="one AOT compile capture (obs/compile.py)"),
    _spec("recompile", required=("label",),
          optional=("n_recompiles", "n_changed_leaves", "diff"),
          doc="argument-signature change after the legitimate set closed"),
    _spec("compile_fallback", required=("label",), optional=("error",),
          doc="AOT capture failed; telemetry fell back to plain jit"),
    # -- checkpoints ------------------------------------------------------
    _spec("checkpoint_save", required=("path",),
          optional=("seconds", "bytes", "leaves", "writer"),
          doc="one durable checkpoint commit (sync or async writer)"),
    _spec("checkpoint_restore", required=("path",),
          optional=("seconds", "leaves"),
          doc="checkpoint loaded into the train state"),
    _spec("checkpoint_fallback", required=("path", "reason"),
          doc="--resume auto skipped an invalid checkpoint"),
    _spec("checkpoint_gc", optional=("removed", "keep"),
          doc="--keep_ckpts retention GC removed old checkpoints"),
    _spec("ckpt_async_save", required=("path",),
          optional=("snapshot_s", "write_s", "overlap_s"),
          doc="async checkpoint: snapshot/write/overlap seconds"),
    # -- resilience -------------------------------------------------------
    _spec("preemption_signal", required=("signal",),
          doc="SIGTERM/SIGINT observed; stop at next step boundary"),
    _spec("preemption_stop", optional=("tokens_seen",),
          doc="graceful stop checkpoint written at the step boundary"),
    _spec("watchdog_halt", required=("reason",),
          optional=("loss", "recent", "median", "spike_factor"),
          open_fields=True,
          doc="loss watchdog halt (+ dynamic per-layer health context)"),
    _spec("stall", optional=("elapsed_s", "threshold_s", "memory"),
          doc="flight recorder fired: stacks + device memory dumped"),
    # -- serving: request lifecycle ---------------------------------------
    _spec("request_done", required=("request_id",),
          optional=("n_prompt_tokens", "n_tokens", "finish_reason", "slot",
                    "deadline_s", "queue_wait_s", "ttft_s", "tpot_s",
                    "e2e_s", "adapter", "spec_drafted", "spec_accepted",
                    "kv_bytes_peak", "prefix_bytes_saved", "long_prompt",
                    "replica"),
          doc="one request completed normally (latency summary; "
              "spec_drafted/spec_accepted = this request's speculative "
              "acceptance ledger on --serve_spec_k engines; "
              "kv_bytes_peak = the slot KV bytes the request occupied at "
              "its longest; prefix_bytes_saved = KV bytes prefix-cache "
              "hits spared it from recomputing; long_prompt = the prompt "
              "exceeded one device's pane on a --serve_sp engine, so "
              "prefill ran sequence-sharded)"),
    _spec("request_rejected", required=("request_id", "reason"),
          optional=("queue_depth", "replica"),
          doc="bounded queue at capacity at submit (HTTP 429)"),
    _spec("request_shed", required=("request_id", "reason"),
          optional=("queue_depth", "deadline_s", "estimated_e2e_s",
                    "retry_after_s", "replica"),
          doc="SLO-predicted deadline miss rejected at submit"),
    _spec("request_expired", required=("request_id", "reason"),
          optional=("deadline_s", "queue_wait_s", "queue_depth", "replica"),
          doc="deadline passed while queued (TTL shed, HTTP 504)"),
    _spec("request_failed", required=("request_id", "reason"),
          optional=("error", "slot", "n_tokens", "adapter", "replica"),
          doc="one request failed in isolation (or engine death/restart)"),
    # -- serving: multi-tenant LoRA adapters ------------------------------
    _spec("adapter_save", required=("path",),
          optional=("rank", "alpha", "n_params", "fingerprint", "job_id"),
          doc="finetuning exported a LoRA adapter artifact "
              "(--save_adapter, or a fused-fleet job finishing — then "
              "job_id names the tenant whose deployment just unblocked)"),
    # -- fused multi-LoRA training (training/lora_fusion.py) ---------------
    _spec("finetune_job_start", required=("job_id",),
          optional=("slot", "total_steps", "n_records", "n_epochs",
                    "rows_per_step"),
          doc="a fleet job hot-joined a free slot (identity is data: "
              "joining never recompiles the fused step)"),
    _spec("finetune_job_done", required=("job_id",),
          optional=("steps", "final_loss", "artifact", "deployed",
                    "seconds"),
          doc="a fleet job completed: its adapter exported at JOB "
              "finish (slow co-tenants don't block it) and optionally "
              "hot-loaded into the deploy registry"),
    _spec("finetune_job_failed", required=("job_id", "reason"),
          optional=("slot", "steps", "loss", "grad_norm"),
          doc="a fleet job retired in isolation (non-finite training "
              "signal; its in-graph updates were already skipped, "
              "co-trained jobs bit-identical)"),
    _spec("finetune_fleet", required=("phase",),
          optional=("n_jobs", "capacity", "rank", "alpha", "rows_per_job",
                    "jobs_done", "jobs_failed", "seconds",
                    "flops_per_token_base", "flops_per_token_adapter"),
          doc="fleet run bracketing (phase: start|end) + the analytic "
              "base-vs-adapter FLOPs split the renderer reports"),
    _spec("adapter_load", required=("name",),
          optional=("path", "row", "rank", "alpha", "seconds",
                    "n_loaded", "capacity"),
          doc="registry hot-loaded an adapter into a pool row "
              "(zero recompiles — same pool shapes)"),
    _spec("adapter_evict", required=("name",),
          optional=("row", "n_loaded"),
          doc="registry unloaded an adapter (row reused only once no "
              "active slot references it)"),
    # -- serving: KV-cache memory engine ----------------------------------
    _spec("prefix_hit", required=("request_id",),
          optional=("span_tokens", "prompt_tokens", "key",
                    "n_suffix_chunks", "adapter", "late", "replica"),
          doc="a stored prefix matched: its panes were copied into the "
              "slot (zero forward FLOPs for the cached span). late=True "
              "is the mid-prefill catch-up hit — a co-admitted sharer "
              "jumping ahead on a pane stored after its admission"),
    _spec("prefix_miss", required=("request_id",),
          optional=("prompt_tokens", "adapter", "replica"),
          doc="no stored prefix matched; the prompt prefills in full "
              "(and its chunk-aligned prefix is stored for successors)"),
    _spec("prefix_evict", required=("key",),
          optional=("bytes", "span_tokens", "hits", "age_s",
                    "entries_left", "bytes_left"),
          doc="LRU eviction under the prefix store's byte budget "
              "(pinned entries are never evicted)"),
    _spec("prefix_insert", required=("request_id",),
          optional=("span_tokens", "bytes", "entries", "adapter", "replica"),
          doc="a completed prefill's chunk-aligned prefix pane entered "
              "the store"),
    # -- serving: paged KV (page pool + page-table attention) --------------
    _spec("page_admit", required=("request_id",),
          optional=("slot", "pages_reserved", "pool_free", "replica"),
          doc="paged admission reserved the request's worst-case page "
              "need from the pool (admission gates on free pages, not "
              "free slots)"),
    _spec("page_share", required=("request_id",),
          optional=("slot", "n_pages", "span_tokens", "late", "pool_free",
                    "replica"),
          doc="a paged prefix hit: the slot's table now references the "
              "stored entry's shared refcounted pages — zero pane-copy "
              "bytes, zero forward FLOPs for the span"),
    _spec("page_release", required=("slot",),
          optional=("n_pages", "pages_freed", "pages_unreserved",
                    "pool_free", "replica"),
          doc="slot retirement decrefed its table columns (shared pages "
              "survive under the store/co-sharers) and returned the "
              "unused reservation to the pool"),
    _spec("page_pool_exhausted", required=("request_id",),
          optional=("pages_needed", "pages_available", "replica"),
          doc="paged admission refused the queue head: the pool cannot "
              "cover its worst-case need — the request re-queues at the "
              "front and retries after the next release (one event per "
              "exhaustion episode)"),
    # -- perf observatory -------------------------------------------------
    _spec("bench_result", required=("name",),
          optional=("metric", "value", "unit", "n_repeats", "quick",
                    "fingerprint_sha"),
          doc="one BenchResult landed (obs/perf.py): a bench arm's "
              "metrics JSONL records what it measured, so the perf "
              "gate's differential diagnosis can join telemetry to "
              "the bench row it belongs to"),
    # -- serving: engine lifecycle ----------------------------------------
    _spec("serve_warmup",
          optional=("n_prefill_buckets", "buckets", "seconds", "n_slots",
                    "max_len", "kv_quant", "prefix_cache", "prefill_chunk",
                    "kv_bytes_per_slot", "prefix_pane_tokens", "spec_k",
                    "drafter", "replica", "kv_paged", "page_tokens",
                    "pool_pages", "sp", "prompt_pane_tokens", "max_prompt"),
          doc="prefill programs + decode (or spec verify) program "
              "compiled; watchers frozen; records the KVCachePolicy "
              "(quant/chunk/prefix), the speculative config "
              "(spec_k/drafter) when on, and the seq-sharded prefill "
              "geometry (sp/prompt_pane_tokens/max_prompt) on "
              "--serve_sp engines"),
    _spec("serve_summary", open_fields=True,
          doc="shutdown stats snapshot (histogram percentiles, counters)"),
    _spec("serve_error", required=("error",),
          optional=("n_failed", "failed_request_ids", "replica"),
          doc="engine died; every in-flight/queued request failed"),
    _spec("engine_restart", required=("reason",),
          optional=("detail", "n_restart", "max_restarts", "backoff_s",
                    "n_inflight_failed", "failed_request_ids",
                    "queue_depth", "replica"),
          doc="supervisor abandoned a wedged loop and restarted it"),
    # -- serving: fleet tier (serving/router.py) ---------------------------
    _spec("serve_fleet", required=("phase",),
          optional=("n_replicas", "tp", "sp", "disjoint_devices",
                    "n_adapters", "seconds"),
          doc="router lifecycle bracketing (phase: build|end): replica "
              "count, tensor-parallel x sequence-parallel degrees, "
              "whether replicas got disjoint device slices"),
    _spec("replica_drain", required=("replica", "phase"),
          optional=("timeout_s", "n_active", "queue_depth",
                    "n_redispatched", "n_preempted", "seconds"),
          doc="one replica drained out of the fleet (phase: start|end); "
              "its queued work re-dispatched onto live replicas"),
    _spec("replica_restart", required=("replica",),
          optional=("seconds",),
          doc="a drained/dead replica re-entered dispatch as a fresh "
              "engine (its own warmup compiles, then frozen watchers)"),
    _spec("router_redispatch", required=("request_id",),
          optional=("from_replica", "to_replica", "adapter"),
          doc="one queued request moved between replicas during a "
              "replica drain — same Request handle, zero client impact"),
    # -- serving: cross-process fleet (serving/fleet.py) -------------------
    _spec("worker_spawn", required=("replica", "pid"),
          optional=("restarts", "seconds"),
          doc="a supervised worker process came up and passed its ready "
              "handshake (restarts counts prior incarnations)"),
    _spec("worker_heartbeat_missed", required=("replica",),
          optional=("age_s", "timeout_s", "pid"),
          doc="a live worker went silent past the heartbeat timeout — "
              "the supervisor kills it (the death path follows)"),
    _spec("worker_dead", required=("replica", "reason"),
          optional=("pid", "queued_redispatched", "inflight_failed",
                    "restarts"),
          doc="a worker process died (reason: pipe_eof|exit_N|"
              "heartbeat_missed|events_lost): queued work re-dispatched "
              "onto survivors, in-flight failed typed"),
    _spec("worker_restart", required=("replica", "restarts"),
          optional=("backoff_s", "downtime_s", "pid"),
          doc="the supervisor restarted a dead worker's PROCESS after "
              "exponential backoff; it re-enters dispatch"),
    _spec("pane_handoff", required=("from_replica", "to_replica"),
          optional=("entries", "imported", "bytes", "seconds"),
          doc="a draining worker's hot PrefixStore panes shipped over "
              "the transport to an adopting replica (keys are config-"
              "fingerprinted, so they transfer verbatim)"),
    _spec("clock_sync", required=("replica", "offset_s", "uncertainty_s"),
          optional=("rtt_s", "incarnation", "pid", "source", "n_samples"),
          doc="NTP-style worker-clock offset estimate from an RPC "
              "round-trip midpoint: offset_s = worker wall clock minus "
              "supervisor wall clock, bounded by uncertainty_s = rtt/2 "
              "(source: ping|heartbeat). The fleet exporter uses the "
              "min-uncertainty sample per incarnation to shift worker "
              "rows onto the supervisor's timeline"),
    _spec("incident_snapshot", required=("reason", "path"),
          optional=("n_events", "replica"),
          doc="the fleet's bounded in-memory event ring was snapshotted "
              "to an incident file (worker death / restart-budget "
              "exhaustion) — the file holds the last N fleet events "
              "leading up to the incident"),
    _spec("drain", required=("phase",),
          optional=("timeout_s", "n_active", "queue_depth", "n_preempted",
                    "seconds", "requests_finished", "replica"),
          doc="graceful drain bracketing events (phase: start|end)"),
    # -- memory observatory (obs/memory.py) --------------------------------
    _spec("memory_snapshot", required=("source", "components"),
          optional=("total_bytes", "device_bytes", "host_bytes",
                    "capacity_bytes", "headroom_bytes", "labeled",
                    "replica"),
          doc="one MemoryLedger cadence snapshot: component -> bytes, "
              "measured from the live pytrees (nbytes sums — "
              "deterministic, so the trace's memory counter tracks are "
              "byte-identical across identical runs). labeled = the "
              "attribution series (per-tenant live KV, per-namespace "
              "prefix bytes, per-tenant adapter rows)"),
    _spec("memory_drift", required=("component", "reason"),
          optional=("expected_bytes", "measured_bytes", "delta_bytes",
                    "streak", "pinned_bytes", "pinned_entries",
                    "device_bytes", "ledger_bytes", "source", "replica"),
          doc="the leak detector fired: a component diverged from its "
              "byte-exact expectation (reason: reconcile), only ever "
              "grows (monotonic_growth), violated a probe invariant "
              "(e.g. pinned_orphan — a prefix pane still pinned at a "
              "cadence boundary), or the ledger diverged from "
              "device.memory_stats() (device_divergence)"),
    _spec("memory_pressure", required=("headroom_bytes", "capacity_bytes"),
          optional=("used_frac", "threshold_frac", "device_bytes",
                    "total_bytes", "components", "labeled", "source",
                    "replica"),
          doc="near-OOM flight recorder: device components crossed "
              "pressure_frac of capacity — the event carries the FULL "
              "component breakdown so the post-mortem has the "
              "composition at the moment headroom vanished"),
]

#: kind -> EventSpec. The single source of truth the GL04x lint, the
#: renderer and the trace exporter consume.
EVENTS: Dict[str, EventSpec] = {s.name: s for s in _EVENT_LIST}


def validate_event(kind: str, fields: Dict[str, Any]) -> List[str]:
    """Schema-check one event emission; returns a list of problems
    (empty = conforming). Used by the analyzer's runtime twin and the
    telemetry tests — emission itself stays unvalidated (a telemetry row
    must never crash the run it observes)."""
    spec = EVENTS.get(kind)
    if spec is None:
        return [f"unregistered event kind '{kind}'"]
    problems = []
    missing = spec.required - set(fields) - ALWAYS_ALLOWED_FIELDS
    if missing:
        problems.append(
            f"event '{kind}' missing required field(s) "
            f"{sorted(missing)}")
    if not spec.open_fields:
        unknown = set(fields) - spec.known_fields()
        if unknown:
            problems.append(
                f"event '{kind}' carries undeclared field(s) "
                f"{sorted(unknown)}")
    return problems


# sanity: the trace-exporter groups must be subsets of the registry —
# an entry here that no emitter can produce is schema drift in the other
# direction (also test-asserted so a failure names the stray entry)
for _group in (INCIDENT_EVENTS, REQUEST_EVENTS, SERVING_LIFECYCLE_EVENTS):
    for _name in _group:
        assert _name in EVENTS, f"{_name} not in the event registry"
