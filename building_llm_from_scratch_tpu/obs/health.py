"""In-graph per-layer-group training-health metrics.

The global pre-clip ``grad_norm`` in the step metrics says *that* something
went wrong, never *where*: a loss spike caused by one block's exploding
gradients, a clipped update silently capping progress, or a single layer
going non-finite all look identical from one scalar. This module computes
the localized view INSIDE the jitted train step — per-layer-group gradient
norms, parameter norms, update norms (post-clip: ``optax.clip_by_global_norm``
sits first in the optimizer chain, so the update already reflects it),
update-to-param ratios, and first-non-finite-group localization — as
compact ``(n_groups,)`` arrays in the metrics pytree. The host only ever
*appends* the device arrays and fetches them at the logging cadence, so the
no-per-step-host-sync invariant from the obs/ round holds unchanged.

Grouping: the trainable pytree's top-level keys become groups, except
``"blocks"`` — whose leaves are stacked per-layer ``(L, ...)`` tensors
(models/transformer.py scans layers) — which expands into one group per
transformer block. The same rule applied to a LoRA adapter tree (also
rooted at ``blocks``/``head``) or a pipeline-stage tree (stacked leading
stage axis) yields per-block / per-stage groups with no special cases.
Keys are sorted so the group order is identical across the
``grad_accum=1``, scan-accumulated, shard_map and pipeline step builders —
the arrays must line up with ``group_names`` computed host-side.

Everything here is pure ``jax.numpy`` on already-materialized trees: no
host callbacks, no new collectives (under GSPMD the reductions shard like
any other compute), and the whole bundle is O(n_groups) scalars of
device->host traffic per fetch.
"""

from __future__ import annotations

from typing import Any, Dict, List

import jax
import jax.numpy as jnp

#: Top-level pytree key whose leaves carry a stacked leading layer axis.
STACKED_KEY = "blocks"

#: Metric names emitted per group (each a (n_groups,) float32 array).
HEALTH_ARRAYS = ("grad_norm", "param_norm", "update_norm", "update_ratio")


def _stacked_len(tree: Dict[str, Any]) -> int:
    """Leading-axis length shared by the stacked subtree's leaves (the
    layer count for ``blocks``), or 0 when absent/empty."""
    sub = tree.get(STACKED_KEY)
    if not isinstance(sub, dict):
        return 0
    leaves = jax.tree_util.tree_leaves(sub)
    return int(leaves[0].shape[0]) if leaves else 0


def group_names(tree: Dict[str, Any]) -> List[str]:
    """Ordered group labels for ``tree`` (host-side; pairs with the arrays
    ``group_health`` returns). Sorted top-level keys, with the stacked
    ``blocks`` subtree expanded to ``block_00..block_{L-1}``."""
    names: List[str] = []
    for key in sorted(tree):
        if key == STACKED_KEY:
            names.extend(f"block_{i:02d}" for i in range(_stacked_len(tree)))
        else:
            names.append(str(key))
    return names


def _group_sumsq(tree: Dict[str, Any]) -> jnp.ndarray:
    """(n_groups,) fp32 sum-of-squares per group, in ``group_names``
    order. Per-layer values come from one vectorized reduction over each
    stacked leaf's trailing axes — no per-layer slicing, so the compiled
    program stays O(n_leaves) reductions regardless of depth."""
    parts: List[jnp.ndarray] = []
    for key in sorted(tree):
        leaves = jax.tree_util.tree_leaves(tree[key])
        if key == STACKED_KEY:
            L = _stacked_len(tree)
            acc = jnp.zeros((L,), jnp.float32)
            for leaf in leaves:
                x = leaf.astype(jnp.float32)
                acc = acc + jnp.sum(jnp.square(x),
                                    axis=tuple(range(1, x.ndim)))
            parts.append(acc)
        else:
            acc0 = jnp.zeros((), jnp.float32)
            for leaf in leaves:
                x = leaf.astype(jnp.float32)
                acc0 = acc0 + jnp.sum(jnp.square(x))
            parts.append(acc0[None])
    if not parts:
        return jnp.zeros((0,), jnp.float32)
    return jnp.concatenate(parts)


def _group_nonfinite(tree: Dict[str, Any]) -> jnp.ndarray:
    """(n_groups,) bool: any non-finite element in the group. Computed
    directly on the leaves — a sum-of-squares can overflow to inf on its
    own, which would mislabel a merely-large group as broken."""
    parts: List[jnp.ndarray] = []
    for key in sorted(tree):
        leaves = jax.tree_util.tree_leaves(tree[key])
        if key == STACKED_KEY:
            L = _stacked_len(tree)
            acc = jnp.zeros((L,), bool)
            for leaf in leaves:
                acc = acc | jnp.any(
                    ~jnp.isfinite(leaf.astype(jnp.float32)),
                    axis=tuple(range(1, leaf.ndim)))
            parts.append(acc)
        else:
            acc0 = jnp.zeros((), bool)
            for leaf in leaves:
                acc0 = acc0 | jnp.any(~jnp.isfinite(leaf.astype(jnp.float32)))
            parts.append(acc0[None])
    if not parts:
        return jnp.zeros((0,), bool)
    return jnp.concatenate(parts)


def group_norms(tree: Dict[str, Any]) -> jnp.ndarray:
    """(n_groups,) fp32 L2 norms per group, in ``group_names`` order —
    the public pre-clip view of ``_group_sumsq`` (the fused multi-LoRA
    step clips each job's gradient by ITS group norm, so it needs the
    norms before it can build the updates ``group_health`` wants)."""
    return jnp.sqrt(_group_sumsq(tree))


def first_nonfinite_group(tree: Dict[str, Any]) -> jnp.ndarray:
    """Index (int32 scalar) of the first group containing a non-finite
    value, or -1 when all groups are finite. Index into ``group_names``."""
    bad = _group_nonfinite(tree)
    if bad.shape[0] == 0:
        return jnp.asarray(-1, jnp.int32)
    return jnp.where(jnp.any(bad),
                     jnp.argmax(bad).astype(jnp.int32),
                     jnp.asarray(-1, jnp.int32))


def group_health(grads: Dict[str, Any], params: Dict[str, Any],
                 updates: Dict[str, Any]) -> Dict[str, jnp.ndarray]:
    """The health bundle for one optimizer step.

    ``grads`` are pre-clip (matching the step's global ``grad_norm``);
    ``updates`` are what ``optax.apply_updates`` adds — post-clip,
    post-adam, post-LR, so clipping and any optimizer pathology are
    visible; ``params`` are the post-update trainable leaves.

    Returns (all fp32 unless noted):
      - ``grad_norm`` / ``param_norm`` / ``update_norm``: (G,) L2 norms;
      - ``update_ratio``: (G,) update_norm / param_norm (the classic
        should-be-~1e-3 training-health signal; 0-param groups report 0);
      - ``first_nonfinite``: int32 scalar group index, -1 when healthy.
    """
    g = jnp.sqrt(_group_sumsq(grads))
    p = jnp.sqrt(_group_sumsq(params))
    u = jnp.sqrt(_group_sumsq(updates))
    ratio = u / jnp.maximum(p, 1e-12)
    return {
        "grad_norm": g,
        "param_norm": p,
        "update_norm": u,
        "update_ratio": ratio,
        "first_nonfinite": first_nonfinite_group(grads),
    }


def nonfinite_group_name(names: List[str], fetched: Dict[str, Any]):
    """Resolve a fetched bundle's ``first_nonfinite`` index to its group
    name (None when healthy/out of range) — the ONE place the sentinel
    convention lives, shared by the JSONL health row and the watchdog
    context so they can never disagree."""
    import numpy as np

    idx = int(np.asarray(fetched.get("first_nonfinite", -1)))
    return names[idx] if 0 <= idx < len(names) else None


def describe_health(names: List[str], fetched: Dict[str, Any],
                    top_k: int = 3) -> Dict[str, Any]:
    """Host-side digest of one fetched health bundle for event attachment
    (the watchdog_halt path): names the first non-finite group (if any)
    and the ``top_k`` groups by gradient norm, so a halt diagnostic says
    *which layer* instead of just *diverged*."""
    import numpy as np

    out: Dict[str, Any] = {}
    out["first_nonfinite_group"] = nonfinite_group_name(names, fetched)
    gn = np.asarray(fetched.get("grad_norm", []), dtype=np.float64)
    if gn.size and len(names) == gn.size:
        order = np.argsort(gn)[::-1][:top_k]
        out["top_grad_norm_groups"] = [
            {"group": names[int(i)], "grad_norm": round(float(gn[int(i)]), 6)}
            for i in order]
    return out


def health_summary_line(names: List[str], fetched: Dict[str, Any]) -> str:
    """One log line: 'health: max grad block_07 1.2e+01, max ratio head
    3.1e-03' — for humans tailing the log while the JSONL carries the
    full arrays (the trainer emits it at eval cadence)."""
    import numpy as np

    gn = np.asarray(fetched.get("grad_norm", []), dtype=np.float64)
    ur = np.asarray(fetched.get("update_ratio", []), dtype=np.float64)
    if not gn.size or len(names) != gn.size:
        return "health: n/a"
    gi = int(np.argmax(gn))
    line = f"health: max grad {names[gi]} {gn[gi]:.2e}"
    if ur.size == gn.size:
        ri = int(np.argmax(ur))
        line += f", max ratio {names[ri]} {ur[ri]:.2e}"
    return line
