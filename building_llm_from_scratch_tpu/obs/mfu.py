"""Analytic model FLOPs + MFU against TPU-generation peak compute.

MFU (model FLOPs utilization) is the throughput number the TPU systems
literature reports (PaLM App. B; the Gemma-on-TPU and LoRAFusion comparison
studies in PAPERS.md attribute wins the same way): achieved model FLOPs/s
over the chip's peak, counting only the FLOPs the MODEL requires — remat
recompute does not inflate it.

FLOPs/token uses the standard decomposition:

    6 * N_matmul  +  12 * n_layers * emb_dim * seq_len

where ``N_matmul`` is the parameter count EXCLUDING embedding lookups
(gathers, no FLOPs) but INCLUDING the output head, 6 = fwd(2) + bwd(4)
multiply-accumulates per parameter per token, and the second term is the
attention score/value matmuls (QK^T and AV, fwd+bwd, PaLM's ``12 L H Q T``
convention — no causal discount).

Peak FLOPs come from a small per-generation table keyed on
``device.device_kind`` (bf16 dense peak per chip). Unknown kinds — CPU test
meshes in particular — report ``None`` and the callers print "n/a" rather
than a made-up number.
"""

from __future__ import annotations

from typing import Optional

from building_llm_from_scratch_tpu.configs import ModelConfig

#: Per-chip public specs by device_kind substring (lowercased):
#: (peak bf16 dense FLOPs/s, HBM bytes/s). The ONE table — bench.py's
#: roofline math and the trainer's MFU both read it, so a new TPU
#: generation is one line here, not a hunt for private copies.
#: Order matters: first match wins, so longer/more specific keys go first
#: (jax reports v5e as "TPU v5 lite" and v5p as plain "TPU v5").
DEVICE_SPECS = (
    ("v6e", (918e12, 1640e9)),        # Trillium
    ("v6 lite", (918e12, 1640e9)),
    ("v6", (918e12, 1640e9)),
    ("v5p", (459e12, 2765e9)),
    ("v5e", (197e12, 819e9)),
    ("v5 lite", (197e12, 819e9)),
    ("v5litepod", (197e12, 819e9)),
    ("v5", (459e12, 2765e9)),
    ("v4", (275e12, 1228e9)),
    ("v3", (123e12, 900e9)),
    ("v2", (45e12, 700e9)),
)

#: Back-compat view: (key, peak FLOPs) pairs.
TPU_PEAK_FLOPS = tuple((k, spec[0]) for k, spec in DEVICE_SPECS)


def flops_per_token(cfg: ModelConfig, seq_len: Optional[int] = None) -> int:
    """Analytic train-step FLOPs per token (fwd+bwd) for this config."""
    t = cfg.context_length if seq_len is None else seq_len
    n_matmul = cfg.num_params(exclude_embeddings=True)
    attention = 12 * cfg.n_layers * cfg.emb_dim * t
    return 6 * n_matmul + attention


def device_specs(device=None) -> Optional[tuple]:
    """(peak bf16 FLOPs, HBM bytes/s) for one chip, or None when unknown
    (CPU/GPU test backends). Never initializes a backend the caller
    hasn't."""
    if device is None:
        try:
            import jax

            device = jax.local_devices()[0]
        except Exception:
            return None
    kind = str(getattr(device, "device_kind", "")).lower()
    if "tpu" not in kind and not kind.startswith("v"):
        return None
    for key, spec in DEVICE_SPECS:
        if key in kind:
            return spec
    return None


def device_peak_flops(device=None) -> Optional[float]:
    """Peak bf16 FLOPs for one chip, or None when unknown."""
    spec = device_specs(device)
    return spec[0] if spec is not None else None


def mfu_from_flops(tokens_per_s: float, flops_per_token: float,
                   n_devices: Optional[int] = None,
                   peak: Optional[float] = None) -> Optional[float]:
    """MFU for an arbitrary FLOPs/token figure — the shared denominator
    math for the analytic estimate AND the HLO-measured cross-check
    (obs/compile.py's ``cost_analysis`` FLOPs). None when the chip peak
    is unknown or inputs are degenerate."""
    if peak is None:
        peak = device_peak_flops()
    if peak is None or tokens_per_s <= 0 or not flops_per_token:
        return None
    if n_devices is None:
        import jax

        n_devices = jax.local_device_count()
    return tokens_per_s * flops_per_token / (peak * max(1, n_devices))


def compute_mfu(tokens_per_s: float, cfg: ModelConfig,
                n_devices: Optional[int] = None,
                peak: Optional[float] = None,
                seq_len: Optional[int] = None) -> Optional[float]:
    """MFU in [0, 1] for a measured throughput, or None when the peak is
    unknown.

    ``tokens_per_s`` and ``n_devices`` must describe the same scope: the
    trainer passes its PER-PROCESS throughput with
    ``jax.local_device_count()``, which equals the global ratio on
    symmetric pods.
    """
    return mfu_from_flops(tokens_per_s, flops_per_token(cfg, seq_len),
                          n_devices=n_devices, peak=peak)


def format_mfu(mfu: Optional[float]) -> str:
    """Log-line rendering: '41.4% MFU' or 'MFU n/a' off-TPU."""
    return "MFU n/a" if mfu is None else f"{100.0 * mfu:.1f}% MFU"
