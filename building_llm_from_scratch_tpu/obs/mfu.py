"""Analytic model FLOPs + MFU against TPU-generation peak compute.

MFU (model FLOPs utilization) is the throughput number the TPU systems
literature reports (PaLM App. B; the Gemma-on-TPU and LoRAFusion comparison
studies in PAPERS.md attribute wins the same way): achieved model FLOPs/s
over the chip's peak, counting only the FLOPs the MODEL requires — remat
recompute does not inflate it.

FLOPs/token uses the standard decomposition:

    6 * N_matmul  +  12 * n_layers * emb_dim * seq_len

where ``N_matmul`` is the parameter count EXCLUDING embedding lookups
(gathers, no FLOPs) but INCLUDING the output head, 6 = fwd(2) + bwd(4)
multiply-accumulates per parameter per token, and the second term is the
attention score/value matmuls (QK^T and AV, fwd+bwd, PaLM's ``12 L H Q T``
convention — no causal discount).

Peak FLOPs come from a small per-generation table keyed on
``device.device_kind`` (bf16 dense peak per chip). Unknown kinds — CPU test
meshes in particular — report ``None`` and the callers print "n/a" rather
than a made-up number.
"""

from __future__ import annotations

from typing import Optional

from building_llm_from_scratch_tpu.configs import ModelConfig

#: bf16 dense peak FLOPs per CHIP, by device_kind substring (lowercased).
#: Order matters: first match wins, so longer/more specific keys go first.
TPU_PEAK_FLOPS = (
    ("v6e", 918e12),         # Trillium
    ("v6", 918e12),
    ("v5p", 459e12),
    ("v5e", 197e12),
    ("v5 lite", 197e12),     # jax reports v5e as "TPU v5 lite"
    ("v5litepod", 197e12),
    ("v5", 459e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)


def flops_per_token(cfg: ModelConfig, seq_len: Optional[int] = None) -> int:
    """Analytic train-step FLOPs per token (fwd+bwd) for this config."""
    t = cfg.context_length if seq_len is None else seq_len
    n_matmul = cfg.num_params(exclude_embeddings=True)
    attention = 12 * cfg.n_layers * cfg.emb_dim * t
    return 6 * n_matmul + attention


def device_peak_flops(device=None) -> Optional[float]:
    """Peak bf16 FLOPs for one chip, or None when unknown (CPU/GPU test
    backends). Never initializes a backend the caller hasn't."""
    if device is None:
        try:
            import jax

            device = jax.local_devices()[0]
        except Exception:
            return None
    kind = str(getattr(device, "device_kind", "")).lower()
    if "tpu" not in kind and not kind.startswith("v"):
        return None
    for key, peak in TPU_PEAK_FLOPS:
        if key in kind:
            return peak
    return None


def compute_mfu(tokens_per_s: float, cfg: ModelConfig,
                n_devices: Optional[int] = None,
                peak: Optional[float] = None,
                seq_len: Optional[int] = None) -> Optional[float]:
    """MFU in [0, 1] for a measured throughput, or None when the peak is
    unknown.

    ``tokens_per_s`` and ``n_devices`` must describe the same scope: the
    trainer passes its PER-PROCESS throughput with
    ``jax.local_device_count()``, which equals the global ratio on
    symmetric pods.
    """
    if peak is None:
        peak = device_peak_flops()
    if peak is None or tokens_per_s <= 0:
        return None
    if n_devices is None:
        import jax

        n_devices = jax.local_device_count()
    achieved = tokens_per_s * flops_per_token(cfg, seq_len)
    return achieved / (peak * max(1, n_devices))


def format_mfu(mfu: Optional[float]) -> str:
    """Log-line rendering: '41.4% MFU' or 'MFU n/a' off-TPU."""
    return "MFU n/a" if mfu is None else f"{100.0 * mfu:.1f}% MFU"
