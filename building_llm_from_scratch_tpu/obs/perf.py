"""The perf observatory: schema'd benchmark results, structural HLO
fingerprints, and the machine-readable perf trajectory.

Before this module, the repo's perf methodology was weaker than its
telemetry: ``bench.py`` printed loose single-metric JSON lines with no
environment capture, no repeat/variance discipline, and no baseline gate,
and the BENCH_r01–r05 history was five opaque snapshot files no tool could
read. ROADMAP mandates that perf work prove itself via CPU A/Bs, HLO cost
analysis and zero-recompile invariants — this module is where those proofs
become ARTIFACTS:

  - ``BenchResult`` — the one schema every ``bench.py`` entry returns:
    headline value + unit, named extra metrics (each with a unit), repeat
    stats (min/median/mean/stddev over ``--repeats k``), an ``env`` block
    (jax version, backend, device kind/count, mesh, git sha, argv) and a
    **structural fingerprint** of everything XLA compiled during the run.

  - ``FingerprintCollector`` — a context manager that registers with
    ``obs/compile.py``: every ``CompileWatcher`` capture (the trainer step,
    the serving engine's prefill/decode programs) reports its label, arg
    signature, HLO cost-analysis FLOPs and memory breakdown here. The
    resulting fingerprint is TIMING-FREE and deterministic on CPU — two
    identical runs produce byte-identical structural parts — which is what
    lets ``scripts/perf_gate.py`` gate perf regressions in CI without
    trusting a shared container's wall clock.

  - ``compare_structural`` / ``compare_timing`` — the two gate modes.
    Structural: FLOPs / program count / arg signatures / recompile count /
    HBM breakdown must match the baseline EXACTLY; any drift yields a
    per-program differential finding (the offending program is NAMED).
    Timing: variance-aware; fires only when the fresh median falls past a
    noise floor derived from both arms' repeat stddev.

  - ``TrajectoryStore`` — reads/writes ``results/perf/*.jsonl``: one JSONL
    per bench name, one ``BenchResult`` row per measurement, so the perf
    history is machine-readable. ``backfill_bench_history`` converts the
    legacy BENCH_r01–r05 snapshot files into trajectory rows once.

Stdlib-only at import time (jax is imported lazily inside ``bench_env``),
so the gate's pure-compare paths (``--report``, baseline diffs) run
without touching the accelerator stack.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import math
import os
import re
import statistics
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional

#: Version of the BenchResult row schema. Bump when a field changes
#: meaning; rows carry it so the gate can refuse to compare across
#: incompatible schemas instead of mis-diagnosing.
PERF_SCHEMA_VERSION = 1

#: Row discriminators in a bench/trajectory JSONL.
PERF_ROW_TYPES = ("header", "bench")

#: Structural fingerprint keys compared by the gate (everything else in a
#: fingerprint — timing, stability flags — is informational).
STRUCTURAL_KEYS = ("programs", "n_programs", "n_recompiles",
                   "recompile_labels")

#: Per-program structural fields (exact-match in the gate). ``memory`` is
#: the HBM breakdown dict; ``tokens_per_step`` is shape-derived.
PROGRAM_STRUCTURAL_FIELDS = ("label", "arg_sig", "flops", "transcendentals",
                             "bytes_accessed", "memory", "tokens_per_step")


# ---------------------------------------------------------------------------
# Environment capture
# ---------------------------------------------------------------------------

def git_info(root: Optional[str] = None) -> Dict[str, Any]:
    """{"git_sha": ..., "git_dirty": bool} for ``root`` (default: this
    file's repo), or {} when git is unavailable — env capture must never
    fail a bench run."""
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
    try:
        sha = subprocess.run(
            ["git", "rev-parse", "HEAD"], cwd=root, capture_output=True,
            text=True, timeout=10)
        if sha.returncode != 0:
            return {}
        dirty = subprocess.run(
            ["git", "status", "--porcelain"], cwd=root,
            capture_output=True, text=True, timeout=10)
        return {"git_sha": sha.stdout.strip(),
                "git_dirty": bool(dirty.stdout.strip())
                if dirty.returncode == 0 else None}
    except (OSError, subprocess.SubprocessError):
        return {}


def bench_env(mesh: Optional[Dict[str, int]] = None) -> Dict[str, Any]:
    """The ``env`` block every BenchResult carries: jax version, backend,
    device kind/count, mesh, git sha, argv. A number without this block is
    not comparable to anything — the Gemma-on-TPU comparison discipline
    (PAPERS.md): fixed workloads need recorded environments."""
    env: Dict[str, Any] = {
        "python": sys.version.split()[0],
        "argv": list(sys.argv),
        "mesh": mesh,
    }
    env.update(git_info())
    try:
        import jax

        devices = jax.devices()
        env.update({
            "jax_version": jax.__version__,
            "backend": jax.default_backend(),
            "device_kind": devices[0].device_kind if devices else "unknown",
            "device_count": len(devices),
            "local_device_count": jax.local_device_count(),
            "process_count": jax.process_count(),
        })
    except Exception:                      # pragma: no cover - env capture
        env.setdefault("jax_version", None)
    return env


# ---------------------------------------------------------------------------
# Structural fingerprint capture (via obs/compile.py's CompileWatcher)
# ---------------------------------------------------------------------------

def _sig_digest(sig: Any) -> str:
    """Stable short digest of one program's argument signature: a tuple
    of per-argument ``tree_signature`` tuples, each a sequence of
    (path, shape, dtype, sharding) leaf entries. Shardings are rendered
    through their spec/str like the recompile diff does, so the digest
    is deterministic across identical runs."""
    rendered = []
    for arg_sig in sig or ():
        arg = []
        for entry in arg_sig or ():
            path, shape, dtype = entry[0], entry[1], entry[2]
            sharding = entry[3] if len(entry) > 3 else None
            if sharding is not None:
                spec = getattr(sharding, "spec", None)
                sharding = str(spec if spec is not None else sharding)
            arg.append([str(path), list(shape), str(dtype), sharding])
        rendered.append(arg)
    blob = json.dumps(rendered, sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


class FingerprintCollector:
    """Collects every CompileWatcher capture/recompile while installed.

    Use as a context manager around one bench run::

        with FingerprintCollector() as col:
            result = bench_fn()
        result.fingerprint = col.fingerprint()

    Thread-safe: serving-engine programs may compile from engine threads.
    """

    def __init__(self):
        import threading

        self._lock = threading.Lock()
        self._programs: List[Dict[str, Any]] = []    # guarded-by: _lock
        self._recompiles: List[Dict[str, Any]] = []  # guarded-by: _lock
        self._compile_seconds = 0.0                  # guarded-by: _lock

    # -- CompileWatcher callbacks (obs/compile.py) -----------------------

    def on_compile(self, label: str, sig: Any, stats: Dict[str, Any],
                   n_tokens: Optional[int] = None) -> None:
        prog: Dict[str, Any] = {"label": label, "arg_sig": _sig_digest(sig)}
        for key in ("flops", "transcendentals", "bytes_accessed"):
            if isinstance(stats.get(key), (int, float)):
                prog[key] = stats[key]
        mem = stats.get("memory")
        if isinstance(mem, dict) and mem:
            prog["memory"] = dict(mem)
        if n_tokens:
            prog["tokens_per_step"] = int(n_tokens)
        with self._lock:
            self._programs.append(prog)
            self._compile_seconds += float(
                stats.get("compile_seconds") or 0.0)

    def on_recompile(self, label: str, diff: List[Dict[str, Any]]) -> None:
        with self._lock:
            self._recompiles.append(
                {"label": label, "n_changed_leaves": len(diff),
                 "leaves": [d.get("leaf") for d in diff[:8]]})

    # -- lifecycle -------------------------------------------------------

    def __enter__(self) -> "FingerprintCollector":
        from building_llm_from_scratch_tpu.obs import compile as _compile

        _compile.add_collector(self)
        return self

    def __exit__(self, *exc) -> None:
        from building_llm_from_scratch_tpu.obs import compile as _compile

        _compile.remove_collector(self)

    # -- the fingerprint -------------------------------------------------

    def fingerprint(self) -> Dict[str, Any]:
        """Structural fingerprint + timing info for everything compiled
        while installed. The structural part (``structural_part`` strips
        the rest) is deterministic across identical runs; ``timing`` is
        informational (the trajectory tracks compile seconds, the gate
        never compares them structurally)."""
        with self._lock:
            programs = [dict(p) for p in self._programs]
            recompiles = [dict(r) for r in self._recompiles]
            compile_s = self._compile_seconds
        # chronologically-last capture kept aside (non-structural): the
        # sorted programs list loses which program was compiled LAST,
        # which is what the legacy stdout line's HLO fields report
        last = dict(programs[-1]) if programs else None
        programs.sort(key=lambda p: (p["label"], p["arg_sig"]))
        return {
            "programs": programs,
            "n_programs": len(programs),
            "n_recompiles": len(recompiles),
            "recompile_labels": sorted({r["label"] for r in recompiles}),
            "recompile_diffs": recompiles,
            "last_program": last,
            "timing": {"compile_seconds_total": round(compile_s, 4)},
        }


def structural_part(fingerprint: Optional[Dict[str, Any]]
                    ) -> Dict[str, Any]:
    """The timing-free slice of a fingerprint the gate compares: per-
    program FLOPs/signatures/memory, program count, recompile count."""
    fingerprint = fingerprint or {}
    out: Dict[str, Any] = {}
    for key in STRUCTURAL_KEYS:
        if key == "programs":
            out["programs"] = [
                {f: p[f] for f in PROGRAM_STRUCTURAL_FIELDS if f in p}
                for p in fingerprint.get("programs", ())]
        else:
            out[key] = fingerprint.get(key, 0 if key != "recompile_labels"
                                       else [])
    return out


def fingerprint_digest(fingerprint: Optional[Dict[str, Any]]) -> str:
    """sha256 of the canonical-JSON structural part — the byte-identity
    the determinism tests pin."""
    blob = json.dumps(structural_part(fingerprint), sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest()


# ---------------------------------------------------------------------------
# BenchResult: the one schema every bench returns
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class BenchResult:
    """One benchmark measurement, self-describing.

    ``value``/``unit`` is the headline metric (what the trajectory plots
    and the timing gate compares); ``metrics`` holds named extra numbers,
    each ``{"value": v, "unit": u}``; ``detail`` is the bench's free-form
    arm breakdown (the dicts the serve benches print). The runner
    (``bench.run_bench``) fills ``repeats``/``env``/``fingerprint``.
    """

    name: str
    metric: str
    value: float
    unit: str = "tokens/sec/chip"
    metrics: Dict[str, Dict[str, Any]] = dataclasses.field(
        default_factory=dict)
    detail: Optional[Dict[str, Any]] = None
    repeats: Optional[Dict[str, Any]] = None
    env: Optional[Dict[str, Any]] = None
    fingerprint: Optional[Dict[str, Any]] = None
    vs_baseline: Optional[float] = None
    quick: bool = False
    time: Optional[float] = None
    source: Optional[str] = None    # backfill provenance (BENCH_r0N.json)

    def add_metric(self, key: str, value: float, unit: str) -> None:
        self.metrics[key] = {"value": value, "unit": unit}

    def metric_value(self, key: str) -> Optional[float]:
        entry = self.metrics.get(key)
        return entry.get("value") if isinstance(entry, dict) else None

    def to_row(self) -> Dict[str, Any]:
        row: Dict[str, Any] = {"type": "bench",
                               "perf_schema_version": PERF_SCHEMA_VERSION,
                               "name": self.name, "metric": self.metric,
                               "value": self.value, "unit": self.unit}
        for key in ("metrics", "detail", "repeats", "env", "fingerprint",
                    "vs_baseline", "time", "source"):
            val = getattr(self, key)
            if val is not None and val != {}:
                row[key] = val
        if self.quick:
            row["quick"] = True
        return row

    @classmethod
    def from_row(cls, row: Dict[str, Any]) -> "BenchResult":
        problems = validate_row(row)
        if problems:
            raise ValueError("invalid BenchResult row: "
                             + "; ".join(problems))
        kw = {f.name: row[f.name] for f in dataclasses.fields(cls)
              if f.name in row}
        return cls(**kw)


def validate_row(row: Dict[str, Any]) -> List[str]:
    """Schema-check one bench row; returns problems (empty = valid)."""
    problems = []
    if row.get("type") != "bench":
        problems.append(f"type must be 'bench', got {row.get('type')!r}")
    if not isinstance(row.get("name"), str) or not row.get("name"):
        problems.append("missing/empty 'name'")
    if not isinstance(row.get("metric"), str) or not row.get("metric"):
        problems.append("missing/empty 'metric'")
    if not isinstance(row.get("value"), (int, float)):
        problems.append("'value' must be a number")
    if not isinstance(row.get("unit"), str):
        problems.append("'unit' must be a string")
    ver = row.get("perf_schema_version")
    if not isinstance(ver, int):
        problems.append("missing 'perf_schema_version'")
    elif ver > PERF_SCHEMA_VERSION:
        problems.append(f"perf_schema_version {ver} is newer than this "
                        f"reader ({PERF_SCHEMA_VERSION})")
    metrics = row.get("metrics")
    if metrics is not None:
        if not isinstance(metrics, dict):
            problems.append("'metrics' must be a dict")
        else:
            for key, entry in metrics.items():
                if (not isinstance(entry, dict) or "value" not in entry
                        or "unit" not in entry):
                    problems.append(
                        f"metrics[{key!r}] must be {{value, unit}}")
    reps = row.get("repeats")
    if reps is not None and not (
            isinstance(reps, dict) and isinstance(reps.get("n"), int)):
        problems.append("'repeats' must carry an integer 'n'")
    env = row.get("env")
    if env is not None and not isinstance(env, dict):
        problems.append("'env' must be a dict")
    return problems


def repeat_stats(values: List[float]) -> Dict[str, Any]:
    """min/median/mean/stddev over a bench's repeated headline values —
    the variance discipline the timing gate's noise floor is derived
    from. ``stddev`` is the sample stddev (0.0 for n=1)."""
    vals = [float(v) for v in values]
    return {
        "n": len(vals),
        "values": [round(v, 4) for v in vals],
        "min": round(min(vals), 4),
        "median": round(statistics.median(vals), 4),
        "mean": round(statistics.fmean(vals), 4),
        "stddev": round(statistics.stdev(vals), 4) if len(vals) > 1 else 0.0,
    }


def header_row(**extra: Any) -> Dict[str, Any]:
    """The run-metadata header row (one per bench stdout stream / --json
    file): schema version + the env block. One constructor, so the two
    sinks can never diverge on what a header carries."""
    row: Dict[str, Any] = {"type": "header",
                           "perf_schema_version": PERF_SCHEMA_VERSION,
                           "time": time.time()}
    row.update(bench_env())
    row.update(extra)
    return row


def emit_bench_result(result: "BenchResult") -> None:
    """One ``bench_result`` event into the configured metrics JSONL, so a
    bench arm's telemetry file is self-describing about what it measured
    (the gate's differential diagnosis joins on it)."""
    from building_llm_from_scratch_tpu.obs.metrics import get_metrics

    get_metrics().event(
        "bench_result", name=result.name, metric=result.metric,
        value=round(float(result.value), 4), unit=result.unit,
        n_repeats=(result.repeats or {}).get("n"),
        quick=bool(result.quick),
        fingerprint_sha=fingerprint_digest(result.fingerprint))


# ---------------------------------------------------------------------------
# Gate comparisons
# ---------------------------------------------------------------------------

def _fmt_delta(base: float, fresh: float) -> str:
    if base:
        return f"{fresh - base:+.4g} ({100.0 * (fresh - base) / base:+.2f}%)"
    return f"{fresh - base:+.4g}"


def compare_structural(base_fp: Optional[Dict[str, Any]],
                       fresh_fp: Optional[Dict[str, Any]]
                       ) -> List[Dict[str, Any]]:
    """Timing-free differential between two structural fingerprints.

    Returns findings (empty = identical). Exact-match discipline: on the
    shared CPU container the fingerprint is deterministic, so ANY drift —
    per-program FLOPs, a new/removed program, an arg-signature change, a
    recompile, an HBM-breakdown byte — is a real structural change in
    what XLA was asked to build, and the finding NAMES the program."""
    base = structural_part(base_fp)
    fresh = structural_part(fresh_fp)
    findings: List[Dict[str, Any]] = []
    if base == fresh:
        return findings

    def field_diffs(label, sig, b, f):
        for field in ("flops", "transcendentals", "bytes_accessed",
                      "tokens_per_step"):
            if b.get(field) != f.get(field):
                findings.append({
                    "kind": f"{field}_delta", "program": label,
                    "arg_sig": sig, "base": b.get(field),
                    "fresh": f.get(field),
                    "detail": f"program '{label}' {field}: "
                              f"{b.get(field)} -> {f.get(field)} "
                              + (_fmt_delta(b[field], f[field])
                                 if isinstance(b.get(field), (int, float))
                                 and isinstance(f.get(field), (int, float))
                                 else "")})
        bm, fm = b.get("memory") or {}, f.get("memory") or {}
        if bm != fm:
            deltas = {k: (bm.get(k), fm.get(k))
                      for k in sorted(set(bm) | set(fm))
                      if bm.get(k) != fm.get(k)}
            findings.append({
                "kind": "memory_delta", "program": label,
                "arg_sig": sig, "base": bm, "fresh": fm,
                "detail": f"program '{label}' HBM breakdown changed: "
                          + ", ".join(f"{k} {v[0]} -> {v[1]}"
                                      for k, v in deltas.items())})

    base_progs = base.get("programs", [])
    fresh_progs = fresh.get("programs", [])
    labels = sorted({p["label"] for p in base_progs}
                    | {p["label"] for p in fresh_progs})
    for label in labels:
        b_by_sig = {p["arg_sig"]: p for p in base_progs
                    if p["label"] == label}
        f_by_sig = {p["arg_sig"]: p for p in fresh_progs
                    if p["label"] == label}
        for sig in sorted(set(b_by_sig) & set(f_by_sig)):
            field_diffs(label, sig, b_by_sig[sig], f_by_sig[sig])
        b_only = sorted(set(b_by_sig) - set(f_by_sig))
        f_only = sorted(set(f_by_sig) - set(b_by_sig))
        if not b_by_sig:
            for sig in f_only:
                findings.append({
                    "kind": "new_program", "program": label,
                    "arg_sig": sig, "base": None, "fresh": f_by_sig[sig],
                    "detail": f"NEW program '{label}' (sig {sig}, flops "
                              f"{f_by_sig[sig].get('flops')})"})
        elif not f_by_sig:
            for sig in b_only:
                findings.append({
                    "kind": "removed_program", "program": label,
                    "arg_sig": sig, "base": b_by_sig[sig], "fresh": None,
                    "detail": f"program '{label}' (sig {sig}) is no "
                              "longer compiled"})
        elif len(b_only) == 1 and len(f_only) == 1:
            # 1:1 signature change — pair them so the finding carries the
            # FLOP drift that usually rides along with a shape change
            b, f = b_by_sig[b_only[0]], f_by_sig[f_only[0]]
            extra = ""
            if isinstance(b.get("flops"), (int, float)) and isinstance(
                    f.get("flops"), (int, float)) \
                    and b["flops"] != f["flops"]:
                extra = ", flops " + _fmt_delta(b["flops"], f["flops"])
            findings.append({
                "kind": "arg_signature_changed", "program": label,
                "arg_sig": b_only[0], "base": b, "fresh": f,
                "detail": f"program '{label}' changed its argument "
                          f"signature ({b_only[0]} -> {f_only[0]}"
                          f"{extra})"})
        else:
            # the label survives with shared variants but grew and/or
            # lost some — the bucket-leak shape: every stray variant is
            # NAMED, never collapsed into a bare program-count delta
            for sig in f_only:
                findings.append({
                    "kind": "new_program_variant", "program": label,
                    "arg_sig": sig, "base": None, "fresh": f_by_sig[sig],
                    "detail": f"NEW variant of program '{label}' "
                              f"(sig {sig}, flops "
                              f"{f_by_sig[sig].get('flops')}) — a "
                              "signature outside the baselined set"})
            for sig in b_only:
                findings.append({
                    "kind": "removed_program_variant", "program": label,
                    "arg_sig": sig, "base": b_by_sig[sig], "fresh": None,
                    "detail": f"variant of program '{label}' (sig {sig}) "
                              "is no longer compiled"})

    if base.get("n_programs") != fresh.get("n_programs"):
        findings.append({
            "kind": "program_count", "program": None,
            "base": base.get("n_programs"), "fresh": fresh.get("n_programs"),
            "detail": f"compiled-program count {base.get('n_programs')} -> "
                      f"{fresh.get('n_programs')}"})
    if base.get("n_recompiles") != fresh.get("n_recompiles"):
        findings.append({
            "kind": "recompiles", "program": None,
            "base": base.get("n_recompiles"),
            "fresh": fresh.get("n_recompiles"),
            "detail": f"recompile count {base.get('n_recompiles')} -> "
                      f"{fresh.get('n_recompiles')} "
                      f"(labels: {fresh.get('recompile_labels')})"})
    elif base.get("recompile_labels") != fresh.get("recompile_labels"):
        # same count, different victims (reachable when an AOT capture
        # fails and the program set stays unchanged)
        findings.append({
            "kind": "recompiles", "program": None,
            "base": base.get("recompile_labels"),
            "fresh": fresh.get("recompile_labels"),
            "detail": "recompiled programs changed: "
                      f"{base.get('recompile_labels')} -> "
                      f"{fresh.get('recompile_labels')}"})
    if not findings:
        # safety net for the exact-match contract: base != fresh was
        # established above, so ANY drift the specific rules missed
        # still fails the gate (with the digests to chase)
        findings.append({
            "kind": "structural_drift", "program": None,
            "base": fingerprint_digest(base_fp),
            "fresh": fingerprint_digest(fresh_fp),
            "detail": "structural fingerprints differ "
                      f"({fingerprint_digest(base_fp)[:12]} -> "
                      f"{fingerprint_digest(fresh_fp)[:12]}) outside the "
                      "itemized fields — diff the baseline's fingerprint "
                      "JSON against a fresh bench row's"})
    return findings


def compare_timing(base_row: Dict[str, Any], fresh_row: Dict[str, Any],
                   sigma: float = 4.0, floor_frac: float = 0.10
                   ) -> Optional[Dict[str, Any]]:
    """Variance-aware timing comparison of two BenchResult rows (higher
    value = better, the bench convention). Fires ONLY when the fresh
    median falls below the baseline median by more than the noise floor:

        noise = max(sigma * sqrt(base_std^2 + fresh_std^2),
                    floor_frac * base_median)

    so k identical reruns (stddev ~0, delta 0) never fire, and a genuine
    1.5x slowdown always does. Returns a finding dict or None."""
    def med_std(row):
        reps = row.get("repeats") or {}
        med = reps.get("median", row.get("value"))
        std = reps.get("stddev", 0.0) or 0.0
        return float(med), float(std)

    base_med, base_std = med_std(base_row)
    fresh_med, fresh_std = med_std(fresh_row)
    noise = max(sigma * math.sqrt(base_std ** 2 + fresh_std ** 2),
                floor_frac * abs(base_med))
    delta = fresh_med - base_med
    if delta >= -noise:
        return None
    return {
        "kind": "timing_regression",
        "base": round(base_med, 4), "fresh": round(fresh_med, 4),
        "ratio": round(fresh_med / base_med, 4) if base_med else None,
        "noise_floor": round(noise, 4),
        "detail": (f"median {base_med:.4g} -> {fresh_med:.4g} "
                   f"{row_unit(base_row)} "
                   f"({100 * delta / base_med:+.1f}%), past the "
                   f"noise floor of {noise:.4g} "
                   f"(sigma={sigma}, base std {base_std:.4g}, "
                   f"fresh std {fresh_std:.4g})"),
    }


def row_unit(row: Dict[str, Any]) -> str:
    return row.get("unit", "")


# ---------------------------------------------------------------------------
# Trajectory store: results/perf/*.jsonl
# ---------------------------------------------------------------------------

def default_trajectory_root() -> str:
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(repo, "results", "perf")


class TrajectoryStore:
    """One JSONL per bench name under ``root`` (``results/perf/`` by
    default); each line is a ``BenchResult`` row. Appending validates;
    loading skips unparseable lines loudly rather than dying — the
    trajectory must survive a half-written row from a killed run."""

    def __init__(self, root: Optional[str] = None):
        self.root = root or default_trajectory_root()

    def path(self, name: str) -> str:
        safe = re.sub(r"[^A-Za-z0-9_.-]", "_", name)
        return os.path.join(self.root, f"{safe}.jsonl")

    def names(self) -> List[str]:
        if not os.path.isdir(self.root):
            return []
        return sorted(n[:-6] for n in os.listdir(self.root)
                      if n.endswith(".jsonl"))

    def append(self, result) -> str:
        row = result.to_row() if isinstance(result, BenchResult) else result
        problems = validate_row(row)
        if problems:
            raise ValueError("refusing to store invalid row: "
                             + "; ".join(problems))
        os.makedirs(self.root, exist_ok=True)
        path = self.path(row["name"])
        with open(path, "a") as f:
            f.write(json.dumps(row, sort_keys=True) + "\n")
        return path

    def load(self, name: str) -> List[Dict[str, Any]]:
        """Bench rows only: a file fed through ``bench.py --json
        <file>.jsonl`` carries a header row too — the trajectory
        consumers (report table, backfill dedup) never want it."""
        path = self.path(name)
        if not os.path.exists(path):
            return []
        rows = []
        with open(path) as f:
            for i, line in enumerate(f):
                line = line.strip()
                if not line:
                    continue
                try:
                    row = json.loads(line)
                except json.JSONDecodeError:
                    print(f"warning: {path}:{i + 1} unparseable; skipped",
                          file=sys.stderr)
                    continue
                if row.get("type") == "bench":
                    rows.append(row)
        return rows


# ---------------------------------------------------------------------------
# Legacy BENCH_r0N.json backfill + trajectory rendering
# ---------------------------------------------------------------------------

_TS_RE = re.compile(r"(\d{4}-\d{2}-\d{2} \d{2}:\d{2}:\d{2})")


def backfill_bench_history(repo_root: str,
                           store: Optional[TrajectoryStore] = None) -> int:
    """Convert the legacy ``BENCH_r*.json`` snapshot files (one opaque
    driver capture per round) into trajectory rows under the store. The
    snapshots all measure the default bench (``python bench.py``), so
    they land in the ``headline`` trajectory with ``source`` provenance;
    re-running is idempotent (a source already present is skipped).
    Returns the number of rows added."""
    store = store or TrajectoryStore()
    existing = {r.get("source") for r in store.load("headline")}
    added = 0
    for fname in sorted(os.listdir(repo_root)):
        if not (fname.startswith("BENCH_r") and fname.endswith(".json")):
            continue
        if fname in existing:
            continue
        try:
            with open(os.path.join(repo_root, fname)) as f:
                snap = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print(f"warning: {fname} unreadable ({e}); skipped",
                  file=sys.stderr)
            continue
        parsed = snap.get("parsed") or {}
        if not isinstance(parsed.get("value"), (int, float)):
            continue
        ts = None
        m = _TS_RE.search(snap.get("tail", ""))
        if m:
            try:
                ts = time.mktime(time.strptime(m.group(1),
                                               "%Y-%m-%d %H:%M:%S"))
            except ValueError:
                pass
        res = BenchResult(
            name="headline", metric=parsed.get("metric", "?"),
            value=float(parsed["value"]),
            unit=parsed.get("unit", "tokens/sec/chip"),
            vs_baseline=parsed.get("vs_baseline"),
            env={"backend": "axon", "note":
                 f"backfilled from {fname} (round {snap.get('n')})"},
            time=ts, source=fname)
        if isinstance(parsed.get("mfu"), (int, float)):
            res.add_metric("mfu", parsed["mfu"], "fraction")
        if isinstance(parsed.get("hlo_flops_per_step"), (int, float)):
            res.add_metric("hlo_flops_per_step",
                           parsed["hlo_flops_per_step"], "flops")
        if isinstance(parsed.get("compile_seconds"), (int, float)):
            res.add_metric("compile_seconds", parsed["compile_seconds"],
                           "seconds")
        store.append(res)
        added += 1
    return added


def render_trajectory(store: Optional[TrajectoryStore] = None,
                      names: Optional[List[str]] = None,
                      out=None) -> int:
    """Print the tok/s + MFU + compile-seconds trajectory table per bench
    — the machine-readable replacement for eyeballing five BENCH_r0N
    snapshot files. Returns the number of rows rendered."""
    store = store or TrajectoryStore()
    write = (out or sys.stdout).write
    names = names or store.names()
    n_rows = 0
    for name in names:
        rows = store.load(name)
        if not rows:
            continue
        rows.sort(key=lambda r: (r.get("time") or 0))
        write(f"\n== perf trajectory: {name} ==\n")
        write(f"{'when':<17}{'source':<22}{'value':>12} "
              f"{'unit':<18}{'mfu':>7}{'compile_s':>11}{'vs_base':>9}\n")
        for r in rows:
            when = (time.strftime("%Y-%m-%d %H:%M",
                                  time.localtime(r["time"]))
                    if isinstance(r.get("time"), (int, float)) else "?")
            metrics = r.get("metrics") or {}

            def mval(key):
                entry = metrics.get(key)
                return entry.get("value") if isinstance(entry, dict) \
                    else None

            mfu = mval("mfu")
            compile_s = mval("compile_seconds")
            if compile_s is None:
                compile_s = ((r.get("fingerprint") or {}).get("timing")
                             or {}).get("compile_seconds_total")
            source = r.get("source") or (
                "quick" if r.get("quick") else "run")
            vsb = r.get("vs_baseline")
            write(f"{when:<17}{source:<22}{r['value']:>12.1f} "
                  f"{r.get('unit', ''):<18}"
                  f"{mfu if mfu is not None else '-':>7}"
                  f"{compile_s if compile_s is not None else '-':>11}"
                  f"{vsb if vsb is not None else '-':>9}\n")
            n_rows += 1
    if n_rows == 0:
        write("no trajectory rows under "
              f"{store.root} (run scripts/perf_gate.py --backfill, or "
              "bench.py <name> --json results/perf/)\n")
    return n_rows
