"""XLA compile telemetry: AOT compile capture, HLO cost/memory analysis,
recompile detection, and persistent-compilation-cache wiring.

The analytic MFU in obs/mfu.py trusts a hand-derived FLOPs formula; XLA
knows what it actually built. ``CompileWatcher`` wraps the trainer's jitted
train step and, on the first call for each argument signature, runs the
explicit AOT path (``lower()`` -> ``compile()``) so compile time becomes a
measured number instead of an invisible chunk of the first step, then reads
the executable's ``cost_analysis()`` (HLO-counted FLOPs -> an HLO-measured
MFU to cross-check the analytic one) and ``memory_analysis()`` (HBM
breakdown: arguments / outputs / temps / generated code vs device
capacity — the OOM postmortem numbers). Each capture lands as one
``compile`` event in the metrics JSONL plus gauges.

A signature change after the first call is a RECOMPILE — the classic silent
TPU performance bug (a ragged last batch, a dtype drift after resume): the
watcher emits a ``recompile`` event naming the exact leaf-path shape/dtype
diff, then captures the new executable the same way. Steady-state calls are
a dict lookup + the dispatch itself.

``--compile_cache_dir`` enables JAX's persistent compilation cache with
entry-count/bytes telemetry: the compile event records whether this
process's compile was served from cache (no new entries written) or paid
for (new entries landed), so relaunch latency is measurable.

Failure policy: telemetry must never take down the run it observes. If the
AOT path raises for an exotic step builder, the watcher logs, emits a
``compile_fallback`` event, and permanently delegates to the wrapped jit
function (whose implicit compile still happens, just unmeasured).
"""

from __future__ import annotations

import os
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from building_llm_from_scratch_tpu.obs.metrics import get_metrics
from building_llm_from_scratch_tpu.utils.logging import setup_logger

logger = setup_logger(__name__)

#: Active fingerprint collectors (obs/perf.FingerprintCollector installs
#: itself here for the duration of one bench run): every CompileWatcher
#: capture/recompile is reported to each, so a bench's structural
#: fingerprint covers EVERY watched program that compiled while it ran —
#: the trainer step and all five serving-engine programs alike.
_collectors: List[Any] = []


def add_collector(collector: Any) -> None:
    """Register a fingerprint collector (``on_compile(label, sig, stats,
    n_tokens=)`` / ``on_recompile(label, diff)`` duck type)."""
    _collectors.append(collector)


def remove_collector(collector: Any) -> None:
    try:
        _collectors.remove(collector)
    except ValueError:
        pass


def _notify_collectors(method: str, *args, **kw) -> None:
    # observation must never take down the observed program
    for c in list(_collectors):
        try:
            getattr(c, method)(*args, **kw)
        except Exception as e:            # pragma: no cover - collector bug
            logger.warning("fingerprint collector %s failed: %s", method, e)


#: memory_analysis() attributes surfaced in the compile event (bytes).
_MEMORY_FIELDS = (
    ("argument_size_in_bytes", "args_bytes"),
    ("output_size_in_bytes", "output_bytes"),
    ("temp_size_in_bytes", "temp_bytes"),
    ("alias_size_in_bytes", "alias_bytes"),
    ("generated_code_size_in_bytes", "generated_code_bytes"),
)


def fast_signature(tree: Any) -> Tuple:
    """Steady-state cache key for the watcher's per-step check: (treedef,
    per-leaf (shape, dtype, sharding)). Unlike ``tree_signature`` it builds
    NO path strings — shape tuples, dtype objects and shardings are
    existing hashables, so the hot loop pays one tree_flatten and a tuple
    build, keeping the no-per-step-host-work discipline. The treedef
    covers structural changes that path strings would have caught."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    return treedef, tuple(
        (getattr(leaf, "shape", ()), getattr(leaf, "dtype", None),
         getattr(leaf, "sharding", None))
        for leaf in leaves)


def tree_signature(tree: Any) -> Tuple:
    """Hashable (path, shape, dtype, sharding) signature of a pytree of
    arrays — what XLA keys its compiled executables on. Shardings are part
    of the key because an AOT executable is pinned to them: under fsdp the
    optimizer-state shardings legitimately change between the first and
    second step (shard_state places them replicated, the step's
    with_sharding_constraint pins them sharded), which plain jit silently
    re-compiled for — the watcher must key on it too (and now reports it).
    Cheap host work: attribute reads only, no device sync."""
    flat, treedef = jax_tree_flatten_with_path(tree)
    return tuple(
        (path, tuple(getattr(leaf, "shape", ())),
         str(getattr(leaf, "dtype", type(leaf).__name__)),
         getattr(leaf, "sharding", None))
        for path, leaf in flat)


def jax_tree_flatten_with_path(tree: Any) -> Tuple[List[Tuple[str, Any]], Any]:
    """tree_flatten_with_path with the path rendered as a compact string
    ('trainable/blocks/attn/wq') so signature diffs read as leaf names."""
    import jax

    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    out = []
    for path, leaf in flat:
        parts = []
        for p in path:
            key = getattr(p, "key", None)
            if key is None:
                key = getattr(p, "idx", None)
            parts.append(str(key))
        out.append(("/".join(parts), leaf))
    return out, treedef


def _leaf_desc(sig_entry) -> Dict[str, Any]:
    shape, dtype = sig_entry[0], sig_entry[1]
    out: Dict[str, Any] = {"shape": list(shape), "dtype": dtype}
    sharding = sig_entry[2] if len(sig_entry) > 2 else None
    if sharding is not None:
        spec = getattr(sharding, "spec", None)
        out["sharding"] = str(spec if spec is not None else sharding)
    return out


def signature_diff(old: Tuple, new: Tuple) -> List[Dict[str, Any]]:
    """Human-readable leaf-level diff between two tree signatures: changed
    shapes/dtypes/shardings plus added/removed leaves."""
    old_map = {e[0]: e[1:] for e in old}
    new_map = {e[0]: e[1:] for e in new}
    diff: List[Dict[str, Any]] = []
    for path in sorted(set(old_map) | set(new_map)):
        a, b = old_map.get(path), new_map.get(path)
        if a == b:
            continue
        entry: Dict[str, Any] = {"leaf": path}
        if a is None:
            entry["added"] = _leaf_desc(b)
        elif b is None:
            entry["removed"] = _leaf_desc(a)
        else:
            entry["was"] = _leaf_desc(a)
            entry["now"] = _leaf_desc(b)
        diff.append(entry)
    return diff


def extract_cost_analysis(compiled) -> Dict[str, float]:
    """Normalize ``Compiled.cost_analysis()`` across jax versions (dict in
    newer releases, [dict] per-device in 0.4.x) to flat float fields."""
    try:
        cost = compiled.cost_analysis()
    except Exception as e:                     # pragma: no cover - backend gap
        logger.warning("cost_analysis unavailable: %s", e)
        return {}
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    if not isinstance(cost, dict):
        return {}
    out: Dict[str, float] = {}
    for key in ("flops", "transcendentals", "bytes accessed"):
        val = cost.get(key)
        if isinstance(val, (int, float)):
            out[key.replace(" ", "_")] = float(val)
    return out


def extract_memory_analysis(compiled) -> Dict[str, int]:
    """``Compiled.memory_analysis()`` -> {args/output/temp/alias/
    generated_code}_bytes (+ total), or {} when the backend exposes none."""
    try:
        mem = compiled.memory_analysis()
    except Exception as e:                     # pragma: no cover - backend gap
        logger.warning("memory_analysis unavailable: %s", e)
        return {}
    if mem is None:
        return {}
    out: Dict[str, int] = {}
    for attr, name in _MEMORY_FIELDS:
        val = getattr(mem, attr, None)
        if isinstance(val, int):
            out[name] = val
    if out:
        # peak-footprint proxy: aliased bytes (donated inputs) are reused
        # by outputs, so counting args+outputs+temps double-counts them
        out["total_bytes"] = (out.get("args_bytes", 0)
                              + out.get("output_bytes", 0)
                              + out.get("temp_bytes", 0)
                              + out.get("generated_code_bytes", 0)
                              - out.get("alias_bytes", 0))
    return out


def device_hbm_capacity() -> Optional[int]:
    """bytes_limit of device 0, or None off-TPU (CPU memory_stats is None)."""
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats()
    except Exception:
        return None
    if not stats:
        return None
    limit = stats.get("bytes_limit")
    return int(limit) if isinstance(limit, int) else None


def executable_device_count(compiled) -> int:
    """Number of devices the compiled executable spans, read off its input
    shardings (1 for a plain single-device jit). Needed to globalize
    ``cost_analysis()``: under SPMD it reports the PER-DEVICE module's
    numbers."""
    try:
        import jax

        best = 1
        for s in jax.tree_util.tree_leaves(compiled.input_shardings):
            device_set = getattr(s, "device_set", None)
            if device_set:
                best = max(best, len(device_set))
        return best
    except Exception:
        return 1


def aot_compile(fn: Callable, *args) -> Tuple[Any, Dict[str, Any]]:
    """Explicitly lower+compile a jitted callable for ``args``; returns
    (compiled_executable, stats). Stats carry ``compile_seconds`` split
    into lower/backend-compile, cost analysis and the memory breakdown.

    Cost numbers are GLOBAL: ``cost_analysis()`` reports the per-device
    SPMD module (measured: a 2-device-sharded matmul reports half the
    single-device FLOPs), so ``flops``/``transcendentals``/
    ``bytes_accessed`` are scaled by the executable's device count —
    consumers divide by global token counts. The per-device figure stays
    as ``flops_per_device``; the ``memory`` breakdown is deliberately
    per-device (it is compared against one device's HBM capacity).

    Raises whatever the trace/compile raises — callers own fallback."""
    t0 = time.perf_counter()
    lowered = fn.lower(*args)
    t1 = time.perf_counter()
    compiled = lowered.compile()
    t2 = time.perf_counter()
    stats: Dict[str, Any] = {
        "compile_seconds": round(t2 - t0, 4),
        "lower_seconds": round(t1 - t0, 4),
        "backend_compile_seconds": round(t2 - t1, 4),
    }
    cost = extract_cost_analysis(compiled)
    n_dev = executable_device_count(compiled)
    stats["executable_device_count"] = n_dev
    if n_dev > 1 and "flops" in cost:
        cost["flops_per_device"] = cost["flops"]
        for key in ("flops", "transcendentals", "bytes_accessed"):
            if key in cost:
                cost[key] = cost[key] * n_dev
    stats.update(cost)
    mem = extract_memory_analysis(compiled)
    if mem:
        stats["memory"] = mem
    return compiled, stats


class CompileWatcher:
    """Wraps a jitted callable: AOT-compiles per argument signature, emits
    ``compile``/``recompile`` telemetry, and exposes the HLO-measured
    FLOPs for the trainer's MFU cross-check.

    Call-compatible with the wrapped step (any arity): for the trainer,
    ``watcher(state, batch)``.

    Two recompile policies:
      - default (``multi_program=False``, the train step): ONE signature is
        legitimate — any later signature change is a silent-perf-bug
        recompile.
      - ``multi_program=True`` (the serving engine's bucketed prefill /
        decode programs): a KNOWN SET of signatures is legitimate. New
        signatures during warmup are plain ``compile`` events; after the
        caller ``freeze()``s the set, an unseen signature is a bucket miss
        and emits ``recompile`` with the leaf diff — the silent latency
        cliff the serving telemetry exists to surface.
    """

    def __init__(self, fn: Callable, label: str = "train_step",
                 cache_dir: Optional[str] = None,
                 multi_program: bool = False):
        self._fn = fn
        self.label = label
        self.cache_dir = cache_dir
        self.multi_program = multi_program
        self.frozen = False
        self._compiled: Dict[Tuple, Callable] = {}
        self._last_sig: Optional[Tuple] = None
        self._disabled = False
        self.n_compiles = 0
        self.n_recompiles = 0
        self.compile_seconds_total = 0.0
        #: HLO-counted FLOPs for ONE step at the latest signature (None
        #: until the first capture, or when cost_analysis has no flops).
        self.hlo_flops_per_step: Optional[float] = None
        #: ... divided by the batch's token count (set when the batch
        #: carries an "inputs" leaf), for the HLO-measured MFU.
        self.hlo_flops_per_token: Optional[float] = None
        self.memory: Dict[str, int] = {}

    # -- internals -------------------------------------------------------

    def _cache_entries(self) -> Optional[int]:
        if not self.cache_dir or not os.path.isdir(self.cache_dir):
            return None
        try:
            return sum(1 for n in os.listdir(self.cache_dir)
                       if n.endswith("-cache"))
        except OSError:
            return None

    def freeze(self) -> None:
        """Close the legitimate-signature set (multi_program mode): the
        serving engine calls this after warming its prefill buckets and
        decode program — from here on, a new signature is a bucket miss."""
        self.frozen = True

    def _capture(self, sig: Tuple, *args) -> Callable:
        entries_before = self._cache_entries()
        compiled, stats = aot_compile(self._fn, *args)
        entries_after = self._cache_entries()
        self.n_compiles += 1
        self.compile_seconds_total += stats["compile_seconds"]
        self.hlo_flops_per_step = stats.get("flops")
        self.memory = stats.get("memory", {})
        n_tokens = None
        try:
            batch = next(a for a in args
                         if isinstance(a, dict) and "inputs" in a)
            n_tokens = int(batch["inputs"].size)
        except (StopIteration, TypeError, KeyError, AttributeError):
            pass
        if n_tokens and self.hlo_flops_per_step:
            self.hlo_flops_per_token = self.hlo_flops_per_step / n_tokens
        event = dict(stats, label=self.label, n_compiles=self.n_compiles)
        if n_tokens:
            event["tokens_per_step"] = n_tokens
        capacity = device_hbm_capacity()
        if capacity and self.memory:
            event["hbm_capacity_bytes"] = capacity
            event["hbm_budget_frac"] = round(
                self.memory.get("total_bytes", 0) / capacity, 4)
        if entries_before is not None and entries_after is not None:
            event["cache_dir"] = self.cache_dir
            event["cache_entries"] = entries_after
            # a served-from-cache compile writes no new entries; count
            # deltas instead of guessing from timing
            event["cache_hit"] = (entries_after == entries_before
                                  and entries_before > 0)
        _notify_collectors("on_compile", self.label, sig, stats,
                           n_tokens=n_tokens)
        sink = get_metrics()
        sink.event("compile", **event)
        sink.gauge("compile_seconds_total",
                   round(self.compile_seconds_total, 4))
        for name, val in self.memory.items():
            sink.gauge(f"hlo_{name}", val)
        logger.info(
            "%s compiled in %.2fs (HLO %s flops/step%s)", self.label,
            stats["compile_seconds"],
            f"{self.hlo_flops_per_step:.3g}" if self.hlo_flops_per_step
            else "n/a",
            f", temps {self.memory['temp_bytes'] / 1024**2:.0f} MiB"
            if "temp_bytes" in self.memory else "")
        return compiled

    # -- the step --------------------------------------------------------

    @property
    def __name__(self) -> str:
        # call-compatible includes introspection: tests (and tqdm-style
        # tooling) read the step function's name
        return getattr(self._fn, "__name__", self.label)

    def __call__(self, *args):
        if self._disabled:
            return self._fn(*args)
        key = tuple(fast_signature(a) for a in args)
        fn = self._compiled.get(key)
        if fn is None:
            # only a miss pays for the human-readable path-string
            # signature (the diff needs leaf names); steady-state steps
            # never build strings
            sig = tuple(tree_signature(a) for a in args)
            is_recompile = (self.frozen if self.multi_program
                            else self._last_sig is not None)
            if is_recompile:
                self.n_recompiles += 1
                diff = ([d for pair in zip(self._last_sig, sig)
                         for d in signature_diff(*pair)]
                        if self._last_sig is not None else [])
                sink = get_metrics()
                # a tree-wide drift (fsdp opt-state resharding, resume
                # dtype change) diffs every leaf — cap the serialized row
                sink.event("recompile", label=self.label,
                           n_recompiles=self.n_recompiles,
                           n_changed_leaves=len(diff), diff=diff[:50])
                _notify_collectors("on_recompile", self.label, diff)
                sink.gauge("recompile_count", self.n_recompiles)
                leaves = [d["leaf"] for d in diff]
                shown = "; ".join(leaves[:6]) + (
                    f"; … +{len(leaves) - 6} more" if len(leaves) > 6 else "")
                logger.warning(
                    "%s RECOMPILE #%d: argument signature changed (%s)",
                    self.label, self.n_recompiles, shown or "unknown leaf")
            try:
                fn = self._capture(sig, *args)
            except Exception as e:
                # telemetry must not kill the run: fall back to the plain
                # jit path (which will surface REAL trace errors itself)
                logger.warning(
                    "AOT compile capture failed for %s (%s: %s); compile "
                    "telemetry disabled for this step.", self.label,
                    type(e).__name__, e)
                get_metrics().event("compile_fallback", label=self.label,
                                    error=f"{type(e).__name__}: {e}")
                self._disabled = True
                return self._fn(*args)
            self._compiled[key] = fn
            self._last_sig = sig
        return fn(*args)


def enable_persistent_cache(cache_dir: str) -> None:
    """Wire JAX's persistent compilation cache at ``cache_dir``
    (--compile_cache_dir): relaunches — the preemption-resume loop — skip
    the multi-minute XLA compile entirely. Thresholds are zeroed so every
    executable is eligible (default jax skips sub-second compiles, which
    would make smoke-test telemetry read as permanent misses)."""
    import jax
    from jax.experimental.compilation_cache import compilation_cache

    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    try:
        # any compile BEFORE the dir is set (set_seed's PRNG key, a device
        # put) initializes the cache machinery in its disabled state, and
        # set_cache_dir alone cannot revive it — reset first (measured on
        # jax 0.4.37: without this the dir stays empty forever)
        compilation_cache.reset_cache()
    except Exception:
        pass
    compilation_cache.set_cache_dir(cache_dir)
    logger.info("Persistent compilation cache at %s", cache_dir)
