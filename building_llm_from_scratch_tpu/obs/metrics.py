"""Structured training telemetry: counters/gauges/timings and a JSONL sink.

The reference framework's only observability is per-module console logging
and a post-hoc loss plot (utils.py:171-191). Production TPU runs need the
numbers the systems literature treats as table stakes — per-step timing
breakdowns, MFU, HBM usage — as machine-readable ARTIFACTS, not grepped
logs. This module is the hub: one ``MetricLogger`` owns the JSONL file and
every other layer (trainer, resilience, checkpoint, retry, weight fetch)
reports through it.

JSONL schema (one JSON object per line, ``type`` discriminates):

  - ``header``  — exactly one, first line: run metadata (jax version,
    device kind/count, process count, mesh shape, model config, argv,
    parsed flags, schema_version).
  - ``metrics`` — per-cadence numbers: ``step`` plus free-form scalar
    fields (loss/lr/tok_s/mfu/step_time_s/memory gauges/...). ``step`` is
    monotonically increasing across rows.
  - ``health``  — per-cadence PER-LAYER-GROUP training-health arrays
    (obs/health.py): ``step``, ``groups`` (ordered names) and parallel
    ``grad_norm``/``param_norm``/``update_norm``/``update_ratio`` lists,
    plus ``first_nonfinite`` (group name or null). Separate from
    ``metrics`` so scalar-row consumers never see list-valued fields.
  - ``event``   — typed structured events (``event`` names the kind:
    checkpoint_save, checkpoint_fallback, preemption_stop, watchdog_halt,
    compile, recompile, retry, stall, ...), with free-form fields.
  - ``span``    — one closed wall-clock span (v3): ``name``, ``cat``,
    ``t0`` (unix seconds), ``dur_s``, optional nested ``children``
    (same shape, no further nesting) and correlation fields
    (``request_id``...). The serving engine emits one span row per
    request at its terminal state; ``obs/trace.py`` renders span rows
    (plus metric/event rows) as Chrome trace-event JSON for Perfetto.

One run = one file: if the path already holds a previous run's telemetry
(a ``--resume auto`` relaunch reuses the same command), the old file is
rotated aside (``.1``, ``.2``, ...) at first write, so every file keeps
the header-first / monotone-step invariants.

Coordinator-aware: by default only process 0 writes (the sink mirrors the
reference's rank-0 gating for artifacts). The module-level singleton
(``configure_metrics`` / ``get_metrics`` / ``emit_event``) lets deep layers
emit events without plumbing a logger handle through every call — when
nothing is configured, emission is a cheap no-op, so library use without a
run context costs nothing.

Writes are lock-guarded: the stall detector (obs/stall.py) emits from its
watcher thread.
"""

from __future__ import annotations

import bisect
import json
import os
import re
import sys
import threading
import time
from typing import Any, Dict, IO, Optional

from building_llm_from_scratch_tpu.obs.schema import SCHEMA_VERSION
from building_llm_from_scratch_tpu.utils.logging import setup_logger

logger = setup_logger(__name__)


def _is_coordinator() -> bool:
    """Lazy coordinator check that never *initializes* jax: metrics must be
    importable (and no-op usable) before ``jax.distributed.initialize``.
    One implementation, shared with the log-gating filter — the metrics
    sink and the console logs must never disagree about who writes."""
    from building_llm_from_scratch_tpu.utils.logging import (
        _coordinator_if_known,
    )

    return _coordinator_if_known()


def _jsonable(value: Any) -> Any:
    """Best-effort conversion to JSON-serializable values: numpy scalars
    become python scalars, unknown objects become their repr — a telemetry
    row must never crash the run it observes."""
    if value is None or isinstance(value, (bool, int, str)):
        return value
    if isinstance(value, float):
        # json rejects NaN/Inf under allow_nan=False; keep rows parseable
        import math

        return value if math.isfinite(value) else str(value)
    if isinstance(value, dict):
        return {str(k): _jsonable(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_jsonable(v) for v in value]
    if getattr(value, "ndim", None):
        # numpy / jax arrays (health bundles): element-wise via tolist so
        # NaN/Inf entries still get the finite-only treatment above
        tolist = getattr(value, "tolist", None)
        if callable(tolist):
            try:
                return _jsonable(tolist())
            except Exception:
                pass
    item = getattr(value, "item", None)
    if callable(item):
        try:
            return _jsonable(item())
        except Exception:
            pass
    return repr(value)


# ---------------------------------------------------------------------------
# Serving-grade aggregation: fixed-bucket histograms + rolling SLO window
# ---------------------------------------------------------------------------

#: Default latency buckets (seconds) for TTFT/TPOT/e2e/queue-wait: log-ish
#: spacing from 1ms to 2min. Fixed buckets — unlike a reservoir deque, the
#: memory cost is O(buckets) forever and two scrapes of a long-running
#: server are COMPARABLE (Prometheus histogram semantics: cumulative
#: bucket counters, rate()-able).
LATENCY_BUCKETS_S = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                     0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0)


class Histogram:
    """Thread-safe fixed-bucket histogram (Prometheus semantics).

    ``bounds`` are the buckets' inclusive upper edges; an implicit +Inf
    bucket catches the tail. ``observe()`` is O(log buckets); state is
    cumulative and never forgets — this replaces the engine's bounded
    deque reservoirs, whose percentiles silently covered only the most
    recent 8192 requests of a long-running server.
    """

    def __init__(self, bounds=LATENCY_BUCKETS_S):
        self.bounds = tuple(sorted(float(b) for b in bounds))
        if not self.bounds:
            raise ValueError("histogram needs at least one bucket bound")
        self._lock = threading.Lock()
        self._counts = [0] * (len(self.bounds) + 1)   # guarded-by: _lock
        self.count = 0                                # guarded-by: _lock
        self.sum = 0.0                                # guarded-by: _lock

    def observe(self, value: float) -> None:
        value = float(value)
        i = bisect.bisect_left(self.bounds, value)
        with self._lock:
            self._counts[i] += 1
            self.count += 1
            self.sum += value

    def __len__(self) -> int:                 # observations, not buckets
        with self._lock:
            return self.count

    def snapshot(self) -> Dict[str, Any]:
        """{"buckets": [(le, cumulative_count), ..., ("+Inf", n)],
        "count": n, "sum": s} — a consistent point-in-time view."""
        with self._lock:
            counts = list(self._counts)
            total, s = self.count, self.sum
        cum, out = 0, []
        for le, c in zip(self.bounds, counts):
            cum += c
            out.append((le, cum))
        out.append(("+Inf", total))
        return {"buckets": out, "count": total, "sum": s}

    def percentile(self, p: float) -> Optional[float]:
        """Estimated p-th percentile: linear interpolation inside the
        target bucket (Prometheus ``histogram_quantile`` semantics; the
        +Inf bucket clamps to the largest finite bound). None when empty.
        """
        with self._lock:
            counts = list(self._counts)
            total = self.count
        if total == 0:
            return None
        rank = (p / 100.0) * total
        cum = 0
        for i, c in enumerate(counts):
            if c == 0:
                continue
            if cum + c >= rank:
                if i >= len(self.bounds):     # +Inf bucket: clamp
                    return self.bounds[-1]
                lo = self.bounds[i - 1] if i > 0 else 0.0
                hi = self.bounds[i]
                frac = (rank - cum) / c
                return lo + (hi - lo) * frac
            cum += c
        return self.bounds[-1]

    def percentiles(self, ps=(50, 95, 99)) -> Dict[str, float]:
        out = {}
        for p in ps:
            v = self.percentile(p)
            if v is not None:
                out[f"p{p}"] = round(v, 6)
        return out


class RollingRatio:
    """Rolling-window hit/miss ratio over wall time (SLO burn rate).

    Time is chopped into ``n_buckets`` sub-windows of the last
    ``window_s`` seconds; ``observe(miss)`` lands in the current
    sub-window and expired sub-windows are dropped lazily — so
    ``ratio()`` always answers "what fraction of deadline-carrying
    requests missed over the last window", which is the number an
    SLO-aware router alerts and routes on. O(n_buckets) memory forever.
    """

    def __init__(self, window_s: float = 300.0, n_buckets: int = 30):
        if window_s <= 0 or n_buckets < 1:
            raise ValueError("window_s > 0 and n_buckets >= 1 required")
        self.window_s = float(window_s)
        self.bucket_s = self.window_s / int(n_buckets)
        self._lock = threading.Lock()
        # bucket index -> [total, misses]
        self._buckets: Dict[int, list] = {}   # guarded-by: _lock

    # holds: _lock
    def _expire(self, now: float) -> None:
        horizon = now - self.window_s
        dead = [k for k in self._buckets
                if (k + 1) * self.bucket_s <= horizon]
        for k in dead:
            del self._buckets[k]

    def observe(self, miss: bool, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        k = int(now // self.bucket_s)
        with self._lock:
            self._expire(now)
            b = self._buckets.setdefault(k, [0, 0])
            b[0] += 1
            if miss:
                b[1] += 1

    def counts(self, now: Optional[float] = None) -> tuple:
        """(total, misses) inside the current window."""
        now = time.monotonic() if now is None else now
        with self._lock:
            self._expire(now)
            total = sum(b[0] for b in self._buckets.values())
            misses = sum(b[1] for b in self._buckets.values())
        return total, misses

    def ratio(self, now: Optional[float] = None) -> Optional[float]:
        total, misses = self.counts(now)
        if total == 0:
            return None
        return misses / total


# ---------------------------------------------------------------------------
# Prometheus text exposition (version 0.0.4; no client library needed)
# ---------------------------------------------------------------------------

def _prom_name(name: str) -> str:
    """Sanitize to the Prometheus metric-name charset."""
    return re.sub(r"[^a-zA-Z0-9_:]", "_", name)


def _prom_series(prefix: str, name: str, counter: bool = False) -> str:
    """Series name for one counter/gauge key. A key may carry a label
    set — ``adapter_requests_finished{adapter="x"}`` — in which case only
    the metric-name part is sanitized (and the counter ``_total`` suffix
    lands BEFORE the braces, per exposition-format grammar)."""
    base, sep, labels = name.partition("{")
    n = _prom_name(prefix + base)
    if counter and not n.endswith("_total"):
        n += "_total"
    return n + sep + labels


def render_prometheus(counters: Dict[str, float],
                      gauges: Dict[str, float],
                      histograms: Dict[str, "Histogram"],
                      prefix: str = "bllm_") -> str:
    """Render counters/gauges/histograms as Prometheus text exposition
    (``GET /metrics`` body). Counters get a ``_total`` suffix; histogram
    series follow the ``_bucket{le=}``/``_sum``/``_count`` convention, so
    ``histogram_quantile()`` works on them unmodified."""
    lines = []
    typed: set = set()

    def emit(name: str, v, kind: str, counter: bool) -> None:
        n = _prom_series(prefix, name, counter=counter)
        bare = n.partition("{")[0]
        # one TYPE line per metric name, even when labeled keys produce
        # several series of it (exposition-format requirement)
        if bare not in typed:
            typed.add(bare)
            lines.append(f"# TYPE {bare} {kind}")
        lines.append(f"{n} {v}")

    for name in sorted(counters):
        v = counters[name]
        if not isinstance(v, (int, float)):
            continue
        emit(name, v, "counter", counter=True)
    for name in sorted(gauges):
        v = gauges[name]
        if not isinstance(v, (int, float)):
            continue
        emit(name, v, "gauge", counter=False)
    for name in sorted(histograms):
        snap = histograms[name].snapshot()
        # histogram keys may carry a label set too (the replica router
        # re-exports each replica's histograms as ttft_seconds{replica=
        # "i"}): labels merge INSIDE the _bucket/_sum/_count series per
        # exposition grammar — ..._bucket{replica="i",le="0.1"}
        base, _sep, labels = name.partition("{")
        labels = labels[:-1] if labels else ""
        n = _prom_name(prefix + base)
        if n not in typed:
            typed.add(n)
            lines.append(f"# TYPE {n} histogram")
        pre = labels + "," if labels else ""
        suffix = "{" + labels + "}" if labels else ""
        for le, cum in snap["buckets"]:
            le_txt = "+Inf" if le == "+Inf" else repr(float(le))
            lines.append(f'{n}_bucket{{{pre}le="{le_txt}"}} {cum}')
        lines.append(f"{n}_sum{suffix} {snap['sum']}")
        lines.append(f"{n}_count{suffix} {snap['count']}")
    return "\n".join(lines) + "\n"


class MetricLogger:
    """Counters/gauges/timings plus a typed JSONL sink.

    ``jsonl_path=None`` keeps the in-memory aggregation (counters survive
    for tests/inspection) but writes nothing. All writes go through one
    lock; rows are flushed immediately — a preempted run keeps every row
    up to its last completed cadence.
    """

    def __init__(self, jsonl_path: Optional[str] = None,
                 coordinator_only: bool = True, append: bool = False):
        self.jsonl_path = jsonl_path
        self.coordinator_only = coordinator_only
        # append=True: a restarted process APPENDS to the existing file
        # instead of rotating it aside — the fleet-worker convention,
        # where one file accumulates one header per incarnation and the
        # renderer splits on headers (summarize_metrics.split_incarnations)
        self.append = append
        # REENTRANT: GracefulStopper's signal handler emits an event, and
        # the signal can land while THIS thread already holds the lock
        # inside a write — a plain Lock would self-deadlock. Reentry is
        # safe because every row is appended as one complete newline-
        # terminated write, so an interleaved handler row never splits a
        # line.
        self._lock = threading.RLock()
        self.counters: Dict[str, float] = {}      # guarded-by: _lock
        self.gauges: Dict[str, float] = {}        # guarded-by: _lock
        self._timings: Dict[str, float] = {}      # guarded-by: _lock
        self._file: Optional[IO[str]] = None      # guarded-by: _lock
        self._closed = False                      # guarded-by: _lock
        self._header_written = False              # guarded-by: _lock
        # rows emitted before the header (build-time fetch/retry events —
        # the run metadata needs the built components) are buffered and
        # flushed right after it, keeping the header the first line
        self._pre_header: list = []               # guarded-by: _lock
        self._last_step = -1                      # guarded-by: _lock

    # -- aggregation -----------------------------------------------------

    def count(self, name: str, n: float = 1) -> None:
        """Monotonic counter (e.g. retries, checkpoints written)."""
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + n

    def gauge(self, name: str, value: float) -> None:
        """Last-value-wins gauge (e.g. bytes_in_use)."""
        with self._lock:
            self.gauges[name] = value

    def timing(self, name: str, seconds: float) -> None:
        """Accumulating timing bucket; drained into the next metrics row."""
        with self._lock:
            self._timings[name] = self._timings.get(name, 0.0) + seconds

    # -- sink ------------------------------------------------------------

    # holds: _lock
    def _writable(self) -> bool:
        # a closed sink stays closed: a late write (stall-detector thread
        # firing during teardown) must not reopen the path — that would
        # rotate the COMPLETED run's artifact aside for one stray row
        if self.jsonl_path is None or self._closed:
            return False
        return not self.coordinator_only or _is_coordinator()

    def _write_row(self, row: Dict[str, Any]) -> None:
        """Append one row. Never raises: telemetry failure must not take
        down the training loop it observes."""
        try:
            with self._lock:
                # writability is decided under the lock: a close() racing
                # this write either lands before (row dropped) or after
                # (row flushed) — never between check and write
                if not self._writable():
                    return
                if not self._header_written and row.get("type") != "header":
                    self._pre_header.append(row)
                    return
                if self._file is None:
                    d = os.path.dirname(self.jsonl_path)
                    if d:
                        os.makedirs(d, exist_ok=True)
                    # one run = one file: a --resume auto relaunch reuses
                    # the same path, and appending would put a second
                    # header mid-file and restart the monotone step
                    # sequence. Rotate the previous run's file aside
                    # (.1, .2, ...) instead of truncating it — the killed
                    # run's telemetry is exactly what a postmortem needs.
                    # append mode opts out: restarted fleet workers stack
                    # incarnations (header-delimited) in ONE file, so the
                    # victim's last rows and its successor's share a path.
                    if not self.append and os.path.exists(
                            self.jsonl_path) and os.path.getsize(
                            self.jsonl_path) > 0:
                        n = 1
                        while os.path.exists(f"{self.jsonl_path}.{n}"):
                            n += 1
                        os.rename(self.jsonl_path, f"{self.jsonl_path}.{n}")
                    self._file = open(self.jsonl_path, "a")
                self._file.write(json.dumps(_jsonable(row)) + "\n")
                self._file.flush()
        except OSError as e:
            logger.warning("Metrics sink write failed (%s); row dropped.", e)

    def write_header(self, **metadata: Any) -> None:
        row = {"type": "header", "time": time.time(),
               "schema_version": SCHEMA_VERSION}
        row.update(metadata)
        with self._lock:
            self._header_written = True
            buffered, self._pre_header = self._pre_header, []
        self._write_row(row)
        for b in buffered:
            self._write_row(b)

    def log_metrics(self, step: int, monotonic: bool = True,
                    **values: Any) -> None:
        """One ``metrics`` row; merges and drains the timing buckets and
        attaches current counters/gauges. ``monotonic=False`` skips the
        step-regression warning — fleet-serving replicas interleave
        their per-engine tick counters into one sink by design."""
        with self._lock:
            timings = {f"{k}_s": round(v, 6)
                       for k, v in self._timings.items()}
            self._timings.clear()
            extra = dict(self.counters)
            extra.update(self.gauges)
        row = {"type": "metrics", "time": time.time(), "step": int(step)}
        row.update(timings)
        row.update(extra)
        row.update(values)
        with self._lock:
            if monotonic and step < self._last_step:
                logger.warning("Metrics row step went backwards (%d < %d)",
                               step, self._last_step)
            self._last_step = max(self._last_step, int(step))
        self._write_row(row)

    def log_health(self, step: int, groups, **arrays: Any) -> None:
        """One ``health`` row: ordered group names + parallel per-group
        arrays (obs/health.py bundle). List-valued by design — kept out of
        the scalar ``metrics`` rows so existing consumers stay flat."""
        row = {"type": "health", "time": time.time(), "step": int(step),
               "groups": list(groups)}
        row.update(arrays)
        self._write_row(row)

    def log_span(self, name: str, t0: float, dur_s: float,
                 cat: str = "span", children=None, **fields: Any) -> None:
        """One closed wall-clock ``span`` row: ``t0`` is unix seconds,
        ``dur_s`` its duration; ``children`` is an optional list of
        ``{"name", "t0", "dur_s"}`` sub-spans (one level — the serving
        request tree is root + phases). Correlation keys (``request_id``)
        ride as free-form fields; ``obs/trace.py`` joins them."""
        row: Dict[str, Any] = {"type": "span", "time": time.time(),
                               "name": name, "cat": cat,
                               "t0": round(float(t0), 6),
                               "dur_s": round(float(dur_s), 6)}
        if children:
            # clamp children inside the ROUNDED root: rounding t0/dur_s
            # independently can push a child's end past the root's by up
            # to ~1.5us, and consumers (Perfetto nesting, the span tests)
            # rely on strict containment
            root_t0 = row["t0"]
            root_end = root_t0 + row["dur_s"]
            kids = []
            for c in children:
                ct0 = min(max(round(float(c["t0"]), 6), root_t0), root_end)
                cdur = max(min(round(float(c["dur_s"]), 6),
                               root_end - ct0), 0.0)
                kids.append({"name": c["name"], "t0": ct0, "dur_s": cdur})
            row["children"] = kids
        row.update(fields)
        self._write_row(row)

    def event(self, kind: str, step: Optional[int] = None,
              **fields: Any) -> None:
        """One typed ``event`` row (also bumps the ``event:<kind>``
        counter, so unconfigured library use still aggregates)."""
        self.count(f"event:{kind}")
        row = {"type": "event", "time": time.time(), "event": kind}
        if step is not None:
            row["step"] = int(step)
        row.update(fields)
        self._write_row(row)

    def close(self) -> None:
        # a run that dies before its header still keeps its buffered rows:
        # a headerless telemetry file beats a silently empty one. The
        # buffer check happens under the lock (two racing close() calls
        # must not both claim the buffer); the flush itself re-enters
        # _write_row, which the RLock permits.
        with self._lock:
            buffered, self._pre_header = self._pre_header, []
            if buffered:
                self._header_written = True
        for b in buffered:
            self._write_row(b)
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None
            self._closed = True


# ---------------------------------------------------------------------------
# Module-level singleton: deep layers emit without plumbing
# ---------------------------------------------------------------------------

_global_logger = MetricLogger(None)
_atexit_registered = False


def _close_global_at_exit() -> None:
    # closes whatever logger is CURRENT at interpreter exit — registered
    # once, so repeated configure_metrics calls (tests, multiple main()
    # runs in one process) neither stack callbacks nor pin old loggers
    _global_logger.close()


def configure_metrics(jsonl_path: Optional[str],
                      run_metadata: Optional[Dict[str, Any]] = None,
                      append: bool = False) -> MetricLogger:
    """Install the process-global MetricLogger (closing any previous one).
    With ``run_metadata`` the header is written immediately; without it,
    rows buffer until the caller's ``write_header`` (main.py configures
    before component build so fetch/retry events are captured, then writes
    the header once mesh + model metadata exist). ``jsonl_path=None``
    resets to the no-op sink (tests use this to isolate). ``append=True``
    appends to an existing file instead of rotating it (fleet workers:
    one file per replica, one header per incarnation)."""
    global _global_logger, _atexit_registered
    _global_logger.close()
    _global_logger = MetricLogger(jsonl_path, append=append)
    if jsonl_path is not None and not _atexit_registered:
        # flush-at-exit makes the pre-header buffering promise real: if
        # the run dies before its header (e.g. build_components exhausts
        # its fetch retries and raises), the buffered retry/fetch events
        # still land in a headerless file instead of vanishing. close()
        # is idempotent, so the normal path is unaffected.
        import atexit

        atexit.register(_close_global_at_exit)
        _atexit_registered = True
    if jsonl_path is not None and run_metadata is not None:
        _global_logger.write_header(**run_metadata)
    return _global_logger


def get_metrics() -> MetricLogger:
    return _global_logger


def emit_event(kind: str, step: Optional[int] = None, **fields: Any) -> None:
    """Fire-and-forget structured event through the global logger. Safe to
    call from any layer at any time (no-op sink when unconfigured)."""
    _global_logger.event(kind, step=step, **fields)


def run_metadata(args=None, cfg=None, plan=None) -> Dict[str, Any]:
    """Assemble the header row's run metadata: jax version, device
    kind/count, process count, mesh shape, model config, argv, flags.
    Call AFTER ``initialize_distributed`` so the distributed view is real.
    """
    import dataclasses

    import jax

    devices = jax.devices()
    meta: Dict[str, Any] = {
        "jax_version": jax.__version__,
        "backend": jax.default_backend(),
        "device_kind": devices[0].device_kind if devices else "unknown",
        "device_count": len(devices),
        "local_device_count": jax.local_device_count(),
        "process_count": jax.process_count(),
        "process_index": jax.process_index(),
        "argv": list(sys.argv),
    }
    if plan is not None and getattr(plan, "mesh", None) is not None:
        meta["mesh_shape"] = {str(k): int(v)
                              for k, v in plan.mesh.shape.items()}
    else:
        meta["mesh_shape"] = None
    if cfg is not None:
        meta["model"] = dataclasses.asdict(cfg)
    if args is not None:
        meta["flags"] = dict(vars(args))
    return meta
