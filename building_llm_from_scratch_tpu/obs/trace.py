"""Run-trace export: metrics JSONL -> Chrome trace-event JSON (Perfetto).

The metrics JSONL (obs/metrics.py) already records everything that
happened in a run — request ``span`` rows from the serving engine, the
trainer's ``StepTimeline`` cadence windows, the engine's per-tick phase
breakdown, compile/recompile events, and every incident (restart, drain,
stall, watchdog halt, preemption). This module renders that one artifact
as ONE timeline: a Chrome trace-event JSON file loadable in Perfetto
(https://ui.perfetto.dev) or ``chrome://tracing``, so a whole run —
training and serving tiers alike — is scrubbable instead of greppable.

Track layout (Chrome trace ``pid``/``tid`` become Perfetto process/thread
tracks):

  - ``requests``  — one track per request id: the root ``request`` span
    with its ``queued``/``prefill``/``decode`` children, plus instant
    markers for that request's lifecycle events (rejected, shed, expired,
    failed). Span rows are emitted once, at the request's terminal state.
  - ``engine``    — the tick-phase breakdown at the engine's metrics
    cadence: each window is a ``ticks xN`` slice whose children are the
    window's per-phase AGGREGATES (admit, prefill, decode_dispatch,
    host_fetch, sample_commit, callback_detok) laid end-to-end. Phases
    interleave tick-by-tick in reality; the aggregate layout preserves
    the budget split, which is what head-of-line diagnosis needs.
    Counter tracks carry slot occupancy and queue depth.
  - ``train``     — the ``StepTimeline`` cadence windows (data_wait,
    dispatch, host_fetch, eval, sample, checkpoint), same aggregate
    layout, plus loss/throughput counters.
  - ``incidents`` — instants for restarts, drains, stalls, watchdog
    halts, preemption signals, engine death; ``compile``/``recompile``
    events as slices (their measured compile seconds).

Timestamps are unix-epoch microseconds rebased to the first event, so
every row type lands on one consistent clock (span rows carry wall-clock
``t0`` precisely for this join).

CLI:  python -m building_llm_from_scratch_tpu.obs.trace out/metrics.jsonl \
          [-o out/trace.json]
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

# the phase/segment/event tables live in the ONE schema registry
# (obs/schema.py) — this module used to own private copies, which is the
# drift class graft-lint GL044 now forbids. Re-exported here because the
# engine and tests historically import TICK_PHASES from obs.trace.
from building_llm_from_scratch_tpu.obs.schema import (  # noqa: F401
    INCIDENT_EVENTS,
    REQUEST_EVENTS,
    TICK_PHASES,
    TRAIN_SEGMENTS,
)

_PID_REQUESTS, _PID_ENGINE, _PID_TRAIN, _PID_INCIDENTS = 1, 2, 3, 4


def _meta(pid: int, name: str, tid: Optional[int] = None,
          tname: Optional[str] = None) -> List[dict]:
    out = [{"ph": "M", "pid": pid, "name": "process_name",
            "args": {"name": name}}]
    if tid is not None:
        out.append({"ph": "M", "pid": pid, "tid": tid,
                    "name": "thread_name", "args": {"name": tname}})
    return out


def _x(name: str, pid: int, tid: int, ts_us: float, dur_us: float,
       cat: str, args: Optional[dict] = None) -> dict:
    ev = {"ph": "X", "name": name, "pid": pid, "tid": tid,
          "ts": round(ts_us, 3), "dur": round(max(dur_us, 0.0), 3),
          "cat": cat}
    if args:
        ev["args"] = args
    return ev


def _instant(name: str, pid: int, tid: int, ts_us: float, cat: str,
             args: Optional[dict] = None) -> dict:
    ev = {"ph": "i", "s": "t", "name": name, "pid": pid, "tid": tid,
          "ts": round(ts_us, 3), "cat": cat}
    if args:
        ev["args"] = args
    return ev


def _counter(name: str, pid: int, ts_us: float, values: dict) -> dict:
    return {"ph": "C", "name": name, "pid": pid, "tid": 0,
            "ts": round(ts_us, 3), "args": values}


def _num(row: dict, key: str) -> Optional[float]:
    v = row.get(key)
    return float(v) if isinstance(v, (int, float)) else None


def _memory_counters(row: dict, pid: int, ts_us: float) -> List[dict]:
    """One ``memory_snapshot`` event -> Perfetto counter samples: the
    component composition as ONE stacked counter track (Perfetto stacks
    the args keys), plus a headroom track when capacity is known. The
    values are the ledger's deterministic ``nbytes`` sums — identical
    runs produce byte-identical tracks (keys sorted so the rendering
    never depends on emission order). ``host_rss`` is the one POLLED
    component (OS-dependent, run-to-run noise) and is host memory
    besides — it stays off the device-composition track."""
    events: List[dict] = []
    comps = row.get("components")
    if isinstance(comps, dict):
        values = {k: comps[k] for k in sorted(comps)
                  if k != "host_rss"
                  and isinstance(comps[k], (int, float))}
        if values:
            events.append(_counter("memory (bytes)", pid, ts_us, values))
    headroom = row.get("headroom_bytes")
    if isinstance(headroom, (int, float)):
        events.append(_counter("memory headroom (bytes)", pid, ts_us,
                               {"headroom": headroom}))
    return events


def load_jsonl(path: str) -> List[dict]:
    rows = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rows.append(json.loads(line))
            except json.JSONDecodeError:
                continue                      # a torn row must not kill export
    return rows


def _span_events(row: dict, base_s: float) -> List[dict]:
    """One request span row -> root X + child X events on its own track."""
    rid = row.get("request_id")
    tid = int(rid) if isinstance(rid, int) else 0
    args = {k: v for k, v in row.items()
            if k not in ("type", "time", "children", "t0", "dur_s", "cat",
                         "name")}
    t0 = _num(row, "t0")
    dur = _num(row, "dur_s")
    if t0 is None or dur is None:
        return []
    out = [_x(str(row.get("name", "span")), _PID_REQUESTS, tid,
              (t0 - base_s) * 1e6, dur * 1e6, str(row.get("cat", "span")),
              args)]
    for c in row.get("children") or []:
        ct0, cdur = _num(c, "t0"), _num(c, "dur_s")
        if ct0 is None or cdur is None:
            continue
        out.append(_x(str(c.get("name", "phase")), _PID_REQUESTS, tid,
                      (ct0 - base_s) * 1e6, cdur * 1e6, "request_phase"))
    return out


def _window_events(row: dict, pid: int, label: str, phases,
                   prefix: str, base_s: float, t_prev: Optional[float],
                   n_key: str) -> List[dict]:
    """One cadence metrics row -> a window slice + sequential per-phase
    aggregate children. ``prefix`` maps phase -> row field (e.g.
    ``tick_admit_s``); the window ends at the row's wall time."""
    t_end = _num(row, "time")
    if t_end is None:
        return []
    sums = {ph: (_num(row, f"{prefix}{ph}_s") or 0.0) for ph in phases}
    total = sum(sums.values())
    if total <= 0:
        return []
    win_t0 = _num(row, "win_t0")
    if win_t0 is None:
        # trainer rows carry no window anchor: reconstruct from the
        # previous cadence row, floored at the phase-sum (clock skew)
        win_t0 = t_prev if t_prev is not None else t_end - total
        win_t0 = min(win_t0, t_end - total)
    n = row.get(n_key)
    name = f"{label} x{int(n)}" if isinstance(n, (int, float)) else label
    out = [_x(name, pid, 1, (win_t0 - base_s) * 1e6,
              (t_end - win_t0) * 1e6, label,
              {k: v for k, v in row.items()
               if isinstance(v, (int, float)) and k != "time"})]
    cursor = win_t0
    for ph in phases:
        if sums[ph] <= 0:
            continue
        out.append(_x(ph, pid, 2, (cursor - base_s) * 1e6,
                      sums[ph] * 1e6, f"{label}_phase"))
        cursor += sums[ph]
    return out


def chrome_trace(rows: List[dict],
                 base_s: Optional[float] = None) -> Dict[str, Any]:
    """Convert parsed metrics-JSONL rows to a Chrome trace-event dict
    (``json.dump`` it to get a Perfetto-loadable file). ``base_s`` pins
    the epoch the timeline rebases onto — the fleet exporter passes the
    minimum across ALL merged files so every process shares one clock;
    single-file export derives it from this file's rows."""
    if base_s is None:
        times = [r["time"] for r in rows
                 if isinstance(r.get("time"), (int, float))]
        times += [r["t0"] for r in rows if r.get("type") == "span"
                  and isinstance(r.get("t0"), (int, float))]
        base_s = min(times) if times else 0.0
    events: List[dict] = []
    events += _meta(_PID_REQUESTS, "requests")
    events += _meta(_PID_ENGINE, "engine", 1, "tick windows")
    events += _meta(_PID_ENGINE, "engine", 2, "tick phases")
    events += _meta(_PID_TRAIN, "train", 1, "step windows")
    events += _meta(_PID_TRAIN, "train", 2, "step phases")
    events += _meta(_PID_INCIDENTS, "incidents", 1, "incidents")
    events += _meta(_PID_INCIDENTS, "incidents", 2, "compiles")

    n_request_spans = n_tick_windows = n_train_windows = 0
    t_prev_tick: Optional[float] = None
    t_prev_train: Optional[float] = None
    named_req_tracks = set()
    for row in rows:
        kind = row.get("type")
        t = _num(row, "time")
        if kind == "span":
            evs = _span_events(row, base_s)
            if evs:
                n_request_spans += 1
                rid = row.get("request_id")
                if isinstance(rid, int) and rid not in named_req_tracks:
                    named_req_tracks.add(rid)
                    events.append(
                        {"ph": "M", "pid": _PID_REQUESTS, "tid": rid,
                         "name": "thread_name",
                         "args": {"name": f"request {rid}"}})
            events += evs
        elif kind == "metrics" and t is not None:
            if _num(row, "tick_total_s"):
                evs = _window_events(row, _PID_ENGINE, "ticks",
                                     TICK_PHASES, "tick_", base_s,
                                     t_prev_tick, "ticks_in_window")
                if evs:
                    n_tick_windows += 1
                events += evs
                t_prev_tick = t
                gauges = {k: row[k] for k in ("slot_occupancy",
                                              "queue_depth")
                          if isinstance(row.get(k), (int, float))}
                if gauges:
                    events.append(_counter("engine load", _PID_ENGINE,
                                           (t - base_s) * 1e6, gauges))
            elif any(_num(row, f"{s}_s") for s in TRAIN_SEGMENTS):
                evs = _window_events(row, _PID_TRAIN, "steps",
                                     TRAIN_SEGMENTS, "", base_s,
                                     t_prev_train, "steps_in_window")
                if evs:
                    n_train_windows += 1
                events += evs
                t_prev_train = t
        elif kind == "event" and t is not None:
            name = row.get("event")
            args = {k: v for k, v in row.items()
                    if k not in ("type", "time")}
            ts_us = (t - base_s) * 1e6
            if name in ("compile", "recompile"):
                dur = _num(row, "compile_seconds") or 0.0
                events.append(_x(f"{name}:{row.get('label', '?')}",
                                 _PID_INCIDENTS, 2,
                                 ts_us - dur * 1e6, dur * 1e6,
                                 "compile", args))
            elif name == "memory_snapshot":
                # memory composition over time, next to the tick/step
                # phases of whichever tier emitted it
                events += _memory_counters(
                    row, _PID_TRAIN if row.get("source") == "trainer"
                    else _PID_ENGINE, ts_us)
            elif name in REQUEST_EVENTS and isinstance(
                    row.get("request_id"), int):
                events.append(_instant(name, _PID_REQUESTS,
                                       int(row["request_id"]), ts_us,
                                       "request_event", args))
            elif name in INCIDENT_EVENTS:
                events.append(_instant(str(name), _PID_INCIDENTS, 1,
                                       ts_us, "incident", args))
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "metadata": {
            "source": "building_llm_from_scratch_tpu obs/trace.py",
            "n_request_spans": n_request_spans,
            "n_tick_windows": n_tick_windows,
            "n_train_windows": n_train_windows,
            "trace_base_unix_s": base_s,
        },
    }


def export_chrome_trace(jsonl_path: str, out_path: str) -> Dict[str, Any]:
    """Render ``jsonl_path`` as Chrome trace JSON at ``out_path``; returns
    the trace's ``metadata`` summary (span/window counts)."""
    trace = chrome_trace(load_jsonl(jsonl_path))
    with open(out_path, "w") as f:
        json.dump(trace, f)
    return trace["metadata"]


def main(argv=None) -> int:
    import argparse
    import os

    p = argparse.ArgumentParser(
        description="Export a --metrics_jsonl file as Chrome trace-event "
                    "JSON (load it at https://ui.perfetto.dev).")
    p.add_argument("jsonl", help="metrics JSONL written by --metrics_jsonl")
    p.add_argument("-o", "--out", default=None,
                   help="output path (default: <jsonl>.trace.json)")
    args = p.parse_args(argv)
    out = args.out or (os.path.splitext(args.jsonl)[0] + ".trace.json")
    meta = export_chrome_trace(args.jsonl, out)
    print(f"wrote {out}: {meta['n_request_spans']} request spans, "
          f"{meta['n_tick_windows']} tick windows, "
          f"{meta['n_train_windows']} train windows")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
