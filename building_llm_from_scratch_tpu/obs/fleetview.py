"""Fleet observatory: merge fleet + worker JSONLs into ONE timeline.

A ``ProcessFleet`` run writes N+1 metrics files: the fleet's own JSONL
(request spans, dispatch events, worker births/deaths, ``clock_sync``
samples) and one JSONL per worker process (its engine's tick windows,
``worker_request``/``rpc`` spans, serving events) — with one HEADER per
incarnation stacked in the same file, because restarted workers append.
Each process stamps rows with ITS OWN wall clock, so a naive merge puts
a worker's prefill *before* the RPC that delivered the request whenever
the clocks disagree.

This module renders the whole set as one skew-corrected Perfetto
timeline:

  - worker rows are shifted onto the fleet's clock using the NTP-style
    offsets the fleet measured over its RPC channel (``clock_sync``
    events: ``offset_s`` = worker wall − fleet wall at the round-trip
    midpoint, ``uncertainty_s`` = rtt/2 — the lowest-uncertainty sample
    per (replica, incarnation) wins);
  - the fleet's file renders exactly as ``obs/trace.py`` would render
    it alone (request span trees with their ``rpc:<method>`` children,
    incident instants), pinned to the merged clock base;
  - each worker gets its own process track (``worker<i>``): engine tick
    windows, per-request ``worker_request`` + ``rpc`` server spans, and
    its incident instants, all keyed by the FLEET request id so a
    request's router-side and worker-side spans sit on aligned tracks;
  - Chrome flow arrows connect each fleet request span to the
    ``worker_request`` span(s) that served it — the cross-process edge
    is scrubbable, not inferred.

CLI:  python -m building_llm_from_scratch_tpu.obs.fleetview \
          out/metrics.jsonl [-o out/fleet_trace.json]
(worker files are discovered as ``<fleet_jsonl>.worker*.jsonl`` — the
``ProcessFleet`` naming convention.)
"""

from __future__ import annotations

import glob
import json
from typing import Any, Dict, List, Optional, Tuple

from building_llm_from_scratch_tpu.obs.schema import (
    INCIDENT_EVENTS,
    TICK_PHASES,
)
from building_llm_from_scratch_tpu.obs.trace import (
    _PID_REQUESTS,
    _instant,
    _memory_counters,
    _meta,
    _num,
    _window_events,
    _x,
    chrome_trace,
    load_jsonl,
)

#: Worker process tracks start here (fleet tracks are pids 1..4).
_PID_WORKER0 = 10

#: Span rows on a worker track sit at ``tid = request_id + _TID_SPANS``
#: — the offset keeps small client ids clear of the window tids (1, 2).
_TID_SPANS = 100
_TID_INCIDENTS = 3


class _Segment:
    """One worker incarnation's slice of its (append-mode) JSONL."""

    __slots__ = ("replica", "incarnation", "pid", "rows", "offset_s",
                 "uncertainty_s")

    def __init__(self, replica: int, incarnation: int,
                 pid: Optional[int], rows: List[dict]):
        self.replica = replica
        self.incarnation = incarnation
        self.pid = pid
        self.rows = rows
        self.offset_s = 0.0          # worker wall − fleet wall
        self.uncertainty_s: Optional[float] = None


def discover_worker_files(fleet_jsonl: str) -> List[str]:
    """The fleet's workers write ``<fleet_jsonl>.worker<i>.jsonl``."""
    return sorted(glob.glob(fleet_jsonl + ".worker*.jsonl"))


def split_incarnations(rows: List[dict],
                       fallback_replica: int = -1) -> List[_Segment]:
    """Split an append-mode worker JSONL into per-incarnation segments.

    Restarted workers APPEND to their file, so it holds one header per
    incarnation; each header starts a new segment and carries the
    incarnation's replica/incarnation/pid identity. Pre-header rows
    (there should be none) attach to a synthetic segment so no row is
    silently dropped.
    """
    segments: List[_Segment] = []
    current: Optional[_Segment] = None
    for row in rows:
        if row.get("type") == "header":
            rep = row.get("replica", fallback_replica)
            inc = row.get("incarnation",
                          len(segments))  # pre-v10 files: ordinal
            current = _Segment(rep, inc, row.get("pid"), [row])
            segments.append(current)
            continue
        if current is None:
            current = _Segment(fallback_replica, 0, None, [])
            segments.append(current)
        current.rows.append(row)
    return segments


def clock_offsets(fleet_rows: List[dict]
                  ) -> Dict[Tuple[int, int], Tuple[float, float]]:
    """(replica, incarnation) -> (offset_s, uncertainty_s) from the
    fleet's ``clock_sync`` events; the lowest-uncertainty sample wins.
    """
    best: Dict[Tuple[int, int], Tuple[float, float]] = {}
    for row in fleet_rows:
        if row.get("type") != "event" or row.get("event") != "clock_sync":
            continue
        rep, inc = row.get("replica"), row.get("incarnation", 0)
        off, unc = _num(row, "offset_s"), _num(row, "uncertainty_s")
        if rep is None or off is None:
            continue
        unc = unc if unc is not None else float("inf")
        key = (rep, inc)
        if key not in best or unc <= best[key][1]:
            best[key] = (off, unc)
    return best


def _shift_row(row: dict, offset_s: float) -> dict:
    """A worker row rebased onto the fleet clock (subtract the measured
    worker−fleet offset from every wall-time field, children too)."""
    if not offset_s:
        return row
    out = dict(row)
    for key in ("time", "t0", "win_t0"):
        v = out.get(key)
        if isinstance(v, (int, float)):
            out[key] = v - offset_s
    if isinstance(out.get("children"), list):
        kids = []
        for c in out["children"]:
            c = dict(c)
            if isinstance(c.get("t0"), (int, float)):
                c["t0"] = c["t0"] - offset_s
            kids.append(c)
        out["children"] = kids
    return out


def _segment_events(seg: _Segment, pid: int, base_s: float,
                    named_tracks: set) -> Tuple[List[dict], int, int]:
    """One incarnation's rows -> Chrome events on the worker's track.
    Returns (events, n_spans, n_incidents)."""
    events: List[dict] = []
    n_spans = n_incidents = 0
    t_prev: Optional[float] = None
    for row in seg.rows:
        kind = row.get("type")
        if kind == "span":
            t0, dur = _num(row, "t0"), _num(row, "dur_s")
            if t0 is None or dur is None:
                continue
            rid = row.get("request_id")
            tid = (rid + _TID_SPANS if isinstance(rid, int)
                   else _TID_SPANS - 1)
            if tid not in named_tracks:
                named_tracks.add(tid)
                events.append({"ph": "M", "pid": pid, "tid": tid,
                               "name": "thread_name",
                               "args": {"name": f"request {rid}"}})
            args = {k: v for k, v in row.items()
                    if k not in ("type", "time", "children", "t0",
                                 "dur_s", "cat", "name")}
            n_spans += 1
            events.append(_x(str(row.get("name", "span")), pid, tid,
                             (t0 - base_s) * 1e6, dur * 1e6,
                             str(row.get("cat", "span")), args))
            for c in row.get("children") or []:
                ct0, cdur = _num(c, "t0"), _num(c, "dur_s")
                if ct0 is None or cdur is None:
                    continue
                events.append(_x(str(c.get("name", "phase")), pid, tid,
                                 (ct0 - base_s) * 1e6, cdur * 1e6,
                                 "request_phase"))
        elif kind == "metrics":
            t = _num(row, "time")
            if t is not None and _num(row, "tick_total_s"):
                events += _window_events(row, pid, "ticks", TICK_PHASES,
                                         "tick_", base_s, t_prev,
                                         "ticks_in_window")
                t_prev = t
        elif kind == "event":
            t = _num(row, "time")
            name = row.get("event")
            if t is not None and name == "memory_snapshot":
                # each worker's HBM composition on its OWN process row,
                # skew-corrected like every other worker timestamp
                events += _memory_counters(row, pid, (t - base_s) * 1e6)
            elif t is not None and name in INCIDENT_EVENTS:
                n_incidents += 1
                events.append(_instant(
                    str(name), pid, _TID_INCIDENTS, (t - base_s) * 1e6,
                    "incident",
                    {k: v for k, v in row.items()
                     if k not in ("type", "time")}))
    return events, n_spans, n_incidents


def _flow_events(fleet_rows: List[dict], segments: List[_Segment],
                 base_s: float) -> List[dict]:
    """Chrome flow arrows: fleet request span -> the worker_request
    span(s) that served it, joined on the FLEET request id."""
    starts: Dict[int, float] = {}
    for row in fleet_rows:
        if (row.get("type") == "span" and row.get("name") == "request"
                and isinstance(row.get("request_id"), int)):
            t0 = _num(row, "t0")
            if t0 is not None:
                starts.setdefault(row["request_id"], t0)
    events: List[dict] = []
    for seg in segments:
        pid = _PID_WORKER0 + seg.replica
        for row in seg.rows:
            if (row.get("type") != "span"
                    or row.get("name") != "worker_request"
                    or not isinstance(row.get("request_id"), int)):
                continue
            rid = row["request_id"]
            t0 = _num(row, "t0")
            if rid not in starts or t0 is None:
                continue
            # flow ids must be unique per arrow; requests can be served
            # twice (redispatch), so fold the worker into the id
            fid = rid * 64 + (seg.replica % 64)
            events.append({"ph": "s", "id": fid, "pid": _PID_REQUESTS,
                           "tid": rid, "name": "dispatch", "cat": "rpc",
                           "ts": round((starts[rid] - base_s) * 1e6 + 1,
                                       3)})
            events.append({"ph": "f", "bp": "e", "id": fid, "pid": pid,
                           "tid": rid + _TID_SPANS, "name": "dispatch",
                           "cat": "rpc",
                           "ts": round((t0 - base_s) * 1e6 + 1, 3)})
    return events


def fleet_chrome_trace(fleet_jsonl: str,
                       worker_jsonls: Optional[List[str]] = None
                       ) -> Dict[str, Any]:
    """Merge the fleet JSONL + its workers' JSONLs into one Chrome
    trace-event dict on the fleet's clock."""
    fleet_rows = load_jsonl(fleet_jsonl)
    paths = (worker_jsonls if worker_jsonls is not None
             else discover_worker_files(fleet_jsonl))
    offsets = clock_offsets(fleet_rows)
    segments: List[_Segment] = []
    for i, path in enumerate(paths):
        for seg in split_incarnations(load_jsonl(path),
                                      fallback_replica=i):
            got = (offsets.get((seg.replica, seg.incarnation))
                   # an incarnation that died before any clock_sync
                   # reached the JSONL: reuse the replica's best sample
                   # (same host — the skew is the host's, not the
                   # process's)
                   or min((v for (r, _), v in offsets.items()
                           if r == seg.replica),
                          key=lambda v: v[1], default=None))
            if got is not None:
                seg.offset_s, seg.uncertainty_s = got
                seg.rows = [_shift_row(r, seg.offset_s)
                            for r in seg.rows]
            segments.append(seg)

    times: List[float] = []
    for rows in [fleet_rows] + [s.rows for s in segments]:
        times += [r["time"] for r in rows
                  if isinstance(r.get("time"), (int, float))]
        times += [r["t0"] for r in rows if r.get("type") == "span"
                  and isinstance(r.get("t0"), (int, float))]
    base_s = min(times) if times else 0.0

    trace = chrome_trace(fleet_rows, base_s=base_s)
    events = trace["traceEvents"]
    n_worker_spans = n_worker_incidents = 0
    named: Dict[int, set] = {}
    for seg in segments:
        pid = _PID_WORKER0 + seg.replica
        if seg.replica not in named:
            named[seg.replica] = set()
            events += _meta(pid, f"worker{seg.replica}", 1,
                            "tick windows")
            events += _meta(pid, f"worker{seg.replica}", 2,
                            "tick phases")
            events += _meta(pid, f"worker{seg.replica}",
                            _TID_INCIDENTS, "incidents")
        evs, n_s, n_i = _segment_events(seg, pid, base_s,
                                        named[seg.replica])
        events += evs
        n_worker_spans += n_s
        n_worker_incidents += n_i
    flows = _flow_events(fleet_rows, segments, base_s)
    events += flows

    trace["metadata"].update({
        "source": "building_llm_from_scratch_tpu obs/fleetview.py",
        "n_worker_files": len(paths),
        "n_incarnations": len(segments),
        "n_worker_spans": n_worker_spans,
        "n_worker_incidents": n_worker_incidents,
        "n_flow_edges": len(flows) // 2,
        "clock_offsets_s": {
            f"worker{s.replica}.inc{s.incarnation}":
                {"offset_s": round(s.offset_s, 6),
                 "uncertainty_s": (round(s.uncertainty_s, 6)
                                   if s.uncertainty_s is not None
                                   else None)}
            for s in segments},
    })
    return trace


def export_fleet_trace(fleet_jsonl: str, out_path: str,
                       worker_jsonls: Optional[List[str]] = None
                       ) -> Dict[str, Any]:
    """Render the merged fleet timeline at ``out_path``; returns the
    trace's ``metadata`` summary."""
    trace = fleet_chrome_trace(fleet_jsonl, worker_jsonls)
    with open(out_path, "w") as f:
        json.dump(trace, f, sort_keys=True)
    return trace["metadata"]


def main(argv=None) -> int:
    import argparse
    import os

    p = argparse.ArgumentParser(
        description="Merge a ProcessFleet's metrics JSONL + its "
                    "<jsonl>.worker*.jsonl files into one skew-"
                    "corrected Chrome trace (https://ui.perfetto.dev).")
    p.add_argument("jsonl", help="the FLEET's metrics JSONL")
    p.add_argument("-o", "--out", default=None,
                   help="output path (default: <jsonl>.fleet_trace.json)")
    p.add_argument("--worker", action="append", default=None,
                   help="explicit worker JSONL (repeatable; default: "
                        "discover <jsonl>.worker*.jsonl)")
    args = p.parse_args(argv)
    out = args.out or (os.path.splitext(args.jsonl)[0]
                       + ".fleet_trace.json")
    meta = export_fleet_trace(args.jsonl, out, args.worker)
    print(f"wrote {out}: {meta['n_request_spans']} fleet request spans, "
          f"{meta['n_worker_spans']} worker spans across "
          f"{meta['n_incarnations']} incarnations "
          f"({meta['n_worker_files']} worker files), "
          f"{meta['n_flow_edges']} rpc edges, "
          f"{meta['n_worker_incidents']} worker incidents")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
