"""Memory observatory: a byte-exact ledger of device (and host) memory.

``MemoryLedger`` makes memory a first-class observed resource: every
consumer of HBM registers a named **component** whose size is measured
from the ACTUAL arrays (``nbytes`` sums over the live pytree — metadata
reads, never re-derived formulas and never a device sync), and the
ledger turns those into

  - a composition **snapshot** per cadence window (the ``memory_snapshot``
    event — pure ``nbytes`` math, so identical runs produce byte-identical
    snapshots and the Chrome-trace counter tracks built from them are
    deterministic);
  - **drift** detection (the leak detector): a component whose measured
    bytes diverge from its registered byte-exact expectation, a component
    that only ever grows, a probe-reported invariant violation (e.g. a
    prefix pane still pinned at a cadence boundary — pins are transient
    by design), or ledger-vs-``device.memory_stats()`` divergence where
    the platform reports stats — each emits ``memory_drift`` naming the
    component;
  - **pressure** detection (the near-OOM flight recorder): when device
    components exceed ``pressure_frac`` of capacity, ``memory_pressure``
    fires with the full component breakdown attached, so the post-mortem
    has the composition at the moment headroom vanished. n/a-safe: on
    CPU (no ``bytes_limit``) the headroom gauge is simply absent;
  - labeled **attribution** series for ``/metrics`` (live KV bytes by
    tenant, prefix-store bytes by namespace, adapter-pool bytes by
    tenant) with per-label high watermarks.

Sync discipline: providers return host ints computed from array METADATA
(``.nbytes``, host-side numpy state). ``snapshot``/``observe``/``gauges``
are registered GL01x hot paths (analysis/hostsync.py) — nothing in here
may block the host on the device; the only host-side polls (``/proc``
RSS, ``device.memory_stats()``) happen at cadence inside ``observe`` and
never enter the deterministic snapshot values.

One source of truth: ``utils/memory.py``'s ``device_memory_stats`` /
``host_rss_bytes`` are polled ONLY through the ledger (the trainer's
former ad-hoc gauges now read ``legacy_row()``), so HBM-in-use, peak and
RSS can never disagree between surfaces.
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Dict, List, Optional, Tuple

from building_llm_from_scratch_tpu.utils.memory import (
    device_memory_stats,
    host_rss_bytes,
)

logger = logging.getLogger(__name__)

__all__ = ["MemoryLedger", "pytree_nbytes"]


def pytree_nbytes(tree: Any) -> int:
    """Total bytes of every array leaf in ``tree`` — metadata only
    (``.nbytes`` never syncs), measured from the actual arrays."""
    try:
        import jax

        leaves = jax.tree_util.tree_leaves(tree)
    except Exception:                      # jax-free caller: walk manually
        leaves = []
        stack = [tree]
        while stack:
            node = stack.pop()
            if isinstance(node, dict):
                stack.extend(node.values())
            elif isinstance(node, (list, tuple)):
                stack.extend(node)
            else:
                leaves.append(node)
    return sum(int(leaf.nbytes) for leaf in leaves
               if hasattr(leaf, "nbytes"))


def _escape_label(value: str) -> str:
    return str(value).replace("\\", "\\\\").replace('"', '\\"')


class MemoryLedger:
    """Byte-exact component ledger with drift + pressure detection.

    ``emit`` is the event sink — ``emit(kind, **fields)`` — so the engine
    can inject its replica-labeling wrapper and the trainer its metrics
    sink; defaults to the global metrics singleton. ``source`` labels
    which tier's ledger emitted a row ("engine"/"trainer"/...), which is
    how the trace renderer picks the process track."""

    def __init__(self, *, emit: Optional[Callable[..., None]] = None,
                 source: str = "engine",
                 capacity_bytes: Optional[int] = None,
                 auto_capacity: bool = True,
                 pressure_frac: float = 0.92,
                 device_drift_frac: float = 0.10,
                 device_drift_min_bytes: int = 64 << 20,
                 growth_streak: int = 12,
                 poll_device: bool = True,
                 device_stats_fn: Callable[[], Dict[str, int]] =
                 device_memory_stats,
                 rss_fn: Callable[[], Optional[int]] = host_rss_bytes):
        if emit is None:
            from building_llm_from_scratch_tpu.obs.metrics import emit_event

            emit = emit_event
        self._emit = emit
        self.source = source
        if capacity_bytes is None and auto_capacity:
            from building_llm_from_scratch_tpu.obs.compile import (
                device_hbm_capacity,
            )

            capacity_bytes = device_hbm_capacity()
        self.capacity_bytes = capacity_bytes
        self.pressure_frac = float(pressure_frac)
        self.device_drift_frac = float(device_drift_frac)
        self.device_drift_min_bytes = int(device_drift_min_bytes)
        self.growth_streak = int(growth_streak)
        self._poll_device = bool(poll_device)
        self._device_stats_fn = device_stats_fn
        self._rss_fn = rss_fn

        # name -> (provider, device?)   providers return host int bytes
        self._components: Dict[str, Tuple[Callable[[], int], bool]] = {}
        self._expected: Dict[str, Callable[[], int]] = {}
        # series -> (label key, provider returning {label value: bytes})
        self._labeled: Dict[str, Tuple[str, Callable[[], Dict[str, int]]]] \
            = {}
        self._probes: Dict[str, Callable[[], Optional[Dict[str, Any]]]] = {}

        self.sizes: Dict[str, int] = {}
        self.watermarks: Dict[str, int] = {}
        self.labeled_sizes: Dict[str, Dict[str, int]] = {}
        self.labeled_peaks: Dict[str, Dict[str, int]] = {}
        self._growth_last: Dict[str, int] = {}
        self._growth_streaks: Dict[str, int] = {}
        self._growth_fired: Dict[str, bool] = {}
        self._pressure_armed = True
        self.n_snapshots = 0
        self.n_drift_events = 0
        self.n_pressure_events = 0
        self.device_stats: Dict[str, int] = {}
        self.host_rss: Optional[int] = None

    # -- registration -----------------------------------------------------

    def register(self, name: str, provider: Callable[[], int], *,
                 device: bool = True,
                 expected: Optional[Callable[[], int]] = None) -> None:
        """Register component ``name``. ``provider()`` -> bytes, measured
        from live arrays (``pytree_nbytes``-style). ``expected`` is the
        optional byte-exact expectation (e.g. ``bytes_per_slot x n_slots``
        for the slot cache) — ANY mismatch is a ``memory_drift``."""
        self._components[name] = (provider, bool(device))
        if expected is not None:
            self._expected[name] = expected

    def register_labeled(self, series: str, label: str,
                         provider: Callable[[], Dict[str, int]]) -> None:
        """Register an attribution series (``series{label="..."}``) —
        per-tenant live KV, per-namespace prefix bytes, etc. High
        watermarks are tracked per label value."""
        self._labeled[series] = (label, provider)

    def register_probe(self, name: str,
                       probe: Callable[[], Optional[Dict[str, Any]]]) \
            -> None:
        """Register an invariant probe run each ``observe``. A non-None
        return is a violation: ``memory_drift`` fires with
        ``component=name`` and the probe's dict merged into the event
        (the probe supplies ``reason``, default "invariant")."""
        self._probes[name] = probe

    def track_host_rss(self) -> None:
        """Track host RSS as a (non-device) ledger component, so host
        growth (e.g. checkpoint staging buffers) is attributed instead
        of being mystery growth next to the device numbers."""
        def _rss() -> int:
            v = self._rss_fn()
            return 0 if v is None else v

        self.register("host_rss", _rss, device=False)

    # -- measurement ------------------------------------------------------

    # graft: hot-path
    def snapshot(self) -> Dict[str, int]:
        """Refresh every component from its provider; update watermarks.
        Pure metadata math — no events, no device polls, no syncs."""
        for name, (provider, _device) in self._components.items():
            size = int(provider())   # graft-ok: GL011 providers return host ints
            self.sizes[name] = size
            if size > self.watermarks.get(name, -1):
                self.watermarks[name] = size
        for series, (_label, provider) in self._labeled.items():
            sizes = {str(k): int(v)   # graft-ok: GL011 host attribution dict
                     for k, v in provider().items()}
            self.labeled_sizes[series] = sizes
            peaks = self.labeled_peaks.setdefault(series, {})
            for key, size in sizes.items():
                if size > peaks.get(key, -1):
                    peaks[key] = size
        return dict(self.sizes)

    def device_bytes(self) -> int:
        return sum(size for name, size in self.sizes.items()
                   if self._components[name][1])

    def host_bytes(self) -> int:
        return sum(size for name, size in self.sizes.items()
                   if not self._components[name][1])

    def total_bytes(self) -> int:
        return sum(self.sizes.values())

    def headroom_bytes(self) -> Optional[int]:
        """capacity − device components; None where capacity is unknown
        (CPU backends report no ``bytes_limit``) — n/a-safe by absence."""
        if self.capacity_bytes is None:
            return None
        return self.capacity_bytes - self.device_bytes()

    # -- cadence ----------------------------------------------------------

    # graft: hot-path
    def observe(self, step: Optional[int] = None) -> Dict[str, int]:
        """The cadence entry point: snapshot, run every detector, emit
        ``memory_snapshot`` (+ ``memory_drift``/``memory_pressure`` as
        needed). The snapshot event carries ONLY deterministic ``nbytes``
        values — polled device/RSS numbers stay out of it so the trace
        counter tracks are byte-identical across identical runs."""
        comps = self.snapshot()
        self.n_snapshots += 1
        self._check_expected()
        self._check_growth()
        self._check_probes()
        if self._poll_device:
            self._poll()
            self._check_device_divergence()
        self._check_pressure(step)
        fields: Dict[str, Any] = {
            "source": self.source,
            "components": comps,
            "total_bytes": self.total_bytes(),
            "device_bytes": self.device_bytes(),
        }
        host = self.host_bytes()
        if host:
            fields["host_bytes"] = host
        if self.capacity_bytes is not None:
            fields["capacity_bytes"] = self.capacity_bytes
            fields["headroom_bytes"] = self.headroom_bytes()
        if self.labeled_sizes:
            fields["labeled"] = {series: dict(sizes) for series, sizes
                                 in self.labeled_sizes.items() if sizes}
        if step is not None:
            fields["step"] = step
        self._emit("memory_snapshot", **fields)
        return comps

    def _poll(self) -> None:
        try:
            self.device_stats = self._device_stats_fn() or {}
        except Exception:                            # platform quirk: skip
            self.device_stats = {}
        try:
            self.host_rss = self._rss_fn()
        except Exception:
            self.host_rss = None

    # -- detectors --------------------------------------------------------

    def _drift(self, component: str, reason: str, **fields: Any) -> None:
        self.n_drift_events += 1
        self._emit("memory_drift", component=component, reason=reason,
                   source=self.source, **fields)
        logger.warning("memory_drift[%s]: %s %s", component, reason,
                       fields)

    def _check_expected(self) -> None:
        for name, expected_fn in self._expected.items():
            expected = int(expected_fn())  # graft-ok: GL011 host int math
            measured = self.sizes.get(name, 0)
            if measured != expected:
                self._drift(name, "reconcile", expected_bytes=expected,
                            measured_bytes=measured,
                            delta_bytes=measured - expected)

    def _check_growth(self) -> None:
        """A component that grows on EVERY snapshot for ``growth_streak``
        consecutive windows is leaking (healthy components plateau or
        shrink under eviction). Fires once per streak; re-arms when the
        component stops growing."""
        for name, size in self.sizes.items():
            prev = self._growth_last.get(name)
            self._growth_last[name] = size
            if prev is None:
                continue
            if size > prev:
                streaks = self._growth_streaks
                streaks[name] = streaks.get(name, 0) + 1
                if (streaks[name] >= self.growth_streak
                        and not self._growth_fired.get(name)):
                    self._growth_fired[name] = True
                    self._drift(name, "monotonic_growth",
                                streak=streaks[name],
                                measured_bytes=size)
            else:
                self._growth_streaks.pop(name, None)
                self._growth_fired.pop(name, None)

    def _check_probes(self) -> None:
        for name, probe in self._probes.items():
            try:
                violation = probe()
            except Exception:
                logger.exception("memory probe %s raised", name)
                continue
            if violation:
                fields = dict(violation)
                reason = fields.pop("reason", "invariant")
                self._drift(name, reason, **fields)

    def _check_device_divergence(self) -> None:
        """Ledger vs the runtime's own accounting, where the platform
        reports it (TPU/GPU; CPU returns {} and this is a no-op). Large
        untracked usage = something allocating outside the ledger."""
        in_use = self.device_stats.get("bytes_in_use")
        if in_use is None:
            return
        ledger = self.device_bytes()
        delta = in_use - ledger
        threshold = max(self.device_drift_min_bytes,
                        int(self.device_drift_frac * max(in_use, ledger)))
        if abs(delta) > threshold:
            self._drift("device", "device_divergence",
                        device_bytes=in_use, ledger_bytes=ledger,
                        delta_bytes=delta)

    def _check_pressure(self, step: Optional[int]) -> None:
        """Headroom watch with the flight-recorder dump: on the upward
        crossing of ``pressure_frac`` the FULL breakdown rides the event
        — the post-mortem should never need a second run to learn what
        was resident. Hysteresis: re-arms when usage falls back under."""
        if self.capacity_bytes is None or self.capacity_bytes <= 0:
            return
        used = self.device_bytes()
        frac = used / self.capacity_bytes
        if frac >= self.pressure_frac:
            if self._pressure_armed:
                self._pressure_armed = False
                self.n_pressure_events += 1
                fields: Dict[str, Any] = {
                    "source": self.source,
                    "headroom_bytes": self.capacity_bytes - used,
                    "capacity_bytes": self.capacity_bytes,
                    "used_frac": round(frac, 6),
                    "threshold_frac": self.pressure_frac,
                    "device_bytes": used,
                    "total_bytes": self.total_bytes(),
                    "components": {
                        name: size for name, size in self.sizes.items()
                        if self._components[name][1]},
                }
                if self.labeled_sizes:
                    fields["labeled"] = {
                        series: dict(sizes) for series, sizes
                        in self.labeled_sizes.items() if sizes}
                if step is not None:
                    fields["step"] = step
                self._emit("memory_pressure", **fields)
                logger.warning(
                    "memory_pressure: %.1f%% of %d bytes used "
                    "(headroom %d)", 100 * frac, self.capacity_bytes,
                    self.capacity_bytes - used)
        else:
            self._pressure_armed = True

    # -- export -----------------------------------------------------------

    # graft: hot-path
    def gauges(self) -> Dict[str, Any]:
        """Metric-ready gauges for a ``metrics_snapshot()`` merge: one
        labeled series per component (+ its high watermark), totals,
        headroom, the attribution series, and the last polled device/RSS
        numbers. Everything here is host state — safe under the scrape
        path's timed lock."""
        out: Dict[str, Any] = {}
        for name, size in self.sizes.items():
            lbl = f'{{component="{_escape_label(name)}"}}'
            out[f"mem_component_bytes{lbl}"] = size
            out[f"mem_component_peak_bytes{lbl}"] = self.watermarks[name]
        out["mem_total_bytes"] = self.total_bytes()
        out["mem_device_bytes"] = self.device_bytes()
        host = self.host_bytes()
        if host:
            out["mem_host_bytes"] = host
        if self.capacity_bytes is not None:
            out["mem_capacity_bytes"] = self.capacity_bytes
            out["mem_headroom_bytes"] = self.headroom_bytes()
        out["mem_drift_events"] = self.n_drift_events
        out["mem_pressure_events"] = self.n_pressure_events
        for series, (label, _provider) in self._labeled.items():
            sizes = self.labeled_sizes.get(series, {})
            peaks = self.labeled_peaks.get(series, {})
            for key in sorted(set(sizes) | set(peaks)):
                lbl = f'{{{label}="{_escape_label(key)}"}}'
                if key in sizes:
                    out[f"{series}{lbl}"] = sizes[key]
                if key in peaks:
                    out[f"{series}_peak{lbl}"] = peaks[key]
        for stats_key, gauge in (("bytes_in_use", "hbm_bytes_in_use"),
                                 ("peak_bytes_in_use", "hbm_peak_bytes")):
            if stats_key in self.device_stats:
                out[gauge] = self.device_stats[stats_key]
        if self.host_rss is not None:
            out["host_rss_bytes"] = self.host_rss
        return out

    def legacy_row(self) -> Dict[str, int]:
        """The trainer's historical metrics-row keys, now sourced from
        the ledger's single poll (Satellite: the ad-hoc gauges dedupe
        onto the ledger; renderers keep working unchanged)."""
        out: Dict[str, int] = {}
        if "bytes_in_use" in self.device_stats:
            out["hbm_bytes_in_use"] = self.device_stats["bytes_in_use"]
        if "peak_bytes_in_use" in self.device_stats:
            out["hbm_peak_bytes"] = self.device_stats["peak_bytes_in_use"]
        if self.host_rss is not None:
            out["host_rss_bytes"] = self.host_rss
        return out

    def describe(self) -> Dict[str, Any]:
        """Host-side summary for ``stats()``-style surfaces."""
        out: Dict[str, Any] = {
            "components": dict(self.sizes),
            "watermarks": dict(self.watermarks),
            "total_bytes": self.total_bytes(),
            "device_bytes": self.device_bytes(),
            "n_snapshots": self.n_snapshots,
            "n_drift_events": self.n_drift_events,
            "n_pressure_events": self.n_pressure_events,
        }
        if self.capacity_bytes is not None:
            out["capacity_bytes"] = self.capacity_bytes
            out["headroom_bytes"] = self.headroom_bytes()
        return out
