"""Per-step wall-clock breakdown + profiler trace annotation.

Two jobs, one API:

  1. **Accounting** — the trainer's cadence window needs to know where the
     wall-clock went: waiting on the data pipeline (``data_wait``),
     dispatching the jitted step (``dispatch`` — NOT execution: steps are
     async), blocking host fetches (``host_fetch``), and the non-step
     cadence work (``eval``/``sample``/``checkpoint``) whose time must be
     EXCLUDED from tok/s so the reported throughput measures training, not
     sampling (ISSUE-2 satellite: the old ``t_tokens/t_start`` window
     deflated tok/s whenever a sample or save fired inside it).
  2. **Navigability** — the same spans become ``jax.profiler``
     ``TraceAnnotation`` blocks, and each train step gets a
     ``StepTraceAnnotation``, so a ``--profile`` xplane capture shows named
     regions instead of an undifferentiated op soup.

Annotations are no-ops when no trace is active (jax makes them ~free), so
the spans stay on permanently — they are NOT gated on ``--profile``.
"""

from __future__ import annotations

import contextlib
import time
from typing import Dict, Iterator, Optional

#: Segments excluded from the throughput window: host-side cadence work
#: that is not training (the step loop is paused, not slow).
NON_STEP_SEGMENTS = ("eval", "sample", "checkpoint")


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named ``jax.profiler.TraceAnnotation`` span (degrades to a no-op if
    the profiler API is unavailable)."""
    try:
        import jax

        ctx = jax.profiler.TraceAnnotation(name)
    except Exception:
        ctx = contextlib.nullcontext()
    with ctx:
        yield


class StepTimeline:
    """Accumulates named wall-clock segments between ``drain()`` calls.

    The trainer drains once per logging cadence; the returned dict is the
    window's breakdown in seconds. Spans double as profiler trace
    annotations (see module docstring).
    """

    def __init__(self):
        self.seconds: Dict[str, float] = {}
        self.steps_in_window = 0

    def add(self, segment: str, dt: float) -> None:
        self.seconds[segment] = self.seconds.get(segment, 0.0) + dt

    @contextlib.contextmanager
    def span(self, segment: str) -> Iterator[None]:
        """Time a block into ``segment`` and annotate it in the trace."""
        t0 = time.perf_counter()
        try:
            with annotate(segment):
                yield
        finally:
            self.add(segment, time.perf_counter() - t0)

    @contextlib.contextmanager
    def step_span(self, step_num: int) -> Iterator[None]:
        """One train step: ``StepTraceAnnotation`` (so xplane groups ops
        per step) + ``dispatch`` accounting. The measured time is DISPATCH
        latency — jitted steps return before the device finishes; the
        execution catch-up is visible as ``host_fetch`` at cadence."""
        t0 = time.perf_counter()
        try:
            import jax

            ctx = jax.profiler.StepTraceAnnotation("train",
                                                   step_num=step_num)
        except Exception:
            ctx = contextlib.nullcontext()
        try:
            with ctx:
                yield
        finally:
            self.add("dispatch", time.perf_counter() - t0)
            self.steps_in_window += 1

    def non_step_seconds(self) -> float:
        return sum(self.seconds.get(k, 0.0) for k in NON_STEP_SEGMENTS)

    def drain(self) -> Dict[str, float]:
        """Return and reset the current window's breakdown. The dict also
        carries ``steps`` (train steps dispatched in the window)."""
        out = dict(self.seconds)
        out["steps"] = self.steps_in_window
        self.seconds = {}
        self.steps_in_window = 0
        return out


def window_stats(window: Dict[str, float], elapsed: float,
                 tokens: int) -> Dict[str, Optional[float]]:
    """Throughput/step-time numbers for one drained cadence window.

    ``elapsed`` is the full wall-clock since the window opened; the
    non-step segments (eval/sample/checkpoint) are subtracted so tok/s and
    step_time measure the training loop only.
    """
    non_step = sum(window.get(k, 0.0) for k in NON_STEP_SEGMENTS)
    step_seconds = max(elapsed - non_step, 0.0)
    steps = int(window.get("steps", 0))
    return {
        "tok_s": tokens / step_seconds if step_seconds > 0 else 0.0,
        "step_time_s": step_seconds / steps if steps else None,
        "step_seconds": step_seconds,
        "non_step_seconds": non_step,
    }
