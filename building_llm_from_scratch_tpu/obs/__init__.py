"""Observability subsystem: structured metrics (JSONL), step timeline +
trace annotations, MFU accounting, and the per-host stall detector.

Entry points:
  - ``MetricLogger`` / ``configure_metrics`` / ``get_metrics`` /
    ``emit_event`` — counters, gauges, timings, typed events, JSONL sink
    (obs/metrics.py);
  - ``StepTimeline`` / ``annotate`` / ``window_stats`` — per-step
    wall-clock breakdown + jax.profiler trace annotation (obs/timeline.py);
  - ``flops_per_token`` / ``compute_mfu`` / ``format_mfu`` — analytic
    FLOPs and MFU against chip peak (obs/mfu.py);
  - ``StallDetector`` — opt-in hung-step flight recorder (obs/stall.py).
"""

from building_llm_from_scratch_tpu.obs.metrics import (
    MetricLogger,
    configure_metrics,
    emit_event,
    get_metrics,
    run_metadata,
)
from building_llm_from_scratch_tpu.obs.mfu import (
    compute_mfu,
    device_peak_flops,
    flops_per_token,
    format_mfu,
)
from building_llm_from_scratch_tpu.obs.stall import StallDetector
from building_llm_from_scratch_tpu.obs.timeline import (
    NON_STEP_SEGMENTS,
    StepTimeline,
    annotate,
    window_stats,
)

__all__ = [
    "MetricLogger",
    "configure_metrics",
    "emit_event",
    "get_metrics",
    "run_metadata",
    "compute_mfu",
    "device_peak_flops",
    "flops_per_token",
    "format_mfu",
    "StallDetector",
    "NON_STEP_SEGMENTS",
    "StepTimeline",
    "annotate",
    "window_stats",
]
