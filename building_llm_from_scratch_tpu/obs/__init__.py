"""Observability subsystem: structured metrics (JSONL), step timeline +
trace annotations, MFU accounting, per-layer-group training health, XLA
compile telemetry, and the per-host stall detector.

Entry points:
  - ``MetricLogger`` / ``configure_metrics`` / ``get_metrics`` /
    ``emit_event`` — counters, gauges, timings, typed events, JSONL sink
    (obs/metrics.py);
  - ``StepTimeline`` / ``annotate`` / ``window_stats`` — per-step
    wall-clock breakdown + jax.profiler trace annotation (obs/timeline.py);
  - ``flops_per_token`` / ``compute_mfu`` / ``mfu_from_flops`` /
    ``format_mfu`` / ``device_specs`` — analytic FLOPs and MFU against
    chip peak, one device-spec table (obs/mfu.py);
  - ``group_health`` / ``group_names`` / ``describe_health`` — in-graph
    per-layer-group gradient/param/update norms + non-finite localization
    (obs/health.py);
  - ``CompileWatcher`` / ``aot_compile`` / ``enable_persistent_cache`` —
    AOT compile capture, HLO cost/memory analysis, recompile detection,
    persistent-cache wiring (obs/compile.py);
  - ``StallDetector`` — opt-in hung-step flight recorder (obs/stall.py);
  - ``Histogram`` / ``RollingRatio`` / ``render_prometheus`` — serving
    aggregation: fixed-bucket latency histograms, rolling SLO burn-rate
    window, Prometheus text exposition (obs/metrics.py);
  - ``chrome_trace`` / ``export_chrome_trace`` / ``TICK_PHASES`` —
    metrics-JSONL -> Chrome trace-event JSON for Perfetto (obs/trace.py);
  - ``BenchResult`` / ``FingerprintCollector`` / ``TrajectoryStore`` /
    ``compare_structural`` / ``compare_timing`` — the perf observatory:
    schema'd bench results with env + structural HLO fingerprints, the
    results/perf trajectory store, and the two perf-gate comparison
    modes (obs/perf.py; gated by scripts/perf_gate.py).
"""

from building_llm_from_scratch_tpu.obs.compile import (
    CompileWatcher,
    aot_compile,
    enable_persistent_cache,
)
from building_llm_from_scratch_tpu.obs.health import (
    describe_health,
    first_nonfinite_group,
    group_health,
    group_names,
    health_summary_line,
)
from building_llm_from_scratch_tpu.obs.metrics import (
    LATENCY_BUCKETS_S,
    Histogram,
    MetricLogger,
    RollingRatio,
    configure_metrics,
    emit_event,
    get_metrics,
    render_prometheus,
    run_metadata,
)
from building_llm_from_scratch_tpu.obs.trace import (
    TICK_PHASES,
    chrome_trace,
    export_chrome_trace,
)
from building_llm_from_scratch_tpu.obs.mfu import (
    compute_mfu,
    device_peak_flops,
    device_specs,
    flops_per_token,
    format_mfu,
    mfu_from_flops,
)
from building_llm_from_scratch_tpu.obs.perf import (
    BenchResult,
    FingerprintCollector,
    TrajectoryStore,
    bench_env,
    compare_structural,
    compare_timing,
    fingerprint_digest,
)
from building_llm_from_scratch_tpu.obs.stall import StallDetector
from building_llm_from_scratch_tpu.obs.timeline import (
    NON_STEP_SEGMENTS,
    StepTimeline,
    annotate,
    window_stats,
)

__all__ = [
    "MetricLogger",
    "Histogram",
    "RollingRatio",
    "LATENCY_BUCKETS_S",
    "render_prometheus",
    "configure_metrics",
    "emit_event",
    "get_metrics",
    "run_metadata",
    "TICK_PHASES",
    "chrome_trace",
    "export_chrome_trace",
    "compute_mfu",
    "device_peak_flops",
    "device_specs",
    "flops_per_token",
    "format_mfu",
    "mfu_from_flops",
    "CompileWatcher",
    "aot_compile",
    "enable_persistent_cache",
    "describe_health",
    "first_nonfinite_group",
    "group_health",
    "group_names",
    "health_summary_line",
    "BenchResult",
    "FingerprintCollector",
    "TrajectoryStore",
    "bench_env",
    "compare_structural",
    "compare_timing",
    "fingerprint_digest",
    "StallDetector",
    "NON_STEP_SEGMENTS",
    "StepTimeline",
    "annotate",
    "window_stats",
]
