"""Per-host stall detector: dump thread stacks + HBM stats when the step
loop stops making progress.

The failure mode this targets: one host of a pod slice wedges inside a
collective (a peer died, a DMA hung, the data pipeline deadlocked) and the
job sits silent for hours burning reserved capacity. The ONLY safe
diagnostic at that point is strictly host-local — any cross-host collective
would itself hang behind the wedged one — so this watcher:

  - runs a daemon thread per host, armed by ``Trainer`` heartbeats
    (``notify_step`` once per step-loop iteration);
  - fires when no heartbeat lands within ``timeout`` seconds, or within
    ``factor`` x the rolling median step interval once enough history
    exists (whichever is SOONER — a run stepping at 100ms that goes quiet
    for minutes is stalled long before a 600s timeout). The adaptive
    trigger is floored at ``median_floor`` (default 30s): heartbeats come
    once per step-LOOP iteration, and an iteration legitimately stretches
    far past 10x the median step when cadence work runs (first-compile
    eval, checkpoint saves) — without the floor a fast-stepping run
    false-fires on its first eval;
  - on firing, logs every Python thread's stack (``sys._current_frames``)
    and live ``device.memory_stats()`` for the local devices, and emits a
    structured ``stall`` event — all local, no collectives;
  - never kills anything: it is a flight recorder, not a watchdog. It
    re-arms after the next heartbeat, so an intermittent stall produces
    one dump per episode instead of a dump per poll tick.

Opt-in via ``--stall_timeout N`` (seconds; 0 = off). The first interval
gets ``first_grace`` x the threshold: the first step pays jit tracing +
compilation, which on big models legitimately takes minutes.
"""

from __future__ import annotations

import sys
import threading
import time
import traceback
from typing import List, Optional

from building_llm_from_scratch_tpu.utils.logging import setup_logger

logger = setup_logger(__name__)


def format_all_stacks() -> str:
    """Every live Python thread's stack as one readable block."""
    names = {t.ident: t.name for t in threading.enumerate()}
    parts: List[str] = []
    for ident, frame in sys._current_frames().items():
        name = names.get(ident, "unknown")
        parts.append(f"--- Thread {name} (ident {ident}) ---")
        parts.append("".join(traceback.format_stack(frame)).rstrip())
    return "\n".join(parts)


def _device_memory_report() -> dict:
    """Live HBM stats per local device (best-effort, strictly local)."""
    try:
        import jax

        from building_llm_from_scratch_tpu.utils.memory import (
            device_memory_stats,
        )

        return {str(d): device_memory_stats(d) for d in jax.local_devices()}
    except Exception as e:
        return {"error": repr(e)}


class StallDetector:
    """See module docstring. Thread-safe: heartbeats come from the trainer
    thread, checks run on the watcher thread."""

    def __init__(self, timeout: float, factor: float = 10.0,
                 poll_interval: float = 0.25, first_grace: float = 5.0,
                 median_floor: float = 30.0, history: int = 64,
                 on_stall=None):
        if timeout <= 0:
            raise ValueError(f"timeout must be > 0 seconds, got {timeout}")
        self.timeout = float(timeout)
        self.factor = float(factor)
        self.median_floor = float(median_floor)
        self.poll_interval = float(poll_interval)
        self.first_grace = float(first_grace)
        self.on_stall = on_stall          # test hook: fn(elapsed, threshold)
        self.stall_count = 0
        self._history_max = history
        self._intervals: List[float] = []
        self._last: Optional[float] = None
        self._fired_for_current_gap = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    # -- heartbeat (trainer thread) --------------------------------------

    def notify_step(self) -> None:
        now = time.monotonic()
        with self._lock:
            if self._last is not None:
                self._intervals.append(now - self._last)
                if len(self._intervals) > self._history_max:
                    del self._intervals[0]
            self._last = now
            self._fired_for_current_gap = False

    # -- watcher ---------------------------------------------------------

    def threshold(self) -> float:
        """Current firing threshold in seconds."""
        with self._lock:
            intervals = list(self._intervals)
            armed = self._last is not None
        thr = self.timeout
        if len(intervals) >= 8:
            srt = sorted(intervals)
            median = srt[len(srt) // 2]
            # adaptive trigger, floored (see module docstring: cadence
            # work inside one loop iteration legitimately dwarfs the
            # median step interval)
            thr = min(thr, max(self.factor * median, self.median_floor))
        if armed and not intervals:
            thr *= self.first_grace     # first step pays compilation
        return thr

    def _check(self) -> None:
        with self._lock:
            last = self._last
            fired = self._fired_for_current_gap
        if last is None or fired:
            return
        elapsed = time.monotonic() - last
        thr = self.threshold()
        if elapsed < thr:
            return
        with self._lock:
            if self._last != last:
                # a heartbeat landed between the read above and here: the
                # gap we measured just ended, and marking the NEW gap as
                # fired would permanently silence the detector for the
                # very intermittent-stall pattern it exists to catch
                return
            self._fired_for_current_gap = True
        self.stall_count += 1
        self._dump(elapsed, thr)
        if self.on_stall is not None:
            try:
                self.on_stall(elapsed, thr)
            except Exception:
                logger.exception("stall callback failed")

    def _dump(self, elapsed: float, thr: float) -> None:
        mem = _device_memory_report()
        logger.error(
            "STALL: no train step completed in %.1fs (threshold %.1fs). "
            "Dumping all Python thread stacks (host-local; no collectives):"
            "\n%s\nDevice memory stats: %s",
            elapsed, thr, format_all_stacks(), mem)
        from building_llm_from_scratch_tpu.obs.metrics import emit_event

        emit_event("stall", elapsed_s=round(elapsed, 3),
                   threshold_s=round(thr, 3), memory=mem)

    def _run(self) -> None:
        while not self._stop.wait(self.poll_interval):
            try:
                self._check()
            except Exception:
                # the flight recorder must never crash the run
                logger.exception("stall detector check failed")

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "StallDetector":
        if self._thread is not None:
            return self
        with self._lock:
            if self._last is None:
                # arm NOW: a run that wedges in its very first step (first
                # batch's data pipeline, first collective, jit compile) is
                # the headline failure mode and must still dump — the
                # first monitored gap simply gets first_grace x the
                # threshold (threshold() applies it while no step interval
                # exists yet) to cover legitimate compilation time
                self._last = time.monotonic()
        self._stop.clear()
        self._thread = threading.Thread(target=self._run,
                                        name="stall-detector", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5 * self.poll_interval + 1)
            self._thread = None

    def __enter__(self) -> "StallDetector":
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False
