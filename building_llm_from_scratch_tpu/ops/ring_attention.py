"""Ring attention: causal attention with the sequence sharded over a mesh
axis (sequence/context parallelism).

Long-context training the reference cannot express: its attention always
materializes (or flash-scans) the full sequence on ONE device, so context
length is capped by a single GPU's memory (reference
Models/Llama/Llama3.py:108-155 — full-sequence GQA per device). Here the
sequence axis is sharded over ``SEQ_AXIS``; each device holds a T/S block of
Q/K/V and the KV blocks rotate around the ring (``lax.ppermute``), one hop
per step, so every Q block sees every KV block after S-1 rotations while
per-device attention memory stays O((T/S)^2). This is the blockwise/ring
formulation of Liu et al. 2023 ("Ring Attention with Blockwise
Transformers") expressed in shard_map + online softmax.

Causality skips work at the schedule level too: a KV block strictly in the
future of the local Q block contributes nothing; its scores are fully
masked and the online-softmax update degenerates to a no-op (exp(-inf)=0),
letting XLA overlap the ppermute with the masked-block math.

The ring hop rides the ICI neighbor links — ``ppermute`` with the
(i -> i+1) permutation is exactly the collective the TPU torus is built
for; bandwidth per step is one KV block, independent of S.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from building_llm_from_scratch_tpu.parallel.collectives import shard_map
from building_llm_from_scratch_tpu.parallel.mesh import DATA_AXIS, SEQ_AXIS

_NEG_INF = -1e30


def _ring_attention_local(q, k, v, *, axis_name: str, axis_size: int,
                          scale: float, dropout_rate: float = 0.0,
                          dropout_rng: Optional[jax.Array] = None,
                          shard_fold_axes: tuple = ()):
    """Per-device ring attention body (runs INSIDE shard_map).

    q: (B, Tl, Hq, D) local query block; k/v: (B, Tl, Hkv, D) local KV
    block. Returns the local output block (B, Tl, Hq, D). Numerics follow
    ops/attention.py's xla oracle: fp32 scores + online softmax, output cast
    back to v.dtype.

    Attention dropout (round-3 VERDICT weakness #6 lifted): each (q-shard,
    kv-block) pair is visited exactly once per step, so folding
    (shard indices, rotation source) into the PRNG key yields one iid
    Bernoulli mask per global weight entry — applied to the exp() terms but
    NOT the denominator (dropout multiplies the normalized weights), with
    the 1/(1-p) rescale at the end. ``shard_fold_axes`` lists extra mapped
    mesh axes (data/model) whose indices must decorrelate the masks.
    """
    B, Tl, Hq, D = q.shape
    Hkv = k.shape[2]
    G = Hq // Hkv
    my = jax.lax.axis_index(axis_name)

    dropout_on = dropout_rate > 0.0 and dropout_rng is not None
    if dropout_on:
        key = jax.random.fold_in(dropout_rng, my)
        for ax in shard_fold_axes:
            key = jax.random.fold_in(key, jax.lax.axis_index(ax))

    qg = q.reshape(B, Tl, Hkv, G, D)
    iq = jnp.arange(Tl)
    ik = jnp.arange(Tl)
    q_pos = my * Tl + iq                                   # global positions

    # online-softmax accumulators, fp32
    m = jnp.full((B, Hkv, G, Tl), _NEG_INF, jnp.float32)   # running max
    l = jnp.zeros((B, Hkv, G, Tl), jnp.float32)            # running denom
    o = jnp.zeros((B, Hkv, G, Tl, D), jnp.float32)         # running numer

    # Python loop: axis_size is static and small; unrolling lets XLA overlap
    # each ppermute with the previous block's math
    for r in range(axis_size):
        # after r forward rotations, this device holds the KV block that
        # started on device (my - r) mod S
        src = (my - r) % axis_size
        kv_pos = src * Tl + ik
        s = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k,
                       preferred_element_type=jnp.float32) * scale
        mask = (q_pos[:, None] >= kv_pos[None, :])[None, None, None]
        s = jnp.where(mask, s, _NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        corr = jnp.exp(m - m_new)
        p_blk = jnp.exp(s - m_new[..., None])
        # a fully-masked (future) block: p_blk == 0 everywhere, so l/o pass
        # through unchanged — the causal skip falls out of the math
        l = l * corr + p_blk.sum(axis=-1)
        if dropout_on:
            keep = jax.random.bernoulli(jax.random.fold_in(key, r),
                                        1.0 - dropout_rate, p_blk.shape)
            p_blk = jnp.where(keep, p_blk, 0.0)
        o = o * corr[..., None] + jnp.einsum(
            "bhgqk,bkhd->bhgqd", p_blk, v.astype(jnp.float32))
        m = m_new
        if r + 1 < axis_size:
            perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
            k = jax.lax.ppermute(k, axis_name, perm)
            v = jax.lax.ppermute(v, axis_name, perm)

    out = o / jnp.maximum(l, 1e-37)[..., None]             # (B,Hkv,G,Tl,D)
    if dropout_on:
        out = out * (1.0 / (1.0 - dropout_rate))
    return out.transpose(0, 3, 1, 2, 4).reshape(B, Tl, Hq, D).astype(v.dtype)


def ring_causal_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                          mesh: Mesh,
                          seq_axis: str = SEQ_AXIS,
                          batch_axis: Optional[str] = DATA_AXIS,
                          dropout_rate: float = 0.0,
                          dropout_rng: Optional[jax.Array] = None
                          ) -> jnp.ndarray:
    """Causal GQA attention with the T axis sharded over ``mesh[seq_axis]``.

    q: (B, T, Hq, D), k/v: (B, T, Hkv, D) — GLOBAL shapes; inside the
    shard_map each device sees its (B/dp, T/S, H, D) block. Call from code
    already running under jit with GSPMD shardings (transformer.forward);
    the shard_map boundary forces the (batch, seq) layout and hands the ring
    schedule ownership of the communication.

    ``dropout_rate``/``dropout_rng`` enable per-shard attention dropout
    (see _ring_attention_local) — masks decorrelate across seq, data and
    model shards via axis-index folding.
    """
    S = mesh.shape[seq_axis]
    if S <= 1:
        raise ValueError("ring_causal_attention needs a seq axis > 1; "
                         "use ops.attention.causal_attention instead")
    if q.shape[1] % S != 0:
        raise ValueError(
            f"sequence length {q.shape[1]} not divisible by seq axis {S}")
    D = q.shape[-1]
    scale = 1.0 / float(D) ** 0.5

    # compose with tensor parallelism: when the model axis is live and the
    # head counts divide it, keep heads sharded through the ring (each model
    # shard rings only its own heads) instead of all-gathering and
    # recomputing every head tp times
    from building_llm_from_scratch_tpu.parallel.mesh import MODEL_AXIS

    tp = mesh.shape.get(MODEL_AXIS, 1)
    Hq, Hkv = q.shape[2], k.shape[2]
    head_axis = (MODEL_AXIS
                 if tp > 1 and Hq % tp == 0 and Hkv % tp == 0 else None)
    spec = P(batch_axis, seq_axis, head_axis, None)

    fold_axes = tuple(ax for ax in (batch_axis, head_axis) if ax)
    body = functools.partial(_ring_attention_local, axis_name=seq_axis,
                             axis_size=S, scale=scale,
                             dropout_rate=dropout_rate,
                             shard_fold_axes=fold_axes)
    if dropout_rate > 0.0 and dropout_rng is not None:
        return shard_map(
            lambda q, k, v, r: body(q, k, v, dropout_rng=r),
            mesh=mesh, in_specs=(spec, spec, spec, P()),
            out_specs=spec, check_vma=False)(q, k, v, dropout_rng)
    return shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, check_vma=False)(q, k, v)
