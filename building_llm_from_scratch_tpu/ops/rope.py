"""Rotary position embeddings.

Capability parity with the reference:
  - plain RoPE, theta=10k (LLaMA-2)      — Models/Llama/Llama2.py:34-55
  - RoPE with LLaMA-3.1 frequency
    smoothing (wavelength bands)         — Models/Llama/Llama3.py:74-104
  - rotate-half application on (b,h,t,d) — Models/Llama/common_components.py:6-35

Design difference from the reference: cos/sin tables are computed once per
model setup as fp32 host constants and closed over by the jitted step (the
reference caches them per-process in a ``SharedBuffers`` dict keyed by config,
Models/Llama/Llama3.py:55-70 — under jit, constant-folding makes that cache
unnecessary). No (ctx, ctx) mask buffer is ever built.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp

from building_llm_from_scratch_tpu.configs import RopeScaling


def precompute_rope_params(
    head_dim: int,
    theta_base: float = 10_000.0,
    context_length: int = 4096,
    rope_scaling: Optional[RopeScaling] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Return (cos, sin), each of shape (context_length, head_dim), fp32."""
    assert head_dim % 2 == 0, "head_dim must be even for RoPE"
    inv_freq = 1.0 / (
        theta_base ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    )

    if rope_scaling is not None:
        # LLaMA-3.1 frequency smoothing: keep high-frequency components,
        # downscale low-frequency ones, and blend linearly in between.
        orig_ctx = rope_scaling.original_context_length
        low_freq_wavelen = orig_ctx / rope_scaling.low_freq_factor
        high_freq_wavelen = orig_ctx / rope_scaling.high_freq_factor
        wavelen = 2.0 * jnp.pi / inv_freq

        scaled = inv_freq / rope_scaling.factor
        smooth = (orig_ctx / wavelen - rope_scaling.low_freq_factor) / (
            rope_scaling.high_freq_factor - rope_scaling.low_freq_factor
        )
        smoothed = (1.0 - smooth) * scaled + smooth * inv_freq

        inv_freq = jnp.where(wavelen > low_freq_wavelen, scaled, inv_freq)
        is_medium = (wavelen <= low_freq_wavelen) & (wavelen >= high_freq_wavelen)
        inv_freq = jnp.where(is_medium, smoothed, inv_freq)

    positions = jnp.arange(context_length, dtype=jnp.float32)
    angles = positions[:, None] * inv_freq[None, :]        # (T, head_dim/2)
    angles = jnp.concatenate([angles, angles], axis=-1)    # (T, head_dim)
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray,
               positions: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Rotate-half RoPE application.

    x: (batch, seq, n_heads, head_dim) — note head axis AFTER seq (our layout;
    the reference uses (b, h, t, d)).
    positions: optional (seq,) or (batch, seq) absolute positions for decode;
    defaults to arange(seq).
    """
    b, t, h, d = x.shape
    if positions is None:
        cos_t = cos[:t]                                    # (T, d)
        sin_t = sin[:t]
        cos_t = cos_t[None, :, None, :]                    # (1, T, 1, d)
        sin_t = sin_t[None, :, None, :]
    else:
        cos_t = jnp.take(cos, positions, axis=0)           # (..., d)
        sin_t = jnp.take(sin, positions, axis=0)
        if positions.ndim == 1:
            cos_t = cos_t[None, :, None, :]
            sin_t = sin_t[None, :, None, :]
        else:  # (batch, seq)
            cos_t = cos_t[:, :, None, :]
            sin_t = sin_t[:, :, None, :]

    x1 = x[..., : d // 2]
    x2 = x[..., d // 2:]
    rotated = jnp.concatenate([-x2, x1], axis=-1)
    out = x.astype(jnp.float32) * cos_t + rotated.astype(jnp.float32) * sin_t
    return out.astype(x.dtype)
