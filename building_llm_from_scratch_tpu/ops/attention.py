"""Causal (grouped-query) attention.

One implementation replaces the reference's three attention classes:
  - MultiHeadAttention        (Models/GPT2/GPT2.py:6-49)
  - MHA w/ RoPE               (Models/Llama/Llama2.py:61-114)
  - GroupedQueryAttention     (Models/Llama/Llama3.py:108-155)

TPU-first differences:
  - no (ctx, ctx) mask *buffer*: the causal mask is generated from position
    iota inside the kernel, so context length is not memory-bound by a
    persistent O(T^2) tensor;
  - KV heads are expanded by broadcasting inside the einsum (the reference
    materializes ``repeat_interleave`` copies, Llama3.py:133-137);
  - softmax runs in fp32 and the matmuls carry
    ``preferred_element_type=float32`` so bf16 training is stable on the MXU.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

# Implementations currently wired up; args.py validates --attn_impl against
# this so unimplemented choices fail at flag time, not mid-run.
AVAILABLE_IMPLS = ("auto", "xla")


def causal_attention(
    q: jnp.ndarray,               # (B, Tq, Hq, D)
    k: jnp.ndarray,               # (B, Tkv, Hkv, D)
    v: jnp.ndarray,               # (B, Tkv, Hkv, D)
    *,
    q_positions: Optional[jnp.ndarray] = None,   # (Tq,) or (B, Tq) absolute pos
    kv_length: Optional[jnp.ndarray] = None,     # scalar or (B,): valid kv prefix
    dropout_rate: float = 0.0,
    dropout_rng: Optional[jax.Array] = None,
    deterministic: bool = True,
    impl: str = "auto",
) -> jnp.ndarray:
    """Scaled dot-product attention with causal masking and GQA.

    For training, call with q=k=v lengths equal and no kv_length. For
    cached decode, pass the full cache as k/v, absolute ``q_positions`` and
    ``kv_length`` = number of valid cache entries.
    """
    B, Tq, Hq, D = q.shape
    _, Tkv, Hkv, _ = k.shape
    assert Hq % Hkv == 0, "query heads must be a multiple of kv heads"
    G = Hq // Hkv

    if impl not in AVAILABLE_IMPLS:
        raise NotImplementedError(
            f"attention impl '{impl}' is not available yet; "
            f"options: {AVAILABLE_IMPLS}")

    if q_positions is None:
        # training path: q and kv are the same sequence
        q_pos = jnp.arange(Tq)
    else:
        q_pos = q_positions
    kv_pos = jnp.arange(Tkv)

    if q_pos.ndim == 1:
        mask = q_pos[:, None] >= kv_pos[None, :]            # (Tq, Tkv)
        mask = mask[None, None, None, :, :]                 # (1,1,1,Tq,Tkv)
    else:
        mask = q_pos[:, :, None] >= kv_pos[None, None, :]   # (B, Tq, Tkv)
        mask = mask[:, None, None, :, :]                    # (B,1,1,Tq,Tkv)
    if kv_length is not None:
        valid = kv_pos[None, :] < jnp.reshape(kv_length, (-1, 1))  # (B|1, Tkv)
        mask = mask & valid[:, None, None, None, :]

    qg = q.reshape(B, Tq, Hkv, G, D)
    scale = 1.0 / jnp.sqrt(jnp.asarray(D, dtype=jnp.float32))
    # (B, Hkv, G, Tq, Tkv) in fp32 for a stable softmax
    scores = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k,
                        preferred_element_type=jnp.float32)
    scores = scores * scale
    scores = jnp.where(mask, scores, jnp.asarray(-1e30, dtype=scores.dtype))
    weights = jax.nn.softmax(scores, axis=-1)

    if dropout_rate > 0.0 and not deterministic:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate,
                                    weights.shape)
        weights = jnp.where(keep, weights / (1.0 - dropout_rate), 0.0)

    weights = weights.astype(v.dtype)
    out = jnp.einsum("bhgqk,bkhd->bqhgd", weights, v)
    return out.reshape(B, Tq, Hq, D)
