"""Causal (grouped-query) attention.

One implementation surface replaces the reference's three attention classes:
  - MultiHeadAttention        (Models/GPT2/GPT2.py:6-49)
  - MHA w/ RoPE               (Models/Llama/Llama2.py:61-114)
  - GroupedQueryAttention     (Models/Llama/Llama3.py:108-155)

Interchangeable implementations (``ModelConfig.attn_impl``):

  xla     — einsum scores + masked softmax. Materializes the full
            (B, Hkv, G, Tq, Tkv) fp32 score tensor; exact, used for short
            sequences and as the oracle in parity tests. Also the only path
            for cached decode (tiny Tq — blocking buys nothing there).
  flash   — chunked online attention: ``lax.scan`` over query blocks with a
            remat'd block body, so live score memory is O(BQ · Tkv) in both
            forward and backward instead of O(Tq · Tkv). Pure XLA: runs on
            CPU/TPU, differentiable, supports attention dropout (per-block
            folded PRNG).
  pallas  — the stock JAX pallas TPU kernel
            (jax.experimental.pallas.ops.tpu.flash_attention) with 512x512
            blocks. TPU only, no dropout. Kept as a cross-check; auto now
            prefers the in-house ``fused`` kernel.
  fused   — the in-house pallas kernel (ops/fused_attention.py): tiled
            online-softmax with IN-KERNEL PRNG attention dropout, custom
            fwd + dq + dkv kernels, causal block skipping, GQA via head
            index mapping. The only fast path that carries the reference's
            attention-dropout semantics (GPT2.py:30-41); measured 56.3ms ->
            GPT2-124M headline step vs 64.5ms on flash (r4).
  auto    — on TPU: fused for every block-divisible self-attention shape
            (dropout or not); else flash for block-divisible sequences;
            else xla.

Measured fwd+bwd ms on v5e-1, bf16 (2026-07, this module's impls; pallas =
512x512 blocks; best per row in [brackets]):

  shape                          xla     flash   pallas
  GPT2   b4  t1024 H12  D64      [5.2]   [5.1]    7.7
  GPT2   b4  t2048 H12  D64       9.3     9.9    [6.0]
  L3.2   b8  t1024 H32/8 D64     11.8    [8.9]    7.6*
  L2-7B  b4  t1024 H32  D128      7.4     8.5    [5.8]*
  L3.2   b4  t2048 H32/8 D64     18.7    16.2   [10.4]
  8B-ish b2  t4096 H32/8 D128    34.0    29.4   [11.8]

  (*r3 table, kept for the stock-kernel cross-check. Since r4 auto routes
  every block-divisible TPU training shape to the in-house ``fused``
  kernel instead — measured in-model: GPT2-124M bf16 step 56.3ms fused vs
  64.5ms flash at bs4, with identical dropout semantics.)

TPU-first details shared by all paths:
  - no (ctx, ctx) mask *buffer*: the causal mask comes from position iota
    (the reference registers a persistent O(T^2) buffer per layer);
  - KV heads are expanded by broadcasting inside the einsum for xla/flash
    (the reference materializes ``repeat_interleave`` copies, Llama3.py:133-137);
  - softmax runs in fp32 and matmuls carry
    ``preferred_element_type=float32`` so bf16 training is stable on the MXU.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

# Implementations currently wired up; args.py validates --attn_impl against
# this so unimplemented choices fail at flag time, not mid-run.
AVAILABLE_IMPLS = ("auto", "xla", "flash", "pallas", "fused")

_NEG_INF = -1e30


def _on_tpu() -> bool:
    return jax.default_backend() == "tpu"


def _resolve_impl(impl: str, Tq: int, Tkv: int, head_dim: int,
                  q_positions, kv_length, dropout_active: bool,
                  block_q: int) -> str:
    """Pick the concrete implementation for ``impl='auto'`` and validate
    eligibility of explicit choices (falling back where semantics require)."""
    if impl not in AVAILABLE_IMPLS:
        raise NotImplementedError(
            f"attention impl '{impl}' is not available yet; "
            f"options: {AVAILABLE_IMPLS}")
    if kv_length is not None:
        # cached decode: Tq is 1 (or a short prefill) — the score tensor is
        # already small and the fused kernels don't model cache validity
        return "xla"
    if q_positions is not None:
        # flash/pallas assume q starts at kv position 0; silently computing
        # the wrong causal mask for a chunked prefill would be a correctness
        # hazard (round-2 ADVICE low), so only xla honors q_positions
        return "xla"
    if impl != "auto":
        return impl
    # auto: on TPU the in-house fused kernel (ops/fused_attention.py) owns
    # every block-divisible training shape — with OR without dropout (its
    # in-kernel PRNG keeps T^2 masks out of HBM); flash/xla cover CPU and
    # odd shapes
    if _on_tpu():
        from building_llm_from_scratch_tpu.ops.fused_attention import (
            supports_shape,
        )

        if supports_shape(Tq, Tkv, head_dim):
            return "fused"
    if Tq == Tkv and Tq >= 2 * block_q and Tq % block_q == 0:
        return "flash"
    return "xla"


# ---------------------------------------------------------------------------
# xla path (exact oracle; also the decode path)
# ---------------------------------------------------------------------------

def _xla_attention(q, k, v, *, q_positions, kv_length, dropout_rate,
                   dropout_rng, deterministic):
    B, Tq, Hq, D = q.shape
    _, Tkv, Hkv, _ = k.shape
    G = Hq // Hkv

    if q_positions is None:
        q_pos = jnp.arange(Tq)
    else:
        q_pos = q_positions
    kv_pos = jnp.arange(Tkv)

    if q_pos.ndim == 1:
        mask = q_pos[:, None] >= kv_pos[None, :]            # (Tq, Tkv)
        mask = mask[None, None, None, :, :]                 # (1,1,1,Tq,Tkv)
    else:
        mask = q_pos[:, :, None] >= kv_pos[None, None, :]   # (B, Tq, Tkv)
        mask = mask[:, None, None, :, :]                    # (B,1,1,Tq,Tkv)
    if kv_length is not None:
        valid = kv_pos[None, :] < jnp.reshape(kv_length, (-1, 1))  # (B|1, Tkv)
        mask = mask & valid[:, None, None, None, :]

    qg = q.reshape(B, Tq, Hkv, G, D)
    scale = 1.0 / jnp.sqrt(jnp.asarray(D, dtype=jnp.float32))
    # (B, Hkv, G, Tq, Tkv) in fp32 for a stable softmax
    scores = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k,
                        preferred_element_type=jnp.float32)
    scores = scores * scale
    scores = jnp.where(mask, scores, jnp.asarray(_NEG_INF, scores.dtype))
    weights = jax.nn.softmax(scores, axis=-1)

    if dropout_rate > 0.0 and not deterministic:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate,
                                    weights.shape)
        weights = jnp.where(keep, weights / (1.0 - dropout_rate), 0.0)

    weights = weights.astype(v.dtype)
    out = jnp.einsum("bhgqk,bkhd->bqhgd", weights, v)
    return out.reshape(B, Tq, Hq, D)


# ---------------------------------------------------------------------------
# flash path: chunked query blocks, remat'd body
# ---------------------------------------------------------------------------

def _flash_attention_xla(q, k, v, *, block_q, dropout_rate, dropout_rng,
                         deterministic):
    """Blockwise causal attention: scan over query blocks.

    Live memory per step is one (B, Hkv, G, BQ, Tkv) fp32 score block; the
    remat'd body makes the backward recompute it per block instead of
    saving all Tq/BQ blocks.
    """
    B, Tq, Hq, D = q.shape
    _, Tkv, Hkv, _ = k.shape
    G = Hq // Hkv
    assert Tq % block_q == 0, "flash impl requires Tq divisible by block_q"
    n_blocks = Tq // block_q
    scale = 1.0 / jnp.sqrt(jnp.asarray(D, dtype=jnp.float32))
    kv_pos = jnp.arange(Tkv)
    dropout_active = dropout_rate > 0.0 and not deterministic
    if not dropout_active:
        dropout_rng = jax.random.PRNGKey(0)          # unused, fixed for scan

    # (n_blocks, B, Hkv, G, BQ, D) query blocks
    qb = q.reshape(B, n_blocks, block_q, Hkv, G, D).transpose(1, 0, 3, 4, 2, 5)

    def body(_, xs):
        q_block, block_idx = xs
        q_pos = block_idx * block_q + jnp.arange(block_q)
        s = jnp.einsum("bhgqd,bkhd->bhgqk", q_block, k,
                       preferred_element_type=jnp.float32) * scale
        mask = (q_pos[:, None] >= kv_pos[None, :])[None, None, None]
        s = jnp.where(mask, s, jnp.asarray(_NEG_INF, s.dtype))
        w = jax.nn.softmax(s, axis=-1)
        if dropout_active:
            keep = jax.random.bernoulli(
                jax.random.fold_in(dropout_rng, block_idx),
                1.0 - dropout_rate, w.shape)
            w = jnp.where(keep, w / (1.0 - dropout_rate), 0.0)
        o = jnp.einsum("bhgqk,bkhd->bhgqd", w.astype(v.dtype), v)
        return None, o

    _, ob = jax.lax.scan(jax.checkpoint(body, prevent_cse=False), None,
                         (qb, jnp.arange(n_blocks)))
    # (n_blocks, B, Hkv, G, BQ, D) -> (B, Tq, Hq, D)
    out = ob.transpose(1, 0, 4, 2, 3, 5).reshape(B, Tq, Hq, D)
    return out


# ---------------------------------------------------------------------------
# pallas path: fused TPU kernel
# ---------------------------------------------------------------------------

def _pallas_flash_attention(q, k, v, block: int = 512):
    """Fused flash attention on the MXU via the pallas TPU kernel
    (jax.experimental.pallas.ops.tpu.flash_attention — public JAX op with
    custom forward AND backward kernels, causal-block skipping included).

    512x512 blocks measured 1.3-2.2x faster than the kernel's defaults on
    v5e (module docstring table) — big K blocks amortize the causal-block
    skip and keep the MXU fed.
    """
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        BlockSizes,
        flash_attention,
    )

    B, Tq, Hq, D = q.shape
    _, Tkv, Hkv, _ = k.shape
    G = Hq // Hkv
    # kernel layout (B, H, T, D); broadcast KV heads up to Hq for GQA
    qh = q.transpose(0, 2, 1, 3)
    kh = jnp.repeat(k.transpose(0, 2, 1, 3), G, axis=1)
    vh = jnp.repeat(v.transpose(0, 2, 1, 3), G, axis=1)
    scale = 1.0 / float(D) ** 0.5
    bq, bk = min(block, Tq), min(block, Tkv)
    if Tq % bq == 0 and Tkv % bk == 0:
        bs = BlockSizes(
            block_q=bq, block_k_major=bk, block_k=bk, block_b=1,
            block_q_major_dkv=bq, block_k_major_dkv=bk, block_k_dkv=bk,
            block_q_dkv=bq, block_k_major_dq=bk, block_k_dq=bk,
            block_q_dq=bq)
    else:
        bs = None                      # odd length: kernel's own defaults
    out = flash_attention(qh, kh, vh, causal=True, sm_scale=scale,
                          block_sizes=bs)
    return out.transpose(0, 2, 1, 3)


# ---------------------------------------------------------------------------
# decode path: cache-layout-native attention
# ---------------------------------------------------------------------------

def decode_attention(
    q: jnp.ndarray,               # (B, Tq, Hq, D) — model layout (tiny Tq)
    k_cache: jnp.ndarray,         # (B, Hkv, Tmax, D) — cache-native layout
    v_cache: jnp.ndarray,         # (B, Hkv, Tmax, D)
    *,
    q_positions: jnp.ndarray,     # (Tq,) or (B, Tq) absolute positions
    kv_length: jnp.ndarray,       # scalar or (B,): valid cache prefix
    k_scale: Optional[jnp.ndarray] = None,   # (B, Hkv, Tmax, 1) int8 cache
    v_scale: Optional[jnp.ndarray] = None,   # (B, Hkv, Tmax, 1) scales
) -> jnp.ndarray:
    """Attention for KV-cache decode, consuming the cache in its OWN
    (B, H, T, D) layout.

    The general ``causal_attention`` takes (B, T, H, D) k/v; feeding it the
    cache made XLA materialize a transposed copy of the ENTIRE cache for
    every layer of every decoded token (r5 profile: ~24 full-buffer
    copies/step, ~40% of decode step time on GPT2-124M bs8). Here the
    score/value einsums batch over (B, H) directly, so the cache streams
    without re-layout. Exact same math/masking as the xla path with
    ``q_positions``/``kv_length``; no dropout (decode is eval-only).

    Per-row ``q_positions`` (B, Tq) + ``kv_length`` (B,) serve the serving
    engine's slot batch, where every row is a different request at a
    different sequence length (serving/engine.py).

    ``k_scale``/``v_scale`` dequantize an int8 cache (serving/kvcache.py
    int8 policy) WITHOUT materializing a dequantized copy: the per-
    position scales are constant over head_dim, so they factor out of
    the score dot (``q . (k8*s) = (q . k8) * s``) and fold into the
    probability row before the value dot (``sum_k p_k*(v8_k*s_k) =
    sum_k (p_k*s_k)*v8_k``) — exactly equal to dequantize-then-attend.
    """
    B, Tq, Hq, D = q.shape
    _, Hkv, Tkv, _ = k_cache.shape
    G = Hq // Hkv
    scale = 1.0 / jnp.sqrt(jnp.asarray(D, dtype=jnp.float32))
    if k_scale is not None:
        k_cache = k_cache.astype(jnp.float32)
        v_cache = v_cache.astype(jnp.float32)
    # (B, Hkv, G, Tq, D) — tiny transpose (Tq is 1 for decode steps)
    qg = q.reshape(B, Tq, Hkv, G, D).transpose(0, 2, 3, 1, 4)
    scores = jnp.einsum("bhgqd,bhkd->bhgqk", qg, k_cache,
                        preferred_element_type=jnp.float32) * scale
    if k_scale is not None:
        # (B, Hkv, Tkv, 1) -> (B, Hkv, 1, 1, Tkv), broadcast over (G,
        # Tq): one multiply per score, the whole K-side dequant cost
        scores = scores * k_scale[:, :, :, 0][:, :, None, None, :]
    kv_pos = jnp.arange(Tkv)
    if q_positions.ndim == 2:
        # per-row positions/lengths: mask (B, Tq, Tkv) -> (B, 1, 1, Tq, Tkv)
        mask = (q_positions[:, :, None] >= kv_pos[None, None, :]) \
            & (kv_pos[None, None, :] < jnp.reshape(kv_length, (-1, 1, 1)))
        mask = mask[:, None, None]
    else:
        mask = (q_positions[:, None] >= kv_pos[None, :]) \
            & (kv_pos[None, :] < kv_length)
        mask = mask[None, None, None]
    scores = jnp.where(mask, scores,
                       jnp.asarray(_NEG_INF, scores.dtype))
    weights = jax.nn.softmax(scores, axis=-1).astype(v_cache.dtype)
    if v_scale is not None:
        # fold the V-side scales into the probability row (exact):
        # sum_k p_k * (v8_k * s_k) == sum_k (p_k * s_k) * v8_k
        weights = weights * v_scale[:, :, :, 0][:, :, None, None, :]
    out = jnp.einsum("bhgqk,bhkd->bhgqd", weights, v_cache)
    # (B, Hkv, G, Tq, D) -> (B, Tq, Hq, D)
    return out.transpose(0, 3, 1, 2, 4).reshape(B, Tq, Hq, D)


# ---------------------------------------------------------------------------
# public entry
# ---------------------------------------------------------------------------

def causal_attention(
    q: jnp.ndarray,               # (B, Tq, Hq, D)
    k: jnp.ndarray,               # (B, Tkv, Hkv, D)
    v: jnp.ndarray,               # (B, Tkv, Hkv, D)
    *,
    q_positions: Optional[jnp.ndarray] = None,   # (Tq,) or (B, Tq) absolute pos
    kv_length: Optional[jnp.ndarray] = None,     # scalar or (B,): valid kv prefix
    dropout_rate: float = 0.0,
    dropout_rng: Optional[jax.Array] = None,
    deterministic: bool = True,
    impl: str = "auto",
    block_q: int = 256,
) -> jnp.ndarray:
    """Scaled dot-product attention with causal masking and GQA.

    For training, call with q=k=v lengths equal and no kv_length. For
    cached decode, pass the full cache as k/v, absolute ``q_positions`` and
    ``kv_length`` = number of valid cache entries.
    """
    B, Tq, Hq, D = q.shape
    _, Tkv, Hkv, _ = k.shape
    assert Hq % Hkv == 0, "query heads must be a multiple of kv heads"

    dropout_active = dropout_rate > 0.0 and not deterministic
    chosen = _resolve_impl(impl, Tq, Tkv, D, q_positions, kv_length,
                           dropout_active, block_q)

    if chosen == "fused":
        from building_llm_from_scratch_tpu.ops.fused_attention import (
            fused_causal_attention,
        )

        return fused_causal_attention(
            q, k, v,
            dropout_rate=dropout_rate if dropout_active else 0.0,
            dropout_rng=dropout_rng)
    if chosen == "pallas":
        if dropout_active:
            raise ValueError(
                "attn_impl='pallas' does not support attention dropout; "
                "use 'flash' or set drop_rate=0")
        return _pallas_flash_attention(q, k, v)
    if chosen == "flash":
        bq = min(block_q, Tq)
        while Tq % bq:                   # largest divisor <= block_q (static)
            bq -= 1
        return _flash_attention_xla(q, k, v, block_q=bq,
                                    dropout_rate=dropout_rate,
                                    dropout_rng=dropout_rng,
                                    deterministic=deterministic)
    return _xla_attention(q, k, v, q_positions=q_positions,
                          kv_length=kv_length, dropout_rate=dropout_rate,
                          dropout_rng=dropout_rng,
                          deterministic=deterministic)
