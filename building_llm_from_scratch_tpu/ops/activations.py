"""Activations.

GELU (tanh approximation, matching torch.nn.GELU's default erf variant closely
enough for training; we use the exact erf form since XLA fuses it fine) and
SiLU (reference hand-writes it, common_components.py:78-88).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def gelu(x: jnp.ndarray) -> jnp.ndarray:
    # Exact erf GELU — same as torch.nn.GELU() used by the reference GPT-2 MLP
    # (Models/GPT2/GPT2.py:52-65).
    return jax.nn.gelu(x, approximate=False)


def silu(x: jnp.ndarray) -> jnp.ndarray:
    # x * sigmoid(x) (reference common_components.py:78-88).
    return x * jax.nn.sigmoid(x)
