"""Fused TPU flash attention WITH in-kernel attention-weight dropout.

The reference applies dropout to the softmaxed attention weights
(/root/reference/Models/GPT2/GPT2.py:30-41). On TPU that semantics made the
fast path unusable: the stock pallas flash kernel has no dropout, so every
dropout-enabled config (all GPT-2 training) fell back to an XLA blockwise
path that materializes, stores, and re-reads O(T^2) dropout masks per layer
— measured at >20ms of a 61ms GPT2-124M step (round-4 profile).

This kernel keeps the masks entirely on-chip: each (q-block, kv-block) tile
reseeds the per-core PRNG from (seed, batch, head, qblk, kvblk) and draws
its keep-mask into VMEM, both in the forward pass and again — bit-identical
— in the backward recompute. Nothing T^2-sized ever touches HBM.

Math (flash + dropout): with P = softmax(S) and keep mask M ~ Bern(1-p),
    out_i = sum_j P_ij * M_ij * v_j / (1 - p)
The online-softmax accumulation applies M to the exp() terms but NOT to the
denominator l, because dropout multiplies the *normalized* weights. In the
backward, with Mt = M/(1-p) and D_i = sum(dO_i * O_i) (the usual flash
delta), the softmax jacobian still collapses:
    dS_ij = P_ij * (Mt_ij * (dO_i . v_j) - D_i)
because sum_k P_ik Mt_ik (dO_i . v_k) = dO_i . O_i = D_i exactly.

Layouts: kernel-internal (B, H, T, D); the public wrapper takes the model's
(B, T, H, D) and transposes (cheap, XLA-fused). GQA never materializes
repeated KV heads — the kv BlockSpec index_map divides the head index.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# lse/delta are stored row-scalar-replicated across this many lanes. 8 (the
# fp32 sublane tile) measured ~3% faster than 128 on the bs8 headline shape
# (4.17 vs 4.31 ms fwd+bwd) by cutting the replicated fp32 HBM traffic 16x.
LANES = 8
_NEG_BIG = -1e30


def _keep_mask(seed_ref, rate: float, b, h, i, j, n_i: int, n_j: int, shape):
    """Draw the Bernoulli(1-rate) keep mask for tile (b,h,i,j).

    Reseeding per tile makes the mask a pure function of the tile
    coordinates, so the backward regenerates bit-identical masks in any
    loop order without storing them.
    """
    tile = (b * pl.num_programs(1) + h) * (n_i * n_j) + i * n_j + j
    # the TPU PRNG seeds from at most 2 words: mix the tile index into the
    # second with a Weyl-sequence constant (wrapping int32 multiply)
    pltpu.prng_seed(seed_ref[0, 0],
                    seed_ref[0, 1] + tile * jnp.int32(-1640531527))
    # prng_random_bits yields SIGNED int32 — bitcast before the unsigned
    # threshold compare or half the range lands below any positive threshold
    # (empirically: keep fraction 0.4 instead of 0.9 at rate 0.1)
    bits = pltpu.bitcast(pltpu.prng_random_bits(shape), jnp.uint32)
    threshold = min(int(rate * (2 ** 32)), 2 ** 32 - 1)
    return bits >= jnp.uint32(threshold)          # True = keep, P = 1-rate


def _causal_mask(i, j, bq: int, bk: int):
    q_pos = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    k_pos = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return q_pos >= k_pos


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(seed_ref, q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                scale: float, rate: float, block_q: int, block_k: int,
                n_kv: int):
    b, h, i = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    n_q = pl.num_programs(2)
    q = q_ref[0, 0]                                   # (BQ, D)

    acc = jnp.zeros((block_q, q.shape[-1]), jnp.float32)
    m = jnp.full((block_q, 1), _NEG_BIG, jnp.float32)
    l = jnp.zeros((block_q, 1), jnp.float32)

    def body(j, carry):
        acc, m, l = carry
        kb = k_ref[0, 0, pl.ds(j * block_k, block_k), :]
        vb = v_ref[0, 0, pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(q, kb, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        s = jnp.where(_causal_mask(i, j, block_q, block_k), s, _NEG_BIG)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        if rate > 0.0:
            keep = _keep_mask(seed_ref, rate, b, h, i, j, n_q, n_kv,
                              (block_q, block_k))
            p = jnp.where(keep, p, 0.0)
        acc = acc * corr + jax.lax.dot(
            p.astype(vb.dtype), vb, preferred_element_type=jnp.float32)
        return acc, m_new, l

    # causal block skipping: only kv blocks overlapping [0, (i+1)*BQ)
    hi = jax.lax.div((i + 1) * block_q + block_k - 1, block_k)
    acc, m, l = jax.lax.fori_loop(0, hi, body, (acc, m, l))

    out = acc / l
    if rate > 0.0:
        out = out * (1.0 / (1.0 - rate))
    o_ref[0, 0] = out.astype(o_ref.dtype)
    lse_ref[0, 0] = jnp.broadcast_to(m + jnp.log(l), (block_q, LANES))


# ---------------------------------------------------------------------------
# backward: dq kernel (grid over q blocks)
# ---------------------------------------------------------------------------

def _dq_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
               dq_ref, *, scale: float, rate: float, block_q: int,
               block_k: int, n_kv: int):
    b, h, i = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    n_q = pl.num_programs(2)
    q = q_ref[0, 0]                                   # (BQ, D)
    do = do_ref[0, 0]                                 # (BQ, D), model dtype
    lse = lse_ref[0, 0][:, :1]                        # (BQ, 1)
    delta = delta_ref[0, 0][:, :1]                    # (BQ, 1)
    inv_keep = 1.0 / (1.0 - rate) if rate > 0.0 else 1.0

    dq = jnp.zeros((block_q, q.shape[-1]), jnp.float32)

    def body(j, dq):
        kb = k_ref[0, 0, pl.ds(j * block_k, block_k), :]
        vb = v_ref[0, 0, pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(q, kb, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        s = jnp.where(_causal_mask(i, j, block_q, block_k), s, _NEG_BIG)
        p = jnp.exp(s - lse)                          # true softmax weights
        dp = jax.lax.dot_general(do, vb, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if rate > 0.0:
            keep = _keep_mask(seed_ref, rate, b, h, i, j, n_q, n_kv,
                              (block_q, block_k))
            dp = jnp.where(keep, dp * inv_keep, 0.0)
        ds = p * (dp - delta) * scale
        return dq + jax.lax.dot(ds.astype(kb.dtype), kb,
                                preferred_element_type=jnp.float32)

    hi = jax.lax.div((i + 1) * block_q + block_k - 1, block_k)
    dq = jax.lax.fori_loop(0, hi, body, dq)
    dq_ref[0, 0] = dq.astype(dq_ref.dtype)


# ---------------------------------------------------------------------------
# backward: dk/dv kernel (grid over kv blocks, per QUERY head; the wrapper
# group-sums for GQA)
# ---------------------------------------------------------------------------

def _dkv_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, *, scale: float, rate: float, block_q: int,
                block_k: int, n_q: int):
    b, h, j = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    n_kv = pl.num_programs(2)
    kb = k_ref[0, 0]                                  # (BK, D)
    vb = v_ref[0, 0]                                  # (BK, D)
    inv_keep = 1.0 / (1.0 - rate) if rate > 0.0 else 1.0

    dk = jnp.zeros((block_k, kb.shape[-1]), jnp.float32)
    dv = jnp.zeros((block_k, vb.shape[-1]), jnp.float32)

    def body(i, carry):
        dk, dv = carry
        q = q_ref[0, 0, pl.ds(i * block_q, block_q), :]
        do = do_ref[0, 0, pl.ds(i * block_q, block_q), :]
        lse = lse_ref[0, 0, pl.ds(i * block_q, block_q), :1]
        delta = delta_ref[0, 0, pl.ds(i * block_q, block_q), :1]
        s = jax.lax.dot_general(q, kb, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        s = jnp.where(_causal_mask(i, j, block_q, block_k), s, _NEG_BIG)
        p = jnp.exp(s - lse)                          # (BQ, BK)
        if rate > 0.0:
            keep = _keep_mask(seed_ref, rate, b, h, i, j, n_q, n_kv,
                              (block_q, block_k))
            pt = jnp.where(keep, p * inv_keep, 0.0)
        else:
            pt = p
        dv = dv + jax.lax.dot_general(                # pt^T @ do
            pt.astype(do.dtype), do,
            (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, vb, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if rate > 0.0:
            dp = jnp.where(keep, dp * inv_keep, 0.0)
        ds = p * (dp - delta) * scale                 # (BQ, BK)
        dk = dk + jax.lax.dot_general(                # ds^T @ q
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk, dv

    lo = jax.lax.div(j * block_k, block_q)            # first overlapping qblk
    dk, dv = jax.lax.fori_loop(lo, n_q, body, (dk, dv))
    dk_ref[0, 0] = dk.astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# pallas_call plumbing
# ---------------------------------------------------------------------------

def _specs_fwd(B, Hq, Hkv, T, D, bq, bk):
    G = Hq // Hkv
    seed = pl.BlockSpec((1, 2), lambda b, h, i: (0, 0),
                        memory_space=pltpu.SMEM)
    qs = pl.BlockSpec((1, 1, bq, D), lambda b, h, i: (b, h, i, 0))
    kv = pl.BlockSpec((1, 1, T, D), lambda b, h, i: (b, h // G, 0, 0))
    return [seed, qs, kv, kv]


def _fwd(q, k, v, seed, *, scale, rate, bq, bk):
    B, Hq, T, D = q.shape
    Hkv = k.shape[1]
    n_q, n_kv = T // bq, T // bk
    kernel = functools.partial(_fwd_kernel, scale=scale, rate=rate,
                               block_q=bq, block_k=bk, n_kv=n_kv)
    out, lse = pl.pallas_call(
        kernel,
        grid=(B, Hq, n_q),
        in_specs=_specs_fwd(B, Hq, Hkv, T, D, bq, bk),
        out_specs=[
            pl.BlockSpec((1, 1, bq, D), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bq, LANES), lambda b, h, i: (b, h, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, Hq, T, D), q.dtype),
            jax.ShapeDtypeStruct((B, Hq, T, LANES), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel")),
    )(seed, q, k, v)
    return out, lse


def _bwd(q, k, v, seed, out, lse, do, *, scale, rate, bq, bk):
    B, Hq, T, D = q.shape
    Hkv = k.shape[1]
    G = Hq // Hkv
    n_q, n_kv = T // bq, T // bk
    # flash delta: D_i = sum_d dO_id * O_id, lane-replicated like lse.
    # The 128x replication of lse/delta costs ~0.3% of the headline step
    # (~300MB of redundant fp32 traffic at bs8) — accepted for the simple
    # always-2D tile layout; revisit only if these rows show up in profiles.
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)
    delta = jnp.broadcast_to(delta, (B, Hq, T, LANES))

    seed_spec = pl.BlockSpec((1, 2), lambda b, h, i: (0, 0),
                             memory_space=pltpu.SMEM)
    qs_blk = pl.BlockSpec((1, 1, bq, D), lambda b, h, i: (b, h, i, 0))
    qs_full = pl.BlockSpec((1, 1, T, D), lambda b, h, j: (b, h, 0, 0))
    kv_full = pl.BlockSpec((1, 1, T, D), lambda b, h, i: (b, h // G, 0, 0))
    kv_blk = pl.BlockSpec((1, 1, bk, D), lambda b, h, j: (b, h // G, j, 0))
    lane_blk = pl.BlockSpec((1, 1, bq, LANES), lambda b, h, i: (b, h, i, 0))
    lane_full = pl.BlockSpec((1, 1, T, LANES), lambda b, h, j: (b, h, 0, 0))

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, rate=rate, block_q=bq,
                          block_k=bk, n_kv=n_kv),
        grid=(B, Hq, n_q),
        in_specs=[seed_spec, qs_blk, kv_full, kv_full, qs_blk, lane_blk,
                  lane_blk],
        out_specs=pl.BlockSpec((1, 1, bq, D), lambda b, h, i: (b, h, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B, Hq, T, D), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel")),
    )(seed, q, k, v, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, rate=rate, block_q=bq,
                          block_k=bk, n_q=n_q),
        grid=(B, Hq, n_kv),
        in_specs=[seed_spec, qs_full, kv_blk, kv_blk, qs_full, lane_full,
                  lane_full],
        out_specs=[
            pl.BlockSpec((1, 1, bk, D), lambda b, h, j: (b, h, j, 0)),
            pl.BlockSpec((1, 1, bk, D), lambda b, h, j: (b, h, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, Hq, T, D), q.dtype),
            jax.ShapeDtypeStruct((B, Hq, T, D), q.dtype),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel")),
    )(seed, q, k, v, do, lse, delta)

    if G > 1:        # GQA: per-query-head dk/dv -> sum over the group
        dk = dk.reshape(B, Hkv, G, T, D).sum(axis=2).astype(k.dtype)
        dv = dv.reshape(B, Hkv, G, T, D).sum(axis=2).astype(v.dtype)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom-vjp public op (kernel layout (B, H, T, D))
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _fused_bhtd(q, k, v, seed, rate, bq, bk):
    scale = 1.0 / float(q.shape[-1]) ** 0.5
    out, _ = _fwd(q, k, v, seed, scale=scale, rate=rate, bq=bq, bk=bk)
    return out


def _fused_fwd_rule(q, k, v, seed, rate, bq, bk):
    from jax.ad_checkpoint import checkpoint_name

    scale = 1.0 / float(q.shape[-1]) ** 0.5
    out, lse = _fwd(q, k, v, seed, scale=scale, rate=rate, bq=bq, bk=bk)
    # named so the transformer's selective-save remat policy stores these
    # residuals instead of re-running the forward kernel in the backward
    out = checkpoint_name(out, "attn_raw_out")
    lse = checkpoint_name(lse, "attn_lse")
    return out, (q, k, v, seed, out, lse)


def _fused_bwd_rule(rate, bq, bk, res, do):
    q, k, v, seed, out, lse = res
    scale = 1.0 / float(q.shape[-1]) ** 0.5
    dq, dk, dv = _bwd(q, k, v, seed, out, lse, do,
                      scale=scale, rate=rate, bq=bq, bk=bk)
    return dq, dk, dv, None


_fused_bhtd.defvjp(_fused_fwd_rule, _fused_bwd_rule)


def fused_causal_attention(
    q: jnp.ndarray,               # (B, T, Hq, D) — model layout
    k: jnp.ndarray,               # (B, T, Hkv, D)
    v: jnp.ndarray,               # (B, T, Hkv, D)
    *,
    dropout_rate: float = 0.0,
    dropout_rng: jax.Array | None = None,
    block_q: int | None = None,
    block_k: int | None = None,
) -> jnp.ndarray:
    """Fused causal flash attention, optional in-kernel attention dropout.

    Requires T divisible by the block sizes (the auto-policy in
    ops/attention.py guarantees it; explicit callers must check
    ``supports_shape``).
    """
    import os

    if block_q is None:
        block_q = int(os.environ.get("BLLM_ATTN_BQ", "512"))
    if block_k is None:
        block_k = int(os.environ.get("BLLM_ATTN_BK", "512"))
    B, T, Hq, D = q.shape
    if k.shape[1] != T or v.shape[1] != T:
        raise ValueError(
            f"fused attention is self-attention only (Tq == Tkv); got "
            f"q T={T}, k T={k.shape[1]}, v T={v.shape[1]}")
    bq, bk = min(block_q, T), min(block_k, T)
    if T % bq or T % bk or T % 128:
        raise ValueError(f"fused attention needs T % block == 0 and lane-"
                         f"aligned T; T={T}, blocks=({bq},{bk})")
    if dropout_rate > 0.0:
        if dropout_rng is None:
            raise ValueError("dropout_rate > 0 requires dropout_rng")
        seed = jax.random.bits(dropout_rng, (1, 2), jnp.uint32)
        seed = seed.astype(jnp.int32)
    else:
        seed = jnp.zeros((1, 2), jnp.int32)
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    out = _fused_bhtd(qt, kt, vt, seed, float(dropout_rate), bq, bk)
    return out.transpose(0, 2, 1, 3)


def supports_shape(Tq: int, Tkv: int, D: int, block: int = 512) -> bool:
    """Shapes the fused kernel handles: self-attention, lane-aligned and
    block-divisible sequence, lane-friendly head dim. Note ``min(block,Tq)``
    makes ``Tq % b`` vacuous for short Tq — the explicit ``Tq % 128`` keeps
    non-lane-aligned shapes (e.g. T=300) on the exact paths."""
    b = min(block, Tq)
    return (Tq == Tkv and Tq >= 2 * 128 and Tq % b == 0 and Tq % 128 == 0
            and D % 64 == 0 and D <= 256)
