"""Fused residual/embedding dropout for TPU.

The reference drops the embedding output and both residual branches
(/root/reference/Models/GPT2/GPT2.py:79-87,110-113). Under XLA those
dropouts cost mask generation + storage across fwd/bwd; this kernel draws
the Bernoulli mask from the per-core PRNG inside the kernel — seeded purely
by (seed, tile index) — so the backward regenerates the exact mask and
nothing mask-shaped is ever stored.

Two entry points, one kernel body:
  dropout(h, rate, rng)           -> dropout(h)          (embedding path)
  dropout_add(x, h, rate, rng)    -> x + dropout(h)      (residual path)
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_WEYL = -1640531527  # 0x9E3779B9 as int32


def _tile_keep(seed_ref, rate: float, shape):
    tile = pl.program_id(0)
    pltpu.prng_seed(seed_ref[0, 0],
                    seed_ref[0, 1] + tile * jnp.int32(_WEYL))
    bits = pltpu.bitcast(pltpu.prng_random_bits(shape), jnp.uint32)
    threshold = min(int(rate * (2 ** 32)), 2 ** 32 - 1)
    return bits >= jnp.uint32(threshold)


def _fwd_kernel(seed_ref, h_ref, o_ref, *, rate, add, x_ref=None):
    keep = _tile_keep(seed_ref, rate, h_ref.shape[1:])
    inv = 1.0 / (1.0 - rate)
    h = jnp.where(keep, h_ref[0] * jnp.asarray(inv, h_ref.dtype),
                  jnp.zeros_like(h_ref[0]))
    o_ref[0] = (x_ref[0] + h) if add else h


def _fwd_kernel_add(seed_ref, x_ref, h_ref, o_ref, *, rate):
    _fwd_kernel(seed_ref, h_ref, o_ref, rate=rate, add=True, x_ref=x_ref)


def _bwd_kernel(seed_ref, g_ref, dh_ref, *, rate):
    keep = _tile_keep(seed_ref, rate, g_ref.shape[1:])
    inv = 1.0 / (1.0 - rate)
    dh_ref[0] = jnp.where(keep, g_ref[0] * jnp.asarray(inv, g_ref.dtype),
                          jnp.zeros_like(g_ref[0]))


_ROWS = 512
# below this row-block size the grid degenerates toward one PRNG reseed per
# handful of rows (worst case N prime: N single-row tiles) — the XLA path
# wins there (round-4 ADVICE low #3)
_MIN_ROWS = 8


def _best_rows(n: int) -> int:
    r = min(_ROWS, n)
    while n % r:
        r -= 1
    return r


def _tiles(h):
    n, d = h.shape
    r = _best_rows(n)
    return n // r, r


def _seed_spec():
    return pl.BlockSpec((1, 2), lambda i: (0, 0), memory_space=pltpu.SMEM)


def _call_fwd(x, h, seed, rate):
    n_tiles, r = _tiles(h)
    blk = pl.BlockSpec((1, r, h.shape[1]),
                       lambda i: (i, 0, 0))
    h3 = h.reshape(n_tiles, r, h.shape[1])
    if x is None:
        kern = functools.partial(_fwd_kernel, rate=rate, add=False)
        args, specs = (seed, h3), [_seed_spec(), blk]
    else:
        kern = functools.partial(_fwd_kernel_add, rate=rate)
        args = (seed, x.reshape(n_tiles, r, h.shape[1]), h3)
        specs = [_seed_spec(), blk, blk]
    out = pl.pallas_call(
        kern, grid=(n_tiles,), in_specs=specs, out_specs=blk,
        out_shape=jax.ShapeDtypeStruct(h3.shape, h.dtype),
    )(*args)
    return out.reshape(h.shape)


def _call_bwd(g, seed, rate):
    n_tiles, r = _tiles(g)
    blk = pl.BlockSpec((1, r, g.shape[1]), lambda i: (i, 0, 0))
    g3 = g.reshape(n_tiles, r, g.shape[1])
    dh = pl.pallas_call(
        functools.partial(_bwd_kernel, rate=rate),
        grid=(n_tiles,), in_specs=[_seed_spec(), blk], out_specs=blk,
        out_shape=jax.ShapeDtypeStruct(g3.shape, g.dtype),
    )(seed, g3)
    return dh.reshape(g.shape)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _dropout_add2d(x, h, seed, rate):
    return _call_fwd(x, h, seed, rate)


def _da_fwd(x, h, seed, rate):
    return _call_fwd(x, h, seed, rate), seed


def _da_bwd(rate, seed, g):
    return g, _call_bwd(g, seed, rate), None


_dropout_add2d.defvjp(_da_fwd, _da_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _dropout2d(h, seed, rate):
    return _call_fwd(None, h, seed, rate)


def _d_fwd(h, seed, rate):
    return _call_fwd(None, h, seed, rate), seed


def _d_bwd(rate, seed, g):
    return _call_bwd(g, seed, rate), None


_dropout2d.defvjp(_d_fwd, _d_bwd)


def supports_shape(shape) -> bool:
    """Last dim lane-aligned AND the folded leading dims admit a row block
    of at least ``_MIN_ROWS`` (otherwise the pallas grid degenerates into
    per-row tiles that each reseed the PRNG — slower than XLA dropout)."""
    if len(shape) < 2 or shape[-1] % 128 != 0:
        return False
    n = 1
    for d in shape[:-1]:
        n *= int(d)
    return _best_rows(n) >= _MIN_ROWS


def _seed_from_rng(rng):
    return jax.random.bits(rng, (1, 2), jnp.uint32).astype(jnp.int32)


def fused_dropout(h: jnp.ndarray, rate: float, rng: jax.Array) -> jnp.ndarray:
    """dropout(h) with the mask drawn in-kernel (never stored)."""
    shape = h.shape
    out = _dropout2d(h.reshape(-1, shape[-1]), _seed_from_rng(rng),
                     float(rate))
    return out.reshape(shape)


def fused_dropout_add(x: jnp.ndarray, h: jnp.ndarray, rate: float,
                      rng: jax.Array) -> jnp.ndarray:
    """x + dropout(h) — the pre-norm residual update — in one pass."""
    shape = h.shape
    out = _dropout_add2d(x.reshape(-1, shape[-1]),
                         h.reshape(-1, shape[-1]),
                         _seed_from_rng(rng), float(rate))
    return out.reshape(shape)
