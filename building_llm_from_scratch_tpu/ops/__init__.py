"""Numerical ops shared by all models (reference: Models/Llama/common_components.py,
the attention bodies of Models/GPT2/GPT2.py and Models/Llama/Llama3.py)."""

from building_llm_from_scratch_tpu.ops.norms import layernorm, rmsnorm
from building_llm_from_scratch_tpu.ops.activations import gelu, silu
from building_llm_from_scratch_tpu.ops.rope import (
    precompute_rope_params,
    apply_rope,
)
from building_llm_from_scratch_tpu.ops.attention import causal_attention

__all__ = [
    "layernorm",
    "rmsnorm",
    "gelu",
    "silu",
    "precompute_rope_params",
    "apply_rope",
    "causal_attention",
]
