"""Normalization ops.

Reference equivalents: ``nn.LayerNorm`` uses in Models/GPT2/GPT2.py and the
hand-written fp32 RMSNorm in Models/Llama/common_components.py:54-70.

Both are computed in fp32 regardless of the activation dtype (matching the
reference's RMSNorm, and torch LayerNorm's internal accumulation) and cast
back to the input dtype, which keeps bf16 training stable on TPU.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp


def layernorm(x: jnp.ndarray, scale: jnp.ndarray,
              bias: Optional[jnp.ndarray] = None,
              eps: float = 1e-5) -> jnp.ndarray:
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    y = (x32 - mean) * jnp.reciprocal(jnp.sqrt(var + eps))
    y = y * scale.astype(jnp.float32)
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    return y.astype(dtype)


def rmsnorm(x: jnp.ndarray, scale: jnp.ndarray, eps: float = 1e-5) -> jnp.ndarray:
    """Root-mean-square norm (reference common_components.py:54-70)."""
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    ms = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    y = x32 * jnp.reciprocal(jnp.sqrt(ms + eps)) * scale.astype(jnp.float32)
    return y.astype(dtype)
