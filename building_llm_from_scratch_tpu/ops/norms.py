"""Normalization ops.

Reference equivalents: ``nn.LayerNorm`` uses in Models/GPT2/GPT2.py and the
hand-written fp32 RMSNorm in Models/Llama/common_components.py:54-70.

Both are computed in fp32 regardless of the activation dtype (matching the
reference's RMSNorm, and torch LayerNorm's internal accumulation) and cast
back to the input dtype, which keeps bf16 training stable on TPU.

Custom VJP (round 5): under plain autodiff XLA saved the fp32 normalized
intermediates of every norm for the backward — on the GPT2-124M bs8 profile
that is multiple f32[L,B,T,D] residual buffers carried across the layer
scan (~300MB each, written in the forward and re-read in the backward).
The custom rule saves only the compute-dtype input plus the per-row fp32
stats (mean/rstd — (B,T,1)) and recomputes x-hat in the backward: same
math, ~2x less norm-related HBM traffic.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp


# ---------------------------------------------------------------------------
# layernorm
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _layernorm(x, scale, bias, eps):
    y, _, _ = _ln_fwd_math(x, scale, bias, eps)
    return y


def _ln_fwd_math(x, scale, bias, eps):
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    rstd = jnp.reciprocal(jnp.sqrt(var + eps))
    y = (x32 - mean) * rstd * scale.astype(jnp.float32)
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    return y.astype(x.dtype), mean, rstd


def _ln_fwd(x, scale, bias, eps):
    y, mean, rstd = _ln_fwd_math(x, scale, bias, eps)
    # residuals: compute-dtype x + tiny fp32 row stats — NOT the fp32 x-hat
    return y, (x, scale, bias, mean, rstd)


def _ln_bwd(eps, res, g):
    x, scale, bias, mean, rstd = res
    x32 = x.astype(jnp.float32)
    g32 = g.astype(jnp.float32)
    xhat = (x32 - mean) * rstd
    axes = tuple(range(x.ndim - 1))
    dscale = jnp.sum(g32 * xhat, axis=axes).astype(scale.dtype)
    dbias = (jnp.sum(g32, axis=axes).astype(bias.dtype)
             if bias is not None else None)
    u = g32 * scale.astype(jnp.float32)
    # dx = r * (u - mean(u) - xhat * mean(u * xhat))
    dx = rstd * (u - jnp.mean(u, axis=-1, keepdims=True)
                 - xhat * jnp.mean(u * xhat, axis=-1, keepdims=True))
    return dx.astype(x.dtype), dscale, dbias


_layernorm.defvjp(_ln_fwd, _ln_bwd)


def layernorm(x: jnp.ndarray, scale: jnp.ndarray,
              bias: Optional[jnp.ndarray] = None,
              eps: float = 1e-5) -> jnp.ndarray:
    return _layernorm(x, scale, bias, float(eps))


# ---------------------------------------------------------------------------
# rmsnorm
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _rmsnorm(x, scale, eps):
    y, _ = _rms_fwd_math(x, scale, eps)
    return y


def _rms_fwd_math(x, scale, eps):
    x32 = x.astype(jnp.float32)
    ms = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    rstd = jnp.reciprocal(jnp.sqrt(ms + eps))
    y = x32 * rstd * scale.astype(jnp.float32)
    return y.astype(x.dtype), rstd


def _rms_fwd(x, scale, eps):
    y, rstd = _rms_fwd_math(x, scale, eps)
    return y, (x, scale, rstd)


def _rms_bwd(eps, res, g):
    x, scale, rstd = res
    x32 = x.astype(jnp.float32)
    g32 = g.astype(jnp.float32)
    xhat = x32 * rstd
    axes = tuple(range(x.ndim - 1))
    dscale = jnp.sum(g32 * xhat, axis=axes).astype(scale.dtype)
    u = g32 * scale.astype(jnp.float32)
    # dx = r * (u - xhat * mean(u * xhat))
    dx = rstd * (u - xhat * jnp.mean(u * xhat, axis=-1, keepdims=True))
    return dx.astype(x.dtype), dscale


_rmsnorm.defvjp(_rms_fwd, _rms_bwd)


def rmsnorm(x: jnp.ndarray, scale: jnp.ndarray, eps: float = 1e-5) -> jnp.ndarray:
    """Root-mean-square norm (reference common_components.py:54-70)."""
    return _rmsnorm(x, scale, float(eps))
