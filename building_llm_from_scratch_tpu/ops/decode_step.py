"""Fused KV-cache update + attention for decode (pallas, TPU).

Why this kernel exists: the decode loop carries the KV cache through a
``lax.while_loop`` and appends one position per step with
``dynamic_update_slice``. XLA's buffer assignment refuses to alias that
update in place — every layer of every decoded token paid a full-cache
copy (r5 profiles: ~40% of GPT2-124M bs8 step time as copy-start/copy-done
pairs, surviving both cache layouts and per-layer buffer splits). A pallas
kernel with ``input_output_aliases`` DECLARES the in-place update, so the
cache never copies; as a bonus the new k/v rows are written in the same
pass that computes attention, and masked scores never leave VMEM.

Semantics (exactly ``ops.attention.decode_attention``):
  - cache layout (B, Hkv, Tmax, hd); valid prefix ``length``; the kernel
    writes k/v for positions [length, length+Tq) and attends with the
    causal mask  kv_pos <= length + row  (row < Tq).
  - eval-only (no dropout, no grad) — generation never trains.

Grid (B, Hkv): each cell streams one (Tmax, hd) K and V pane through VMEM
once — the HBM-roofline minimum for un-paged decode.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_BIG = -1e30
# mosaic wants >= 8 sublanes; decode's G*Tq is often 1 — pad the query rows
_MIN_ROWS = 8


def _kernel(len_ref, q_ref, kn_ref, vn_ref, K_ref, V_ref,
            Ko_ref, Vo_ref, o_ref, *, scale: float):
    """Single-token (Tq=1) append + attend for one batch-row grid cell
    (all Hkv heads per cell — big DMAs keep HBM busy; the first kernel
    revision's (B, Hkv) grid moved 40KB blocks and ran 8x off roofline).

    The append stores only the 8-row aligned window containing position
    ``t`` (mosaic requires provably 8-aligned dynamic sublane offsets —
    ``pl.multiple_of((t // 8) * 8, 8)`` supplies the proof), merging the
    new row into it; the attention then reads the full pane from VMEM.
    """
    t = len_ref[0, 0]
    t8 = pl.multiple_of((t // 8) * 8, 8)
    Hkv, Tmax, hd = K_ref.shape[1:]

    def merge_store(new_ref, ref):
        old = ref[0, :, pl.ds(t8, 8), :]              # (Hkv, 8, hd)
        row = t8 + jax.lax.broadcasted_iota(jnp.int32, (Hkv, 8, hd), 1)
        new = jnp.broadcast_to(new_ref[0], (Hkv, 8, hd))
        ref[0, :, pl.ds(t8, 8), :] = jnp.where(row == t, new, old)

    merge_store(kn_ref, Ko_ref)
    merge_store(vn_ref, Vo_ref)

    q = q_ref[0]                                      # (Hkv, R, hd)
    k = Ko_ref[0]                                     # (Hkv, Tmax, hd)
    v = Vo_ref[0]
    R = q.shape[1]
    s = jax.lax.dot_general(q, k, (((2,), (2,)), ((0,), (0,))),
                            preferred_element_type=jnp.float32) * scale
    kv_pos = jax.lax.broadcasted_iota(jnp.int32, (Hkv, R, Tmax), 2)
    s = jnp.where(kv_pos <= t, s, _NEG_BIG)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    p = p / jnp.sum(p, axis=-1, keepdims=True)
    o_ref[0] = jax.lax.dot_general(
        p.astype(v.dtype), v, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32).astype(o_ref.dtype)


#: symmetric int8 KV quantization floor: an all-zero position (zeroed
#: pad, never-written cache row) quantizes to scale EPS and exact-zero
#: codes, so dequantization is exactly zero — byte-deterministic panes
KV_QUANT_EPS = 1e-8


def quantize_kv(x: jnp.ndarray) -> tuple:
    """Symmetric int8 quantization over the trailing head_dim axis:
    one fp32 scale per (..., position, head) written — computed at
    APPEND time, so every cache write is self-describing and appends at
    different times never re-scale each other's history.

    Returns (codes int8 (..., hd), scales fp32 (..., 1)) with
    ``codes * scales ~= x`` (max error scale/2 per element)."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
    scale = jnp.maximum(amax / 127.0, KV_QUANT_EPS)
    codes = jnp.clip(jnp.round(xf / scale), -127.0, 127.0).astype(jnp.int8)
    return codes, scale


def dequantize_kv(codes: jnp.ndarray, scale: jnp.ndarray) -> jnp.ndarray:
    """Inverse of ``quantize_kv`` (fp32). The decode path never calls
    this on a whole cache — ``decode_attention`` folds the scales into
    its einsums instead — but parity tests and one-off consumers do."""
    return codes.astype(jnp.float32) * scale


def slot_cache_append(cache: jnp.ndarray, new: jnp.ndarray,
                      lengths: jnp.ndarray) -> jnp.ndarray:
    """Batched slot-indexed cache append: write ``new`` (B, Hkv, Tq, hd)
    into ``cache`` (B, Hkv, Tmax, hd) at PER-ROW time offsets ``lengths``
    (B,) — the continuous-batching primitive where every batch row is a
    different request at a different sequence length.

    Scalar ``lengths`` degrades to the shared-offset single
    ``dynamic_update_slice`` the one-shot decode path uses. The vmap'd
    per-row form lowers to a batched DUS; on TPU the serving engine routes
    single-token appends through the pallas kernel below instead (which
    additionally aliases the cache in place).
    """
    lengths = jnp.asarray(lengths)
    if lengths.ndim == 0:
        return jax.lax.dynamic_update_slice(
            cache, new.astype(cache.dtype), (0, 0, lengths, 0))

    def one(c, n, t):                      # c (Hkv, Tmax, hd), n (Hkv, Tq, hd)
        return jax.lax.dynamic_update_slice(c, n.astype(c.dtype), (0, t, 0))

    return jax.vmap(one)(cache, new, lengths.astype(jnp.int32))


def fused_decode_step(q, k_new, v_new, k_cache, v_cache, length):
    """Append k_new/v_new at ``length`` (IN PLACE via aliasing) and attend.

    q:                (B, Tq, Hq, hd)   — model layout, Tq small
    k_new, v_new:     (B, Tq, Hkv, hd)
    k_cache, v_cache: (B, Hkv, Tmax, hd)
    length:           scalar int32 (valid prefix), or (B,) per-row
                      lengths for the slot-batched serving engine — the
                      grid already runs one cell per batch row, so each
                      cell simply reads ITS row's length from SMEM.

    Returns (out (B, Tq, Hq, hd), k_cache', v_cache').
    """
    B, Tq, Hq, hd = q.shape
    _, Hkv, Tmax, _ = k_cache.shape
    if Tq != 1:
        raise ValueError(f"fused_decode_step is single-token only; Tq={Tq}")
    G = Hq // Hkv
    R = G * Tq
    Rp = max(_MIN_ROWS, R)
    # (B, Hkv, G*Tq, hd) query rows, padded to the sublane minimum
    qr = q.reshape(B, Tq, Hkv, G, hd).transpose(0, 2, 3, 1, 4)
    qr = qr.reshape(B, Hkv, R, hd)
    if Rp != R:
        qr = jnp.pad(qr, ((0, 0), (0, 0), (0, Rp - R), (0, 0)))
    knt = k_new.transpose(0, 2, 1, 3)                 # (B, Hkv, Tq, hd)
    vnt = v_new.transpose(0, 2, 1, 3)
    # (B, 1) per-row lengths in SMEM; a scalar broadcasts to every row
    len2 = jnp.broadcast_to(
        jnp.reshape(jnp.asarray(length, jnp.int32), (-1, 1)), (B, 1))

    blk = lambda rows: pl.BlockSpec((1, Hkv, rows, hd),
                                    lambda b: (b, 0, 0, 0))
    ko, vo, out = pl.pallas_call(
        functools.partial(_kernel, scale=1.0 / float(hd) ** 0.5),
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, 1), lambda b: (b, 0),
                         memory_space=pltpu.SMEM),
            blk(Rp), blk(Tq), blk(Tq), blk(Tmax), blk(Tmax),
        ],
        out_specs=[blk(Tmax), blk(Tmax), blk(Rp)],
        out_shape=[
            jax.ShapeDtypeStruct(k_cache.shape, k_cache.dtype),
            jax.ShapeDtypeStruct(v_cache.shape, v_cache.dtype),
            jax.ShapeDtypeStruct((B, Hkv, Rp, hd), q.dtype),
        ],
        input_output_aliases={4: 0, 5: 1},   # K->Ko, V->Vo in place
    )(len2, qr, knt, vnt, k_cache, v_cache)
    out = out[:, :, :R]                               # drop row padding
    # (B, Hkv, G, Tq, hd) -> (B, Tq, Hq, hd)
    out = out.reshape(B, Hkv, G, Tq, hd).transpose(0, 3, 1, 2, 4)
    return out.reshape(B, Tq, Hq, hd), ko, vo


def _bgmv_kernel(ids_ref, x_ref, a_ref, b_ref, scale_ref, o_ref, *,
                 n_pool: int):
    """One batch row per grid cell: the row's adapter id (scalar-prefetched
    SMEM) selected WHICH (D, r)/(r, O) pool panes the BlockSpec index maps
    DMA'd into VMEM; here we just multiply through and scale. id −1 rows
    fetch the clamped pane but scale by 0 — exact zero delta, no branch."""
    s = pl.program_id(0)
    i = ids_ref[s]
    sc = jnp.where(i >= 0, scale_ref[jnp.clip(i, 0, n_pool - 1)], 0.0)
    xa = jax.lax.dot(x_ref[0], a_ref[0],
                     preferred_element_type=jnp.float32)       # (rows, r)
    o_ref[0] = jax.lax.dot(xa.astype(b_ref.dtype), b_ref[0],
                           preferred_element_type=jnp.float32) * sc


def lora_bgmv(x, a_pool, b_pool, ids, scales, *, interpret=False):
    """Punica/S-LoRA-style BGMV: per-row gathered LoRA delta, fused.

    x:       (S, D)  one activation row per slot (single-token decode)
    a_pool:  (N, D, r)  stacked adapter A matrices (N = pool capacity)
    b_pool:  (N, r, O)
    ids:     (S,) int32 adapter id per row; −1 = base model (zero delta)
    scales:  (N,) fp32 alpha/rank per pool row

    Returns (S, O) fp32: ``scales[ids[s]] * (x[s] @ A[ids[s]]) @ B[ids[s]]``.

    Each grid cell DMAs exactly ONE adapter's panes from the pool (the
    scalar-prefetched ``ids`` drive the BlockSpec index maps), so HBM
    traffic is O(S · adapter_size), independent of pool capacity — the
    XLA gather-then-einsum fallback materializes the same gather but
    cannot skip fetching for id −1 rows. Adapter identity is DATA: any
    id mix compiles to this one program. TPU-gated via
    ``supports_lora_shape``; ``interpret=True`` runs the kernel on CPU
    for parity tests."""
    S, D = x.shape
    N, _, r = a_pool.shape
    O = b_pool.shape[-1]
    # mosaic wants >= 8 sublanes; one activation row -> pad to 8 zero rows
    xp = jnp.zeros((S, _MIN_ROWS, D), x.dtype)
    xp = jax.lax.dynamic_update_slice(xp, x[:, None, :], (0, 0, 0))
    ids = ids.astype(jnp.int32)
    scales = scales.astype(jnp.float32)

    def pool_idx(s, ids_ref):
        return (jnp.clip(ids_ref[s], 0, N - 1), 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(S,),
        in_specs=[
            pl.BlockSpec((1, _MIN_ROWS, D), lambda s, ids_ref: (s, 0, 0)),
            pl.BlockSpec((1, D, r), pool_idx),
            pl.BlockSpec((1, r, O), pool_idx),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((1, _MIN_ROWS, O),
                               lambda s, ids_ref: (s, 0, 0)),
    )
    out = pl.pallas_call(
        functools.partial(_bgmv_kernel, n_pool=N),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, _MIN_ROWS, O), jnp.float32),
        interpret=interpret,
    )(ids, xp, a_pool, b_pool, scales)
    return out[:, 0]


def supports_lora_shape(D: int, r: int, O: int) -> bool:
    """BGMV kernel eligibility for one (in=D, rank=r, out=O) projection:
    lane-aligned in/out dims and a sublane-aligned rank (the r-wide
    intermediate). Unsupported shapes keep the XLA gather+einsum path —
    same numbers, just without the per-row pool-pane DMA savings."""
    return D % 128 == 0 and O % 128 == 0 and r % 8 == 0 and 8 <= r <= 256


def _paged_kernel(tab_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                  mx_ref, d_ref, acc_ref, *, scale: float,
                  page_tokens: int, max_pages: int):
    """Online-softmax attention over one row's page list: grid cell
    (s, m) DMAs physical page ``tab_ref[s * max_pages + m]`` — the
    scalar-prefetched flattened page table drives the K/V BlockSpec
    index maps, exactly the ``lora_bgmv`` gather discipline — and folds
    its ``page_tokens`` positions into the running (max, denom, acc)
    scratch. Initialized at m == 0, finalized into ``o_ref`` at the last
    page. Mask: global position  m*P + p  <=  lengths[s]  (the
    ``decode_attention`` Tq=1 causal rule); pages past the row's
    frontier are all-masked, contributing exp(_NEG_BIG - max) == 0."""
    s = pl.program_id(0)
    m = pl.program_id(1)

    @pl.when(m == 0)
    def _init():
        mx_ref[...] = jnp.full_like(mx_ref, _NEG_BIG)
        d_ref[...] = jnp.zeros_like(d_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    q = q_ref[0]                                      # (Hkv, Rp, hd)
    k = k_ref[0]                                      # (Hkv, P, hd)
    v = v_ref[0]
    Hkv, Rp, _ = q.shape
    P = k.shape[1]
    sc = jax.lax.dot_general(q, k, (((2,), (2,)), ((0,), (0,))),
                             preferred_element_type=jnp.float32) * scale
    pos = (m * page_tokens
           + jax.lax.broadcasted_iota(jnp.int32, (Hkv, Rp, P), 2))
    sc = jnp.where(pos <= len_ref[s], sc, _NEG_BIG)
    m_new = jnp.maximum(mx_ref[...], jnp.max(sc, axis=-1, keepdims=True))
    alpha = jnp.exp(mx_ref[...] - m_new)
    p = jnp.exp(sc - m_new)
    mx_ref[...] = m_new
    d_ref[...] = d_ref[...] * alpha + jnp.sum(p, axis=-1, keepdims=True)
    acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
        p.astype(v.dtype), v, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32)

    @pl.when(m == max_pages - 1)
    def _finalize():
        o_ref[0] = (acc_ref[...] / d_ref[...]).astype(o_ref.dtype)


def paged_decode_attention(q, k_pool, v_pool, page_table, lengths, *,
                           interpret=False):
    """Page-table attention for single-token decode: attend each slot's
    logical row WITHOUT materializing it — grid cell (s, m) streams only
    the physical page the row's table names, so HBM traffic is
    O(tokens in flight), identical to the contiguous kernel's, while the
    XLA reference path (``transformer._paged_view``) first gathers a
    (S, Hkv, Tmax, hd) copy per layer.

    q:          (S, 1, Hq, hd)  model layout, single token
    k_pool:     (N, Hkv, P, hd) shared page pool (unquantized)
    v_pool:     (N, Hkv, P, hd)
    page_table: (S, M) int32 physical page per logical page (0 = trash)
    lengths:    (S,) int32 valid prefix per row; attends kv_pos <=
                lengths[s] (the new token's position, appended by the
                caller BEFORE this kernel runs)

    Returns (S, 1, Hq, hd) attention output. Page identity is DATA
    (scalar-prefetched), so any table contents run through one compiled
    program. ``interpret=True`` runs on CPU for parity tests."""
    S, Tq, Hq, hd = q.shape
    N, Hkv, P, _ = k_pool.shape
    M = page_table.shape[1]
    if Tq != 1:
        raise ValueError(f"paged_decode_attention is single-token only; "
                         f"Tq={Tq}")
    G = Hq // Hkv
    R = G * Tq
    Rp = max(_MIN_ROWS, R)
    qr = q.reshape(S, Tq, Hkv, G, hd).transpose(0, 2, 3, 1, 4)
    qr = qr.reshape(S, Hkv, R, hd)
    if Rp != R:
        qr = jnp.pad(qr, ((0, 0), (0, 0), (0, Rp - R), (0, 0)))
    tab = page_table.astype(jnp.int32).reshape(-1)
    lens = jnp.asarray(lengths, jnp.int32)

    def kv_idx(s, m, tab_ref, len_ref):
        return (jnp.clip(tab_ref[s * M + m], 0, N - 1), 0, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(S, M),
        in_specs=[
            pl.BlockSpec((1, Hkv, Rp, hd),
                         lambda s, m, tab_ref, len_ref: (s, 0, 0, 0)),
            pl.BlockSpec((1, Hkv, P, hd), kv_idx),
            pl.BlockSpec((1, Hkv, P, hd), kv_idx),
        ],
        out_specs=pl.BlockSpec((1, Hkv, Rp, hd),
                               lambda s, m, tab_ref, len_ref: (s, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((Hkv, Rp, 1), jnp.float32),
            pltpu.VMEM((Hkv, Rp, 1), jnp.float32),
            pltpu.VMEM((Hkv, Rp, hd), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_paged_kernel, scale=1.0 / float(hd) ** 0.5,
                          page_tokens=P, max_pages=M),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, Hkv, Rp, hd), q.dtype),
        interpret=interpret,
    )(tab, lens, qr, k_pool, v_pool)
    out = out[:, :, :R]
    out = out.reshape(S, Hkv, G, Tq, hd).transpose(0, 3, 1, 2, 4)
    return out.reshape(S, Tq, Hq, hd)


def supports_paged_shape(Tq: int, page_tokens: int, hd: int) -> bool:
    """Paged-attention kernel eligibility: single-token decode,
    lane-aligned head dim, sublane-aligned page length (each page is one
    VMEM pane). Ineligible shapes — and int8 pools, gated off by the
    caller exactly like ``supports_shape`` — keep the XLA gather
    reference path."""
    return (Tq == 1 and hd % 64 == 0 and hd <= 256
            and page_tokens % 8 == 0)


def supports_shape(Tq: int, Tmax: int, hd: int) -> bool:
    """Kernel eligibility: single-token decode, lane-aligned head dim,
    cache panes that fit VMEM comfortably, and 8-row-aligned Tmax (the
    merge_store window [t8, t8+8) must stay inside the pane for every
    t < Tmax). Prefill (Tq > 1) keeps the dynamic-update-slice +
    ``decode_attention`` path — it runs once per generation, so its
    copies don't matter. int8-quantized caches (serving/kvcache.py) are
    additionally gated OFF by the caller: the kernel would need an
    in-VMEM dequant pass (quantize on merge_store, fold scales into the
    score/value dots) that has no hardware to be A/B'd against in this
    container — the XLA path carries the scales instead."""
    return (Tq == 1 and hd % 64 == 0 and hd <= 256 and Tmax <= 8192
            and Tmax % 8 == 0)
