"""Chunked softmax cross-entropy from the final hidden states.

The reference computes ``F.cross_entropy(model(x).flatten(...), targets)``
(/root/reference/train.py:88-92) — logits materialize, then log_softmax,
then the backward materializes dlogits. At GPT-2's 50257 vocab that is a
(B*T, 50257) fp32 tensor written and re-read several times per step: the
round-4 bs8 profile shows ~18ms of a 102ms step in the loss/head block
(log_softmax 5.0ms, lse reduce 2.2ms, fused softmax-grad+dx 6.7ms, ...).

This op chunks the vocabulary: the forward runs online logsumexp over
``chunk``-wide slices of the head matmul (peak live logits = (N, chunk))
and saves only the per-token lse; the backward recomputes each chunk's
logits and feeds dlogits straight into the dx/dW matmuls. fp32 logits
never exist in HBM at full width in either pass.

Pure JAX (lax.scan + dynamic_slice) — runs on CPU/TPU, shards under GSPMD
like any matmul, and is exact (same fp32 math as dense log_softmax; parity
tested to 1e-5 in tests/test_softmax_xent.py).

Chunk-size note (v5e-1, bs8 GPT2-124M loss+grad micro-bench): dense 16.1ms;
chunk 6400/12800/25600: 19.5-20.1ms; chunk 51200 (single padded chunk):
15.3ms. Sub-vocab chunking re-reads x2/W per chunk and loses more to that
than it saves in logits traffic at this model size — the win here comes
from the custom backward (no stored log-probs, dlogits feeding matmuls
directly), so the default is one padded chunk. Smaller chunks remain
correct and useful when (N, V) temps must be bounded (long-context eval).
r5 also split ``fwd_chunk`` from the backward chunk (the backward's three
matmuls run at ~87% MXU and only lose W re-reads from chunking, while the
forward's fp32 logits temp is pure HBM traffic) — measured in-model:
fwd_chunk 6400/12800/25600 gave 91.7/93.5/93.2k tok/s vs ~94-98k dense,
i.e. chunking the forward alone still loses (the scan boundary breaks
XLA's matmul+exp fusion). Dense stays the default on both sides;
``BLLM_XENT_FWD_CHUNK`` keeps the forward bound available for
long-context eval.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

_NEG_BIG = -1e30


def _pad_vocab(w_head: jnp.ndarray, chunk: int) -> Tuple[jnp.ndarray, int]:
    D, V = w_head.shape
    n_chunks = -(-V // chunk)
    Vp = n_chunks * chunk
    if Vp != V:
        w_head = jnp.pad(w_head, ((0, 0), (0, Vp - V)))
    return w_head, n_chunks


def _chunk_logits(x2, wp, c, chunk, V):
    """(N, chunk) fp32 logits for vocab slice [c*chunk, (c+1)*chunk), with
    out-of-vocab (padded) columns masked to -inf."""
    D = x2.shape[1]
    wc = jax.lax.dynamic_slice(wp, (0, c * chunk), (D, chunk))
    logits = jnp.einsum("nd,dc->nc", x2, wc,
                        preferred_element_type=jnp.float32)
    col = c * chunk + jnp.arange(chunk)
    return jnp.where(col[None, :] < V, logits, _NEG_BIG)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def softmax_xent(x2: jnp.ndarray,        # (N, D) final hidden states
                 w_head: jnp.ndarray,    # (D, V) untied output head
                 targets: jnp.ndarray,   # (N,) int32
                 chunk: int = 51200,
                 fwd_chunk: Optional[int] = None) -> jnp.ndarray:
    """Per-token negative log-likelihood (N,) fp32.

    ``chunk`` drives the BACKWARD's recompute granularity; ``fwd_chunk``
    (defaults to ``chunk``) the forward's. They are split because their
    trade-offs differ: the backward is three near-peak matmuls whose
    chunking only adds W re-reads, while the forward's live fp32 logits
    temp (N, fwd_chunk) is pure HBM traffic the online logsumexp can
    shrink."""
    nll, _ = _xent_fwd_impl(x2, w_head, targets, fwd_chunk or chunk)
    return nll


def _use_pallas_fwd(N, D, V) -> bool:
    """Opt-in (BLLM_XENT_PALLAS=1): the pallas forward streams the vocab
    through VMEM so the (N, Vp) fp32 logits temp (1.6GB at GPT2-124M bs8)
    never exists — but measured DEAD-EVEN on the headline (97.42k vs
    97.41k tok/s, r5 A/B): XLA overlaps the logits HBM traffic with
    compute. Kept opt-in for memory-constrained shapes rather than
    default: it buys HBM headroom, not steady-state speed."""
    import os

    if os.environ.get("BLLM_XENT_PALLAS", "0") != "1":
        return False
    if jax.default_backend() != "tpu" or len(jax.devices()) != 1:
        # pallas_call is not auto-partitioned by GSPMD: on a sharded mesh
        # it would force gathering the (N, D)/(D, V) operands, and the
        # VMEM gate below would be evaluated on GLOBAL shapes anyway —
        # single-device only (a shard_map wrapper could lift this)
        return False
    from building_llm_from_scratch_tpu.ops.xent_fwd_pallas import (
        supports_shape,
    )

    return supports_shape(N, D, V)


def _xent_fwd_impl(x2, w_head, targets, chunk):
    N, D = x2.shape
    V = w_head.shape[1]
    if _use_pallas_fwd(N, D, V):
        # pallas forward (ops/xent_fwd_pallas.py): vocabulary streamed
        # through VMEM, fp32 logits never reach HBM
        from building_llm_from_scratch_tpu.ops.xent_fwd_pallas import (
            xent_fwd,
        )

        return xent_fwd(x2, w_head, targets)
    wp, n_chunks = _pad_vocab(w_head, chunk)

    def body(carry, c):
        m, s, tl = carry
        logits = _chunk_logits(x2, wp, c, chunk, V)
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
        s = s * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(logits - m_new[:, None]), axis=-1)
        local = targets.astype(jnp.int32) - c * chunk
        in_range = (local >= 0) & (local < chunk)
        picked = jnp.take_along_axis(
            logits, jnp.clip(local, 0, chunk - 1)[:, None], axis=-1)[:, 0]
        tl = jnp.where(in_range, picked, tl)
        return (m_new, s, tl), None

    init = (jnp.full((N,), _NEG_BIG, jnp.float32),
            jnp.zeros((N,), jnp.float32),
            jnp.full((N,), _NEG_BIG, jnp.float32))
    (m, s, tl), _ = jax.lax.scan(body, init, jnp.arange(n_chunks))
    lse = m + jnp.log(s)
    return lse - tl, lse


def _xent_fwd(x2, w_head, targets, chunk, fwd_chunk):
    nll, lse = _xent_fwd_impl(x2, w_head, targets, fwd_chunk or chunk)
    return nll, (x2, w_head, targets, lse)


def _xent_bwd(chunk, fwd_chunk, res, g):
    """g: (N,) cotangent of the per-token nll."""
    x2, w_head, targets, lse = res
    N, D = x2.shape
    V = w_head.shape[1]
    wp, n_chunks = _pad_vocab(w_head, chunk)
    gx = g.astype(jnp.float32)

    def body(carry, c):
        dx, dwp = carry
        logits = _chunk_logits(x2, wp, c, chunk, V)
        p = jnp.exp(logits - lse[:, None])            # softmax over V
        local = targets.astype(jnp.int32) - c * chunk
        onehot = (local[:, None] == jnp.arange(chunk)[None, :])
        dl = (p - onehot.astype(jnp.float32)) * gx[:, None]
        dl = dl.astype(x2.dtype)
        wc = jax.lax.dynamic_slice(wp, (0, c * chunk), (D, chunk))
        dx = dx + jnp.einsum("nc,dc->nd", dl, wc,
                             preferred_element_type=jnp.float32)
        dwc = jnp.einsum("nd,nc->dc", x2, dl,
                         preferred_element_type=jnp.float32)
        dwp = jax.lax.dynamic_update_slice(
            dwp, dwc.astype(dwp.dtype), (0, c * chunk))
        return (dx, dwp), None

    init = (jnp.zeros((N, D), jnp.float32),
            jnp.zeros(wp.shape, w_head.dtype))
    (dx, dwp), _ = jax.lax.scan(body, init, jnp.arange(n_chunks))
    return dx.astype(x2.dtype), dwp[:, :V], None


softmax_xent.defvjp(_xent_fwd, _xent_bwd)


def _default_chunk() -> int:
    import os

    return int(os.environ.get("BLLM_XENT_CHUNK", "51200"))


def _default_fwd_chunk() -> Optional[int]:
    import os

    v = os.environ.get("BLLM_XENT_FWD_CHUNK")
    return int(v) if v else None


def fused_cross_entropy_loss(hidden: jnp.ndarray,      # (B, T, D)
                             w_head: jnp.ndarray,      # (D, V)
                             targets: jnp.ndarray,     # (B, T)
                             weights: Optional[jnp.ndarray] = None,
                             chunk: Optional[int] = None) -> jnp.ndarray:
    """Weighted token-mean CE — same semantics as
    training.train_step.cross_entropy_loss(forward(...), targets, weights)
    without ever materializing (B, T, V) fp32 logits."""
    # the env fwd-chunk default applies ONLY when the caller did not pass
    # an explicit chunk — an explicit bound must always win
    fwd_chunk = _default_fwd_chunk() if chunk is None else None
    if chunk is None:
        chunk = _default_chunk()
    B, T, D = hidden.shape
    nll = softmax_xent(hidden.reshape(B * T, D), w_head,
                       targets.reshape(B * T).astype(jnp.int32), chunk,
                       fwd_chunk)
    nll = nll.reshape(B, T)
    if weights is None:
        return jnp.mean(nll)
    w = weights.astype(jnp.float32)
    return (nll * w).sum() / jnp.maximum(w.sum(), 1.0)


def fused_cross_entropy_sums(hidden, w_head, targets, weights,
                             chunk: Optional[int] = None):
    """(weighted nll sum, weight sum) — the cross-shard-psum variant
    (mirrors train_step.cross_entropy_sums)."""
    fwd_chunk = _default_fwd_chunk() if chunk is None else None
    if chunk is None:
        chunk = _default_chunk()
    B, T, D = hidden.shape
    nll = softmax_xent(hidden.reshape(B * T, D), w_head,
                       targets.reshape(B * T).astype(jnp.int32), chunk,
                       fwd_chunk)
    nll = nll.reshape(B, T)
    if weights is None:
        weights = jnp.ones_like(nll)
    w = weights.astype(jnp.float32)
    return (nll * w).sum(), w.sum()
