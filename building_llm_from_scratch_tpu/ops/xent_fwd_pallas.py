"""Pallas TPU forward for the chunked softmax cross-entropy.

The XLA forward of ops/softmax_xent.py materializes the full (N, Vp) fp32
logits in HBM (1.6GB for GPT2-124M bs8) and re-reads them for the
logsumexp — ~7.8ms of the 80ms headline step (r5 profile: logits fusion
3.5ms + exponential_reduce 2.2ms + ancillary traffic). This kernel streams
the vocabulary in lane-chunks through ONE grid pass: the (N, D) hidden
block stays resident in VMEM (constant index map — pallas fetches it
once), each grid step matmuls one (D, BV) weight chunk, applies the online
logsumexp update and the target-logit pick entirely in VMEM, and only the
(N,) lse / target-logit vectors ever reach HBM.

Backward stays the XLA implementation in softmax_xent.py (its three
matmuls already run at ~87% MXU utilization).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_BIG = -1e30
# accumulators are (N, LANES) lane-replicated (mosaic wants 2D tiles);
# 128 lanes keeps the reductions layout-native
_LANES = 128


def _kernel(x_ref, w_ref, tgt_ref, lse_ref, tl_ref, m_ref, s_ref, *,
            bv: int, V: int):
    c = pl.program_id(0)
    n_chunks = pl.num_programs(0)

    @pl.when(c == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_BIG)
        s_ref[...] = jnp.zeros_like(s_ref)
        tl_ref[...] = jnp.full_like(tl_ref, _NEG_BIG)

    x = x_ref[...]                                    # (N, D) bf16
    w = w_ref[...]                                    # (D, BV)
    s = jax.lax.dot(x, w, preferred_element_type=jnp.float32)  # (N, BV)
    col = c * bv + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    s = jnp.where(col < V, s, _NEG_BIG)               # mask padded vocab

    m_old = m_ref[:, :1]                              # (N, 1)
    m_new = jnp.maximum(m_old, jnp.max(s, axis=-1, keepdims=True))
    corr = jnp.exp(m_old - m_new)
    s_sum = jnp.sum(jnp.exp(s - m_new), axis=-1, keepdims=True)
    s_ref[...] = jnp.broadcast_to(s_ref[:, :1] * corr + s_sum,
                                  s_ref.shape)
    m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)

    # target logit: rows whose target falls in this chunk pick it up
    tgt = tgt_ref[:, :1]                              # (N, 1) int32
    local = tgt - c * bv
    in_chunk = (local >= 0) & (local < bv)
    lane = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    picked = jnp.sum(jnp.where(lane == local, s, 0.0), axis=-1,
                     keepdims=True)
    tl_ref[...] = jnp.where(
        jnp.broadcast_to(in_chunk, tl_ref.shape),
        jnp.broadcast_to(picked, tl_ref.shape), tl_ref[...])

    @pl.when(c == n_chunks - 1)
    def _finish():
        lse_ref[...] = m_ref[...] + jnp.log(s_ref[...])


def xent_fwd(x2: jnp.ndarray,       # (N, D) hidden states
             w_head: jnp.ndarray,   # (D, V)
             targets: jnp.ndarray,  # (N,) int32
             bv: int = 512):
    """(nll (N,), lse (N,)) fp32 — same math as softmax_xent's forward."""
    N, D = x2.shape
    V = w_head.shape[1]
    n_chunks = -(-V // bv)
    Vp = n_chunks * bv
    if Vp != V:
        w_head = jnp.pad(w_head, ((0, 0), (0, Vp - V)))
    tgt2 = jnp.broadcast_to(targets.astype(jnp.int32)[:, None],
                            (N, _LANES))

    lse, tl = pl.pallas_call(
        functools.partial(_kernel, bv=bv, V=V),
        grid=(n_chunks,),
        in_specs=[
            pl.BlockSpec((N, D), lambda c: (0, 0)),        # resident
            pl.BlockSpec((D, bv), lambda c: (0, c)),       # streamed
            pl.BlockSpec((N, _LANES), lambda c: (0, 0)),   # resident
        ],
        out_specs=[
            pl.BlockSpec((N, _LANES), lambda c: (0, 0)),
            pl.BlockSpec((N, _LANES), lambda c: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((N, _LANES), jnp.float32),  # lse
            jax.ShapeDtypeStruct((N, _LANES), jnp.float32),  # target logit
        ],
        scratch_shapes=[
            pltpu.VMEM((N, _LANES), jnp.float32),            # running max
            pltpu.VMEM((N, _LANES), jnp.float32),            # running sum
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
    )(x2, w_head, tgt2)
    lse1 = lse[:, 0]
    return lse1 - tl[:, 0], lse1


def supports_shape(N: int, D: int, V: int, bv: int = 512) -> bool:
    """VMEM budget: resident x (N*D bf16) + logits chunk (N*bv f32) +
    4 accumulator panes (N*128 f32) + weight chunk; gate well under the
    16MB-per-buffer / ~128MB total VMEM of v5e."""
    x_mb = N * D * 2 / 1e6
    s_mb = N * bv * 4 / 1e6
    acc_mb = 4 * N * _LANES * 4 / 1e6
    return (N % 8 == 0 and D % 128 == 0 and N >= 128
            and x_mb + s_mb + acc_mb + D * bv * 2 / 1e6 < 90)
