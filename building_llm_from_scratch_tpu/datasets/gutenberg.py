"""Gutenberg corpus preparation.

Parity with ``/root/reference/Datasets/Gutenberg/prepare_dataset.py:9-61``
and ``setup.sh``: walk a directory of raw Project Gutenberg ``.txt`` files,
keep predominantly-English texts (ASCII-ratio test), strip the PG license
boilerplate, squeeze blank-line runs, and pack everything into a few large
``combined_N.txt`` files (<= ``max_size_mb`` each) joined by the
``<|endoftext|>`` separator — the exact input shape ``--dataset gutenberg``
pretraining consumes.

Differences from the reference:
  - ``strip_gutenberg_boilerplate`` is implemented here (the reference
    imports ``gutenberg.src.cleanup.strip_headers`` from the cloned pgcorpus
    repo, setup.sh:27) — same marker-scanning behavior, no external clone;
  - the download step is a plain-urllib hook (``download_archive``) instead
    of a hardcoded Google-Drive ``gdown`` call with a placeholder file id
    (download.py:4 ships ``'GIVE YOUR FILE ID'``);
  - files stream one at a time — packing never holds more than one book
    plus the current output buffer in memory.
"""

from __future__ import annotations

import argparse
import os
import re
import zipfile
from typing import Iterable, List, Optional

from building_llm_from_scratch_tpu.utils.logging import setup_logger

logger = setup_logger(__name__)

EOT = "<|endoftext|>"

# Project Gutenberg boilerplate delimiters. The opening marker ends the
# license header; the closing marker starts the license footer. Older files
# use the "SMALL PRINT" legalese block instead.
_START_MARKERS = (
    "*** START OF", "***START OF", "*END*THE SMALL PRINT",
    "*END THE SMALL PRINT",
)
_END_MARKERS = (
    "*** END OF", "***END OF", "End of the Project Gutenberg",
    "End of The Project Gutenberg", "End of Project Gutenberg",
)


def is_english(text: str, threshold: float = 0.9) -> bool:
    """ASCII-ratio language filter (reference prepare_dataset.py:9-11)."""
    if not text:
        return False
    ascii_chars = sum(1 for c in text if ord(c) < 128)
    return ascii_chars / len(text) > threshold


def strip_gutenberg_boilerplate(text: str) -> str:
    """Cut the PG license header/footer around the actual book text.

    Scans for the standard delimiter lines (same convention the pgcorpus
    ``strip_headers`` relies on); if a marker is absent the corresponding
    side is left untouched, so non-PG text passes through unchanged.
    """
    lines = text.splitlines(keepends=True)
    start = 0
    end = len(lines)
    # the opening marker legitimately appears only near the top; scanning
    # the whole file could hit quoted markers inside the book text
    # first start marker / last end marker win (pgcorpus strip_headers
    # behavior): without the breaks, a quoted marker line inside the book
    # text would silently truncate real content
    for i, line in enumerate(lines[:600]):
        if any(m in line for m in _START_MARKERS):
            start = i + 1
            break
    for i in range(len(lines) - 1, max(start, len(lines) - 600) - 1, -1):
        if any(m in lines[i] for m in _END_MARKERS):
            end = i
            break
    return "".join(lines[start:end])


def _read_text(path: str, fallback_encoding: str = "latin1") -> str:
    """UTF-8 first, latin-1 fallback (reference prepare_dataset.py:25-31)."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            return f.read()
    except UnicodeDecodeError:
        logger.warning("UnicodeDecodeError: using %s for %s",
                       fallback_encoding, path)
        with open(path, "r", encoding=fallback_encoding) as f:
            return f.read()


def clean_book(text: str) -> str:
    """Boilerplate strip + blank-line squeeze (prepare_dataset.py:37-38)."""
    text = strip_gutenberg_boilerplate(text)
    return re.sub(r"\n\s*\n", "\n\n", text)


def pack_files(file_paths: Iterable[str], target_dir: str,
               max_size_mb: int = 500, separator: str = EOT) -> int:
    """Pack cleaned books into ``combined_N.txt`` files of <= max_size_mb.

    Returns the number of combined files written (reference
    prepare_dataset.py:14-61). Non-English books are skipped; books are
    joined by ``separator`` so the pretrain loader's document-boundary
    handling sees the same token the reference trains with.
    """
    os.makedirs(target_dir, exist_ok=True)
    max_bytes = max_size_mb * 1024 * 1024
    sep_bytes = len(separator.encode("utf-8"))

    counter = 0
    out = None
    out_size = 0

    def open_next():
        nonlocal counter, out, out_size
        counter += 1
        path = os.path.join(target_dir, f"combined_{counter}.txt")
        out = open(path, "w", encoding="utf-8")
        out_size = 0

    try:
        for path in file_paths:
            content = _read_text(path)
            if not is_english(content):
                logger.info("Skipping non-English file: %s", path)
                continue
            content = clean_book(content)
            size = len(content.encode("utf-8"))
            if out is None:
                open_next()
            elif out_size + sep_bytes + size > max_bytes:
                out.close()
                open_next()
            if out_size > 0:
                out.write(separator)
                out_size += sep_bytes
            out.write(content)
            out_size += size
    finally:
        if out is not None:
            out.close()
    return counter


def find_txt_files(data_dir: str) -> List[str]:
    """All ``.txt`` files under ``data_dir``, recursively, sorted — the
    same discovery rule the training entry point uses
    (utils/io.discover_training_files)."""
    from building_llm_from_scratch_tpu.utils.io import (
        discover_training_files,
    )

    return discover_training_files(data_dir)[0]


def download_archive(url: str, output_path: str,
                     extract_dir: Optional[str] = None) -> str:
    """Fetch a corpus archive and optionally unzip it (the step
    setup.sh:12-21 performs with gdown + unzip). Skips the download when
    ``output_path`` already exists (cache-if-exists, like the Alpaca
    fetch)."""
    if not os.path.exists(output_path):
        from urllib import request

        logger.info("Downloading %s -> %s", url, output_path)
        tmp = output_path + ".tmp"
        with request.urlopen(url) as resp, open(tmp, "wb") as f:
            f.write(resp.read())
        # rename-on-success: an interrupted download must not poison the
        # cache-if-exists check (same pattern as alpaca.fetch_alpaca)
        os.replace(tmp, output_path)
    else:
        logger.info("Archive already exists at %s", output_path)
    if extract_dir is not None:
        if not zipfile.is_zipfile(output_path):
            raise ValueError(
                f"{output_path} is not a zip archive; cannot extract to "
                f"{extract_dir} (delete it to re-download)")
        with zipfile.ZipFile(output_path) as zf:
            zf.extractall(extract_dir)
        return extract_dir
    return output_path


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="Prepare Gutenberg text files for LLM pretraining")
    parser.add_argument("--data_dir", type=str, required=True,
                        help="Input directory containing raw .txt files.")
    parser.add_argument("--output_dir", type=str, default="data",
                        help="Output directory for combined files.")
    parser.add_argument("--max_size_mb", type=int, default=500,
                        help="Maximum size (MB) of each combined file.")
    parser.add_argument("--archive_url", type=str, default=None,
                        help="Optional corpus archive URL to download and "
                             "unzip into --data_dir first.")
    args = parser.parse_args(argv)

    if args.archive_url:
        os.makedirs(args.data_dir, exist_ok=True)
        download_archive(args.archive_url,
                         os.path.join(args.data_dir, "corpus.zip"),
                         extract_dir=args.data_dir)
    files = find_txt_files(args.data_dir)
    logger.info("Found %d text file(s) to process.", len(files))
    n = pack_files(files, args.output_dir, max_size_mb=args.max_size_mb)
    logger.info("%d file(s) saved in: %s", n, os.path.abspath(args.output_dir))
    return n


if __name__ == "__main__":
    main()
