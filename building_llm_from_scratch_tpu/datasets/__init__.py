"""Dataset acquisition (reference layer L7, ``Datasets/``).

Corpus bootstrap for the two advertised workloads: Gutenberg pretraining
(``datasets/gutenberg.py``) and Alpaca instruction finetuning
(``datasets/alpaca.py``). Each module is runnable:

    python -m building_llm_from_scratch_tpu.datasets.alpaca --data_dir data
    python -m building_llm_from_scratch_tpu.datasets.gutenberg \
        --data_dir raw_txt --output_dir data
"""

from building_llm_from_scratch_tpu.datasets.alpaca import fetch_alpaca
from building_llm_from_scratch_tpu.datasets.gutenberg import (
    is_english,
    pack_files,
    strip_gutenberg_boilerplate,
)

__all__ = [
    "fetch_alpaca",
    "is_english",
    "pack_files",
    "strip_gutenberg_boilerplate",
]
