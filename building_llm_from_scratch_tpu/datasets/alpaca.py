"""Alpaca instruction-dataset fetch.

Parity with ``/root/reference/Datasets/Alpaca/download.py:5-44``: download
the Stanford Alpaca JSON once (cache-if-exists), validate it parses, and
report the record count. The output file is what ``--finetune --dataset
alpaca`` consumes.
"""

from __future__ import annotations

import argparse
import json
import os
from typing import List

from building_llm_from_scratch_tpu.utils.logging import setup_logger

logger = setup_logger(__name__)

ALPACA_URL = ("https://raw.githubusercontent.com/tatsu-lab/stanford_alpaca/"
              "main/alpaca_data.json")
DEFAULT_FILENAME = "instruction-data-alpaca.json"


def fetch_alpaca(file_path: str, url: str = ALPACA_URL) -> List[dict]:
    """Download-if-missing + load (reference download.py:19-37).

    Returns the parsed records so callers can chain straight into the
    instruction loader; raises on malformed JSON instead of caching a bad
    download (the temp-file rename keeps a failed fetch from poisoning the
    cache).
    """
    if not os.path.exists(file_path):
        from urllib import request

        logger.info("Downloading from %s ...", url)
        with request.urlopen(url) as resp:
            text = resp.read().decode("utf-8")
        data = json.loads(text)             # validate BEFORE caching
        tmp = file_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(text)
        os.replace(tmp, file_path)
        logger.info("Saved to %s", file_path)
    else:
        logger.info("File already exists at %s", file_path)
        with open(file_path, "r", encoding="utf-8") as f:
            data = json.load(f)
    logger.info("Loaded %d records from %s", len(data), file_path)
    return data


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="Fetch the Stanford Alpaca instruction dataset")
    parser.add_argument("--data_dir", type=str, default="data",
                        help="Directory to place the dataset in.")
    parser.add_argument("--url", type=str, default=ALPACA_URL)
    args = parser.parse_args(argv)

    os.makedirs(args.data_dir, exist_ok=True)
    path = os.path.join(args.data_dir, DEFAULT_FILENAME)
    data = fetch_alpaca(path, url=args.url)
    return path, len(data)


if __name__ == "__main__":
    main()
