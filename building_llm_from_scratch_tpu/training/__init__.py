"""Training engine (reference: train.py, build_components.py optimizer tier)."""

from building_llm_from_scratch_tpu.training.optim import (
    build_optimizer,
    warmup_cosine_schedule,
)
from building_llm_from_scratch_tpu.training.precision import (
    POLICIES,
    PrecisionPolicy,
    cast_floating,
    get_policy,
)
from building_llm_from_scratch_tpu.training.train_step import (
    cross_entropy_loss,
    cross_entropy_sums,
    init_train_state,
    make_eval_step,
    make_sharded_train_step,
    make_train_step,
)
from building_llm_from_scratch_tpu.training.async_checkpoint import (
    AsyncCheckpointer,
)
from building_llm_from_scratch_tpu.training.checkpoint import (
    export_params,
    load_checkpoint,
    load_exported_params,
    save_checkpoint,
    save_checkpoint_gathered,
)
from building_llm_from_scratch_tpu.training.resilience import (
    GracefulStopper,
    LossWatchdog,
    PreemptionStop,
    TrainingDivergedError,
    find_latest_valid_checkpoint,
    prune_checkpoints,
    resolve_resume,
    validate_checkpoint,
)
from building_llm_from_scratch_tpu.training.lora_fusion import (
    FinetuneJob,
    FusedLoRATrainer,
    make_fused_train_step,
)
from building_llm_from_scratch_tpu.training.trainer import Trainer

__all__ = [
    "FinetuneJob",
    "FusedLoRATrainer",
    "make_fused_train_step",
    "build_optimizer",
    "warmup_cosine_schedule",
    "POLICIES",
    "PrecisionPolicy",
    "cast_floating",
    "get_policy",
    "AsyncCheckpointer",
    "cross_entropy_loss",
    "cross_entropy_sums",
    "init_train_state",
    "make_eval_step",
    "make_sharded_train_step",
    "make_train_step",
    "export_params",
    "load_checkpoint",
    "load_exported_params",
    "save_checkpoint",
    "save_checkpoint_gathered",
    "GracefulStopper",
    "LossWatchdog",
    "PreemptionStop",
    "TrainingDivergedError",
    "find_latest_valid_checkpoint",
    "prune_checkpoints",
    "resolve_resume",
    "validate_checkpoint",
    "Trainer",
]
