"""Fused multi-LoRA finetuning: k tenants' adapters through ONE base
forward/backward, with continuous train→deploy.

The serving tier (PR 9) multiplexes thousands of adapters on one resident
base model, but every adapter was still TRAINED in its own solo run — k
tenants cost k× the dominant FLOPs (and k× the compiles, k× the dispatch).
This module fuses the fleet (LoRAFusion / FLoRA, PAPERS.md): k jobs'
adapters live in a stacked ``(n_jobs, ...)`` device-resident pool — the
same stacked layout as ``serving.adapters.AdapterRegistry`` — their Alpaca
batches stack along a jobs axis with per-row ``job_ids`` as traced data,
and ONE jitted train step runs them all:

  - the frozen base forward/backward is computed once over the stacked
    batch; per-job LoRA deltas ride the existing BGMV gather + einsum
    (``models/lora.apply_lora`` via ``forward(..., adapter=)``) — and
    because the base is frozen, the backward never materializes dense
    weight gradients (the merged solo path pays them as the ``merge_lora``
    chain's intermediate), so fused FLOPs/token ~ 4·N instead of 6·N;
  - gradients flow ONLY to the stacked adapter leaves (the gather's
    transpose scatter-adds each row's grads into its own pool row — jobs
    are mathematically isolated because the base is frozen and the
    per-job losses are additive);
  - optimizer state is per-job: stacked AdamW moments, per-job step
    counts, per-job warmup+cosine LR over each job's OWN horizon (a
    traced ``(J,)`` vector — joining a short job next to a long one never
    recompiles), per-job global-norm clipping (one job's spike cannot cap
    its co-tenants), per-job loss masking (weighted-CE mean per job,
    exactly the solo trainer's semantics);
  - per-job health rides the existing ``obs/health.py`` group machinery:
    the stacked trees ARE a stacked-leading-axis group tree, so
    ``group_health`` returns (J,) grad/param/update norms and first-
    non-finite-JOB localization with no new code;
  - a job whose gradients go non-finite is skipped in-graph the same step
    (params/moments/count kept) and retired by the host at the next
    metrics flush — co-trained jobs' trajectories are bit-identical to a
    run where the sick job never misbehaved (test-pinned, mirroring the
    serving fault-isolation tests).

Job identity is DATA and job count is static capacity: jobs hot-join free
slots and finish early without recompiling — the one-compiled-program
invariant, enforced by a frozen ``obs/compile.CompileWatcher`` (label
``fused_step``) and the GL02x graft-lint rules. A finished job exports
through the existing ``models/lora.save_adapter`` artifact path (atomic
tmp+rename, base-config fingerprint) the moment IT finishes — slow jobs
never block fast tenants' deployments — and can hot-load straight into a
live ``AdapterRegistry`` (``deploy=``), closing the loop: tenant uploads
data, gets a served adapter, all on one resident base model.

CLI: ``--mode finetune_fleet --fleet_jobs a=a.json,b=b.json`` (main.py
dispatches to ``run_finetune_fleet``). Proof rides the perf observatory:
``bench.py lora_fusion`` A/Bs k sequential solo finetunes against one
fused run; ``micro_lora_fusion`` structurally gates the fused step's HLO
in CI (PERF_BASELINE.json).

Known cost (documented, ROADMAP follow-up): the per-row gather
materializes each job's A/B once per ROW (``rows_per_job``-fold
duplication — rows of one job share an adapter). Fine at current slot
counts; large capacities want slot-aligned application over a
``(J, R, T)`` reshape, which applies each adapter once.
"""

from __future__ import annotations

import collections
import dataclasses
import os
import re
import time
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from building_llm_from_scratch_tpu.configs import ModelConfig
from building_llm_from_scratch_tpu.data.instruct import (
    InstructionDataset,
    collate_batch,
)
from building_llm_from_scratch_tpu.models.lora import (
    adapter_fingerprint,
    count_lora_params,
    init_lora_params,
    save_adapter,
)
from building_llm_from_scratch_tpu.models.transformer import forward
from building_llm_from_scratch_tpu.obs.compile import CompileWatcher
from building_llm_from_scratch_tpu.obs.health import group_health, group_norms
from building_llm_from_scratch_tpu.obs.metrics import get_metrics
from building_llm_from_scratch_tpu.utils.logging import setup_logger

logger = setup_logger(__name__)

Params = Dict[str, Any]

#: free slots are named "slot<N>" in per-job telemetry (slot_names);
#: job names matching it are refused at add_job so a tenant can never be
#: mistaken for an empty slot in health rows or the renderer
_FREE_SLOT_RE = re.compile(r"slot\d+")

#: metrics arrays the host defers-then-fetches per step (obs discipline:
#: DMAs posted at append time, converted only at flush cadence). Only
#: what _flush actually reads — update_norm rides the health bundle;
#: weights feeds the per-job supervised-token ledger (a job that never
#: saw a supervised token must not export).
_FETCHED_METRICS = ("loss", "grad_norm", "lr", "finite", "weights")


def stack_fleet_batch(job_batches, *, capacity: int, scaling: float,
                      horizon=1) -> Dict[str, np.ndarray]:
    """Stack per-slot ``{"inputs","targets","weights"}`` row-blocks into
    ONE fused batch: slot j's rows occupy ``[j*R, (j+1)*R)`` with
    ``job_ids = j``; a ``None`` entry (a free slot) and slots past
    ``len(job_batches)`` are inactive padding (ids −1, zero rows).
    ``horizon`` is an int or a per-slot sequence. THE one fused-batch
    constructor — the engine's ``_build_batch``, the benches and the
    tests all delegate here, so the step's batch contract cannot drift
    between them."""
    entries = list(job_batches)
    if len(entries) > capacity:
        raise ValueError(f"{len(entries)} job batches exceed "
                         f"capacity {capacity}")
    first = next((e for e in entries if e is not None), None)
    if first is None:
        raise ValueError("stack_fleet_batch needs at least one job batch")
    R, T = first["inputs"].shape
    J = int(capacity)
    horizons = np.maximum(
        1, np.broadcast_to(np.asarray(horizon, np.int32), (J,)))
    batch = {
        "inputs": np.zeros((J * R, T), np.int32),
        "targets": np.zeros((J * R, T), np.int32),
        "weights": np.zeros((J * R, T), np.float32),
        "job_ids": np.full((J * R,), -1, np.int32),
        "active": np.zeros((J,), bool),
        "scaling": np.full((J,), scaling, np.float32),
        "horizon": horizons.astype(np.int32),
    }
    for j, jb in enumerate(entries):
        if jb is None:
            continue
        sl = slice(j * R, (j + 1) * R)
        batch["inputs"][sl] = jb["inputs"]
        batch["targets"][sl] = jb["targets"]
        batch["weights"][sl] = jb["weights"]
        batch["job_ids"][sl] = j
        batch["active"][j] = True
    return batch


def fleet_lr_schedule(counts: jnp.ndarray, horizons: jnp.ndarray, *,
                      peak_lr: float, initial_lr: float, min_lr: float,
                      warmup_steps: int) -> jnp.ndarray:
    """Vectorized warmup+cosine LR: ``training/optim.warmup_cosine_
    schedule`` elementwise over per-job step counts with per-job horizons
    as TRACED data — k jobs with k different dataset sizes share one
    compiled step. ``counts`` is each job's pre-increment optimizer count
    (optax ``scale_by_schedule`` semantics: the schedule sees the count
    before the step increments it)."""
    warmup = max(1, int(warmup_steps))
    step = counts.astype(jnp.float32) + 1.0        # pre-incremented step
    warm = initial_lr + step * (peak_lr - initial_lr) / warmup
    denom = jnp.maximum(1.0, horizons.astype(jnp.float32) - warmup)
    progress = (step - warmup) / denom
    cosine = min_lr + (peak_lr - min_lr) * 0.5 * (
        1.0 + jnp.cos(jnp.pi * progress))
    return jnp.where(step < warmup, warm, cosine)


def init_fleet_state(cfg: ModelConfig, base_params: Params, *,
                     capacity: int, rank: int, rng: jax.Array) -> Params:
    """The fused step's donated state: a zeroed stacked ``(J, ...)``
    adapter pool (rows are initialized per-job at admission), stacked
    AdamW moments, per-job int32 step counts, the frozen base, a fused
    step counter and the dropout RNG. Plain pytree — donates, shards and
    checkpoints like any train state."""
    template = init_lora_params(cfg, base_params, jax.random.PRNGKey(0),
                                rank=rank)
    pool = jax.tree_util.tree_map(
        lambda a: jnp.zeros((capacity,) + a.shape, a.dtype), template)
    zeros_like_pool = lambda: jax.tree_util.tree_map(jnp.zeros_like, pool)
    # the first donated step consumes these buffers — base_params may be
    # the caller's live tree (Trainer learned this in round 2)
    frozen = jax.tree_util.tree_map(
        lambda x: x.copy() if isinstance(x, jax.Array) else jnp.asarray(x),
        base_params)
    return {
        "trainable": pool,
        "frozen": frozen,
        "mu": zeros_like_pool(),
        "nu": zeros_like_pool(),
        "counts": jnp.zeros((capacity,), jnp.int32),
        "step": jnp.zeros((), jnp.int32),
        "rng": rng,
    }


def make_fused_train_step(cfg: ModelConfig, *, capacity: int,
                          peak_lr: float = 5e-4, initial_lr: float = 1e-5,
                          min_lr: float = 1e-6, warmup_steps: int = 10,
                          weight_decay: float = 0.1,
                          grad_clip_norm: float = 1.0,
                          b1: float = 0.9, b2: float = 0.999,
                          eps: float = 1e-8,
                          jit: bool = True,
                          aligned: bool = True) -> Callable:
    """Build ``fused_step(state, batch) -> (state, metrics)``.

    ``batch``: ``inputs``/``targets``/``weights`` (B, T) stacked across
    jobs, ``job_ids`` (B,) int32 (−1 = padding row: gather clamps, scale
    zeroes, loss weight zero), ``active`` (J,) bool, ``scaling`` (J,)
    fp32 (alpha/rank per slot), ``horizon`` (J,) int32 (per-job schedule
    total). All per-job identity is traced data; the ONE compiled program
    serves every join/finish/retire.

    The optimizer reproduces the solo chain
    ``clip_by_global_norm -> scale_by_adam -> add_decayed_weights ->
    scale_by_learning_rate`` per job: clipping scopes to the job's own
    adapter tree (exactly the solo trainer's global norm), bias
    correction uses per-job counts, and a job whose loss or gradient
    norm is non-finite keeps its params/moments/count untouched this
    step (the in-graph half of fault isolation; the host retires it at
    the next flush).

    ``aligned`` (default): apply each job's adapter ONCE against its
    contiguous row block via the ``(J, R*T)`` reshape
    (``models/lora.aligned_lora_delta``) — the fused batch is always
    slot-aligned (``stack_fleet_batch`` is THE constructor), so the
    per-row gather's rows_per_job-fold A/B duplication (and its
    scatter-add backward) buys nothing here. ``aligned=False`` keeps
    the historical gather path (the serving-engine math; the k=3
    aligned-vs-gather parity test pins the two equal)."""
    J = int(capacity)

    def bcast(vec: jnp.ndarray, leaf: jnp.ndarray) -> jnp.ndarray:
        return vec.reshape((J,) + (1,) * (leaf.ndim - 1))

    def fused_step(state: Params, batch: Dict[str, jnp.ndarray]):
        step_rng = jax.random.fold_in(state["rng"], state["step"])
        ids = batch["job_ids"].astype(jnp.int32)
        active = batch["active"]
        # belt + suspenders: an inactive slot's scaling is zeroed even if
        # a stale row id slipped into the batch
        scaling = jnp.where(active, batch["scaling"].astype(jnp.float32),
                            0.0)

        def loss_fn(trainable):
            if aligned:
                rows_per_job = batch["inputs"].shape[0] // J
                adapter = {"pool": trainable, "scaling": scaling,
                           "rows_per_job": rows_per_job}
            else:
                adapter = {"pool": trainable, "scaling": scaling,
                           "ids": ids}
            logits = forward(state["frozen"], cfg, batch["inputs"],
                             rng=step_rng,
                             deterministic=(cfg.drop_rate <= 0.0),
                             adapter=adapter)
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            ll = jnp.take_along_axis(
                logp, batch["targets"][..., None].astype(jnp.int32),
                axis=-1)[..., 0]
            w = batch["weights"].astype(jnp.float32)
            # where-masked: a NaN logit in one job's rows must never ride
            # a 0-weight product into another job's sum
            row_nll = -jnp.sum(jnp.where(w > 0, ll * w, 0.0), axis=-1)
            row_w = jnp.sum(w, axis=-1)
            m = (ids[:, None] == jnp.arange(J)[None, :]) & (
                ids >= 0)[:, None]
            nll_j = jnp.sum(jnp.where(m, row_nll[:, None], 0.0), axis=0)
            w_j = jnp.sum(jnp.where(m, row_w[:, None], 0.0), axis=0)
            # per-job weighted mean — the solo trainer's loss, one per job
            loss_j = nll_j / jnp.maximum(w_j, 1.0)
            # summing per-job means gives each job's adapter EXACTLY the
            # gradient of its own loss (the base is frozen; cross terms
            # are structurally zero)
            total = jnp.sum(jnp.where(w_j > 0, loss_j, 0.0))
            return total, (loss_j, w_j)

        (_, (loss_j, w_j)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state["trainable"])

        # per-job pre-clip gradient norms via the health group machinery:
        # a stacked-leading-axis tree IS a group tree (obs/health.py)
        gnorm_j = group_norms({"blocks": grads})
        finite_j = jnp.isfinite(loss_j) & jnp.isfinite(gnorm_j)
        ok_j = active & finite_j

        clip = float(grad_clip_norm)
        cscale = jnp.where(gnorm_j < clip, 1.0,
                           clip / jnp.maximum(gnorm_j, 1e-38))
        gc = jax.tree_util.tree_map(
            lambda g: g * bcast(cscale, g).astype(g.dtype), grads)

        trainable, mu, nu = state["trainable"], state["mu"], state["nu"]
        cc = (state["counts"] + 1).astype(jnp.float32)
        bc1 = 1.0 - jnp.power(b1, cc)
        bc2 = 1.0 - jnp.power(b2, cc)
        mu_new = jax.tree_util.tree_map(
            lambda m, g: b1 * m + (1.0 - b1) * g, mu, gc)
        nu_new = jax.tree_util.tree_map(
            lambda v, g: b2 * v + (1.0 - b2) * (g * g), nu, gc)
        upd = jax.tree_util.tree_map(
            lambda m, v, p: (m / bcast(bc1, m))
            / (jnp.sqrt(v / bcast(bc2, v)) + eps)
            + weight_decay * p,
            mu_new, nu_new, trainable)
        lr_j = fleet_lr_schedule(state["counts"], batch["horizon"],
                                 peak_lr=peak_lr, initial_lr=initial_lr,
                                 min_lr=min_lr, warmup_steps=warmup_steps)
        stepped = jax.tree_util.tree_map(
            lambda p, u: p - (bcast(lr_j, u) * u).astype(p.dtype),
            trainable, upd)

        def select(new, old):
            return jax.tree_util.tree_map(
                lambda n, o: jnp.where(bcast(ok_j, n), n, o), new, old)

        new_trainable = select(stepped, trainable)
        applied = jax.tree_util.tree_map(
            lambda n, o: n - o, new_trainable, trainable)
        health = group_health({"blocks": grads},
                              {"blocks": new_trainable},
                              {"blocks": applied})
        new_state = {
            "trainable": new_trainable,
            "frozen": state["frozen"],
            "mu": select(mu_new, mu),
            "nu": select(nu_new, nu),
            "counts": state["counts"] + ok_j.astype(jnp.int32),
            "step": state["step"] + 1,
            "rng": state["rng"],
        }
        metrics = {
            "loss": loss_j,                        # (J,) per-job means
            "grad_norm": gnorm_j,                  # (J,) pre-clip
            "update_norm": health["update_norm"],  # (J,) post-clip applied
            "lr": lr_j,
            "finite": finite_j,
            "ok": ok_j,
            "weights": w_j,                        # supervised tokens/job
            "health": health,
        }
        return new_state, metrics

    if jit:
        return jax.jit(fused_step, donate_argnums=(0,))
    return fused_step


# ---------------------------------------------------------------------------
# Jobs
# ---------------------------------------------------------------------------

def _plain_items(records: Sequence[Dict[str, str]], tokenizer):
    """Template-free (instruction, output) encoding for tiny-context
    runs: the Alpaca template alone exceeds a --debug model's 16-token
    context, which would zero every loss weight. Same (instr_len, ids)
    item shape as ``InstructionDataset``."""
    items = []
    for entry in records:
        prompt = entry["instruction"] + (
            "\n" + entry["input"] if entry.get("input") else "")
        ids = tokenizer.encode(prompt + " " + entry["output"])
        items.append((len(tokenizer.encode(prompt)), ids))
    return items


@dataclasses.dataclass
class FinetuneJob:
    """One tenant's finetune job: a deterministic per-epoch batch factory
    plus the host-side run state the fleet engine tracks.

    ``make_batches(epoch)`` yields ``(inputs, targets, weights)`` arrays
    of exactly ``rows_per_step`` rows; ``total_steps`` is the job's
    schedule horizon (its cosine decays over its OWN length)."""

    name: str
    make_batches: Callable[[int], Iterator]
    steps_per_epoch: int
    n_epochs: int
    export_path: Optional[str] = None
    n_records: int = 0
    init: Optional[Params] = None      # adapter init override (tests)

    # runtime (engine-owned)
    slot: Optional[int] = None
    steps_done: int = 0
    status: str = "pending"            # pending|running|done|failed
    supervised_tokens: float = 0.0     # Σ loss weights actually trained on
    final_loss: Optional[float] = None
    artifact: Optional[str] = None
    error: Optional[str] = None
    t_admitted: Optional[float] = None
    _epoch: int = dataclasses.field(default=0, repr=False)
    _iter: Optional[Iterator] = dataclasses.field(default=None, repr=False)

    @property
    def total_steps(self) -> int:
        return self.steps_per_epoch * self.n_epochs

    def fast_forward(self, steps_done: int) -> None:
        """Resume positioning: place the batch iterator exactly where a
        job that has consumed ``steps_done`` batches stands — epoch
        ``steps_done // steps_per_epoch``, ``steps_done %
        steps_per_epoch`` batches into it. Batches are a pure function
        of (seed, epoch, index), so the post-resume row sequence is
        bit-identical to the uninterrupted run's (the same cursor
        discipline the PR 1 trainer resume uses)."""
        self.steps_done = int(steps_done)
        self._epoch = self.steps_done // max(self.steps_per_epoch, 1)
        skip = self.steps_done % max(self.steps_per_epoch, 1)
        self._iter = iter(self.make_batches(self._epoch))
        for _ in range(skip):
            next(self._iter)

    def next_rows(self):
        """The job's next ``rows_per_step`` collated rows, cycling epochs
        (each epoch reshuffles deterministically in (seed, epoch)).
        Bounded: a fresh epoch iterator that yields NOTHING raises
        instead of busy-looping the whole fleet (``from_records`` guards
        this, but ``make_batches`` is caller-supplied)."""
        for _ in range(2):
            if self._iter is None:
                self._iter = iter(self.make_batches(self._epoch))
            try:
                return next(self._iter)
            except StopIteration:
                self._epoch += 1
                self._iter = None
        raise ValueError(
            f"job '{self.name}': make_batches(epoch={self._epoch - 1}) "
            "yielded no batches")

    @classmethod
    def from_records(cls, name: str, records: Sequence[Dict[str, str]],
                     tokenizer, *, max_length: int, rows_per_step: int,
                     n_epochs: int, pad_token_id: int, seed: int = 123,
                     style: str = "alpaca",
                     export_path: Optional[str] = None) -> "FinetuneJob":
        """Build a job from Alpaca-format records: encode ONCE, then
        yield shuffled fixed-shape ``collate_batch`` batches per epoch
        (the InstructLoader discipline, per-tenant)."""
        if style == "alpaca":
            ds = InstructionDataset(records, tokenizer)
            items = [ds[i] for i in range(len(ds))]
        elif style == "plain":
            items = _plain_items(records, tokenizer)
        else:
            raise ValueError(f"unknown job style '{style}' "
                             "(alpaca|plain)")
        if len(items) < rows_per_step:
            raise ValueError(
                f"job '{name}': {len(items)} records cannot fill one "
                f"{rows_per_step}-row step")
        steps_per_epoch = len(items) // rows_per_step

        def make_batches(epoch: int):
            order = np.arange(len(items))
            rng = np.random.default_rng(seed + epoch)
            rng.shuffle(order)
            for b in range(steps_per_epoch):
                sl = order[b * rows_per_step:(b + 1) * rows_per_step]
                yield collate_batch([items[i] for i in sl],
                                    pad_token_id=pad_token_id,
                                    allowed_max_length=max_length)

        return cls(name=name, make_batches=make_batches,
                   steps_per_epoch=steps_per_epoch, n_epochs=n_epochs,
                   export_path=export_path, n_records=len(records))


# ---------------------------------------------------------------------------
# The fleet engine
# ---------------------------------------------------------------------------

def fleet_flops_split(cfg: ModelConfig, rank: int) -> Dict[str, float]:
    """Analytic per-token FLOPs split the renderer's fused-finetune
    section reports: the shared frozen-base share (4·N — forward + dx
    backward, no dense dW) vs the per-job adapter share (A/B forward +
    their three backward contractions)."""
    D, F, hd = cfg.emb_dim, cfg.hidden_dim, cfg.head_dim
    Hq, Hkv, T = cfg.n_heads, cfg.n_kv_groups, cfg.context_length
    per_layer = (D * Hq * hd + 2 * D * Hkv * hd + Hq * hd * D
                 + (3 if cfg.activation == "swiglu" else 2) * D * F)
    n_matmul = cfg.n_layers * per_layer + D * cfg.vocab_size
    attn = cfg.n_layers * 2 * 2 * (T / 2) * (Hq * hd) * 3
    base = 4 * n_matmul + attn
    proj_dims = [(D, Hq * hd), (D, Hkv * hd), (D, Hkv * hd), (Hq * hd, D),
                 (D, F), (F, D)]
    if cfg.activation == "swiglu":
        proj_dims.append((D, F))
    adapter_matmul = (cfg.n_layers * sum(i + o for i, o in proj_dims)
                      + (D + cfg.vocab_size)) * rank
    # fwd (2·) + backward dx/dA/dB (~3 more matmul pairs of the same size)
    adapter = 2 * adapter_matmul * 4
    return {"flops_per_token_base": float(base),
            "flops_per_token_adapter": float(adapter)}


class FusedLoRATrainer:
    """Drives k LoRA finetune jobs through one fused train step on one
    resident base model, with per-job export-on-finish and an optional
    hot-load deploy hop into a live ``AdapterRegistry``.

        fleet = FusedLoRATrainer(cfg, params, tokenizer=tok, capacity=4,
                                 rank=8, alpha=16)
        fleet.add_job(FinetuneJob.from_records("tenant-a", records, tok,
                                               ...))
        fleet.run()

    ``capacity`` (job slots) and ``rank`` are static — they size the
    stacked pool the one compiled program closes over; everything that
    changes while the fleet runs (which jobs, their horizons, their
    activity) is data. ``deploy=`` an ``AdapterRegistry`` built on the
    same base to hot-load each artifact the moment it exports."""

    def __init__(self, cfg: ModelConfig, base_params: Params, *,
                 tokenizer=None, capacity: int = 4, rank: int = 8,
                 alpha: float = 16.0, rows_per_job: int = 4,
                 peak_lr: float = 5e-4, initial_lr: float = 1e-5,
                 min_lr: float = 1e-6, warmup_steps: int = 10,
                 weight_decay: float = 0.1, grad_clip_norm: float = 1.0,
                 seed: int = 123, log_every: int = 10,
                 export_dir: Optional[str] = None,
                 deploy=None, compile_telemetry: bool = True,
                 ckpt_dir: Optional[str] = None, save_every: int = 0,
                 keep_ckpts: int = 0, aligned: bool = True):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        if rank < 1:
            raise ValueError("rank must be >= 1")
        if rows_per_job < 1:
            raise ValueError("rows_per_job must be >= 1")
        self.cfg = cfg
        self.tokenizer = tokenizer
        self.capacity = int(capacity)
        self.rank = int(rank)
        self.alpha = float(alpha)
        self.rows_per_job = int(rows_per_job)
        self.seed = int(seed)
        self.log_every = max(1, int(log_every))
        self.export_dir = export_dir
        self.deploy = deploy
        self.jobs: List[FinetuneJob] = []
        self.global_step = 0
        self.tokens_seen = 0
        self.preempted = False
        #: fleet checkpoint/resume (the PR 1 machinery applied to the
        #: stacked pool state — it is a plain pytree): model_pg_<step>
        #: dirs under ckpt_dir, manifest-validated, retention-GC'd
        self.ckpt_dir = ckpt_dir
        self.save_every = int(save_every)
        self.keep_ckpts = int(keep_ckpts)
        self._pending_jobs: collections.deque = collections.deque()
        self._slots: List[Optional[FinetuneJob]] = [None] * self.capacity
        self._pending_metrics: List = []
        self._last_fetched: Optional[Dict[str, Any]] = None
        self._n_admitted = 0
        self.state = init_fleet_state(cfg, base_params,
                                      capacity=self.capacity,
                                      rank=self.rank,
                                      rng=jax.random.PRNGKey(self.seed))
        self._step_fn = make_fused_train_step(
            cfg, capacity=self.capacity, peak_lr=peak_lr,
            initial_lr=initial_lr, min_lr=min_lr,
            warmup_steps=warmup_steps, weight_decay=weight_decay,
            grad_clip_norm=grad_clip_norm, aligned=aligned)
        self._watcher: Optional[CompileWatcher] = None
        if compile_telemetry:
            self._watcher = CompileWatcher(self._step_fn,
                                           label="fused_step")
            self._step_fn = self._watcher
        #: test/fault-injection hook, called after every fused step with
        #: the engine (the serving FaultHooks pattern): lets tests poison
        #: a slot mid-run to prove co-residency isolation
        self.on_step: Optional[Callable[["FusedLoRATrainer"], None]] = None

    # -- introspection -----------------------------------------------------

    @property
    def n_recompiles(self) -> int:
        return self._watcher.n_recompiles if self._watcher is not None \
            else 0

    @property
    def metrics_sink(self):
        return get_metrics()

    def slot_names(self) -> List[str]:
        return [job.name if job is not None else f"slot{j}"
                for j, job in enumerate(self._slots)]

    def stats(self) -> Dict[str, Any]:
        by_status: Dict[str, int] = {}
        for job in self.jobs:
            by_status[job.status] = by_status.get(job.status, 0) + 1
        return {
            "capacity": self.capacity,
            "rank": self.rank,
            "n_jobs": len(self.jobs),
            "jobs": {j.name: {"status": j.status, "steps": j.steps_done,
                              "final_loss": j.final_loss,
                              "artifact": j.artifact}
                     for j in self.jobs},
            "by_status": by_status,
            "fused_steps": self.global_step,
            "tokens_seen": self.tokens_seen,
            "recompiles": self.n_recompiles,
        }

    # -- job lifecycle -----------------------------------------------------

    def add_job(self, job: FinetuneJob) -> FinetuneJob:
        if any(j.name == job.name for j in self.jobs):
            raise ValueError(f"job '{job.name}' already queued")
        if _FREE_SLOT_RE.fullmatch(job.name):
            raise ValueError(
                f"job name '{job.name}' collides with the free-slot "
                "placeholder names in per-job telemetry (slot<N>)")
        if job.total_steps < 1:
            raise ValueError(f"job '{job.name}' has no training steps")
        self.jobs.append(job)
        self._pending_jobs.append(job)
        return job

    def _free_slots(self) -> List[int]:
        return [j for j, s in enumerate(self._slots) if s is None]

    def _running(self) -> List[FinetuneJob]:
        return [s for s in self._slots if s is not None]

    def _admit_pending(self) -> None:
        for j in self._free_slots():
            if not self._pending_jobs:
                break
            job = self._pending_jobs.popleft()
            self._admit(job, j)

    def _admit(self, job: FinetuneJob, slot: int) -> None:
        """Hot-join: initialize the slot's pool row (fresh per-job
        kaiming A / zero B), zero its moments and count — all functional
        row writes, never a recompile."""
        self._n_admitted += 1
        init = job.init
        if init is None:
            init = init_lora_params(
                self.cfg, self.state["frozen"],
                jax.random.PRNGKey(self.seed + 1 + self._n_admitted),
                rank=self.rank)
        idx = jnp.asarray(slot, jnp.int32)
        self.state["trainable"] = jax.tree_util.tree_map(
            lambda pool, leaf: pool.at[idx].set(leaf.astype(pool.dtype)),
            self.state["trainable"], init)
        self._zero_slot_opt(slot)
        job.slot = slot
        job.status = "running"
        job.t_admitted = time.monotonic()
        self._slots[slot] = job
        self.metrics_sink.event(
            "finetune_job_start", step=self.global_step, job_id=job.name,
            slot=slot, total_steps=job.total_steps,
            n_records=job.n_records, n_epochs=job.n_epochs,
            rows_per_step=self.rows_per_job)
        logger.info("Fleet job '%s' joined slot %d (%d steps over %d "
                    "epochs).", job.name, slot, job.total_steps,
                    job.n_epochs)

    def _zero_slot_opt(self, slot: int) -> None:
        idx = jnp.asarray(slot, jnp.int32)
        zero_row = lambda t: jax.tree_util.tree_map(
            lambda a: a.at[idx].set(jnp.zeros(a.shape[1:], a.dtype)), t)
        self.state["mu"] = zero_row(self.state["mu"])
        self.state["nu"] = zero_row(self.state["nu"])
        self.state["counts"] = self.state["counts"].at[idx].set(0)

    def _zero_slot_row(self, slot: int) -> None:
        """Zero a retired slot's pool row: padding rows clamp their
        gather to row 0, and 0 × NaN is NaN — a poisoned row must never
        outlive its job."""
        idx = jnp.asarray(slot, jnp.int32)
        self.state["trainable"] = jax.tree_util.tree_map(
            lambda a: a.at[idx].set(jnp.zeros(a.shape[1:], a.dtype)),
            self.state["trainable"])
        self._zero_slot_opt(slot)

    # -- checkpoint / resume -----------------------------------------------
    #
    # The stacked pool/optimizer state is a plain pytree, so the PR 1
    # checkpoint machinery applies directly: sharded manifest writes
    # (per-shard bytes+sha256), `--resume auto` latest-valid discovery,
    # retention GC. The host-side fleet state (per-job cursors, slot
    # assignments, admission counter) rides the manifest metadata; job
    # batches are a pure function of (seed, epoch, index), so a resumed
    # fleet's per-job loss trajectories continue bit-for-bit
    # (test-pinned, incl. across a real SIGTERM).

    def _ckpt_metadata(self) -> Dict[str, Any]:
        return {
            "global_step": self.global_step,
            "fleet": True,
            "tokens_seen": self.tokens_seen,
            "n_admitted": self._n_admitted,
            "capacity": self.capacity,
            "rank": self.rank,
            "slots": [j.name if j is not None else None
                      for j in self._slots],
            "pending": [j.name for j in self._pending_jobs],
            "jobs": {j.name: {
                "status": j.status, "steps_done": j.steps_done,
                "supervised_tokens": j.supervised_tokens,
                "final_loss": j.final_loss, "artifact": j.artifact,
                "error": j.error} for j in self.jobs},
        }

    def save_checkpoint(self) -> Optional[str]:
        """Write one step-tagged fleet checkpoint (no-op without
        ``ckpt_dir``). Called only at flush boundaries, so no posted
        metric DMAs straddle the save and the job ledgers in the
        metadata are consistent with ``global_step``."""
        if not self.ckpt_dir:
            return None
        from building_llm_from_scratch_tpu.training.checkpoint import (
            save_checkpoint,
        )
        from building_llm_from_scratch_tpu.training.resilience import (
            prune_checkpoints,
        )

        path = os.path.join(self.ckpt_dir,
                            f"model_pg_{self.global_step}")
        save_checkpoint(path, self.state,
                        extra_metadata=self._ckpt_metadata())
        if self.keep_ckpts > 0:
            prune_checkpoints(self.ckpt_dir, self.keep_ckpts)
        return path

    def restore(self, ckpt_path: str) -> "FusedLoRATrainer":
        """Resume from a fleet checkpoint: device state restores through
        ``load_checkpoint`` (manifest-validated), host job state maps
        back by NAME onto the jobs already added via ``add_job`` —
        running jobs re-enter their slots with their batch cursors
        fast-forwarded, finished/failed jobs stay retired, the pending
        queue keeps its order. Jobs added but absent from the
        checkpoint queue as NEW pending tenants (hot-join on a freed
        slot, the fleet's normal admission)."""
        from building_llm_from_scratch_tpu.training.checkpoint import (
            checkpoint_metadata,
            load_checkpoint,
        )

        meta = checkpoint_metadata(ckpt_path)
        if not meta.get("fleet"):
            raise ValueError(
                f"{ckpt_path} is not a fleet checkpoint (trainer "
                "checkpoints don't restore into FusedLoRATrainer)")
        if (int(meta.get("capacity", -1)) != self.capacity
                or int(meta.get("rank", -1)) != self.rank):
            raise ValueError(
                f"{ckpt_path}: checkpoint capacity/rank "
                f"({meta.get('capacity')}/{meta.get('rank')}) does not "
                f"match this fleet ({self.capacity}/{self.rank})")
        self.state = load_checkpoint(ckpt_path, self.state)
        self.global_step = int(meta.get("global_step", 0))
        self.tokens_seen = int(meta.get("tokens_seen", 0))
        self._n_admitted = int(meta.get("n_admitted", 0))
        by_name = {j.name: j for j in self.jobs}
        job_meta = meta.get("jobs", {})
        for name, jm in job_meta.items():
            job = by_name.get(name)
            if job is None:
                logger.warning(
                    "Fleet resume: checkpoint job '%s' (%s) was not "
                    "re-added; its pool row resumes untrained-on.",
                    name, jm.get("status"))
                continue
            job.status = jm.get("status", "pending")
            job.supervised_tokens = float(
                jm.get("supervised_tokens", 0.0))
            job.final_loss = jm.get("final_loss")
            job.artifact = jm.get("artifact")
            job.error = jm.get("error")
            job.fast_forward(int(jm.get("steps_done", 0)))
        # rebuild the slot map + pending queue in checkpoint order; jobs
        # the checkpoint never saw stay pending at the back (in add_job
        # order, which the initial _pending_jobs preserved)
        self._slots = [None] * self.capacity
        for slot, name in enumerate(meta.get("slots", [])):
            if name is not None and name in by_name:
                job = by_name[name]
                job.slot = slot
                self._slots[slot] = job
        pend = [by_name[n] for n in meta.get("pending", ())
                if n in by_name]
        new = [j for j in self.jobs
               if j.name not in job_meta and j.status == "pending"]
        self._pending_jobs = collections.deque(pend + new)
        logger.info(
            "Fleet resumed from %s at fused step %d: %d running, %d "
            "pending, %d done, %d failed.", ckpt_path, self.global_step,
            sum(1 for s in self._slots if s is not None),
            len(self._pending_jobs),
            sum(1 for j in self.jobs if j.status == "done"),
            sum(1 for j in self.jobs if j.status == "failed"))
        return self

    # -- the fused loop ----------------------------------------------------

    def _build_batch(self) -> Dict[str, np.ndarray]:
        """Stack each running slot's next rows via the ONE fused-batch
        constructor; free slots contribute zero rows with ``job_id = -1``
        (clamped gather × zero scale × zero loss weight — structurally
        inert)."""
        entries, horizons = [], np.ones((self.capacity,), np.int32)
        for j, job in enumerate(self._slots):
            if job is None:
                entries.append(None)
                continue
            inp, tgt, w = job.next_rows()
            entries.append({"inputs": inp, "targets": tgt, "weights": w})
            horizons[j] = job.total_steps
        return stack_fleet_batch(entries, capacity=self.capacity,
                                 scaling=self.alpha / self.rank,
                                 horizon=horizons)

    def run(self, stopper=None) -> "FusedLoRATrainer":
        """Train every queued job to completion (admitting into freed
        slots as earlier jobs finish), exporting each artifact the moment
        its job is done. Returns self.

        ``stopper`` (training/resilience.GracefulStopper): SIGTERM/SIGINT
        stop the fleet at the next step boundary — metrics flushed, one
        step-tagged checkpoint written (``save_checkpoint``) — so a
        relaunch with ``--resume auto`` continues every job's loss
        trajectory bit-for-bit. ``save_every`` fused steps additionally
        checkpoint at flush boundaries (retention-GC'd to
        ``keep_ckpts``)."""
        t0 = time.monotonic()
        split = fleet_flops_split(self.cfg, self.rank)
        self.metrics_sink.event(
            "finetune_fleet", phase="start", n_jobs=len(self.jobs),
            capacity=self.capacity, rank=self.rank, alpha=self.alpha,
            rows_per_job=self.rows_per_job,
            flops_per_token_base=split["flops_per_token_base"],
            flops_per_token_adapter=split["flops_per_token_adapter"])
        self._admit_pending()
        window_tokens, window_t0 = 0, time.perf_counter()
        try:
            while self._running():
                if stopper is not None and stopper.should_stop():
                    # preemption: flush (so ledgers are current), write
                    # ONE step-tagged checkpoint, stop at the boundary —
                    # the PR 1 trainer's stop discipline, fleet-wide
                    self.preempted = True
                    self._flush(window_tokens,
                                time.perf_counter() - window_t0)
                    window_tokens, window_t0 = 0, time.perf_counter()
                    path = self.save_checkpoint()
                    self.metrics_sink.event(
                        "preemption_stop", step=self.global_step,
                        tokens_seen=self.tokens_seen)
                    logger.warning(
                        "Fleet preempted at fused step %d%s; relaunch "
                        "with --resume auto to continue.",
                        self.global_step,
                        f" (checkpoint {path})" if path else "")
                    break
                batch = self._build_batch()
                self.state, metrics = self._step_fn(self.state, batch)
                if self._watcher is not None and self.global_step == 0:
                    # the one legitimate compile happened; anything after
                    # this (join, finish, retire) is a recompile event
                    self._watcher.freeze()
                self.global_step += 1
                n_tok = int(batch["active"].sum()) * self.rows_per_job \
                    * self.cfg.context_length
                self.tokens_seen += n_tok
                window_tokens += n_tok
                self._post_metrics(metrics)
                if self.on_step is not None:
                    self.on_step(self)
                due = []
                for job in self._running():
                    job.steps_done += 1
                    if job.steps_done >= job.total_steps:
                        due.append(job)
                save_due = (self.save_every > 0
                            and self.global_step % self.save_every == 0)
                if due or save_due \
                        or self.global_step % self.log_every == 0:
                    self._flush(window_tokens,
                                time.perf_counter() - window_t0)
                    window_tokens, window_t0 = 0, time.perf_counter()
                    for job in due:
                        if job.status == "running":
                            self._finish(job)
                    self._admit_pending()
                    if save_due:
                        self.save_checkpoint()
        except KeyboardInterrupt:
            self.preempted = True
            logger.warning("Fleet interrupted at fused step %d.",
                           self.global_step)
            raise
        finally:
            self._flush(window_tokens, time.perf_counter() - window_t0)
            done = sum(1 for j in self.jobs if j.status == "done")
            failed = sum(1 for j in self.jobs if j.status == "failed")
            self.metrics_sink.event(
                "finetune_fleet", phase="end", n_jobs=len(self.jobs),
                jobs_done=done, jobs_failed=failed,
                seconds=round(time.monotonic() - t0, 3))
        return self

    def _post_metrics(self, metrics: Dict[str, Any]) -> None:
        """Deferred-fetch discipline: post the (J,)-array DMAs now,
        convert to host values only at flush cadence."""
        keep = {}
        for key in _FETCHED_METRICS:
            v = metrics[key]
            try:
                v.copy_to_host_async()
            except (AttributeError, RuntimeError):
                pass
            keep[key] = v
        for v in metrics["health"].values():
            try:
                v.copy_to_host_async()
            except (AttributeError, RuntimeError):
                pass
        keep["health"] = metrics["health"]
        self._pending_metrics.append((self.global_step, keep))

    def _flush(self, window_tokens: int, window_s: float) -> None:
        """Fetch pending per-step metrics (explicit ``jax.device_get`` —
        the sanctioned cadence fetch), retire any job that went
        non-finite, and emit the fleet's metrics + per-job health rows."""
        if not self._pending_metrics:
            return
        pending, self._pending_metrics = self._pending_metrics, []
        fetched = jax.device_get([m for _, m in pending])
        for (step, _), vals in zip(pending, fetched):
            for j, job in enumerate(self._slots):
                if job is None or job.status != "running":
                    continue
                job.supervised_tokens += float(vals["weights"][j])
                if not bool(vals["finite"][j]):
                    self._fail(job, step=step, reason="non_finite",
                               loss=float(vals["loss"][j]),
                               grad_norm=float(vals["grad_norm"][j]))
        last_step, _ = pending[-1]
        last = fetched[-1]
        self._last_fetched = last
        for j, job in enumerate(self._slots):
            if job is not None and job.status == "running":
                job.final_loss = float(last["loss"][j])
        names = self.slot_names()
        active = [j for j in self._running() if j.status == "running"]
        tok_s = window_tokens / window_s if window_s > 0 else 0.0
        self.metrics_sink.log_metrics(
            last_step, fleet=True, tok_s=round(tok_s, 1),
            active_jobs=len(active),
            jobs_done=sum(1 for j in self.jobs if j.status == "done"),
            jobs_failed=sum(1 for j in self.jobs
                            if j.status == "failed"),
            jobs_pending=len(self._pending_jobs))
        h = last["health"]
        self.metrics_sink.log_health(
            last_step, names, fleet=True,
            loss=[round(float(x), 6) for x in last["loss"]],
            lr=[round(float(x), 8) for x in last["lr"]],
            grad_norm=[round(float(x), 8) for x in h["grad_norm"]],
            param_norm=[round(float(x), 8) for x in h["param_norm"]],
            update_norm=[round(float(x), 8) for x in h["update_norm"]],
            update_ratio=[round(float(x), 10)
                          for x in h["update_ratio"]],
            first_nonfinite=(
                names[int(h["first_nonfinite"])]
                if 0 <= int(h["first_nonfinite"]) < len(names) else None))
        if active:
            logger.info(
                "fleet step %d: %d active, %.0f tok/s, losses %s",
                last_step, len(active), tok_s,
                ", ".join(f"{j.name}={j.final_loss:.3f}"
                          for j in active if j.final_loss is not None))

    def _fail(self, job: FinetuneJob, step: int, reason: str,
              loss: Optional[float] = None,
              grad_norm: Optional[float] = None) -> None:
        """Retire ONE sick job (non-finite signal, or a dataset that
        never produced a supervised token): for the non-finite case its
        in-graph updates were already being skipped (params/moments kept
        finite-side), so co-trained jobs never saw a single poisoned
        value. The slot frees for the next pending job; no artifact is
        exported."""
        slot = job.slot
        job.status = "failed"
        if reason == "non_finite":
            job.error = (f"non-finite training signal at fused step "
                         f"{step} (loss={loss}, grad_norm={grad_norm})")
        else:
            job.error = (f"retired at fused step {step}: {reason}")
        self._slots[slot] = None
        job.slot = None
        self._zero_slot_row(slot)
        fields = {}
        if loss is not None:
            fields["loss"] = loss
        if grad_norm is not None:
            fields["grad_norm"] = grad_norm
        self.metrics_sink.event(
            "finetune_job_failed", step=step, job_id=job.name,
            reason=reason, slot=slot, steps=job.steps_done, **fields)
        logger.warning("Fleet job '%s' retired (%s at step %d); "
                       "co-trained jobs unaffected.", job.name, reason,
                       step)

    def _export_path(self, job: FinetuneJob) -> str:
        if job.export_path:
            return job.export_path
        base = self.export_dir or "adapters"
        return os.path.join(base, f"{job.name}.npz")

    def _finish(self, job: FinetuneJob) -> None:
        """Per-JOB export at job completion (not run end): slice the
        job's adapter out of the pool, write the standard artifact
        (atomic tmp+rename, fingerprint — models/lora.save_adapter),
        optionally hot-load it into the deploy registry, free the slot.

        A job whose ledger shows ZERO supervised tokens (every row fully
        loss-masked — e.g. a template that overflows the context) never
        trained: exporting its zero-delta adapter as 'done' would
        silently deploy an untrained tenant, so it retires as failed
        instead."""
        if job.supervised_tokens <= 0:
            self._fail(job, step=self.global_step,
                       reason="no_supervised_tokens")
            return
        slot = job.slot
        lora = jax.tree_util.tree_map(lambda a: a[slot],
                                      self.state["trainable"])
        path = self._export_path(job)
        save_adapter(path, lora, rank=self.rank, alpha=self.alpha,
                     cfg=self.cfg)
        job.artifact = path
        job.status = "done"
        self._slots[slot] = None
        job.slot = None
        self._zero_slot_row(slot)
        self.metrics_sink.event(
            "adapter_save", step=self.global_step, path=path,
            job_id=job.name, rank=self.rank, alpha=self.alpha,
            n_params=count_lora_params(lora),
            fingerprint=adapter_fingerprint(self.cfg))
        deployed = False
        if self.deploy is not None:
            try:
                self.deploy.replace(job.name, path)
                deployed = True
            except Exception as e:      # noqa: BLE001 — a deploy-side
                # refusal (capacity, fingerprint) must not kill the
                # still-training fleet; the artifact is durable on disk
                logger.warning("Deploy hop for '%s' failed: %s",
                               job.name, e)
        self.metrics_sink.event(
            "finetune_job_done", step=self.global_step, job_id=job.name,
            steps=job.steps_done, final_loss=job.final_loss,
            artifact=path, deployed=deployed,
            seconds=round(time.monotonic() - (job.t_admitted or 0), 3))
        logger.info("Fleet job '%s' done after %d steps (loss %.4f): "
                    "exported %s%s.", job.name, job.steps_done,
                    job.final_loss if job.final_loss is not None
                    else float("nan"), path,
                    ", deployed" if deployed else "")


# ---------------------------------------------------------------------------
# CLI entry (--mode finetune_fleet; main.py dispatches here)
# ---------------------------------------------------------------------------

def run_finetune_fleet(args, comps, metric_logger) -> FusedLoRATrainer:
    """Train a fleet of per-tenant LoRA jobs fused on one base model:
    ``--fleet_jobs name=records.json,...`` each becomes a job; every
    finished job exports ``<export_dir>/<name>.npz`` — the exact
    artifacts ``--serve_adapters`` hot-loads."""
    from building_llm_from_scratch_tpu.serving.frontend import (
        parse_adapter_specs,
    )
    from building_llm_from_scratch_tpu.training.resilience import (
        GracefulStopper,
        resolve_resume,
    )
    from building_llm_from_scratch_tpu.utils.io import read_json_file

    specs = parse_adapter_specs(args.fleet_jobs, flag="--fleet_jobs")
    export_dir = args.fleet_export_dir or os.path.join(
        args.output_dir, "adapters")
    engine = FusedLoRATrainer(
        comps.cfg, comps.params, tokenizer=comps.tokenizer,
        capacity=(args.fleet_capacity or len(specs)),
        rank=args.lora_rank, alpha=args.lora_alpha,
        rows_per_job=args.fleet_rows_per_job,
        peak_lr=args.lr, initial_lr=args.initial_lr, min_lr=args.min_lr,
        warmup_steps=args.warmup_steps, seed=args.seed,
        log_every=(args.log_every or 10), export_dir=export_dir,
        ckpt_dir=args.output_dir, save_every=args.save_ckpt_freq,
        keep_ckpts=args.keep_ckpts)
    for name, path in specs.items():
        records = read_json_file(path)
        engine.add_job(FinetuneJob.from_records(
            name, records, comps.tokenizer,
            max_length=comps.cfg.context_length,
            rows_per_step=args.fleet_rows_per_job,
            n_epochs=args.n_epochs, pad_token_id=comps.cfg.eos_id,
            seed=args.seed, style=args.fleet_style,
            export_path=os.path.join(export_dir, f"{name}.npz")))
    # fault tolerance: --resume auto discovers the latest VALID fleet
    # checkpoint in --output_dir (manifest-validated, PR 1 machinery);
    # SIGTERM/SIGINT checkpoint-and-stop at the next fused-step boundary.
    # The predicate skips TRAINER checkpoints sharing the output_dir —
    # auto-discovery must not pick one and die in restore(); an explicit
    # --resume_from still refuses loudly there
    resume_dir = resolve_resume(getattr(args, "resume", "auto"),
                                args.resume_from, args.output_dir,
                                predicate=lambda meta: bool(
                                    meta.get("fleet")))
    if resume_dir is not None:
        engine.restore(resume_dir)
    with GracefulStopper() as stopper:
        engine.run(stopper=stopper)
    done = [j.name for j in engine.jobs if j.status == "done"]
    failed = [j.name for j in engine.jobs if j.status == "failed"]
    if engine.preempted:
        logger.warning(
            "Fleet preempted: %d/%d jobs exported; relaunch the same "
            "command to resume (--resume auto).", len(done),
            len(engine.jobs))
    else:
        logger.info("Fleet complete: %d/%d jobs exported (%s)%s.",
                    len(done), len(engine.jobs), ", ".join(done) or "none",
                    f"; failed: {', '.join(failed)}" if failed else "")
    metric_logger.close()
    return engine
