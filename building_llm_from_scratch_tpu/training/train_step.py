"""The jitted training step.

The reference's per-batch work — LR schedule math, forward, CE loss,
backward, global-norm clip, optimizer step, tokens-seen accounting
(train.py:94-126) — compiles into ONE XLA program with donated state.
Host Python only feeds batches and reads metrics.

State layout (a plain pytree, so it shards/donates/checkpoints trivially):

  state = {
    "trainable": <params being optimized>,   # full model, or LoRA adapters
    "frozen":    <non-trained params>,       # {} normally; base model w/ LoRA
    "opt_state": <optax state>,
    "step":      int32 scalar,
    "rng":       PRNGKey (dropout stream; folded with step each batch),
  }

Loss masking: a single weighted cross entropy covers both workloads —
pretraining passes weights=1 (plain mean, reference train.py:88-92) and
instruction finetuning passes the collator's 0/1 weights, which reproduces
torch F.cross_entropy's ignore_index=-100 mean exactly
(see tests/test_data.py::test_collate_matches_reference_loss_set).

Loss implementation choice: the chunked custom-VJP cross entropy
(ops/softmax_xent.py) avoids storing (B,T,V) fp32 log-probs but recomputes
the head matmul in the backward — a win only when emb_dim is small
relative to HBM/MXU ratios (measured v5e-1: GPT2-124M D=768 wins ~2ms/step;
LLaMA3-8B-arch D=4096 LOSES ~44ms/step). ``_auto_fused_xent`` picks per
config; pass ``use_fused_xent`` to override.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from building_llm_from_scratch_tpu.configs import ModelConfig
from building_llm_from_scratch_tpu.models.lora import merge_lora
from building_llm_from_scratch_tpu.obs.health import group_health
from building_llm_from_scratch_tpu.models.transformer import (
    forward_hidden,
)
from building_llm_from_scratch_tpu.ops.softmax_xent import (
    fused_cross_entropy_loss,
    fused_cross_entropy_sums,
)
from building_llm_from_scratch_tpu.training.precision import (
    PrecisionPolicy,
    cast_floating,
)

Params = Dict[str, Any]


def cross_entropy_loss(logits: jnp.ndarray, targets: jnp.ndarray,
                       weights: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Weighted token-mean cross entropy in fp32."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None].astype(jnp.int32),
                             axis=-1)[..., 0]
    if weights is None:
        return -jnp.mean(ll)
    w = weights.astype(jnp.float32)
    return -(ll * w).sum() / jnp.maximum(w.sum(), 1.0)


def _auto_fused_xent(cfg: ModelConfig, use_fused_xent: Optional[bool]) -> bool:
    """Chunked-CE break-even on v5e: saved logits traffic (~12·N·V bytes)
    vs backward head-matmul recompute (2·N·D·V flops) → wins below
    D ~ 900; gate at 1024 with measured margins on both sides."""
    if use_fused_xent is not None:
        return use_fused_xent
    return cfg.emb_dim <= 1024


def make_loss_fns(cfg: ModelConfig, use_fused_xent: Optional[bool] = None):
    """(loss, sums) callables: (params, hidden-fn args...) -> scalar parts.

    Both take (params, hidden, targets, weights) where ``hidden`` is the
    pre-head activation from ``forward_hidden``."""
    if _auto_fused_xent(cfg, use_fused_xent):
        def loss(params, hidden, targets, weights):
            return fused_cross_entropy_loss(hidden,
                                            params["head"]["weight"],
                                            targets, weights)

        def sums(params, hidden, targets, weights):
            return fused_cross_entropy_sums(hidden,
                                            params["head"]["weight"],
                                            targets, weights)
    else:
        def _logits(params, hidden):
            return jnp.einsum("btd,dv->btv", hidden,
                              params["head"]["weight"],
                              preferred_element_type=jnp.float32)

        def loss(params, hidden, targets, weights):
            return cross_entropy_loss(_logits(params, hidden), targets,
                                      weights)

        def sums(params, hidden, targets, weights):
            return cross_entropy_sums(_logits(params, hidden), targets,
                                      weights)
    return loss, sums


def make_full_params_fn(cfg: ModelConfig, *,
                        lora_alpha: Optional[float] = None,
                        lora_rank: Optional[int] = None,
                        policy: Optional[PrecisionPolicy] = None
                        ) -> Callable[[Params, Params], Params]:
    """Build the trainable/frozen -> full-model-params combinator."""
    use_lora = lora_rank is not None

    def full_params(trainable: Params, frozen: Params) -> Params:
        if use_lora:
            params = merge_lora(frozen, trainable, lora_alpha, lora_rank)
        else:
            params = trainable
        if policy is not None:
            params = cast_floating(params, policy.jax_compute_dtype)
        return params

    return full_params


def init_train_state(trainable: Params, optimizer: optax.GradientTransformation,
                     rng: jax.Array, frozen: Optional[Params] = None,
                     policy: Optional[PrecisionPolicy] = None) -> Params:
    state = {
        "trainable": trainable,
        "frozen": frozen if frozen is not None else {},
        "opt_state": optimizer.init(trainable),
        "step": jnp.zeros((), jnp.int32),
        "rng": rng,
    }
    if policy is not None and policy.compute_dtype == "fp16":
        # dynamic loss scaling state: fp16 grads underflow without it
        # (the reference's fp16 FSDP policy has no scaler either — that is
        # round-1 weakness #3, fixed here rather than reproduced)
        state["loss_scale"] = jnp.asarray(policy.init_loss_scale, jnp.float32)
        state["growth_count"] = jnp.zeros((), jnp.int32)
    return state


def make_train_step(cfg: ModelConfig, optimizer: optax.GradientTransformation,
                    *, lr_schedule: Optional[Callable] = None,
                    lora_alpha: Optional[float] = None,
                    lora_rank: Optional[int] = None,
                    policy: Optional[PrecisionPolicy] = None,
                    sp_mesh=None,
                    use_fused_xent: Optional[bool] = None,
                    grad_accum: int = 1,
                    jit: bool = True) -> Callable:
    """Build train_step(state, batch) -> (state, metrics).

    batch: {"inputs": (B,T) i32, "targets": (B,T) i32, "weights": (B,T) f32}.
    ``sp_mesh``: mesh with seq axis > 1 routes attention through the ring
    schedule (sequence parallelism; see ops/ring_attention.py).
    ``grad_accum`` > 1 splits the batch into that many microbatches and
    runs them through a ``lax.scan`` INSIDE the jitted step, accumulating
    fp32 gradients and the weighted-CE numerator/denominator — activation
    memory is one microbatch's, numerics are the full-batch weighted mean
    exactly (accumulate-then-normalize; parity test
    tests/test_training.py::test_grad_accum_matches_full_batch). Composes
    with every GSPMD shard mode (the scan body is ordinary sharded
    compute); each microbatch gets its own folded dropout stream.
    """
    full_params = make_full_params_fn(cfg, lora_alpha=lora_alpha,
                                      lora_rank=lora_rank, policy=policy)
    loss_impl, sums_impl = make_loss_fns(cfg, use_fused_xent)
    if grad_accum < 1:
        raise ValueError(f"grad_accum must be >= 1, got {grad_accum}")

    def train_step(state: Params, batch: Dict[str, jnp.ndarray]
                   ) -> Tuple[Params, Dict[str, jnp.ndarray]]:
        step_rng = jax.random.fold_in(state["rng"], state["step"])

        def loss_fn(trainable):
            params = full_params(trainable, state["frozen"])
            hidden = forward_hidden(params, cfg, batch["inputs"],
                                    rng=step_rng,
                                    deterministic=(cfg.drop_rate <= 0.0),
                                    sp_mesh=sp_mesh)
            return loss_impl(params, hidden, batch["targets"],
                             batch.get("weights"))

        loss, grads = _compute_grads(loss_fn, state)
        return _finish_step(state, loss, grads, batch["inputs"].size,
                            optimizer, lr_schedule, policy)

    def train_step_accum(state: Params, batch: Dict[str, jnp.ndarray]
                         ) -> Tuple[Params, Dict[str, jnp.ndarray]]:
        B = batch["inputs"].shape[0]
        if B % grad_accum:
            raise ValueError(
                f"batch size {B} not divisible by grad_accum {grad_accum}")
        mb = B // grad_accum
        if "weights" not in batch:
            batch = dict(batch, weights=jnp.ones_like(
                batch["targets"], jnp.float32))
        micro = jax.tree_util.tree_map(
            lambda x: x.reshape(grad_accum, mb, *x.shape[1:]), batch)
        step_rng = jax.random.fold_in(state["rng"], state["step"])
        scale = state.get("loss_scale")

        def body(carry, xs):
            g_acc, nll_acc, w_acc = carry
            mb_batch, idx = xs
            rng_m = jax.random.fold_in(step_rng, idx)

            def loss_fn(trainable):
                params = full_params(trainable, state["frozen"])
                hidden = forward_hidden(params, cfg, mb_batch["inputs"],
                                        rng=rng_m,
                                        deterministic=(cfg.drop_rate <= 0.0),
                                        sp_mesh=sp_mesh)
                nll, w = sums_impl(params, hidden, mb_batch["targets"],
                                   mb_batch["weights"])
                scaled = nll if scale is None else nll * scale
                return scaled, (nll, w)

            (_, (nll, w)), g = jax.value_and_grad(loss_fn, has_aux=True)(
                state["trainable"])
            g_acc = jax.tree_util.tree_map(
                lambda a, b: a + b.astype(jnp.float32), g_acc, g)
            return (g_acc, nll_acc + nll, w_acc + w), None

        g0 = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), state["trainable"])
        (g_sum, nll_sum, w_sum), _ = jax.lax.scan(
            body, (g0, jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)),
            (micro, jnp.arange(grad_accum)))
        denom = jnp.maximum(w_sum, 1.0)
        if scale is not None:
            # grads carry the loss scale; divide it out with the weight sum
            denom = denom * scale
        grads = jax.tree_util.tree_map(lambda g: g / denom, g_sum)
        loss = nll_sum / jnp.maximum(w_sum, 1.0)
        return _finish_step(state, loss, grads, batch["inputs"].size,
                            optimizer, lr_schedule, policy)

    fn = train_step if grad_accum == 1 else train_step_accum
    if jit:
        return jax.jit(fn, donate_argnums=(0,))
    return fn


def _compute_grads(loss_fn: Callable, state: Params):
    """value_and_grad with dynamic loss scaling when the state carries a
    ``loss_scale``: the loss is scaled up so fp16 grads don't underflow and
    the grads unscaled in fp32 afterwards."""
    use_scaling = "loss_scale" in state
    if not use_scaling:
        return jax.value_and_grad(loss_fn)(state["trainable"])
    scale = state["loss_scale"]
    loss, grads = jax.value_and_grad(
        lambda t: loss_fn(t) * scale)(state["trainable"])
    loss = loss / scale
    grads = cast_floating(grads, jnp.float32)
    grads = jax.tree_util.tree_map(lambda g: g / scale, grads)
    return loss, grads


def _finish_step(state: Params, loss, grads, n_tokens: int,
                 optimizer, lr_schedule, policy):
    """Optimizer update + new state + metrics; with loss scaling, overflow
    steps are skipped (params/opt state kept) and the scale halved, while a
    streak of ``scale_growth_interval`` finite steps doubles it.

    Metrics carry the global pre-clip ``grad_norm`` AND the post-clip
    ``update_norm`` (``optax.clip_by_global_norm`` sits first in the
    optimizer chain, so a capped step is finally observable instead of
    silent), plus the per-layer-group ``health`` bundle (obs/health.py):
    (n_groups,) grad/param/update norms, update-to-param ratios, and
    first-non-finite-group localization — all in-graph, fetched by the
    trainer only at logging cadence."""
    use_scaling = "loss_scale" in state
    grad_norm = optax.global_norm(grads)
    updates, new_opt_state = optimizer.update(grads, state["opt_state"],
                                              state["trainable"])
    new_trainable = optax.apply_updates(state["trainable"], updates)
    new_state = {
        "trainable": new_trainable,
        "frozen": state["frozen"],
        "opt_state": new_opt_state,
        "step": state["step"] + 1,
        "rng": state["rng"],
    }
    metrics = {
        "loss": loss,
        "grad_norm": grad_norm,
        "update_norm": optax.global_norm(updates),
        "tokens": jnp.asarray(n_tokens, jnp.int32),
        "health": group_health(grads, new_trainable, updates),
    }
    if use_scaling:
        scale = state["loss_scale"]
        finite = jnp.isfinite(grad_norm) & jnp.isfinite(loss)
        keep = lambda new, old: jax.tree_util.tree_map(
            lambda n, o: jnp.where(finite, n, o), new, old)
        new_state["trainable"] = keep(new_trainable, state["trainable"])
        new_state["opt_state"] = keep(new_opt_state, state["opt_state"])
        growth = jnp.where(finite, state["growth_count"] + 1, 0)
        grow_now = growth >= policy.scale_growth_interval
        new_state["loss_scale"] = jnp.where(
            ~finite, jnp.maximum(scale * 0.5, 1.0),
            jnp.where(grow_now, scale * 2.0, scale))
        new_state["growth_count"] = jnp.where(grow_now, 0, growth)
        metrics["loss_scale"] = new_state["loss_scale"]
        metrics["skipped"] = (~finite).astype(jnp.int32)
    if lr_schedule is not None:
        metrics["lr"] = lr_schedule(state["step"])
    return new_state, metrics


def cross_entropy_sums(logits: jnp.ndarray, targets: jnp.ndarray,
                       weights: Optional[jnp.ndarray]):
    """(weighted negative-log-likelihood sum, weight sum) in fp32 — the
    un-normalized pieces of ``cross_entropy_loss``, for losses whose
    denominator is a cross-shard psum."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None].astype(jnp.int32),
                             axis=-1)[..., 0]
    if weights is None:
        weights = jnp.ones_like(ll)
    w = weights.astype(jnp.float32)
    return -(ll * w).sum(), w.sum()


def make_sharded_train_step(cfg: ModelConfig,
                            optimizer: optax.GradientTransformation,
                            plan, *, lr_schedule: Optional[Callable] = None,
                            lora_alpha: Optional[float] = None,
                            lora_rank: Optional[int] = None,
                            policy: Optional[PrecisionPolicy] = None,
                            sp_mesh=None,
                            jit: bool = True) -> Callable:
    """Explicit-collective train step via ``jax.shard_map``.

    Unlike ``make_train_step`` (GSPMD inserts the gradient all-reduce with
    whatever dtype the grads happen to have), this step OWNS the
    communication boundary — it delivers the reference's bf16_hybrid policy
    (fp32 params+compute / bf16 grad comms,
    datautils/mixed_precision.py:24-29) for real:

      dp     grads cast to ``policy.reduce_dtype`` -> explicit ``psum``
      zero1  same psum; the optimizer phase keeps the adam moments sharded
      fsdp   param shards cast to the compute dtype BEFORE an explicit
             ``all_gather`` (comms in param_dtype, FSDP-style) and grads
             cast to the reduce dtype into a ``psum_scatter`` that lands
             them sharded like the params

    Structure (round-5, lifting round-4 VERDICT weak #4 — hybrid was dp
    only): a shard_map GRADIENT phase owns every collective and its dtype;
    the OPTIMIZER phase runs outside the shard_map in the same jit under
    GSPMD, with explicit sharding constraints pinning the new params and
    optimizer state to ``plan.state_shardings`` — so zero1/fsdp state stays
    sharded end to end (round-2 ADVICE medium #1 still honored).
    tp modes are rejected: Megatron activation psums live inside the
    forward, where GSPMD owns the dtype — ``args.perform_checks`` refuses
    the flag combination.
    """
    from jax.sharding import PartitionSpec as P

    from building_llm_from_scratch_tpu.parallel.collectives import shard_map
    from building_llm_from_scratch_tpu.parallel.mesh import (
        DATA_AXIS,
        SEQ_AXIS,
    )

    if sp_mesh is not None and sp_mesh is not plan.mesh:
        raise ValueError(
            "make_sharded_train_step derives sequence parallelism from "
            "plan.mesh; a different sp_mesh would be silently ignored")
    if plan.shard_mode not in ("dp", "fsdp", "zero1"):
        raise ValueError(
            f"the explicit-collective step supports dp/fsdp/zero1, not "
            f"'{plan.shard_mode}' (tp reductions happen inside the forward "
            "under GSPMD)")
    use_lora = lora_rank is not None
    _, sums_impl = make_loss_fns(cfg)
    reduce_dtype = (policy.jax_reduce_dtype if policy is not None
                    else jnp.float32)
    compute_dtype = (policy.jax_compute_dtype if policy is not None
                     else None)
    mesh = plan.mesh
    S = mesh.shape.get(SEQ_AXIS, 1)
    # sp composes (r3 VERDICT weakness #6 lifted in r4): the shard_map maps
    # batch rows over data AND tokens over seq; the forward runs the ring
    # body directly (sp_inside) and every reduction covers both axes, so
    # the reduce-dtype boundary spans the complete gradient reduction
    reduce_axes = (DATA_AXIS, SEQ_AXIS) if S > 1 else (DATA_AXIS,)
    batch_spec = P(DATA_AXIS, SEQ_AXIS) if S > 1 else P(DATA_AXIS)
    sp_inside = (SEQ_AXIS, S) if S > 1 else None

    def _gather_leaf(x, spec):
        """all_gather a (cast) param shard to full size along its
        data-sharded axes — the FSDP forward gather, comms in the dtype x
        already carries."""
        for axis, name in enumerate(spec):
            if name == DATA_AXIS:
                x = jax.lax.all_gather(x, DATA_AXIS, axis=axis, tiled=True)
        return x

    def _reduce_leaf(g, spec):
        """Reduce one grad leaf (already cast to reduce_dtype): replicated
        leaves psum over every mapped axis; fsdp-sharded leaves
        psum_scatter back onto their shard axis."""
        shard_axis = None
        for axis, name in enumerate(spec):
            if name == DATA_AXIS:
                shard_axis = axis
        if shard_axis is None:
            return jax.lax.psum(g, reduce_axes)
        g = jax.lax.psum_scatter(g, DATA_AXIS,
                                 scatter_dimension=shard_axis, tiled=True)
        if S > 1:
            g = jax.lax.psum(g, SEQ_AXIS)
        return g

    def make_body(t_specs, f_specs):
        def body(trainable, frozen, scalars, batch):
            step_rng = jax.random.fold_in(scalars["rng"], scalars["step"])
            # distinct dropout streams per (data, seq) shard (a replicated
            # stream would correlate masks across the global batch)
            shard_rng = jax.random.fold_in(step_rng,
                                           jax.lax.axis_index(DATA_AXIS))
            if S > 1:
                shard_rng = jax.random.fold_in(shard_rng,
                                               jax.lax.axis_index(SEQ_AXIS))
            w_global = jax.lax.psum(
                jnp.sum(batch["weights"].astype(jnp.float32)), reduce_axes)

            # FSDP param path: cast the SHARD to the compute dtype first,
            # then gather — the all_gather moves compute-dtype bytes
            # (reference MixedPrecision param_dtype semantics); dp/zero1
            # specs are fully replicated so the gathers are no-ops.
            # Gathering happens OUTSIDE the grad: we differentiate w.r.t.
            # the gathered full-shape params (mixed-precision "compute
            # copy"), so the one and only gradient reduction is the
            # explicit cast+psum/psum_scatter below — differentiating
            # through all_gather would insert a second, compute-dtype
            # psum_scatter via its transpose.
            def as_full(tree, specs):
                if compute_dtype is not None:
                    tree = cast_floating(tree, compute_dtype)
                return jax.tree_util.tree_map(_gather_leaf, tree, specs)

            frozen_full = as_full(frozen, f_specs)
            t_full = as_full(trainable, t_specs)

            def loss_fn(t):
                if use_lora:
                    from building_llm_from_scratch_tpu.models.lora import (
                        merge_lora,
                    )

                    params = merge_lora(frozen_full, t, lora_alpha,
                                        lora_rank)
                else:
                    params = t
                hidden = forward_hidden(params, cfg, batch["inputs"],
                                        rng=shard_rng,
                                        deterministic=(cfg.drop_rate <= 0.0),
                                        sp_inside=sp_inside)
                nll_sum, _ = sums_impl(params, hidden, batch["targets"],
                                       batch.get("weights"))
                # local share of the GLOBAL mean -> reduced grads are the
                # exact global gradient
                return nll_sum / jnp.maximum(w_global, 1.0)

            pseudo = {"trainable": t_full}
            if "loss_scale" in scalars:
                pseudo["loss_scale"] = scalars["loss_scale"]
            loss, grads = _compute_grads(loss_fn, pseudo)
            # >>> the communication boundary: policy.reduce_dtype <<<
            grads = cast_floating(grads, reduce_dtype)
            grads = jax.tree_util.tree_map(_reduce_leaf, grads, t_specs)
            grads = cast_floating(grads, jnp.float32)
            loss = jax.lax.psum(loss, reduce_axes)
            return loss, grads

        return body

    def train_step(state, batch):
        t_specs = plan.param_spec_tree(state["trainable"])
        f_specs = plan.param_spec_tree(state["frozen"])
        scalars = {"rng": state["rng"], "step": state["step"]}
        if "loss_scale" in state:
            scalars["loss_scale"] = state["loss_scale"]
        sharded_grads = shard_map(
            make_body(t_specs, f_specs), mesh=mesh,
            in_specs=(t_specs, f_specs, P(), batch_spec),
            out_specs=(P(), t_specs),
            check_vma=False,
        )
        loss, grads = sharded_grads(state["trainable"], state["frozen"],
                                    scalars, batch)
        n_tokens = batch["inputs"].size  # global batch (unmapped here)
        new_state, metrics = _finish_step(state, loss, grads, n_tokens,
                                          optimizer, lr_schedule, policy)
        # pin the optimizer phase's outputs to the plan's placements so
        # zero1's adam moments / fsdp's params+moments STAY sharded
        shardings = plan.state_shardings(state)
        new_state["trainable"] = jax.lax.with_sharding_constraint(
            new_state["trainable"], shardings["trainable"])
        new_state["opt_state"] = jax.lax.with_sharding_constraint(
            new_state["opt_state"], shardings["opt_state"])
        return new_state, metrics

    if jit:
        return jax.jit(train_step, donate_argnums=(0,))
    return train_step


def make_eval_step(cfg: ModelConfig, *,
                   lora_alpha: Optional[float] = None,
                   lora_rank: Optional[int] = None,
                   policy: Optional[PrecisionPolicy] = None,
                   sp_mesh=None,
                   jit: bool = True) -> Callable:
    """Build eval_step(state, batch) -> loss (deterministic, no grads)."""
    full_params = make_full_params_fn(cfg, lora_alpha=lora_alpha,
                                      lora_rank=lora_rank, policy=policy)
    loss_impl, _ = make_loss_fns(cfg)

    def eval_step(state: Params, batch: Dict[str, jnp.ndarray]) -> jnp.ndarray:
        params = full_params(state["trainable"], state["frozen"])
        hidden = forward_hidden(params, cfg, batch["inputs"],
                                sp_mesh=sp_mesh)
        return loss_impl(params, hidden, batch["targets"],
                         batch.get("weights"))

    if jit:
        return jax.jit(eval_step)
    return eval_step
