"""The jitted training step.

The reference's per-batch work — LR schedule math, forward, CE loss,
backward, global-norm clip, optimizer step, tokens-seen accounting
(train.py:94-126) — compiles into ONE XLA program with donated state.
Host Python only feeds batches and reads metrics.

State layout (a plain pytree, so it shards/donates/checkpoints trivially):

  state = {
    "trainable": <params being optimized>,   # full model, or LoRA adapters
    "frozen":    <non-trained params>,       # {} normally; base model w/ LoRA
    "opt_state": <optax state>,
    "step":      int32 scalar,
    "rng":       PRNGKey (dropout stream; folded with step each batch),
  }

Loss masking: a single weighted cross entropy covers both workloads —
pretraining passes weights=1 (plain mean, reference train.py:88-92) and
instruction finetuning passes the collator's 0/1 weights, which reproduces
torch F.cross_entropy's ignore_index=-100 mean exactly
(see tests/test_data.py::test_collate_matches_reference_loss_set).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from building_llm_from_scratch_tpu.configs import ModelConfig
from building_llm_from_scratch_tpu.models.lora import merge_lora
from building_llm_from_scratch_tpu.models.transformer import forward
from building_llm_from_scratch_tpu.training.precision import (
    PrecisionPolicy,
    cast_floating,
)

Params = Dict[str, Any]


def cross_entropy_loss(logits: jnp.ndarray, targets: jnp.ndarray,
                       weights: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Weighted token-mean cross entropy in fp32."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None].astype(jnp.int32),
                             axis=-1)[..., 0]
    if weights is None:
        return -jnp.mean(ll)
    w = weights.astype(jnp.float32)
    return -(ll * w).sum() / jnp.maximum(w.sum(), 1.0)


def make_full_params_fn(cfg: ModelConfig, *,
                        lora_alpha: Optional[float] = None,
                        lora_rank: Optional[int] = None,
                        policy: Optional[PrecisionPolicy] = None
                        ) -> Callable[[Params, Params], Params]:
    """Build the trainable/frozen -> full-model-params combinator."""
    use_lora = lora_rank is not None

    def full_params(trainable: Params, frozen: Params) -> Params:
        if use_lora:
            params = merge_lora(frozen, trainable, lora_alpha, lora_rank)
        else:
            params = trainable
        if policy is not None:
            params = cast_floating(params, policy.jax_compute_dtype)
        return params

    return full_params


def init_train_state(trainable: Params, optimizer: optax.GradientTransformation,
                     rng: jax.Array, frozen: Optional[Params] = None) -> Params:
    return {
        "trainable": trainable,
        "frozen": frozen if frozen is not None else {},
        "opt_state": optimizer.init(trainable),
        "step": jnp.zeros((), jnp.int32),
        "rng": rng,
    }


def make_train_step(cfg: ModelConfig, optimizer: optax.GradientTransformation,
                    *, lr_schedule: Optional[Callable] = None,
                    lora_alpha: Optional[float] = None,
                    lora_rank: Optional[int] = None,
                    policy: Optional[PrecisionPolicy] = None,
                    jit: bool = True) -> Callable:
    """Build train_step(state, batch) -> (state, metrics).

    batch: {"inputs": (B,T) i32, "targets": (B,T) i32, "weights": (B,T) f32}.
    """
    full_params = make_full_params_fn(cfg, lora_alpha=lora_alpha,
                                      lora_rank=lora_rank, policy=policy)

    def train_step(state: Params, batch: Dict[str, jnp.ndarray]
                   ) -> Tuple[Params, Dict[str, jnp.ndarray]]:
        step_rng = jax.random.fold_in(state["rng"], state["step"])

        def loss_fn(trainable):
            params = full_params(trainable, state["frozen"])
            logits = forward(params, cfg, batch["inputs"], rng=step_rng,
                             deterministic=(cfg.drop_rate <= 0.0))
            return cross_entropy_loss(logits, batch["targets"],
                                      batch.get("weights"))

        loss, grads = jax.value_and_grad(loss_fn)(state["trainable"])
        if policy is not None and policy.reduce_dtype != "fp32":
            grads = cast_floating(grads, policy.jax_reduce_dtype)
            grads = cast_floating(grads, jnp.float32)
        updates, new_opt_state = optimizer.update(grads, state["opt_state"],
                                                  state["trainable"])
        new_trainable = optax.apply_updates(state["trainable"], updates)
        new_state = {
            "trainable": new_trainable,
            "frozen": state["frozen"],
            "opt_state": new_opt_state,
            "step": state["step"] + 1,
            "rng": state["rng"],
        }
        metrics = {
            "loss": loss,
            "grad_norm": optax.global_norm(grads),
            "tokens": jnp.asarray(batch["inputs"].size, jnp.int32),
        }
        if lr_schedule is not None:
            metrics["lr"] = lr_schedule(state["step"])
        return new_state, metrics

    if jit:
        return jax.jit(train_step, donate_argnums=(0,))
    return train_step


def make_eval_step(cfg: ModelConfig, *,
                   lora_alpha: Optional[float] = None,
                   lora_rank: Optional[int] = None,
                   policy: Optional[PrecisionPolicy] = None,
                   jit: bool = True) -> Callable:
    """Build eval_step(state, batch) -> loss (deterministic, no grads)."""
    full_params = make_full_params_fn(cfg, lora_alpha=lora_alpha,
                                      lora_rank=lora_rank, policy=policy)

    def eval_step(state: Params, batch: Dict[str, jnp.ndarray]) -> jnp.ndarray:
        params = full_params(state["trainable"], state["frozen"])
        logits = forward(params, cfg, batch["inputs"])
        return cross_entropy_loss(logits, batch["targets"],
                                  batch.get("weights"))

    if jit:
        return jax.jit(eval_step)
    return eval_step
