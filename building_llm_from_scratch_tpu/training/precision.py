"""Mixed-precision policies.

Parity with the reference's two precision mechanisms:
  1. whole-model dtype (``--data_type fp32|fp16|bf16``): config dtype applied
     to params and activations (build_components.py:67, utils.py:37-41);
  2. FSDP ``MixedPrecision`` policies (``--mixed_precision``):
     fp16 / bf16 / bf16_hybrid / fp32 with separate param, reduce (grad
     comms) and buffer dtypes (datautils/mixed_precision.py:10-46).

The TPU-native mapping: master params stay fp32, the train step casts a
compute copy to ``compute_dtype`` for forward/backward, and gradients are
accumulated/reduced in ``reduce_dtype`` (XLA's psum over ICI honors the
operand dtype). ``bf16_hybrid`` (fp32 params / bf16 comms) becomes
reduce_dtype=bf16 with compute_dtype=fp32.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from building_llm_from_scratch_tpu.configs import DTYPE_MAP


@dataclasses.dataclass(frozen=True)
class PrecisionPolicy:
    name: str
    compute_dtype: str = "fp32"   # dtype params are cast to for fwd/bwd
    reduce_dtype: str = "fp32"    # dtype gradients are reduced in
    master_dtype: str = "fp32"    # dtype of the optimizer's master params
    # dynamic loss scaling (fp16 only): fp16's 5-bit exponent underflows on
    # typical LM gradients, so the loss is scaled up before backward and
    # grads unscaled after; overflow steps are skipped and halve the scale,
    # a streak of finite steps doubles it
    init_loss_scale: float = 2.0 ** 15
    scale_growth_interval: int = 2000

    @property
    def jax_compute_dtype(self):
        return DTYPE_MAP[self.compute_dtype]

    @property
    def jax_reduce_dtype(self):
        return DTYPE_MAP[self.reduce_dtype]


# Reference datautils/mixed_precision.py:10-46 name -> policy table.
POLICIES = {
    "fp16": PrecisionPolicy("fp16", compute_dtype="fp16", reduce_dtype="fp16"),
    "bf16": PrecisionPolicy("bf16", compute_dtype="bf16", reduce_dtype="bf16"),
    "bf16_hybrid": PrecisionPolicy("bf16_hybrid", compute_dtype="fp32",
                                   reduce_dtype="bf16"),
    "fp32": PrecisionPolicy("fp32"),
}


def get_policy(name: Optional[str]) -> Optional[PrecisionPolicy]:
    """Look up a mixed-precision policy (None -> no policy, use model dtype)."""
    if name is None:
        return None
    if name not in POLICIES:
        raise ValueError(
            f"Unknown mixed-precision policy '{name}'; "
            f"options: {list(POLICIES)}")
    return POLICIES[name]


def cast_floating(tree, dtype):
    """Cast floating-point leaves of a pytree to ``dtype`` (ints untouched)."""
    def cast(x):
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(dtype)
        return x

    return jax.tree_util.tree_map(cast, tree)
