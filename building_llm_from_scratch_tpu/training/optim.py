"""Optimizer + LR schedule.

Parity with the reference:
  - AdamW, weight_decay=0.1, torch defaults    (build_components.py:243-258)
  - hand-rolled linear-warmup + cosine-decay
    LR computed per step                       (train.py:100-107)
  - global-norm gradient clipping at 1.0       (train.py:114-120)

The reference mutates optimizer.param_groups every step; here the schedule
is a pure function of the step folded into the optax chain, so the whole
update lives inside the jitted train step. ZeRO-1 (ZeroRedundancyOptimizer,
build_components.py:250-256) is not a different optimizer in this design —
it is a sharding spec over this optimizer's state (parallel/sharding.py).
"""

from __future__ import annotations

import jax.numpy as jnp
import optax


def warmup_cosine_schedule(peak_lr: float, initial_lr: float, min_lr: float,
                           warmup_steps: int, total_steps: int):
    """The reference's exact LR curve (train.py:100-107).

    Step semantics match the reference's pre-increment counter: the first
    optimizer step sees global_step=1.
    """
    warmup_steps = max(1, warmup_steps)
    lr_increment = (peak_lr - initial_lr) / warmup_steps

    def schedule(count):
        step = count + 1                       # pre-incremented global_step
        warm = initial_lr + step * lr_increment
        denom = jnp.maximum(1, total_steps - warmup_steps)
        progress = (step - warmup_steps) / denom
        cosine = min_lr + (peak_lr - min_lr) * 0.5 * (
            1.0 + jnp.cos(jnp.pi * progress))
        return jnp.where(step < warmup_steps, warm, cosine)

    return schedule


def build_optimizer(peak_lr: float = 5e-4, initial_lr: float = 1e-5,
                    min_lr: float = 1e-6, warmup_steps: int = 10,
                    total_steps: int = 1000, weight_decay: float = 0.1,
                    grad_clip_norm: float = 1.0,
                    b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8,
                    schedule=None) -> optax.GradientTransformation:
    """clip(1.0) -> AdamW(wd=0.1) with the reference's warmup+cosine LR.

    Pass ``schedule`` to reuse an already-built LR schedule (keeps the
    logged LR and the applied LR the same object).
    """
    if schedule is None:
        schedule = warmup_cosine_schedule(peak_lr, initial_lr, min_lr,
                                          warmup_steps, total_steps)
    return optax.chain(
        optax.clip_by_global_norm(grad_clip_norm),
        optax.scale_by_adam(b1=b1, b2=b2, eps=eps),
        optax.add_decayed_weights(weight_decay),
        optax.scale_by_learning_rate(schedule),
    )
