"""Checkpoint save/restore.

The reference saves model weights only, with no optimizer state and NO
resume path anywhere (train.py:231-257, SURVEY.md §5). This module provides
the full design the reference lacks while keeping its export semantics:

  - ``save_checkpoint`` / ``load_checkpoint``: the COMPLETE train state
    (trainable + frozen params, optax state, step, rng), SHARDED: every
    process writes only its addressable shards (one ``.npy`` per unique
    shard, deduplicated across replicas) plus a JSON manifest — an
    Orbax-style resumable checkpoint (SURVEY.md §5 target). Peak host
    memory is ONE SHARD on both save and restore; nothing is gathered.
    Restore streams shard files (mmap) straight onto a target sharding —
    which may differ from the save-time sharding (any slice of the global
    array is assembled from the files that cover it), so an fsdp-8 run can
    restore into a dp-4 run. Requires the checkpoint dir to be on storage
    every process can reach (the norm for pod slices).
  - ``load_checkpoint`` also still reads the round-3 gathered format
    (one full .npy per leaf) for backward compatibility.
  - ``export_params`` / ``load_exported_params``: a single ``.npz`` of just
    the model params, gathered to process 0 — the analog of the reference's
    final ``model_pg_final.pth`` full-state-dict export (main.py:171-172).

Manifest integrity fields (fault-tolerance round): each shard entry in
``manifest["leaves"][i]["shards"]`` additionally records ``bytes`` (file
size) and ``sha256`` (content hash), computed by process 0 after the
all-shards barrier and before the manifest is committed. They are what
``training/resilience.validate_checkpoint`` checks so ``--resume auto``
can reject truncated/bit-rotted checkpoints and fall back to the previous
valid one. Manifests written before this round (no checksum fields) still
load and validate on shard existence alone. ``manifest["metadata"]`` may
also carry a ``cursor`` dict (epoch, file_index, batch_index) written by
the Trainer so resume fast-forwards the deterministic shuffled loader to
the exact mid-epoch position.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, Optional

import jax
import numpy as np

from building_llm_from_scratch_tpu.obs.metrics import emit_event
from building_llm_from_scratch_tpu.utils.logging import setup_logger

logger = setup_logger(__name__)

Params = Dict[str, Any]

_SHARDED_FORMAT = "sharded-v1"


def _restore_dtype(arr: np.ndarray, dtype_name: str) -> np.ndarray:
    """Recover the recorded dtype. np.load returns bf16 (and other
    ml_dtypes) arrays as raw void bytes; a view restores them losslessly."""
    target = np.dtype(dtype_name)        # ml_dtypes names resolve (jax loads it)
    if arr.dtype == target:
        return arr
    if arr.dtype.kind == "V" and arr.dtype.itemsize == target.itemsize:
        return arr.view(target)
    return arr.astype(target)


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        else:
            parts.append(str(p))
    return "/".join(parts)


def _norm_index(index, shape):
    """Serialize a devices_indices_map index (tuple of slices) as
    [[start, stop], ...] with Nones resolved against the global shape."""
    out = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        out.append([start, stop])
    return out


def _unique_shards(leaf):
    """(owner_device, index) per UNIQUE shard of a jax.Array: replicas are
    deduplicated; the device with the lowest id in each replica group owns
    the write."""
    shape = leaf.shape
    index_map = leaf.sharding.devices_indices_map(shape)
    groups: Dict[tuple, list] = {}
    for dev, index in index_map.items():
        key = tuple(tuple(b) for b in _norm_index(index, shape))
        groups.setdefault(key, []).append(dev)
    out = []
    for key in sorted(groups):
        devs = groups[key]
        owner = min(devs, key=lambda d: d.id)
        out.append((owner, key))
    return out


def sha256_file(path: str, chunk: int = 1 << 20) -> str:
    """Chunked file hash — the single implementation shared by the save
    path (recording) and resilience.validate_checkpoint (verifying)."""
    import hashlib

    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(chunk), b""):
            h.update(block)
    return h.hexdigest()


class _HashingWriter:
    """File-object tee: forwards writes while folding the exact bytes into
    a sha256. No ``fileno`` on purpose — numpy then streams the array
    through ``write()`` in chunks, so hashing adds NO extra array copy and
    the save path keeps its peak-host-memory-is-one-shard contract."""

    def __init__(self, f):
        import hashlib

        self._f = f
        self._h = hashlib.sha256()
        self.nbytes = 0

    def write(self, data):
        self._h.update(data)
        self.nbytes += len(data)
        return self._f.write(data)

    def hexdigest(self) -> str:
        return self._h.hexdigest()


def _write_shard_hashed(path: str, arr: np.ndarray):
    """np.save through a hashing tee — locally-written shards get their
    integrity record for free instead of a full read-back at manifest
    time. Returns (nbytes, sha256hex)."""
    with open(path, "wb") as f:
        w = _HashingWriter(f)
        np.save(w, arr)
    return w.nbytes, w.hexdigest()


def _barrier(name: str) -> None:
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name)


def _plan_leaf_shards(index: int, leaf):
    """Shard plan for one (``jnp_asarray``'d) leaf: the manifest shard
    entries plus ``owned`` — the ``[(fname, device_buffer)]`` THIS process
    is responsible for writing. One implementation shared by the
    synchronous save loop and the async snapshot (``snapshot_for_save``),
    so the two paths can never disagree about file layout or ownership."""
    n_procs = jax.process_count()
    local_ids = {d.id for d in jax.local_devices()}
    shards_meta, owned = [], []
    if n_procs > 1 and leaf.sharding.is_fully_addressable:
        # host-local leaf (e.g. jnp.asarray of a python scalar before any
        # jitted step): every process sees only its OWN devices in
        # devices_indices_map, so each would elect a local owner for the
        # same index and race np.save on the same file (round-4 ADVICE
        # low #2). Route through process 0 alone.
        fname = f"leaf_{index:05d}.shard_000.npy"
        shards_meta.append({"file": fname,
                            "index": [[0, d] for d in leaf.shape]})
        if jax.process_index() == 0:
            owned.append((fname, leaf))
    else:
        by_device = {s.device.id: s for s in leaf.addressable_shards}
        for k, (owner, index_key) in enumerate(_unique_shards(leaf)):
            fname = f"leaf_{index:05d}.shard_{k:03d}.npy"
            shards_meta.append({"file": fname,
                                "index": [list(se) for se in index_key]})
            if owner.id in local_ids:
                owned.append((fname, by_device[owner.id].data))
    return shards_meta, owned


def _plan_state_shards(state: Params):
    """Flatten ``state`` into per-leaf shard plans and post every owned
    shard's device->host copy asynchronously: ``np.asarray`` on each shard
    otherwise serializes one transfer per leaf, and on a remote-tunnel
    backend each blocking fetch pays full latency (r5: a save-every-100-
    steps run measured ~10x slower than training). Only OWNER shards are
    prefetched — replicas would multiply the transferred bytes by the
    local device count for nothing. Returns
    ``[(path, leaf, shards_meta, owned)]``."""
    leaves = jax.tree_util.tree_flatten_with_path(state)[0]
    planned = []
    for i, (path, leaf) in enumerate(leaves):
        leaf = jnp_asarray(leaf)
        shards_meta, owned = _plan_leaf_shards(i, leaf)
        planned.append((path, leaf, shards_meta, owned))
    for _, _, _, owned in planned:
        for _, buf in owned:
            try:
                buf.copy_to_host_async()
            except (AttributeError, RuntimeError):
                pass
    return planned


def save_checkpoint(ckpt_dir: str, state: Params,
                    extra_metadata: Optional[dict] = None) -> str:
    """Write ``state`` as a SHARDED checkpoint. Returns the dir.

    Every process writes the unique shards it owns (lowest-device-id
    replica wins, so replicated leaves are written exactly once across the
    job); process 0 writes the manifest. Nothing is gathered — peak host
    memory is one shard. All processes must see the same filesystem.

    Atomic commit (round-4 ADVICE medium #1): all shards land in a
    ``<dir>.tmp`` staging dir; after a cross-process barrier confirms every
    shard write finished, process 0 writes the manifest (still into the
    staging dir) and renames it over the target. A reader therefore never
    sees a manifest without all its shards. The commit is two renames
    (previous -> ``.old``, staging -> final); a preemption in the window
    between them leaves no dir at the tag itself, but BOTH neighbours are
    complete (``.tmp`` holds the new checkpoint incl. manifest, ``.old``
    the previous one) and ``load_checkpoint``/``checkpoint_metadata``
    transparently fall back to them (``_resolve_ckpt_dir``), so no commit
    ordering loses a restorable checkpoint.
    """
    t_save = time.perf_counter()
    is_proc0 = jax.process_index() == 0
    tmp_dir = ckpt_dir.rstrip("/") + ".tmp"
    if is_proc0:
        # a crashed earlier save may have left a stale staging dir
        if os.path.isdir(tmp_dir):
            import shutil

            shutil.rmtree(tmp_dir)
        os.makedirs(tmp_dir, exist_ok=True)
    _barrier(f"ckpt_stage:{ckpt_dir}")
    os.makedirs(tmp_dir, exist_ok=True)
    manifest = {"format": _SHARDED_FORMAT, "leaves": [],
                "metadata": extra_metadata or {}}
    planned = _plan_state_shards(state)
    local_hashes: Dict[str, tuple] = {}      # fname -> (bytes, sha256)
    for i, (path, leaf, shards_meta, owned) in enumerate(planned):
        for fname, buf in owned:
            nb, hx = _write_shard_hashed(os.path.join(tmp_dir, fname),
                                         np.asarray(buf))
            if is_proc0:
                local_hashes[fname] = (nb, hx)
        manifest["leaves"].append({
            "index": i,
            "path": _path_str(path),
            "shape": list(leaf.shape),
            "dtype": str(leaf.dtype),
            "shards": shards_meta,
        })
    # every shard file is on disk before the manifest exists anywhere
    _barrier(f"ckpt_shards:{ckpt_dir}")
    if is_proc0:
        import shutil

        # integrity records for resilience.validate_checkpoint: every shard
        # gets its size + sha256 into the manifest BEFORE the commit
        # rename, so a truncated or bit-flipped file is detectable at
        # resume time. Shards this process wrote were hashed at write time;
        # only shards OTHER hosts wrote (on the shared filesystem, complete
        # per the barrier above) need a read-back — zero extra I/O on
        # single-host runs.
        for leaf_meta in manifest["leaves"]:
            for sh in leaf_meta["shards"]:
                if sh["file"] in local_hashes:
                    sh["bytes"], sh["sha256"] = local_hashes[sh["file"]]
                else:
                    spath = os.path.join(tmp_dir, sh["file"])
                    sh["bytes"] = os.path.getsize(spath)
                    sh["sha256"] = sha256_file(spath)
        with open(os.path.join(tmp_dir, "manifest.json"), "w") as f:
            json.dump(manifest, f, indent=1)
        old_dir = None
        if os.path.isdir(ckpt_dir):
            old_dir = ckpt_dir.rstrip("/") + ".old"
            if os.path.isdir(old_dir):
                shutil.rmtree(old_dir)
            os.rename(ckpt_dir, old_dir)
        os.rename(tmp_dir, ckpt_dir)
        if old_dir is not None:
            shutil.rmtree(old_dir)
    # no process returns (and e.g. immediately resaves the same tag or
    # resumes from it) before the commit rename is visible
    _barrier(f"ckpt_commit:{ckpt_dir}")
    # structured telemetry: the coordinator's manifest carries every
    # shard's size, so total bytes come for free (other hosts report None
    # rather than a partial local sum)
    total_bytes = (sum(int(sh.get("bytes", 0)) for leaf in manifest["leaves"]
                       for sh in leaf["shards"]) if is_proc0 else None)
    emit_event("checkpoint_save", path=ckpt_dir,
               step=(extra_metadata or {}).get("global_step"),
               seconds=round(time.perf_counter() - t_save, 4),
               bytes=total_bytes, leaves=len(manifest["leaves"]))
    return ckpt_dir


def snapshot_for_save(state: Params,
                      extra_metadata: Optional[dict] = None) -> dict:
    """Materialize everything ``write_snapshot`` needs to write a sharded
    checkpoint WITHOUT touching device state again: the manifest skeleton
    plus host copies of every owned shard.

    This is the synchronous half of an async save (training/
    async_checkpoint.py): it MUST run on the main thread — ``np.asarray``
    below blocks until the in-flight donated steps that produce ``state``
    have finished and the posted D2H DMAs land, which is device work the
    background writer thread must never touch. Cost vs the streaming
    synchronous save: the whole state is host-resident at once (that IS
    the async tradeoff — the write, hash and commit I/O move off the
    critical path in exchange for one state-sized host buffer).
    """
    planned = _plan_state_shards(state)
    manifest = {"format": _SHARDED_FORMAT, "leaves": [],
                "metadata": extra_metadata or {}}
    arrays: Dict[str, np.ndarray] = {}
    for i, (path, leaf, shards_meta, owned) in enumerate(planned):
        for fname, buf in owned:
            arrays[fname] = np.asarray(buf)
        manifest["leaves"].append({
            "index": i,
            "path": _path_str(path),
            "shape": list(leaf.shape),
            "dtype": str(leaf.dtype),
            "shards": shards_meta,
        })
    return {"manifest": manifest, "arrays": arrays}


def write_snapshot(ckpt_dir: str, snapshot: dict) -> str:
    """Write a ``snapshot_for_save`` snapshot as a committed checkpoint.

    Pure host I/O over host arrays — safe on a background thread; the
    same ``.tmp`` staging, sha256-manifest and two-rename commit sequence
    as ``save_checkpoint``, so readers (``load_checkpoint``,
    ``validate_checkpoint``, ``_resolve_ckpt_dir`` recovery) cannot tell
    the two writers apart. Single-process writes only: the async path
    falls back to the synchronous (barrier-using) save on multi-host runs
    — ``AsyncCheckpointer`` enforces that, this function just refuses.
    """
    import shutil

    if jax.process_count() > 1:
        raise RuntimeError(
            "write_snapshot is single-process only (its commit sequence "
            "has no cross-host barriers); use save_checkpoint.")
    t_save = time.perf_counter()
    manifest, arrays = snapshot["manifest"], snapshot["arrays"]
    tmp_dir = ckpt_dir.rstrip("/") + ".tmp"
    if os.path.isdir(tmp_dir):
        shutil.rmtree(tmp_dir)
    os.makedirs(tmp_dir, exist_ok=True)
    for leaf_meta in manifest["leaves"]:
        for sh in leaf_meta["shards"]:
            nb, hx = _write_shard_hashed(os.path.join(tmp_dir, sh["file"]),
                                         arrays[sh["file"]])
            sh["bytes"], sh["sha256"] = nb, hx
    with open(os.path.join(tmp_dir, "manifest.json"), "w") as f:
        json.dump(manifest, f, indent=1)
    old_dir = None
    if os.path.isdir(ckpt_dir):
        old_dir = ckpt_dir.rstrip("/") + ".old"
        if os.path.isdir(old_dir):
            shutil.rmtree(old_dir)
        os.rename(ckpt_dir, old_dir)
    os.rename(tmp_dir, ckpt_dir)
    if old_dir is not None:
        shutil.rmtree(old_dir)
    total_bytes = sum(int(sh.get("bytes", 0)) for leaf in manifest["leaves"]
                      for sh in leaf["shards"])
    emit_event("checkpoint_save", path=ckpt_dir,
               step=manifest["metadata"].get("global_step"),
               seconds=round(time.perf_counter() - t_save, 4),
               bytes=total_bytes, leaves=len(manifest["leaves"]),
               writer="async")
    return ckpt_dir


def save_checkpoint_gathered(ckpt_dir: str, state: Params,
                             extra_metadata: Optional[dict] = None) -> str:
    """The round-3 format: every leaf gathered full and written by process
    0 (the reference's FULL_STATE_DICT rank-0 gather, train.py:244-249).
    Kept for interop with round-3 checkpoints and as the compat-path test
    fixture; ``save_checkpoint`` (sharded) is the default."""
    from building_llm_from_scratch_tpu.parallel.collectives import gather_full

    is_writer = jax.process_index() == 0
    leaves = jax.tree_util.tree_flatten_with_path(state)[0]
    if is_writer:
        os.makedirs(ckpt_dir, exist_ok=True)
    manifest = {"leaves": [], "metadata": extra_metadata or {}}
    for i, (path, leaf) in enumerate(leaves):
        arr = np.asarray(gather_full(leaf))
        manifest["leaves"].append({
            "index": i,
            "path": _path_str(path),
            "shape": list(arr.shape),
            "dtype": str(arr.dtype),
        })
        if is_writer:
            np.save(os.path.join(ckpt_dir, f"leaf_{i:05d}.npy"), arr)
    if is_writer:
        with open(os.path.join(ckpt_dir, "manifest.json"), "w") as f:
            json.dump(manifest, f, indent=1)
    return ckpt_dir


def jnp_asarray(leaf):
    """Leaves like python ints (step counters built outside jit) become
    committed jax arrays so sharding introspection works uniformly."""
    if isinstance(leaf, jax.Array):
        return leaf
    import jax.numpy as jnp

    return jnp.asarray(leaf)


def _read_leaf_slice(ckpt_dir: str, meta: dict, index) -> np.ndarray:
    """Assemble an arbitrary slice of a leaf from its shard files (mmap —
    only the bytes covering the request are read)."""
    shape = tuple(meta["shape"])
    bounds = _norm_index(index, shape)
    target_shape = tuple(b[1] - b[0] for b in bounds)
    dtype = np.dtype(meta["dtype"])
    # fast path: a single shard exactly matches the request
    for sh in meta["shards"]:
        if [list(map(int, b)) for b in sh["index"]] == bounds:
            arr = np.load(os.path.join(ckpt_dir, sh["file"]))
            return _restore_dtype(arr, meta["dtype"])
    out = np.empty(target_shape, dtype)
    filled = 0
    for sh in meta["shards"]:
        s_bounds = sh["index"]
        # overlap of shard box and requested box, per dim
        lo = [max(a[0], b[0]) for a, b in zip(s_bounds, bounds)]
        hi = [min(a[1], b[1]) for a, b in zip(s_bounds, bounds)]
        if any(l >= h for l, h in zip(lo, hi)):
            continue
        src = np.load(os.path.join(ckpt_dir, sh["file"]), mmap_mode="r")
        src = _restore_dtype(np.asarray(src[tuple(
            slice(l - sb[0], h - sb[0])
            for l, h, sb in zip(lo, hi, s_bounds))]), meta["dtype"])
        out[tuple(slice(l - b[0], h - b[0])
                  for l, h, b in zip(lo, hi, bounds))] = src
        filled += src.size
    if filled < int(np.prod(target_shape)):
        raise ValueError(
            f"Checkpoint shards for leaf '{meta['path']}' do not cover the "
            f"requested slice {bounds} — incomplete checkpoint?")
    return out


def _resolve_ckpt_dir(ckpt_dir: str) -> str:
    """Resolve a checkpoint tag to a readable dir, recovering from a save
    preempted inside the two-rename commit window: prefer the tag itself,
    then the completed staging dir (``.tmp`` — manifest is written there
    last, so its presence means every shard is on disk), then the
    displaced previous checkpoint (``.old``)."""
    if os.path.exists(os.path.join(ckpt_dir, "manifest.json")):
        return ckpt_dir
    for suffix in (".tmp", ".old"):
        cand = ckpt_dir.rstrip("/") + suffix
        if os.path.exists(os.path.join(cand, "manifest.json")):
            logger.warning(
                "Checkpoint %s has no manifest (save preempted mid-commit?)"
                "; recovering from %s", ckpt_dir, cand)
            return cand
    return ckpt_dir


def _cleanup_stale_siblings(ckpt_dir: str) -> None:
    """Remove ``.tmp``/``.old`` staging dirs orphaned by a crashed save.

    Only called once the tag itself resolved (its manifest exists), so the
    siblings are by definition leftovers, not the recovery copy. Process 0
    only — peers resolve the committed tag and never read the orphans."""
    import jax as _jax

    if _jax.process_index() != 0:
        return
    import shutil

    for suffix in (".tmp", ".old"):
        cand = ckpt_dir.rstrip("/") + suffix
        if os.path.isdir(cand):
            logger.warning(
                "Removing orphaned checkpoint staging dir %s (left by a "
                "crashed save).", cand)
            shutil.rmtree(cand, ignore_errors=True)


def _read_manifest(ckpt_dir: str) -> dict:
    """Read + structurally check a checkpoint manifest, raising ONE clear
    ``ValueError`` naming the dir and what is missing/malformed instead of
    a raw ``FileNotFoundError``/``KeyError``/``JSONDecodeError``."""
    manifest_path = os.path.join(ckpt_dir, "manifest.json")
    if not os.path.isfile(manifest_path):
        raise ValueError(
            f"'{ckpt_dir}' is not a readable checkpoint: manifest.json is "
            "missing (not a checkpoint directory, or the save died before "
            "its commit and left no recoverable .tmp/.old staging dir).")
    try:
        with open(manifest_path) as f:
            manifest = json.load(f)
    except (json.JSONDecodeError, UnicodeDecodeError, OSError) as e:
        raise ValueError(
            f"Checkpoint manifest {manifest_path} is malformed "
            f"({type(e).__name__}: {e}); the checkpoint cannot be "
            "restored.") from e
    if not isinstance(manifest, dict) or not isinstance(
            manifest.get("leaves"), list):
        raise ValueError(
            f"Checkpoint manifest {manifest_path} is malformed: expected a "
            "JSON object with a 'leaves' list.")
    return manifest


def load_checkpoint(ckpt_dir: str, template_state: Params,
                    shardings: Optional[Params] = None) -> Params:
    """Restore a checkpoint into the structure of ``template_state``.

    ``template_state`` (e.g. a freshly initialized state) supplies the
    pytree structure; leaf paths are cross-checked against the manifest.
    If ``shardings`` (a matching pytree of jax.sharding.Sharding) is given,
    each leaf lands directly on its target placement — for sharded-v1
    checkpoints each process reads ONLY the bytes its devices need
    (restore-time sharding may differ from save-time sharding).

    Handles both the sharded-v1 format and the round-3 gathered format
    (full ``leaf_NNNNN.npy`` files).
    """
    t_load = time.perf_counter()
    resolved = _resolve_ckpt_dir(ckpt_dir)
    if resolved == ckpt_dir:
        _cleanup_stale_siblings(ckpt_dir)
    ckpt_dir = resolved
    manifest = _read_manifest(ckpt_dir)
    sharded = manifest.get("format") == _SHARDED_FORMAT
    flat, treedef = jax.tree_util.tree_flatten_with_path(template_state)
    if len(flat) != len(manifest["leaves"]):
        raise ValueError(
            f"Checkpoint has {len(manifest['leaves'])} leaves but template "
            f"state has {len(flat)} — structure mismatch.")
    shard_leaves = (jax.tree_util.tree_leaves(shardings)
                    if shardings is not None else [None] * len(flat))
    loaded = []
    for (path, tmpl), meta, shard in zip(flat, manifest["leaves"],
                                         shard_leaves):
        if _path_str(path) != meta["path"]:
            raise ValueError(
                f"Leaf path mismatch: template {_path_str(path)} vs "
                f"checkpoint {meta['path']}")
        tmpl_shape = tuple(getattr(tmpl, "shape", ()))
        tmpl_dtype = str(getattr(tmpl, "dtype", ""))
        if tuple(meta["shape"]) != tmpl_shape:
            # exactly the train-state PRNG leaf (state["rng"]) — an
            # endswith match would also catch unrelated leaves whose name
            # merely ends in "rng" and silently skip their structure check
            if meta["path"] == "rng":
                # PRNG keys are impl-specific (threefry (2,) vs rbg (4,)
                # uint32); a checkpoint written under a different default
                # impl cannot restore its dropout stream — keep the
                # template's fresh key instead of bricking the resume
                logger.warning(
                    "Checkpoint rng leaf has shape %s but the current PRNG "
                    "impl uses %s; keeping a fresh rng (dropout stream "
                    "restarts).", tuple(meta["shape"]), tmpl_shape)
                # same placement contract as every other restored leaf
                loaded.append(jax.device_put(tmpl, shard)
                              if shard is not None else tmpl)
                continue
            raise ValueError(
                f"Checkpoint leaf '{meta['path']}' has shape "
                f"{tuple(meta['shape'])} but the model expects {tmpl_shape} "
                "— wrong model size/config for this checkpoint.")
        if tmpl_dtype and meta["dtype"] != tmpl_dtype:
            raise ValueError(
                f"Checkpoint leaf '{meta['path']}' has dtype "
                f"{meta['dtype']} but the model expects {tmpl_dtype} "
                "— was the checkpoint written with a different --data_type?")
        if sharded and shard is not None:
            # stream shard files straight onto the target sharding: the
            # callback is invoked once per addressable shard index
            arr = jax.make_array_from_callback(
                tuple(meta["shape"]), shard,
                lambda idx, meta=meta: _read_leaf_slice(ckpt_dir, meta, idx))
            loaded.append(arr)
            continue
        if sharded:
            full_idx = tuple(slice(0, d) for d in meta["shape"])
            arr = _read_leaf_slice(ckpt_dir, meta, full_idx)
        else:
            arr = np.load(os.path.join(ckpt_dir,
                                       f"leaf_{meta['index']:05d}.npy"))
            arr = _restore_dtype(arr, meta["dtype"])
        if shard is not None:
            loaded.append(jax.device_put(arr, shard))
        else:
            loaded.append(jax.device_put(arr))
    emit_event("checkpoint_restore", path=ckpt_dir,
               step=manifest.get("metadata", {}).get("global_step"),
               seconds=round(time.perf_counter() - t_load, 4),
               leaves=len(manifest["leaves"]))
    return jax.tree_util.tree_unflatten(treedef, loaded)


def checkpoint_metadata(ckpt_dir: str) -> dict:
    return _read_manifest(_resolve_ckpt_dir(ckpt_dir)).get("metadata", {})


def export_params(path: str, params: Params) -> str:
    """Single-file params export (reference final .pth, main.py:171-172).

    Like ``save_checkpoint``, each leaf passes through ``gather_full``
    (leaf-at-a-time — all processes iterate in the same order) so
    mesh-sharded params on multi-host runs reassemble before process 0
    writes. Dtypes are recorded per array (``__dtype__.<key>`` entries)
    because np.savez stores ml_dtypes arrays as raw void bytes."""
    from building_llm_from_scratch_tpu.parallel.collectives import gather_full

    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    arrays = {}
    for p, leaf in flat:
        key = _path_str(p)
        arr = np.asarray(gather_full(leaf))
        arrays[key] = arr
        arrays[f"__dtype__.{key}"] = np.asarray(str(arr.dtype))
    if jax.process_index() == 0:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        np.savez(path, **arrays)
    return path


def load_exported_params(path: str, template_params: Params) -> Params:
    """Load an ``export_params`` file into the template's structure."""
    data = np.load(path)
    flat, treedef = jax.tree_util.tree_flatten_with_path(template_params)
    leaves = []
    for p, tmpl in flat:
        key = _path_str(p)
        if key not in data:
            raise KeyError(f"Export missing parameter {key}")
        dtype_key = f"__dtype__.{key}"
        # restore through the RECORDED dtype (falling back to the template
        # for exports written before dtypes were recorded), then cast to the
        # template — never reinterpret bits across same-width dtypes
        recorded = (str(data[dtype_key]) if dtype_key in data
                    else str(tmpl.dtype))
        arr = _restore_dtype(data[key], recorded).astype(tmpl.dtype)
        leaves.append(jax.device_put(arr))
    return jax.tree_util.tree_unflatten(treedef, leaves)
