"""Checkpoint save/restore.

The reference saves model weights only, with no optimizer state and NO
resume path anywhere (train.py:231-257, SURVEY.md §5). This module provides
the full design the reference lacks while keeping its export semantics:

  - ``save_checkpoint`` / ``load_checkpoint``: the COMPLETE train state
    (trainable + frozen params, optax state, step, rng) as one .npy file per
    leaf + a JSON manifest — a resumable checkpoint. Only process 0 writes
    (the reference's rank-0-save-with-barriers pattern, train.py:232-240);
    restore can place leaves directly onto a target sharding so large models
    never materialize unsharded on one chip.
  - ``export_params`` / ``load_exported_params``: a single ``.npz`` of just
    the model params — the analog of the reference's final
    ``model_pg_final.pth`` full-state-dict export (main.py:171-172).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

import jax
import numpy as np

from building_llm_from_scratch_tpu.utils.logging import setup_logger

logger = setup_logger(__name__)

Params = Dict[str, Any]


def _restore_dtype(arr: np.ndarray, dtype_name: str) -> np.ndarray:
    """Recover the recorded dtype. np.load returns bf16 (and other
    ml_dtypes) arrays as raw void bytes; a view restores them losslessly."""
    target = np.dtype(dtype_name)        # ml_dtypes names resolve (jax loads it)
    if arr.dtype == target:
        return arr
    if arr.dtype.kind == "V" and arr.dtype.itemsize == target.itemsize:
        return arr.view(target)
    return arr.astype(target)


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        else:
            parts.append(str(p))
    return "/".join(parts)


def save_checkpoint(ckpt_dir: str, state: Params,
                    extra_metadata: Optional[dict] = None) -> str:
    """Write every leaf of ``state`` plus a manifest. Returns the dir.

    Each leaf goes through ``gather_full`` so fsdp/zero1-sharded state on a
    multi-host mesh (non-addressable arrays, where a bare device_get
    raises) is reassembled via process_allgather before process 0 writes —
    the reference's FULL_STATE_DICT rank-0 gather (train.py:244-249).
    Gathering happens ONE LEAF AT A TIME inside the loop (every process
    iterates leaves in the same order, so the collectives line up) to keep
    peak host RAM at one full leaf, not the whole state.
    """
    from building_llm_from_scratch_tpu.parallel.collectives import gather_full

    is_writer = jax.process_index() == 0
    leaves = jax.tree_util.tree_flatten_with_path(state)[0]
    if is_writer:
        os.makedirs(ckpt_dir, exist_ok=True)
    manifest = {"leaves": [], "metadata": extra_metadata or {}}
    for i, (path, leaf) in enumerate(leaves):
        name = f"leaf_{i:05d}"
        arr = np.asarray(gather_full(leaf))
        manifest["leaves"].append({
            "index": i,
            "path": _path_str(path),
            "shape": list(arr.shape),
            "dtype": str(arr.dtype),
        })
        if is_writer:
            np.save(os.path.join(ckpt_dir, name + ".npy"), arr)
    if is_writer:
        with open(os.path.join(ckpt_dir, "manifest.json"), "w") as f:
            json.dump(manifest, f, indent=1)
    return ckpt_dir


def load_checkpoint(ckpt_dir: str, template_state: Params,
                    shardings: Optional[Params] = None) -> Params:
    """Restore a checkpoint into the structure of ``template_state``.

    ``template_state`` (e.g. a freshly initialized state) supplies the
    pytree structure; leaf paths are cross-checked against the manifest.
    If ``shardings`` (a matching pytree of jax.sharding.Sharding) is given,
    each leaf is device_put directly to its target placement.
    """
    with open(os.path.join(ckpt_dir, "manifest.json")) as f:
        manifest = json.load(f)
    flat, treedef = jax.tree_util.tree_flatten_with_path(template_state)
    if len(flat) != len(manifest["leaves"]):
        raise ValueError(
            f"Checkpoint has {len(manifest['leaves'])} leaves but template "
            f"state has {len(flat)} — structure mismatch.")
    shard_leaves = (jax.tree_util.tree_leaves(shardings)
                    if shardings is not None else [None] * len(flat))
    loaded = []
    for (path, tmpl), meta, shard in zip(flat, manifest["leaves"],
                                         shard_leaves):
        if _path_str(path) != meta["path"]:
            raise ValueError(
                f"Leaf path mismatch: template {_path_str(path)} vs "
                f"checkpoint {meta['path']}")
        tmpl_shape = tuple(getattr(tmpl, "shape", ()))
        tmpl_dtype = str(getattr(tmpl, "dtype", ""))
        if tuple(meta["shape"]) != tmpl_shape:
            if meta["path"].endswith("rng"):
                # PRNG keys are impl-specific (threefry (2,) vs rbg (4,)
                # uint32); a checkpoint written under a different default
                # impl cannot restore its dropout stream — keep the
                # template's fresh key instead of bricking the resume
                logger.warning(
                    "Checkpoint rng leaf has shape %s but the current PRNG "
                    "impl uses %s; keeping a fresh rng (dropout stream "
                    "restarts).", tuple(meta["shape"]), tmpl_shape)
                # same placement contract as every other restored leaf
                loaded.append(jax.device_put(tmpl, shard)
                              if shard is not None else tmpl)
                continue
            raise ValueError(
                f"Checkpoint leaf '{meta['path']}' has shape "
                f"{tuple(meta['shape'])} but the model expects {tmpl_shape} "
                "— wrong model size/config for this checkpoint.")
        if tmpl_dtype and meta["dtype"] != tmpl_dtype:
            raise ValueError(
                f"Checkpoint leaf '{meta['path']}' has dtype "
                f"{meta['dtype']} but the model expects {tmpl_dtype} "
                "— was the checkpoint written with a different --data_type?")
        arr = np.load(os.path.join(ckpt_dir, f"leaf_{meta['index']:05d}.npy"))
        arr = _restore_dtype(arr, meta["dtype"])
        if shard is not None:
            loaded.append(jax.device_put(arr, shard))
        else:
            loaded.append(jax.device_put(arr))
    return jax.tree_util.tree_unflatten(treedef, loaded)


def checkpoint_metadata(ckpt_dir: str) -> dict:
    with open(os.path.join(ckpt_dir, "manifest.json")) as f:
        return json.load(f)["metadata"]


def export_params(path: str, params: Params) -> str:
    """Single-file params export (reference final .pth, main.py:171-172).

    Like ``save_checkpoint``, each leaf passes through ``gather_full``
    (leaf-at-a-time — all processes iterate in the same order) so
    mesh-sharded params on multi-host runs reassemble before process 0
    writes. Dtypes are recorded per array (``__dtype__.<key>`` entries)
    because np.savez stores ml_dtypes arrays as raw void bytes."""
    from building_llm_from_scratch_tpu.parallel.collectives import gather_full

    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    arrays = {}
    for p, leaf in flat:
        key = _path_str(p)
        arr = np.asarray(gather_full(leaf))
        arrays[key] = arr
        arrays[f"__dtype__.{key}"] = np.asarray(str(arr.dtype))
    if jax.process_index() == 0:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        np.savez(path, **arrays)
    return path


def load_exported_params(path: str, template_params: Params) -> Params:
    """Load an ``export_params`` file into the template's structure."""
    data = np.load(path)
    flat, treedef = jax.tree_util.tree_flatten_with_path(template_params)
    leaves = []
    for p, tmpl in flat:
        key = _path_str(p)
        if key not in data:
            raise KeyError(f"Export missing parameter {key}")
        dtype_key = f"__dtype__.{key}"
        # restore through the RECORDED dtype (falling back to the template
        # for exports written before dtypes were recorded), then cast to the
        # template — never reinterpret bits across same-width dtypes
        recorded = (str(data[dtype_key]) if dtype_key in data
                    else str(tmpl.dtype))
        arr = _restore_dtype(data[key], recorded).astype(tmpl.dtype)
        leaves.append(jax.device_put(arr))
    return jax.tree_util.tree_unflatten(treedef, leaves)
