"""Fault-tolerance subsystem: preemption-safe stop, checkpoint integrity,
auto-resume discovery, retention GC, and a loss watchdog.

A real TPU run dies to preemption, not Ctrl-C: v5e capacity is routinely
preemptible, and spot economics only work if a killed worker loses seconds,
not epochs. This module supplies the pieces the trainer wires together:

  - ``GracefulStopper``: SIGTERM/SIGINT set a flag; the trainer polls it at
    step boundaries, writes a final checkpoint, and returns cleanly (exit 0).
    Multi-host safe: the signal is observed locally but the stop decision is
    agreed globally (all-reduce OR over processes), so no host bails out of
    a step loop while its peers block in a collective.
  - ``validate_checkpoint`` / ``find_latest_valid_checkpoint``: integrity
    checks over the manifest's per-shard ``bytes``/``sha256`` records
    (written by ``save_checkpoint`` since this round; manifests without them
    still validate on existence alone). Auto-resume walks checkpoints
    newest-first and falls back — loudly — past corrupt ones.
  - ``resolve_resume``: the ``--resume auto|off|<dir>`` policy. ``auto``
    discovers the latest valid checkpoint under ``output_dir`` so a
    relaunched preempted job needs no hand-typed path.
  - ``prune_checkpoints``: retention GC for ``--keep_ckpts N`` — only
    step-tagged ``model_pg_<step>`` dirs are eligible; ``interrupted`` and
    ``final`` checkpoints and the newest N survive.
  - ``LossWatchdog``: running-median spike / non-finite detection for
    bf16/fp32 runs (fp16 already skips bad steps via loss scaling) — halts
    with a diagnostic instead of training on a diverged model for hours.
"""

from __future__ import annotations

import os
import re
import shutil
import signal
from collections import deque
from typing import List, Optional, Tuple

import numpy as np

from building_llm_from_scratch_tpu.obs.metrics import emit_event
from building_llm_from_scratch_tpu.utils.logging import setup_logger

logger = setup_logger(__name__)

#: Prefix shared by every Trainer-written checkpoint dir (model_pg_<tag>).
CKPT_PREFIX = "model_pg_"

_STEP_TAGGED = re.compile(r"^" + re.escape(CKPT_PREFIX) + r"(\d+)$")


class PreemptionStop(Exception):
    """Raised by the trainer at a step boundary after a graceful-stop
    request; callers treat it as a clean early return, not a failure."""


class TrainingDivergedError(RuntimeError):
    """Raised by ``LossWatchdog`` on non-finite or spiking train loss."""


# ---------------------------------------------------------------------------
# Graceful stop (SIGTERM/SIGINT -> stop at the next step boundary)
# ---------------------------------------------------------------------------

class GracefulStopper:
    """Context manager that converts SIGTERM/SIGINT into a polled flag.

    Inside the context the first signal only records the request — the
    training loop finishes its current step, writes a checkpoint, and
    returns. A second SIGINT raises ``KeyboardInterrupt`` (the impatient
    Ctrl-C Ctrl-C escape hatch). Handlers are restored on exit; when not
    running in the main thread (where ``signal.signal`` is illegal) the
    stopper degrades to a plain never-set flag.
    """

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT),
                 sync_every: int = 8):
        self._signals = signals
        self._previous = {}
        self._calls = 0
        self._sigint_seen = False
        self.sync_every = max(1, sync_every)
        self.requested = False

    def _handle(self, signum, frame):
        # only a SECOND Ctrl-C aborts: a SIGINT after a SIGTERM (operator
        # watching a preemption drain) must not degrade the in-progress
        # graceful stop into the best-effort abort path
        if signum == signal.SIGINT:
            if self._sigint_seen:
                raise KeyboardInterrupt
            self._sigint_seen = True
        self.requested = True
        emit_event("preemption_signal",
                   signal=signal.Signals(signum).name)
        logger.warning(
            "Received %s: will checkpoint and stop at the next step "
            "boundary (send SIGINT again to abort immediately).",
            signal.Signals(signum).name)

    def __enter__(self) -> "GracefulStopper":
        for s in self._signals:
            try:
                self._previous[s] = signal.signal(s, self._handle)
            except ValueError:          # not the main thread
                logger.warning(
                    "Cannot install %s handler outside the main thread; "
                    "graceful stop disabled for it.", signal.Signals(s).name)
        return self

    def __exit__(self, *exc):
        for s, prev in self._previous.items():
            signal.signal(s, prev)
        self._previous.clear()
        return False

    def should_stop(self) -> bool:
        """Global stop decision: OR of every process's local flag.

        All processes must call this the same number of times — the trainer
        calls it exactly once per step, which every host executes in
        lockstep. Multi-host, the agreement collective only runs every
        ``sync_every`` calls (a blocking per-step allgather would serialize
        hosts to the slowest one on every step); between sync points this
        returns False even if the local flag is set, so no host ever stops
        without its peers — the stop lands at most sync_every-1 steps late,
        well inside any preemption grace window.
        """
        import jax

        if jax.process_count() == 1:
            return self.requested
        self._calls += 1
        if self._calls % self.sync_every:
            return False
        from jax.experimental import multihost_utils

        flags = multihost_utils.process_allgather(
            np.asarray([self.requested], dtype=np.int32))
        return bool(np.max(flags))


# ---------------------------------------------------------------------------
# Checkpoint integrity + discovery
# ---------------------------------------------------------------------------

def validate_checkpoint(ckpt_dir: str) -> Optional[str]:
    """Integrity-check one checkpoint. Returns None when valid, else a
    human-readable reason — it NEVER raises, because its whole purpose is
    letting ``--resume auto`` fall back past corrupt checkpoints.

    Validity = the manifest parses (``checkpoint._read_manifest``) AND
    every shard file referenced by it exists with the recorded size and
    sha256. Manifests written before checksums were recorded (no
    ``bytes``/``sha256`` fields) validate on existence alone — old
    checkpoints stay readable.
    """
    from building_llm_from_scratch_tpu.training.checkpoint import (
        _read_manifest,
        _resolve_ckpt_dir,
        sha256_file,
    )

    resolved = _resolve_ckpt_dir(ckpt_dir)
    try:
        manifest = _read_manifest(resolved)
        for meta in manifest["leaves"]:
            shards = meta.get("shards")
            if shards is None:
                # round-3 gathered format: one full .npy per leaf
                shards = [{"file": f"leaf_{meta.get('index', 0):05d}.npy"}]
            for sh in shards:
                path = os.path.join(resolved, sh["file"])
                if not os.path.isfile(path):
                    return f"shard file {sh['file']} is missing"
                if "bytes" in sh:
                    size = os.path.getsize(path)
                    if size != int(sh["bytes"]):
                        return (f"shard file {sh['file']} is {size} bytes, "
                                f"manifest records {sh['bytes']} "
                                "(truncated?)")
                if "sha256" in sh and sha256_file(path) != sh["sha256"]:
                    return f"shard file {sh['file']} fails its sha256 checksum"
    except (ValueError, KeyError, TypeError, AttributeError, OSError) as e:
        # structurally-corrupt manifests (leaves entries that aren't dicts,
        # shard entries missing 'file', ...) are just another invalid shape
        return f"manifest is unusable ({type(e).__name__}: {e})"
    return None


def list_checkpoints(output_dir: str) -> List[Tuple[int, str]]:
    """All Trainer checkpoints under ``output_dir`` as (step, path), path
    being the commit tag (``.tmp``/``.old`` recovery is handled inside the
    checkpoint reader). Unreadable entries are skipped with a log line.
    """
    from building_llm_from_scratch_tpu.training.checkpoint import (
        checkpoint_metadata,
    )

    if not os.path.isdir(output_dir):
        return []
    tags = set()
    for name in sorted(os.listdir(output_dir)):
        if not name.startswith(CKPT_PREFIX):
            continue
        if not os.path.isdir(os.path.join(output_dir, name)):
            continue                     # e.g. model_pg_final.npz export
        for suffix in (".tmp", ".old"):
            if name.endswith(suffix):
                # a save preempted mid-commit may have left ONLY the staging
                # dir; resolve through the base tag
                name = name[: -len(suffix)]
                break
        tags.add(name)
    out = []
    for name in sorted(tags):
        path = os.path.join(output_dir, name)
        try:
            meta = checkpoint_metadata(path)
            out.append((int(meta.get("global_step", 0)), path))
        except (ValueError, OSError) as e:
            logger.warning("Skipping unreadable checkpoint %s: %s", path, e)
    return sorted(out)


def find_latest_valid_checkpoint(output_dir: str,
                                 predicate=None) -> Optional[str]:
    """The newest checkpoint (by recorded global_step) that passes
    ``validate_checkpoint``. Corrupt candidates are logged LOUDLY and
    skipped, so a truncated latest checkpoint falls back to the previous
    valid one instead of crashing the resume.

    ``predicate(metadata) -> bool`` filters candidates by manifest
    metadata: trainer and fleet (``--mode finetune_fleet``) checkpoints
    share the ``model_pg_`` prefix and one ``--output_dir``, so each
    mode's AUTO-discovery must skip the other's checkpoints QUIETLY
    (they are valid, just not restorable here) instead of picking one
    and dying in the restore — the loud type refusal is reserved for an
    explicitly named ``--resume_from``."""
    from building_llm_from_scratch_tpu.training.checkpoint import (
        checkpoint_metadata,
    )

    for step, path in reversed(list_checkpoints(output_dir)):
        if predicate is not None:
            try:
                keep = predicate(checkpoint_metadata(path))
            except (ValueError, OSError) as e:
                # discovery must NEVER raise: a candidate that vanished
                # or corrupted between listing and filtering (e.g. a
                # concurrent run's retention GC) is skipped like any
                # other invalid checkpoint
                logger.error(
                    "Checkpoint %s became unreadable during resume "
                    "discovery (%s) — skipping it.", path, e)
                continue
            if not keep:
                logger.info(
                    "Resume discovery: skipping %s (another run mode's "
                    "checkpoint).", path)
                continue
        reason = validate_checkpoint(path)
        if reason is None:
            return path
        emit_event("checkpoint_fallback", step=step, path=path,
                   reason=reason)
        logger.error(
            "Checkpoint %s (step %d) is INVALID: %s — falling back to the "
            "previous checkpoint.", path, step, reason)
    return None


def resolve_resume(resume: Optional[str], resume_from: Optional[str],
                   output_dir: str, predicate=None) -> Optional[str]:
    """Turn the (--resume, --resume_from) flag pair into a checkpoint dir
    (or None for a fresh start).

    ``--resume_from <dir>`` keeps its historical meaning and wins outright.
    ``--resume auto`` (the default) discovers the latest valid checkpoint
    under ``output_dir`` — a relaunched preempted job resumes with the
    exact command that started it. ``--resume off`` forces a fresh start;
    any other value is taken as an explicit checkpoint dir.

    ``predicate`` applies ONLY to auto-discovery (see
    ``find_latest_valid_checkpoint``): explicitly named checkpoints go
    through so the restore path can refuse them loudly.
    """
    if resume_from is not None:
        return resume_from
    if resume is None or resume == "off":
        return None
    if resume != "auto":
        return resume
    found = find_latest_valid_checkpoint(output_dir, predicate=predicate)
    if found is not None:
        logger.info("--resume auto: found checkpoint %s", found)
    return found


def resolve_resume_agreed(resume: Optional[str], resume_from: Optional[str],
                          output_dir: str,
                          predicate=None) -> Optional[str]:
    """Multi-host-safe ``resolve_resume``: the coordinator alone runs the
    discovery + validation pass (one full-checkpoint hash read instead of
    one per host) and shares its choice through a marker file on the shared
    filesystem, bracketed by barriers — independent per-host discovery
    could pick DIFFERENT checkpoints if one host races a still-landing or
    transiently-unreadable shard, and divergent restores deadlock in the
    load collectives. ``output_dir`` must already exist on every host."""
    import jax

    if jax.process_count() == 1:
        return resolve_resume(resume, resume_from, output_dir,
                              predicate=predicate)
    from building_llm_from_scratch_tpu.parallel.collectives import (
        sync_global_devices,
    )

    marker = os.path.join(output_dir, ".resume_choice")
    if jax.process_index() == 0:
        choice = resolve_resume(resume, resume_from, output_dir,
                                predicate=predicate)
        with open(marker, "w") as f:
            f.write(choice or "")
    sync_global_devices("resume_choice_written")
    with open(marker) as f:
        choice = f.read() or None
    sync_global_devices("resume_choice_read")
    if jax.process_index() == 0:
        os.remove(marker)
    return choice


# ---------------------------------------------------------------------------
# Retention GC
# ---------------------------------------------------------------------------

def prune_checkpoints(output_dir: str, keep: int) -> List[str]:
    """Delete the oldest step-tagged checkpoints, keeping the newest
    ``keep``. Only ``model_pg_<step>`` dirs are eligible — ``interrupted``
    and ``final`` tags are never touched, and the newest checkpoint (the
    one just written) is always within the kept set. Returns removed paths.

    Call on ONE process only (the coordinator): deletion is not a
    collective, and the pruned dirs are by construction ones nobody reads.
    """
    if keep < 1:
        raise ValueError(f"keep must be >= 1, got {keep}")
    if not os.path.isdir(output_dir):
        return []
    tagged = []
    for name in os.listdir(output_dir):
        m = _STEP_TAGGED.match(name)
        if m and os.path.isdir(os.path.join(output_dir, name)):
            tagged.append((int(m.group(1)), name))
    removed = []
    for step, name in sorted(tagged)[:-keep]:
        for suffix in ("", ".tmp", ".old"):
            path = os.path.join(output_dir, name + suffix)
            if os.path.isdir(path):
                shutil.rmtree(path, ignore_errors=True)
                if not suffix:
                    removed.append(path)
    if removed:
        emit_event("checkpoint_gc",
                   removed=[os.path.basename(p) for p in removed],
                   keep=keep)
        logger.info("Retention GC: removed %d old checkpoint(s): %s",
                    len(removed), ", ".join(os.path.basename(p)
                                            for p in removed))
    return removed


# ---------------------------------------------------------------------------
# Loss watchdog
# ---------------------------------------------------------------------------

class LossWatchdog:
    """Halt on silent divergence: non-finite train loss, or a spike above
    ``spike_factor`` x the running median of the last ``window`` steps.

    Intended for bf16/fp32 runs — fp16 policies already skip non-finite
    steps via dynamic loss scaling, so the trainer does not attach a
    watchdog there. The spike check arms only after ``min_history``
    observations so noisy warmup steps cannot trip it.
    """

    def __init__(self, spike_factor: float = 10.0, window: int = 50,
                 min_history: int = 20, check_finite: bool = True,
                 context_fn=None):
        if spike_factor <= 1.0:
            raise ValueError(
                f"spike_factor must be > 1, got {spike_factor}")
        self.spike_factor = spike_factor
        # the history deque caps at `window`, so an arming threshold above
        # it could never be reached and the spike check would be silently
        # dead (e.g. --watchdog_window 10 with the default min_history 20)
        self.min_history = min(min_history, window)
        self.check_finite = check_finite
        self._history: deque = deque(maxlen=window)
        # optional context provider (the trainer wires its per-layer-group
        # health digest, obs/health.py): extra fields attached to the
        # watchdog_halt event + diagnostic so the halt names the offending
        # LAYER, not just "diverged somewhere"
        self.context_fn = context_fn

    def _context(self) -> dict:
        if self.context_fn is None:
            return {}
        try:
            return dict(self.context_fn() or {})
        except Exception as e:   # context is best-effort: never mask the halt
            logger.warning("Watchdog context provider failed: %s", e)
            return {}

    @staticmethod
    def _context_note(ctx: dict) -> str:
        group = ctx.get("first_nonfinite_group")
        if group:
            return f" First non-finite layer group: {group}."
        top = ctx.get("top_grad_norm_groups")
        if top:
            head = top[0]
            return (f" Largest gradient norm: {head.get('group')} "
                    f"({head.get('grad_norm')}).")
        return ""

    @staticmethod
    def _merge_fields(fields: dict, ctx: dict) -> dict:
        """Context fields must never shadow the event's own kwargs: a
        colliding key (a context that returns 'reason' or 'recent') would
        raise TypeError at emit time and mask the halt diagnostic."""
        fields.update({k: v for k, v in ctx.items()
                       if k not in fields
                       and k not in ("step", "event", "type", "time")})
        return fields

    def observe(self, step: int, loss: float) -> None:
        if self.check_finite and not np.isfinite(loss):
            ctx = self._context()
            fields = self._merge_fields(
                dict(loss=float(loss), reason="non_finite",
                     recent=self._tail()), ctx)
            emit_event("watchdog_halt", step=step, **fields)
            raise TrainingDivergedError(
                f"Train loss became non-finite ({loss}) by step {step}. "
                f"Recent losses: {self._tail()}.{self._context_note(ctx)} "
                "The model has diverged — lower the learning rate, raise "
                "warmup, or resume from an earlier checkpoint.")
        if len(self._history) >= self.min_history:
            median = float(np.median(self._history))
            if np.isfinite(loss) and loss > self.spike_factor * max(
                    median, 1e-8):
                ctx = self._context()
                fields = self._merge_fields(
                    dict(loss=float(loss), reason="spike", median=median,
                         spike_factor=self.spike_factor,
                         recent=self._tail()), ctx)
                emit_event("watchdog_halt", step=step, **fields)
                raise TrainingDivergedError(
                    f"Train loss {loss:.4f} at step {step} spiked above "
                    f"{self.spike_factor:g}x the running median "
                    f"{median:.4f} (window={self._history.maxlen}). Recent "
                    f"losses: {self._tail()}.{self._context_note(ctx)} "
                    "Halting instead of training on a diverged model; "
                    "resume from an earlier checkpoint with a lower LR.")
        self._history.append(float(loss))

    def _tail(self, n: int = 8) -> List[float]:
        return [round(x, 4) for x in list(self._history)[-n:]]
