"""The training engine.

Parity with the reference ``Trainer`` (train.py:43-277): per-file epoch
structure, warmup+cosine LR over the precomputed total steps, periodic
evaluation (<=5 batches of each loader), periodic sample generation,
periodic checkpointing, tokens-seen/LR/loss tracking, KeyboardInterrupt
checkpoint, and a final export.

TPU-first differences:
  - the per-batch math is one donated jitted step (train_step.py) instead of
    eager autograd + host LR mutation;
  - eval/sample/checkpoint cadence runs on the host BETWEEN jitted steps —
    no host callbacks inside compiled code;
  - device placement goes through an optional ``MeshPlan`` (parallel/) that
    shards batches and state instead of DDP/FSDP wrappers;
  - errors are NOT swallowed per batch/epoch (reference defect §2.3 #9);
  - checkpoints carry optimizer state + step and can resume (the reference
    cannot);
  - fault tolerance (training/resilience.py): SIGTERM/SIGINT checkpoint-
    and-stop at the step boundary, a data cursor in checkpoint metadata so
    resume fast-forwards to the exact mid-epoch batch, --keep_ckpts
    retention GC, and an optional loss watchdog that halts on divergence;
  - observability (obs/): a StepTimeline breaks each cadence window into
    data_wait/dispatch/host_fetch plus excluded eval/sample/checkpoint
    segments (so tok/s measures training, not cadence work), every span
    doubles as a profiler trace annotation, metric rows (loss/lr/tok_s/
    MFU/step-time/memory) land in the --metrics_jsonl sink at --log_every
    cadence, and an optional per-host stall detector gets one heartbeat
    per step-loop iteration. The deferred-fetch discipline is unchanged:
    device scalars are still only fetched at cadence (_flush_metrics);
  - host/device overlap (data/prefetch.py, training/async_checkpoint.py):
    with prefetch>0 a bounded worker thread stages already-placed device
    batches (H2D for batch k+1 under step k; exact FIFO order, so loss
    trajectories are bit-identical and the data-cursor resume contract
    holds), eval batches ride their own small prefetcher so the cadence
    never drains the training queue, and with async_ckpt periodic saves
    snapshot on the step loop but write/commit on a background thread
    (exit-path saves still block until durable).
"""

from __future__ import annotations

import os
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import numpy as np

from building_llm_from_scratch_tpu.configs import ModelConfig
from building_llm_from_scratch_tpu.generate import (
    generate,
    text_to_token_ids,
    token_ids_to_text,
)
from building_llm_from_scratch_tpu.models.lora import merge_lora
from building_llm_from_scratch_tpu.obs import (
    CompileWatcher,
    StepTimeline,
    compute_mfu,
    describe_health,
    format_mfu,
    get_metrics,
    mfu_from_flops,
    window_stats,
)
from building_llm_from_scratch_tpu.obs.health import (
    group_names as health_group_names,
    health_summary_line,
    nonfinite_group_name,
)
from building_llm_from_scratch_tpu.data.prefetch import Prefetcher
from building_llm_from_scratch_tpu.training.async_checkpoint import (
    AsyncCheckpointer,
)
from building_llm_from_scratch_tpu.training.checkpoint import (
    checkpoint_metadata,
    export_params,
    load_checkpoint,
    save_checkpoint,
)
from building_llm_from_scratch_tpu.training.resilience import (
    GracefulStopper,
    LossWatchdog,
    PreemptionStop,
    prune_checkpoints,
)
from building_llm_from_scratch_tpu.training.optim import (
    build_optimizer,
    warmup_cosine_schedule,
)
from building_llm_from_scratch_tpu.training.train_step import (
    init_train_state,
    make_eval_step,
    make_sharded_train_step,
    make_train_step,
)
from building_llm_from_scratch_tpu.utils.io import (
    read_json_file,
    read_text_file,
)
from building_llm_from_scratch_tpu.utils.logging import setup_logger
from building_llm_from_scratch_tpu.obs.memory import (
    MemoryLedger,
    pytree_nbytes,
)

logger = setup_logger(__name__)


class Trainer:
    """Drives pretraining (``train_model``) and instruction finetuning
    (``finetune_model``) over a file list, one model, one optimizer."""

    def __init__(self, cfg: ModelConfig, params: Dict[str, Any], tokenizer,
                 loader, *, output_dir: str = "model_checkpoints",
                 peak_lr: float = 5e-4, initial_lr: float = 1e-5,
                 min_lr: float = 1e-6, warmup_steps: int = 10,
                 weight_decay: float = 0.1, grad_clip_norm: float = 1.0,
                 eval_freq: int = 10, eval_iters: int = 5,
                 print_sample_iter: int = 10, save_ckpt_freq: int = 100,
                 lora_params: Optional[Dict[str, Any]] = None,
                 lora_alpha: Optional[float] = None,
                 lora_rank: Optional[int] = None,
                 policy=None, plan=None, seed: int = 123,
                 grad_accum: int = 1,
                 resume_from: Optional[str] = None,
                 warmup_sample: bool = False,
                 profile_dir: Optional[str] = None,
                 profile_steps: int = 10,
                 show_progress: bool = True,
                 keep_ckpts: int = 0,
                 watchdog: Optional[LossWatchdog] = None,
                 stopper: Optional[GracefulStopper] = None,
                 log_every: int = 0,
                 stall=None,
                 compile_cache_dir: Optional[str] = None,
                 compile_telemetry: bool = True,
                 prefetch: int = 0,
                 async_ckpt: bool = False):
        self.cfg = cfg
        self.tokenizer = tokenizer
        self.loader = loader
        self.output_dir = output_dir
        self.opt_hparams = dict(peak_lr=peak_lr, initial_lr=initial_lr,
                                min_lr=min_lr, warmup_steps=warmup_steps,
                                weight_decay=weight_decay,
                                grad_clip_norm=grad_clip_norm)
        self.eval_freq = eval_freq
        self.eval_iters = eval_iters
        self.print_sample_iter = print_sample_iter
        self.save_ckpt_freq = save_ckpt_freq
        self.lora_alpha = lora_alpha
        self.lora_rank = lora_rank
        self.policy = policy
        self.plan = plan
        self.seed = seed
        self.grad_accum = grad_accum
        self.resume_from = resume_from
        self.warmup_sample = warmup_sample
        self.profile_dir = profile_dir
        self.profile_steps = profile_steps
        self.show_progress = show_progress
        self._profiling = False
        self.keep_ckpts = keep_ckpts
        self.watchdog = watchdog
        self.stopper = stopper
        # observability (obs/): metrics cadence decoupled from eval
        # (--log_every; 0 keeps the historical eval-cadence behavior), a
        # wall-clock timeline whose spans double as profiler trace
        # annotations, an optional JSONL sink, and an optional per-host
        # stall detector heartbeated once per step-loop iteration
        self.log_every = log_every
        self.stall = stall
        # compile telemetry (obs/compile.py): the AOT watcher wrapping the
        # train step (compile seconds, HLO cost/memory analysis, recompile
        # detection); cache_dir only feeds entry-count hit/miss telemetry —
        # enabling the persistent cache itself is main.py's job (it must
        # happen before ANY compile, not just the train step's)
        self.compile_cache_dir = compile_cache_dir
        self.compile_telemetry = compile_telemetry
        self._compile_watcher: Optional[CompileWatcher] = None
        # per-layer-group health (obs/health.py): device arrays appended per
        # step (async DMA posted), fetched ONLY at _flush_metrics cadence
        self._health_names: List[str] = []
        self._pending_health: List[Any] = []
        self._health_by_step: Dict[int, Any] = {}
        self._last_health = None
        self._ctx_health = None
        # host/device overlap (data/prefetch.py + training/
        # async_checkpoint.py): prefetch>0 runs the batch pipeline + H2D
        # transfer on a bounded worker thread so data_wait collapses to
        # queue-pop time; async_ckpt moves the checkpoint write/commit off
        # the step loop (snapshot stays synchronous — see the module)
        self.prefetch = prefetch
        self._async_ckpt = AsyncCheckpointer() if async_ckpt else None
        self._pf_base = {"stalls": 0, "pops": 0, "fill_sum": 0}
        # run-level overlap accounting (bench.py --prefetch A/B reads
        # these): cadence-window sums of data-pipeline wait vs step time
        self.data_wait_total_s = 0.0
        self.step_seconds_total = 0.0
        self.prefetch_stall_total = 0
        self.timeline = StepTimeline()
        # (epoch, file_index, batch_index) of the NEXT batch to train —
        # written into checkpoint metadata so resume fast-forwards the
        # deterministic shuffled loader to the exact mid-epoch position
        self._cursor: Optional[Dict[str, int]] = None
        self._resume_cursor: Optional[Dict[str, int]] = None
        self.preempted = False
        self._pending_losses: List[Any] = []

        if (lora_params is None) != (lora_rank is None):
            raise ValueError(
                "lora_params and lora_rank must be passed together "
                "(got one without the other)")
        if lora_params is not None and lora_alpha is None:
            raise ValueError("lora_alpha is required when using LoRA")
        self._params = params
        self._lora_params = lora_params
        self.use_lora = lora_params is not None

        self.state: Optional[Dict[str, Any]] = None
        self.global_step = 0
        self.tokens_seen = 0
        self.train_losses: List[float] = []
        self.val_losses: List[float] = []
        self.track_lrs: List[float] = []
        self._pending_lrs: List[Any] = []
        self.track_tokens_seen: List[int] = []
        self.throughput_tokens_per_s: List[float] = []
        # memory observatory (obs/memory.py): built lazily at the first
        # metrics cadence (the train state must exist first); the
        # trainer's former ad-hoc HBM/RSS gauges now read THROUGH it —
        # one source of truth for every memory number the run reports
        self._memory_ledger: Optional[MemoryLedger] = None

    @property
    def metrics_sink(self):
        """The structured-metrics sink: always the PROCESS-GLOBAL logger
        (resolved per call, so late ``configure_metrics`` wins), never an
        injected one — checkpoint/resilience/retry layers emit through the
        same global, and a trainer-private sink would split the event
        trail across two files. Always non-None: unconfigured use gets
        the no-op sink."""
        return get_metrics()

    def _build_memory_ledger(self) -> MemoryLedger:
        """The training tier's memory ledger: model params (trainable +
        frozen), optimizer state, compile-time temps (HLO memory
        analysis), host RSS — each measured from the LIVE pytrees
        (``nbytes`` sums), with drift/pressure detection and the
        ``memory_snapshot`` cadence event the trace renders as counter
        tracks on the train process row."""
        ledger = MemoryLedger(source="trainer")
        ledger.register(
            "model_params",
            lambda: (pytree_nbytes(self.state["trainable"])
                     + pytree_nbytes(self.state["frozen"])))
        ledger.register(
            "optimizer_state",
            lambda: pytree_nbytes(self.state["opt_state"]))

        def _temps() -> int:
            w = self._compile_watcher
            mem = (getattr(w, "memory", None) or {}) if w else {}
            return mem.get("temp_bytes", 0)

        ledger.register("compile_temps", _temps)
        ledger.track_host_rss()
        return ledger

    # ------------------------------------------------------------------
    # Setup
    # ------------------------------------------------------------------

    def _setup(self, total_steps: int):
        """Build optimizer/schedule/jitted steps once total steps are known
        (the reference computes its cosine horizon the same way,
        train.py:155). On resume the ORIGINAL schedule horizon (persisted in
        checkpoint metadata) is reused so the decay trajectory matches an
        uninterrupted run; it only extends when the requested steps overshoot
        it (e.g. resuming with extra epochs)."""
        prev_steps = 0
        prev_horizon = 0
        mid_run = False
        if self.resume_from is not None:
            meta = checkpoint_metadata(self.resume_from)
            ckpt_model = meta.get("model")
            if ckpt_model and ckpt_model != self.cfg.name:
                raise ValueError(
                    f"Checkpoint {self.resume_from} was written by model "
                    f"'{ckpt_model}' but this run builds '{self.cfg.name}' "
                    "— a stale checkpoint in a reused --output_dir? Pass "
                    "--resume off for a fresh start or point --resume_from "
                    "at a matching checkpoint.")
            prev_steps = int(meta.get("global_step", 0))
            prev_horizon = int(meta.get("schedule_horizon", 0))
            # a data cursor marks a MID-RUN checkpoint: the caller re-runs
            # the ORIGINAL plan (total_steps already counts the epochs the
            # cursor will fast-forward past), so the horizon must not grow
            # by the steps already taken. Cursor-less checkpoints (final)
            # keep the historical "train total_steps more" semantics.
            mid_run = meta.get("cursor") is not None
        horizon = max(prev_horizon,
                      total_steps if mid_run else total_steps + prev_steps)
        self._schedule_horizon = horizon
        self.lr_schedule = warmup_cosine_schedule(
            self.opt_hparams["peak_lr"], self.opt_hparams["initial_lr"],
            self.opt_hparams["min_lr"], self.opt_hparams["warmup_steps"],
            horizon)
        self.optimizer = build_optimizer(total_steps=horizon,
                                         schedule=self.lr_schedule,
                                         **self.opt_hparams)
        if self.use_lora:
            trainable, frozen = self._lora_params, self._params
        else:
            trainable, frozen = self._params, None
        state = init_train_state(trainable, self.optimizer,
                                 jax.random.PRNGKey(self.seed), frozen,
                                 policy=self.policy)
        if self.plan is not None and self.resume_from is None:
            # shard_state copies any leaf that would alias caller buffers
            state = self.plan.shard_state(state)
        elif self.resume_from is None:
            # the first donated train_step deletes the state's input buffers;
            # without a fresh copy that kills self._params, breaking a second
            # train_model() call on this Trainer (round-2 VERDICT weak #1).
            # Only trainable/frozen can alias caller buffers — opt_state/step/
            # rng are freshly created by init_train_state.
            fresh = lambda t: jax.tree_util.tree_map(
                lambda x: x.copy() if isinstance(x, jax.Array) else x, t)
            state["trainable"] = fresh(state["trainable"])
            state["frozen"] = fresh(state["frozen"])
        if self.resume_from is not None:
            # restore the full train state (params + optax m/v + step + rng)
            # onto the plan's shardings — the resume path the reference lacks
            # (SURVEY §5 "No resume, no optimizer state"). The un-placed
            # state is ONLY a structure/shape template here: load_checkpoint
            # builds every leaf fresh from disk, so sharding or copying the
            # template first would be pure transient-HBM waste
            shardings = (self.plan.state_shardings(state)
                         if self.plan is not None else None)
            state = load_checkpoint(self.resume_from, state,
                                    shardings=shardings)
            meta = checkpoint_metadata(self.resume_from)
            self.global_step = int(meta.get("global_step", 0))
            self.tokens_seen = int(meta.get("tokens_seen", 0))
            # mid-run checkpoints carry a data cursor; final ones do not
            # (resuming a COMPLETED run means "train n_epochs more"). The
            # LIVE cursor starts as the restored one so an interruption
            # before the first post-resume step re-checkpoints the same
            # position instead of silently dropping it
            self._resume_cursor = meta.get("cursor")
            self._cursor = self._resume_cursor
            logger.info("Resumed from %s at step %d (%d tokens seen)%s",
                        self.resume_from, self.global_step, self.tokens_seen,
                        f", data cursor {self._resume_cursor}"
                        if self._resume_cursor else "")
        self.state = state
        kw = dict(lora_alpha=self.lora_alpha, lora_rank=self.lora_rank,
                  policy=self.policy,
                  sp_mesh=(self.plan.sp_mesh if self.plan is not None
                           else None))
        if self.grad_accum > 1 and self.plan is not None and (
                self.plan.shard_mode == "pp"
                or (self.policy is not None
                    and self.policy.reduce_dtype != self.policy.compute_dtype)):
            raise ValueError(
                "--grad_accum composes with the GSPMD step only: pp has its "
                "own microbatching (--pp_micro) and the explicit "
                "reduce-dtype step does not accumulate")
        if self.plan is not None and self.plan.shard_mode == "pp":
            from building_llm_from_scratch_tpu.parallel.pipeline import (
                make_pp_eval_step,
                make_pp_train_step,
            )

            pp_kw = dict(n_micro=self.plan.n_micro,
                         lora_alpha=self.lora_alpha,
                         lora_rank=self.lora_rank, policy=self.policy)
            self.train_step = make_pp_train_step(
                self.cfg, self.optimizer, self.plan.mesh,
                lr_schedule=self.lr_schedule, **pp_kw)
            self.eval_step = make_pp_eval_step(self.cfg, self.plan.mesh,
                                               **pp_kw)
            self._finalize_steps()
            return
        if (self.plan is not None and self.policy is not None
                and self.policy.reduce_dtype != self.policy.compute_dtype
                and self.plan.shard_mode in ("dp", "fsdp", "zero1")):
            # the policy separates compute and reduce dtypes (bf16_hybrid):
            # only the explicit shard_map step controls the collective
            # dtypes. Supported for dp, fsdp and zero1 (round-4 VERDICT
            # weak #4 lifted): the step's gradient phase owns the psum /
            # psum_scatter / all_gather dtypes and its optimizer phase pins
            # zero1/fsdp state to plan shardings. tp modes are rejected at
            # flag time (args.perform_checks) — their activation psums live
            # inside the GSPMD forward where the reduce dtype cannot be
            # controlled from outside.
            self.train_step = make_sharded_train_step(
                self.cfg, self.optimizer, self.plan,
                lr_schedule=self.lr_schedule, **kw)
        else:
            if (self.plan is not None and self.policy is not None
                    and self.policy.reduce_dtype != self.policy.compute_dtype):
                raise ValueError(
                    f"shard_mode {self.plan.shard_mode} does not support "
                    f"the explicit {self.policy.name} reduce-dtype step "
                    "(dp/fsdp/zero1 only); rejecting rather than silently "
                    "reducing in the compute dtype")
            self.train_step = make_train_step(
                self.cfg, self.optimizer, lr_schedule=self.lr_schedule,
                grad_accum=self.grad_accum, **kw)
        self.eval_step = make_eval_step(self.cfg, **kw)
        self._finalize_steps()

    def _finalize_steps(self):
        """Common post-step-builder wiring: per-layer-group health names
        (host-side mirror of the in-graph group order), the watchdog's
        which-layer context provider, and the AOT compile watcher around
        the train step (compile/recompile telemetry, obs/compile.py)."""
        self._health_names = health_group_names(self.state["trainable"])
        if self.watchdog is not None and self.watchdog.context_fn is None:
            self.watchdog.context_fn = self._watchdog_context
        if self.compile_telemetry:
            self._compile_watcher = CompileWatcher(
                self.train_step, label="train_step",
                cache_dir=self.compile_cache_dir)
            self.train_step = self._compile_watcher

    def _watchdog_context(self) -> Dict[str, Any]:
        """Health digest attached to watchdog_halt events: names the first
        non-finite layer group (or the top gradient-norm groups) for the
        step whose loss tripped the halt."""
        fetched = self._ctx_health if self._ctx_health is not None \
            else self._last_health
        if fetched is None or not self._health_names:
            return {}
        return describe_health(self._health_names, fetched)

    def _device_batch(self, arrays: Sequence[np.ndarray]) -> Dict[str, Any]:
        names = ("inputs", "targets", "weights")
        batch = dict(zip(names, arrays))
        if "weights" not in batch:
            batch["weights"] = np.ones_like(batch["targets"], np.float32)
        if self.plan is not None:
            return self.plan.shard_batch(batch)
        return batch

    def _staged_batch(self, arrays: Sequence[np.ndarray]) -> Dict[str, Any]:
        """Prefetcher placement hook: the sharded transfer (plan.shard_batch
        / make_array_from_process_local_data), or a plain device_put when
        no mesh plan exists — either way the queue holds device-resident
        batches, so the H2D DMA for batch k+1 overlaps step k instead of
        hiding inside jit dispatch."""
        batch = self._device_batch(arrays)
        if self.plan is None:
            batch = jax.device_put(batch)
        return batch

    def _staged_item(self, arrays: Sequence[np.ndarray]):
        """What the prefetch queue holds: (placed batch, per-process token
        count). The count comes from the HOST arrays — after plan.shard_batch
        the device array's leading dim is the GLOBAL batch, and tokens_seen
        has always counted this process's share."""
        return (self._staged_batch(arrays),
                int(np.prod(np.shape(arrays[0]))))

    def _place_in_worker(self) -> bool:
        """Whether the prefetch worker thread may perform device placement
        itself. True on real accelerators and single-device runs; False for
        multi-device placement on the forced-host-platform CPU backend —
        that is the collective-rendezvous surface that CHECK-aborts under
        thread contention (see the round-4 note in ``_flush_metrics``), so
        there the queue stays host-side and placement happens at pop."""
        return self.plan is None or jax.default_backend() != "cpu"

    def _batch_prefetcher(self, batches, *, depth: int,
                          name: str) -> Prefetcher:
        return Prefetcher(batches, depth, place_fn=self._staged_item,
                          place_in_worker=self._place_in_worker(), name=name)

    # ------------------------------------------------------------------
    # Evaluation / sampling (reference train.py:213-276)
    # ------------------------------------------------------------------

    def calc_loss_loader(self, batches, num_batches: Optional[int] = None
                         ) -> float:
        losses = []
        if self.prefetch > 0:
            # pre-stage eval batches through a SECOND small prefetcher:
            # eval gets its own queue + iterator, so the cadence never
            # drains or disorders the training prefetcher's queue (which
            # keeps refilling underneath while eval runs)
            import itertools

            if num_batches is not None:
                batches = itertools.islice(batches, num_batches)
            pf = self._batch_prefetcher(batches, depth=min(self.prefetch, 2),
                                        name="eval-prefetch")
            try:
                for batch, _n_tok in pf:
                    losses.append(float(jax.device_get(
                        self.eval_step(self.state, batch))))
            finally:
                pf.close()
            return float(np.mean(losses)) if losses else float("nan")
        for i, arrays in enumerate(batches):
            if num_batches is not None and i >= num_batches:
                break
            losses.append(float(jax.device_get(
                self.eval_step(self.state, self._device_batch(arrays)))))
        return float(np.mean(losses)) if losses else float("nan")

    def evaluate_model(self, train_batches, val_batches):
        train_loss = self.calc_loss_loader(train_batches, self.eval_iters)
        val_loss = self.calc_loss_loader(val_batches, self.eval_iters)
        return train_loss, val_loss

    def _full_params(self):
        if self.use_lora:
            return merge_lora(self.state["frozen"], self.state["trainable"],
                              self.lora_alpha, self.lora_rank)
        return self.state["trainable"]

    def generate_and_print_sample(self, start_context: str,
                                  max_new_tokens: int = 50) -> str:
        ids = text_to_token_ids(start_context, self.tokenizer)
        ids = ids[:, -self.cfg.context_length:]
        if self.use_lora:
            # merge-free sampling (models/lora.apply_lora): the adapter
            # delta rides the projections unmerged — the same path the
            # multi-tenant serving engine decodes with, and no per-sample
            # merged-weight materialization of the full model
            out = generate(self.state["frozen"], self.cfg, ids,
                           max_new_tokens=max_new_tokens,
                           context_size=self.cfg.context_length,
                           eos_id=self.cfg.eos_id,
                           rng=jax.random.PRNGKey(self.global_step),
                           lora=self.state["trainable"],
                           lora_alpha=self.lora_alpha,
                           lora_rank=self.lora_rank)
        else:
            out = generate(self._full_params(), self.cfg, ids,
                           max_new_tokens=max_new_tokens,
                           context_size=self.cfg.context_length,
                           eos_id=self.cfg.eos_id,
                           rng=jax.random.PRNGKey(self.global_step))
        text = token_ids_to_text(out, self.tokenizer)
        logger.info("Sample: %s", text.replace("\n", " "))
        return text

    # ------------------------------------------------------------------
    # Checkpointing (reference train.py:231-257)
    # ------------------------------------------------------------------

    def save_checkpoint(self, tag: str,
                        cursor: Optional[Dict[str, int]] = None,
                        prune_after: bool = False) -> str:
        path = os.path.join(self.output_dir, f"model_pg_{tag}")
        metadata = {
            "global_step": self.global_step,
            "tokens_seen": self.tokens_seen,
            "model": self.cfg.name,
            # resume rebuilds the cosine schedule over THIS horizon so the
            # decay matches an uninterrupted run (round-2 ADVICE low #5)
            "schedule_horizon": getattr(self, "_schedule_horizon", 0),
        }
        if cursor is not None:
            metadata["cursor"] = cursor
        if self._async_ckpt is not None:
            # retention GC rides the commit callback: pruning here, at
            # queue time, would delete old recovery points on the strength
            # of a checkpoint that is not yet (and may never be) durable
            self._async_ckpt.save(
                path, self.state, extra_metadata=metadata,
                on_commit=(self._prune_old_checkpoints if prune_after
                           else None))
            if tag in ("interrupted", "final"):
                # exit-path checkpoints must be DURABLE before the caller
                # proceeds (the preemption grace window, the final export)
                self._async_ckpt.wait()
                logger.info("Saved checkpoint %s", path)
            else:
                logger.info("Queued async checkpoint %s "
                            "(write overlaps training)", path)
        else:
            save_checkpoint(path, self.state, extra_metadata=metadata)
            logger.info("Saved checkpoint %s", path)
            if prune_after:
                self._prune_old_checkpoints()
        return path

    def _prune_old_checkpoints(self) -> None:
        """--keep_ckpts retention GC after a successful periodic save:
        coordinator-only deletion of the oldest step-tagged checkpoints
        (never ``interrupted``/``final``, never the one just written)."""
        if self.keep_ckpts > 0 and jax.process_index() == 0:
            prune_checkpoints(self.output_dir, keep=self.keep_ckpts)

    def _resume_skip(self, epoch: int, file_index: int, path: str = ""):
        """(skip_batches, skip_file_entirely) for the resume fast-forward.

        The restored cursor names the next (epoch, file, batch) to train;
        earlier files replay nothing, the cursor's own file skips its
        already-trained batch prefix (the loader's shuffle is deterministic
        in (seed, epoch), so position k is reproduced exactly), and
        everything after runs normally. The cursor also fingerprints its
        file by basename: a data_dir whose contents shifted between
        launches would otherwise fast-forward into the WRONG file while
        claiming an exact resume."""
        cur = self._resume_cursor
        if not cur:
            return 0, False
        ce = int(cur.get("epoch", 0))
        cf = int(cur.get("file_index", 0))
        if (epoch, file_index) < (ce, cf):
            return 0, True
        if (epoch, file_index) == (ce, cf):
            want = cur.get("file")
            have = os.path.basename(path) if path else ""
            if want and have and want != have:
                raise ValueError(
                    f"Resume cursor points at file '{want}' (position "
                    f"{cf}) but the discovered file list now has '{have}' "
                    "there — data_dir contents changed since the "
                    "checkpoint. Restore the original file list or restart "
                    "with --resume off.")
            return int(cur.get("batch_index", 0)), False
        return 0, False

    # ------------------------------------------------------------------
    # Core loops (reference train.py:128-211)
    # ------------------------------------------------------------------

    def _run_epoch(self, train_batches_fn: Callable[[int], Any],
                   val_batches_fn: Callable[[int], Any], epoch: int,
                   start_context: str, n_batches: Optional[int] = None,
                   desc: str = "", file_index: int = 0,
                   skip_batches: int = 0, file_name: str = ""):
        """One pass over one file's batches with cadence work.

        ``skip_batches`` fast-forwards a resumed run past the batches the
        checkpointed cursor already trained (the iterator is consumed
        cheaply — batches materialize lazily)."""
        if self.warmup_sample and self.global_step == 0:
            # warm-up sample before the first step (reference main.py:143-145)
            with self.timeline.span("sample"):
                self.generate_and_print_sample(start_context)
            self.warmup_sample = False
        if self.profile_dir is not None and not self._profiling:
            # --profile: jax.profiler trace of the first training steps
            # (SURVEY §5's TPU equivalent of the reference's memory introspection)
            jax.profiler.start_trace(self.profile_dir)
            self._profiling = True
            self._profile_stop_at = self.global_step + self.profile_steps
        # discard timeline segments accumulated outside any window (warmup
        # sample above, the previous file's trailing cadence work): the
        # window that opens at t_start below must only subtract non-step
        # time that actually fell inside it
        self.timeline.drain()
        t_tokens, t_start = 0, time.perf_counter()
        log_cadence = self.log_every if self.log_every > 0 else self.eval_freq
        batches = train_batches_fn(epoch)
        if skip_batches:
            import itertools

            batches = itertools.islice(batches, skip_batches, None)
            if n_batches is not None:
                n_batches = max(0, n_batches - skip_batches)
        # host/device overlap: wrap the (already fast-forwarded) iterator
        # in the bounded background prefetcher — the resume skip above ran
        # BEFORE the queue exists, so it only ever stages batches that
        # will train, and exact FIFO order keeps the data-cursor contract.
        # tqdm wraps the prefetcher (not the source) so progress counts
        # batches CONSUMED, not batches staged.
        prefetcher = None
        stream = batches
        if self.prefetch > 0:
            prefetcher = self._batch_prefetcher(stream, depth=self.prefetch,
                                                name="train-prefetch")
            self._pf_base = prefetcher.counters()
            stream = prefetcher
        if self.show_progress and jax.process_index() == 0:
            # per-file batch progress (reference train.py:159,188 wraps the
            # loader in tqdm); leave=False keeps the log uncluttered
            from tqdm import tqdm

            stream = tqdm(stream, total=n_batches, desc=desc,
                          unit="batch", leave=False)
        batch_in_file = skip_batches
        batches_iter = iter(stream)
        try:
            self._epoch_steps(batches_iter, prefetcher, train_batches_fn,
                              val_batches_fn, epoch, file_index, file_name,
                              batch_in_file, start_context, t_tokens,
                              t_start, log_cadence)
        finally:
            # the worker must die on EVERY exit: normal exhaustion,
            # PreemptionStop, watchdog halt, or any exception unwinding
            if prefetcher is not None:
                self.prefetch_stall_total += prefetcher.stalls
                prefetcher.close()

    def _epoch_steps(self, batches_iter, prefetcher, train_batches_fn,
                     val_batches_fn, epoch: int, file_index: int,
                     file_name: str, batch_in_file: int, start_context: str,
                     t_tokens: int, t_start: float, log_cadence: int):
        """The per-batch step loop of ``_run_epoch`` (split out so the
        prefetcher teardown wraps it in one ``finally``)."""
        while True:
            # explicit next() so the wait on the data pipeline is its own
            # timeline segment (and trace span) instead of vanishing into
            # the loop header. With the prefetcher this measures QUEUE-POP
            # time (near zero in steady state); genuine host starvation
            # shows up in the prefetch_stall counter instead.
            with self.timeline.span("data_wait"):
                item = next(batches_iter, None)
            if item is None:
                break
            # the prefetcher already placed the batch (its worker ran
            # _staged_item); the synchronous path places here
            if prefetcher is not None:
                batch, n_tok = item
            else:
                batch = self._device_batch(item)
                # graft-ok: GL011 host batch-shape metadata, no device sync
                n_tok = int(np.prod(item[0].shape))
            with self.timeline.step_span(self.global_step + 1):
                self.state, metrics = self.train_step(self.state, batch)
            self.global_step += 1
            batch_in_file += 1
            self._cursor = {"epoch": epoch, "file_index": file_index,
                            "file": file_name,
                            "batch_index": batch_in_file}
            self.tokens_seen += n_tok
            t_tokens += n_tok
            # keep the device scalar; float() here would block the host on
            # every step and stall dispatch of step N+1 (round-2 VERDICT
            # weak #3) — pending metrics are fetched at eval cadence. The
            # async copy posts the device->host DMA now so the flush finds
            # host-resident values instead of paying one round trip each.
            lr = metrics["lr"]
            try:
                lr.copy_to_host_async()
            except (AttributeError, RuntimeError):
                pass
            self._pending_lrs.append(lr)
            if self.watchdog is not None and "loss" in metrics:
                # same deferred-fetch discipline as lr: the watchdog reads
                # these at flush cadence, never blocking the step loop
                loss = metrics["loss"]
                try:
                    loss.copy_to_host_async()
                except (AttributeError, RuntimeError):
                    pass
                self._pending_losses.append(loss)
            health = metrics.get("health")
            if health is not None:
                # same deferred-fetch discipline: post the (G,)-array DMAs
                # now, convert to host values only at flush cadence
                for v in health.values():
                    try:
                        v.copy_to_host_async()
                    except (AttributeError, RuntimeError):
                        pass
                self._pending_health.append((self.global_step, health))

            if self._profiling and self.global_step >= self._profile_stop_at:
                jax.profiler.stop_trace()
                self._profiling = False
                self.profile_dir = None
                logger.info("Profiler trace captured (%d steps)",
                            self.profile_steps)

            at_eval = self.global_step % self.eval_freq == 0
            if at_eval or self.global_step % log_cadence == 0:
                # flush FIRST: float() on the last pending lr blocks until
                # the final dispatched step finishes, so the window
                # measures execution, not async dispatch (the blocking
                # catch-up shows up as the host_fetch segment)
                with self.timeline.span("host_fetch"):
                    self._flush_metrics()
                elapsed = time.perf_counter() - t_start
                window = self.timeline.drain()
                stats = window_stats(window, elapsed, t_tokens)
                tps = stats["tok_s"]
                self.throughput_tokens_per_s.append(tps)
                self.data_wait_total_s += window.get("data_wait", 0.0)
                self.step_seconds_total += stats["step_seconds"] or 0.0
                # the window reopens HERE: the eval below (and any
                # sample/checkpoint cadence after it) runs inside the new
                # window but lands in excluded timeline segments, so the
                # next tok/s measures training time only — the old
                # t_tokens/t_start accounting charged sample+save time to
                # the throughput window and deflated it
                t_tokens, t_start = 0, time.perf_counter()
                mfu = compute_mfu(tps, self.cfg)
                # HLO-measured MFU cross-check: same throughput, but the
                # FLOPs/token XLA counted in the compiled step instead of
                # the analytic formula — a drifting delta means the
                # formula (or the graph) changed
                watcher = self._compile_watcher
                mfu_hlo = (mfu_from_flops(tps, watcher.hlo_flops_per_token)
                           if watcher is not None
                           and watcher.hlo_flops_per_token else None)
                row = {
                    "lr": self.track_lrs[-1] if self.track_lrs else None,
                    "tokens_seen": self.tokens_seen,
                    "tok_s": round(tps, 1),
                    "mfu": mfu,
                    "step_time_s": stats["step_time_s"],
                    "data_wait_s": round(window.get("data_wait", 0.0), 6),
                    "dispatch_s": round(window.get("dispatch", 0.0), 6),
                    "host_fetch_s": round(window.get("host_fetch", 0.0), 6),
                    # graft-ok: GL011 host timeline dict, cadence boundary
                    "steps_in_window": int(window.get("steps", 0)),
                }
                stall_delta = 0
                if prefetcher is not None:
                    # prefetch telemetry, as window deltas: stalls (pops
                    # that found the queue empty — the host can't keep
                    # up), mean fill ratio, and the instantaneous depth
                    c = prefetcher.counters()
                    stall_delta = c["stalls"] - self._pf_base["stalls"]
                    pops = c["pops"] - self._pf_base["pops"]
                    fill = c["fill_sum"] - self._pf_base["fill_sum"]
                    self._pf_base = c
                    row["prefetch_stall"] = stall_delta
                    row["prefetch_qdepth"] = prefetcher.qsize()
                    if pops > 0:
                        row["prefetch_fill_ratio"] = round(
                            fill / pops / prefetcher.depth, 3)
                if mfu_hlo is not None:
                    row["mfu_hlo"] = mfu_hlo
                    if mfu is not None:
                        row["mfu_delta"] = round(mfu_hlo - mfu, 4)
                if self._last_health is not None:
                    # global pre-clip grad norm and post-clip update norm,
                    # derived from the already-fetched health bundle (the
                    # group-norms-compose identity is test-asserted) — no
                    # extra device fetch
                    for key in ("grad_norm", "update_norm"):
                        # graft-ok: GL011, GL012 already-fetched host bundle
                        row[key] = round(float(np.sqrt(np.sum(
                            # graft-ok: GL012 host bundle (see above)
                            np.asarray(self._last_health[key],
                                       np.float64) ** 2))), 8)
                # memory ledger cadence: byte-exact components from the
                # live train state + the single device-stats/RSS poll
                # (legacy_row keeps the historical hbm_*/host_rss_bytes
                # row keys, so renderers and plots read unchanged)
                if self._memory_ledger is None:
                    self._memory_ledger = self._build_memory_ledger()
                self._memory_ledger.observe(self.global_step)
                row.update(self._memory_ledger.legacy_row())
                if at_eval:
                    with self.timeline.span("eval"):
                        train_loss, val_loss = self.evaluate_model(
                            train_batches_fn(epoch), val_batches_fn(epoch))
                    self.train_losses.append(train_loss)
                    self.val_losses.append(val_loss)
                    self.track_tokens_seen.append(self.tokens_seen)
                    row["train_loss"] = train_loss
                    row["val_loss"] = val_loss
                    logger.info(
                        "step %d: train %.3f, val %.3f, lr %.2e, "
                        "%.0f tok/s, %s",
                        self.global_step, train_loss, val_loss,
                        self.track_lrs[-1], tps, format_mfu(mfu))
                    if self._last_health is not None:
                        logger.info("%s", health_summary_line(
                            self._health_names, self._last_health))
                else:
                    logger.info(
                        "step %d: lr %.2e, %.0f tok/s, %s, "
                        "step %.1fms (data_wait %.1fms%s)",
                        self.global_step, self.track_lrs[-1], tps,
                        format_mfu(mfu),
                        1e3 * (stats["step_time_s"] or 0.0),
                        1e3 * window.get("data_wait", 0.0),
                        f", {stall_delta} prefetch stalls"
                        if prefetcher is not None else "")
                self.metrics_sink.log_metrics(self.global_step, **row)
                self._emit_health_row()

            if self.global_step % self.print_sample_iter == 0:
                with self.timeline.span("sample"):
                    self.generate_and_print_sample(start_context)

            if self.global_step % self.save_ckpt_freq == 0:
                with self.timeline.span("checkpoint"):
                    self.save_checkpoint(str(self.global_step),
                                         cursor=self._cursor,
                                         prune_after=True)

            if self.stopper is not None and self.stopper.should_stop():
                # preemption-safe stop at the step boundary: the signal was
                # observed locally, but the decision is GLOBAL (should_stop
                # all-reduces the flag), so every host reaches the
                # checkpoint collectives below together instead of one host
                # exiting while its peers hang in a psum
                logger.warning(
                    "Graceful stop requested: writing checkpoint at step "
                    "%d and exiting.", self.global_step)
                self.metrics_sink.event("preemption_stop",
                                        step=self.global_step,
                                        tokens_seen=self.tokens_seen)
                with self.timeline.span("checkpoint"):
                    self.save_checkpoint("interrupted", cursor=self._cursor)
                self.preempted = True
                raise PreemptionStop

            if self.stall is not None:
                # one heartbeat per step-loop iteration: if the loop wedges
                # anywhere (collective, data pipeline, host fetch), the
                # per-host detector dumps stacks after its timeout
                self.stall.notify_step()

    def _flush_metrics(self, check_watchdog: bool = True):
        """Fetch pending per-step device metrics to host floats. Per-scalar
        blocking float() at step time costs a round trip each (~100ms over a
        remote-tunnel backend; round-2 VERDICT weak #3), so values are
        fetched only at cadence — and the DMA was already posted by
        ``copy_to_host_async`` at append time, so each read here is a cheap
        sync on an in-flight/done transfer.

        Deliberately NO device computation here (r4 stacked the scalars
        with ``jnp.stack`` first): that compiled and dispatched a fresh
        multi-device SPMD program over the committed 8-device arrays while
        the last donated train steps were still in flight — on the
        forced-host-platform CPU backend that is exactly the
        collective-rendezvous surface that CHECK-aborts (SIGABRT) under
        thread contention, which is how `pytest tests/test_sharding.py`
        could die order-dependently in its zero1 Trainer test (round-4
        VERDICT weak #1). Host-side reads have no such surface.

        All fetches here are EXPLICIT ``jax.device_get``: this is the
        sanctioned cadence-time fetch point, and the transfer-guard
        sentry (analysis/runtime.py) proves the off-cadence step loop
        performs no implicit device->host transfer at all."""
        if self._pending_lrs:
            self.track_lrs.extend(
                float(v) for v in jax.device_get(self._pending_lrs))
            self._pending_lrs.clear()
        if self._pending_health:
            pending, self._pending_health = self._pending_health, []
            # (G,)-sized arrays whose DMAs were posted at append time: the
            # reads here are cheap syncs, and keeping the per-step map lets
            # the watchdog context name the layer AT THE HALT STEP, not
            # whatever step happened to be last in the window
            self._health_by_step = {
                step: jax.device_get(h) for step, h in pending}
            self._last_health = self._health_by_step[pending[-1][0]]
        if self._pending_losses:
            fetched = [float(v)
                       for v in jax.device_get(self._pending_losses)]
            self._pending_losses.clear()
            if self.watchdog is not None and check_watchdog:
                # base step of the oldest pending loss, so the diagnostic
                # names the step the divergence actually happened at
                base = self.global_step - len(fetched)
                try:
                    for i, loss in enumerate(fetched):
                        self._ctx_health = self._health_by_step.get(
                            base + i + 1)
                        self.watchdog.observe(base + i + 1, loss)
                finally:
                    self._ctx_health = None

    def _emit_health_row(self):
        """One ``health`` JSONL row per logging cadence: group names +
        per-group arrays from the latest flushed step (obs/health.py)."""
        h = self._last_health
        if h is None or not self._health_names:
            return
        self.metrics_sink.log_health(
            self.global_step, self._health_names,
            grad_norm=[round(float(x), 8) for x in h["grad_norm"]],
            param_norm=[round(float(x), 8) for x in h["param_norm"]],
            update_norm=[round(float(x), 8) for x in h["update_norm"]],
            update_ratio=[round(float(x), 10) for x in h["update_ratio"]],
            first_nonfinite=nonfinite_group_name(self._health_names, h))

    def _stop_profiler(self):
        if self._profiling:
            jax.profiler.stop_trace()
            self._profiling = False

    def train_model(self, files: Sequence[str], n_epochs: int,
                    start_context: str = "Every effort moves you"):
        """Causal-LM pretraining over raw-text files
        (reference train.py:153-180)."""
        total_steps = self.loader.get_total_steps_epoch(
            list(files), eos_text=self.cfg.eos_text) * n_epochs
        self._setup(max(1, total_steps))
        logger.info("Total training steps: %d", total_steps)
        try:
            for epoch in range(n_epochs):
                for file_index, path in enumerate(files):
                    skip, skip_file = self._resume_skip(epoch, file_index,
                                                        path)
                    if skip_file:
                        continue
                    if hasattr(self.loader, "create_datasets_for_file"):
                        # tokenize-once path: the total-steps pre-pass
                        # above already warmed the per-file token cache,
                        # so this (and every later epoch) is a cache hit —
                        # no re-read, no re-encode (data/pretrain.py)
                        train_ds, val_ds = self.loader.create_datasets_for_file(
                            path, eos_text=self.cfg.eos_text)
                    else:
                        text = read_text_file(path) + f" {self.cfg.eos_text} "
                        train_ds, val_ds = self.loader.create_datasets(text)
                    if self.loader.num_batches(train_ds) == 0:
                        logger.warning("File %s too small for one batch; "
                                       "skipping", path)
                        continue
                    self._run_epoch(
                        lambda e, ds=train_ds: self.loader.batches(
                            ds, shuffle=True, epoch=e),
                        lambda e, ds=val_ds: self.loader.batches(
                            ds, shuffle=False, epoch=e),
                        epoch, start_context,
                        n_batches=self.loader.num_batches(train_ds),
                        desc=f"epoch {epoch + 1}/{n_epochs} "
                             f"{os.path.basename(path)}",
                        file_index=file_index, skip_batches=skip,
                        file_name=os.path.basename(path))
        except PreemptionStop:
            logger.warning(
                "Training stopped gracefully at step %d; relaunch with "
                "--resume auto to continue.", self.global_step)
        except KeyboardInterrupt:
            # best-effort abort save (direct Ctrl-C with no stopper, or the
            # impatient second SIGINT): the interrupt is asynchronous, so in
            # the tiny window between the step-count and cursor updates the
            # saved cursor can trail the state by one batch — resume then
            # replays that batch. The GRACEFUL stop path (stopper) saves at
            # an exact step boundary and has no such window.
            self.save_checkpoint("interrupted", cursor=self._cursor)
            raise
        finally:
            self._stop_profiler()
            # no watchdog here: raising out of finally would mask an
            # in-flight exception from the try body
            self._flush_metrics(check_watchdog=False)
            if self._async_ckpt is not None:
                # drain the background writer before returning — and
                # non-raising, so a write failure here can't mask an
                # in-flight exception (exit-path saves already waited
                # with reraise inside save_checkpoint)
                self._async_ckpt.close()
        return self

    def finetune_model(self, files: Sequence[str], n_epochs: int):
        """Instruction finetuning over Alpaca-format JSON files
        (reference train.py:182-211)."""
        total_steps = self.loader.get_total_steps_epoch(list(files)) * n_epochs
        self._setup(max(1, total_steps))
        logger.info("Total finetuning steps: %d", total_steps)
        try:
            for epoch in range(n_epochs):
                for file_index, path in enumerate(files):
                    skip, skip_file = self._resume_skip(epoch, file_index,
                                                        path)
                    if skip_file:
                        continue
                    records = read_json_file(path)
                    train_ds, val_ds = self.loader.create_datasets(records)
                    if self.loader.num_batches(train_ds) == 0:
                        logger.warning("File %s too small for one batch; "
                                       "skipping", path)
                        continue
                    # sample prompt comes from the val split's first record
                    # (reference train.py:201-203 uses the Alpaca template)
                    from building_llm_from_scratch_tpu.data.instruct import (
                        format_input,
                    )
                    sample_entry = (val_ds.data[0] if len(val_ds) > 0
                                    else train_ds.data[0])
                    start_context = format_input(sample_entry)
                    self._run_epoch(
                        lambda e, ds=train_ds: self.loader.batches(
                            ds, shuffle=True, epoch=e),
                        lambda e, ds=val_ds: self.loader.batches(
                            ds, shuffle=False, epoch=e),
                        epoch, start_context,
                        n_batches=self.loader.num_batches(train_ds),
                        desc=f"epoch {epoch + 1}/{n_epochs} "
                             f"{os.path.basename(path)}",
                        file_index=file_index, skip_batches=skip,
                        file_name=os.path.basename(path))
        except PreemptionStop:
            logger.warning(
                "Finetuning stopped gracefully at step %d; relaunch with "
                "--resume auto to continue.", self.global_step)
        except KeyboardInterrupt:
            # best-effort abort save (direct Ctrl-C with no stopper, or the
            # impatient second SIGINT): the interrupt is asynchronous, so in
            # the tiny window between the step-count and cursor updates the
            # saved cursor can trail the state by one batch — resume then
            # replays that batch. The GRACEFUL stop path (stopper) saves at
            # an exact step boundary and has no such window.
            self.save_checkpoint("interrupted", cursor=self._cursor)
            raise
        finally:
            self._stop_profiler()
            self._flush_metrics(check_watchdog=False)
            if self._async_ckpt is not None:
                self._async_ckpt.close()
        return self

    def export_final(self, filename: str = "model_pg_final.npz") -> str:
        """Final single-file params export (reference main.py:171-172)."""
        path = os.path.join(self.output_dir, filename)
        return export_params(path, self._full_params())

    def export_adapter(self, path: str) -> str:
        """``--save_adapter``: write the trained LoRA tree as a standalone
        npz artifact (rank/alpha + base-config fingerprint) that the
        serving ``AdapterRegistry`` hot-loads — the multi-tenant
        alternative to baking the adapter into ``export_final``'s merged
        weights."""
        from building_llm_from_scratch_tpu.models.lora import (
            adapter_fingerprint,
            count_lora_params,
            save_adapter,
        )

        if not self.use_lora:
            raise ValueError("export_adapter needs a LoRA run "
                             "(no adapter tree to export)")
        lora = self.state["trainable"]
        save_adapter(path, lora, rank=self.lora_rank,
                     alpha=self.lora_alpha, cfg=self.cfg)
        get_metrics().event("adapter_save", step=self.global_step,
                            path=path, rank=self.lora_rank,
                            alpha=self.lora_alpha,
                            n_params=count_lora_params(lora),
                            fingerprint=adapter_fingerprint(self.cfg))
        logger.info("Exported LoRA adapter to %s (rank %d, alpha %s).",
                    path, self.lora_rank, self.lora_alpha)
        return path
