"""Non-blocking checkpoint writes: snapshot on the step loop, commit on a
background thread.

``save_checkpoint`` holds the step loop hostage for the full file-write
dance — shard writes, sha256 hashing, manifest, two-rename commit — which
at a realistic save cadence is pure device idle time. ``AsyncCheckpointer``
splits the save at the only point that must be synchronous:

  1. **Snapshot (main thread, blocking, cheap).** ``snapshot_for_save``
     posts async D2H copies for every owned shard and materializes them as
     host numpy — it blocks only until the in-flight donated steps finish
     and the DMAs land. After this the checkpoint is decoupled from device
     state: training may mutate (donate) the state freely.
  2. **Write (background thread).** ``write_snapshot`` runs the identical
     ``.tmp`` staging / sha256 manifest / two-rename commit sequence as the
     synchronous save, so every PR-1 integrity consumer
     (``validate_checkpoint``, ``--resume auto`` fallback, ``.tmp``/``.old``
     recovery) works on its output unchanged.

Contracts:

  - **Serialized saves.** At most one write in flight: a new ``save()``
    first ``wait()``s for the previous commit, so two saves can never
    interleave their ``.tmp`` staging dirs (or race the ``.old`` dance on
    the same tag).
  - **Durability on demand.** ``wait()`` blocks until the last queued
    checkpoint is committed; the trainer calls it at exit and on the
    preemption path so the final checkpoint is always durable before the
    process returns.
  - **Failures surface.** A background write error is re-raised on the
    main thread at the next ``save()``/``wait()`` — a run never trains for
    hours believing checkpoints exist that don't.
  - **Multi-host falls back to synchronous.** The sharded save's
    correctness on pods rests on cross-host barriers (all shards on disk
    before the manifest commits), and collectives are main-thread-only —
    so with ``jax.process_count() > 1`` ``save()`` simply calls
    ``save_checkpoint`` at the snapshot point, where they are legal. The
    API is uniform either way; single-host runs (and each host of a
    per-host-filesystem setup that opts out) get the overlap.

Telemetry: each committed async save emits ``ckpt_async_save`` with
``snapshot_s`` (what the step loop actually paid), ``write_s`` (the I/O
that ran under training) and ``overlap_s`` (wall-clock the step loop kept
training while the write proceeded — write start to commit).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional

from building_llm_from_scratch_tpu.obs.metrics import emit_event
from building_llm_from_scratch_tpu.training.checkpoint import (
    save_checkpoint,
    snapshot_for_save,
    write_snapshot,
)
from building_llm_from_scratch_tpu.utils.logging import setup_logger

logger = setup_logger(__name__)


class AsyncCheckpointer:
    """Background checkpoint writer; see module docstring."""

    def __init__(self):
        import jax

        self._sync_fallback = jax.process_count() > 1
        if self._sync_fallback:
            logger.warning(
                "AsyncCheckpointer: multi-host run — checkpoint writes "
                "stay synchronous (the sharded save's cross-host barriers "
                "are main-thread collectives).")
        self._thread: Optional[threading.Thread] = None
        self._exc: Optional[BaseException] = None
        self.saves_started = 0
        self.saves_committed = 0

    # ------------------------------------------------------------------

    @property
    def in_flight(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def save(self, ckpt_dir: str, state: Dict[str, Any],
             extra_metadata: Optional[dict] = None,
             on_commit: Optional[Callable[[], None]] = None) -> None:
        """Queue one checkpoint write. Blocks only for the previous save's
        commit (serialization) and the host snapshot of ``state``.

        ``on_commit`` runs AFTER the background commit succeeds (never on
        failure) — for work that must only see durable checkpoints, e.g.
        retention GC: pruning at queue time would count a checkpoint that
        may never materialize. It runs on the writer thread, so it must be
        collective-free (file ops only).
        """
        if self._sync_fallback:
            save_checkpoint(ckpt_dir, state, extra_metadata=extra_metadata)
            if on_commit is not None:
                on_commit()
            return
        # at most one save in flight; also re-raises a previous failure
        self.wait()
        t0 = time.perf_counter()
        snapshot = snapshot_for_save(state, extra_metadata=extra_metadata)
        snapshot_s = time.perf_counter() - t0
        step = (extra_metadata or {}).get("global_step")
        self.saves_started += 1
        t_resume = time.perf_counter()

        def _write() -> None:
            try:
                t_w = time.perf_counter()
                write_snapshot(ckpt_dir, snapshot)
                now = time.perf_counter()
                self.saves_committed += 1
                emit_event("ckpt_async_save", path=ckpt_dir, step=step,
                           snapshot_s=round(snapshot_s, 4),
                           write_s=round(now - t_w, 4),
                           overlap_s=round(now - t_resume, 4))
                if on_commit is not None:
                    on_commit()
            except BaseException as e:      # noqa: BLE001 — re-raised at wait
                self._exc = e

        self._thread = threading.Thread(target=_write, daemon=True,
                                        name="async-ckpt-writer")
        self._thread.start()

    def wait(self, reraise: bool = True) -> None:
        """Block until the in-flight write (if any) committed. With
        ``reraise`` (default) a background failure is raised HERE, on the
        main thread; ``reraise=False`` logs it instead — for ``finally``
        blocks that must not mask an already-propagating exception."""
        t = self._thread
        if t is not None:
            t.join()
            self._thread = None
        if self._exc is not None:
            exc, self._exc = self._exc, None
            if reraise:
                raise RuntimeError(
                    "Async checkpoint write failed") from exc
            logger.error("Async checkpoint write failed: %r", exc)

    def close(self, reraise: bool = False) -> None:
        """Trainer-exit hook: drain the writer (non-raising by default)."""
        self.wait(reraise=reraise)
