"""``python -m building_llm_from_scratch_tpu`` entry point."""

from building_llm_from_scratch_tpu.main import run

run()
