"""Benchmarks: tokens/sec/chip for the five BASELINE.json configs.

Usage:
  python bench.py            # headline: GPT2-124M pretrain bf16 (one JSON line)
  python bench.py cfg1       # GPT2-124M fp32 bs4 ctx1024 (BASELINE #1)
  python bench.py cfg2       # GPT2-774M bf16 + remat (BASELINE #2)
  python bench.py cfg3       # LLaMA3.2-1B LoRA r8 SFT bf16 (BASELINE #3)
  python bench.py cfg4       # LLaMA3-8B-arch fsdp slice (BASELINE #4, see note)
  python bench.py cfg5       # LLaMA2-7B-arch zero1 slice (BASELINE #5, see note)
  python bench.py trainer    # Trainer-loop path (vs raw-step, VERDICT r2 #3)
  python bench.py serve      # continuous-batching engine vs sequential decode
  python bench.py serve_fleet  # router replica sweep (1/2/4 replicas,
                               # one forced-host device per replica)
  python bench.py micro_train  # debug-size perf-gate micro-bench (CI)
  python bench.py all        # everything, one JSON line each

Runner flags (the perf observatory, obs/perf.py):
  --repeats K   run each bench K times; the result row carries
                min/median/mean/stddev repeat stats (timing-gate noise floor)
  --json OUT    append schema'd BenchResult rows to OUT (JSONL; a
                run-metadata header row is written first), or into
                OUT/<name>.jsonl when OUT is a directory (trajectory layout)
  --quick       shrink iteration/request counts (never shapes — the
                structural fingerprint is quick-invariant); the CI gate mode

Every bench returns an ``obs/perf.BenchResult``: headline value + unit,
named extra metrics, the bench's arm-detail dict, and — filled by the
runner — env metadata (jax version, backend, device kind/count, mesh, git
sha, argv), repeat stats, and a structural HLO fingerprint (per-program
cost-analysis FLOPs, memory breakdown, arg signatures, recompile count)
captured via ``obs/compile.CompileWatcher``. ``scripts/perf_gate.py``
compares those fingerprints against PERF_BASELINE.json in CI.

The reference publishes NO numbers (BASELINE.md), so ``vs_baseline``
compares against this repo's first recorded figure: headline/cfg1 against
round-2's 37,039.6 (BASELINE.md history line), the rest against the round-3
measured table in BASELINE.md. Configs #4/#5 target multi-chip
pods this harness doesn't have; they run the exact fsdp/zero1 code paths on
the largest model slice that fits one v5e chip (reduced layer count,
recorded in the metric name) — the full-size sharding compiles+executes in
``__graft_entry__.dryrun_multichip``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import jax
import numpy as np

from building_llm_from_scratch_tpu.obs import perf

#: --quick: shrink iteration/request counts so the CI perf gate finishes
#: in seconds. NEVER shrinks shapes (batch size, context, slots) — the
#: structural fingerprint must be identical in quick and full mode.
_QUICK = False


def _q_iters(warmup: int, iters: int):
    """Quick-mode iteration budget: fewer timed steps, same shapes."""
    if _QUICK:
        return min(warmup, 1), min(iters, 4)
    return warmup, iters


def _result(name: str, metric: str, value, unit: str = "tokens/sec/chip",
            mfu=None, detail=None) -> perf.BenchResult:
    """Build the BenchResult every BENCHES entry returns (the old
    ``(metric, value[, mfu])`` tuple contract, made schema'd)."""
    res = perf.BenchResult(name=name, metric=metric, value=float(value),
                           unit=unit, detail=detail)
    if mfu is not None:
        res.add_metric("mfu", round(float(mfu), 4), "fraction")
    return res

# First recorded tokens/sec/chip per config on TPU v5e-1 (BASELINE.md).
RECORDED = {
    "headline": 37039.6,   # r02's fp32 figure — the number to beat
    "cfg1": 37039.6,       # r02 (threefry PRNG, pre-rbg)
    "cfg2": 7601.0,        # r03 first recorded (BASELINE.md measured table)
    "cfg3": 11062.9,       # r03
    "cfg4": 17877.9,       # r03
    "cfg5": 16330.3,       # r03
    "trainer": 60781.6,    # r03 headline — the loop must keep up with it
    "prefetch": 60781.6,   # overlap loop must beat the r03 sync loop figure
    "decode": 3437.6,     # r03 first recorded
}

# NOTE: on the axon remote backend jax.block_until_ready() returns at
# dispatch time — only a literal device_get round-trips to the chip, so
# all timing syncs use float()/device_get.

# Per-chip peak FLOPs + HBM bandwidth come from the ONE device-spec table
# in obs/mfu.py (deduplicated this round — bench kept a private copy that
# had already drifted from the trainer's). MFU below is MODEL-flops
# utilization: 6*N_matmul per token for full training, 4*N_matmul for LoRA
# (no dW for frozen weights; dx still flows), plus causal attention matmul
# flops; remat recompute is NOT counted (standard MFU convention), so remat
# configs understate hardware efficiency.


def _device_specs():
    from building_llm_from_scratch_tpu.obs import mfu as _mfu

    spec = _mfu.device_specs()
    if spec is not None:
        return spec
    # unknown device kind: fall back to v5e numbers so ratios stay
    # comparable with BASELINE.md history — but say so when it's a real
    # TPU, because the reported MFU/roofline would be silently wrong
    if jax.default_backend() == "tpu":
        kind = jax.devices()[0].device_kind.lower()
        print(json.dumps({"warning": f"unknown TPU device kind '{kind}'; "
                          "MFU/roofline use v5e peak numbers"}), flush=True)
    return dict(_mfu.DEVICE_SPECS)["v5e"]


def _model_flops_per_token(cfg, lora: bool = False) -> float:
    D, F, hd = cfg.emb_dim, cfg.hidden_dim, cfg.head_dim
    Hq, Hkv, T = cfg.n_heads, cfg.n_kv_groups, cfg.context_length
    per_layer = (D * Hq * hd + 2 * D * Hkv * hd + Hq * hd * D  # wq wk wv wo
                 + (3 if cfg.activation == "swiglu" else 2) * D * F)
    n_matmul = cfg.n_layers * per_layer + D * cfg.vocab_size    # + head
    # causal attention: q.k^T and p.v, ~T/2 keys per query, fwd+bwd(2x)
    attn = cfg.n_layers * 2 * 2 * (T / 2) * (Hq * hd) * 3
    factor = 4 if lora else 6
    return factor * n_matmul + attn


def _mfu(tps: float, cfg, lora: bool = False) -> float:
    peak_flops, _ = _device_specs()
    return tps * _model_flops_per_token(cfg, lora) / peak_flops


def _time_steps(step, state, batch, warmup=3, iters=20):
    for _ in range(max(1, warmup)):
        state, metrics = step(state, batch)
    float(metrics["loss"])
    t0 = time.perf_counter()
    for _ in range(iters):
        state, metrics = step(state, batch)
    float(metrics["loss"])
    return time.perf_counter() - t0


def _batch(cfg, batch_size, seed=0, sft_mask=False):
    rng = np.random.default_rng(seed)
    T = cfg.context_length
    w = np.ones((batch_size, T), np.float32)
    if sft_mask:
        # instruction finetune: prompt tokens carry no loss (collator 0/1
        # weights); mask the first half like a typical Alpaca prompt
        w[:, : T // 2] = 0.0
    return {
        "inputs": rng.integers(0, cfg.vocab_size, (batch_size, T)).astype(
            np.int32),
        "targets": rng.integers(0, cfg.vocab_size, (batch_size, T)).astype(
            np.int32),
        "weights": w,
    }


def _pretrain_tps(cfg, batch_size, policy=None, warmup=3, iters=20,
                  shard_mode=None, lora_rank=None, lora_alpha=None,
                  sft_mask=False, grad_accum=1):
    from building_llm_from_scratch_tpu.models import init_params
    from building_llm_from_scratch_tpu.parallel import build_mesh_plan
    from building_llm_from_scratch_tpu.training import (
        build_optimizer,
        init_train_state,
        make_train_step,
    )

    params = init_params(cfg, jax.random.PRNGKey(0))
    if lora_rank is not None:
        from building_llm_from_scratch_tpu.models.lora import init_lora_params

        trainable = init_lora_params(cfg, params, jax.random.PRNGKey(1),
                                     rank=lora_rank)
        frozen = params
    else:
        trainable, frozen = params, None
    opt = build_optimizer(total_steps=warmup + iters + 1)
    state = init_train_state(trainable, opt, jax.random.PRNGKey(0),
                             frozen=frozen, policy=policy)
    batch = _batch(cfg, batch_size, sft_mask=sft_mask)
    if shard_mode is not None:
        plan = build_mesh_plan(shard_mode)
        state = plan.shard_state(state)
        batch = plan.shard_batch(batch)
    step = make_train_step(cfg, opt, policy=policy, lora_rank=lora_rank,
                           lora_alpha=lora_alpha, grad_accum=grad_accum)
    # CompileWatcher-wrap the step (obs/compile.py): the AOT capture makes
    # the line carry XLA's own cost accounting next to the measured tok/s
    # (compile seconds, HLO FLOPs, HBM breakdown), an active
    # FingerprintCollector (obs/perf.py) records it into the bench's
    # structural fingerprint, and the timed executable is the AOT-compiled
    # one (one compile either way; on capture failure the watcher falls
    # back to the plain jit path itself).
    from building_llm_from_scratch_tpu.obs.compile import CompileWatcher

    step = CompileWatcher(step, label="bench_step")
    warmup, iters = _q_iters(warmup, iters)
    dt = _time_steps(step, state, batch, warmup, iters)
    return batch_size * cfg.context_length * iters / dt / jax.device_count()


def bench_cfg1():
    """BASELINE #1: GPT2-124M single-device pretrain, fp32, no LoRA/ckpt.

    batch 4 == the reference's default (args.py:53); fp32 + no remat at
    batch 8 exceeds one v5e chip's 16GB HBM.
    """
    from building_llm_from_scratch_tpu.configs import get_config

    cfg = get_config("GPT2", "124M", dtype="fp32")
    tps = _pretrain_tps(cfg, batch_size=4)
    return _result("cfg1", "tokens/sec/chip GPT2-124M pretrain fp32 bs4 "
                   "ctx1024", tps, mfu=_mfu(tps, cfg))


def bench_headline():
    """Headline: GPT2-124M pretrain in bf16 — the dtype a TPU user would
    actually run (MXU-native), per round-2 VERDICT #3.

    bs8 since round 4: the fused attention kernel generates dropout masks
    in-kernel (ops/fused_attention.py), so the bs8 mask-temp HBM pressure
    that made bs4 faster in round 3 is gone (r4 measured: bs8 76.6k vs
    bs4 72.7k tok/s/chip)."""
    from building_llm_from_scratch_tpu.configs import get_config
    from building_llm_from_scratch_tpu.training import get_policy

    cfg = get_config("GPT2", "124M", dtype="fp32")
    tps = _pretrain_tps(cfg, batch_size=8, policy=get_policy("bf16"))
    return _result("headline", "tokens/sec/chip GPT2-124M pretrain bf16 "
                   "bs8 ctx1024", tps, mfu=_mfu(tps, cfg))


def bench_cfg2():
    """BASELINE #2: GPT2-774M pretrain, bf16 + activation ckpt (remat)."""
    from building_llm_from_scratch_tpu.configs import get_config
    from building_llm_from_scratch_tpu.training import get_policy

    cfg = get_config("GPT2", "774M", dtype="bf16", use_actv_ckpt=True)
    tps = _pretrain_tps(cfg, batch_size=8, warmup=2, iters=10,
                        policy=get_policy("bf16"))
    return _result("cfg2", "tokens/sec/chip GPT2-774M pretrain bf16+remat "
                   "bs8 ctx1024", tps, mfu=_mfu(tps, cfg))


def bench_cfg3():
    """BASELINE #3: LLaMA3.2-1B instruction SFT with LoRA rank 8, bf16
    (the second north-star metric)."""
    from building_llm_from_scratch_tpu.configs import get_config
    from building_llm_from_scratch_tpu.training import get_policy

    # remat: without it the scan saves (L=16, B, T, hidden=8192) activation
    # tensors for backward — 12GB+ of HLO temps, over one chip's 16GB
    cfg = get_config("llama3_2", "1B", dtype="bf16", use_actv_ckpt=True,
                     target_context_length=1024)
    tps = _pretrain_tps(cfg, batch_size=8, warmup=2, iters=10,
                        policy=get_policy("bf16"), lora_rank=8,
                        lora_alpha=16, sft_mask=True)
    return _result("cfg3", "tokens/sec/chip LLaMA3.2-1B LoRA-r8 SFT bf16 "
                   "bs8 ctx1024", tps, mfu=_mfu(tps, cfg, lora=True))


def bench_cfg4():
    """BASELINE #4: LLaMA3-8B fsdp — 8B does not fit one 16GB chip, so this
    runs the exact fsdp code path on the deepest 8B-architecture slice that
    fits (full 4096-dim layers, reduced layer count; the name records it).
    Full-size 8-way fsdp compiles+runs in dryrun_multichip."""
    from building_llm_from_scratch_tpu.configs import get_config
    from building_llm_from_scratch_tpu.training import get_policy

    cfg = get_config("llama3", "8B", dtype="bf16", use_actv_ckpt=True,
                     target_context_length=1024).replace(n_layers=2)
    tps = _pretrain_tps(cfg, batch_size=4, warmup=2, iters=10,
                        policy=get_policy("bf16"), shard_mode="fsdp")
    return _result("cfg4", "tokens/sec/chip LLaMA3-8B-arch[2/32 layers] "
                   "SFT bf16 fsdp bs4 ctx1024", tps, mfu=_mfu(tps, cfg))


def bench_cfg5():
    """BASELINE #5: LLaMA2-7B zero1 — same one-chip constraint as #4; runs
    the zero1 (optimizer-state sharding) path on the deepest 7B-architecture
    slice that fits."""
    from building_llm_from_scratch_tpu.configs import get_config
    from building_llm_from_scratch_tpu.training import get_policy

    cfg = get_config("llama2", "7B", dtype="bf16", use_actv_ckpt=True,
                     target_context_length=1024).replace(n_layers=4)
    tps = _pretrain_tps(cfg, batch_size=4, warmup=2, iters=10,
                        policy=get_policy("bf16"), shard_mode="zero1")
    return _result("cfg5", "tokens/sec/chip LLaMA2-7B-arch[4/32 layers] "
                   "pretrain bf16 zero1 bs4 ctx1024", tps,
                   mfu=_mfu(tps, cfg))


def bench_accum():
    """--grad_accum: global batch 32 as 4 scanned microbatches of 8 — the
    large-global-batch/small-microbatch regime pods want (round-5 VERDICT
    #7). Activation memory is one bs-8 microbatch's; throughput should sit
    near the bs8 headline (the scan adds one fp32 grad accumulator
    read-modify-write per micro)."""
    from building_llm_from_scratch_tpu.configs import get_config
    from building_llm_from_scratch_tpu.training import get_policy

    cfg = get_config("GPT2", "124M", dtype="fp32")
    tps = _pretrain_tps(cfg, batch_size=32, warmup=2, iters=10,
                        policy=get_policy("bf16"), grad_accum=4)
    return _result("accum", "tokens/sec/chip GPT2-124M pretrain bf16 bs32 "
                   "grad_accum4", tps, mfu=_mfu(tps, cfg))


def _trainer_run(n_steps=60, prefetch=0, async_ckpt=False, save_every=None):
    """One Trainer-loop run; returns (mean steady-state tok/s, stats dict
    with the overlap accounting bench_prefetch A/Bs). ``save_every`` turns
    on periodic checkpointing (sync or async per ``async_ckpt``); default
    off so the headline bench_trainer figure stays comparable to history."""
    import tempfile

    from building_llm_from_scratch_tpu.configs import get_config
    from building_llm_from_scratch_tpu.data import ByteTokenizer, PretrainLoader
    from building_llm_from_scratch_tpu.models import init_params
    from building_llm_from_scratch_tpu.training import Trainer, get_policy

    if _QUICK:
        n_steps = min(n_steps, 12)
    cfg = get_config("GPT2", "124M", dtype="fp32")
    params = init_params(cfg, jax.random.PRNGKey(0))
    tok = ByteTokenizer()
    loader = PretrainLoader(tok, batch_size=4, max_length=cfg.context_length)
    with tempfile.TemporaryDirectory() as d:
        path = f"{d}/corpus.txt"
        # enough bytes for > n_steps batches of 8x1024 tokens
        with open(path, "w") as f:
            f.write("the quick brown fox jumps over the lazy dog. "
                    * (n_steps * 4 * 1024 // 44 + 200))
        trainer = Trainer(cfg, params, tok, loader, output_dir=d,
                          policy=get_policy("bf16"),
                          eval_freq=20, eval_iters=1,
                          print_sample_iter=10 ** 9,
                          save_ckpt_freq=save_every or 10 ** 9,
                          warmup_steps=2, show_progress=False,
                          prefetch=prefetch, async_ckpt=async_ckpt)
        trainer.train_model([path], n_epochs=1)
        # drop the first window (compile); average the steady-state windows
        tps_windows = trainer.throughput_tokens_per_s[1:]
    tps = float(np.mean(tps_windows)) if tps_windows else 0.0
    steps = max(trainer.global_step, 1)
    stats = {
        "data_wait_s_per_step": round(
            trainer.data_wait_total_s / steps, 6),
        "data_wait_frac": round(
            trainer.data_wait_total_s / max(trainer.step_seconds_total,
                                            1e-9), 4),
        "prefetch_stalls": trainer.prefetch_stall_total,
        "steps": trainer.global_step,
    }
    return tps, stats


def bench_trainer(n_steps=60):
    """The Trainer-loop path (cadence work, metric tracking, data pipeline)
    — must be within ~5% of the raw-step headline (round-2 VERDICT #3).
    Runs with the CLI-default --prefetch 2 since the host-overlap round."""
    tps, stats = _trainer_run(n_steps, prefetch=2)
    return _result("trainer", "tokens/sec/chip GPT2-124M Trainer-loop bf16 "
                   "bs4 ctx1024", tps, detail=stats)


def bench_prefetch(n_steps=60):
    """Host-overlap A/B: the identical Trainer workload with --prefetch 0
    (strict synchronous data path, blocking saves) vs --prefetch 2 + async
    checkpoints. Both arms checkpoint every n_steps//3 steps so the save
    cost is actually in the measurement — sync pays the full write barrier
    in-loop, async pays only the snapshot. The JSON line carries per-step
    data_wait and its fraction of step time for BOTH runs — the overlap
    win the BENCH history tracks — alongside the prefetched tok/s the
    headline metric reports."""
    save_every = max(n_steps // 3, 1)
    tps_off, off = _trainer_run(n_steps, prefetch=0, save_every=save_every)
    tps_on, on = _trainer_run(n_steps, prefetch=2, async_ckpt=True,
                              save_every=save_every)
    wait_off = max(off["data_wait_s_per_step"], 1e-9)
    detail = {
        "prefetch_off": dict(off, tok_s=round(tps_off, 1)),
        "prefetch_on": dict(on, tok_s=round(tps_on, 1)),
        "data_wait_speedup": round(
            wait_off / max(on["data_wait_s_per_step"], 1e-9), 1),
    }
    print(json.dumps(detail), flush=True)
    return _result("prefetch", "tokens/sec/chip GPT2-124M Trainer-loop "
                   "prefetch2+async_ckpt bf16 bs4 ctx1024", tps_on,
                   detail=detail)


def bench_decode(max_new=256):
    """Generation throughput: jitted KV-cache greedy decode on GPT2-124M
    (beyond reference parity — its generate.py re-runs the FULL forward per
    token with no cache, generate.py:36-45).

    Also logs per-seq tok/s and % of the weight-streaming roofline
    (param bytes measured from the actual tree, HBM bandwidth from the
    detected device kind — round-4 ADVICE low #4; for GPT2-124M bf16 on
    v5e: 248MB/step over ~820GB/s -> ~3,300 steps/s ceiling at
    bs-independent decode)."""
    import time

    from building_llm_from_scratch_tpu.configs import get_config
    from building_llm_from_scratch_tpu.generate import generate
    from building_llm_from_scratch_tpu.models import init_params

    if _QUICK:
        max_new = min(max_new, 64)
    cfg = get_config("GPT2", "124M", dtype="bf16")
    params = init_params(cfg, jax.random.PRNGKey(0))
    param_bytes = sum(leaf.size * leaf.dtype.itemsize
                      for leaf in jax.tree_util.tree_leaves(params))
    prompt = np.arange(32, dtype=np.int32)[None].repeat(8, 0)  # bs8
    kw = dict(max_new_tokens=max_new, context_size=cfg.context_length)
    out = generate(params, cfg, prompt, **kw)       # compile + warm
    # best-of-3: each call pays one device_get whose tunnel latency varies
    # by 100ms+ run to run on the remote backend
    dt = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        out = generate(params, cfg, prompt, **kw)
        dt = min(dt, time.perf_counter() - t0)
    n_steps = out.shape[1] - prompt.shape[1]
    n_tok = n_steps * prompt.shape[0]
    _, hbm_bw = _device_specs()
    roofline_steps = hbm_bw / param_bytes           # HBM BW / weight bytes

    # Device-side rate: every generate() call pays a fixed host/tunnel
    # latency (~100ms+ on the axon remote backend) that a 256-token decode
    # cannot amortize; differencing two budgets cancels it, isolating the
    # per-token device time the roofline actually bounds.
    def best_wall(budget):
        kw2 = dict(kw, max_new_tokens=budget)
        o = generate(params, cfg, prompt, **kw2)
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            o = generate(params, cfg, prompt, **kw2)
            best = min(best, time.perf_counter() - t0)
        assert o.shape[1] - prompt.shape[1] == budget
        return best

    lo, hi = (32, 96) if _QUICK else (128, 384)
    t_low, t_high = best_wall(lo), best_wall(hi)
    dev_steps_s = (hi - lo) / max(t_high - t_low, 1e-9)
    detail = {
        "decode_per_seq_tok_s": round(n_steps / dt, 1),
        "decode_pct_of_weight_stream_roofline":
            round(100 * (n_steps / dt) / roofline_steps, 1),
        "decode_device_per_seq_tok_s": round(dev_steps_s, 1),
        "decode_device_pct_of_weight_stream_roofline":
            round(100 * dev_steps_s / roofline_steps, 1),
    }
    print(json.dumps(detail), flush=True)
    return _result("decode", "decode tokens/sec GPT2-124M bf16 bs8 "
                   "kv-cache greedy", n_tok / dt, unit="tokens/sec",
                   detail=detail)


def bench_serve(n_requests=8, max_new=32, prompt_len=16):
    """Continuous-batching serving (serving/engine.py) vs the naive
    sequential baseline: the SAME n_requests prompts decoded one
    ``generate()`` call at a time (bs1 — what the repo could do before the
    engine existed) vs pumped through the slot engine at growing
    concurrency. Reports aggregate tok/s + p50/p99 e2e latency per arm;
    the acceptance bar is the engine beating sequential at >= 4 slots.

    bf16 on TPU, fp32 elsewhere (CPU bf16 is emulated and would distort
    the A/B)."""
    import time

    from building_llm_from_scratch_tpu.configs import get_config
    from building_llm_from_scratch_tpu.generate import _bucket, generate
    from building_llm_from_scratch_tpu.models import init_params
    from building_llm_from_scratch_tpu.serving import (
        DecodeEngine,
        SamplingParams,
    )

    if _QUICK:
        n_requests, max_new = min(n_requests, 4), min(max_new, 8)
    dtype = "bf16" if jax.default_backend() == "tpu" else "fp32"
    cfg = get_config("GPT2", "124M", dtype=dtype)
    params = init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    prompts = rng.integers(0, cfg.vocab_size,
                           (n_requests, prompt_len)).astype(np.int32)
    sp = SamplingParams(max_new_tokens=max_new, ignore_eos=True)

    # sequential baseline (eos disabled so both arms decode the full
    # budget — the A/B measures throughput, not stopping luck). Latency
    # is e2e from batch start (request i waits for 0..i-1), the same
    # all-submitted-at-t0 semantics as the engine arm's e2e_hist — NOT
    # per-call decode time, which would flatter the sequential tail
    generate(params, cfg, prompts[0][None], max_new_tokens=max_new)  # warm
    lat_seq = []
    t0 = time.perf_counter()
    for p in prompts:
        out = generate(params, cfg, p[None], max_new_tokens=max_new)
        assert out.shape[1] == prompt_len + max_new
        lat_seq.append(time.perf_counter() - t0)
    dt_seq = time.perf_counter() - t0
    seq_tok_s = n_requests * max_new / dt_seq
    detail = {"sequential": {
        "tok_s": round(seq_tok_s, 1),
        "p50_s": round(float(np.percentile(lat_seq, 50)), 4),
        "p99_s": round(float(np.percentile(lat_seq, 99)), 4),
    }}

    engine_at_4 = None
    for slots in (1, 4, 8):
        engine = DecodeEngine(cfg, params, n_slots=slots,
                              max_len=_bucket(prompt_len + max_new),
                              max_queue=n_requests,
                              warmup_prompt_cap=prompt_len)
        engine.warmup()
        t0 = time.perf_counter()
        handles = [engine.submit(p, sp, block=True) for p in prompts]
        engine.run_until_idle()
        dt = time.perf_counter() - t0
        for h in handles:
            assert len(h.output_ids) == max_new, h.finish_reason
        tok_s = n_requests * max_new / dt
        e2e_pct = engine.e2e_hist.percentiles((50, 99))
        detail[f"engine_slots{slots}"] = {
            "tok_s": round(tok_s, 1),
            "p50_s": e2e_pct.get("p50"),
            "p99_s": e2e_pct.get("p99"),
            "vs_sequential": round(tok_s / seq_tok_s, 2),
            "recompiles": engine.n_recompiles,
        }
        if slots == 4:
            engine_at_4 = tok_s
        engine.shutdown()
    print(json.dumps(detail), flush=True)
    return _result("serve", f"serve tokens/sec GPT2-124M {dtype} "
                   f"{n_requests}req x {max_new}new continuous-batching "
                   "slots4", engine_at_4, unit="tokens/sec", detail=detail)


def bench_serve_load(n_slots=4, max_new=24, prompt_len=16,
                     n_requests=40, deadline_factor=2.0):
    """Open-loop Poisson-arrival load sweep (the load-harness seed for
    the scale-out serving roadmap item): requests arrive on a Poisson
    schedule regardless of completions — unlike the closed-loop
    ``bench.py serve`` arm, this can actually SEE saturation, because
    offered load keeps coming when the engine falls behind.

    Arms sweep offered load at 0.5x / 1.0x / 1.5x the engine's measured
    closed-loop capacity. Every request carries a deadline
    (``deadline_factor`` x its ideal solo service time), so the overload
    arm exercises the real admission stack: SLO shedding at submit,
    TTL expiry in the queue, 429-style queue-full rejection. Reported
    per arm: offered/completed rps, shed/expired/rejected counts, and
    TTFT/TPOT/e2e percentiles — the latency-vs-throughput curve.

    Each arm writes its own metrics JSONL (reported as
    ``metrics_jsonl`` in the arm detail), so the per-arm tick-phase
    breakdown, request span trees and SLO burn are renderable after the
    fact: ``python scripts/summarize_metrics.py <arm.jsonl> --trace
    <arm.trace.json>``.

    fp32 on CPU, bf16 on TPU (same policy as ``bench_serve``)."""
    import tempfile
    import time

    from building_llm_from_scratch_tpu.configs import get_config
    from building_llm_from_scratch_tpu.generate import _bucket
    from building_llm_from_scratch_tpu.models import init_params
    from building_llm_from_scratch_tpu.serving import (
        DecodeEngine,
        QueueFullError,
        SLOShedError,
        SamplingParams,
    )
    from building_llm_from_scratch_tpu.serving.request import (
        RequestExpiredError,
    )

    if _QUICK:
        n_requests, max_new = min(n_requests, 12), min(max_new, 8)
    dtype = "bf16" if jax.default_backend() == "tpu" else "fp32"
    cfg = get_config("GPT2", "124M", dtype=dtype)
    params = init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    prompts = rng.integers(0, cfg.vocab_size,
                           (n_requests, prompt_len)).astype(np.int32)

    def new_engine():
        # metrics_every=8: short arms still emit tick-breakdown cadence
        # rows into their per-arm JSONL (the default 32 would leave a
        # small sweep with request events but no tick phases)
        eng = DecodeEngine(cfg, params, n_slots=n_slots,
                           max_len=_bucket(prompt_len + max_new),
                           max_queue=max(2 * n_slots, 16),
                           warmup_prompt_cap=prompt_len,
                           metrics_every=8)
        eng.warmup()
        return eng

    # measure closed-loop capacity first: n_slots requests decoded flat out
    eng = new_engine()
    t0 = time.perf_counter()
    sp = SamplingParams(max_new_tokens=max_new, ignore_eos=True)
    handles = [eng.submit(p, sp, block=True) for p in prompts[:n_slots]]
    eng.run_until_idle()
    cap_tok_s = n_slots * max_new / (time.perf_counter() - t0)
    cap_rps = cap_tok_s / max_new            # requests/sec at saturation
    solo_s = max_new / (cap_tok_s / n_slots)  # ideal one-request service
    eng.shutdown()
    detail = {"capacity": {"tok_s": round(cap_tok_s, 1),
                           "rps": round(cap_rps, 3)}}

    deadline_s = deadline_factor * solo_s
    completed_at_1x = 0.0
    from building_llm_from_scratch_tpu.obs import configure_metrics

    jsonl_dir = tempfile.mkdtemp(prefix="bench_serve_load_")
    for load in (0.5, 1.0, 1.5):
        lam = load * cap_rps                 # offered arrival rate
        arrivals = np.cumsum(rng.exponential(1.0 / lam, n_requests))
        # one telemetry file per arm: tick breakdown / span trees / SLO
        # burn stay attributable to THIS offered-load point
        arm_jsonl = os.path.join(jsonl_dir, f"load_{load:g}x.jsonl")
        configure_metrics(arm_jsonl, run_metadata={
            "bench": "serve_load", "offered_load_x": load,
            "n_slots": n_slots, "n_requests": n_requests})
        eng = new_engine()
        eng.start()
        handles, shed, rejected = [], 0, 0
        t0 = time.perf_counter()
        for i, (p, at) in enumerate(zip(prompts, arrivals)):
            delay = at - (time.perf_counter() - t0)
            if delay > 0:
                time.sleep(delay)            # open loop: arrivals wait
            try:                             # for the CLOCK, not the engine
                handles.append(eng.submit(p, SamplingParams(
                    max_new_tokens=max_new, ignore_eos=True,
                    deadline_s=deadline_s, seed=i)))
            except SLOShedError:
                shed += 1
            except QueueFullError:
                rejected += 1
        done, expired = 0, 0
        for h in handles:
            try:
                h.result(timeout=120)
                done += 1
            except RequestExpiredError:
                expired += 1
            except RuntimeError:
                pass
        dt = time.perf_counter() - t0
        eng.shutdown()
        configure_metrics(None)              # close + detach the arm sink
        stats = eng.stats()
        arm = {
            "offered_rps": round(lam, 3),
            "completed_rps": round(done / dt, 3),
            "done": done, "shed": shed, "expired": expired,
            "rejected": rejected,
            "shed_rate": round((shed + expired + rejected)
                               / n_requests, 3),
            "metrics_jsonl": arm_jsonl,
        }
        for key in ("ttft_s", "tpot_s", "e2e_s"):
            if key in stats:
                arm[key] = stats[key]
        detail[f"load_{load:g}x"] = arm
        if load == 1.0:
            completed_at_1x = done / dt
    print(json.dumps(detail), flush=True)
    return _result("serve_load", f"serve offered-load sweep GPT2-124M "
                   f"{dtype} {n_requests}req poisson slots{n_slots} "
                   "completed-rps@1.0x", completed_at_1x * max_new,
                   unit="tokens/sec", detail=detail)


def bench_serve_fleet(max_new=24, prompt_len=16, n_slots=4,
                      requests_per_replica=32, replica_counts=(1, 2, 4)):
    """Replica-scaling sweep through the fleet router (serving/router.py):
    the ``serve_load`` open-loop Poisson harness pointed at an
    ``EngineRouter`` at 1/2/4 replicas, offered load scaled with the
    replica count (per-replica capacity measured once by the 1-replica
    arm). Each arm runs in a SUBPROCESS with
    ``--xla_force_host_platform_device_count=8`` so every replica gets
    its own CPU device — per-device execution threads are independent
    and XLA releases the GIL, so this measures real concurrent replicas,
    not time-slicing (scripts/bench_fleet_worker.py). Aggregate
    completed-rps should scale near-linearly; the headline metric is the
    2-replica aggregate tokens/sec, ``speedup_2x``/``speedup_4x`` ride
    as extra metrics. This bench has no in-process fingerprint (the
    programs compile in the workers) — ``micro_router`` structurally
    gates the per-replica program family in CI instead."""
    import subprocess

    rpr, mnew = requests_per_replica, max_new
    if _QUICK:
        rpr, mnew = min(rpr, 8), min(mnew, 8)
    repo = os.path.dirname(os.path.abspath(__file__))
    worker = os.path.join(repo, "scripts", "bench_fleet_worker.py")
    env = dict(os.environ, JAX_PLATFORMS=os.environ.get("JAX_PLATFORMS",
                                                        "cpu"))
    # the worker imports the package from the repo root (running it by
    # path puts scripts/ at sys.path[0], not the repo)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    detail = {}
    cap_rps = 0.0
    completed = {}
    for r in replica_counts:
        cmd = [sys.executable, worker, "--replicas", str(r),
               "--cap_rps", str(cap_rps),
               "--requests_per_replica", str(rpr),
               "--max_new", str(mnew), "--prompt_len", str(prompt_len),
               "--slots", str(n_slots), "--loads", "0.75,1.25"]
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=1800, env=env)
        if proc.returncode != 0:
            raise RuntimeError(
                f"fleet worker (replicas={r}) failed rc="
                f"{proc.returncode}:\n{proc.stderr[-2000:]}")
        row = json.loads(proc.stdout.strip().splitlines()[-1])
        if cap_rps <= 0:
            cap_rps = row["cap_rps"]
            detail["capacity"] = row.get("capacity")
        detail[f"replicas_{r}"] = row["arms"]
        completed[r] = row["arms"]["load_1.25x"]["completed_rps"]
    for r in replica_counts[1:]:
        if completed.get(1):
            detail[f"speedup_{r}x"] = round(completed[r] / completed[1], 3)
    # cross-process arm: the SAME sweep at 2 replicas through a
    # ProcessFleet of supervised worker subprocesses (serving/fleet.py)
    # reusing the in-process capacity point — the ratio vs the
    # in-process router bounds RPC-transport + supervision overhead
    crossproc_ratio = None
    if 2 in completed:
        cmd = [sys.executable, worker, "--replicas", "2",
               "--transport", "process", "--cap_rps", str(cap_rps),
               "--requests_per_replica", str(rpr),
               "--max_new", str(mnew), "--prompt_len", str(prompt_len),
               "--slots", str(n_slots), "--loads", "1.25"]
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=1800, env=env)
        if proc.returncode != 0:
            raise RuntimeError(
                f"fleet worker (crossproc) failed rc="
                f"{proc.returncode}:\n{proc.stderr[-2000:]}")
        row = json.loads(proc.stdout.strip().splitlines()[-1])
        detail["crossproc_2"] = row["arms"]
        cp = row["arms"]["load_1.25x"]["completed_rps"]
        crossproc_ratio = round(cp / completed[2], 3) if completed[2] \
            else None
        detail["crossproc_ratio"] = crossproc_ratio
    print(json.dumps(detail), flush=True)
    res = _result("serve_fleet", "fleet aggregate tokens/sec GPT2-124M "
                  f"router {len(replica_counts)}-arm sweep slots{n_slots} "
                  "completed@1.25x 2-replicas",
                  completed.get(2, completed[replica_counts[0]]) * mnew,
                  unit="tokens/sec", detail=detail)
    for r in replica_counts[1:]:
        if f"speedup_{r}x" in detail:
            res.add_metric(f"speedup_{r}x", detail[f"speedup_{r}x"],
                           "ratio")
    if crossproc_ratio is not None:
        res.add_metric("crossproc_ratio", crossproc_ratio, "ratio")
    return res


def bench_micro_router(n_replicas=2):
    """Debug-size fleet router (2 replicas x 2 slots, 8 mixed requests):
    the gate workload for the scale-out tier. ``watch_compiles="first"``
    wraps only replica 0's programs, so the captured fingerprint is the
    PER-REPLICA compiled-program family — replica-count invariant by
    construction (a 3-replica router fingerprints identically,
    test-pinned), while a change to what one replica compiles (router
    construction altering cache placement, an extra program, a warmup
    recompile) fails the structural gate with the program named."""
    from building_llm_from_scratch_tpu.configs import get_config
    from building_llm_from_scratch_tpu.models import init_params
    from building_llm_from_scratch_tpu.serving import (
        EngineRouter,
        SamplingParams,
    )

    n_requests, max_new, prompt_len = 8, 4, 4
    cfg = get_config("GPT2", "124M", dtype="fp32", debug=True)
    params = init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    prompts = rng.integers(0, cfg.vocab_size,
                           (n_requests, prompt_len)).astype(np.int32)
    sp = SamplingParams(max_new_tokens=max_new, ignore_eos=True)
    router = EngineRouter.build(cfg, params, n_replicas=n_replicas,
                                n_slots=2, max_queue=n_requests,
                                warmup_prompt_cap=prompt_len,
                                metrics_every=2,
                                watch_compiles="first")
    router.warmup()
    t0 = time.perf_counter()
    handles = [router.submit(p, sp, block=True) for p in prompts]
    router.run_until_idle()
    dt = time.perf_counter() - t0
    for h in handles:
        assert len(h.output_ids) == max_new, h.finish_reason
    detail = {"recompiles": router.n_recompiles,
              "routed_total": router.routed_total}
    router.shutdown()
    return _result("micro_router", "fleet tokens/sec GPT2-debug fp32 "
                   f"{n_requests}req x {max_new}new "
                   f"{n_replicas}replicas x slots2",
                   n_requests * max_new / dt, unit="tokens/sec",
                   detail=detail)


def bench_serve_lora(n_adapters=3, n_requests=16, max_new=24,
                     prompt_len=16, rank=8, n_slots=4):
    """Multi-tenant LoRA serving A/B (serving/adapters.py): the SAME
    request set decoded (a) by the historical registry-less engine,
    (b) by an adapter-pooled engine serving base-only traffic (the pure
    overhead of carrying the pool through the compiled programs), and
    (c) mixed traffic round-robining ``n_adapters`` adapters + base —
    the multi-tenant case a merge-based LoRA deployment cannot co-batch
    at all. Every arm must finish with ZERO recompiles (adapter identity
    is data, not a compile signature).

    bf16 on TPU, fp32 elsewhere (same policy as ``bench_serve``)."""
    import tempfile
    import time

    from building_llm_from_scratch_tpu.configs import get_config
    from building_llm_from_scratch_tpu.generate import _bucket
    from building_llm_from_scratch_tpu.models import init_params
    from building_llm_from_scratch_tpu.models.lora import (
        init_lora_params,
        save_adapter,
    )
    from building_llm_from_scratch_tpu.serving import (
        AdapterRegistry,
        DecodeEngine,
        SamplingParams,
    )

    if _QUICK:
        n_requests, max_new = min(n_requests, 8), min(max_new, 8)
    dtype = "bf16" if jax.default_backend() == "tpu" else "fp32"
    cfg = get_config("GPT2", "124M", dtype=dtype)
    params = init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    prompts = rng.integers(0, cfg.vocab_size,
                           (n_requests, prompt_len)).astype(np.int32)

    art_dir = tempfile.mkdtemp(prefix="bench_serve_lora_")
    specs = {}
    for i in range(n_adapters):
        lora = init_lora_params(cfg, params, jax.random.PRNGKey(100 + i),
                                rank=rank)
        lora = jax.tree_util.tree_map(
            lambda a, i=i: a + 0.02 * jax.random.normal(
                jax.random.PRNGKey(200 + i), a.shape, a.dtype), lora)
        path = os.path.join(art_dir, f"adapter_{i}.npz")
        save_adapter(path, lora, rank=rank, alpha=2.0 * rank, cfg=cfg)
        specs[f"tenant{i}"] = path

    def run_arm(adapters, names):
        eng = DecodeEngine(cfg, params, n_slots=n_slots,
                           max_len=_bucket(prompt_len + max_new),
                           max_queue=n_requests,
                           warmup_prompt_cap=prompt_len, adapters=adapters)
        eng.warmup()
        t0 = time.perf_counter()
        handles = [eng.submit(p, SamplingParams(
            max_new_tokens=max_new, ignore_eos=True, seed=i,
            adapter=names[i % len(names)]), block=True)
            for i, p in enumerate(prompts)]
        eng.run_until_idle()
        dt = time.perf_counter() - t0
        for h in handles:
            assert len(h.output_ids) == max_new, h.error
        assert eng.n_recompiles == 0, "adapter traffic recompiled"
        tok_s = n_requests * max_new / dt
        eng.shutdown()
        return tok_s

    base_tok_s = run_arm(None, [None])
    reg = AdapterRegistry.from_artifacts(cfg, params, specs)
    pool_tok_s = run_arm(reg, [None])
    mixed_names = [None] + list(specs)
    mixed_tok_s = run_arm(reg, mixed_names)
    detail = {
        "no_registry": {"tok_s": round(base_tok_s, 1)},
        "registry_base_only": {
            "tok_s": round(pool_tok_s, 1),
            "vs_no_registry": round(pool_tok_s / base_tok_s, 3)},
        "mixed_adapters": {
            "tok_s": round(mixed_tok_s, 1),
            "n_adapters": n_adapters, "rank": rank,
            "vs_no_registry": round(mixed_tok_s / base_tok_s, 3)},
        "recompiles": 0,
    }
    print(json.dumps(detail), flush=True)
    return _result("serve_lora", f"serve_lora tokens/sec GPT2-124M {dtype} "
                   f"{n_requests}req x {max_new}new {n_adapters}adapters"
                   f"+base slots{n_slots}", mixed_tok_s,
                   unit="tokens/sec", detail=detail)


def bench_serve_prefix(n_requests=10, prefix_len=192, suffix_len=8,
                       max_new=16, n_slots=4, chunk=64):
    """Shared-system-prompt A/B for the KV-cache memory engine
    (serving/kvcache.py): ``n_requests`` requests share one
    ``prefix_len``-token system prompt and differ only in a short
    suffix — the workload millions-of-users serving is made of.

    Three arms over the SAME requests:
      - ``unchunked``: the historical monolithic bucketed prefill
        (baseline for the per-tick prefill stall);
      - ``chunk_only``: chunked prefill (C=``chunk``), prefix cache OFF
        — isolates the head-of-line bound;
      - ``prefix_on``: chunked prefill + prefix cache — the first
        request prefills the prefix once, every successor copies its
        panes and chunk-prefills only the suffix.

    Reported per arm: TTFT p50/p95, per-tick prefill-wall p50/p95
    (``tick_prefill_hist`` — the head-of-line metric chunking bounds),
    prefix hit count, recompiles. The headline value is the prefix-ON
    aggregate tok/s; the acceptance bar is prefix_on TTFT p95 <
    chunk_only TTFT p95 (cached span skips its forward) with zero
    recompiles after warmup, and chunked tick-prefill p95 < unchunked.

    bf16 on TPU, fp32 elsewhere (same policy as ``bench_serve``)."""
    import time

    from building_llm_from_scratch_tpu.configs import get_config
    from building_llm_from_scratch_tpu.generate import _bucket
    from building_llm_from_scratch_tpu.models import init_params
    from building_llm_from_scratch_tpu.serving import (
        DecodeEngine,
        KVCachePolicy,
        SamplingParams,
    )

    if _QUICK:
        n_requests, max_new = min(n_requests, 6), min(max_new, 8)
    dtype = "bf16" if jax.default_backend() == "tpu" else "fp32"
    cfg = get_config("GPT2", "124M", dtype=dtype)
    params = init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    prefix = rng.integers(0, cfg.vocab_size, (prefix_len,)).astype(np.int32)
    prompts = [np.concatenate([
        prefix, rng.integers(0, cfg.vocab_size,
                             (suffix_len,)).astype(np.int32)])
        for _ in range(n_requests)]
    sp = SamplingParams(max_new_tokens=max_new, ignore_eos=True)
    cap = prefix_len + suffix_len
    max_len = _bucket(cap + max_new)

    arms = {
        "unchunked": KVCachePolicy(),
        "chunk_only": KVCachePolicy(prefill_chunk=chunk),
        "prefix_on": KVCachePolicy(prefill_chunk=chunk, prefix_cache=True),
    }
    detail = {}
    headline = None
    for arm, policy in arms.items():
        engine = DecodeEngine(cfg, params, n_slots=n_slots,
                              max_len=max_len, max_queue=n_requests,
                              warmup_prompt_cap=cap, kv_policy=policy)
        engine.warmup()
        t0 = time.perf_counter()
        handles = [engine.submit(p, sp, block=True) for p in prompts]
        engine.run_until_idle()
        dt = time.perf_counter() - t0
        for h in handles:
            assert len(h.output_ids) == max_new, h.finish_reason
        tok_s = n_requests * max_new / dt
        ttft = engine.ttft_hist.percentiles((50, 95))
        tick_pf = engine.tick_prefill_hist.percentiles((50, 95))
        row = {
            "tok_s": round(tok_s, 1),
            "ttft_p50_s": ttft.get("p50"),
            "ttft_p95_s": ttft.get("p95"),
            "tick_prefill_p50_s": tick_pf.get("p50"),
            "tick_prefill_p95_s": tick_pf.get("p95"),
            "recompiles": engine.n_recompiles,
        }
        if engine.prefix_store is not None:
            st = engine.prefix_store.stats()
            row["prefix_hits"] = st["hits"]
            row["prefix_misses"] = st["misses"]
            row["prefix_bytes"] = st["bytes"]
        detail[arm] = row
        if arm == "prefix_on":
            headline = tok_s
        engine.shutdown()
    off, on = detail["chunk_only"], detail["prefix_on"]
    if off.get("ttft_p95_s") and on.get("ttft_p95_s"):
        detail["ttft_p95_speedup_prefix"] = round(
            off["ttft_p95_s"] / on["ttft_p95_s"], 2)
    un, ch = detail["unchunked"], detail["chunk_only"]
    if un.get("tick_prefill_p95_s") and ch.get("tick_prefill_p95_s"):
        detail["tick_prefill_p95_ratio_chunked"] = round(
            ch["tick_prefill_p95_s"] / un["tick_prefill_p95_s"], 3)
    print(json.dumps(detail), flush=True)
    return _result("serve_prefix", f"serve_prefix tokens/sec GPT2-124M "
                   f"{dtype} {n_requests}req shared-{prefix_len}tok-prefix "
                   f"chunk{chunk} prefix-cache", headline,
                   unit="tokens/sec", detail=detail)


def bench_serve_mem(n_requests=12, prefix_len=192, suffix_len=8,
                    max_new=16, n_slots=4, chunk=64):
    """Shared-prefix LIVE-BYTES A/B for the memory observatory
    (obs/memory.py): the same workload as ``serve_prefix`` —
    ``n_requests`` requests sharing one ``prefix_len``-token system
    prompt — but the measured quantity is MEMORY, not latency. Every
    number comes off the engine's ``MemoryLedger`` (byte-exact pytree
    ``nbytes`` sums), never re-derived from shape formulas.

    Two arms over the SAME requests:
      - ``prefix_off``: chunked prefill, prefix cache OFF — every slot
        recomputes AND stores its own copy of the shared prefix;
      - ``prefix_on``: prefix cache ON — the store holds ONE pane set,
        successors copy it into their slot instead of prefilling it.

    Reported per arm: slot-cache resident bytes (the fixed carve-out),
    per-tenant live-KV peak from the ledger's labeled series, and the
    summed ``kv_bytes_peak`` over request_done. The prefix arm adds
    ``prefix_bytes_saved`` (KV bytes NOT re-prefilled thanks to hits)
    and ``pane_copy_duplication_x`` — live KV at peak still holds up to
    ``n_slots`` COPIES of panes the store holds once, because the hit
    path copies panes into the slot carve-out. That duplication factor
    is the committed baseline a paged/shared-block KV design (ROADMAP
    item 1) must collapse toward 1x; the headline is total
    ``prefix_bytes_saved`` so the trajectory row records today's
    copy-based savings next to the duplication it leaves on the table.

    bf16 on TPU, fp32 elsewhere (same policy as ``bench_serve``)."""
    import tempfile

    from building_llm_from_scratch_tpu.configs import get_config
    from building_llm_from_scratch_tpu.generate import _bucket
    from building_llm_from_scratch_tpu.models import init_params
    from building_llm_from_scratch_tpu.serving import (
        DecodeEngine,
        KVCachePolicy,
        SamplingParams,
    )

    if _QUICK:
        n_requests, max_new = min(n_requests, 6), min(max_new, 8)
    dtype = "bf16" if jax.default_backend() == "tpu" else "fp32"
    cfg = get_config("GPT2", "124M", dtype=dtype)
    params = init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    prefix = rng.integers(0, cfg.vocab_size, (prefix_len,)).astype(np.int32)
    prompts = [np.concatenate([
        prefix, rng.integers(0, cfg.vocab_size,
                             (suffix_len,)).astype(np.int32)])
        for _ in range(n_requests)]
    sp = SamplingParams(max_new_tokens=max_new, ignore_eos=True)
    cap = prefix_len + suffix_len
    max_len = _bucket(cap + max_new)

    arms = {
        "prefix_off": KVCachePolicy(prefill_chunk=chunk),
        "prefix_on": KVCachePolicy(prefill_chunk=chunk, prefix_cache=True),
        # the ROADMAP-item-1 arm: page-table KV — prefix hits are TABLE
        # WRITES against refcounted shared pages, so the duplication the
        # prefix_on arm leaves on the table collapses to ~1x
        "paged": KVCachePolicy(prefill_chunk=chunk, prefix_cache=True,
                               paged=True, page_tokens=16),
    }
    detail = {}
    headline = None
    from building_llm_from_scratch_tpu.obs import configure_metrics

    jsonl_dir = tempfile.mkdtemp(prefix="bench_serve_mem_")
    for arm, policy in arms.items():
        # one telemetry file per arm (serve_load idiom): the
        # memory_snapshot stream stays attributable to THIS arm
        configure_metrics(os.path.join(jsonl_dir, f"{arm}.jsonl"),
                          run_metadata={"bench": "serve_mem", "arm": arm,
                                        "n_slots": n_slots,
                                        "n_requests": n_requests})
        # metrics_every=1: the ledger observes every tick, so the
        # labeled kv_live_bytes peak is tick-accurate, not cadence-lossy
        engine = DecodeEngine(cfg, params, n_slots=n_slots,
                              max_len=max_len, max_queue=n_requests,
                              warmup_prompt_cap=cap, kv_policy=policy,
                              metrics_every=1)
        engine.warmup()
        on_token = None
        if policy.paged:
            # physical prefix residency, sampled at every token commit:
            # the distinct PHYSICAL pages backing the shared prefix span
            # across all active slots. Contiguous arms hold one pane
            # COPY per sharer; shared refcounted pages keep this at the
            # store's own page count (duplication_x == 1.0)
            n_prefix_pages = prefix_len // policy.page_tokens
            peak_prefix_pages = [0]

            def on_token(_req, _tok, _txt):
                tab, cols = engine._page_table, engine._slot_cols
                pages = set()
                for s in range(n_slots):
                    if cols[s] >= n_prefix_pages:
                        pages.update(
                            int(p) for p in tab[s, :n_prefix_pages])
                pages.discard(0)
                if len(pages) > peak_prefix_pages[0]:
                    peak_prefix_pages[0] = len(pages)

        handles = [engine.submit(p, sp, block=True, on_token=on_token)
                   for p in prompts]
        engine.run_until_idle()
        for h in handles:
            assert len(h.output_ids) == max_new, h.finish_reason
        ledger = engine.memory_ledger
        snap = ledger.snapshot()
        gauges = ledger.gauges()
        live_peak = max(
            ledger.labeled_peaks.get("kv_live_bytes", {}).values(),
            default=0)
        row = {
            "slot_kv_bytes": (snap["page_pool"] if policy.paged
                              else snap["slot_kv"] + snap.get("kv_scales",
                                                              0)),
            "kv_live_peak_bytes": live_peak,
            "kv_bytes_peak_sum": sum(h.kv_bytes_peak for h in handles),
            "mem_total_bytes": gauges["mem_total_bytes"],
            "recompiles": engine.n_recompiles,
        }
        if engine.prefix_store is not None:
            st = engine.prefix_store.stats()
            saved = sum(h.prefix_bytes_saved for h in handles)
            row["prefix_store_bytes"] = (
                engine.prefix_store.bytes_total if policy.paged
                else snap["prefix_store"])
            row["prefix_hits"] = st["hits"]
            row["prefix_bytes_saved"] = saved
            if policy.paged:
                # shared pages make duplication PHYSICAL, so it is
                # measured physically: distinct pages backing the
                # prefix span at peak / the store's own page count
                pool = engine.page_pool.stats()
                row["page_pool_peak_bytes"] = (pool["peak_used"]
                                               * pool["page_bytes"])
                row["pane_copies"] = engine.pane_copies
                row["pane_copy_duplication_x"] = round(
                    peak_prefix_pages[0] / n_prefix_pages, 2)
            elif snap["prefix_store"]:
                # peak live KV / the single stored pane set: how many
                # resident COPIES of the shared prefix the slot
                # carve-out holds at peak (the paged-KV target is ~1)
                row["pane_copy_duplication_x"] = round(
                    live_peak / snap["prefix_store"], 2)
            if arm == "prefix_on":
                headline = float(saved)
        detail[arm] = row
        engine.shutdown()
        configure_metrics(None)              # close + detach the arm sink
    off, on = detail["prefix_off"], detail["prefix_on"]
    if off["kv_live_peak_bytes"]:
        detail["live_peak_ratio_prefix"] = round(
            on["kv_live_peak_bytes"] / off["kv_live_peak_bytes"], 3)
        # physical pool bytes at peak vs the contiguous arm's live KV:
        # the oversubscription headroom paged KV actually buys
        detail["physical_peak_ratio_paged"] = round(
            detail["paged"]["page_pool_peak_bytes"]
            / off["kv_live_peak_bytes"], 3)
    print(json.dumps(detail), flush=True)
    return _result("serve_mem", f"serve_mem prefix_bytes_saved GPT2-124M "
                   f"{dtype} {n_requests}req shared-{prefix_len}tok-prefix "
                   f"chunk{chunk} slots{n_slots}", headline,
                   unit="bytes", detail=detail)


def _spec_bench_model(ctx=128, train_steps=60, period=7, seed=0):
    """A tiny byte-ish model TRAINED briefly on a cyclic token stream —
    the honest 'repetitive/greedy workload' for the speculative-decoding
    A/B. An untrained model's greedy output is position-dependent noise
    (random learned positions), which no self-history drafter can
    predict; ~30 train steps on a short cycle make greedy decode
    actually CONTINUE the cycle, so the n-gram drafter earns its
    acceptance the same way it does on real templated/extractive
    traffic. Returns (cfg, trained_params, token_stream)."""
    from building_llm_from_scratch_tpu.configs import ModelConfig
    from building_llm_from_scratch_tpu.models import init_params
    from building_llm_from_scratch_tpu.training import (
        build_optimizer,
        init_train_state,
        make_train_step,
    )

    cfg = ModelConfig(name="spec-bench-tiny", vocab_size=96,
                      context_length=ctx, emb_dim=32, n_heads=2,
                      n_layers=2, hidden_dim=64, n_kv_groups=2,
                      norm="layernorm", positional="learned",
                      activation="gelu", drop_rate=0.0, eos_id=1)
    params = init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.default_rng(seed)
    cycle = rng.integers(2, cfg.vocab_size, (period,)).astype(np.int32)
    stream = np.tile(cycle, (4 * ctx) // period + 2)

    def batch(bs=4):
        starts = rng.integers(0, period, (bs,))
        rows = np.stack([stream[s: s + ctx + 1] for s in starts])
        return {"inputs": rows[:, :-1].astype(np.int32),
                "targets": rows[:, 1:].astype(np.int32),
                "weights": np.ones((bs, ctx), np.float32)}

    opt = build_optimizer(total_steps=train_steps + 2)
    state = init_train_state(params, opt, jax.random.PRNGKey(0))
    step = make_train_step(cfg, opt)
    for _ in range(train_steps):
        state, m = step(state, batch())
    jax.device_get(m["loss"])
    return cfg, state["trainable"], stream


def bench_serve_spec(n_requests=8, max_new=96, prompt_len=24, n_slots=4,
                     ks=(2, 4, 8)):
    """Speculative-decoding A/B (serving/spec.py + verify_slots): the
    SAME repetitive greedy request set decoded spec-off vs spec-on at
    k in ``ks`` — per arm: decode tok/s, TPOT p50/p95 (the per-token
    latency speculation exists to attack), acceptance rate, recompiles.

    The workload is what prompt-lookup drafting is FOR: a briefly
    trained tiny model whose greedy continuation repeats its context
    (templated prompts / extraction / code in miniature) — see
    ``_spec_bench_model``. Tokens are bit-identical across arms (the
    accept rule is exact; test-pinned in tests/test_spec.py), so every
    arm decodes the same work. Acceptance bar: >= 1.3x decode tok/s at
    k=4 with ZERO recompiles across acceptance churn.

    CPU numbers (tiny model, dispatch-bound ticks) UNDERSTATE the TPU
    win: there decode is weight-streaming-bound, so k+1 verify
    positions cost ~one decode step while committing up to k+1
    tokens."""
    import time

    from building_llm_from_scratch_tpu.generate import _bucket
    from building_llm_from_scratch_tpu.serving import (
        DecodeEngine,
        SamplingParams,
    )

    if _QUICK:
        n_requests, max_new = min(n_requests, 4), min(max_new, 16)
    t_train = time.perf_counter()
    # quick mode also trims the drafter-training iterations (acceptance
    # drops a little; the fingerprint-relevant shapes are unchanged)
    cfg, params, stream = _spec_bench_model(
        train_steps=20 if _QUICK else 60)
    train_s = time.perf_counter() - t_train
    prompts = [stream[s: s + prompt_len].astype(np.int32)
               for s in range(n_requests)]
    sp = SamplingParams(max_new_tokens=max_new, ignore_eos=True)

    def run_arm(spec_k):
        eng = DecodeEngine(cfg, params, n_slots=n_slots,
                           max_queue=n_requests,
                           max_len=_bucket(prompt_len + max_new),
                           warmup_prompt_cap=prompt_len, spec_k=spec_k)
        eng.warmup()
        t0 = time.perf_counter()
        handles = [eng.submit(p, sp, block=True) for p in prompts]
        eng.run_until_idle()
        dt = time.perf_counter() - t0
        for h in handles:
            assert len(h.output_ids) == max_new, h.finish_reason
        stats = eng.stats()
        # exact per-request TPOT (the engine histogram's sub-ms buckets
        # are too coarse to resolve a tiny model's per-token latency)
        tpots = [t for t in (h.tpot_s() for h in handles)
                 if t is not None]
        row = {
            "tok_s": round(n_requests * max_new / dt, 1),
            "ticks": stats["n_ticks"],
            "tpot_mean_ms": round(1e3 * float(np.mean(tpots)), 4),
            "recompiles": eng.n_recompiles,
        }
        if spec_k:
            row["acceptance"] = stats.get("spec_acceptance_ratio", 0.0)
            row["drafted"] = stats.get("spec_tokens_drafted", 0)
            row["accepted"] = stats.get("spec_tokens_accepted", 0)
        assert eng.n_recompiles == 0, "spec traffic recompiled"
        eng.shutdown()
        return row

    detail = {"train_seconds": round(train_s, 2),
              "spec_off": run_arm(0)}
    headline = None
    for k in ks:
        detail[f"spec_k{k}"] = run_arm(k)
        if k == 4:
            headline = detail["spec_k4"]["tok_s"]
    off = detail["spec_off"]
    if "spec_k4" in detail:
        on = detail["spec_k4"]
        detail["decode_tok_s_speedup_k4"] = round(
            on["tok_s"] / off["tok_s"], 2)
        if off.get("tpot_mean_ms") and on.get("tpot_mean_ms"):
            detail["tpot_speedup_k4"] = round(
                off["tpot_mean_ms"] / on["tpot_mean_ms"], 2)
    print(json.dumps(detail), flush=True)
    return _result("serve_spec", f"serve_spec tokens/sec spec-bench-tiny "
                   f"fp32 {n_requests}req x {max_new}new repetitive-greedy "
                   "slots4 k4", headline, unit="tokens/sec", detail=detail)


def _fleet_batches(cfg, k, rows, seed=0):
    """Per-job synthetic SFT batches (random tokens, Alpaca-style
    prompt-half loss mask) — the same rows feed both A/B arms."""
    rng = np.random.default_rng(seed)
    T = cfg.context_length
    out = []
    for _ in range(k):
        w = np.ones((rows, T), np.float32)
        w[:, : T // 2] = 0.0
        out.append({
            "inputs": rng.integers(0, cfg.vocab_size,
                                   (rows, T)).astype(np.int32),
            "targets": rng.integers(0, cfg.vocab_size,
                                    (rows, T)).astype(np.int32),
            "weights": w,
        })
    return out


def bench_lora_fusion(k=4, rows=2, rank=4, n_steps=12):
    """Fused multi-LoRA training A/B (training/lora_fusion.py): train the
    SAME k jobs (identical per-job batches, rank, hyperparameters)
    (a) the pre-fusion way — k sequential solo LoRA finetune runs, each
    its own merged-weights train step, its own XLA compile, its own
    dispatch stream — vs (b) ONE fused run whose step carries all k
    jobs' rows with per-row job_ids, gradients flowing only to the
    stacked adapter pool.

    Debug-size on CPU (the micro-bench convention), sized like real
    tenant jobs: small per-job batches, short horizons. The HEADLINE is
    aggregate adapter-training throughput for the WHOLE FLEET — fleet
    tokens / fleet wall, where each solo finetune is a fresh run and so
    pays its own compile (that is what 'k sequential solo finetunes'
    costs; the fused service compiles once, ever, and every later tenant
    hot-joins the same program). Also reported: steady-state tok/s per
    arm (compile excluded — on CPU this is compute-bound and near-even;
    the fused win there is the HLO FLOPs line, not wall), and the HLO
    cost-analysis FLOPs: fused FLOPs/step vs k x solo FLOPs/step — < 1.0
    because the frozen base never materializes dense weight gradients
    (the merged solo path pays the full dW as the merge chain's backward
    intermediate: ~6N vs ~4N per token)."""
    from building_llm_from_scratch_tpu.configs import get_config
    from building_llm_from_scratch_tpu.models import init_params
    from building_llm_from_scratch_tpu.models.lora import init_lora_params
    from building_llm_from_scratch_tpu.obs.compile import CompileWatcher
    from building_llm_from_scratch_tpu.training import (
        build_optimizer,
        init_train_state,
        make_train_step,
    )
    from building_llm_from_scratch_tpu.training.lora_fusion import (
        init_fleet_state,
        make_fused_train_step,
    )

    if _QUICK:
        n_steps = min(n_steps, 6)
    alpha = 2.0 * rank
    cfg = get_config("GPT2", "124M", dtype="fp32", debug=True)
    params = init_params(cfg, jax.random.PRNGKey(0))
    T = cfg.context_length
    batches = _fleet_batches(cfg, k, rows)
    fleet_tokens = k * rows * T * n_steps

    # -- arm A: k sequential solo finetunes (merged-lora step each) ------
    solo_steady_s, solo_total_s, solo_flops = 0.0, 0.0, None
    for j in range(k):
        t_run = time.perf_counter()
        opt = build_optimizer(total_steps=n_steps + 2)
        lora = init_lora_params(cfg, params, jax.random.PRNGKey(10 + j),
                                rank=rank)
        # the donated step consumes the state's buffers — every solo run
        # (and the fused arm after them) needs the base params alive
        state = init_train_state(
            lora, opt, jax.random.PRNGKey(j),
            frozen=jax.tree_util.tree_map(lambda x: x.copy(), params))
        step = CompileWatcher(
            make_train_step(cfg, opt, lora_rank=rank, lora_alpha=alpha),
            label="solo_step")
        state, m = step(state, batches[j])      # compile + warm
        float(jax.device_get(m["loss"]))
        t0 = time.perf_counter()
        for _ in range(n_steps):
            state, m = step(state, batches[j])
        float(jax.device_get(m["loss"]))
        solo_steady_s += time.perf_counter() - t0
        solo_total_s += time.perf_counter() - t_run
        if solo_flops is None:
            solo_flops = step.hlo_flops_per_step

    # -- arm B: one fused run, all k jobs per step -----------------------
    t_run = time.perf_counter()
    fstate = init_fleet_state(cfg, params, capacity=k,
                              rng=jax.random.PRNGKey(0), rank=rank)
    for j in range(k):
        lora = init_lora_params(cfg, params, jax.random.PRNGKey(10 + j),
                                rank=rank)
        fstate["trainable"] = jax.tree_util.tree_map(
            lambda pool, leaf, j=j: pool.at[j].set(leaf),
            fstate["trainable"], lora)
    from building_llm_from_scratch_tpu.training.lora_fusion import (
        stack_fleet_batch,
    )

    fbatch = stack_fleet_batch(batches, capacity=k, scaling=alpha / rank,
                               horizon=n_steps + 2)
    fstep = CompileWatcher(make_fused_train_step(cfg, capacity=k),
                           label="fused_step")
    fstate, fm = fstep(fstate, fbatch)          # compile + warm
    jax.device_get(fm["loss"])
    t0 = time.perf_counter()
    for _ in range(n_steps):
        fstate, fm = fstep(fstate, fbatch)
    jax.device_get(fm["loss"])
    fused_steady_s = time.perf_counter() - t0
    fused_total_s = time.perf_counter() - t_run
    fused_flops = fstep.hlo_flops_per_step

    detail = {
        "k": k, "rows_per_job": rows, "rank": rank, "n_steps": n_steps,
        "solo_sequential": {
            "fleet_tok_s": round(fleet_tokens / solo_total_s, 1),
            "steady_tok_s": round(fleet_tokens / solo_steady_s, 1),
            "fleet_wall_s": round(solo_total_s, 3),
            "flops_per_step": solo_flops,
        },
        "fused": {
            "fleet_tok_s": round(fleet_tokens / fused_total_s, 1),
            "steady_tok_s": round(fleet_tokens / fused_steady_s, 1),
            "fleet_wall_s": round(fused_total_s, 3),
            "flops_per_step": fused_flops,
            "recompiles": fstep.n_recompiles,
        },
        "agg_throughput_speedup": round(solo_total_s / fused_total_s, 2),
        "steady_state_speedup": round(solo_steady_s / fused_steady_s, 2),
    }
    if solo_flops and fused_flops:
        # fused step carries k jobs' tokens; k solo steps carry the same —
        # < 1.0 means the shared frozen base is cheaper fused than merged
        detail["fused_flops_vs_k_solo_steps"] = round(
            fused_flops / (k * solo_flops), 3)
        detail["per_token_flops_ratio"] = round(
            (fused_flops / (k * rows * T)) / (solo_flops / (rows * T)), 3)
    print(json.dumps(detail), flush=True)
    return _result("lora_fusion", f"fused multi-LoRA agg adapter-train "
                   f"tokens/sec (fleet wall) GPT2-debug fp32 k{k} x "
                   f"{rows}rows rank{rank}",
                   fleet_tokens / fused_total_s, unit="tokens/sec",
                   detail=detail)


# ---------------------------------------------------------------------------
# Micro-benches: the CI perf-gate workloads (scripts/perf_gate.py)
# ---------------------------------------------------------------------------

def bench_micro_train():
    """Debug-size GPT2 raw train step (ctx 16, emb 32, 2 layers): seconds
    on CPU, so the structural perf gate can run it on every CI pass. The
    tok/s number is meaningless as throughput — what matters is the
    fingerprint: the step's HLO FLOPs, program count and HBM breakdown
    must match PERF_BASELINE.json exactly."""
    from building_llm_from_scratch_tpu.configs import get_config

    cfg = get_config("GPT2", "124M", dtype="fp32", debug=True)
    tps = _pretrain_tps(cfg, batch_size=4, warmup=1, iters=4)
    return _result("micro_train", "tokens/sec GPT2-debug pretrain fp32 "
                   "bs4 ctx16", tps, unit="tokens/sec")


def bench_micro_accum():
    """Debug-size grad-accum step (2 scanned microbatches): a second,
    structurally DIFFERENT program for the gate — accumulation bugs that
    change the compiled graph (a dropped scan, a dtype drift in the
    accumulator) show up as a FLOP/memory diff here."""
    from building_llm_from_scratch_tpu.configs import get_config

    cfg = get_config("GPT2", "124M", dtype="fp32", debug=True)
    tps = _pretrain_tps(cfg, batch_size=8, warmup=1, iters=4, grad_accum=2)
    return _result("micro_accum", "tokens/sec GPT2-debug pretrain fp32 "
                   "bs8 grad_accum2 ctx16", tps, unit="tokens/sec")


def bench_micro_serve():
    """Debug-size continuous-batching engine (2 slots, 6 requests): the
    gate workload for the serving tier — its fingerprint covers the
    engine's whole compiled-program family (bucketed prefill + decode),
    so a bucket-set change, an extra program, or a warmup recompile
    fails the structural gate with the program named."""
    from building_llm_from_scratch_tpu.configs import get_config
    from building_llm_from_scratch_tpu.models import init_params
    from building_llm_from_scratch_tpu.serving import (
        DecodeEngine,
        SamplingParams,
    )

    n_requests, max_new, prompt_len = 6, 4, 4
    cfg = get_config("GPT2", "124M", dtype="fp32", debug=True)
    params = init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    prompts = rng.integers(0, cfg.vocab_size,
                           (n_requests, prompt_len)).astype(np.int32)
    sp = SamplingParams(max_new_tokens=max_new, ignore_eos=True)
    engine = DecodeEngine(cfg, params, n_slots=2, max_queue=n_requests,
                          warmup_prompt_cap=prompt_len, metrics_every=2)
    engine.warmup()
    t0 = time.perf_counter()
    handles = [engine.submit(p, sp, block=True) for p in prompts]
    engine.run_until_idle()
    dt = time.perf_counter() - t0
    for h in handles:
        assert len(h.output_ids) == max_new, h.finish_reason
    detail = {"recompiles": engine.n_recompiles}
    engine.shutdown()
    return _result("micro_serve", "serve tokens/sec GPT2-debug fp32 "
                   f"{n_requests}req x {max_new}new slots2",
                   n_requests * max_new / dt, unit="tokens/sec",
                   detail=detail)


def bench_micro_paged():
    """Debug-size paged-KV engine (2 slots, 6 shared-prefix requests):
    the gate workload for the page-table serving tier — its fingerprint
    covers the paged compiled-program family (paged chunk prefill +
    paged decode), so page-identity leaking into shapes (a table-churn
    recompile), an extra program, or FLOP growth in the gather path
    fails the structural gate with the program named."""
    from building_llm_from_scratch_tpu.configs import get_config
    from building_llm_from_scratch_tpu.models import init_params
    from building_llm_from_scratch_tpu.serving import (
        DecodeEngine,
        KVCachePolicy,
        SamplingParams,
    )

    n_requests, max_new = 6, 4
    cfg = get_config("GPT2", "124M", dtype="fp32", debug=True)
    params = init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    prefix = rng.integers(0, cfg.vocab_size, (8,)).astype(np.int32)
    prompts = [np.concatenate([prefix, rng.integers(
        0, cfg.vocab_size, (1,)).astype(np.int32)])
        for _ in range(n_requests)]
    sp = SamplingParams(max_new_tokens=max_new, ignore_eos=True)
    policy = KVCachePolicy(paged=True, page_tokens=8, prefill_chunk=8,
                           prefix_cache=True)
    engine = DecodeEngine(cfg, params, n_slots=2, max_queue=n_requests,
                          warmup_prompt_cap=9, kv_policy=policy,
                          metrics_every=2)
    engine.warmup()
    t0 = time.perf_counter()
    handles = [engine.submit(p, sp, block=True) for p in prompts]
    engine.run_until_idle()
    dt = time.perf_counter() - t0
    for h in handles:
        assert len(h.output_ids) == max_new, h.finish_reason
    assert engine.pane_copies == 0, "paged hit copied panes"
    detail = {"recompiles": engine.n_recompiles,
              "prefix_hits": engine.prefix_store.stats()["hits"],
              "page_pool": engine.page_pool.stats()}
    engine.shutdown()
    return _result("micro_paged", "paged serve tokens/sec GPT2-debug "
                   f"fp32 {n_requests}req x {max_new}new slots2 page8",
                   n_requests * max_new / dt, unit="tokens/sec",
                   detail=detail)


def bench_micro_lora_fusion():
    """Debug-size fused multi-LoRA train step (2 jobs x 2 rows, rank 4):
    the gate workload for the fused-finetune tier. Its fingerprint pins
    the fused step's HLO — a lost gather (adapters silently merged), a
    dense base-weight gradient sneaking into the backward, or a
    per-job-identity recompile all show up as FLOP/program diffs with
    the program named."""
    from building_llm_from_scratch_tpu.configs import get_config
    from building_llm_from_scratch_tpu.models import init_params
    from building_llm_from_scratch_tpu.obs.compile import CompileWatcher
    from building_llm_from_scratch_tpu.training.lora_fusion import (
        init_fleet_state,
        make_fused_train_step,
        stack_fleet_batch,
    )

    k, rows, rank = 2, 2, 4
    cfg = get_config("GPT2", "124M", dtype="fp32", debug=True)
    params = init_params(cfg, jax.random.PRNGKey(0))
    T = cfg.context_length
    batches = _fleet_batches(cfg, k, rows)
    state = init_fleet_state(cfg, params, capacity=k, rank=rank,
                             rng=jax.random.PRNGKey(0))
    batch = stack_fleet_batch(batches, capacity=k, scaling=2.0, horizon=8)
    step = CompileWatcher(make_fused_train_step(cfg, capacity=k),
                          label="fused_step")
    warmup, iters = _q_iters(1, 4)
    for _ in range(max(1, warmup)):
        state, m = step(state, batch)
    jax.device_get(m["loss"])
    t0 = time.perf_counter()
    for _ in range(iters):
        state, m = step(state, batch)
    jax.device_get(m["loss"])
    dt = time.perf_counter() - t0
    return _result("micro_lora_fusion", "fused multi-LoRA tokens/sec "
                   f"GPT2-debug fp32 k{k} x {rows}rows rank{rank} ctx16",
                   k * rows * T * iters / dt, unit="tokens/sec",
                   detail={"recompiles": step.n_recompiles})


def bench_micro_spec():
    """Debug-size speculative serving engine (2 slots, 6 requests,
    k=4): the gate workload for the spec tier — its fingerprint pins
    the Tq=k+1 verify program's HLO next to the bucketed prefill, so a
    verify-graph change (a lost candidate position, an accidental extra
    program, a warmup recompile, acceptance leaking into a compile
    signature) fails the structural gate with the program named. The
    model is untrained (acceptance ~0 — irrelevant: structure only)."""
    from building_llm_from_scratch_tpu.configs import get_config
    from building_llm_from_scratch_tpu.models import init_params
    from building_llm_from_scratch_tpu.serving import (
        DecodeEngine,
        SamplingParams,
    )

    n_requests, max_new, prompt_len = 6, 4, 4
    cfg = get_config("GPT2", "124M", dtype="fp32", debug=True)
    params = init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    prompts = rng.integers(0, cfg.vocab_size,
                           (n_requests, prompt_len)).astype(np.int32)
    sp = SamplingParams(max_new_tokens=max_new, ignore_eos=True)
    engine = DecodeEngine(cfg, params, n_slots=2, max_queue=n_requests,
                          warmup_prompt_cap=prompt_len, metrics_every=2,
                          spec_k=4)
    engine.warmup()
    t0 = time.perf_counter()
    handles = [engine.submit(p, sp, block=True) for p in prompts]
    engine.run_until_idle()
    dt = time.perf_counter() - t0
    for h in handles:
        assert len(h.output_ids) == max_new, h.finish_reason
    detail = {"recompiles": engine.n_recompiles,
              "acceptance": engine.stats().get("spec_acceptance_ratio",
                                               0.0)}
    engine.shutdown()
    return _result("micro_spec", "serve tokens/sec GPT2-debug fp32 "
                   f"{n_requests}req x {max_new}new slots2 spec-k4",
                   n_requests * max_new / dt, unit="tokens/sec",
                   detail=detail)


def bench_micro_longctx(sp=2, warmup=1, iters=4):
    """Debug-size sequence-sharded train step (longctx-32k architecture
    shrunk, sp=2 over the seq mesh axis): the gate workload for the
    long-context tier — its fingerprint pins the ring-attention step's
    HLO (the ppermute ring schedule, the online-softmax rescale chain,
    the seq-sharded batch signature) so a ring-graph change, a dropped
    collective, or a signature-churn recompile fails the structural
    gate with the program named. Needs a multi-device host: the gate
    (scripts/perf_gate.py) forces 8 CPU devices before importing jax."""
    from building_llm_from_scratch_tpu.configs import get_config
    from building_llm_from_scratch_tpu.models import init_params
    from building_llm_from_scratch_tpu.obs.compile import CompileWatcher
    from building_llm_from_scratch_tpu.parallel import build_mesh_plan
    from building_llm_from_scratch_tpu.training import (
        build_optimizer,
        init_train_state,
        make_train_step,
    )

    if jax.device_count() < 2:
        raise RuntimeError(
            "micro_longctx needs >= 2 devices for the seq mesh axis; "
            "run under XLA_FLAGS=--xla_force_host_platform_device_count=8 "
            "(scripts/perf_gate.py sets this itself).")
    cfg = get_config("longctx", "32k", dtype="fp32", debug=True)
    batch_size = 4                      # divides the data axis (8/sp)
    plan = build_mesh_plan("dp", sp=sp)
    opt = build_optimizer(total_steps=warmup + iters + 1)
    state = plan.shard_state(init_train_state(
        init_params(cfg, jax.random.PRNGKey(0)), opt, jax.random.PRNGKey(0)))
    batch = plan.shard_batch(_batch(cfg, batch_size))
    step = CompileWatcher(make_train_step(cfg, opt, sp_mesh=plan.sp_mesh),
                          label="longctx_step")
    warmup, iters = _q_iters(warmup, iters)
    dt = _time_steps(step, state, batch, warmup, iters)
    assert step.n_recompiles == 0, step.n_recompiles
    return _result("micro_longctx", "tokens/sec longctx-debug pretrain "
                   f"fp32 bs{batch_size} ctx{cfg.context_length} sp{sp}",
                   batch_size * cfg.context_length * iters / dt,
                   unit="tokens/sec",
                   detail={"sp": sp, "mesh": dict(plan.mesh.shape)})


def _longctx_worker(arm: str, extra_args, timeout=1800) -> dict:
    """Run one scripts/bench_longctx_worker.py arm (subprocess: the arm
    needs a forced multi-device host set before jax imports; the parent
    bench process's device count is pinned by the perf-gate baselines)."""
    import subprocess

    repo = os.path.dirname(os.path.abspath(__file__))
    worker = os.path.join(repo, "scripts", "bench_longctx_worker.py")
    env = dict(os.environ, JAX_PLATFORMS=os.environ.get("JAX_PLATFORMS",
                                                        "cpu"))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    cmd = [sys.executable, worker, "--arm", arm] + \
        [str(a) for a in extra_args]
    proc = subprocess.run(cmd, capture_output=True, text=True,
                          timeout=timeout, env=env)
    if proc.returncode != 0:
        raise RuntimeError(f"longctx worker ({arm}) failed rc="
                           f"{proc.returncode}:\n{proc.stderr[-2000:]}")
    return json.loads(proc.stdout.strip().splitlines()[-1])


def bench_pretrain_longctx(ctx=1024, sp=4, steps=3, batch=4):
    """Long-context pretrain A/B (ROADMAP item 2): the SAME batches
    through an unsharded reference step and a sequence-sharded one
    (dp x sp mesh, ring attention). Asserts the loss trajectories agree
    to rtol 2e-4 — NOT bit-identical, and deliberately so: the ring's
    online softmax reduces KV panes in ring order while the dense
    reference reduces the full row at once, a floating-point
    REASSOCIATION of the same sum (the pinned tolerance matches
    tests/test_ring_attention.py's parity suite) — and that neither arm
    recompiles after step 1. On CPU the sp arm is SLOWER (host
    collectives, no real interconnect): the headline is the sp arm's
    tok/s with the ref's riding as a metric; the sp>=ref throughput
    assertion is TPU-gated."""
    steps = max(2, min(steps, 2) if _QUICK else steps)
    row = _longctx_worker("train", ["--sp", sp, "--ctx", ctx,
                                    "--steps", steps, "--batch", batch])
    rel = max(abs(a - b) / max(abs(b), 1e-9)
              for a, b in zip(row["losses_sp"], row["losses_ref"]))
    assert rel <= 2e-4, (rel, row)
    assert row["recompiles_ref"] == 0, row
    assert row["recompiles_sp"] == 0, row
    if jax.default_backend() == "tpu":
        # on a real pod the seq shards must buy throughput, not just fit
        assert row["tok_s_sp"] >= row["tok_s_ref"], row
    print(json.dumps(row), flush=True)
    res = _result("pretrain_longctx",
                  f"tokens/sec longctx pretrain fp32 bs{batch} "
                  f"ctx{row['ctx']} sp{sp} vs unsharded ref",
                  row["tok_s_sp"], unit="tokens/sec", detail=row)
    res.add_metric("tok_s_ref", row["tok_s_ref"], "tokens/sec")
    res.add_metric("loss_parity_max_rel", round(rel, 9), "fraction")
    res.add_metric("recompiles_sp", row["recompiles_sp"], "count")
    return res


def bench_serve_longctx(sp=2, max_len=512, n_long=4, n_short=8,
                        max_new=16):
    """Seq-sharded prefill under mixed traffic: one sp=2 engine serving
    interleaved long prompts (384 tokens — beyond one device's 256-token
    pane, the admission the long-context tier exists for) and short
    ones. Asserts zero post-warmup recompiles (the sharding constraint
    is static — long prompts reuse the same chunk program) and reports
    the long-vs-short TTFT split next to aggregate tok/s."""
    if _QUICK:
        n_long, n_short, max_new = 2, 4, 8
    row = _longctx_worker("serve", ["--sp", sp, "--max_len", max_len,
                                    "--n_long", n_long,
                                    "--n_short", n_short,
                                    "--max_new", max_new])
    assert row["recompiles"] == 0, row
    assert row["n_long"] == n_long and row["n_short"] == n_short, row
    print(json.dumps(row), flush=True)
    res = _result("serve_longctx",
                  f"serve tokens/sec GPT2-124M sp{sp} mixed traffic "
                  f"{n_long}long+{n_short}short maxlen{max_len}",
                  row["tok_s"], unit="tokens/sec", detail=row)
    res.add_metric("ttft_long_p50", row["ttft_long_p50"], "seconds")
    res.add_metric("ttft_short_p50", row["ttft_short_p50"], "seconds")
    res.add_metric("max_prompt", row["max_prompt"], "tokens")
    return res


BENCHES = {
    "headline": bench_headline,
    "cfg1": bench_cfg1,
    "cfg2": bench_cfg2,
    "cfg3": bench_cfg3,
    "cfg4": bench_cfg4,
    "cfg5": bench_cfg5,
    "accum": bench_accum,
    "trainer": bench_trainer,
    "prefetch": bench_prefetch,
    "decode": bench_decode,
    "serve": bench_serve,
    "serve_load": bench_serve_load,
    "serve_fleet": bench_serve_fleet,
    "serve_lora": bench_serve_lora,
    "serve_prefix": bench_serve_prefix,
    "serve_mem": bench_serve_mem,
    "serve_spec": bench_serve_spec,
    "lora_fusion": bench_lora_fusion,
    "micro_train": bench_micro_train,
    "micro_accum": bench_micro_accum,
    "micro_serve": bench_micro_serve,
    "micro_paged": bench_micro_paged,
    "micro_lora_fusion": bench_micro_lora_fusion,
    "micro_spec": bench_micro_spec,
    "micro_router": bench_micro_router,
    "micro_longctx": bench_micro_longctx,
    "pretrain_longctx": bench_pretrain_longctx,
    "serve_longctx": bench_serve_longctx,
}

#: Micro-benches excluded from ``all`` (they are gate workloads, not
#: performance claims — their tok/s on a debug model means nothing).
#: micro_longctx additionally needs a multi-device host (the gate
#: forces one; plain ``bench.py all`` runs may not have it).
MICRO_BENCHES = ("micro_train", "micro_accum", "micro_serve",
                 "micro_paged", "micro_lora_fusion", "micro_spec",
                 "micro_router", "micro_longctx")


def _reset_compilation_cache() -> None:
    """Drop jax's memoized use-the-persistent-cache decision so a
    ``jax_compilation_cache_dir`` flip mid-process actually takes effect
    (the decision is cached per process on first compile)."""
    try:
        from jax._src import compilation_cache as _jcc
        _jcc.reset_cache()
    except Exception:            # private API: degrade to cache-as-is
        pass


def run_bench(name: str, repeats: int = 1, quick: bool = False
              ) -> perf.BenchResult:
    """Run one bench ``repeats`` times; returns the final repeat's
    BenchResult carrying repeat stats over the headline values, the env
    block, and the structural fingerprint (obs/perf.py). The programmatic
    entry the perf gate uses — ``run()`` is the printing CLI wrapper."""
    global _QUICK
    prev_quick, _QUICK = _QUICK, bool(quick)
    fn = BENCHES[name]
    # fingerprints must come from COLD XLA compiles: a persistent-
    # compilation-cache hit deserializes the executable WITHOUT its
    # alias (donation) sizes, which would corrupt the memory breakdown
    # the structural gate pins (and make repeat 2's fingerprint drift
    # from repeat 1's). A cache may be ambiently configured (the
    # --compile_cache_dir resume path, or JAX_COMPILATION_CACHE_DIR) —
    # benches opt out for their duration.
    prev_cache = getattr(jax.config, "jax_compilation_cache_dir", None)
    if prev_cache:
        jax.config.update("jax_compilation_cache_dir", None)
        _reset_compilation_cache()   # drop the memoized use-cache bit
    try:
        values, results, digests = [], [], []
        for _ in range(max(1, int(repeats))):
            with perf.FingerprintCollector() as col:
                res = fn()
            if not isinstance(res, perf.BenchResult):
                raise TypeError(f"bench '{name}' must return a BenchResult,"
                                f" got {type(res).__name__}")
            res.fingerprint = col.fingerprint()
            digests.append(perf.fingerprint_digest(res.fingerprint))
            values.append(res.value)
            results.append(res)
    finally:
        _QUICK = prev_quick
        if prev_cache:
            jax.config.update("jax_compilation_cache_dir", prev_cache)
            _reset_compilation_cache()   # re-arm lazily for later compiles
    final = results[-1]
    final.repeats = perf.repeat_stats(values)
    # a fingerprint that drifts BETWEEN repeats of the same bench is a
    # nondeterministic compile (data-dependent shapes, a cache-warmup
    # recompile) — exactly what the gate exists to catch, so record it
    final.fingerprint["stable_across_repeats"] = len(set(digests)) == 1
    final.env = perf.bench_env()
    final.quick = bool(quick)
    final.time = time.time()
    rec = RECORDED.get(name)
    final.vs_baseline = round(final.value / rec, 3) if rec else None
    perf.emit_bench_result(final)
    return final


def _legacy_line(res: perf.BenchResult) -> dict:
    """The one-JSON-line stdout format the BENCH_r0N driver snapshots
    parse: metric/value/unit/vs_baseline (+mfu and the HLO efficiency
    fields when the capture produced them)."""
    line = {
        "metric": res.metric,
        "value": round(res.value, 1),
        "unit": res.unit,
        "vs_baseline": res.vs_baseline if res.vs_baseline is not None
        else 1.0,
    }
    mfu = res.metric_value("mfu")
    if mfu is not None:
        line["mfu"] = round(mfu, 3)
    fp = res.fingerprint or {}
    # the chronologically LAST bench_step capture is the executable the
    # timed loop actually ran (after any mid-run recompile); the sorted
    # programs list is the deterministic fallback
    last = fp.get("last_program")
    step_progs = [p for p in ([last] if last else [])
                  + list(fp.get("programs", ()))
                  if p["label"] == "bench_step" and p.get("flops")]
    if step_progs:
        from building_llm_from_scratch_tpu.obs.mfu import mfu_from_flops

        prog = step_progs[0]
        line["hlo_flops_per_step"] = prog["flops"]
        compile_s = (res.fingerprint.get("timing") or {}).get(
            "compile_seconds_total")
        if compile_s is not None:
            line["compile_seconds"] = round(compile_s, 2)
        if prog.get("tokens_per_step"):
            # per-chip tps against the same fallback peak _mfu uses, but
            # with XLA's counted FLOPs — the delta vs "mfu" is formula
            # drift
            mfu_hlo = mfu_from_flops(
                res.value, prog["flops"] / prog["tokens_per_step"],
                n_devices=1, peak=_device_specs()[0])
            if mfu_hlo is not None:
                line["mfu_hlo"] = round(mfu_hlo, 3)
    if res.repeats and res.repeats.get("n", 1) > 1:
        line["repeats"] = {k: res.repeats[k]
                           for k in ("n", "min", "median", "stddev")}
    return line


def run(name: str, repeats: int = 1, quick: bool = False,
        json_out=None) -> perf.BenchResult:
    res = run_bench(name, repeats=repeats, quick=quick)
    print(json.dumps(_legacy_line(res)), flush=True)
    if json_out is not None:
        json_out.write(json.dumps(res.to_row(), sort_keys=True) + "\n")
        json_out.flush()
    return res


def _open_json_out(path: str, name: str):
    """``--json`` sink: a directory gets the trajectory layout (one
    ``<name>.jsonl`` per bench, appended — the results/perf convention);
    a file path gets every row plus one run-metadata header. A
    not-yet-existing extensionless path (``--json results/perf``) is
    treated as a directory — writing a FILE named like the intended
    trajectory dir would break every later store open against it."""
    if (os.path.isdir(path) or path.endswith(os.sep)
            or "." not in os.path.basename(path)):
        store = perf.TrajectoryStore(path.rstrip(os.sep))
        os.makedirs(store.root, exist_ok=True)
        return open(store.path(name), "a")
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    f = open(path, "a")
    if f.tell() == 0:
        f.write(json.dumps(perf.header_row(), sort_keys=True) + "\n")
    return f


def main(argv):
    from building_llm_from_scratch_tpu.utils.seeding import (
        configure_default_prng,
    )

    p = argparse.ArgumentParser(
        description="bench runner (see module docstring)")
    p.add_argument("which", nargs="?", default="headline",
                   help="bench name from BENCHES, or 'all'")
    p.add_argument("--repeats", type=int, default=1, metavar="K",
                   help="repeat each bench K times; rows carry "
                        "min/median/stddev stats")
    p.add_argument("--json", default=None, metavar="OUT",
                   help="append BenchResult JSONL rows to OUT (a "
                        "*.json/*.jsonl file gets rows + one header; "
                        "anything else is a directory and gets the "
                        "results/perf one-file-per-bench trajectory "
                        "layout)")
    p.add_argument("--quick", action="store_true",
                   help="shrink iteration counts (CI gate mode; shapes — "
                        "and so fingerprints — are unchanged)")
    args = p.parse_args(argv[1:])

    configure_default_prng()   # rbg PRNG: dropout at full speed (seeding.py)
    # run-metadata header FIRST (jax version, backend, device kind/count,
    # git sha, argv): the BENCH_*.json driver snapshots capture stdout, so
    # every archived bench line is self-describing about where it ran
    print(json.dumps(perf.header_row(), sort_keys=True), flush=True)
    names = list(BENCHES) if args.which == "all" else [args.which]
    if args.which == "all":
        names = [n for n in names if n not in MICRO_BENCHES]
    for name in names:
        if name not in BENCHES:
            p.error(f"unknown bench '{name}' "
                    f"(choose from {', '.join(BENCHES)})")
        json_out = (_open_json_out(args.json, name)
                    if args.json else None)
        try:
            run(name, repeats=args.repeats, quick=args.quick,
                json_out=json_out)
        finally:
            if json_out is not None:
                json_out.close()


if __name__ == "__main__":
    main(sys.argv)
