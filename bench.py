"""Benchmark: tokens/sec/chip for GPT2-124M causal-LM pretraining.

BASELINE.json config #1 ("GPT2-124M single-device pretrain on Gutenberg,
fp32, no LoRA/ckpt"). The reference publishes NO numbers (BASELINE.md), so
``vs_baseline`` is measured against the first recorded figure for this repo
(BASELINE.md "measured" table); 1.0 means parity with that record.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import time

import jax
import numpy as np

# First recorded tokens/sec/chip for this config on TPU v5e-1 (BASELINE.md).
RECORDED_BASELINE = None  # set after the first measured run


def bench_gpt2_pretrain(batch_size: int = 4, warmup: int = 3,
                        iters: int = 20) -> float:
    # batch 4 == the reference's default (args.py:53); fp32 + no remat at
    # batch 8 exceeds one v5e chip's 16GB HBM
    from building_llm_from_scratch_tpu.configs import get_config
    from building_llm_from_scratch_tpu.models import init_params
    from building_llm_from_scratch_tpu.training import (
        build_optimizer,
        init_train_state,
        make_train_step,
    )

    cfg = get_config("GPT2", "124M", dtype="fp32")
    params = init_params(cfg, jax.random.PRNGKey(0))
    opt = build_optimizer(total_steps=warmup + iters + 1)
    state = init_train_state(params, opt, jax.random.PRNGKey(0))
    step = make_train_step(cfg, opt)

    rng = np.random.default_rng(0)
    T = cfg.context_length
    batch = {
        "inputs": rng.integers(0, cfg.vocab_size, (batch_size, T)).astype(
            np.int32),
        "targets": rng.integers(0, cfg.vocab_size, (batch_size, T)).astype(
            np.int32),
        "weights": np.ones((batch_size, T), np.float32),
    }

    # NOTE: on the axon remote backend jax.block_until_ready() returns at
    # dispatch time — only a literal device_get round-trips to the chip, so
    # all timing syncs use float()/device_get.
    for _ in range(max(1, warmup)):
        state, metrics = step(state, batch)
    float(metrics["loss"])

    t0 = time.perf_counter()
    for _ in range(iters):
        state, metrics = step(state, batch)
    float(metrics["loss"])
    dt = time.perf_counter() - t0

    tokens_per_step = batch_size * T
    n_chips = jax.device_count()
    return tokens_per_step * iters / dt / n_chips


def main():
    tps = bench_gpt2_pretrain()
    vs = tps / RECORDED_BASELINE if RECORDED_BASELINE else 1.0
    print(json.dumps({
        "metric": "tokens/sec/chip GPT2-124M pretrain fp32 bs4 ctx1024",
        "value": round(tps, 1),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(vs, 3),
    }))


if __name__ == "__main__":
    main()
