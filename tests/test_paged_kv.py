"""Paged KV-cache tests (serving/kvcache.PagePool + the page-table
engine path): page-pool allocator units (alloc/free/refcount, admission
reservations, exhaustion), policy validation, engine-vs-generate() token
BIT-parity with paging ON across greedy/sampled/spec/adapter/int8
traffic, copy-free prefix sharing (pane_copies spy == 0, shared pages
refcounted and released on retire/restart), oversubscription admission
(free PAGES gate, FCFS-preserving bounce, permanent refusal of
can-never-fit requests), byte-exact ledger reconcile over the pool,
zero recompiles throughout, paged telemetry events against the schema,
and interpret-mode parity for the pallas page-gather attention kernel.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from building_llm_from_scratch_tpu.configs import ModelConfig
from building_llm_from_scratch_tpu.generate import generate
from building_llm_from_scratch_tpu.models import init_params
from building_llm_from_scratch_tpu.serving import (
    DecodeEngine,
    KVCachePolicy,
    SamplingParams,
)
from building_llm_from_scratch_tpu.serving.kvcache import (
    DEFAULT_POLICY,
    PagePool,
    cache_nbytes,
)

PAGED = KVCachePolicy(paged=True, page_tokens=8, prefill_chunk=16,
                      prefix_cache=True)


def tiny_cfg(ctx=64, **kw):
    base = dict(name="paged-tiny", vocab_size=96, context_length=ctx,
                emb_dim=32, n_heads=2, n_layers=2, hidden_dim=64,
                n_kv_groups=2, norm="layernorm", positional="learned",
                activation="gelu", drop_rate=0.0, eos_id=1)
    base.update(kw)
    return ModelConfig(**base)


@pytest.fixture(scope="module")
def model():
    cfg = tiny_cfg()
    return cfg, init_params(cfg, jax.random.PRNGKey(0))


def solo_tokens(params, cfg, prompt, sp: SamplingParams):
    out, n = generate(params, cfg, np.asarray(prompt)[None],
                      max_new_tokens=sp.max_new_tokens,
                      temperature=sp.temperature, top_k=sp.top_k,
                      eos_id=(None if sp.ignore_eos
                              else (sp.eos_id if sp.eos_id is not None
                                    else cfg.eos_id)),
                      rng=jax.random.PRNGKey(sp.seed),
                      return_n_generated=True)
    Tp = len(prompt)
    return [int(t) for t in out[0, Tp: Tp + int(n[0])]]


def shared_prefix_prompts(cfg, n, prefix_len=32, seed=0):
    rng = np.random.default_rng(seed)
    prefix = rng.integers(2, cfg.vocab_size, (prefix_len,)).astype(np.int32)
    return [np.concatenate([prefix, rng.integers(
        2, cfg.vocab_size, (2 + i % 3,)).astype(np.int32)])
        for i in range(n)]


# ---------------------------------------------------------------------------
# PagePool allocator units
# ---------------------------------------------------------------------------

def test_page_pool_alloc_free_refcount():
    pool = PagePool(6, 128)                    # trash + 5 usable
    assert pool.n_free == 5 and pool.available() == 5
    assert pool.refcount(0) == 1               # trash page: pinned

    a = pool.alloc()
    b = pool.alloc()
    assert (a, b) == (1, 2)                    # lowest-id-first: dense ids
    assert pool.refcount(a) == 1

    pool.incref(a)                             # a prefix-store sharer
    assert pool.refcount(a) == 2
    assert pool.decref(a) is False             # still one owner left
    assert pool.decref(a) is True              # last owner: back to free
    assert pool.n_free == 4
    with pytest.raises(RuntimeError, match="double free"):
        pool.decref(a)
    with pytest.raises(RuntimeError, match="use-after-free"):
        pool.incref(a)

    # trash page is never refcounted into the free list
    assert pool.decref(0) is False
    with pytest.raises(RuntimeError):
        pool.incref(0)

    # freed ids are reused lowest-first (byte-reproducible sequences)
    assert pool.alloc() == 1
    pool.decref(b)

    st = pool.stats()
    assert st["n_pages"] == 5 and st["page_bytes"] == 128
    assert st["allocs"] == 3 and st["frees"] == 2
    assert st["used"] == 1 and st["free"] == 4
    assert st["peak_used"] == 2


def test_page_pool_reservations_and_exhaustion():
    pool = PagePool(4, 64)                     # 3 usable
    pool.reserve(2)
    assert pool.available() == 1               # free minus promised
    p = pool.alloc(from_reserved=True)         # draws the reservation down
    assert pool.stats()["reserved"] == 1
    assert pool.available() == 1
    pool.unreserve(1)
    assert pool.available() == 2

    q = pool.alloc()
    r = pool.alloc()
    assert pool.n_free == 0
    with pytest.raises(RuntimeError, match="exhausted"):
        pool.alloc()                           # admission gate was bypassed
    for page in (p, q, r):
        pool.decref(page)
    assert pool.n_free == 3


def test_paged_policy_validation():
    with pytest.raises(ValueError, match="chunked prefill"):
        KVCachePolicy(paged=True)              # pages need a chunk frontier
    with pytest.raises(ValueError, match="multiple"):
        KVCachePolicy(paged=True, prefill_chunk=12, page_tokens=8)
    with pytest.raises(ValueError):
        KVCachePolicy(paged=True, prefill_chunk=16, page_tokens=0)
    # contiguous layout stays the pinned default
    assert DEFAULT_POLICY.paged is False
    assert KVCachePolicy().paged is False


# ---------------------------------------------------------------------------
# engine parity + copy-free sharing
# ---------------------------------------------------------------------------

def test_paged_engine_parity_and_copy_free_sharing(model):
    """Greedy + sampled traffic over a shared prefix: tokens bit-equal
    to one-shot generate(), hits are TABLE WRITES (the contiguous pane
    copy spy stays 0), the ledger reconciles byte-exact over the pool,
    and nothing recompiles."""
    cfg, params = model
    eng = DecodeEngine(cfg, params, n_slots=2, max_len=48,
                       warmup_prompt_cap=48, kv_policy=PAGED)
    eng.warmup()
    base = eng.n_recompiles
    prompts = shared_prefix_prompts(cfg, 4)
    plans = [SamplingParams(max_new_tokens=4, ignore_eos=True, seed=7),
             SamplingParams(max_new_tokens=4, temperature=0.8, top_k=20,
                            ignore_eos=True, seed=11),
             SamplingParams(max_new_tokens=3, ignore_eos=True, seed=13),
             SamplingParams(max_new_tokens=4, temperature=1.1, top_k=8,
                            ignore_eos=True, seed=17)]
    handles = [eng.submit(p, sp) for p, sp in zip(prompts, plans)]
    eng.run_until_idle()
    for h, p, sp in zip(handles, prompts, plans):
        assert h.done and h.output_ids == solo_tokens(params, cfg, p, sp)

    st = eng.stats()
    assert eng.n_recompiles == base == 0
    assert st["pane_copies"] == 0              # zero-copy hits: table only
    assert st["prefix_store"]["hits"] >= 1
    pool = st["page_pool"]
    assert pool["frees"] > 0                   # retired slots recycle pages
    # after idle the only retained pages are the store's shared prefix
    # (32 tokens / 8 per page = 4) — capacity is tokens in flight
    assert pool["used"] == 4 and pool["reserved"] == 0

    # ledger: the pool component reconciles byte-exact (expected from
    # the allocator's own arithmetic == measured device bytes)
    eng.memory_ledger.observe(eng.n_ticks)
    desc = eng.memory_ledger.describe()
    assert desc["components"]["page_pool"] == cache_nbytes(eng.cache)
    assert desc["components"]["page_pool"] == (
        eng.page_pool.n_pages * eng.page_pool.page_bytes)
    assert desc["n_drift_events"] == 0         # expected == measured, exact


def test_paged_spec_decode_parity(model):
    """Speculative decoding over paged KV: verify-tick page growth covers
    the k-token window and accepted tokens stay bit-identical."""
    cfg, params = model
    eng = DecodeEngine(cfg, params, n_slots=2, max_len=48,
                       warmup_prompt_cap=48, kv_policy=PAGED, spec_k=3)
    eng.warmup()
    prompts = shared_prefix_prompts(cfg, 3, prefix_len=16, seed=3)
    sp = SamplingParams(max_new_tokens=6, ignore_eos=True, seed=23)
    handles = [eng.submit(p, sp) for p in prompts]
    eng.run_until_idle()
    for h, p in zip(handles, prompts):
        assert h.done and h.output_ids == solo_tokens(params, cfg, p, sp)
    assert eng.n_recompiles == 0
    assert eng.stats()["pane_copies"] == 0


def test_paged_int8_sidecar(model):
    """int8 KV pages carry their fp32 scale sidecar page-for-page: the
    quantized paged engine matches the quantized CONTIGUOUS engine
    bit-for-bit (same quantization points, different layout)."""
    cfg, params = model
    pol8 = KVCachePolicy(paged=True, page_tokens=8, prefill_chunk=16,
                         prefix_cache=True, kv_quant="int8")
    eng = DecodeEngine(cfg, params, n_slots=2, max_len=48,
                       warmup_prompt_cap=48, kv_policy=pol8)
    eng.warmup()
    ref = DecodeEngine(cfg, params, n_slots=2, max_len=48,
                       warmup_prompt_cap=48,
                       kv_policy=KVCachePolicy(kv_quant="int8"))
    ref.warmup()
    prompts = shared_prefix_prompts(cfg, 3, prefix_len=16, seed=5)
    sp = SamplingParams(max_new_tokens=4, ignore_eos=True, seed=31)
    hs = [eng.submit(p, sp) for p in prompts]
    eng.run_until_idle()
    rs = [ref.submit(p, sp) for p in prompts]
    ref.run_until_idle()
    for h, r in zip(hs, rs):
        assert h.done and h.output_ids == r.output_ids
    assert eng.n_recompiles == 0
    # page_bytes includes the sidecar: K+V int8 + two fp32 scale columns
    per_page = eng.kv_policy.page_bytes(cfg)
    assert per_page == eng.page_pool.page_bytes
    k_bytes = cfg.n_kv_groups * 8 * cfg.head_dim      # int8 = 1 B/elt
    s_bytes = cfg.n_kv_groups * 8 * 1 * 4             # fp32 scales
    assert per_page == cfg.n_layers * 2 * (k_bytes + s_bytes)


def test_paged_adapter_parity(model, tmp_path):
    """Mixed base/LoRA traffic over paged KV: every request bit-matches
    generate() on its own merged weights, co-resident, zero recompiles."""
    from building_llm_from_scratch_tpu.models.lora import (
        init_lora_params,
        merge_lora,
        save_adapter,
    )
    from building_llm_from_scratch_tpu.serving import AdapterRegistry

    cfg, params = model
    specs, merged = {}, {}
    for i, (name, rank, alpha) in enumerate([("a", 4, 8.0), ("b", 2, 3.0)]):
        lora = init_lora_params(cfg, params, jax.random.PRNGKey(40 + i),
                                rank=rank)
        lora = jax.tree_util.tree_map(
            lambda x: x + 0.05 * jax.random.normal(
                jax.random.PRNGKey(50 + i), x.shape, x.dtype), lora)
        path = str(tmp_path / f"{name}.npz")
        save_adapter(path, lora, rank=rank, alpha=alpha, cfg=cfg)
        specs[name] = path
        merged[name] = merge_lora(params, lora, alpha=alpha, rank=rank)
    registry = AdapterRegistry.from_artifacts(cfg, params, specs,
                                              capacity=4)
    eng = DecodeEngine(cfg, params, n_slots=2, max_len=48,
                       warmup_prompt_cap=48, kv_policy=PAGED,
                       adapters=registry)
    eng.warmup()
    prompts = shared_prefix_prompts(cfg, 4, prefix_len=16, seed=9)
    sp = SamplingParams(max_new_tokens=4, ignore_eos=True, seed=43)
    names = [None, "a", "b", "a"]
    handles = [eng.submit(p, SamplingParams(max_new_tokens=4,
                                            ignore_eos=True, seed=43,
                                            adapter=name))
               for p, name in zip(prompts, names)]
    eng.run_until_idle()
    for h, p, name in zip(handles, prompts, names):
        ref = params if name is None else merged[name]
        assert h.done and h.output_ids == solo_tokens(ref, cfg, p, sp)
    assert eng.n_recompiles == 0


# ---------------------------------------------------------------------------
# oversubscription: admission gates on FREE PAGES
# ---------------------------------------------------------------------------

def test_pool_oversubscription_admits_by_pages_fcfs(model):
    """A pool sized for ~one request at a time: free SLOTS exceed free
    pages, so admission bounces the queue head (and everything behind
    it, order intact) until a retirement frees pages — every request
    still completes with exact tokens."""
    cfg, params = model
    # worst case per request: ceil((16 prompt + 4 new)/8) = 3 pages;
    # 4 usable pages admit one request (+1 slack), never two
    pol = KVCachePolicy(paged=True, page_tokens=8, prefill_chunk=16,
                        pool_pages=4)
    eng = DecodeEngine(cfg, params, n_slots=2, max_len=32,
                       warmup_prompt_cap=32, kv_policy=pol)
    eng.warmup()
    rng = np.random.default_rng(12)
    prompts = [rng.integers(2, cfg.vocab_size, (16,)).astype(np.int32)
               for _ in range(3)]
    sp = SamplingParams(max_new_tokens=4, ignore_eos=True, seed=51)
    first_token_order = []

    def on_tok(req, _tok, _txt):
        if len(req.output_ids) == 1:
            first_token_order.append(req.id)

    handles = [eng.submit(p, sp, on_token=on_tok) for p in prompts]
    eng.run_until_idle()
    # FCFS preserved through bounces: each request starts decoding in
    # submission order (the bounced head goes back to the FRONT)
    assert first_token_order == [h.id for h in handles]
    for h, p in zip(handles, prompts):
        assert h.done and h.finish_reason == "length"
        assert h.output_ids == solo_tokens(params, cfg, p, sp)
    st = eng.stats()["page_pool"]
    assert st["peak_used"] <= 4                # never oversubscribed the pool
    assert st["used"] == 0 and st["reserved"] == 0
    assert eng.n_recompiles == 0


def test_pool_request_that_can_never_fit_fails_fast(model):
    """A request whose worst-case page need exceeds the WHOLE pool must
    fail at admission (bouncing it would livelock the queue head)."""
    cfg, params = model
    pol = KVCachePolicy(paged=True, page_tokens=8, prefill_chunk=16,
                        pool_pages=2)          # 16 tokens of pool, total
    eng = DecodeEngine(cfg, params, n_slots=2, max_len=32,
                       warmup_prompt_cap=16, kv_policy=pol)
    eng.warmup()
    big = np.arange(2, 18, dtype=np.int32)     # 16 prompt + 4 new > 2 pages
    h = eng.submit(big, SamplingParams(max_new_tokens=4, ignore_eos=True))
    small = np.arange(2, 10, dtype=np.int32)   # 8 + 2 -> 2 pages: fits
    h2 = eng.submit(small, SamplingParams(max_new_tokens=2,
                                          ignore_eos=True, seed=3))
    eng.run_until_idle()
    assert h.done and h.finish_reason == "error"
    assert "pages" in h.error
    # the queue behind the refused request keeps flowing
    assert h2.done and h2.finish_reason == "length"
    assert eng.stats()["page_pool"]["used"] == 0


# ---------------------------------------------------------------------------
# shared-page release: retire / cancel / restart
# ---------------------------------------------------------------------------

def test_shared_pages_release_on_cancel_and_restart(model):
    cfg, params = model
    eng = DecodeEngine(cfg, params, n_slots=2, max_len=48,
                       warmup_prompt_cap=48, kv_policy=PAGED)
    eng.warmup()
    prompts = shared_prefix_prompts(cfg, 2)
    sp = SamplingParams(max_new_tokens=4, ignore_eos=True, seed=61)
    hs = [eng.submit(p, sp) for p in prompts]
    eng.run_until_idle()
    assert all(h.done for h in hs)
    store_pages = eng.stats()["page_pool"]["used"]
    assert store_pages == 4                    # 32-token prefix / 8

    # cancel-while-queued: the request never touches the pool (no
    # background ticker — a submitted request stays QUEUED until
    # run_until_idle steps the engine)
    h_c = eng.submit(prompts[0], sp)
    assert eng.cancel(h_c) is True
    eng.run_until_idle()
    assert h_c.finish_reason == "cancelled"
    assert eng.stats()["page_pool"]["used"] == store_pages

    # restart: fresh pool + cleared store (stale tables must not leak
    # into the rebuilt cache), then traffic still bit-matches
    assert eng._restart(reason="test", detail="paged restart drill")
    st = eng.stats()["page_pool"]
    assert st["used"] == 0 and st["allocs"] == 0 and st["reserved"] == 0
    h = eng.submit(prompts[0], sp)
    eng.run_until_idle()
    assert h.output_ids == solo_tokens(params, cfg, prompts[0], sp)
    eng.memory_ledger.observe(eng.n_ticks)
    assert eng.memory_ledger.describe()["n_drift_events"] == 0


# ---------------------------------------------------------------------------
# telemetry: page events land in the JSONL and validate
# ---------------------------------------------------------------------------

def test_paged_telemetry_events_schema(model, tmp_path):
    from building_llm_from_scratch_tpu.obs.metrics import configure_metrics
    from building_llm_from_scratch_tpu.obs.schema import validate_event

    cfg, params = model
    mj = str(tmp_path / "paged_metrics.jsonl")
    sink = configure_metrics(mj)
    sink.write_header(test="paged_kv")
    try:
        eng = DecodeEngine(cfg, params, n_slots=2, max_len=48,
                           warmup_prompt_cap=48, kv_policy=PAGED)
        eng.warmup()
        sp = SamplingParams(max_new_tokens=2, ignore_eos=True)
        for p in shared_prefix_prompts(cfg, 3):
            eng.submit(p, sp)
            eng.run_until_idle()
        prom = eng.prometheus_text()
    finally:
        sink.close()
        configure_metrics(None)
    rows = [json.loads(line) for line in open(mj)]
    by_kind = {}
    for r in rows:
        if r.get("type") == "event":
            by_kind.setdefault(r["event"], []).append(r)
    assert by_kind.get("page_admit") and by_kind.get("page_release")
    assert by_kind.get("page_share")           # requests 2..3 shared pages
    for kind in ("page_admit", "page_share", "page_release"):
        for e in by_kind[kind]:
            fields = {k: v for k, v in e.items()
                      if k not in ("type", "time", "event", "step")}
            assert validate_event(kind, fields) == [], (kind, e)
    warm = by_kind["serve_warmup"][-1]
    assert warm["kv_paged"] is True and warm["page_tokens"] == 8
    assert warm["pool_pages"] == eng.page_pool.n_pages - 1
    assert "bllm_serve_kv_pages_total" in prom
    assert "bllm_serve_kv_pages_used" in prom


# ---------------------------------------------------------------------------
# pallas paged-attention kernel: interpret-mode parity on CPU
# ---------------------------------------------------------------------------

def test_paged_attention_interpret_parity():
    from building_llm_from_scratch_tpu.ops.attention import decode_attention
    from building_llm_from_scratch_tpu.ops.decode_step import (
        paged_decode_attention,
        supports_paged_shape,
    )

    S, Hq, Hkv, hd, P, N, M = 3, 4, 2, 64, 8, 9, 4
    assert supports_paged_shape(1, P, hd)
    assert not supports_paged_shape(2, P, hd)      # prefill: XLA path
    assert not supports_paged_shape(1, P - 2, hd)  # unaligned page
    assert not supports_paged_shape(1, P, 80)      # unaligned head dim

    ks = jax.random.split(jax.random.PRNGKey(2), 3)
    q = jax.random.normal(ks[0], (S, 1, Hq, hd))
    k_pool = jax.random.normal(ks[1], (N, Hkv, P, hd))
    v_pool = jax.random.normal(ks[2], (N, Hkv, P, hd))
    # rows at different lengths, sharing physical page 1 (prefix hit),
    # with tail table entries parked on the trash page 0
    table = jnp.asarray([[1, 2, 0, 0],
                         [1, 3, 4, 0],
                         [5, 0, 0, 0]], jnp.int32)
    lens = jnp.asarray([12, 20, 5], jnp.int32)     # new token's position

    out = paged_decode_attention(q, k_pool, v_pool, table, lens,
                                 interpret=True)
    assert out.shape == (S, 1, Hq, hd)

    # reference: materialize each row contiguously, then the stock
    # decode_attention rule (attends kv_pos <= q_position)
    K = k_pool[table].transpose(0, 2, 1, 3, 4).reshape(S, Hkv, M * P, hd)
    V = v_pool[table].transpose(0, 2, 1, 3, 4).reshape(S, Hkv, M * P, hd)
    ref = decode_attention(q, K, V, q_positions=lens[:, None],
                           kv_length=lens + 1)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
