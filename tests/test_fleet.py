"""Scale-out serving tests: the tp-sharded engine (NamedSharding'd
weights + heads-sharded slot KV over the ``model`` mesh axis, tokens
bit-identical to the unsharded engine on the forced-host 8-device CPU
backend, zero recompiles) and the fleet router (adapter-affinity +
prefix-affinity dispatch, deadline-aware spill, drain-one-replica with
queued-work re-dispatch and zero request loss, per-replica labeled
``/metrics``, one closed span tree per routed request with the router
hop as a child span, replica-count-invariant gate fingerprint)."""

import json
import os
import tempfile

import jax
import numpy as np
import pytest

from building_llm_from_scratch_tpu.configs import ModelConfig
from building_llm_from_scratch_tpu.models import init_params
from building_llm_from_scratch_tpu.obs import configure_metrics
from building_llm_from_scratch_tpu.parallel.sharding import (
    partition_serve_devices,
    serve_mesh_plan,
)
from building_llm_from_scratch_tpu.serving import (
    DecodeEngine,
    EngineRouter,
    SamplingParams,
)


def tiny_cfg(ctx=64, **kw):
    base = dict(name="fleet-tiny", vocab_size=96, context_length=ctx,
                emb_dim=32, n_heads=2, n_layers=2, hidden_dim=64,
                n_kv_groups=2, norm="layernorm", positional="learned",
                activation="gelu", drop_rate=0.0, eos_id=1)
    base.update(kw)
    return ModelConfig(**base)


@pytest.fixture(scope="module")
def model():
    cfg = tiny_cfg()
    return cfg, init_params(cfg, jax.random.PRNGKey(0))


@pytest.fixture
def sink(tmp_path):
    path = tmp_path / "metrics.jsonl"
    logger = configure_metrics(str(path), run_metadata={"test": True})
    yield str(path)
    logger.close()
    configure_metrics(None)


def load_rows(path):
    return [json.loads(line) for line in open(path)]


def mixed_requests(n, seed=0, max_new=6):
    """Greedy + seeded-sampling mix, varied prompts — the parity diet."""
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        prompt = rng.integers(2, 96, (4 + i % 3,)).astype(np.int32)
        sp = SamplingParams(max_new_tokens=max_new, ignore_eos=True,
                            seed=i, temperature=0.0 if i % 2 else 0.9,
                            top_k=None if i % 2 else 8)
        out.append((prompt, sp))
    return out


def run_engine(engine, reqs):
    handles = [engine.submit(p, sp, block=True) for p, sp in reqs]
    engine.run_until_idle()
    toks = [list(h.result(timeout=120).output_ids) for h in handles]
    engine.shutdown()
    return toks


# ---------------------------------------------------------------------------
# parallel layer units
# ---------------------------------------------------------------------------

def test_cache_spec_rules():
    plan = serve_mesh_plan(tp=2)
    # k/v panes (S, Hkv, T, hd): heads axis on `model` when divisible
    assert tuple(plan.cache_spec((4, 2, 32, 16))) == (None, "model",
                                                      None, None)
    # int8 scale sidecars (S, Hkv, T, 1): same rule (heads axis)
    assert tuple(plan.cache_spec((4, 2, 32, 1))) == (None, "model",
                                                     None, None)
    # indivisible heads replicate; non-4d leaves replicate
    assert tuple(plan.cache_spec((4, 3, 32, 16))) == ()
    assert tuple(plan.cache_spec((4, 32))) == ()
    # tp=1 plans never shard the cache
    assert tuple(serve_mesh_plan(tp=1).cache_spec((4, 2, 32, 16))) == ()


def test_partition_serve_devices():
    devs = jax.devices()
    assert len(devs) == 8        # conftest forces the 8-device backend
    slices = partition_serve_devices(4, tp=2)
    assert [len(s) for s in slices] == [2, 2, 2, 2]
    assert len({d for s in slices for d in s}) == 8     # disjoint
    # oversubscribed: overlapping slices, still tp devices each
    slices = partition_serve_devices(8, tp=2)
    assert all(len(s) == 2 for s in slices)
    with pytest.raises(ValueError):
        partition_serve_devices(1, tp=16)


# ---------------------------------------------------------------------------
# tp-sharded engine
# ---------------------------------------------------------------------------

def test_tp_engine_tokens_bit_identical_zero_recompiles(model):
    """The tentpole invariant: a tp=2-sharded engine (Megatron param
    rules + heads-sharded slot KV over the forced-host 8-device mesh)
    commits the BIT-identical token stream of the unsharded engine over
    mixed greedy+sampled traffic, with zero recompiles under the frozen
    watchers."""
    cfg, params = model
    reqs = mixed_requests(6)
    ref = run_engine(DecodeEngine(cfg, params, n_slots=4, max_len=32,
                                  warmup_prompt_cap=16), reqs)
    plan = serve_mesh_plan(tp=2)
    eng = DecodeEngine(cfg, params, n_slots=4, max_len=32,
                       warmup_prompt_cap=16, mesh_plan=plan)
    eng.warmup()                 # compiles + freezes the watchers
    # the cache really is sharded on the heads axis of the model mesh
    k0 = eng.cache["k"][0]
    assert k0.sharding.spec == plan.cache_spec(tuple(k0.shape))
    tp_toks = run_engine(eng, reqs)
    assert tp_toks == ref
    assert eng.n_recompiles == 0


def test_tp_engine_with_adapters_parity(model):
    """tp x multi-tenant LoRA: the stacked adapter pool is re-placed on
    the replica mesh (replicated), and adapter/base mixed traffic is
    bit-identical to the unsharded registry engine."""
    from building_llm_from_scratch_tpu.models.lora import (
        init_lora_params,
        save_adapter,
    )
    from building_llm_from_scratch_tpu.serving import AdapterRegistry

    cfg, params = model
    d = tempfile.mkdtemp()
    path = os.path.join(d, "a.npz")
    lora = init_lora_params(cfg, params, jax.random.PRNGKey(3), rank=4)
    save_adapter(path, lora, rank=4, alpha=8.0, cfg=cfg)

    def reqs():
        out = []
        for i in range(4):
            sp = SamplingParams(max_new_tokens=5, ignore_eos=True,
                                seed=i, adapter="a" if i % 2 else None)
            out.append((np.arange(3 + i, dtype=np.int32) + 2, sp))
        return out

    ref_reg = AdapterRegistry.from_artifacts(cfg, params, {"a": path})
    ref = run_engine(DecodeEngine(cfg, params, n_slots=2, max_len=32,
                                  warmup_prompt_cap=16,
                                  adapters=ref_reg), reqs())
    tp_reg = AdapterRegistry.from_artifacts(cfg, params, {"a": path})
    eng = DecodeEngine(cfg, params, n_slots=2, max_len=32,
                       warmup_prompt_cap=16, adapters=tp_reg,
                       mesh_plan=serve_mesh_plan(tp=2))
    eng.warmup()
    assert run_engine(eng, reqs()) == ref
    assert eng.n_recompiles == 0


# ---------------------------------------------------------------------------
# router: dispatch, affinity, spans, metrics
# ---------------------------------------------------------------------------

def make_adapters(cfg, params, names, tmp):
    from building_llm_from_scratch_tpu.models.lora import (
        init_lora_params,
        save_adapter,
    )

    paths = {}
    for i, name in enumerate(names):
        lora = init_lora_params(cfg, params, jax.random.PRNGKey(10 + i),
                                rank=4)
        p = os.path.join(str(tmp), f"{name}.npz")
        save_adapter(p, lora, rank=4, alpha=8.0, cfg=cfg)
        paths[name] = p
    return paths


def test_router_affinity_spans_and_metrics(model, sink, tmp_path):
    """Mixed-tenant traffic through a 2-replica router: adapter traffic
    lands on the resident replica (affinity ratio > 0), every request
    closes exactly ONE span tree with the router hop as a child +
    replica attribution, /metrics re-exports per-replica labeled series
    (histograms included) next to fleet gauges, and the whole run costs
    zero recompiles."""
    cfg, params = model
    paths = make_adapters(cfg, params, ("ta", "tb"), tmp_path)
    router = EngineRouter.build(cfg, params, n_replicas=2,
                                adapter_specs=paths, n_slots=2,
                                max_len=32, warmup_prompt_cap=16,
                                metrics_every=2)
    router.warmup()
    # round-robin placement: one adapter per replica
    residency = {name: [i for i, e in enumerate(router.engines)
                        if e.adapters.lookup(name) is not None]
                 for name in paths}
    assert sorted(len(v) for v in residency.values()) == [1, 1]
    rng = np.random.default_rng(0)
    handles = []
    for i in range(9):
        sp = SamplingParams(max_new_tokens=4, ignore_eos=True, seed=i,
                            adapter=[None, "ta", "tb"][i % 3])
        handles.append(router.submit(
            rng.integers(2, 96, (4,)).astype(np.int32), sp, block=True))
    router.run_until_idle()
    for h in handles:
        h.result(timeout=120)
        if h.params.adapter is not None:
            # adapter-affinity measurably routed: the request ran on the
            # replica holding its adapter row
            assert h.route["replica"] in residency[h.params.adapter]
            assert h.route["affinity"] == "adapter"
    stats = router.stats()
    assert stats["routed_by_affinity_ratio"] > 0
    assert stats["requests_finished"] == 9
    assert router.n_recompiles == 0

    rows = load_rows(sink)
    spans = [r for r in rows if r.get("type") == "span"]
    done = [r for r in rows if r.get("event") == "request_done"]
    assert len(spans) == len(done) == 9
    ids = [s["request_id"] for s in spans]
    assert len(set(ids)) == 9           # exactly one closed tree per id
    for s in spans:
        kids = [c["name"] for c in s["children"]]
        assert kids[0] == "router"      # the router hop child span
        assert "replica" in s
        t0, t1 = s["t0"], s["t0"] + s["dur_s"]
        for c in s["children"]:
            assert c["t0"] >= t0 - 1e-6
            assert c["t0"] + c["dur_s"] <= t1 + 1e-6
    for r in done:
        assert r.get("replica") in (0, 1)

    text = router.prometheus_text()
    assert 'bllm_serve_requests_finished_total{replica="0"}' in text
    assert 'bllm_serve_requests_finished_total{replica="1"}' in text
    assert 'bllm_serve_ttft_seconds_bucket{replica="0",le=' in text
    assert "bllm_serve_replicas_up 2" in text
    assert "bllm_serve_routed_by_affinity_ratio" in text
    # adapter + replica labels merge into one label set
    assert 'adapter="ta",replica=' in text
    payload = router.healthz_payload()
    assert payload["status"] == "serving"
    assert payload["replicas_total"] == 2
    assert len(payload["replicas"]) == 2
    router.shutdown()


def test_router_hot_load_on_miss(model, sink, tmp_path):
    """Fleet-wide residency miss: the router hot-loads the tenant's
    artifact onto a live replica and serves — no client-visible 400."""
    cfg, params = model
    paths = make_adapters(cfg, params, ("tc",), tmp_path)
    router = EngineRouter.build(cfg, params, n_replicas=2, n_slots=2,
                                max_len=32, warmup_prompt_cap=16,
                                adapter_specs={}, metrics_every=0)
    # registries exist but are empty; the router knows the path
    router._adapter_paths.update(paths)
    router.warmup()
    h = router.submit(np.array([2, 3, 4], np.int32),
                      SamplingParams(max_new_tokens=4, ignore_eos=True,
                                     adapter="tc"))
    router.run_until_idle()
    h.result(timeout=120)
    assert router.hot_loads == 1
    assert h.route["affinity"] == "adapter"
    # unknown adapter with no path still rejects like a single engine
    with pytest.raises(ValueError):
        router.submit(np.array([2], np.int32),
                      SamplingParams(adapter="nope"))
    router.shutdown()


def test_router_drain_replica_loses_nothing(model, sink):
    """Drain ONE replica under live traffic: its queued work re-dispatches
    onto the survivor (same Request handles), in-flight work finishes,
    every submitted request completes, zero recompiles anywhere."""
    cfg, params = model
    router = EngineRouter.build(cfg, params, n_replicas=2, n_slots=1,
                                max_len=48, warmup_prompt_cap=16,
                                max_queue=16, metrics_every=0)
    router.warmup()
    rng = np.random.default_rng(1)
    # submit BEFORE starting the loops: both replicas' queues fill
    # deterministically, so the drain below must actually re-dispatch
    handles = [router.submit(rng.integers(2, 96, (4,)).astype(np.int32),
                             SamplingParams(max_new_tokens=16,
                                            ignore_eos=True, seed=i),
                             block=True)
               for i in range(8)]
    stolen = len(router.engines[0].queue)
    assert stolen > 0
    router.drain_replica(0, timeout=120)
    assert router.redispatched == stolen      # every queued request moved
    router.start()
    for h in handles:
        h.result(timeout=300)           # raises if anything was dropped
    assert all(len(h.output_ids) == 16 for h in handles)
    stats = router.stats()
    assert stats["requests_finished"] == 8
    assert router.n_recompiles == 0
    rows = load_rows(sink)
    drains = [r for r in rows if r.get("event") == "replica_drain"]
    assert {d["phase"] for d in drains} == {"start", "end"}
    redis = [r for r in rows if r.get("event") == "router_redispatch"]
    end = [d for d in drains if d["phase"] == "end"][0]
    assert end["n_redispatched"] == len(redis)
    assert len(redis) == stolen
    for r in redis:
        assert r["from_replica"] == 0 and r["to_replica"] == 1
    # the drained replica is out of dispatch; traffic still flows
    h = router.submit(np.array([5, 6], np.int32),
                      SamplingParams(max_new_tokens=3, ignore_eos=True))
    h.result(timeout=120)
    assert h.route["replica"] == 1
    router.shutdown()


def test_drain_keeps_tenant_work_on_resident_replica(model, tmp_path):
    """A drain must NOT re-dispatch tenant work onto a replica that
    doesn't hold (and can't load) the adapter — adopt() bypasses
    submit-time validation, so it would fail at admission. The queued
    requests stay with the draining replica, which finishes them."""
    from building_llm_from_scratch_tpu.serving import AdapterRegistry

    cfg, params = model
    paths = make_adapters(cfg, params, ("ta",), tmp_path)
    regs = [AdapterRegistry.from_artifacts(cfg, params, paths),
            AdapterRegistry(cfg, params, capacity=2)]
    engines = [DecodeEngine(cfg, params, n_slots=1, max_len=32,
                            warmup_prompt_cap=16, adapters=regs[i],
                            replica=i)
               for i in range(2)]
    for eng in engines:
        eng.warmup()
    router = EngineRouter(engines)      # no artifact paths known
    handles = [router.submit(np.array([2, 3], np.int32),
                             SamplingParams(max_new_tokens=4,
                                            ignore_eos=True,
                                            adapter="ta", seed=i),
                             block=True)
               for i in range(3)]
    assert len(router.engines[0].queue) == 3    # manual mode: all queued
    router.drain_replica(0, timeout=120)        # drain ticks them done
    for h in handles:
        h.result(timeout=120)                   # nothing dropped/failed
    assert router.redispatched == 0
    router.shutdown()


def test_router_deadline_aware_dispatch(model):
    """Deadline-aware dispatch: with replica 0 backlogged (its live
    TPOT/queue EWMAs predict a miss), a deadline request routes to the
    idle replica; when EVERY replica predicts a miss the router sheds
    fleet-wide with a Retry-After."""
    from building_llm_from_scratch_tpu.serving import SLOShedError

    cfg, params = model
    router = EngineRouter.build(cfg, params, n_replicas=2, n_slots=1,
                                max_len=48, warmup_prompt_cap=16,
                                max_queue=32, metrics_every=0,
                                prefix_affinity=False)
    router.warmup()
    # seed both replicas' service EWMAs with one finished request each
    for eng in router.engines:
        eng.submit(np.array([2, 3], np.int32),
                   SamplingParams(max_new_tokens=4, ignore_eos=True))
        eng.run_until_idle()
    # backlog replica 0 directly (bypassing the router)
    backlog = [router.engines[0].submit(
        np.array([2, 3], np.int32),
        SamplingParams(max_new_tokens=16, ignore_eos=True))
        for _ in range(6)]
    snap = router.engines[0].service_snapshot()
    est0 = router._estimate(snap, 8)
    assert est0 is not None and est0 > 0
    deadline = max(est0 / 4, 0.05)      # replica 0 predicts a miss
    h = router.submit(np.array([4, 5], np.int32),
                      SamplingParams(max_new_tokens=8, ignore_eos=True,
                                     deadline_s=60.0))
    assert h.route["replica"] == 1      # routed around the backlog
    # now blow every replica's budget: fleet-wide shed
    backlog += [router.engines[1].submit(
        np.array([2, 3], np.int32),
        SamplingParams(max_new_tokens=16, ignore_eos=True))
        for _ in range(6)]
    with pytest.raises(SLOShedError):
        router.submit(np.array([4, 5], np.int32),
                      SamplingParams(max_new_tokens=8, ignore_eos=True,
                                     deadline_s=deadline / 1000))
    router.run_until_idle()
    for h2 in backlog:
        h2.result(timeout=300)
    router.shutdown()


def test_router_prefix_affinity(model):
    """Shared-prefix traffic lands on ONE replica (stable hash), so its
    PrefixStore accumulates hits instead of every replica going cold."""
    from building_llm_from_scratch_tpu.serving import KVCachePolicy

    cfg, params = model
    policy = KVCachePolicy(prefix_cache=True, prefill_chunk=8)
    router = EngineRouter.build(cfg, params, n_replicas=2, n_slots=2,
                                max_len=48, warmup_prompt_cap=16,
                                kv_policy=policy, metrics_every=0)
    router.warmup()
    system = np.arange(8, dtype=np.int32) + 2       # shared 8-tok prefix
    handles = []
    for i in range(6):
        prompt = np.concatenate([system,
                                 np.array([20 + i], np.int32)])
        handles.append(router.submit(
            prompt, SamplingParams(max_new_tokens=3, ignore_eos=True,
                                   seed=i)))
    router.run_until_idle()
    replicas = set()
    for h in handles:
        h.result(timeout=120)
        assert h.route["affinity"] == "prefix"
        replicas.add(h.route["replica"])
    assert len(replicas) == 1           # all on one replica
    hit_store = router.engines[replicas.pop()].prefix_store
    assert hit_store.n_hits >= 5        # co-located traffic actually hit
    router.shutdown()


# ---------------------------------------------------------------------------
# CLI wiring (run_serve / make_http_server single-engine assumption fix)
# ---------------------------------------------------------------------------

def _serve_cli(tmp_path, extra, n=6):
    from building_llm_from_scratch_tpu.args import get_args
    from building_llm_from_scratch_tpu.main import main

    d = str(tmp_path)
    reqs = os.path.join(d, "requests.jsonl")
    with open(reqs, "w") as f:
        for i in range(n):
            f.write(json.dumps({"prompt": "abcd"[: 1 + i % 4],
                                "max_new_tokens": 3, "ignore_eos": True,
                                "seed": i}) + "\n")
    out = os.path.join(d, "results.jsonl")
    mj = os.path.join(d, "metrics.jsonl")
    engine = main(get_args([
        "--mode", "serve", "--debug", "--byte_tokenizer",
        "--data_dir", d, "--serve_prompts", reqs, "--serve_out", out,
        "--serve_slots", "2", "--serve_max_queue", str(max(n, 8)),
        "--metrics_jsonl", mj] + extra))
    return engine, [json.loads(line) for line in open(out)], \
        [json.loads(line) for line in open(mj)]


def test_cli_single_replica_path_pinned(tmp_path):
    """--serve_replicas 1 (the default) is the historical path: a plain
    DecodeEngine, NO router object, no replica fields in the telemetry,
    no `router` span child — byte-identical single-engine behavior."""
    engine, results, rows = _serve_cli(tmp_path, [])
    assert isinstance(engine, DecodeEngine)
    assert not isinstance(engine, EngineRouter)
    assert len(results) == 6
    for r in rows:
        if r.get("event") in ("request_done", "serve_warmup"):
            assert "replica" not in r
        if r.get("type") == "span":
            assert "router" not in [c["name"] for c in r["children"]]
            assert "replica" not in r


def test_cli_router_path(tmp_path):
    """--serve_replicas 2 routes through an EngineRouter: all requests
    complete, telemetry rows carry replica attribution, every span tree
    has the router-hop child, zero recompiles in every replica."""
    engine, results, rows = _serve_cli(tmp_path, ["--serve_replicas", "2"])
    assert isinstance(engine, EngineRouter)
    assert engine.n_replicas == 2
    assert len(results) == 6
    assert all(r["finish_reason"] == "length" for r in results)
    assert engine.n_recompiles == 0
    done = [r for r in rows if r.get("event") == "request_done"]
    assert len(done) == 6
    assert all(r.get("replica") in (0, 1) for r in done)
    spans = [r for r in rows if r.get("type") == "span"]
    assert len(spans) == 6
    for s in spans:
        assert [c["name"] for c in s["children"]][0] == "router"
    fleet = [r for r in rows if r.get("event") == "serve_fleet"]
    assert any(f["phase"] == "build" for f in fleet)


def test_stray_serve_replicas_flag_guarded():
    from building_llm_from_scratch_tpu.args import get_args

    with pytest.raises(ValueError, match="serve_replicas"):
        get_args(["--data_dir", "/tmp", "--serve_replicas", "2"])
    with pytest.raises(ValueError, match="serve_tp"):
        get_args(["--data_dir", "/tmp", "--serve_tp", "2"])


def test_micro_router_fingerprint_replica_count_invariant():
    """The micro_router gate contract: with watch_compiles="first" the
    captured fingerprint is ONE replica's program family — adding a
    replica must not change it (same digest at 2 and 3 replicas)."""
    from building_llm_from_scratch_tpu.configs import get_config
    from building_llm_from_scratch_tpu.obs import perf

    cfg = get_config("GPT2", "124M", dtype="fp32", debug=True)
    params = init_params(cfg, jax.random.PRNGKey(0))

    def digest(n):
        with perf.FingerprintCollector() as col:
            router = EngineRouter.build(cfg, params, n_replicas=n,
                                        n_slots=2, warmup_prompt_cap=4,
                                        metrics_every=0,
                                        watch_compiles="first")
            router.warmup()
            router.shutdown()
        return perf.fingerprint_digest(col.fingerprint())

    assert digest(2) == digest(3)
