"""Sharding-tier tests on the virtual 8-device CPU mesh (conftest.py).

The load-bearing test: dp / fsdp / zero1 / tp all produce the SAME losses
as single-device training — the strategies are placement, not semantics.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from building_llm_from_scratch_tpu.configs import get_config
from building_llm_from_scratch_tpu.models import forward, init_params
from building_llm_from_scratch_tpu.parallel import (
    MeshPlan,
    build_mesh_plan,
    gather_full,
    make_mesh,
)
from building_llm_from_scratch_tpu.training import (
    build_optimizer,
    init_train_state,
    make_train_step,
)


def tiny_cfg():
    # emb 64 / hidden 128 so every big tensor divides by 8
    return get_config("GPT2", "124M", debug=True).replace(
        emb_dim=64, hidden_dim=128, vocab_size=50264, drop_rate=0.0)


def make_batch(cfg, bs=8, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.integers(0, cfg.vocab_size, (bs, cfg.context_length)).astype(np.int32)
    return {"inputs": x, "targets": np.roll(x, -1, 1).astype(np.int32),
            "weights": np.ones_like(x, np.float32)}


def test_make_mesh_shapes():
    mesh = make_mesh()
    assert mesh.shape == {"data": 8, "seq": 1, "model": 1}
    mesh2 = make_mesh(data=-1, model=2)
    assert mesh2.shape == {"data": 4, "seq": 1, "model": 2}
    with pytest.raises(ValueError):
        make_mesh(data=3, model=3)


def test_fsdp_specs_shard_large_params_only():
    cfg = tiny_cfg()
    params = init_params(cfg, jax.random.PRNGKey(0))
    plan = build_mesh_plan("fsdp")
    shardings = plan.params_shardings(params)
    # big stacked weights shard a non-layer axis
    wq = shardings["blocks"]["attn"]["wq"]
    assert wq.spec != P() and wq.spec[0] is None
    # embeddings shard
    assert shardings["tok_emb"]["weight"].spec != P()
    # tiny norm scales replicate
    assert shardings["blocks"]["norm1"]["scale"].spec == P()


def test_dp_specs_replicate_params():
    cfg = tiny_cfg()
    params = init_params(cfg, jax.random.PRNGKey(0))
    plan = build_mesh_plan("dp")
    shardings = plan.params_shardings(params)
    assert all(s.spec == P() for s in jax.tree_util.tree_leaves(
        shardings, is_leaf=lambda x: hasattr(x, "spec")))


def test_zero1_shards_opt_state_not_params():
    cfg = tiny_cfg()
    params = init_params(cfg, jax.random.PRNGKey(0))
    opt = build_optimizer(total_steps=10)
    state = init_train_state(params, opt, jax.random.PRNGKey(0))
    plan = build_mesh_plan("zero1")
    shardings = plan.state_shardings(state)
    # params replicated
    assert shardings["trainable"]["blocks"]["attn"]["wq"].spec == P()
    # adam moments sharded
    flat = jax.tree_util.tree_flatten_with_path(shardings["opt_state"])[0]
    mu_specs = [s.spec for p, s in flat
                if any(getattr(e, "name", "") == "mu" for e in p)
                and hasattr(s, "spec")]
    assert any(spec != P() for spec in mu_specs)


def test_fsdp_actually_reduces_per_device_bytes():
    cfg = tiny_cfg()
    params = init_params(cfg, jax.random.PRNGKey(0))
    plan = build_mesh_plan("fsdp")
    sharded = plan.shard_params(params)
    w = sharded["blocks"]["attn"]["wq"]
    shard_elems = w.addressable_shards[0].data.size
    assert shard_elems == w.size // 8


def test_shard_batch_partitions_data_axis():
    cfg = tiny_cfg()
    plan = build_mesh_plan("fsdp")
    batch = plan.shard_batch(make_batch(cfg))
    x = batch["inputs"]
    assert x.sharding.spec[0] == "data"
    assert x.addressable_shards[0].data.shape[0] == 1  # 8 rows / 8 devices


@pytest.mark.parametrize("mode,tp", [("dp", 1), ("fsdp", 1), ("zero1", 1),
                                     ("tp", 2), ("tp_fsdp", 2)])
def test_sharded_training_matches_single_device(mode, tp):
    """3 steps under every strategy == 3 single-device steps."""
    cfg = tiny_cfg()
    opt = build_optimizer(peak_lr=1e-3, warmup_steps=2, total_steps=10)
    batches = [make_batch(cfg, seed=s) for s in range(3)]

    # single-device baseline (fresh params; the step donates its state)
    ref_state = init_train_state(init_params(cfg, jax.random.PRNGKey(0)),
                                 opt, jax.random.PRNGKey(0))
    step = make_train_step(cfg, opt)
    ref_losses = []
    for b in batches:
        ref_state, m = step(ref_state, b)
        ref_losses.append(float(m["loss"]))

    plan = build_mesh_plan(mode, tp=tp)
    state = init_train_state(init_params(cfg, jax.random.PRNGKey(0)),
                             opt, jax.random.PRNGKey(0))
    state = plan.shard_state(state)
    sharded_step = make_train_step(cfg, opt)
    losses = []
    for b in batches:
        state, m = sharded_step(state, plan.shard_batch(b))
        losses.append(float(m["loss"]))

    np.testing.assert_allclose(losses, ref_losses, rtol=2e-4, atol=2e-5)
    # final params agree too
    ref_w = np.asarray(ref_state["trainable"]["blocks"]["attn"]["wq"])
    got_w = gather_full(state)["trainable"]["blocks"]["attn"]["wq"]
    np.testing.assert_allclose(got_w, ref_w, rtol=2e-3, atol=2e-5)


def test_tp_forward_parity():
    cfg = tiny_cfg()
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens = jnp.asarray(make_batch(cfg, bs=4)["inputs"])
    ref = forward(params, cfg, tokens)
    plan = build_mesh_plan("tp", tp=2)
    sharded = plan.shard_params(params)
    got = jax.jit(lambda p, t: forward(p, cfg, t))(sharded, tokens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_tp_spec_placements():
    """TP rules land on the documented axes: column-parallel QKV/up,
    row-parallel wo/down, vocab-parallel embedding and head."""
    cfg = tiny_cfg()
    params = init_params(cfg, jax.random.PRNGKey(0))
    plan = build_mesh_plan("tp", tp=2)
    s = plan.params_shardings(params)
    assert s["blocks"]["attn"]["wq"].spec == P(None, None, "model")
    assert s["blocks"]["attn"]["wo"].spec == P(None, "model", None)
    assert s["blocks"]["mlp"]["up"].spec == P(None, None, "model")
    assert s["blocks"]["mlp"]["down"].spec == P(None, "model", None)
    assert s["tok_emb"]["weight"].spec == P("model", None)   # vocab-parallel
    assert s["head"]["weight"].spec == P(None, "model")      # vocab-parallel


def test_invalid_shard_mode_rejected():
    with pytest.raises(ValueError):
        MeshPlan(mesh=make_mesh(), shard_mode="ddp")


def test_shard_state_is_donation_safe():
    """Round-2 VERDICT weak #1: shard_state must return fresh buffers even
    when device_put would alias — donating its result must not delete arrays
    the caller still holds."""
    cfg = tiny_cfg()
    opt = build_optimizer(total_steps=10)
    params = init_params(cfg, jax.random.PRNGKey(0))
    plan = build_mesh_plan("dp")
    s1 = init_train_state(params, opt, jax.random.PRNGKey(0))
    s2 = plan.shard_state(init_train_state(params, opt, jax.random.PRNGKey(0)))
    step = make_train_step(cfg, opt)           # donates its state argument
    s1, _ = step(s1, make_batch(cfg))          # deletes s1's input buffers
    # s2 shares `params` with the donated s1; it must still be fully alive
    for leaf in jax.tree_util.tree_leaves(s2):
        assert not (hasattr(leaf, "is_deleted") and leaf.is_deleted())
    assert np.isfinite(float(s2["trainable"]["tok_emb"]["weight"].sum()))


def test_zero1_trainer_keeps_opt_state_sharded():
    """Round-2 ADVICE medium #1: zero1 + bf16_hybrid must NOT route through
    the replicated-spec shard_map step; the GSPMD step honors opt_spec, so
    adam moments stay sharded after a real step."""
    from building_llm_from_scratch_tpu.training import get_policy
    from building_llm_from_scratch_tpu.training.trainer import Trainer
    from building_llm_from_scratch_tpu.data import ByteTokenizer, PretrainLoader

    cfg = tiny_cfg().replace(vocab_size=300)
    plan = build_mesh_plan("zero1")
    params = init_params(cfg, jax.random.PRNGKey(0))
    tok = ByteTokenizer()
    loader = PretrainLoader(tok, batch_size=8, max_length=cfg.context_length)
    tr = Trainer(cfg, params, tok, loader, policy=get_policy("bf16_hybrid"),
                 plan=plan, eval_freq=10_000, print_sample_iter=10_000,
                 save_ckpt_freq=10_000)
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        path = f"{d}/c.txt"
        open(path, "w").write("sphinx of black quartz judge my vow. " * 100)
        tr.train_model([path], n_epochs=1)
    assert tr.global_step > 0
    flat = jax.tree_util.tree_flatten_with_path(tr.state["opt_state"])[0]
    mu = [(p, leaf) for p, leaf in flat
          if any(getattr(e, "name", "") == "mu" for e in p)
          and hasattr(leaf, "sharding") and np.ndim(leaf) >= 2]
    assert mu, "no adam mu leaves found"
    # at least the big mu leaves remain sharded over the data axis
    assert any(leaf.sharding.spec != P() for _, leaf in mu), (
        "zero1 optimizer state was silently replicated")
