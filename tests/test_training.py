"""Training-engine tests: schedule, loss, step, LoRA, checkpoints, trainer."""

import math
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from building_llm_from_scratch_tpu.configs import get_config
from building_llm_from_scratch_tpu.data import ByteTokenizer, PretrainLoader
from building_llm_from_scratch_tpu.models import init_params
from building_llm_from_scratch_tpu.models.lora import (
    count_lora_params,
    init_lora_params,
    merge_lora,
)
from building_llm_from_scratch_tpu.training import (
    Trainer,
    build_optimizer,
    cross_entropy_loss,
    get_policy,
    init_train_state,
    load_checkpoint,
    load_exported_params,
    make_eval_step,
    make_train_step,
    save_checkpoint,
    export_params,
    warmup_cosine_schedule,
)


def tiny_cfg(**kw):
    return get_config("GPT2", "124M", debug=True, **kw)


def tiny_llama(**kw):
    return get_config("llama3_2", "1B", debug=True, **kw)


def make_batch(cfg, bs=2, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.integers(0, cfg.vocab_size, (bs, cfg.context_length)).astype(np.int32)
    y = np.roll(x, -1, axis=1).astype(np.int32)
    w = np.ones_like(x, np.float32)
    return {"inputs": x, "targets": y, "weights": w}


# ---------------------------------------------------------------------------
# Gradient accumulation (round-5 VERDICT #7)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("masked", [False, True])
def test_grad_accum_matches_full_batch(masked):
    """accum=4 over a bs-8 batch == one bs-8 step (dropout off): the scan
    accumulates fp32 grads and the weighted-CE sums, so the update is the
    exact full-batch weighted mean."""
    cfg = tiny_cfg().replace(drop_rate=0.0)
    opt = build_optimizer(peak_lr=1e-3, warmup_steps=2, total_steps=10)
    batch = make_batch(cfg, bs=8)
    if masked:
        w = batch["weights"].copy()
        w[:, : cfg.context_length // 2] = 0.0    # SFT-style prompt mask
        batch = dict(batch, weights=w)

    # fresh keys per state: the donated steps delete their rng buffers
    s1 = init_train_state(init_params(cfg, jax.random.PRNGKey(0)), opt,
                          jax.random.PRNGKey(1))
    step1 = make_train_step(cfg, opt)
    s2 = init_train_state(init_params(cfg, jax.random.PRNGKey(0)), opt,
                          jax.random.PRNGKey(1))
    step4 = make_train_step(cfg, opt, grad_accum=4)
    for seed in range(3):
        b = dict(batch) if seed == 0 else make_batch(cfg, bs=8, seed=seed)
        if masked and seed > 0:
            w = b["weights"].copy()
            w[:, : cfg.context_length // 2] = 0.0
            b = dict(b, weights=w)
        s1, m1 = step1(s1, b)
        s2, m2 = step4(s2, b)
        np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]),
                                   rtol=1e-5, atol=1e-6)
        # per-layer-group health arrays (obs/health.py) must agree between
        # the paths too: the scan accumulates the same gradient, so every
        # group's grad/param/update norm is the same number
        for key in ("grad_norm", "param_norm", "update_norm",
                    "update_ratio"):
            np.testing.assert_allclose(
                np.asarray(m1["health"][key]), np.asarray(m2["health"][key]),
                rtol=2e-4, atol=1e-7, err_msg=f"health {key} diverged")
        assert int(m1["health"]["first_nonfinite"]) == -1
        assert int(m2["health"]["first_nonfinite"]) == -1
        np.testing.assert_allclose(float(m1["update_norm"]),
                                   float(m2["update_norm"]),
                                   rtol=2e-4, atol=1e-7)
    for a, b in zip(jax.tree_util.tree_leaves(s1["trainable"]),
                    jax.tree_util.tree_leaves(s2["trainable"])):
        # adam's rsqrt amplifies fp32 reduction-order noise over 3 steps
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-5)


def test_grad_accum_fp16_scaling_still_skips_overflow():
    """fp16 + grad_accum: an overflowing microbatch must still skip the
    update and halve the scale."""
    cfg = tiny_cfg().replace(drop_rate=0.0)
    policy = get_policy("fp16")
    opt = build_optimizer(total_steps=10)
    state = init_train_state(init_params(cfg, jax.random.PRNGKey(0)), opt,
                             jax.random.PRNGKey(1), policy=policy)
    state["trainable"]["head"]["weight"] = (
        state["trainable"]["head"]["weight"] + 1e5)
    before = np.asarray(state["trainable"]["blocks"]["attn"]["wq"])
    step = make_train_step(cfg, opt, policy=policy, grad_accum=2)
    state, m = step(state, make_batch(cfg, bs=4))
    assert int(m["skipped"]) == 1
    assert float(m["loss_scale"]) == 2.0 ** 14
    np.testing.assert_array_equal(
        np.asarray(state["trainable"]["blocks"]["attn"]["wq"]), before)


def test_grad_accum_rejects_indivisible_batch():
    cfg = tiny_cfg().replace(drop_rate=0.0)
    opt = build_optimizer(total_steps=10)
    state = init_train_state(init_params(cfg, jax.random.PRNGKey(0)), opt,
                             jax.random.PRNGKey(1))
    step = make_train_step(cfg, opt, grad_accum=3, jit=False)
    with pytest.raises(ValueError, match="divisible"):
        step(state, make_batch(cfg, bs=4))


# ---------------------------------------------------------------------------
# Per-layer-group training health (obs/health.py via _finish_step)
# ---------------------------------------------------------------------------

def test_step_metrics_carry_health_and_update_norm():
    """Every step's metrics pytree carries the health bundle — (G,) arrays
    aligned with obs.health.group_names — plus the post-clip update_norm
    satellite (clipping was previously invisible)."""
    from building_llm_from_scratch_tpu.obs.health import group_names

    # shrunk well below the debug config: this test compiles its own step
    # and only checks metric plumbing, not model numerics
    cfg = tiny_cfg().replace(drop_rate=0.0, emb_dim=32, hidden_dim=64,
                             n_layers=2, n_heads=2, vocab_size=257,
                             context_length=16)
    opt = build_optimizer(total_steps=10)
    params = init_params(cfg, jax.random.PRNGKey(0))
    names = group_names(params)
    # GPT-2 debug config: 2 stacked blocks + embeddings/norm/head groups
    assert [n for n in names if n.startswith("block_")] == [
        f"block_{i:02d}" for i in range(cfg.n_layers)]
    assert {"tok_emb", "head", "final_norm"} <= set(names)
    state = init_train_state(params, opt, jax.random.PRNGKey(1))
    step = make_train_step(cfg, opt)
    state, m = step(state, make_batch(cfg, bs=2))
    h = m["health"]
    G = len(names)
    for key in ("grad_norm", "param_norm", "update_norm", "update_ratio"):
        arr = np.asarray(h[key])
        assert arr.shape == (G,), key
        assert np.all(np.isfinite(arr)), key
    assert int(h["first_nonfinite"]) == -1
    # group norms compose to the global ones reported alongside them
    np.testing.assert_allclose(
        np.sqrt(np.sum(np.asarray(h["grad_norm"]) ** 2)),
        float(m["grad_norm"]), rtol=1e-5)
    np.testing.assert_allclose(
        np.sqrt(np.sum(np.asarray(h["update_norm"]) ** 2)),
        float(m["update_norm"]), rtol=1e-5)
    assert float(m["update_norm"]) > 0.0


def test_health_group_norms_match_hand_computation():
    """grad_norm[g] is the plain L2 norm over the group's leaves; the
    stacked `blocks` leaves split per layer along their leading axis."""
    from building_llm_from_scratch_tpu.obs.health import (
        group_health,
        group_names,
    )

    tree = {
        "blocks": {"w": jnp.asarray([[3.0, 4.0], [5.0, 12.0]])},  # L=2
        "head": {"weight": jnp.asarray([8.0, -6.0])},
    }
    names = group_names(tree)
    assert names == ["block_00", "block_01", "head"]
    h = group_health(tree, tree, tree)
    np.testing.assert_allclose(np.asarray(h["grad_norm"]),
                               [5.0, 13.0, 10.0], rtol=1e-6)
    # identical trees -> update/param ratio is exactly 1
    np.testing.assert_allclose(np.asarray(h["update_ratio"]),
                               [1.0, 1.0, 1.0], rtol=1e-6)
    assert int(h["first_nonfinite"]) == -1


def test_health_first_nonfinite_names_injected_layer():
    """Localization: a NaN injected into ONE block's gradient leaf maps to
    that block's group index — the watchdog_halt attachment names it."""
    from building_llm_from_scratch_tpu.obs.health import (
        first_nonfinite_group,
        group_health,
        group_names,
    )

    cfg = tiny_cfg().replace(drop_rate=0.0)
    params = init_params(cfg, jax.random.PRNGKey(0))
    names = group_names(params)
    grads = jax.tree_util.tree_map(jnp.zeros_like, params)
    layer = 1
    wq = np.zeros(grads["blocks"]["attn"]["wq"].shape, np.float32)
    wq[layer, 0, 0] = np.nan
    grads["blocks"]["attn"]["wq"] = jnp.asarray(wq)
    idx = int(first_nonfinite_group(grads))
    assert names[idx] == f"block_{layer:02d}"
    # an inf in an EARLIER group wins (first = lowest group index)
    head = np.zeros(np.asarray(grads["head"]["weight"]).shape, np.float32)
    head[0] = np.inf
    grads2 = dict(grads, head={"weight": jnp.asarray(head)})
    first = int(first_nonfinite_group(grads2))
    assert first == min(idx, names.index("head"))
    # the full bundle agrees with the standalone helper
    h = group_health(grads, params, grads)
    assert int(h["first_nonfinite"]) == idx
    # healthy grads localize to -1
    zeros = jax.tree_util.tree_map(jnp.zeros_like, params)
    assert int(first_nonfinite_group(zeros)) == -1


# ---------------------------------------------------------------------------
# LR schedule
# ---------------------------------------------------------------------------

def test_schedule_matches_reference_formula():
    """Transcribe the reference LR math (train.py:100-107) and compare."""
    peak, init, mn, warm, total = 5e-4, 1e-5, 1e-6, 10, 100
    sched = warmup_cosine_schedule(peak, init, mn, warm, total)
    incr = (peak - init) / warm
    for count in range(total):
        step = count + 1                     # reference pre-increments
        if step < warm:
            ref = init + step * incr
        else:
            progress = (step - warm) / (total - warm)
            ref = mn + (peak - mn) * 0.5 * (1 + math.cos(math.pi * progress))
        assert abs(float(sched(count)) - ref) < 1e-9, step


def test_schedule_endpoints():
    sched = warmup_cosine_schedule(5e-4, 1e-5, 1e-6, 10, 1000)
    assert float(sched(0)) < 1e-4            # starts near initial_lr
    assert abs(float(sched(9)) - 5e-4) < 1e-4   # ~peak after warmup
    assert abs(float(sched(999)) - 1e-6) < 1e-8  # ends at min_lr


# ---------------------------------------------------------------------------
# Loss
# ---------------------------------------------------------------------------

def test_cross_entropy_matches_torch():
    torch = pytest.importorskip("torch")
    B, T, V = 2, 8, 32
    logits = np.random.randn(B, T, V).astype(np.float32)
    targets = np.random.randint(0, V, (B, T))
    ours = float(cross_entropy_loss(jnp.asarray(logits), jnp.asarray(targets)))
    ref = float(torch.nn.functional.cross_entropy(
        torch.from_numpy(logits).flatten(0, 1),
        torch.from_numpy(targets).flatten()))
    assert abs(ours - ref) < 1e-5


def test_cross_entropy_weighted_ignores_masked():
    B, T, V = 1, 4, 8
    logits = np.random.randn(B, T, V).astype(np.float32)
    targets = np.array([[1, 2, 3, 4]])
    w_full = np.ones((B, T), np.float32)
    w_half = np.array([[1, 1, 0, 0]], np.float32)
    l_half = float(cross_entropy_loss(jnp.asarray(logits),
                                      jnp.asarray(targets),
                                      jnp.asarray(w_half)))
    ref = float(cross_entropy_loss(jnp.asarray(logits[:, :2]),
                                   jnp.asarray(targets[:, :2]),
                                   jnp.asarray(w_full[:, :2])))
    assert abs(l_half - ref) < 1e-6


# ---------------------------------------------------------------------------
# Train step
# ---------------------------------------------------------------------------

def test_train_step_reduces_loss(rng_key):
    cfg = tiny_cfg()
    params = init_params(cfg, rng_key)
    opt = build_optimizer(peak_lr=1e-2, warmup_steps=2, total_steps=60)
    state = init_train_state(params, opt, jax.random.PRNGKey(0))
    step = make_train_step(cfg, opt,
                           lr_schedule=warmup_cosine_schedule(
                               1e-2, 1e-5, 1e-6, 2, 60))
    batch = make_batch(cfg)                  # memorize one batch
    losses = []
    for _ in range(40):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] - 1.0, losses[:3] + losses[-3:]
    assert int(state["step"]) == 40
    assert "lr" in metrics and metrics["grad_norm"] >= 0


def test_eval_step_deterministic(rng_key):
    cfg = tiny_cfg()
    params = init_params(cfg, rng_key)
    opt = build_optimizer(total_steps=10)
    state = init_train_state(params, opt, jax.random.PRNGKey(0))
    ev = make_eval_step(cfg)
    batch = make_batch(cfg)
    assert float(ev(state, batch)) == float(ev(state, batch))


def test_mixed_precision_policy_step(rng_key):
    cfg = tiny_cfg()
    params = init_params(cfg, rng_key)      # fp32 master
    opt = build_optimizer(total_steps=10)
    state = init_train_state(params, opt, jax.random.PRNGKey(0))
    step = make_train_step(cfg, opt, policy=get_policy("bf16"))
    state, metrics = step(state, make_batch(cfg))
    # master params stay fp32 even though compute ran in bf16
    assert state["trainable"]["tok_emb"]["weight"].dtype == jnp.float32
    assert np.isfinite(float(metrics["loss"]))


def test_policy_registry_matches_reference_names():
    # reference datautils/mixed_precision.py defines exactly these four
    for name in ("fp16", "bf16", "bf16_hybrid", "fp32"):
        assert get_policy(name) is not None
    with pytest.raises(ValueError):
        get_policy("int8")
    assert get_policy(None) is None
    assert get_policy("bf16_hybrid").reduce_dtype == "bf16"
    assert get_policy("bf16_hybrid").compute_dtype == "fp32"


# ---------------------------------------------------------------------------
# LoRA
# ---------------------------------------------------------------------------

def test_lora_zero_init_is_identity(rng_key):
    from building_llm_from_scratch_tpu.models import forward

    cfg = tiny_llama()
    params = init_params(cfg, rng_key)
    lora = init_lora_params(cfg, params, jax.random.PRNGKey(1), rank=4)
    merged = merge_lora(params, lora, alpha=8, rank=4)
    tokens = jnp.zeros((1, 8), jnp.int32)
    np.testing.assert_allclose(
        np.asarray(forward(params, cfg, tokens)),
        np.asarray(forward(merged, cfg, tokens)), rtol=1e-6, atol=1e-6)


def test_lora_adapts_all_linears(rng_key):
    cfg = tiny_llama()
    params = init_params(cfg, rng_key)
    lora = init_lora_params(cfg, params, jax.random.PRNGKey(1), rank=4)
    assert set(lora["blocks"]["attn"]) == {"wq", "wk", "wv", "wo"}
    assert set(lora["blocks"]["mlp"]) == {"up", "down", "gate"}
    assert "weight" in lora["head"]
    # stacked adapters carry the layer axis
    assert lora["blocks"]["attn"]["wq"]["A"].shape[0] == cfg.n_layers
    assert count_lora_params(lora) > 0


def test_lora_train_step_only_updates_adapters(rng_key):
    cfg = tiny_llama()
    params = init_params(cfg, rng_key)
    lora = init_lora_params(cfg, params, jax.random.PRNGKey(1), rank=4)
    opt = build_optimizer(peak_lr=1e-2, total_steps=20)
    state = init_train_state(lora, opt, jax.random.PRNGKey(0), frozen=params)
    step = make_train_step(cfg, opt, lora_alpha=8, lora_rank=4)
    base_before = jax.tree_util.tree_map(np.asarray, state["frozen"])
    batch = make_batch(cfg)
    losses = []
    for _ in range(15):
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]            # adapters actually learn
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(a, np.asarray(b)),
        base_before, state["frozen"])        # base frozen structurally
    # B matrices moved away from zero
    assert float(jnp.abs(state["trainable"]["blocks"]["attn"]["wq"]["B"]).max()) > 0


# ---------------------------------------------------------------------------
# Checkpointing
# ---------------------------------------------------------------------------

def test_checkpoint_roundtrip_and_resume(rng_key, tmp_path):
    cfg = tiny_cfg()
    params = init_params(cfg, rng_key)
    opt = build_optimizer(peak_lr=1e-3, total_steps=20)
    state = init_train_state(params, opt, jax.random.PRNGKey(0))
    step = make_train_step(cfg, opt)
    batch = make_batch(cfg)
    for _ in range(3):
        state, _ = step(state, batch)
    ckpt = str(tmp_path / "ckpt")
    save_checkpoint(ckpt, state, extra_metadata={"global_step": 3})

    template = init_train_state(init_params(cfg, jax.random.PRNGKey(9)), opt,
                                jax.random.PRNGKey(0))
    restored = load_checkpoint(ckpt, template)
    assert int(restored["step"]) == 3
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        state, restored)
    # resuming: one more step from restored equals one more step from live
    s1, m1 = step(state, batch)
    s2, m2 = step(restored, batch)
    assert abs(float(m1["loss"]) - float(m2["loss"])) < 1e-6


def test_export_params_roundtrip(rng_key, tmp_path):
    cfg = tiny_cfg()
    params = init_params(cfg, rng_key)
    path = str(tmp_path / "model_pg_final.npz")
    export_params(path, params)
    restored = load_exported_params(path, init_params(cfg,
                                                      jax.random.PRNGKey(5)))
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        params, restored)


# ---------------------------------------------------------------------------
# Generation
# ---------------------------------------------------------------------------

def test_generate_greedy_deterministic(rng_key):
    from building_llm_from_scratch_tpu.generate import generate

    cfg = tiny_llama()
    params = init_params(cfg, rng_key)
    prompt = np.array([[1, 2, 3]], np.int32)
    out1 = generate(params, cfg, prompt, max_new_tokens=5)
    out2 = generate(params, cfg, prompt, max_new_tokens=5)
    np.testing.assert_array_equal(out1, out2)
    assert out1.shape[1] <= 8
    np.testing.assert_array_equal(out1[:, :3], prompt)


def test_generate_cached_matches_sliding_window(rng_key):
    """The jitted KV-cache path must produce the same greedy tokens as the
    reference-style full-recompute path."""
    from building_llm_from_scratch_tpu.generate import generate

    cfg = tiny_llama()
    params = init_params(cfg, rng_key)
    prompt = np.array([[5, 6, 7, 8]], np.int32)
    cached = generate(params, cfg, prompt, max_new_tokens=6,
                      context_size=cfg.context_length)
    # force the sliding-window fallback with a small context_size
    slide = generate(params, cfg, prompt, max_new_tokens=6,
                     context_size=10)
    # both grow from the same prompt; with ctx>=total they must agree
    np.testing.assert_array_equal(cached, slide)


def test_generate_respects_top_k_and_temperature(rng_key):
    from building_llm_from_scratch_tpu.generate import generate

    cfg = tiny_llama()
    params = init_params(cfg, rng_key)
    prompt = np.array([[1, 2]], np.int32)
    a = generate(params, cfg, prompt, max_new_tokens=5, temperature=1.0,
                 top_k=5, rng=jax.random.PRNGKey(1))
    b = generate(params, cfg, prompt, max_new_tokens=5, temperature=1.0,
                 top_k=5, rng=jax.random.PRNGKey(2))
    assert a.shape == b.shape
    # different rngs usually sample different continuations
    assert not np.array_equal(a, b) or a.shape[1] == 2


# ---------------------------------------------------------------------------
# Trainer end-to-end (tiny, CPU)
# ---------------------------------------------------------------------------

def test_trainer_pretrain_end_to_end(rng_key, tmp_path):
    cfg = tiny_cfg()
    params = init_params(cfg, rng_key)
    tok = ByteTokenizer()
    datafile = tmp_path / "corpus.txt"
    datafile.write_text("the quick brown fox jumps over the lazy dog. " * 200)
    loader = PretrainLoader(tok, batch_size=2, max_length=cfg.context_length)
    trainer = Trainer(cfg, params, tok, loader,
                      output_dir=str(tmp_path / "out"),
                      eval_freq=5, print_sample_iter=1000,
                      save_ckpt_freq=10_000, warmup_steps=2)
    trainer.train_model([str(datafile)], n_epochs=1, start_context="the ")
    assert trainer.global_step > 0
    assert trainer.tokens_seen > 0
    assert len(trainer.train_losses) >= 1
    assert np.isfinite(trainer.train_losses[-1])
    out = trainer.export_final()
    assert os.path.exists(out)


def test_resume_reuses_original_schedule_horizon(rng_key, tmp_path):
    """Round-2 ADVICE low: resuming an interrupted run must complete the
    ORIGINAL cosine schedule, not stretch it by the steps already taken."""
    cfg = tiny_cfg()
    tok = ByteTokenizer()
    loader = PretrainLoader(tok, batch_size=2, max_length=cfg.context_length)

    t1 = Trainer(cfg, init_params(cfg, rng_key), tok, loader,
                 output_dir=str(tmp_path))
    t1._setup(100)                       # original horizon: 100 steps
    t1.global_step = 40                  # pretend we got interrupted here
    ckpt = t1.save_checkpoint("interrupted")

    # resume with exactly the remaining steps: horizon must stay 100
    t2 = Trainer(cfg, init_params(cfg, rng_key), tok, loader,
                 output_dir=str(tmp_path), resume_from=ckpt)
    t2._setup(60)
    for step in (50, 70, 99):
        assert abs(float(t2.lr_schedule(step))
                   - float(t1.lr_schedule(step))) < 1e-12, step

    # resume with MORE work than the original plan: horizon extends
    t3 = Trainer(cfg, init_params(cfg, rng_key), tok, loader,
                 output_dir=str(tmp_path), resume_from=ckpt)
    t3._setup(90)
    assert t3._schedule_horizon == 130


def test_trainer_train_model_twice(rng_key, tmp_path):
    """Round-2 VERDICT weak #1 regression: the first run's donated steps
    must not delete the params the Trainer re-initializes from."""
    cfg = tiny_cfg()
    params = jax.device_put(init_params(cfg, rng_key))  # committed jax.Arrays
    tok = ByteTokenizer()
    datafile = tmp_path / "corpus.txt"
    datafile.write_text("pack my box with five dozen liquor jugs. " * 120)
    loader = PretrainLoader(tok, batch_size=2, max_length=cfg.context_length)
    trainer = Trainer(cfg, params, tok, loader,
                      output_dir=str(tmp_path / "out"),
                      eval_freq=10_000, print_sample_iter=10_000,
                      save_ckpt_freq=10_000, warmup_steps=2)
    trainer.train_model([str(datafile)], n_epochs=1, start_context="the ")
    first_steps = trainer.global_step
    assert first_steps > 0
    # second run re-enters _setup with self._params — previously dead buffers
    trainer.train_model([str(datafile)], n_epochs=1, start_context="the ")
    assert trainer.global_step > first_steps
    # the original params pytree itself must still be alive too
    assert np.isfinite(float(jax.tree_util.tree_leaves(params)[0].sum()))


@pytest.mark.slow
def test_trainer_finetune_end_to_end(rng_key, tmp_path):
    import json

    from building_llm_from_scratch_tpu.data import InstructLoader

    # context long enough that byte-level prompts leave supervised response
    # tokens after the instruction mask
    cfg = tiny_llama().replace(context_length=256)
    params = init_params(cfg, rng_key)
    lora = init_lora_params(cfg, params, jax.random.PRNGKey(1), rank=4)
    tok = ByteTokenizer()
    records = [{"instruction": f"repeat {i}", "input": "",
                "output": f"{i} " * 3} for i in range(40)]
    datafile = tmp_path / "alpaca_data.json"
    datafile.write_text(json.dumps(records))
    loader = InstructLoader(tok, batch_size=2, max_length=cfg.context_length,
                            pad_token_id=tok.eos_id)
    trainer = Trainer(cfg, params, tok, loader,
                      output_dir=str(tmp_path / "out"),
                      eval_freq=5, print_sample_iter=1000,
                      save_ckpt_freq=10_000, warmup_steps=2,
                      lora_params=lora, lora_alpha=8, lora_rank=4)
    trainer.finetune_model([str(datafile)], n_epochs=1)
    assert trainer.global_step > 0
    assert np.isfinite(trainer.train_losses[-1])
    assert trainer.train_losses[-1] > 0  # mask left supervised tokens
