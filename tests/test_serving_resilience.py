"""Serving resilience tests (the serving counterpart of PR 1's training
fault-injection suite): deadline-aware admission (queue TTL expiry, SLO
shed math), per-request fault isolation (a poison request fails ALONE and
co-residents' tokens stay bit-identical to a fault-free run), the
in-graph non-finite-logit guard, the tick-watchdog supervisor
(restart-then-serve with zero recompiles), graceful drain, and the HTTP
frontend's input hardening.
"""

import http.client
import json
import threading
import time

import jax
import numpy as np
import pytest

from building_llm_from_scratch_tpu.configs import ModelConfig
from building_llm_from_scratch_tpu.generate import generate
from building_llm_from_scratch_tpu.models import init_params
from building_llm_from_scratch_tpu.serving import (
    DecodeEngine,
    EngineDrainingError,
    FaultHooks,
    RequestExpiredError,
    SLOShedError,
    SamplingParams,
)


def tiny_cfg(ctx=64, **kw):
    base = dict(name="serve-resil-tiny", vocab_size=96, context_length=ctx,
                emb_dim=32, n_heads=2, n_layers=2, hidden_dim=64,
                n_kv_groups=2, norm="layernorm", positional="learned",
                activation="gelu", drop_rate=0.0, eos_id=1)
    base.update(kw)
    return ModelConfig(**base)


@pytest.fixture(scope="module")
def model():
    cfg = tiny_cfg()
    return cfg, init_params(cfg, jax.random.PRNGKey(0))


def solo_tokens(params, cfg, prompt, sp: SamplingParams):
    out, n = generate(params, cfg, np.asarray(prompt)[None],
                      max_new_tokens=sp.max_new_tokens,
                      temperature=sp.temperature, top_k=sp.top_k,
                      eos_id=(None if sp.ignore_eos
                              else (sp.eos_id if sp.eos_id is not None
                                    else cfg.eos_id)),
                      rng=jax.random.PRNGKey(sp.seed),
                      return_n_generated=True)
    Tp = len(prompt)
    return [int(t) for t in out[0, Tp: Tp + int(n[0])]]


# ---------------------------------------------------------------------------
# deadline-aware admission
# ---------------------------------------------------------------------------

def test_queue_ttl_expiry_sheds_at_admission(model):
    """A queued request whose deadline passes is shed at the admission
    boundary — ``result()`` raises ``RequestExpiredError``, no slot or
    decode tick is spent on it."""
    cfg, params = model
    eng = DecodeEngine(cfg, params, n_slots=1, max_len=64)
    p = np.array([5, 6, 7], np.int32)
    h = eng.submit(p, SamplingParams(max_new_tokens=4, ignore_eos=True,
                                     deadline_s=0.05))
    time.sleep(0.12)
    ticks_before = eng.n_ticks
    eng.run_until_idle()
    assert h.done and h.finish_reason == "expired"
    with pytest.raises(RequestExpiredError, match="expired"):
        h.result(timeout=1)
    assert eng.requests_expired == 1
    assert eng.n_ticks == ticks_before        # zero decode spent on it
    # a request with a live deadline sails through
    h2 = eng.submit(p, SamplingParams(max_new_tokens=3, ignore_eos=True,
                                      deadline_s=60.0))
    eng.run_until_idle()
    assert h2.result().finish_reason == "length"


def test_slo_shed_decision_math(model):
    """submit() sheds exactly when queue position x the TPOT-EWMA service
    estimate + the request's own budget exceeds its deadline."""
    cfg, params = model
    eng = DecodeEngine(cfg, params, n_slots=2, max_len=64)
    # no history yet: estimates are None, admission stays optimistic
    assert eng.estimate_completion_s(4, 5) is None
    eng._tpot_ewma = 0.1
    eng._tokens_ewma = 10.0
    # wait = (depth/slots) * (tokens * tpot); own decode = max_new * tpot
    assert eng.estimate_completion_s(4, 5) == pytest.approx(2.5)
    assert eng.estimate_completion_s(0, 5) == pytest.approx(0.5)
    # fill the queue without stepping, then probe the shed boundary
    p = np.array([2, 3], np.int32)
    for _ in range(3):
        eng.submit(p, SamplingParams(max_new_tokens=2, ignore_eos=True))
    est = eng.estimate_completion_s(3, 2)      # (3/2)*1.0 + 0.2 = 1.7
    assert est == pytest.approx(1.7)
    with pytest.raises(SLOShedError) as ei:
        eng.submit(p, SamplingParams(max_new_tokens=2, ignore_eos=True,
                                     deadline_s=1.0))
    assert ei.value.retry_after_s and ei.value.retry_after_s > 0
    assert eng.requests_shed == 1
    # same request with a meetable deadline is admitted
    h = eng.submit(p, SamplingParams(max_new_tokens=2, ignore_eos=True,
                                     deadline_s=60.0))
    eng.run_until_idle()
    assert h.result().finish_reason == "length"
    assert eng.requests_shed == 1              # no extra sheds
    # in-flight requests count toward the wait (half-done on average):
    # full slots + empty queue must NOT predict zero wait
    eng._tpot_ewma, eng._tokens_ewma = 0.1, 10.0   # re-pin post-run EWMAs
    for _ in range(2):
        eng.submit(p, SamplingParams(max_new_tokens=10, ignore_eos=True))
    eng.step()                                 # admit both into slots
    assert eng.scheduler.n_active == 2
    # wait = ((0 + 0.5*2)/2) * 1.0 = 0.5; own budget = 5*0.1
    assert eng.estimate_completion_s(0, 5) == pytest.approx(1.0)


# ---------------------------------------------------------------------------
# per-request fault isolation
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_poison_prefill_fails_alone_coresidents_bit_identical(model):
    """THE isolation contract: a poison request (injected prefill fault)
    fails alone, and its co-residents' token streams are bit-identical to
    a fault-free run of the same traffic."""
    cfg, params = model
    rng = np.random.default_rng(7)
    prompts = [rng.integers(2, cfg.vocab_size, (4 + i,)).astype(np.int32)
               for i in range(3)]
    sps = [SamplingParams(max_new_tokens=6 + i, seed=i, ignore_eos=True,
                          temperature=0.8 * (i % 2), top_k=9 if i % 2
                          else None)
           for i in range(3)]

    # fault-free reference run
    eng_ref = DecodeEngine(cfg, params, n_slots=3, max_len=64)
    ref = [eng_ref.submit(p, sp) for p, sp in zip(prompts, sps)]
    eng_ref.run_until_idle()
    ref_tokens = [h.output_ids for h in ref]

    # same traffic + a poison request admitted mid-stream
    poison_ids = set()

    class Hooks(FaultHooks):
        def before_prefill(self, req):
            if req.id in poison_ids:
                raise RuntimeError("injected prefill fault")

    eng = DecodeEngine(cfg, params, n_slots=3, max_len=64, hooks=Hooks())
    h0 = eng.submit(prompts[0], sps[0])
    assert eng.step()                          # request 0 decodes alone
    hp = eng.submit(np.array([9, 9, 9], np.int32),
                    SamplingParams(max_new_tokens=8, ignore_eos=True))
    poison_ids.add(hp.id)
    h1 = eng.submit(prompts[1], sps[1])
    h2 = eng.submit(prompts[2], sps[2])
    eng.run_until_idle()

    # poison failed alone ...
    assert hp.done and hp.finish_reason == "error"
    assert "prefill" in hp.error
    with pytest.raises(RuntimeError, match="failed"):
        hp.result(timeout=1)
    assert eng.requests_failed == 1
    # ... the engine is alive (not _fail_all'd), its slot was freed ...
    assert eng._dead is None
    assert eng.scheduler.n_active == 0 and len(eng.queue) == 0
    # ... and the co-residents are BIT-IDENTICAL to the fault-free run
    for h, want, p, sp in zip((h0, h1, h2), ref_tokens, prompts, sps):
        assert h.finish_reason == "length"
        assert h.output_ids == want
        assert h.output_ids == solo_tokens(params, cfg, p, sp)


def test_raising_on_token_callback_fails_request_alone(model):
    """A raising client callback is the REQUEST's fault, not the
    engine's: it fails alone, co-resident and queued requests finish."""
    cfg, params = model
    eng = DecodeEngine(cfg, params, n_slots=2, max_len=64, max_queue=8)

    def bad_callback(req, tok, piece):
        raise RuntimeError("boom from user callback")

    sp = SamplingParams(max_new_tokens=4, ignore_eos=True)
    p = np.array([2, 3, 4], np.int32)
    h_bad = eng.submit(p, sp, on_token=bad_callback)
    h_ok = eng.submit(p, sp)
    h_queued = eng.submit(p, sp)
    eng.run_until_idle()
    assert h_bad.finish_reason == "error" and "callback" in h_bad.error
    assert h_ok.result().output_ids == solo_tokens(params, cfg, p, sp)
    assert h_queued.result().output_ids == solo_tokens(params, cfg, p, sp)
    assert eng._dead is None                   # engine survived
    assert eng.requests_failed == 1


def test_non_finite_logits_retire_slot_not_batch(model):
    """NaN-poisoned KV state (injected) makes ONE row's logits non-finite
    in-graph; the guard retires that slot with an error status while the
    co-resident request's tokens stay bit-identical — and the poisoned
    slot serves cleanly on reuse. Zero recompiles throughout."""
    cfg, params = model
    poison_ids = set()

    class Hooks(FaultHooks):
        def poison_nan(self, req):
            return req.id in poison_ids

    eng = DecodeEngine(cfg, params, n_slots=2, max_len=64, hooks=Hooks())
    eng.warmup()
    pa = np.array([5, 6, 7, 8], np.int32)
    sa = SamplingParams(max_new_tokens=6, seed=3, ignore_eos=True,
                        temperature=1.0, top_k=7)
    ha = eng.submit(pa, sa)
    hp = eng.submit(np.array([4, 4], np.int32),
                    SamplingParams(max_new_tokens=6, ignore_eos=True))
    poison_ids.add(hp.id)
    eng.run_until_idle()
    assert hp.done and hp.finish_reason == "error"
    assert "non-finite" in hp.error
    assert len(hp.output_ids) <= 1             # prefill token at most
    assert ha.result().output_ids == solo_tokens(params, cfg, pa, sa)
    # the poisoned slot is safe to reuse: prefill overwrites its rows and
    # per-slot masking hides the stale NaN tail
    poison_ids.clear()
    h2 = eng.submit(pa, sa)
    eng.run_until_idle()
    assert h2.result().output_ids == solo_tokens(params, cfg, pa, sa)
    assert eng.n_recompiles == 0               # CompileWatcher-asserted


def test_out_of_vocab_prompt_rejected_at_submit(model):
    """Out-of-vocab prompt ids would embed as NaN and stream garbage —
    submit() rejects them before they cost a slot."""
    cfg, params = model
    eng = DecodeEngine(cfg, params, n_slots=1, max_len=64)
    with pytest.raises(ValueError, match="token ids"):
        eng.submit(np.array([5, cfg.vocab_size], np.int32),
                   SamplingParams(max_new_tokens=2))
    with pytest.raises(ValueError, match="token ids"):
        eng.submit(np.array([-1, 5], np.int32),
                   SamplingParams(max_new_tokens=2))


# ---------------------------------------------------------------------------
# tick-watchdog supervisor
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_hung_tick_flight_record_restart_then_serve(model, tmp_path):
    """A wedged tick trips the watchdog: flight record (``stall`` event),
    in-flight requests fail, the loop restarts with bounded backoff
    (``engine_restart`` event), queued work is KEPT, and the engine
    serves new requests afterwards — with zero recompiles (the compiled
    programs and their frozen CompileWatchers survive the restart)."""
    from building_llm_from_scratch_tpu.obs.metrics import configure_metrics

    cfg, params = model
    hang = threading.Event()        # set => wedge the next tick
    release = threading.Event()     # un-wedge the abandoned thread

    class Hooks(FaultHooks):
        def before_tick(self, engine):
            if hang.is_set():
                hang.clear()
                release.wait(30)    # the simulated wedge (bounded)

        def after_token(self, req, tok):
            # slow-client drag stretches the decode so the wedge lands
            # mid-request deterministically (a 40-token burst on the CPU
            # backend can otherwise outrun the test's hang.set())
            time.sleep(0.005)

    mj = str(tmp_path / "restart_metrics.jsonl")
    sink = configure_metrics(mj)
    sink.write_header(test="restart")
    try:
        eng = DecodeEngine(cfg, params, n_slots=2, max_len=64,
                           hooks=Hooks(), tick_timeout_s=0.6,
                           max_restarts=2, restart_backoff_s=0.05)
        eng.warmup()
        eng.start()
        p = np.array([5, 6, 7], np.int32)
        sp_long = SamplingParams(max_new_tokens=60, ignore_eos=True)
        h1 = eng.submit(p, sp_long)
        deadline = time.monotonic() + 20
        while not h1.output_ids and time.monotonic() < deadline:
            time.sleep(0.005)
        assert h1.output_ids                   # mid-decode
        hang.set()
        with pytest.raises(RuntimeError, match="restarted"):
            h1.result(timeout=30)
        assert h1.finish_reason == "error"
        assert eng.n_restarts == 1
        # the engine serves NEW traffic after the restart
        sp_new = SamplingParams(max_new_tokens=5, seed=2, ignore_eos=True)
        h2 = eng.submit(p, sp_new)
        h2.result(timeout=30)
        assert h2.output_ids == solo_tokens(params, cfg, p, sp_new)
        release.set()                          # un-wedge the old thread
        time.sleep(0.1)                        # let it observe the bump
        # the abandoned thread must have committed NOTHING: serve again
        h3 = eng.submit(p, sp_new)
        h3.result(timeout=30)
        assert h3.output_ids == h2.output_ids
        assert eng.n_recompiles == 0           # CompileWatcher-asserted
        eng.shutdown()
    finally:
        release.set()
        sink.close()
        configure_metrics(None)
    rows = [json.loads(line) for line in open(mj)]
    events = [r.get("event") for r in rows if r.get("type") == "event"]
    assert "stall" in events                   # the flight record fired
    restarts = [r for r in rows if r.get("event") == "engine_restart"]
    assert len(restarts) == 1
    assert restarts[0]["reason"] == "hung_tick"
    assert restarts[0]["n_inflight_failed"] == 1
    failed = [r for r in rows if r.get("event") == "request_failed"]
    assert any(r.get("reason") == "engine_restart" for r in failed)
    assert not [r for r in rows if r.get("event") == "recompile"]


def test_restart_budget_exhaustion_fails_engine(model):
    """Restarts are bounded: past ``max_restarts`` the engine dies loudly
    (every caller unblocked) instead of flapping forever."""
    cfg, params = model
    eng = DecodeEngine(cfg, params, n_slots=1, max_len=64,
                       tick_timeout_s=5.0, max_restarts=1,
                       restart_backoff_s=0.01)
    eng.n_restarts = 1                         # budget already spent
    assert eng._restart(reason="hung_tick") is False


# ---------------------------------------------------------------------------
# graceful drain
# ---------------------------------------------------------------------------

def test_drain_completes_in_flight_and_closes_admission(model):
    cfg, params = model
    eng = DecodeEngine(cfg, params, n_slots=1, max_len=64)
    eng.start()
    p = np.array([5, 6, 7], np.int32)
    h1 = eng.submit(p, SamplingParams(max_new_tokens=20, ignore_eos=True))
    h2 = eng.submit(p, SamplingParams(max_new_tokens=5, ignore_eos=True))
    deadline = time.monotonic() + 20
    while not h1.output_ids and time.monotonic() < deadline:
        time.sleep(0.01)
    summary = eng.drain(timeout=60.0)          # generous: everything lands
    assert summary["n_preempted"] == 0
    assert h1.result().finish_reason == "length"
    assert len(h1.output_ids) == 20
    assert h2.result().finish_reason == "length"   # queued work finishes too
    assert eng.draining
    with pytest.raises(EngineDrainingError):
        eng.submit(p, SamplingParams(max_new_tokens=2))
    eng.shutdown()


def test_drain_timeout_preempts_remainder(model):
    cfg, params = model

    class SlowClient(FaultHooks):
        def after_token(self, req, tok):
            time.sleep(0.01)       # the tiny CPU model would otherwise
                                   # finish 50 tokens inside any timeout

    eng = DecodeEngine(cfg, params, n_slots=1, max_len=64,
                       hooks=SlowClient())
    p = np.array([5, 6, 7], np.int32)
    h1 = eng.submit(p, SamplingParams(max_new_tokens=50, ignore_eos=True))
    h2 = eng.submit(p, SamplingParams(max_new_tokens=50, ignore_eos=True))
    for _ in range(3):
        assert eng.step()
    summary = eng.drain(timeout=0.05)          # nowhere near enough
    assert summary["n_preempted"] == 2
    for h in (h1, h2):
        assert h.done and h.finish_reason == "preempted"
        with pytest.raises(RuntimeError, match="preempted"):
            h.result(timeout=1)
    assert eng.scheduler.n_active == 0 and len(eng.queue) == 0


def test_serve_jsonl_streams_every_completed_line_across_drain(model,
                                                               tmp_path):
    """The zero-loss drain contract: a drain mid-batch still ends with
    one line per request on disk, every completed request's tokens
    intact (here the budget is generous, so ALL complete)."""
    from building_llm_from_scratch_tpu.serving.frontend import serve_jsonl

    cfg, params = model
    eng = DecodeEngine(cfg, params, n_slots=1, max_len=64, max_queue=8)
    eng.start()
    reqs = tmp_path / "reqs.jsonl"
    with open(reqs, "w") as f:
        for i in range(4):
            f.write(json.dumps({"prompt_ids": [5, 6, 7],
                                "max_new_tokens": 8 + i,
                                "ignore_eos": True, "seed": i}) + "\n")
    out = tmp_path / "results.jsonl"
    worker = threading.Thread(
        target=serve_jsonl, args=(eng, str(reqs), str(out), 8),
        daemon=True)
    worker.start()
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if out.exists() and out.read_text().count("\n") >= 1:
            break
        time.sleep(0.01)
    eng.drain(timeout=60.0)                    # mid-batch, generous budget
    worker.join(timeout=30)
    assert not worker.is_alive()
    lines = [json.loads(line) for line in open(out)]
    assert len(lines) == 4
    for i, rec in enumerate(lines):
        assert "error" not in rec, rec
        assert rec["finish_reason"] == "length"
        assert rec["n_tokens"] == 8 + i
    eng.shutdown()


# ---------------------------------------------------------------------------
# HTTP frontend hardening
# ---------------------------------------------------------------------------

def _post(port, body: bytes, timeout=30, path="/generate"):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    conn.request("POST", path, body=body,
                 headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    payload = json.loads(resp.read() or b"{}")
    headers = dict(resp.getheaders())
    conn.close()
    return resp.status, payload, headers


def test_http_hardening_and_drain_status(model):
    from building_llm_from_scratch_tpu.serving.frontend import (
        make_http_server,
    )

    cfg, params = model
    eng = DecodeEngine(cfg, params, n_slots=1, max_len=64)
    eng.start()
    server = make_http_server(eng, 0, host="127.0.0.1",
                              max_body_bytes=512)
    port = server.server_address[1]
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    try:
        # oversized body: 413 without reading it
        status, out, _ = _post(port, b"x" * 600)
        assert status == 413 and "limit" in out["error"]
        # malformed JSON: 400, not a handler traceback
        status, out, _ = _post(port, b"{not json")
        assert status == 400
        # well-formed JSON that is not an object: 400
        status, out, _ = _post(port, b"[1, 2, 3]")
        assert status == 400 and "object" in out["error"]
        # mistyped field: 400
        status, out, _ = _post(
            port, json.dumps({"prompt_ids": [5], "top_k": {}}).encode())
        assert status == 400
        # out-of-vocab prompt ids: 400
        status, out, _ = _post(
            port, json.dumps({"prompt_ids": [5, 4000],
                              "max_new_tokens": 2}).encode())
        assert status == 400 and "token ids" in out["error"]
        # healthy request still works
        status, out, _ = _post(
            port, json.dumps({"prompt_ids": [5, 6], "max_new_tokens": 2,
                              "ignore_eos": True}).encode())
        assert status == 200 and len(out["token_ids"]) == 2
        # healthz reflects drain state; draining POST -> 503 + Retry-After
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        conn.request("GET", "/healthz")
        health = json.loads(conn.getresponse().read())
        conn.close()
        assert health["status"] == "serving" and not health["draining"]
        eng.drain(timeout=5.0)
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        conn.request("GET", "/healthz")
        health = json.loads(conn.getresponse().read())
        conn.close()
        assert health["status"] == "draining" and health["draining"]
        status, out, headers = _post(
            port, json.dumps({"prompt_ids": [5, 6],
                              "max_new_tokens": 2}).encode())
        assert status == 503
        assert "Retry-After" in headers
    finally:
        server.shutdown()
        server.server_close()
        eng.shutdown()


def test_http_timeout_cancels_request_and_frees_slot(model):
    """A handler timeout must CANCEL the request: its slot stops decoding
    (today's bug: a timed-out handle kept decoding to max_new_tokens)."""
    from building_llm_from_scratch_tpu.serving.frontend import (
        make_http_server,
    )

    cfg, params = model

    class SlowClient(FaultHooks):
        def after_token(self, req, tok):
            time.sleep(0.01)       # stretch ticks so 50 tokens >> 0.2s

    eng = DecodeEngine(cfg, params, n_slots=1, max_len=64,
                       hooks=SlowClient())
    eng.warmup()                   # prepay compiles: the 2-token success
    eng.start()                    # path below must beat the 0.2s timeout
    server = make_http_server(eng, 0, host="127.0.0.1",
                              request_timeout_s=0.2)
    port = server.server_address[1]
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    try:
        status, out, _ = _post(
            port, json.dumps({"prompt_ids": [5, 6], "max_new_tokens": 50,
                              "ignore_eos": True}).encode())
        assert status == 504
        # the cancel retires the slot at the next tick boundary — long
        # before the 50-token budget would have
        deadline = time.monotonic() + 10
        while eng.scheduler.n_active and time.monotonic() < deadline:
            time.sleep(0.01)
        assert eng.scheduler.n_active == 0
        assert eng.requests_failed >= 1
        # and the engine keeps serving
        status, out, _ = _post(
            port, json.dumps({"prompt_ids": [5, 6], "max_new_tokens": 2,
                              "ignore_eos": True}).encode())
        assert status == 200 and len(out["token_ids"]) == 2
    finally:
        server.shutdown()
        server.server_close()
        eng.shutdown()


def test_cancel_queued_request_immediate(model):
    cfg, params = model
    eng = DecodeEngine(cfg, params, n_slots=1, max_len=64)
    p = np.array([5, 6], np.int32)
    h1 = eng.submit(p, SamplingParams(max_new_tokens=3, ignore_eos=True))
    h2 = eng.submit(p, SamplingParams(max_new_tokens=3, ignore_eos=True))
    assert eng.cancel(h2)                      # still queued: immediate
    assert h2.done and h2.finish_reason == "cancelled"
    eng.run_until_idle()
    assert h1.result().finish_reason == "length"
    assert eng.cancel(h1) is False             # already done
