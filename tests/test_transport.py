"""Transport fuzz tests (serving/transport.py): every way a peer can
misbehave on the wire — truncated frames, oversized length declarations,
garbage JSON, death mid-frame — must surface as a TYPED error on the
other side (``PeerGoneError`` / ``PeerTimeoutError`` /
``FrameTooLargeError`` / ``FrameCorruptError``), never a crash, a hang,
or an unbounded allocation; and the server must keep serving new
connections after any of them. Application errors must round-trip typed
(``QueueFullError`` raised in a handler re-raises as ``QueueFullError``
in the caller, retry hints intact). No jax anywhere — this tier runs in
milliseconds."""

import socket
import struct
import threading
import time

import pytest

from building_llm_from_scratch_tpu.serving.queue import (
    EngineDrainingError,
    QueueFullError,
    SLOShedError,
)
from building_llm_from_scratch_tpu.serving.request import RequestExpiredError
from building_llm_from_scratch_tpu.serving.transport import (
    DETACH,
    FrameCorruptError,
    FrameTooLargeError,
    PeerGoneError,
    PeerTimeoutError,
    RpcClient,
    RpcServer,
    TransportError,
    error_payload,
    raise_typed,
    recv_frame,
    send_frame,
)

_HDR = struct.Struct(">I")


@pytest.fixture
def sock_pair():
    a, b = socket.socketpair(socket.AF_UNIX, socket.SOCK_STREAM)
    yield a, b
    for s in (a, b):
        try:
            s.close()
        except OSError:
            pass


def echo_server(tmp_path, handler=None):
    path = str(tmp_path / "rpc.sock")

    def default(method, args, sock):
        if method == "echo":
            return args
        if method == "boom_queue":
            raise QueueFullError("queue full (remote)")
        if method == "boom_shed":
            raise SLOShedError("shed (remote)", retry_after_s=1.5)
        if method == "boom_drain":
            raise EngineDrainingError("draining (remote)",
                                      retry_after_s=0.5)
        if method == "boom_expired":
            raise RequestExpiredError("expired (remote)")
        if method == "boom_value":
            raise ValueError("bad arg (remote)")
        if method == "slow":
            time.sleep(args.get("s", 1.0))
            return "late"
        if method == "detach":
            return (DETACH, "detached")
        raise RuntimeError(f"no such method {method}")

    srv = RpcServer(path, handler or default)
    srv.start()
    return path, srv


# -- framing -----------------------------------------------------------------


def test_frame_roundtrip(sock_pair):
    a, b = sock_pair
    send_frame(a, {"x": 1, "y": ["a", None, 2.5]})
    assert recv_frame(b) == {"x": 1, "y": ["a", None, 2.5]}


def test_oversized_send_refused(sock_pair):
    a, _ = sock_pair
    with pytest.raises(FrameTooLargeError):
        send_frame(a, {"blob": "z" * 4096}, max_frame_bytes=1024)


def test_oversized_header_rejected_without_reading_payload(sock_pair):
    """A hostile 3GiB length declaration is rejected ON the header —
    the receiver never tries to read (or allocate) the payload, so the
    sender's unsent bytes are irrelevant."""
    a, b = sock_pair
    a.sendall(_HDR.pack(3 * 1024 ** 3) + b"only-a-few-bytes")
    with pytest.raises(FrameTooLargeError, match="declared"):
        recv_frame(b)


def test_truncated_frame_is_peer_gone(sock_pair):
    a, b = sock_pair
    a.sendall(_HDR.pack(100) + b"only 20 of 100 bytes")
    a.close()
    with pytest.raises(PeerGoneError, match="mid-frame"):
        recv_frame(b)


def test_truncated_header_is_peer_gone(sock_pair):
    a, b = sock_pair
    a.sendall(b"\x00\x00")                       # 2 of 4 header bytes
    a.close()
    with pytest.raises(PeerGoneError):
        recv_frame(b)


def test_clean_eof_is_peer_gone(sock_pair):
    a, b = sock_pair
    a.close()
    with pytest.raises(PeerGoneError):
        recv_frame(b)


@pytest.mark.parametrize("payload", [
    b"not json at all {{{",
    b"\xff\xfe\x00garbage bytes",
    b"[1, 2, 3]",                                # valid JSON, not an object
    b'"just a string"',
])
def test_garbage_payload_is_frame_corrupt(sock_pair, payload):
    a, b = sock_pair
    a.sendall(_HDR.pack(len(payload)) + payload)
    with pytest.raises(FrameCorruptError):
        recv_frame(b)


def test_recv_timeout_is_peer_timeout(sock_pair):
    _, b = sock_pair
    b.settimeout(0.05)
    with pytest.raises(PeerTimeoutError):
        recv_frame(b)


# -- typed application errors ------------------------------------------------


def test_error_payload_roundtrip_all_types():
    for exc in (QueueFullError("q"), SLOShedError("s", retry_after_s=2.0),
                EngineDrainingError("d", retry_after_s=0.1),
                RequestExpiredError("e"), ValueError("v"),
                RuntimeError("r")):
        with pytest.raises(type(exc)) as ei:
            raise_typed(error_payload(exc))
        assert str(exc) in str(ei.value)
    assert pytest.raises(SLOShedError, raise_typed,
                         error_payload(SLOShedError("s", retry_after_s=2.0))
                         ).value.retry_after_s == 2.0


def test_error_payload_subclass_maps_to_nearest_tag():
    class CustomQueueFull(QueueFullError):
        pass

    assert error_payload(CustomQueueFull("x"))["type"] == "queue_full"


def test_unknown_error_tag_degrades_to_runtime():
    with pytest.raises(RuntimeError, match="mystery"):
        raise_typed({"type": "from_the_future", "message": "mystery"})


# -- client/server -----------------------------------------------------------


def test_rpc_echo_and_typed_errors(tmp_path):
    path, srv = echo_server(tmp_path)
    try:
        c = RpcClient(path, timeout=5.0)
        assert c.call("echo", a=1, b="two") == {"a": 1, "b": "two"}
        with pytest.raises(QueueFullError):
            c.call("boom_queue")
        with pytest.raises(SLOShedError) as ei:
            c.call("boom_shed")
        assert ei.value.retry_after_s == 1.5
        with pytest.raises(EngineDrainingError) as ei:
            c.call("boom_drain")
        assert ei.value.retry_after_s == 0.5
        with pytest.raises(RequestExpiredError):
            c.call("boom_expired")
        with pytest.raises(ValueError):
            c.call("boom_value")
        # typed errors do NOT poison the connection — next call works
        assert c.call("echo", ok=True) == {"ok": True}
        c.close()
    finally:
        srv.stop()


def test_rpc_connect_to_nothing_is_peer_gone(tmp_path):
    with pytest.raises(PeerGoneError):
        RpcClient(str(tmp_path / "no-such.sock"))


def test_rpc_call_timeout_is_peer_timeout_and_poisons(tmp_path):
    path, srv = echo_server(tmp_path)
    try:
        c = RpcClient(path, timeout=0.1)
        with pytest.raises(PeerTimeoutError):
            c.call("slow", s=5.0)
        # the late response would desync correlation: connection closed
        with pytest.raises(PeerGoneError, match="client closed"):
            c.call("echo")
    finally:
        srv.stop()


def test_rpc_per_call_timeout_override(tmp_path):
    path, srv = echo_server(tmp_path)
    try:
        c = RpcClient(path, timeout=0.1)
        assert c.call("slow", rpc_timeout=5.0, s=0.3) == "late"
        c.close()
    finally:
        srv.stop()


def test_server_survives_garbage_connections(tmp_path):
    """Fuzz the server with every flavor of bad client; it must answer
    (or close) each without dying, and a well-behaved client connecting
    AFTERWARDS must still get served."""
    path, srv = echo_server(tmp_path)
    try:
        attacks = [
            b"",                                        # connect-and-leave
            b"\x00",                                    # truncated header
            _HDR.pack(3 * 1024 ** 3),                   # hostile length
            _HDR.pack(7) + b"garbage",                  # corrupt JSON
            _HDR.pack(6) + b'[1, 2]',                   # non-object frame
            _HDR.pack(2) + b"{}",                       # no method field
            _HDR.pack(100) + b"short",                  # death mid-frame
        ]
        for raw in attacks:
            s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            s.connect(path)
            if raw:
                s.sendall(raw)
            s.close()
        c = RpcClient(path, timeout=5.0)
        assert c.call("echo", alive=1) == {"alive": 1}
        c.close()
    finally:
        srv.stop()


def test_server_replies_typed_on_bad_frame_when_it_can(tmp_path):
    """A corrupt frame gets a best-effort error reply before the close —
    a confused-but-honest client learns why instead of seeing bare EOF."""
    path, srv = echo_server(tmp_path)
    try:
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.connect(path)
        s.sendall(_HDR.pack(7) + b"garbage")
        s.settimeout(5.0)
        resp = recv_frame(s)
        assert "err" in resp and "bad frame" in resp["err"]["message"]
        # ... and then the connection is closed (offset unrecoverable)
        with pytest.raises(PeerGoneError):
            recv_frame(s)
        s.close()
    finally:
        srv.stop()


def test_server_survives_client_death_mid_call(tmp_path):
    """Client dies between sending a request and reading the response;
    the connection thread must fold quietly and the server keep going."""
    hits = []

    def handler(method, args, sock):
        hits.append(method)
        time.sleep(0.2)
        return "ok"

    path, srv = echo_server(tmp_path, handler)
    try:
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.connect(path)
        send_frame(s, {"method": "die", "args": {}})
        s.close()                                     # gone before reply
        deadline = time.monotonic() + 5.0
        while "die" not in hits and time.monotonic() < deadline:
            time.sleep(0.01)
        c = RpcClient(path, timeout=5.0)
        assert c.call("after") == "ok"
        c.close()
    finally:
        srv.stop()


def test_detach_hands_socket_to_handler(tmp_path):
    """(DETACH, ack) replies the ack then stops the server read loop on
    that connection — the handler owns it for event pushes."""
    pushed = threading.Event()

    def handler(method, args, sock):
        if method == "subscribe":
            def pusher():
                time.sleep(0.05)
                send_frame(sock, {"ev": "tick"})
                pushed.set()
            threading.Thread(target=pusher, daemon=True).start()
            return (DETACH, "subscribed")
        return "ok"

    path, srv = echo_server(tmp_path, handler)
    try:
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.connect(path)
        s.settimeout(5.0)
        send_frame(s, {"method": "subscribe", "args": {}})
        assert recv_frame(s) == {"result": "subscribed"}
        assert recv_frame(s) == {"ev": "tick"}        # pushed, not polled
        assert pushed.wait(5.0)
        s.close()
    finally:
        srv.stop()


def test_transport_errors_are_runtime_errors():
    """Callers that only catch RuntimeError (the engine idiom) still see
    transport faults — the hierarchy keeps old except-clauses working."""
    for cls in (PeerGoneError, PeerTimeoutError, FrameTooLargeError,
                FrameCorruptError):
        assert issubclass(cls, TransportError)
        assert issubclass(cls, RuntimeError)
