"""Fused flash-attention kernel (ops/fused_attention.py) — TPU-only tests.

The kernel carries the reference's attention-dropout semantics
(/root/reference/Models/GPT2/GPT2.py:30-41) into the fused fast path. The
key test regenerates the kernel's exact keep-masks with a dump kernel and
checks forward AND backward against a dense same-mask oracle — proving the
forward and the two backward kernels all see bit-identical masks.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

needs_tpu = pytest.mark.skipif(jax.default_backend() != "tpu",
                               reason="pallas fused kernel needs a real TPU")


def _qkv(B=2, T=512, Hq=4, Hkv=4, D=64, dtype=jnp.bfloat16, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (B, T, Hq, D), dtype)
    k = jax.random.normal(ks[1], (B, T, Hkv, D), dtype)
    v = jax.random.normal(ks[2], (B, T, Hkv, D), dtype)
    return q, k, v


def _dump_masks(B, H, T, seed, rate, bq, bk):
    """Regenerate the kernel's keep masks tile-by-tile (same _keep_mask)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from building_llm_from_scratch_tpu.ops import fused_attention as fa

    n_q, n_kv = T // bq, T // bk

    def kernel(seed_ref, out_ref):
        b, h, i = pl.program_id(0), pl.program_id(1), pl.program_id(2)
        for j in range(n_kv):
            keep = fa._keep_mask(seed_ref, rate, b, h, i, j, n_q, n_kv,
                                 (bq, bk))
            out_ref[0, 0, :, pl.ds(j * bk, bk)] = keep.astype(jnp.int8)

    return pl.pallas_call(
        kernel,
        grid=(B, H, n_q),
        in_specs=[pl.BlockSpec((1, 2), lambda b, h, i: (0, 0),
                               memory_space=pltpu.SMEM)],
        out_specs=pl.BlockSpec((1, 1, bq, T), lambda b, h, i: (b, h, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B, H, T, T), jnp.int8),
    )(seed)


def _oracle(q, k, v, mask, rate):
    """Dense attention with an explicit keep mask (B,Hq,T,T)."""
    B, T, Hq, D = q.shape
    Hkv = k.shape[2]
    G = Hq // Hkv
    qh = q.transpose(0, 2, 1, 3)
    kh = jnp.repeat(k.transpose(0, 2, 1, 3), G, axis=1)
    vh = jnp.repeat(v.transpose(0, 2, 1, 3), G, axis=1)
    s = jnp.einsum("bhqd,bhkd->bhqk", qh, kh,
                   preferred_element_type=jnp.float32) / np.sqrt(D)
    causal = np.tril(np.ones((T, T), bool))
    s = jnp.where(causal, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    if mask is not None:
        p = p * mask / (1.0 - rate)
    out = jnp.einsum("bhqk,bhkd->bhqd", p.astype(vh.dtype), vh,
                     preferred_element_type=jnp.float32)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


@needs_tpu
def test_fused_matches_oracle_no_dropout():
    from building_llm_from_scratch_tpu.ops.fused_attention import (
        fused_causal_attention,
    )

    q, k, v = _qkv()
    want = np.asarray(_oracle(q, k, v, None, 0.0), np.float32)
    got = np.asarray(jax.jit(
        lambda q, k, v: fused_causal_attention(q, k, v, block_q=128,
                                               block_k=128))(q, k, v),
        np.float32)
    np.testing.assert_allclose(got, want, atol=2e-2, rtol=2e-2)


@needs_tpu
def test_fused_gradients_match_oracle_no_dropout():
    from building_llm_from_scratch_tpu.ops.fused_attention import (
        fused_causal_attention,
    )

    q, k, v = _qkv(Hq=8, Hkv=2)          # GQA: exercises the group-sum bwd

    def loss(fn, q, k, v):
        return jnp.sum(fn(q, k, v).astype(jnp.float32) ** 2)

    gw = jax.grad(lambda *a: loss(lambda q, k, v: _oracle(q, k, v, None, 0.0),
                                  *a), argnums=(0, 1, 2))(q, k, v)
    gf = jax.jit(jax.grad(
        lambda *a: loss(lambda q, k, v: fused_causal_attention(
            q, k, v, block_q=128, block_k=128), *a),
        argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(gf, gw):
        a32, b32 = np.asarray(a, np.float32), np.asarray(b, np.float32)
        scale = max(1.0, np.abs(b32).max())
        assert np.abs(a32 - b32).max() / scale < 2e-2


@needs_tpu
def test_fused_dropout_exact_vs_same_mask_oracle():
    """Dump the kernel's keep masks; forward and both backward kernels must
    match a dense oracle using those exact masks (fp32, tight tolerance)."""
    from building_llm_from_scratch_tpu.ops.fused_attention import (
        fused_causal_attention,
    )

    B, T, H, D, rate, blk = 2, 512, 4, 64, 0.1, 128
    q, k, v = _qkv(B=B, T=T, Hq=H, Hkv=H, D=D, dtype=jnp.float32)
    rng = jax.random.PRNGKey(7)
    seed = jax.random.bits(rng, (1, 2), jnp.uint32).astype(jnp.int32)
    mask = jnp.asarray(np.asarray(_dump_masks(B, H, T, seed, rate, blk, blk),
                                  np.float32))
    # keep fraction is Bernoulli(1-rate) over B*H*T*T/2 causal entries
    causal = np.tril(np.ones((T, T), bool))
    frac = np.asarray(mask)[:, :, causal].mean()
    assert abs(frac - (1 - rate)) < 5e-3

    fused = jax.jit(lambda q, k, v: fused_causal_attention(
        q, k, v, dropout_rate=rate, dropout_rng=rng, block_q=blk,
        block_k=blk))
    got = np.asarray(fused(q, k, v))
    want = np.asarray(_oracle(q, k, v, mask, rate))
    np.testing.assert_allclose(got, want, atol=2e-2, rtol=2e-2)

    def loss(fn, q, k, v):
        return jnp.sum(fn(q, k, v).astype(jnp.float32) ** 2)

    go = jax.grad(lambda *a: loss(
        lambda q, k, v: _oracle(q, k, v, mask, rate), *a),
        argnums=(0, 1, 2))(q, k, v)
    gf = jax.jit(jax.grad(lambda *a: loss(
        lambda q, k, v: fused_causal_attention(
            q, k, v, dropout_rate=rate, dropout_rng=rng, block_q=blk,
            block_k=blk), *a), argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(gf, go):
        a32, b32 = np.asarray(a, np.float32), np.asarray(b, np.float32)
        scale = max(1.0, np.abs(b32).max())
        assert np.abs(a32 - b32).max() / scale < 2e-2


@needs_tpu
def test_fused_dropout_deterministic_and_causal():
    from building_llm_from_scratch_tpu.ops.fused_attention import (
        fused_causal_attention,
    )

    q, k, v = _qkv(T=1024)
    rng = jax.random.PRNGKey(3)
    f = jax.jit(lambda q, k, v: fused_causal_attention(
        q, k, v, dropout_rate=0.1, dropout_rng=rng))
    o1 = np.asarray(f(q, k, v), np.float32)
    o2 = np.asarray(f(q, k, v), np.float32)
    assert np.array_equal(o1, o2)
    assert np.isfinite(o1).all()
    # causality: zeroing future kv leaves the first half untouched
    k2 = k.at[:, 512:].set(0.0)
    v2 = v.at[:, 512:].set(0.0)
    o3 = np.asarray(f(q, k2, v2), np.float32)
    np.testing.assert_array_equal(o1[:, :512], o3[:, :512])


@needs_tpu
def test_fused_different_rngs_give_different_masks():
    from building_llm_from_scratch_tpu.ops.fused_attention import (
        fused_causal_attention,
    )

    q, k, v = _qkv(T=512)
    f = functools.partial(fused_causal_attention, dropout_rate=0.5)
    o1 = np.asarray(f(q, k, v, dropout_rng=jax.random.PRNGKey(0)), np.float32)
    o2 = np.asarray(f(q, k, v, dropout_rng=jax.random.PRNGKey(1)), np.float32)
    assert not np.array_equal(o1, o2)


def test_supports_shape():
    from building_llm_from_scratch_tpu.ops.fused_attention import (
        supports_shape,
    )

    assert supports_shape(1024, 1024, 64)
    assert supports_shape(2048, 2048, 128)
    assert supports_shape(512, 512, 64)
    assert not supports_shape(1, 1024, 64)       # decode
    assert not supports_shape(1000, 1000, 64)    # not block-divisible
    assert not supports_shape(300, 300, 64)      # short but not lane-aligned
    assert not supports_shape(1024, 1024, 80)    # head dim not lane-friendly
    assert not supports_shape(128, 128, 64)      # too short to block
