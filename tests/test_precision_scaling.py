"""fp16 dynamic loss scaling + explicit reduce-dtype tests
(VERDICT round-1 weaknesses #3 and #4)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from building_llm_from_scratch_tpu.configs import ModelConfig
from building_llm_from_scratch_tpu.models import init_params
from building_llm_from_scratch_tpu.parallel import build_mesh_plan
from building_llm_from_scratch_tpu.training import (
    build_optimizer,
    get_policy,
    init_train_state,
    make_sharded_train_step,
    make_train_step,
)

TINY = ModelConfig(
    name="tiny", vocab_size=128, context_length=32, emb_dim=32, n_heads=2,
    n_layers=2, hidden_dim=64, n_kv_groups=2, norm="layernorm",
    positional="learned", activation="gelu", drop_rate=0.0, dtype="fp32")


def _batch(bs=8, T=32, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "inputs": rng.integers(0, TINY.vocab_size, (bs, T)).astype(np.int32),
        "targets": rng.integers(0, TINY.vocab_size, (bs, T)).astype(np.int32),
        "weights": np.ones((bs, T), np.float32),
    }


def _make(policy=None, peak_lr=5e-4, **kw):
    params = init_params(TINY, jax.random.PRNGKey(0))
    opt = build_optimizer(total_steps=60, peak_lr=peak_lr, warmup_steps=3)
    state = init_train_state(params, opt, jax.random.PRNGKey(0),
                             policy=policy)
    step = make_train_step(TINY, opt, policy=policy, **kw)
    return state, step


def test_fp16_policy_trains_and_converges():
    policy = get_policy("fp16")
    state, step = _make(policy, peak_lr=5e-3)
    assert float(state["loss_scale"]) == 2.0 ** 15
    losses = []
    for i in range(25):
        state, m = step(state, _batch(seed=0))
        losses.append(float(m["loss"]))
        assert int(m["skipped"]) == 0
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] - 0.5, "fp16 training did not converge"


def test_fp16_overflow_skips_step_and_halves_scale():
    policy = get_policy("fp16")
    state, step = _make(policy)
    # inf logits -> inf loss: the step must NOT touch params, and the scale
    # must halve (the reference's fp16 policy would corrupt params to NaN)
    state["trainable"]["head"]["weight"] = (
        state["trainable"]["head"]["weight"] + 1e5)
    before = np.asarray(state["trainable"]["blocks"]["attn"]["wq"])
    state, m = step(state, _batch())
    assert int(m["skipped"]) == 1
    assert float(m["loss_scale"]) == 2.0 ** 14
    np.testing.assert_array_equal(
        np.asarray(state["trainable"]["blocks"]["attn"]["wq"]), before)


def test_fp16_scale_grows_after_finite_streak():
    policy = dataclasses.replace(get_policy("fp16"),
                                 init_loss_scale=8.0,
                                 scale_growth_interval=2)
    state, step = _make(policy)
    state, m = step(state, _batch())
    assert float(m["loss_scale"]) == 8.0          # streak of 1: no growth
    state, m = step(state, _batch())
    assert float(m["loss_scale"]) == 16.0         # streak of 2: doubled


def test_bf16_hybrid_psum_runs_in_bf16():
    """The gradient all-reduce of the shard_map step must carry bf16
    operands under bf16_hybrid — asserted on the traced jaxpr."""
    policy = get_policy("bf16_hybrid")
    plan = build_mesh_plan("dp")
    params = init_params(TINY, jax.random.PRNGKey(0))
    opt = build_optimizer(total_steps=50)
    state = init_train_state(params, opt, jax.random.PRNGKey(0),
                             policy=policy)
    step = make_sharded_train_step(TINY, opt, plan, policy=policy, jit=False)
    jaxpr = str(jax.make_jaxpr(step)(state, _batch()))
    psum_lines = [ln for ln in jaxpr.splitlines() if "psum" in ln]
    assert psum_lines, "no psum in the sharded train step"
    grad_psums = [ln for ln in psum_lines if "bf16[" in ln]
    assert grad_psums, (
        "bf16_hybrid sharded step reduces no gradients in bf16:\n"
        + "\n".join(psum_lines))


@pytest.mark.parametrize("mode", ["dp", "fsdp", "zero1"])
def test_sharded_step_matches_unsharded_numerics(mode):
    """shard_map step == plain jit step (fp32 reduce: exact math modulo
    reduction order) — for every mode the explicit step supports
    (fsdp/zero1 added in round 5, VERDICT weak #4)."""
    plan = build_mesh_plan(mode)
    params = init_params(TINY, jax.random.PRNGKey(0))
    opt = build_optimizer(total_steps=50)

    s1 = init_train_state(params, opt, jax.random.PRNGKey(0))
    step1 = make_train_step(TINY, opt)
    s2 = init_train_state(params, opt, jax.random.PRNGKey(0))
    s2 = plan.shard_state(s2)
    step2 = make_sharded_train_step(TINY, opt, plan)

    batch = _batch(bs=8)
    for _ in range(3):
        s1, m1 = step1(s1, batch)
        s2, m2 = step2(s2, plan.shard_batch(batch))
    assert abs(float(m1["loss"]) - float(m2["loss"])) < 1e-5
    for a, b in zip(jax.tree_util.tree_leaves(s1["trainable"]),
                    jax.tree_util.tree_leaves(s2["trainable"])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5, rtol=1e-5)


def test_fsdp_hybrid_comms_dtypes_and_state_stay_sharded():
    """fsdp + bf16_hybrid (round-4 VERDICT weak #4): the gradient
    reduce-scatter carries bf16 operands, the param all-gather moves
    compute-dtype bytes, and params + adam moments remain data-sharded
    after a real step."""
    from jax.sharding import PartitionSpec as P

    policy = get_policy("bf16_hybrid")
    plan = build_mesh_plan("fsdp")
    params = init_params(TINY, jax.random.PRNGKey(0))
    opt = build_optimizer(total_steps=50)
    state = init_train_state(params, opt, jax.random.PRNGKey(0),
                             policy=policy)
    state = plan.shard_state(state)

    # jaxpr-level: the reduce-scatter runs in bf16 (reduce_dtype)
    step_nojit = make_sharded_train_step(TINY, opt, plan, policy=policy,
                                         jit=False)
    jaxpr = str(jax.make_jaxpr(step_nojit)(state, plan.shard_batch(_batch())))
    rs_lines = [ln for ln in jaxpr.splitlines()
                if "psum_scatter" in ln or "reduce_scatter" in ln]
    assert rs_lines, "fsdp hybrid step contains no reduce-scatter"
    assert any("bf16[" in ln for ln in rs_lines), (
        "fsdp bf16_hybrid reduce-scatter does not carry bf16:\n"
        + "\n".join(rs_lines))

    # executed: one real step keeps the fsdp placements
    step = make_sharded_train_step(TINY, opt, plan, policy=policy)
    state, m = step(state, plan.shard_batch(_batch()))
    assert np.isfinite(float(m["loss"]))
    wq = state["trainable"]["blocks"]["attn"]["wq"]
    assert wq.sharding.spec != P(), "fsdp params were gathered to replicated"
    mu_leaves = [
        leaf for path, leaf in
        jax.tree_util.tree_flatten_with_path(state["opt_state"])[0]
        if any(getattr(e, "name", "") == "mu" for e in path)
        and hasattr(leaf, "sharding") and np.ndim(leaf) >= 2]
    assert mu_leaves and any(l.sharding.spec != P() for l in mu_leaves), (
        "fsdp adam moments were silently replicated")


def test_zero1_hybrid_keeps_opt_state_sharded_after_step():
    """zero1 + bf16_hybrid through the explicit step: adam moments stay
    sharded (round-2 ADVICE medium #1 under the new routing)."""
    from jax.sharding import PartitionSpec as P

    policy = get_policy("bf16_hybrid")
    plan = build_mesh_plan("zero1")
    params = init_params(TINY, jax.random.PRNGKey(0))
    opt = build_optimizer(total_steps=50)
    state = init_train_state(params, opt, jax.random.PRNGKey(0),
                             policy=policy)
    state = plan.shard_state(state)
    step = make_sharded_train_step(TINY, opt, plan, policy=policy)
    for seed in range(2):
        state, m = step(state, plan.shard_batch(_batch(seed=seed)))
    assert np.isfinite(float(m["loss"]))
    # params replicated (zero1), moments sharded
    assert state["trainable"]["blocks"]["attn"]["wq"].sharding.spec == P()
    mu_leaves = [
        leaf for path, leaf in
        jax.tree_util.tree_flatten_with_path(state["opt_state"])[0]
        if any(getattr(e, "name", "") == "mu" for e in path)
        and hasattr(leaf, "sharding") and np.ndim(leaf) >= 2]
    assert mu_leaves and any(l.sharding.spec != P() for l in mu_leaves), (
        "zero1 adam moments were silently replicated"
    )


def test_hybrid_rejected_for_tp_modes():
    """tp + bf16_hybrid must fail fast: flag-time (args) and step-build
    time (make_sharded_train_step)."""
    plan = build_mesh_plan("tp", tp=2)
    opt = build_optimizer(total_steps=50)
    with pytest.raises(ValueError, match="dp/fsdp/zero1"):
        make_sharded_train_step(TINY, opt, plan,
                                policy=get_policy("bf16_hybrid"))


def test_bf16_hybrid_trains_via_trainer_path():
    """End-to-end: Trainer picks the shard_map step for bf16_hybrid + dp."""
    from building_llm_from_scratch_tpu.training.trainer import Trainer
    from building_llm_from_scratch_tpu.data.pretrain import PretrainLoader
    from building_llm_from_scratch_tpu.data.tokenizers import ByteTokenizer

    cfg = TINY.replace(vocab_size=300)
    tok = ByteTokenizer()
    loader = PretrainLoader(tok, batch_size=8, max_length=cfg.context_length)
    plan = build_mesh_plan("dp")
    params = init_params(cfg, jax.random.PRNGKey(0))
    tr = Trainer(cfg, params, tok, loader, policy=get_policy("bf16_hybrid"),
                 plan=plan, eval_freq=1000, print_sample_iter=1000,
                 save_ckpt_freq=1000)
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        path = f"{d}/c.txt"
        open(path, "w").write("the quick brown fox jumps over the dog. " * 80)
        tr.train_model([path], n_epochs=1)
    assert tr.global_step > 0
    # the chosen step really is the shard_map one (psum in its jaxpr)
    assert tr.train_step.__name__ == "train_step"
