"""Unit tests for the op layer: norms, activations, RoPE, attention.

Numerics are validated against torch (CPU) where the reference semantics are
torch-defined, and against hand-computed values elsewhere.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from building_llm_from_scratch_tpu.configs import RopeScaling
from building_llm_from_scratch_tpu.ops import (
    apply_rope,
    causal_attention,
    gelu,
    layernorm,
    precompute_rope_params,
    rmsnorm,
    silu,
)


def test_layernorm_matches_torch():
    torch = pytest.importorskip("torch")
    x = np.random.randn(2, 5, 16).astype(np.float32)
    scale = np.random.randn(16).astype(np.float32)
    bias = np.random.randn(16).astype(np.float32)
    ours = layernorm(jnp.asarray(x), jnp.asarray(scale), jnp.asarray(bias))
    theirs = torch.nn.functional.layer_norm(
        torch.from_numpy(x), (16,), torch.from_numpy(scale),
        torch.from_numpy(bias))
    np.testing.assert_allclose(np.asarray(ours), theirs.numpy(),
                               rtol=1e-5, atol=1e-5)


def test_rmsnorm_matches_torch():
    torch = pytest.importorskip("torch")
    x = np.random.randn(2, 5, 16).astype(np.float32)
    scale = np.random.randn(16).astype(np.float32)
    ours = rmsnorm(jnp.asarray(x), jnp.asarray(scale), eps=1e-5)
    theirs = torch.nn.functional.rms_norm(
        torch.from_numpy(x), (16,), torch.from_numpy(scale), eps=1e-5)
    np.testing.assert_allclose(np.asarray(ours), theirs.numpy(),
                               rtol=1e-5, atol=1e-5)


def test_silu_matches_torch():
    torch = pytest.importorskip("torch")
    x = np.random.randn(64).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(silu(jnp.asarray(x))),
        torch.nn.functional.silu(torch.from_numpy(x)).numpy(),
        rtol=1e-6, atol=1e-6)


def test_gelu_matches_torch():
    torch = pytest.importorskip("torch")
    x = np.random.randn(64).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(gelu(jnp.asarray(x))),
        torch.nn.functional.gelu(torch.from_numpy(x)).numpy(),
        rtol=1e-5, atol=1e-5)


def test_rope_tables_match_hf_llama31_smoothing():
    """The llama3.1 frequency-smoothing formula vs an independent numpy
    transcription of the published algorithm."""
    head_dim, theta, ctx = 64, 500_000.0, 256
    sc = RopeScaling(factor=8.0, low_freq_factor=1.0, high_freq_factor=4.0,
                     original_context_length=8192)
    cos, sin = precompute_rope_params(head_dim, theta, ctx, sc)

    inv = 1.0 / (theta ** (np.arange(0, head_dim, 2) / head_dim))
    wavelen = 2 * np.pi / inv
    out = np.where(wavelen > sc.original_context_length / sc.low_freq_factor,
                   inv / sc.factor, inv)
    smooth = ((sc.original_context_length / wavelen - sc.low_freq_factor)
              / (sc.high_freq_factor - sc.low_freq_factor))
    smoothed = (1 - smooth) * (inv / sc.factor) + smooth * inv
    mid = ((wavelen <= sc.original_context_length / sc.low_freq_factor)
           & (wavelen >= sc.original_context_length / sc.high_freq_factor))
    out = np.where(mid, smoothed, out)
    pos = np.arange(ctx)[:, None] * out[None, :]
    angles = np.concatenate([pos, pos], axis=-1)
    # fp32 angle accumulation vs numpy's fp64: tolerance covers trig of
    # angles up to ~ctx radians rounded at fp32
    np.testing.assert_allclose(np.asarray(cos), np.cos(angles), atol=1e-3)
    np.testing.assert_allclose(np.asarray(sin), np.sin(angles), atol=1e-3)


def test_rope_rotation_preserves_norm():
    cos, sin = precompute_rope_params(32, 10_000.0, 64)
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 10, 4, 32))
    y = apply_rope(x, cos, sin)
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(x), axis=-1),
        np.linalg.norm(np.asarray(y), axis=-1), rtol=1e-4, atol=1e-5)


def test_rope_position_zero_is_identity():
    cos, sin = precompute_rope_params(32, 10_000.0, 64)
    x = jax.random.normal(jax.random.PRNGKey(1), (1, 1, 2, 32))
    y = apply_rope(x, cos, sin)
    np.testing.assert_allclose(np.asarray(x), np.asarray(y), rtol=1e-5,
                               atol=1e-6)


def test_causal_attention_matches_torch_sdpa():
    torch = pytest.importorskip("torch")
    B, T, H, D = 2, 12, 4, 16
    q, k, v = [np.random.randn(B, T, H, D).astype(np.float32)
               for _ in range(3)]
    ours = causal_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    ref = torch.nn.functional.scaled_dot_product_attention(
        torch.from_numpy(q).permute(0, 2, 1, 3),
        torch.from_numpy(k).permute(0, 2, 1, 3),
        torch.from_numpy(v).permute(0, 2, 1, 3),
        is_causal=True).permute(0, 2, 1, 3)
    np.testing.assert_allclose(np.asarray(ours), ref.numpy(), rtol=1e-4,
                               atol=1e-5)


def test_gqa_matches_repeated_kv():
    """GQA broadcast == explicitly repeating kv heads (the reference's
    repeat_interleave approach, Llama3.py:133-137)."""
    B, T, Hq, Hkv, D = 2, 8, 8, 2, 16
    key = jax.random.PRNGKey(0)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, T, Hq, D))
    k = jax.random.normal(kk, (B, T, Hkv, D))
    v = jax.random.normal(kv, (B, T, Hkv, D))
    ours = causal_attention(q, k, v)
    k_rep = jnp.repeat(k, Hq // Hkv, axis=2)
    v_rep = jnp.repeat(v, Hq // Hkv, axis=2)
    full = causal_attention(q, k_rep, v_rep)
    np.testing.assert_allclose(np.asarray(ours), np.asarray(full), rtol=1e-5,
                               atol=1e-5)


def test_causal_mask_blocks_future():
    """Changing future tokens must not change past outputs."""
    B, T, H, D = 1, 6, 2, 8
    key = jax.random.PRNGKey(2)
    q = jax.random.normal(key, (B, T, H, D))
    k, v = q + 1.0, q - 0.5
    base = causal_attention(q, k, v)
    k2 = k.at[:, -1].set(99.0)
    v2 = v.at[:, -1].set(-99.0)
    pert = causal_attention(q, k2, v2)
    np.testing.assert_allclose(np.asarray(base[:, :-1]),
                               np.asarray(pert[:, :-1]), rtol=1e-5, atol=1e-6)


def test_cached_attention_matches_full():
    """Decode-style attention with kv_length/q_positions == full attention."""
    B, T, H, D = 1, 8, 2, 8
    key = jax.random.PRNGKey(3)
    q = jax.random.normal(key, (B, T, H, D))
    k, v = q * 0.5, q * 2.0
    full = causal_attention(q, k, v)
    # last token only, attending over a cache holding all T positions
    last = causal_attention(
        q[:, -1:], k, v,
        q_positions=jnp.array([T - 1]),
        kv_length=jnp.array([T]))
    np.testing.assert_allclose(np.asarray(full[:, -1:]), np.asarray(last),
                               rtol=1e-5, atol=1e-6)
