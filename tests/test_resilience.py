"""Fault-tolerance unit/integration tests (training/resilience.py):
checkpoint integrity + fallback, retention GC, graceful stop, the loss
watchdog, clear manifest errors, orphaned-staging cleanup, and the
data-cursor resume path."""

import json
import os
import signal
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from building_llm_from_scratch_tpu.configs import get_config
from building_llm_from_scratch_tpu.data import ByteTokenizer, PretrainLoader
from building_llm_from_scratch_tpu.models import init_params
from building_llm_from_scratch_tpu.training import Trainer
from building_llm_from_scratch_tpu.training.checkpoint import (
    checkpoint_metadata,
    load_checkpoint,
    save_checkpoint,
)
from building_llm_from_scratch_tpu.training.resilience import (
    GracefulStopper,
    LossWatchdog,
    TrainingDivergedError,
    find_latest_valid_checkpoint,
    list_checkpoints,
    prune_checkpoints,
    resolve_resume,
    validate_checkpoint,
)

# ---------------------------------------------------------------------------
# Helpers
# ---------------------------------------------------------------------------

STATE = {"w": jnp.arange(64, dtype=jnp.float32).reshape(8, 8),
         "b": jnp.ones((8,), jnp.float32)}


def _save(out_dir, tag, step):
    return save_checkpoint(os.path.join(out_dir, f"model_pg_{tag}"), STATE,
                           extra_metadata={"global_step": step})


def _first_shard(ckpt_dir):
    with open(os.path.join(ckpt_dir, "manifest.json")) as f:
        manifest = json.load(f)
    return os.path.join(ckpt_dir, manifest["leaves"][0]["shards"][0]["file"])


def _flip_byte(path, offset=-1):
    with open(path, "r+b") as f:
        f.seek(offset, os.SEEK_END)
        byte = f.read(1)
        f.seek(-1, os.SEEK_CUR)
        f.write(bytes([byte[0] ^ 0xFF]))


def tiny_cfg():
    # smaller than --debug for fast compiles: these tests train real steps
    return get_config("GPT2", "124M", debug=True).replace(
        emb_dim=32, hidden_dim=64, n_layers=2, n_heads=2, vocab_size=257,
        context_length=16)


def make_trainer(tmp_path, params, **kw):
    tok = ByteTokenizer()
    loader = PretrainLoader(tok, batch_size=2, max_length=16)
    defaults = dict(output_dir=str(tmp_path / "out"), eval_freq=4,
                    print_sample_iter=100000, save_ckpt_freq=100000,
                    warmup_steps=2, show_progress=False)
    defaults.update(kw)
    return Trainer(tiny_cfg(), params, tok, loader, **defaults)


# ---------------------------------------------------------------------------
# Checkpoint integrity: checksums, truncation, back-compat, fallback
# ---------------------------------------------------------------------------

def test_manifest_records_bytes_and_sha256(tmp_path):
    ck = _save(str(tmp_path), "10", 10)
    with open(os.path.join(ck, "manifest.json")) as f:
        manifest = json.load(f)
    for leaf in manifest["leaves"]:
        for sh in leaf["shards"]:
            assert sh["bytes"] == os.path.getsize(os.path.join(ck, sh["file"]))
            assert len(sh["sha256"]) == 64
    assert validate_checkpoint(ck) is None


def test_validate_rejects_bitflipped_shard(tmp_path):
    ck = _save(str(tmp_path), "10", 10)
    _flip_byte(_first_shard(ck))
    reason = validate_checkpoint(ck)
    assert reason is not None and "sha256" in reason


def test_validate_rejects_truncated_shard(tmp_path):
    ck = _save(str(tmp_path), "10", 10)
    shard = _first_shard(ck)
    os.truncate(shard, os.path.getsize(shard) - 8)
    reason = validate_checkpoint(ck)
    assert reason is not None and "truncated" in reason


def test_validate_rejects_missing_shard_and_manifest(tmp_path):
    ck = _save(str(tmp_path), "10", 10)
    os.remove(_first_shard(ck))
    assert "missing" in validate_checkpoint(ck)
    assert "manifest" in validate_checkpoint(str(tmp_path / "nope"))


def test_validate_accepts_old_manifest_without_checksums(tmp_path):
    """Checkpoints written before the integrity fields existed must keep
    validating (existence-only)."""
    ck = _save(str(tmp_path), "10", 10)
    mpath = os.path.join(ck, "manifest.json")
    with open(mpath) as f:
        manifest = json.load(f)
    for leaf in manifest["leaves"]:
        for sh in leaf["shards"]:
            del sh["bytes"], sh["sha256"]
    with open(mpath, "w") as f:
        json.dump(manifest, f)
    assert validate_checkpoint(ck) is None
    _flip_byte(_first_shard(ck))          # undetectable without checksums
    assert validate_checkpoint(ck) is None


def test_auto_resume_falls_back_past_corrupt_latest(tmp_path):
    """The acceptance case: a corrupt latest checkpoint must not crash the
    resume — discovery falls back to the previous VALID one, loudly."""
    out = str(tmp_path)
    _save(out, "10", 10)
    ck20 = _save(out, "20", 20)
    assert find_latest_valid_checkpoint(out) == ck20
    _flip_byte(_first_shard(ck20))
    assert find_latest_valid_checkpoint(out).endswith("model_pg_10")
    # resolve_resume("auto") routes through the same fallback
    assert resolve_resume("auto", None, out).endswith("model_pg_10")


def test_list_checkpoints_orders_by_step_and_skips_junk(tmp_path):
    out = str(tmp_path)
    _save(out, "5", 5)
    _save(out, "interrupted", 12)
    _save(out, "final", 8)
    (tmp_path / "model_pg_final.npz").write_bytes(b"not a dir")
    os.makedirs(tmp_path / "model_pg_junk")          # no manifest
    found = list_checkpoints(out)
    assert [s for s, _ in found] == [5, 8, 12]
    assert found[-1][1].endswith("model_pg_interrupted")


def test_resolve_resume_modes(tmp_path):
    out = str(tmp_path)
    assert resolve_resume("off", None, out) is None
    assert resolve_resume("auto", None, out) is None          # nothing there
    ck = _save(out, "10", 10)
    assert resolve_resume("auto", None, out) == ck
    assert resolve_resume("off", None, out) is None
    assert resolve_resume("auto", "/explicit/wins", out) == "/explicit/wins"
    assert resolve_resume(ck, None, str(tmp_path / "empty")) == ck


# ---------------------------------------------------------------------------
# Retention GC
# ---------------------------------------------------------------------------

def test_prune_keeps_newest_and_protected_tags(tmp_path):
    out = str(tmp_path)
    for step in (1, 2, 3, 4, 5):
        _save(out, str(step), step)
    _save(out, "interrupted", 3)
    _save(out, "final", 5)
    removed = prune_checkpoints(out, keep=2)
    assert sorted(os.path.basename(p) for p in removed) == [
        "model_pg_1", "model_pg_2", "model_pg_3"]
    left = sorted(n for n in os.listdir(out) if n.startswith("model_pg_"))
    assert left == ["model_pg_4", "model_pg_5", "model_pg_final",
                    "model_pg_interrupted"]
    assert prune_checkpoints(out, keep=2) == []               # idempotent
    with pytest.raises(ValueError, match="keep"):
        prune_checkpoints(out, keep=0)


def test_trainer_keep_ckpts_bounds_disk(tmp_path):
    """Acceptance: --keep_ckpts 2 leaves at most 2 step-tagged dirs after a
    run with >= 5 saves (interrupted/final tags untouched)."""
    cfg = tiny_cfg()
    datafile = tmp_path / "c.txt"
    datafile.write_text("the quick brown fox jumps over the lazy dog. " * 8)
    trainer = make_trainer(tmp_path, init_params(cfg, jax.random.PRNGKey(0)),
                           save_ckpt_freq=1, keep_ckpts=2)
    trainer.train_model([str(datafile)], n_epochs=1, start_context="the ")
    assert trainer.global_step >= 5                  # >= 5 saves happened
    out = str(tmp_path / "out")
    tagged = sorted(int(n[len("model_pg_"):]) for n in os.listdir(out)
                    if n[len("model_pg_"):].isdigit())
    assert len(tagged) <= 2
    assert tagged[-1] == trainer.global_step         # newest never pruned
    # step-tagged checkpoints carry the data cursor for mid-epoch resume
    meta = checkpoint_metadata(os.path.join(out, f"model_pg_{tagged[-1]}"))
    assert meta["cursor"] == {"epoch": 0, "file_index": 0, "file": "c.txt",
                              "batch_index": trainer.global_step}


# ---------------------------------------------------------------------------
# Clear manifest errors + orphaned staging cleanup (satellite)
# ---------------------------------------------------------------------------

def test_load_missing_manifest_raises_single_clear_error(tmp_path):
    empty = tmp_path / "model_pg_7"
    empty.mkdir()
    with pytest.raises(ValueError, match="manifest.json is missing"):
        load_checkpoint(str(empty), dict(STATE))
    with pytest.raises(ValueError, match=str(empty)):
        checkpoint_metadata(str(empty))


def test_load_malformed_manifest_raises_single_clear_error(tmp_path):
    ck = tmp_path / "model_pg_7"
    ck.mkdir()
    (ck / "manifest.json").write_text("{not json")
    with pytest.raises(ValueError, match="malformed"):
        load_checkpoint(str(ck), dict(STATE))
    (ck / "manifest.json").write_text('{"no_leaves": 1}')
    with pytest.raises(ValueError, match="leaves"):
        checkpoint_metadata(str(ck))


def test_validate_never_raises_on_structural_corruption(tmp_path):
    """validate_checkpoint exists to let --resume auto fall back past
    corrupt checkpoints, so ANY corruption shape must come back as a
    reason string, never an exception."""
    ck = tmp_path / "model_pg_9"
    ck.mkdir()
    for payload in ('{"leaves": [42]}',                    # leaf not a dict
                    '{"leaves": [{"shards": [{}]}]}',      # shard sans file
                    '{"leaves": "nope"}', "{not json"):
        (ck / "manifest.json").write_text(payload)
        reason = validate_checkpoint(str(ck))
        assert isinstance(reason, str) and reason, payload
    # and discovery walks past it instead of crashing
    good = _save(str(tmp_path), "5", 5)
    assert find_latest_valid_checkpoint(str(tmp_path)) == good


def test_load_cleans_orphaned_staging_dirs(tmp_path):
    ck = _save(str(tmp_path), "10", 10)
    for suffix in (".tmp", ".old"):
        os.makedirs(ck + suffix)
        with open(os.path.join(ck + suffix, "leaf_junk.npy"), "w") as f:
            f.write("stale")
    restored = load_checkpoint(ck, jax.tree_util.tree_map(jnp.zeros_like,
                                                          STATE))
    np.testing.assert_array_equal(np.asarray(restored["w"]),
                                  np.asarray(STATE["w"]))
    assert not os.path.exists(ck + ".tmp")
    assert not os.path.exists(ck + ".old")


def test_interrupted_commit_window_still_resumable(tmp_path):
    """A save preempted between the two commit renames leaves only .tmp —
    discovery and load must still see it (via _resolve_ckpt_dir)."""
    ck = _save(str(tmp_path), "10", 10)
    os.rename(ck, ck + ".tmp")
    assert validate_checkpoint(ck) is None
    assert find_latest_valid_checkpoint(str(tmp_path)) == ck
    restored = load_checkpoint(ck, jax.tree_util.tree_map(jnp.zeros_like,
                                                          STATE))
    np.testing.assert_array_equal(np.asarray(restored["b"]), np.ones((8,)))


# ---------------------------------------------------------------------------
# Graceful stop + loss watchdog
# ---------------------------------------------------------------------------

def test_graceful_stopper_signal_sets_flag_and_restores_handlers():
    before_term = signal.getsignal(signal.SIGTERM)
    stopper = GracefulStopper()
    with stopper:
        assert not stopper.should_stop()
        if signal.SIGTERM not in stopper._previous:
            pytest.skip("signal handlers unavailable (non-main thread)")
        os.kill(os.getpid(), signal.SIGTERM)
        deadline = time.monotonic() + 5
        while not stopper.requested and time.monotonic() < deadline:
            time.sleep(0.01)
        assert stopper.requested and stopper.should_stop()
    assert signal.getsignal(signal.SIGTERM) == before_term


def test_watchdog_halts_on_nonfinite_and_spike():
    wd = LossWatchdog(spike_factor=5.0, window=10, min_history=4)
    for i in range(8):
        wd.observe(i, 2.0 + 0.01 * i)
    with pytest.raises(TrainingDivergedError, match="spiked"):
        wd.observe(9, 50.0)
    with pytest.raises(TrainingDivergedError, match="non-finite"):
        wd.observe(10, float("nan"))
    # warmup noise (short history) never trips the spike check
    wd2 = LossWatchdog(spike_factor=5.0, min_history=4)
    wd2.observe(0, 1.0)
    wd2.observe(1, 100.0)


@pytest.mark.slow
def test_trainer_watchdog_halts_on_diverged_loss(tmp_path):
    """End-to-end: a poisoned step metric stops training with a diagnostic
    instead of running to completion."""
    cfg = tiny_cfg()
    datafile = tmp_path / "c.txt"
    datafile.write_text("pack my box with five dozen liquor jugs. " * 12)
    trainer = make_trainer(
        tmp_path, init_params(cfg, jax.random.PRNGKey(0)), eval_freq=2,
        watchdog=LossWatchdog(spike_factor=5.0, min_history=1,
                              check_finite=True))
    real_setup = trainer._setup

    def poisoned_setup(total_steps):
        real_setup(total_steps)
        real_step = trainer.train_step

        def bad_step(state, batch):
            state, metrics = real_step(state, batch)
            if int(state["step"]) >= 4:
                metrics = dict(metrics, loss=jnp.asarray(float("inf")))
            return state, metrics

        trainer.train_step = bad_step

    trainer._setup = poisoned_setup
    with pytest.raises(TrainingDivergedError, match="non-finite"):
        trainer.train_model([str(datafile)], n_epochs=1, start_context="a")


# ---------------------------------------------------------------------------
# Interrupted checkpoint + data-cursor resume (satellite + tentpole)
# ---------------------------------------------------------------------------

class InterruptingLoader(PretrainLoader):
    """Raises KeyboardInterrupt after yielding N training batches — the
    Ctrl-C-mid-epoch fixture."""

    def __init__(self, *a, interrupt_after=3, **kw):
        super().__init__(*a, **kw)
        self.remaining = interrupt_after

    def batches(self, dataset, **kw):
        inner = super().batches(dataset, **kw)

        def gen():
            for b in inner:
                if self.remaining <= 0:
                    raise KeyboardInterrupt
                self.remaining -= 1
                yield b
        return gen()


@pytest.mark.slow
def test_keyboard_interrupt_checkpoint_roundtrips_and_resumes(tmp_path):
    """Satellite: KeyboardInterrupt mid-_run_epoch writes a checkpoint that
    round-trips through load_checkpoint and resumes at the right step."""
    cfg = tiny_cfg()
    datafile = tmp_path / "c.txt"
    datafile.write_text("the quick brown fox jumps over the lazy dog. " * 12)
    tok = ByteTokenizer()
    loader = InterruptingLoader(tok, batch_size=2, max_length=16,
                                interrupt_after=3)
    trainer = Trainer(cfg, init_params(cfg, jax.random.PRNGKey(0)), tok,
                      loader, output_dir=str(tmp_path / "out"),
                      eval_freq=100000, print_sample_iter=100000,
                      save_ckpt_freq=100000, warmup_steps=2,
                      show_progress=False)
    with pytest.raises(KeyboardInterrupt):
        trainer.train_model([str(datafile)], n_epochs=1, start_context="a")
    assert trainer.global_step == 3
    ck = os.path.join(str(tmp_path / "out"), "model_pg_interrupted")
    meta = checkpoint_metadata(ck)
    assert meta["global_step"] == 3
    assert meta["cursor"] == {"epoch": 0, "file_index": 0, "file": "c.txt",
                              "batch_index": 3}

    resumed = make_trainer(tmp_path, init_params(cfg, jax.random.PRNGKey(9)),
                           resume_from=ck)
    resumed._setup(10)
    assert resumed.global_step == 3
    assert int(resumed.state["step"]) == 3
    assert resumed._resume_cursor == meta["cursor"]


class StopAfter(GracefulStopper):
    """Deterministic stand-in for a SIGTERM landing during step N."""

    def __init__(self, after):
        super().__init__(signals=())
        self.after = after

    def should_stop(self):
        self.after -= 1
        return self.after <= 0


# ---------------------------------------------------------------------------
# Structured telemetry: resilience actions land in the metrics sink
# ---------------------------------------------------------------------------

@pytest.fixture()
def event_sink(tmp_path):
    """Route the global metrics sink to a tmp JSONL so the fault paths'
    emit_event calls become observable, restoring the no-op sink after."""
    from building_llm_from_scratch_tpu.obs import configure_metrics

    path = str(tmp_path / "events.jsonl")
    configure_metrics(path, run_metadata={"test": True})
    yield path
    configure_metrics(None)


def _events(path):
    with open(path) as f:
        return [json.loads(line) for line in f
                if json.loads(line).get("type") == "event"]


def test_checkpoint_fallback_emits_event(tmp_path, event_sink):
    out = str(tmp_path)
    _save(out, "10", 10)
    ck20 = _save(out, "20", 20)
    _flip_byte(_first_shard(ck20))
    assert find_latest_valid_checkpoint(out).endswith("model_pg_10")
    ev = [e for e in _events(event_sink) if e["event"] == "checkpoint_fallback"]
    assert ev and ev[0]["step"] == 20 and "sha256" in ev[0]["reason"]


def test_checkpoint_save_and_gc_emit_events(tmp_path, event_sink):
    out = str(tmp_path)
    for step in (1, 2, 3):
        _save(out, str(step), step)
    prune_checkpoints(out, keep=1)
    events = _events(event_sink)
    saves = [e for e in events if e["event"] == "checkpoint_save"]
    assert len(saves) == 3
    assert all(e["bytes"] > 0 and e["seconds"] >= 0 for e in saves)
    gc = [e for e in events if e["event"] == "checkpoint_gc"]
    assert gc and sorted(gc[0]["removed"]) == ["model_pg_1", "model_pg_2"]


def test_watchdog_halt_emits_event(event_sink):
    wd = LossWatchdog(spike_factor=5.0, window=10, min_history=2)
    wd.observe(0, 2.0)
    wd.observe(1, 2.0)
    with pytest.raises(TrainingDivergedError):
        wd.observe(2, 99.0)
    ev = [e for e in _events(event_sink) if e["event"] == "watchdog_halt"]
    assert ev and ev[0]["reason"] == "spike" and ev[0]["step"] == 2


def test_preemption_stop_emits_event(tmp_path, event_sink):
    """The graceful-stop path reports itself: a preemption_stop event plus
    the interrupted checkpoint's save event."""
    cfg = tiny_cfg()
    datafile = tmp_path / "c.txt"
    datafile.write_text("the quick brown fox jumps over the lazy dog. " * 12)
    trainer = make_trainer(tmp_path, init_params(cfg, jax.random.PRNGKey(0)),
                           stopper=StopAfter(3))
    trainer.train_model([str(datafile)], n_epochs=1, start_context="a")
    assert trainer.preempted and trainer.global_step == 3
    events = _events(event_sink)
    stop = [e for e in events if e["event"] == "preemption_stop"]
    assert stop and stop[0]["step"] == 3
    assert any(e["event"] == "checkpoint_save"
               and e["path"].endswith("model_pg_interrupted")
               for e in events)


@pytest.mark.slow
def test_graceful_stop_resume_matches_uninterrupted_run(tmp_path):
    """The tentpole invariant, in-process: stop at a step boundary, resume
    via the data cursor, and the remaining eval-loss trajectory is
    bit-for-bit the uninterrupted run's."""
    cfg = tiny_cfg()
    datafile = tmp_path / "c.txt"
    datafile.write_text("a stitch in time saves nine, they say. " * 16)
    params = jax.device_put(init_params(cfg, jax.random.PRNGKey(0)))

    ref = make_trainer(tmp_path, params, output_dir=str(tmp_path / "ref"),
                       eval_freq=4)
    ref.train_model([str(datafile)], n_epochs=1, start_context="a")
    assert ref.global_step >= 12

    stopped = make_trainer(tmp_path, params,
                           output_dir=str(tmp_path / "pre"),
                           eval_freq=4, stopper=StopAfter(7))
    stopped.train_model([str(datafile)], n_epochs=1, start_context="a")
    assert stopped.preempted and stopped.global_step == 7
    ck = os.path.join(str(tmp_path / "pre"), "model_pg_interrupted")
    assert checkpoint_metadata(ck)["cursor"]["batch_index"] == 7

    resumed = make_trainer(tmp_path, init_params(cfg, jax.random.PRNGKey(5)),
                           output_dir=str(tmp_path / "pre"),
                           eval_freq=4, resume_from=ck)
    resumed.train_model([str(datafile)], n_epochs=1, start_context="a")
    assert not resumed.preempted
    assert resumed.global_step == ref.global_step
    assert resumed.tokens_seen == ref.tokens_seen
    n = len(resumed.train_losses)
    assert n >= 1
    np.testing.assert_array_equal(np.asarray(resumed.train_losses),
                                  np.asarray(ref.train_losses[-n:]))
    np.testing.assert_array_equal(np.asarray(resumed.val_losses),
                                  np.asarray(ref.val_losses[-n:]))
