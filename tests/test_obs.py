"""Observability subsystem tests (obs/): JSONL schema round-trip, MFU
analytic-FLOPs math, the step timeline's non-step exclusion, the stall
detector, the no-per-step-host-sync invariant, and the CPU smoke run
acceptance case (main() + --metrics_jsonl)."""

import json
import os
import threading
import time

import jax
import numpy as np
import pytest

from building_llm_from_scratch_tpu.configs import get_config
from building_llm_from_scratch_tpu.data import ByteTokenizer, PretrainLoader
from building_llm_from_scratch_tpu.models import init_params
from building_llm_from_scratch_tpu.obs import (
    MetricLogger,
    StallDetector,
    StepTimeline,
    compute_mfu,
    configure_metrics,
    device_peak_flops,
    emit_event,
    flops_per_token,
    format_mfu,
    get_metrics,
    window_stats,
)
from building_llm_from_scratch_tpu.training import Trainer


def read_rows(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


@pytest.fixture()
def global_sink(tmp_path):
    """Route the process-global sink to a tmp JSONL for one test, restoring
    the no-op sink afterwards so tests stay isolated."""
    path = str(tmp_path / "metrics.jsonl")
    logger = configure_metrics(path, run_metadata={"test": True})
    yield logger, path
    configure_metrics(None)


def tiny_cfg():
    # same fast fixture shape as test_resilience: real train steps, tiny
    # compiles
    return get_config("GPT2", "124M", debug=True).replace(
        emb_dim=32, hidden_dim=64, n_layers=2, n_heads=2, vocab_size=257,
        context_length=16)


def make_trainer(tmp_path, params, **kw):
    tok = ByteTokenizer()
    loader = PretrainLoader(tok, batch_size=2, max_length=16)
    defaults = dict(output_dir=str(tmp_path / "out"), eval_freq=4,
                    print_sample_iter=100000, save_ckpt_freq=100000,
                    warmup_steps=2, show_progress=False)
    defaults.update(kw)
    return Trainer(tiny_cfg(), params, tok, loader, **defaults)


# ---------------------------------------------------------------------------
# JSONL schema round-trip
# ---------------------------------------------------------------------------

def test_jsonl_schema_roundtrip(tmp_path):
    path = str(tmp_path / "m.jsonl")
    lg = MetricLogger(path)
    lg.write_header(jax_version="0.0", device_kind="test", device_count=1)
    lg.count("widgets", 2)
    lg.gauge("hbm", 123)
    lg.timing("data_wait", 0.25)
    lg.timing("data_wait", 0.25)
    lg.log_metrics(5, lr=1e-3, tok_s=100.0)
    lg.event("checkpoint_save", step=5, bytes=42, seconds=0.1)
    lg.log_metrics(10, lr=2e-3, tok_s=200.0, train_loss=float("nan"))
    lg.close()

    rows = read_rows(path)
    assert [r["type"] for r in rows] == ["header", "metrics", "event",
                                        "metrics"]
    from building_llm_from_scratch_tpu.obs.metrics import SCHEMA_VERSION

    header = rows[0]
    assert header["schema_version"] == SCHEMA_VERSION
    assert header["device_kind"] == "test"
    m1, ev, m2 = rows[1], rows[2], rows[3]
    # timings drained into the first row only, counters/gauges attached
    assert m1["data_wait_s"] == pytest.approx(0.5)
    assert "data_wait_s" not in m2
    assert m1["widgets"] == 2 and m1["hbm"] == 123
    assert ev["event"] == "checkpoint_save" and ev["bytes"] == 42
    # monotonically increasing step across metric rows
    steps = [r["step"] for r in rows if r["type"] == "metrics"]
    assert steps == sorted(steps) == [5, 10]
    # non-finite values stay parseable (stringified, not bare NaN)
    assert isinstance(m2["train_loss"], str)


def test_pre_header_rows_buffer_until_header(tmp_path):
    """Events fired before the run metadata exists (build-time fetches)
    must land AFTER the header line, not before or nowhere."""
    path = str(tmp_path / "m.jsonl")
    lg = MetricLogger(path)
    lg.event("hf_fetch", repo="x/y")
    assert not os.path.exists(path)          # buffered, not written
    lg.write_header(device_kind="test")
    rows = read_rows(path)
    assert [r["type"] for r in rows] == ["header", "event"]
    assert rows[1]["event"] == "hf_fetch"
    lg.close()


def test_jsonl_rotates_previous_run_file(tmp_path):
    """One run = one file: a --resume relaunch reusing the same path must
    rotate the killed run's telemetry aside, not append a second header
    mid-file / restart the monotone step sequence."""
    path = str(tmp_path / "m.jsonl")
    lg = MetricLogger(path)
    lg.write_header(run=1)
    lg.log_metrics(90, lr=1.0)
    lg.close()
    lg2 = MetricLogger(path)
    lg2.write_header(run=2)
    lg2.log_metrics(5, lr=2.0)               # restarts below the old 90
    lg2.close()
    rows = read_rows(path)
    assert [r["type"] for r in rows] == ["header", "metrics"]
    assert rows[0]["run"] == 2 and rows[1]["step"] == 5
    prev = read_rows(path + ".1")
    assert prev[0]["run"] == 1 and prev[1]["step"] == 90


def test_closed_sink_never_reopens_or_rotates(tmp_path):
    """A write after close() (stall-detector thread firing during
    teardown) must not reopen the path — reopening would rotate the
    COMPLETED run's artifact aside for one stray row."""
    path = str(tmp_path / "m.jsonl")
    lg = MetricLogger(path)
    lg.write_header(run=1)
    lg.log_metrics(1, lr=0.1)
    lg.close()
    lg.event("stall")                        # dropped, not written
    assert not os.path.exists(path + ".1")
    assert [r["type"] for r in read_rows(path)] == ["header", "metrics"]


def test_noop_sink_counts_but_never_writes(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    lg = MetricLogger(None)
    lg.event("stall")
    lg.log_metrics(1, lr=0.1)
    assert lg.counters["event:stall"] == 1
    assert list(tmp_path.iterdir()) == []


def test_global_sink_emit_event(global_sink):
    logger, path = global_sink
    assert get_metrics() is logger
    emit_event("custom", step=3, detail="x")
    rows = read_rows(path)
    assert rows[0]["type"] == "header"
    assert rows[-1]["event"] == "custom" and rows[-1]["step"] == 3


# ---------------------------------------------------------------------------
# MFU math
# ---------------------------------------------------------------------------

def test_flops_per_token_matches_hand_computation():
    cfg = tiny_cfg()
    # hand-computed for this exact config (GPT-2 shape: qkv_bias=False from
    # debug replace of the base config, biased out-proj/MLP/norms):
    d, v, t, L, f = 32, 257, 16, 2, 64
    qkv = d * d + 2 * d * d                   # wq + wk,wv (n_kv == n_heads)
    attn_out = d * d + d                      # biased out proj
    mlp = 2 * d * f + (f + d)                 # biased in/out linears
    norms = 2 * (2 * d)                       # 2 biased layernorms
    per_layer = qkv + attn_out + mlp + norms
    n_matmul = per_layer * L + 2 * d + d * v  # + final norm + head
    expected = 6 * n_matmul + 12 * L * d * t
    assert cfg.num_params(exclude_embeddings=True) == n_matmul
    assert flops_per_token(cfg) == expected
    # seq_len override scales only the attention term
    assert flops_per_token(cfg, seq_len=2 * t) - flops_per_token(cfg) == (
        12 * L * d * t)


def test_device_peak_flops_table():
    class FakeDev:
        def __init__(self, kind):
            self.device_kind = kind

    assert device_peak_flops(FakeDev("TPU v4")) == 275e12
    assert device_peak_flops(FakeDev("TPU v5 lite")) == 197e12
    assert device_peak_flops(FakeDev("TPU v5p")) == 459e12
    assert device_peak_flops(FakeDev("cpu")) is None
    # the CPU test backend reports n/a, not a made-up number
    assert device_peak_flops() is None
    assert format_mfu(None) == "MFU n/a"
    assert format_mfu(0.414) == "41.4% MFU"


def test_compute_mfu_against_explicit_peak():
    cfg = tiny_cfg()
    per_tok = flops_per_token(cfg)
    mfu = compute_mfu(1000.0, cfg, n_devices=2, peak=1e12)
    assert mfu == pytest.approx(1000.0 * per_tok / 2e12)
    assert compute_mfu(1000.0, cfg, n_devices=1, peak=None) is None
    assert compute_mfu(0.0, cfg, n_devices=1, peak=1e12) is None


# ---------------------------------------------------------------------------
# Timeline
# ---------------------------------------------------------------------------

def test_timeline_spans_accumulate_and_drain():
    tl = StepTimeline()
    with tl.span("data_wait"):
        time.sleep(0.01)
    with tl.step_span(1):
        pass
    with tl.step_span(2):
        pass
    with tl.span("eval"):
        time.sleep(0.01)
    win = tl.drain()
    assert win["data_wait"] >= 0.01 and win["eval"] >= 0.01
    assert win["steps"] == 2 and "dispatch" in win
    assert tl.drain() == {"steps": 0}        # reset


def test_window_stats_excludes_non_step_time():
    """The satellite fix: sample/checkpoint/eval time inside the window
    must not deflate tok/s."""
    window = {"data_wait": 0.1, "dispatch": 0.2, "host_fetch": 0.1,
              "eval": 2.0, "sample": 1.0, "checkpoint": 1.0, "steps": 4}
    stats = window_stats(window, elapsed=6.0, tokens=8000)
    # 6s wall - 4s non-step = 2s of training
    assert stats["non_step_seconds"] == pytest.approx(4.0)
    assert stats["tok_s"] == pytest.approx(4000.0)
    assert stats["step_time_s"] == pytest.approx(0.5)
    naive = 8000 / 6.0
    assert stats["tok_s"] > 2 * naive


def test_trainer_throughput_excludes_sample_and_checkpoint_time(tmp_path):
    """Integration: with a deliberately slow sampler firing every 2 steps,
    the reported tok/s must track training time, not wall time."""
    datafile = tmp_path / "c.txt"
    datafile.write_text("a stitch in time saves nine, they say. " * 16)
    cfg = tiny_cfg()
    trainer = make_trainer(tmp_path, init_params(cfg, jax.random.PRNGKey(0)),
                           eval_freq=4, print_sample_iter=2)
    trainer.generate_and_print_sample = lambda *a, **kw: time.sleep(0.3)
    t0 = time.perf_counter()
    trainer.train_model([str(datafile)], n_epochs=1, start_context="a")
    wall = time.perf_counter() - t0
    assert trainer.global_step >= 8
    naive = trainer.tokens_seen / wall
    reported = np.mean(trainer.throughput_tokens_per_s)
    # ~0.15s/step of sample sleep vs ~ms-scale tiny-model steps: without
    # the exclusion `reported` would sit near `naive`; with it, far above
    assert reported > 2 * naive, (reported, naive)


# ---------------------------------------------------------------------------
# No new per-step host synchronization (acceptance)
# ---------------------------------------------------------------------------

def test_no_per_step_host_fetch_in_train_loop(tmp_path):
    """Device metric scalars must be fetched ONLY at cadence (the
    _flush_metrics discipline): wrap every step's lr in a guard that
    records the trainer step at which it is converted to a host value."""
    datafile = tmp_path / "c.txt"
    datafile.write_text("pack my box with five dozen liquor jugs. " * 12)
    cfg = tiny_cfg()
    trainer = make_trainer(tmp_path, init_params(cfg, jax.random.PRNGKey(0)),
                           eval_freq=4)
    fetch_steps = []

    class GuardedScalar:
        def __init__(self, val):
            self._val = val

        def copy_to_host_async(self):
            pass

        def __array__(self, dtype=None, copy=None):
            fetch_steps.append(trainer.global_step)
            out = np.asarray(self._val)
            return out.astype(dtype) if dtype is not None else out

    real_setup = trainer._setup

    def guarded_setup(total_steps):
        real_setup(total_steps)
        real_step = trainer.train_step

        def step(state, batch):
            state, metrics = real_step(state, batch)
            # guard the per-layer-group health arrays too: they ride the
            # same deferred-fetch discipline as lr (cadence-only)
            health = {k: GuardedScalar(v)
                      for k, v in metrics["health"].items()}
            return state, dict(metrics, lr=GuardedScalar(metrics["lr"]),
                               health=health)

        trainer.train_step = step

    trainer._setup = guarded_setup
    trainer.train_model([str(datafile)], n_epochs=1, start_context="a")
    assert trainer.global_step >= 8
    assert fetch_steps, "lr metrics were never flushed"
    allowed = {s for s in range(0, trainer.global_step + 1, 4)}
    allowed.add(trainer.global_step)         # final flush in `finally`
    assert set(fetch_steps) <= allowed, (
        f"host fetch outside cadence: {sorted(set(fetch_steps) - allowed)}")
    # and the lr trajectory still arrived intact
    assert len(trainer.track_lrs) == trainer.global_step
    # health made it to the host at cadence (not never-fetched)
    assert trainer._last_health is not None
    assert len(trainer._health_names) == len(
        np.asarray(trainer._last_health["grad_norm"]))


# ---------------------------------------------------------------------------
# Compile telemetry (obs/compile.py)
# ---------------------------------------------------------------------------

def _tiny_step_state_batch(bs=2):
    from building_llm_from_scratch_tpu.training import (
        build_optimizer,
        init_train_state,
        make_train_step,
    )

    cfg = tiny_cfg().replace(drop_rate=0.0)
    opt = build_optimizer(total_steps=10)
    state = init_train_state(init_params(cfg, jax.random.PRNGKey(0)), opt,
                             jax.random.PRNGKey(1))
    step = make_train_step(cfg, opt, lr_schedule=lambda s: 1e-3)
    rng = np.random.default_rng(0)
    T = cfg.context_length
    batch = {
        "inputs": rng.integers(0, cfg.vocab_size, (bs, T)).astype(np.int32),
        "targets": rng.integers(0, cfg.vocab_size, (bs, T)).astype(np.int32),
        "weights": np.ones((bs, T), np.float32),
    }
    return step, state, batch


def test_compile_watcher_captures_cost_and_memory(global_sink):
    """First call AOT-compiles and emits ONE compile event with nonzero
    compile seconds, HLO-counted FLOPs and the HBM breakdown; steady-state
    same-signature calls stay silent (no recompiles, no new events)."""
    from building_llm_from_scratch_tpu.obs import CompileWatcher

    _, path = global_sink
    step, state, batch = _tiny_step_state_batch()
    w = CompileWatcher(step, label="test_step")
    for _ in range(3):
        state, metrics = w(state, batch)
    assert w.n_compiles == 1 and w.n_recompiles == 0
    assert w.hlo_flops_per_step and w.hlo_flops_per_step > 0
    assert w.hlo_flops_per_token == pytest.approx(
        w.hlo_flops_per_step / batch["inputs"].size)
    compiles = [r for r in read_rows(path) if r.get("event") == "compile"]
    assert len(compiles) == 1
    ev = compiles[0]
    assert ev["label"] == "test_step"
    assert ev["compile_seconds"] > 0
    assert ev["flops"] > 0
    assert ev["tokens_per_step"] == batch["inputs"].size
    mem = ev["memory"]
    assert mem["args_bytes"] > 0 and mem["temp_bytes"] >= 0
    assert mem["total_bytes"] > 0
    assert not any(r.get("event") == "recompile" for r in read_rows(path))
    # the step result is the real one (executable actually ran)
    assert np.isfinite(float(metrics["loss"]))


def _stub_aot(monkeypatch, flops=1000.0):
    """Replace the real XLA compile with a stub so watcher-LOGIC tests
    (recompile keying, cache counting) don't pay ~5s of compile each —
    the end-to-end AOT path is covered once by
    test_compile_watcher_captures_cost_and_memory."""
    import building_llm_from_scratch_tpu.obs.compile as obs_compile

    def fake_aot(fn, state, batch):
        return (lambda s, b: (s, {"loss": np.float32(0.0)})), {
            "compile_seconds": 0.01, "lower_seconds": 0.005,
            "backend_compile_seconds": 0.005, "flops": flops,
            "executable_device_count": 1,
            "memory": {"args_bytes": 1, "temp_bytes": 2, "total_bytes": 3}}

    monkeypatch.setattr(obs_compile, "aot_compile", fake_aot)


def test_compile_watcher_detects_recompile_with_shape_diff(global_sink,
                                                           monkeypatch):
    """A changed batch signature fires a recompile event naming the exact
    leaf shape diff — the silent-TPU-perf-bug detector."""
    from building_llm_from_scratch_tpu.obs import CompileWatcher

    _, path = global_sink
    _stub_aot(monkeypatch)
    w = CompileWatcher(lambda s, b: None, label="test_step")
    state = {"x": np.zeros((3,), np.float32)}
    batch2 = {"inputs": np.zeros((2, 16), np.int32)}
    batch4 = {"inputs": np.zeros((4, 16), np.int32)}
    state, _ = w(state, batch2)
    state, _ = w(state, batch2)                  # steady state: silent
    state, _ = w(state, batch4)
    assert w.n_compiles == 2 and w.n_recompiles == 1
    rows = read_rows(path)
    rec = [r for r in rows if r.get("event") == "recompile"]
    assert len(rec) == 1
    leaves = {d["leaf"] for d in rec[0]["diff"]}
    assert "inputs" in leaves
    diff = next(d for d in rec[0]["diff"] if d["leaf"] == "inputs")
    assert diff["was"]["shape"][0] == 2 and diff["now"]["shape"][0] == 4
    assert len([r for r in rows if r.get("event") == "compile"]) == 2


def test_compile_watcher_cache_hit_miss_counting(global_sink, tmp_path,
                                                 monkeypatch):
    """--compile_cache_dir telemetry: a compile that writes no new cache
    entries into a warm dir reports a hit; an empty dir reports a miss."""
    from building_llm_from_scratch_tpu.obs import CompileWatcher

    _, path = global_sink
    _stub_aot(monkeypatch)
    batch = {"inputs": np.zeros((2, 16), np.int32)}
    warm = tmp_path / "warm_cache"
    warm.mkdir()
    (warm / "jit_step-abc123-cache").write_bytes(b"x")
    w = CompileWatcher(lambda s, b: None, cache_dir=str(warm))
    w({"x": np.zeros(2)}, batch)
    ev = [r for r in read_rows(path) if r.get("event") == "compile"][-1]
    assert ev["cache_dir"] == str(warm)
    assert ev["cache_entries"] == 1 and ev["cache_hit"] is True

    cold = tmp_path / "cold_cache"
    cold.mkdir()
    w2 = CompileWatcher(lambda s, b: None, cache_dir=str(cold))
    w2({"x": np.zeros(2)}, batch)
    ev2 = [r for r in read_rows(path) if r.get("event") == "compile"][-1]
    assert ev2["cache_hit"] is False


def test_compile_watcher_falls_back_on_unloweable_step(global_sink):
    """Telemetry must never take down the run: a step without .lower()
    (or whose AOT path raises) delegates to the wrapped callable and emits
    a compile_fallback event."""
    from building_llm_from_scratch_tpu.obs import CompileWatcher

    _, path = global_sink
    calls = []

    def plain_step(state, batch):                  # no .lower attribute
        calls.append(1)
        return state, {"loss": 0.0}

    w = CompileWatcher(plain_step, label="plain")
    state, m = w({"x": np.zeros(2)}, {"inputs": np.zeros((2, 4))})
    assert m["loss"] == 0.0 and len(calls) == 1
    assert w._disabled
    w(state, {"inputs": np.zeros((2, 4))})         # stays delegated
    assert len(calls) == 2
    events = [r.get("event") for r in read_rows(path)]
    assert "compile_fallback" in events
    assert "compile" not in events


def test_aot_cost_analysis_globalized_over_devices():
    """cost_analysis() reports the PER-DEVICE SPMD module; aot_compile must
    scale it by the executable's device span so mfu_hlo (global FLOPs /
    global tokens) is right on multi-chip runs, not just single-chip."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from building_llm_from_scratch_tpu.obs.compile import (
        aot_compile,
        executable_device_count,
    )

    a = jax.numpy.ones((64, 128))
    b = jax.numpy.ones((128, 32))
    c1, s1 = aot_compile(jax.jit(lambda a, b: a @ b), a, b)
    assert executable_device_count(c1) == 1
    assert s1["executable_device_count"] == 1
    assert "flops_per_device" not in s1

    n = len(jax.devices())
    assert n == 8, "conftest forces an 8-device CPU platform"
    mesh = Mesh(np.array(jax.devices()).reshape(n), ("data",))
    sharded = NamedSharding(mesh, P("data"))
    rep = NamedSharding(mesh, P())
    f8 = jax.jit(lambda a, b: a @ b, in_shardings=(sharded, rep),
                 out_shardings=sharded)
    c8, s8 = aot_compile(f8, jax.device_put(a, sharded),
                         jax.device_put(b, rep))
    assert s8["executable_device_count"] == n
    # per-device module counted 1/n of the work; stats carry the GLOBAL sum
    assert s8["flops_per_device"] == pytest.approx(s1["flops"] / n, rel=0.01)
    assert s8["flops"] == pytest.approx(s1["flops"], rel=0.01)


def test_signature_diff_names_changed_leaves():
    from building_llm_from_scratch_tpu.obs.compile import (
        signature_diff,
        tree_signature,
    )

    a = tree_signature({"x": np.zeros((2, 4), np.float32),
                        "y": np.zeros((3,), np.int32)})
    b = tree_signature({"x": np.zeros((8, 4), np.float32),
                        "y": np.zeros((3,), np.int32)})
    diff = signature_diff(a, b)
    assert len(diff) == 1 and diff[0]["leaf"] == "x"
    assert diff[0]["was"]["shape"] == [2, 4]
    assert diff[0]["now"]["shape"] == [8, 4]
    assert signature_diff(a, a) == []


def test_watchdog_halt_names_offending_layer(global_sink):
    """The trainer wires obs/health's digest as the watchdog context: the
    halt event + diagnostic name the first non-finite layer group."""
    from building_llm_from_scratch_tpu.training.resilience import (
        LossWatchdog,
        TrainingDivergedError,
    )

    _, path = global_sink
    wd = LossWatchdog(context_fn=lambda: {
        "first_nonfinite_group": "block_01",
        "top_grad_norm_groups": [{"group": "block_01", "grad_norm": 12.5}]})
    with pytest.raises(TrainingDivergedError, match="block_01"):
        wd.observe(7, float("nan"))
    halt = next(r for r in read_rows(path)
                if r.get("event") == "watchdog_halt")
    assert halt["first_nonfinite_group"] == "block_01"
    assert halt["top_grad_norm_groups"][0]["group"] == "block_01"
    # a broken context provider must not mask the halt itself
    wd2 = LossWatchdog(context_fn=lambda: 1 / 0)
    with pytest.raises(TrainingDivergedError):
        wd2.observe(8, float("inf"))
    # nor may a context key that collides with the event's own kwargs
    # (reason/recent/step) turn the halt into a TypeError
    wd3 = LossWatchdog(context_fn=lambda: {
        "reason": "shadow", "step": 0, "first_nonfinite_group": "head"})
    with pytest.raises(TrainingDivergedError):
        wd3.observe(9, float("nan"))
    halts = [r for r in read_rows(path) if r.get("event") == "watchdog_halt"]
    assert halts[-1]["reason"] == "non_finite"       # event kwarg wins
    assert halts[-1]["first_nonfinite_group"] == "head"


# ---------------------------------------------------------------------------
# utils/logging.py satellite: process-0 INFO gating + level semantics
# ---------------------------------------------------------------------------

def _capture_logger(name, **kw):
    import io
    import logging as pylogging

    from building_llm_from_scratch_tpu.utils.logging import setup_logger

    lg = setup_logger(name, **kw)
    stream = io.StringIO()
    # swap the stdout handler's stream so records (post-filter) are
    # observable; the coordinator filter lives on the handler
    lg.handlers[0].stream = stream
    return lg, stream


def test_logging_non_coordinator_gates_info(monkeypatch):
    """The docstring always promised process-0 INFO gating; now it exists:
    below-WARNING records drop on non-coordinator processes unless
    BLLM_LOG_ALL_HOSTS is set."""
    from jax._src import distributed

    lg, stream = _capture_logger("test_obs.gating")
    monkeypatch.delenv("BLLM_LOG_ALL_HOSTS", raising=False)
    monkeypatch.setattr(distributed.global_state, "process_id", 3)
    lg.info("invisible info")
    lg.warning("visible warning")
    monkeypatch.setenv("BLLM_LOG_ALL_HOSTS", "1")
    lg.info("debug override info")
    out = stream.getvalue()
    assert "invisible info" not in out
    assert "visible warning" in out
    assert "debug override info" in out
    monkeypatch.setattr(distributed.global_state, "process_id", 0)
    lg.info("coordinator info")
    assert "coordinator info" in stream.getvalue()


def test_logging_repeat_call_respects_level():
    import logging as pylogging

    from building_llm_from_scratch_tpu.utils.logging import setup_logger

    lg = setup_logger("test_obs.levels", level=pylogging.INFO)
    assert lg.level == pylogging.INFO
    # a repeat DEFAULT call must not clobber the explicit level...
    assert setup_logger("test_obs.levels").level == pylogging.INFO
    # ...but a repeat EXPLICIT call is respected
    assert setup_logger("test_obs.levels",
                        level=pylogging.ERROR).level == pylogging.ERROR
    # and a fresh logger still defaults to DEBUG
    assert setup_logger("test_obs.fresh").level == pylogging.DEBUG


# ---------------------------------------------------------------------------
# Stall detector
# ---------------------------------------------------------------------------

def test_stall_detector_fires_on_blocked_loop():
    import io
    import logging

    fired = threading.Event()
    det = StallDetector(timeout=0.3, poll_interval=0.05, first_grace=1.0,
                        on_stall=lambda e, t: fired.set())
    # obs loggers don't propagate (utils/logging.py), so attach a capture
    # handler directly instead of caplog
    stream = io.StringIO()
    handler = logging.StreamHandler(stream)
    stall_logger = logging.getLogger("building_llm_from_scratch_tpu.obs.stall")
    stall_logger.addHandler(handler)
    try:
        with det:
            det.notify_step()                # arm, then... nothing: "hang"
            assert fired.wait(3.0), "stall detector never fired"
    finally:
        stall_logger.removeHandler(handler)
    assert det.stall_count == 1
    text = stream.getvalue()
    assert "STALL" in text
    # the dump names THIS (blocked) thread's stack
    assert "test_stall_detector_fires_on_blocked_loop" in text
    assert "Device memory stats" in text


def test_stall_detector_fires_on_first_step_hang():
    """start() must arm the detector: a run that wedges in its very FIRST
    step (first collective / data pipeline / compile) still dumps, after
    first_grace x the threshold."""
    fired = threading.Event()
    det = StallDetector(timeout=0.2, poll_interval=0.05, first_grace=2.0,
                        on_stall=lambda e, t: fired.set())
    with det:                                # never notify_step
        assert det.threshold() == pytest.approx(0.4)   # grace applied
        assert fired.wait(3.0), "never fired on a first-step hang"
    assert det.stall_count == 1


def test_stall_detector_silent_on_healthy_loop():
    det = StallDetector(timeout=0.5, poll_interval=0.05, first_grace=1.0)
    with det:
        for _ in range(20):
            det.notify_step()
            time.sleep(0.02)
    assert det.stall_count == 0


def test_stall_detector_rearms_per_episode():
    """One dump per stall episode: no repeat dumps while still hung, a new
    dump after recovery + a second hang."""
    det = StallDetector(timeout=0.2, poll_interval=0.02, first_grace=1.0)
    with det:
        det.notify_step()
        time.sleep(0.6)                      # episode 1: several polls
        assert det.stall_count == 1
        det.notify_step()                    # recover
        time.sleep(0.6)                      # episode 2
    assert det.stall_count == 2


def test_stall_check_race_guard_keeps_detector_armed():
    """A heartbeat landing between _check's read and its fired-flag set
    must not mark the NEW gap as already-fired (that would permanently
    silence the detector for intermittent stalls)."""
    det = StallDetector(timeout=0.1, poll_interval=0.01, first_grace=1.0)
    det._last = time.monotonic() - 1.0       # wedged for 1s
    real_threshold = det.threshold

    def racy_threshold():
        det.notify_step()                    # stall ends mid-check
        return real_threshold()

    det.threshold = racy_threshold
    det._check()
    assert det.stall_count == 0              # stale gap: no dump...
    assert not det._fired_for_current_gap    # ...and the new gap is armed
    det.threshold = real_threshold
    det._last = time.monotonic() - 1.0       # wedges again
    det._check()
    assert det.stall_count == 1


def test_stall_threshold_median_adaptive_with_floor():
    """Fast steps tighten the threshold below a huge timeout, but never
    below the floor — one loop iteration legitimately stretches past
    10x the median step when cadence work (first-compile eval, checkpoint
    save) runs, and that must not read as a stall (seen live: a 2s first
    eval fired a 10 * 150ms threshold)."""
    det = StallDetector(timeout=600.0, factor=10.0, median_floor=30.0)
    det._last = 0.0
    det._intervals = [0.15] * 20             # 150ms steps
    assert det.threshold() == pytest.approx(30.0)   # floored, not 1.5s
    det._intervals = [5.0] * 20              # slow steps: adaptive wins
    assert det.threshold() == pytest.approx(50.0)
    det._intervals = [90.0] * 20             # timeout is still the cap
    assert det.threshold() == pytest.approx(600.0)
    det._intervals = []                      # pre-first-step: compile grace
    assert det.threshold() == pytest.approx(600.0 * det.first_grace)


def test_stall_detector_rejects_zero_timeout():
    with pytest.raises(ValueError, match="timeout"):
        StallDetector(timeout=0)


def test_stall_event_reaches_sink(global_sink, tmp_path):
    _, path = global_sink
    det = StallDetector(timeout=0.2, poll_interval=0.05, first_grace=1.0)
    with det:
        det.notify_step()
        deadline = time.monotonic() + 3.0
        while det.stall_count == 0 and time.monotonic() < deadline:
            time.sleep(0.05)
    events = [r for r in read_rows(path) if r["type"] == "event"]
    assert any(e["event"] == "stall" for e in events)


# ---------------------------------------------------------------------------
# CPU smoke run (acceptance): main() + --metrics_jsonl
# ---------------------------------------------------------------------------

def test_cli_smoke_metrics_jsonl(tmp_path):
    """A CPU-run main() with --metrics_jsonl produces a parseable JSONL:
    run-metadata header first, per-cadence loss/lr/tok-s/step-time/memory
    rows, and structured events (checkpoint_save, run_complete)."""
    from building_llm_from_scratch_tpu.args import get_args
    from building_llm_from_scratch_tpu.main import main

    d = tmp_path / "data"
    d.mkdir()
    (d / "corpus.txt").write_text(
        "Every effort moves you closer to mastery. " * 80)
    out = str(tmp_path / "out")
    jsonl = os.path.join(out, "metrics.jsonl")
    try:
        trainer = main(get_args([
            "--data_dir", str(d), "--output_dir", out, "--debug",
            "--byte_tokenizer", "--n_epochs", "1", "--batch_size", "8",
            "--eval_freq", "10", "--log_every", "5",
            "--print_sample_iter", "10000", "--save_ckpt_freq", "15",
            "--warmup_steps", "2", "--metrics_jsonl", jsonl]))
    finally:
        configure_metrics(None)              # detach the global sink
    assert trainer.global_step >= 15

    rows = read_rows(jsonl)                  # every line parses
    assert rows[0]["type"] == "header"
    header = rows[0]
    assert header["jax_version"] == jax.__version__
    assert header["device_count"] == len(jax.devices())
    assert header["model"]["name"] == "gpt2-124M"
    assert header["flags"]["batch_size"] == 8
    assert "argv" in header and "mesh_shape" in header

    metrics = [r for r in rows if r["type"] == "metrics"]
    assert metrics, "no metric rows"
    steps = [r["step"] for r in metrics]
    assert steps == sorted(steps)            # monotonically increasing
    # --log_every 5 decoupled from --eval_freq 10: rows at 5, 10, 15, ...
    assert 5 in steps and 10 in steps
    for r in metrics:
        assert r["lr"] is not None and r["tok_s"] > 0
        assert r["step_time_s"] is not None
        assert r["host_rss_bytes"] > 0
        # pre-clip grad norm + post-clip update norm (derived from the
        # health bundle) surface in every metrics row
        assert r["grad_norm"] > 0 and r["update_norm"] > 0
    # loss only on eval-cadence rows
    eval_rows = [r for r in metrics if r["step"] % 10 == 0]
    assert eval_rows and all(
        np.isfinite(r["train_loss"]) and np.isfinite(r["val_loss"])
        for r in eval_rows)
    log_only = [r for r in metrics if r["step"] % 10 and r["step"] % 5 == 0]
    assert log_only and all("train_loss" not in r for r in log_only)

    events = {r["event"] for r in rows if r["type"] == "event"}
    assert "checkpoint_save" in events
    assert "run_complete" in events
    ckpt = next(r for r in rows if r.get("event") == "checkpoint_save")
    assert ckpt["bytes"] > 0 and ckpt["seconds"] > 0

    # compile telemetry (acceptance): exactly ONE compile event — nonzero
    # compile seconds, HLO cost-analysis FLOPs, a memory breakdown — and
    # ZERO recompiles across the fixed-shape run
    compiles = [r for r in rows if r.get("event") == "compile"]
    assert len(compiles) == 1, [r.get("event") for r in rows
                                if r["type"] == "event"]
    ev = compiles[0]
    assert ev["compile_seconds"] > 0
    assert ev["flops"] > 0
    assert ev["memory"]["total_bytes"] > 0
    assert ev["tokens_per_step"] == 8 * trainer.cfg.context_length
    assert not [r for r in rows if r.get("event") == "recompile"]

    # health rows (acceptance): per-layer-group arrays at the log cadence
    health = [r for r in rows if r["type"] == "health"]
    assert health, "no health rows"
    groups = health[0]["groups"]
    assert [g for g in groups if g.startswith("block_")]
    for r in health:
        assert r["groups"] == groups
        for key in ("grad_norm", "param_norm", "update_norm",
                    "update_ratio"):
            assert len(r[key]) == len(groups)
            assert all(np.isfinite(v) for v in r[key])
        assert r["first_nonfinite"] is None
    hsteps = [r["step"] for r in health]
    assert hsteps == sorted(hsteps) and 5 in hsteps and 10 in hsteps
