"""LLaMA tokenizer auto-fetch (round-3 VERDICT missing #1).

The reference pulls tokenizer assets from HF hub behind rank barriers
(build_components.py:265-300); build_tokenizer now does the same
(cache-if-exists) when --tokenizer_path is absent, keeping the flag as the
offline override. Hub traffic is mocked here; the real-download path is the
opt-in @network test in test_network_real_weights.py.
"""

import base64

import pytest

from building_llm_from_scratch_tpu.data import tokenizers as tok_mod
from building_llm_from_scratch_tpu.data.tokenizers import (
    ByteTokenizer,
    build_tokenizer,
)


@pytest.fixture
def tiny_llama3_asset(tmp_path):
    """A minimal tiktoken-format BPE file: 256 byte tokens."""
    path = tmp_path / "tokenizer.model"
    lines = [
        base64.b64encode(bytes([i])).decode() + f" {i}" for i in range(256)
    ]
    path.write_text("\n".join(lines) + "\n")
    return str(path)


def test_llama3_auto_fetch_uses_hub(monkeypatch, tiny_llama3_asset):
    calls = []

    def fake_download(repo_id, filename, cache_dir):
        calls.append((repo_id, filename))
        return tiny_llama3_asset

    import huggingface_hub

    monkeypatch.setattr(huggingface_hub, "hf_hub_download", fake_download)
    tk = build_tokenizer("llama3_2", None)
    assert calls == [("meta-llama/Llama-3.2-1B", "original/tokenizer.model")]
    # round-trips through the tiktoken BPE built from the fetched asset
    ids = tk.encode("hello world")
    assert tk.decode(ids) == "hello world"
    assert tk.eos_id == 256 + 1      # <|end_of_text|> right after base vocab
    # NOTE: with the tiny 256-token base the special ids sit at 256+i; the
    # real Meta file puts them at 128000+i (tokenizers.py:130-142)


def test_explicit_tokenizer_path_skips_hub(monkeypatch, tiny_llama3_asset):
    def boom(*a, **k):
        raise AssertionError("hub must not be called with --tokenizer_path")

    import huggingface_hub

    monkeypatch.setattr(huggingface_hub, "hf_hub_download", boom)
    tk = build_tokenizer("llama3", tiny_llama3_asset)
    assert tk.decode(tk.encode("abc")) == "abc"


def test_offline_failure_mentions_override(monkeypatch):
    import huggingface_hub

    def offline(*a, **k):
        raise ConnectionError("no network")

    monkeypatch.setattr(huggingface_hub, "hf_hub_download", offline)
    with pytest.raises(FileNotFoundError, match="--tokenizer_path"):
        build_tokenizer("llama3_1", None)


def test_offline_failure_falls_back_to_byte_when_asked(monkeypatch):
    import huggingface_hub

    def offline(*a, **k):
        raise ConnectionError("no network")

    monkeypatch.setattr(huggingface_hub, "hf_hub_download", offline)
    tk = build_tokenizer("llama3_2", None, fallback_byte=True)
    assert isinstance(tk, ByteTokenizer)


def test_llama2_auto_fetch_repo_table():
    assert tok_mod.HF_TOKENIZER_ASSETS["llama2"] == (
        "meta-llama/Llama-2-7b", "tokenizer.model")
    with pytest.raises(ValueError, match="GPT2"):
        tok_mod.fetch_tokenizer_asset("GPT2")
