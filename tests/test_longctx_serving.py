"""Long-context serving tier (--serve_sp): sequence-sharded chunk
prefill on the 8-device CPU mesh.

The claim under test: sharding each prefill chunk's tokens across the
``seq`` mesh axis is placement, not semantics — prompts larger than one
device's pane admit, the produced tokens are BIT-IDENTICAL to the
unsharded engine and to one-shot ``generate()``, the compiled program
set never grows under mixed long/short traffic, and the tier composes
with paged KV + int8 (byte-exact ledger included). Admission failures
are typed (``PromptTooLongError`` — the HTTP 413) and report the
seq-sharded ceiling.
"""

import json

import jax
import numpy as np
import pytest

from building_llm_from_scratch_tpu.configs import ModelConfig
from building_llm_from_scratch_tpu.generate import generate
from building_llm_from_scratch_tpu.models import init_params
from building_llm_from_scratch_tpu.obs import configure_metrics
from building_llm_from_scratch_tpu.parallel.sharding import serve_mesh_plan
from building_llm_from_scratch_tpu.serving import (
    DecodeEngine,
    KVCachePolicy,
    PromptTooLongError,
    SamplingParams,
)
from building_llm_from_scratch_tpu.serving.kvcache import cache_nbytes


def tiny_cfg(ctx=64, **kw):
    base = dict(name="longctx-tiny", vocab_size=96, context_length=ctx,
                emb_dim=32, n_heads=2, n_layers=2, hidden_dim=64,
                n_kv_groups=2, norm="layernorm", positional="learned",
                activation="gelu", drop_rate=0.0, eos_id=1)
    base.update(kw)
    return ModelConfig(**base)


@pytest.fixture(scope="module")
def model():
    cfg = tiny_cfg()
    return cfg, init_params(cfg, jax.random.PRNGKey(0))


@pytest.fixture
def sink(tmp_path):
    path = tmp_path / "metrics.jsonl"
    logger = configure_metrics(str(path), run_metadata={"test": True})
    yield str(path)
    logger.close()
    configure_metrics(None)


def solo_tokens(params, cfg, prompt, sp: SamplingParams):
    """One-shot generate() with the matching seed/params — the engine's
    bit-parity oracle (same idiom as test_serving.py)."""
    out, n = generate(params, cfg, np.asarray(prompt)[None],
                      max_new_tokens=sp.max_new_tokens,
                      temperature=sp.temperature, top_k=sp.top_k,
                      eos_id=(None if sp.ignore_eos
                              else (sp.eos_id if sp.eos_id is not None
                                    else cfg.eos_id)),
                      rng=jax.random.PRNGKey(sp.seed),
                      return_n_generated=True)
    Tp = len(prompt)
    return [int(t) for t in out[0, Tp: Tp + int(n[0])]]


def sp_engine(cfg, params, sp=2, chunk=8, **kw):
    kw.setdefault("n_slots", 2)
    kw.setdefault("metrics_every", 4)
    pol = kw.pop("kv_policy", None) or KVCachePolicy(prefill_chunk=chunk)
    eng = DecodeEngine(cfg, params, n_slots=kw.pop("n_slots"),
                       mesh_plan=serve_mesh_plan(sp=sp), kv_policy=pol,
                       **kw)
    return eng


def run_engine(eng, prompts, params_list):
    eng.warmup()
    eng.start()
    handles = [eng.submit(p, s, block=True)
               for p, s in zip(prompts, params_list)]
    eng.run_until_idle()
    out = [[int(t) for t in h.output_ids] for h in handles]
    return out, handles


# ---------------------------------------------------------------------------
# pane geometry + typed admission
# ---------------------------------------------------------------------------

def test_pane_lifts_with_sp(model):
    """The admission ceiling is min(max_len-1, pane x sp): an sp=2
    engine admits prompts DOUBLE one device's pane (up to the slot)."""
    cfg, params = model
    eng = sp_engine(cfg, params, sp=2, chunk=8, max_len=32)
    assert eng.prompt_pane == 16            # ceil(32 / 2) per device
    assert eng.max_prompt == 31             # pane x sp clamped to slot-1
    ref = DecodeEngine(cfg, params, n_slots=2, max_len=32,
                       kv_policy=KVCachePolicy(prefill_chunk=8))
    assert ref.prompt_pane == 32            # unsharded: pane IS the slot
    assert ref.max_prompt == 31
    eng.shutdown()
    ref.shutdown()


def test_explicit_pane_cap(model):
    """--serve_max_prompt pins the per-device pane; the ceiling is
    pane x sp."""
    cfg, params = model
    eng = sp_engine(cfg, params, sp=2, chunk=8, max_len=32, max_prompt=10)
    assert eng.prompt_pane == 10
    assert eng.max_prompt == 20
    eng.shutdown()


def test_prompt_too_long_typed_rejection(model):
    """Over-ceiling prompts raise PromptTooLongError carrying the
    seq-sharded ceiling breakdown (pane_tokens x sp)."""
    cfg, params = model
    eng = sp_engine(cfg, params, sp=2, chunk=8, max_len=32, max_prompt=10)
    eng.warmup()
    with pytest.raises(PromptTooLongError) as ei:
        eng.submit(np.arange(24, dtype=np.int32) % cfg.vocab_size,
                   SamplingParams(max_new_tokens=2))
    err = ei.value
    assert err.prompt_tokens == 24
    assert err.limit == 20
    assert err.pane_tokens == 10
    assert err.sp == 2
    assert "seq-sharded" in str(err)
    assert isinstance(err, ValueError)      # old callers keep working
    eng.shutdown()


def test_sp_requires_chunked_prefill(model):
    cfg, params = model
    with pytest.raises(ValueError, match="chunked prefill"):
        DecodeEngine(cfg, params, n_slots=2,
                     mesh_plan=serve_mesh_plan(sp=2))
    with pytest.raises(ValueError, match="equal token slice"):
        DecodeEngine(cfg, params, n_slots=2,
                     mesh_plan=serve_mesh_plan(sp=2),
                     kv_policy=KVCachePolicy(prefill_chunk=9))


# ---------------------------------------------------------------------------
# bit-parity + zero recompiles
# ---------------------------------------------------------------------------

def test_long_prompt_matches_generate_bit_exact(model):
    """A prompt LARGER than one device's pane, prefilled seq-sharded,
    produces the exact token sequence of one-shot generate() AND of the
    unsharded engine — greedy and sampled."""
    cfg, params = model
    rng = np.random.default_rng(0)
    # pane = ceil(64/2) = 32; 40-token prompts exceed it
    prompts = [rng.integers(0, cfg.vocab_size, (40,)).astype(np.int32)
               for _ in range(3)]
    sps = [SamplingParams(max_new_tokens=6, ignore_eos=True),
           SamplingParams(max_new_tokens=6, temperature=0.9, top_k=20,
                          seed=11, ignore_eos=True),
           SamplingParams(max_new_tokens=6, temperature=0.7, seed=5,
                          ignore_eos=True)]
    eng = sp_engine(cfg, params, sp=2, chunk=8)
    got, handles = run_engine(eng, prompts, sps)
    assert eng.n_recompiles == 0
    for h in handles:
        assert h.long_prompt                # > one pane -> flagged
    eng.shutdown()

    for out, p, s in zip(got, prompts, sps):
        assert out == solo_tokens(params, cfg, p, s)

    ref = DecodeEngine(cfg, params, n_slots=2,
                       kv_policy=KVCachePolicy(prefill_chunk=8))
    ref_out, _ = run_engine(ref, prompts, sps)
    ref.shutdown()
    assert got == ref_out


def test_mixed_traffic_zero_recompiles(model):
    """Interleaved long (> pane) and short prompts reuse one compiled
    chunk program + one decode program: n_recompiles stays 0 and no new
    programs appear after warmup freeze."""
    cfg, params = model
    rng = np.random.default_rng(1)
    prompts, sps = [], []
    for i in range(8):
        n = 40 if i % 2 == 0 else 5
        prompts.append(rng.integers(0, cfg.vocab_size, (n,))
                       .astype(np.int32))
        sps.append(SamplingParams(max_new_tokens=4, seed=i,
                                  temperature=(0.8 if i % 3 == 0 else 0.0),
                                  ignore_eos=True))
    eng = sp_engine(cfg, params, sp=2, chunk=8)
    got, handles = run_engine(eng, prompts, sps)
    assert eng.n_recompiles == 0
    flags = [h.long_prompt for h in handles]
    assert flags == [n > eng.prompt_pane for n in (40, 5) * 4]
    eng.shutdown()
    for out, p, s in zip(got, prompts, sps):
        assert out == solo_tokens(params, cfg, p, s)


def test_sp_composes_with_paged_int8(model):
    """sp=2 + paged KV + int8 quant: long prompts land in the shared
    page pool, outputs still match generate(), the ledger stays
    byte-exact, and no pane copies happen (pages are copy-free)."""
    cfg, params = model
    rng = np.random.default_rng(2)
    prompts = [rng.integers(0, cfg.vocab_size, (40,)).astype(np.int32)
               for _ in range(3)]
    sps = [SamplingParams(max_new_tokens=5, seed=i, ignore_eos=True)
           for i in range(3)]
    pol = KVCachePolicy(prefill_chunk=8, paged=True, page_tokens=8,
                        kv_quant="int8")
    eng = sp_engine(cfg, params, sp=2, kv_policy=pol)
    got, handles = run_engine(eng, prompts, sps)
    assert eng.n_recompiles == 0
    assert all(h.long_prompt for h in handles)
    eng.memory_ledger.observe(eng.n_ticks)
    desc = eng.memory_ledger.describe()
    assert desc["components"]["page_pool"] == cache_nbytes(eng.cache)
    eng.shutdown()
    # int8 KV is NOT bit-exact vs the fp oracle; parity is vs the
    # unsharded engine under the SAME policy — sp must add zero error
    ref = DecodeEngine(cfg, params, n_slots=2,
                       kv_policy=KVCachePolicy(prefill_chunk=8, paged=True,
                                               page_tokens=8,
                                               kv_quant="int8"))
    ref_out, _ = run_engine(ref, prompts, sps)
    ref.shutdown()
    assert got == ref_out


# ---------------------------------------------------------------------------
# telemetry
# ---------------------------------------------------------------------------

def test_warmup_event_reports_sp_geometry(model, sink):
    """serve_warmup carries sp/prompt_pane_tokens/max_prompt on sp
    engines (and omits them off-sp); request_done flags long prompts;
    tick cadence books prefill under prefill_shard."""
    cfg, params = model
    rng = np.random.default_rng(3)
    eng = sp_engine(cfg, params, sp=2, chunk=8, metrics_every=2)
    prompts = [rng.integers(0, cfg.vocab_size, (40,)).astype(np.int32),
               rng.integers(0, cfg.vocab_size, (5,)).astype(np.int32)]
    sps = [SamplingParams(max_new_tokens=4, ignore_eos=True)] * 2
    run_engine(eng, prompts, sps)
    eng.shutdown()
    rows = [json.loads(line) for line in open(sink)]
    warm = [r for r in rows if r.get("event") == "serve_warmup"]
    assert warm and warm[0]["sp"] == 2
    assert warm[0]["prompt_pane_tokens"] == eng.prompt_pane
    assert warm[0]["max_prompt"] == eng.max_prompt
    done = [r for r in rows if r.get("event") == "request_done"]
    assert sorted(bool(r.get("long_prompt")) for r in done) == [False, True]
    ticks = [r for r in rows if r.get("type") == "metrics"
             and "tick_prefill_shard_s" in r]
    assert ticks and sum(r["tick_prefill_shard_s"] for r in ticks) > 0
    # the plain prefill phase stays zero: sp engines book the chunk
    # pump under prefill_shard exclusively
    assert sum(r.get("tick_prefill_s", 0) for r in ticks) == 0


def test_warmup_event_omits_sp_fields_off_sp(model, sink):
    cfg, params = model
    eng = DecodeEngine(cfg, params, n_slots=2,
                       kv_policy=KVCachePolicy(prefill_chunk=8))
    eng.warmup()
    eng.shutdown()
    rows = [json.loads(line) for line in open(sink)]
    warm = [r for r in rows if r.get("event") == "serve_warmup"]
    assert warm and "sp" not in warm[0]


# ---------------------------------------------------------------------------
# mesh-plan geometry
# ---------------------------------------------------------------------------

def test_serve_mesh_plan_sp_geometry():
    plan = serve_mesh_plan(sp=2)
    assert plan.mesh.shape == {"data": 1, "seq": 2, "model": 1}
    assert plan.n_seq == 2 and plan.n_model == 1
    plan2 = serve_mesh_plan(2, sp=2)
    assert plan2.mesh.shape == {"data": 1, "seq": 2, "model": 2}
    with pytest.raises(ValueError):
        serve_mesh_plan(sp=0)


def test_partition_serve_devices_sp():
    from building_llm_from_scratch_tpu.parallel.sharding import (
        partition_serve_devices,
    )

    slices = partition_serve_devices(2, 1, 2)
    assert len(slices) == 2
    assert all(len(s) == 2 for s in slices)
    # disjoint when 2 replicas x (sp=2) = 4 <= 8 devices
    ids = [d.id for s in slices for d in s]
    assert len(set(ids)) == 4
    with pytest.raises(ValueError, match="exceeds"):
        partition_serve_devices(1, 4, 4)
