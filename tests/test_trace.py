"""Serving observability tests: request-span tracing + Chrome trace
export (obs/trace.py), the per-tick engine phase breakdown, the
Prometheus ``/metrics`` endpoint and structured ``/healthz``, the
histogram/rolling-window aggregation primitives, and the serving
extension of the no-per-step-host-sync guard (instrumentation must add
ZERO device fetches to the decode tick).
"""

import http.client
import json
import threading
import time

import jax
import numpy as np
import pytest

from building_llm_from_scratch_tpu.configs import ModelConfig
from building_llm_from_scratch_tpu.models import init_params
from building_llm_from_scratch_tpu.obs import (
    Histogram,
    RollingRatio,
    chrome_trace,
    configure_metrics,
    export_chrome_trace,
    render_prometheus,
)
from building_llm_from_scratch_tpu.obs.trace import TICK_PHASES
from building_llm_from_scratch_tpu.serving import (
    DecodeEngine,
    QueueFullError,
    SamplingParams,
    SLOShedError,
)


def tiny_cfg(ctx=64, **kw):
    base = dict(name="trace-tiny", vocab_size=96, context_length=ctx,
                emb_dim=32, n_heads=2, n_layers=2, hidden_dim=64,
                n_kv_groups=2, norm="layernorm", positional="learned",
                activation="gelu", drop_rate=0.0, eos_id=1)
    base.update(kw)
    return ModelConfig(**base)


@pytest.fixture(scope="module")
def model():
    cfg = tiny_cfg()
    return cfg, init_params(cfg, jax.random.PRNGKey(0))


@pytest.fixture
def sink(tmp_path):
    """A fresh JSONL metrics sink for one test; always detached after."""
    path = tmp_path / "metrics.jsonl"
    logger = configure_metrics(str(path), run_metadata={"test": True})
    yield str(path)
    logger.close()
    configure_metrics(None)


def load_rows(path):
    return [json.loads(line) for line in open(path)]


# ---------------------------------------------------------------------------
# aggregation primitives (no jax)
# ---------------------------------------------------------------------------

def test_histogram_bucket_counts_match_observations():
    h = Histogram(bounds=(0.01, 0.1, 1.0))
    values = [0.005, 0.005, 0.05, 0.5, 5.0]        # 2 / 1 / 1 / 1(+Inf)
    for v in values:
        h.observe(v)
    snap = h.snapshot()
    assert snap["count"] == len(values)
    assert snap["sum"] == pytest.approx(sum(values))
    assert snap["buckets"] == [(0.01, 2), (0.1, 3), (1.0, 4), ("+Inf", 5)]
    # upper-edge inclusivity (prometheus `le` semantics)
    h2 = Histogram(bounds=(1.0, 2.0))
    h2.observe(1.0)
    assert h2.snapshot()["buckets"][0] == (1.0, 1)
    # percentile interpolates inside the target bucket; +Inf clamps
    assert 0.0 < h.percentile(10) <= 0.01
    assert h.percentile(99) == 1.0                  # clamped to last bound
    assert Histogram().percentile(50) is None       # empty


def test_rolling_ratio_window_expires_old_misses():
    r = RollingRatio(window_s=10.0, n_buckets=5)
    t0 = 1000.0
    r.observe(True, now=t0)
    r.observe(True, now=t0)
    r.observe(False, now=t0 + 1)
    assert r.ratio(now=t0 + 1) == pytest.approx(2 / 3)
    # 11s later the misses have aged out; only fresh observations count
    r.observe(False, now=t0 + 12)
    assert r.ratio(now=t0 + 12) == 0.0
    assert RollingRatio().ratio() is None           # nothing observed


def test_render_prometheus_exposition_format():
    h = Histogram(bounds=(0.1, 1.0))
    h.observe(0.05)
    h.observe(5.0)
    text = render_prometheus({"done": 3}, {"occupancy": 0.5},
                             {"ttft_seconds": h}, prefix="x_")
    lines = text.splitlines()
    assert "x_done_total 3" in lines
    assert "x_occupancy 0.5" in lines
    assert 'x_ttft_seconds_bucket{le="0.1"} 1' in lines
    assert 'x_ttft_seconds_bucket{le="+Inf"} 2' in lines
    assert "x_ttft_seconds_count 2" in lines
    # every non-comment line is "name{labels} value" with a float value
    for line in lines:
        if line.startswith("#") or not line:
            continue
        name, value = line.rsplit(" ", 1)
        float(value)
        assert name[0].isalpha()


# ---------------------------------------------------------------------------
# request span trees + Chrome trace export
# ---------------------------------------------------------------------------

def test_request_spans_and_chrome_export_round_trip(model, sink, tmp_path):
    cfg, params = model
    eng = DecodeEngine(cfg, params, n_slots=2, max_len=64, metrics_every=2)
    eng.warmup()
    handles = [eng.submit(np.array([3, 4, 5], np.int32),
                          SamplingParams(max_new_tokens=5, ignore_eos=True,
                                         seed=i))
               for i in range(3)]
    eng.run_until_idle()
    for h in handles:
        h.result(timeout=10)
    eng.shutdown()
    rows = load_rows(sink)
    spans = [r for r in rows if r.get("type") == "span"]
    done = [r for r in rows if r.get("event") == "request_done"]
    # exactly one span row per completed request
    assert len(spans) == len(done) == 3
    for s in spans:
        assert s["name"] == "request" and s["outcome"] == "length"
        kids = {c["name"]: c for c in s["children"]}
        assert set(kids) == {"queued", "prefill", "decode"}
        # children nest inside the root span and all spans are closed
        t0, t1 = s["t0"], s["t0"] + s["dur_s"]
        for c in s["children"]:
            assert c["dur_s"] >= 0
            assert c["t0"] >= t0 - 1e-6
            assert c["t0"] + c["dur_s"] <= t1 + 1e-6
        # phases tile the root span in lifecycle order
        assert kids["queued"]["t0"] <= kids["prefill"]["t0"]
        assert kids["prefill"]["t0"] <= kids["decode"]["t0"]

    out = tmp_path / "trace.json"
    meta = export_chrome_trace(sink, str(out))
    assert meta["n_request_spans"] == 3
    assert meta["n_tick_windows"] >= 1
    trace = json.load(open(out))                   # valid JSON round-trip
    events = trace["traceEvents"]
    assert events
    xs = [e for e in events if e["ph"] == "X"]
    for e in events:
        assert e["ph"] in ("X", "i", "C", "M")
        if e["ph"] == "X":
            assert e["dur"] >= 0 and e["ts"] >= 0
    # one root request slice per request_done, on that request's track
    roots = [e for e in xs if e["name"] == "request"]
    assert len(roots) == 3
    assert len({e["tid"] for e in roots}) == 3
    # tick windows made it out too
    assert any(e["name"].startswith("ticks") for e in xs)


def test_trace_lifecycle_audit_every_outcome_closes_one_tree(model, sink):
    """Satellite: submit one request per terminal outcome (done, rejected,
    shed, expired, failed) and assert the trace joins never drop one —
    every lifecycle event carries request_id (and reason), and every id
    closes exactly one span tree."""
    cfg, params = model
    eng = DecodeEngine(cfg, params, n_slots=1, max_len=64, max_queue=1,
                       metrics_every=0)
    eng.warmup()

    # DONE
    done_h = eng.submit(np.array([3, 4], np.int32),
                        SamplingParams(max_new_tokens=3, ignore_eos=True))
    eng.run_until_idle()
    done_h.result(timeout=10)

    # FAILED: a raising client callback is the request's own fault
    def bad_cb(req, tok, piece):
        raise RuntimeError("client exploded")

    failed_h = eng.submit(np.array([5], np.int32),
                          SamplingParams(max_new_tokens=3, ignore_eos=True),
                          on_token=bad_cb)
    eng.run_until_idle()
    with pytest.raises(RuntimeError):
        failed_h.result(timeout=10)

    # REJECTED: queue capacity 1, nothing ticking
    held = eng.submit(np.array([6], np.int32),
                      SamplingParams(max_new_tokens=2, ignore_eos=True))
    with pytest.raises(QueueFullError):
        eng.submit(np.array([7], np.int32),
                   SamplingParams(max_new_tokens=2, ignore_eos=True))

    # EXPIRED: deadline passes while queued
    eng.run_until_idle()                            # finishes `held`
    held.result(timeout=10)
    expired_h = eng.submit(np.array([8], np.int32),
                           SamplingParams(max_new_tokens=2,
                                          ignore_eos=True,
                                          deadline_s=0.01))
    time.sleep(0.05)                                # deadline passes
    eng.run_until_idle()
    from building_llm_from_scratch_tpu.serving.request import (
        RequestExpiredError,
    )

    with pytest.raises(RequestExpiredError):
        expired_h.result(timeout=10)

    # SHED: service EWMAs exist now; an impossible deadline is rejected
    # at submit (predicted miss), without ever entering the queue
    with pytest.raises(SLOShedError):
        eng.submit(np.array([9], np.int32),
                   SamplingParams(max_new_tokens=60, ignore_eos=True,
                                  deadline_s=1e-6))
    eng.shutdown()

    rows = load_rows(sink)
    events = [r for r in rows if r.get("type") == "event"]
    spans = [r for r in rows if r.get("type") == "span"]
    by_kind = {}
    for e in events:
        by_kind.setdefault(e["event"], []).append(e)
    # every lifecycle event names its request and its reason
    for kind in ("request_rejected", "request_shed", "request_expired",
                 "request_failed"):
        assert by_kind.get(kind), f"missing {kind} event"
        for e in by_kind[kind]:
            assert isinstance(e.get("request_id"), int), (kind, e)
            assert e.get("reason"), (kind, e)
    # exactly ONE closed span tree per request id, outcome attached
    by_id = {}
    for s in spans:
        by_id.setdefault(s["request_id"], []).append(s)
    assert all(len(v) == 1 for v in by_id.values()), by_id
    outcomes = {s["request_id"]: s["outcome"] for s in spans}
    expected = {"length", "error", "rejected", "shed", "expired"}
    assert expected <= set(outcomes.values()), outcomes
    for s in spans:
        assert s["dur_s"] >= 0 and s["children"], s
        assert s["children"][0]["name"] == "queued"
    # ... and the trace join sees them all (5 requests -> 5 trees:
    # done, failed, held/done, rejected, expired, shed = 6 actually)
    trace = chrome_trace(rows)
    assert trace["metadata"]["n_request_spans"] == len(spans) == 6


def test_trace_export_handles_training_fixture(tmp_path):
    """The exporter renders TRAINING runs too: the checked-in fixture's
    StepTimeline cadence rows become train windows and its compile events
    become slices — one exporter for both tiers."""
    out = tmp_path / "train_trace.json"
    meta = export_chrome_trace("tests/fixtures/metrics_fixture.jsonl",
                               str(out))
    assert meta["n_train_windows"] >= 1
    trace = json.load(open(out))
    xs = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    assert any(e["name"].startswith("steps") for e in xs)
    assert any(e["name"].startswith("compile:") for e in xs)
    assert any(e["cat"] == "steps_phase" for e in xs)
    # incidents (watchdog_halt in the fixture) land as instants
    instants = [e for e in trace["traceEvents"] if e["ph"] == "i"]
    assert any(e["name"] == "watchdog_halt" for e in instants)


# ---------------------------------------------------------------------------
# per-tick engine phase breakdown
# ---------------------------------------------------------------------------

def test_tick_phase_breakdown_sums_to_tick_wall_time(model, sink):
    cfg, params = model
    eng = DecodeEngine(cfg, params, n_slots=2, max_len=64, metrics_every=4)
    eng.warmup()
    handles = [eng.submit(np.array([3, 4, 5, 6], np.int32),
                          SamplingParams(max_new_tokens=12,
                                         ignore_eos=True, seed=i))
               for i in range(4)]
    eng.run_until_idle()
    for h in handles:
        h.result(timeout=10)
    eng.shutdown()
    rows = load_rows(sink)
    ticks = [r for r in rows if r.get("type") == "metrics"
             and isinstance(r.get("tick_total_s"), (int, float))
             and r.get("ticks_in_window")]
    assert ticks, "no serving cadence rows with a tick breakdown"
    for r in ticks:
        phase_sum = sum(r[f"tick_{ph}_s"] for ph in TICK_PHASES)
        total = r["tick_total_s"]
        # phases are measured sub-intervals of the tick: their sum can
        # never exceed the tick wall time, and the unattributed remainder
        # (branching, scheduler bookkeeping) must stay small
        assert phase_sum <= total * 1.02 + 1e-6, r
        assert phase_sum >= total * 0.5, r
        assert r["win_dur_s"] > 0 and r["win_t0"] > 0
    # cumulative totals cover the whole run for /metrics counters
    assert eng.tick_seconds_total > 0
    assert sum(eng.tick_phase_totals.values()) <= eng.tick_seconds_total * 1.02
    # decode must be a real, nonzero phase on every loaded window
    assert all(r["tick_decode_dispatch_s"] > 0 for r in ticks)


def test_tick_steady_state_has_zero_implicit_transfers(model):
    """Serving extension of the PR-3 no-per-step-host-sync guard, now via
    the transfer-guard sentry (analysis/runtime.py — replaces the old
    hand-rolled 'exactly 2 conversions per tick' spy): a full serving
    burst — admissions, prefill, decode ticks, retirement, cadence
    metrics flushes — runs with ZERO implicit device->host transfers.
    The tick's sanctioned fetches are explicit ``jax.device_get`` (which
    the sentry admits); anything implicit (a float()/np.asarray sneaking
    into the tick or the metrics flush) raises ImplicitTransferError.
    The KV cache must also never round-trip through the host."""
    from building_llm_from_scratch_tpu.analysis.runtime import (
        ImplicitTransferError,
        no_implicit_device_to_host,
    )

    import jax as _jax

    cfg, params = model
    eng = DecodeEngine(cfg, params, n_slots=2, max_len=64, metrics_every=2,
                       watch_compiles=False)
    eng.warmup()
    handles = [eng.submit(np.array([3, 4], np.int32),
                          SamplingParams(max_new_tokens=8, ignore_eos=True,
                                         seed=i))
               for i in range(3)]
    # count the EXPLICIT fetches too: the sentry proves nothing implicit
    # remains, and the spy keeps the old per-tick budget pinned — a new
    # device_get added to the tick (a real extra host sync, even though
    # explicit) must fail this test, not ship silently
    n_gets = {"n": 0}
    real_device_get = _jax.device_get

    def counting_device_get(x):
        n_gets["n"] += 1
        return real_device_get(x)

    _jax.device_get = counting_device_get
    try:
        with no_implicit_device_to_host():
            eng.run_until_idle()
    finally:
        _jax.device_get = real_device_get
    for h in handles:
        h.result(timeout=10)
    assert eng.n_ticks >= 8
    # the sanctioned budget: 2 fetches per decode tick (next-token row +
    # finite-ok mask) and 3 per admission (PRNG key, prefill ok, first
    # token) — nothing else
    assert n_gets["n"] == 2 * eng.n_ticks + 3 * len(handles), (
        n_gets, eng.n_ticks)
    # the KV cache stayed on device end to end
    import jax as _jax

    for pane in ("k", "v"):
        for layer in eng.cache[pane]:
            assert isinstance(layer, _jax.Array), type(layer)

    # the sentry has teeth on this very engine: an implicit fetch of a
    # device value inside the guarded region raises
    with pytest.raises(ImplicitTransferError):
        with no_implicit_device_to_host():
            float(eng.cache["k"][0][0, 0, 0, 0])
    eng.shutdown()


def test_trainer_step_off_cadence_has_zero_implicit_transfers(tmp_path):
    """The trainer twin: with every cadence (eval/sample/checkpoint/log)
    pushed beyond the horizon, a whole training epoch — step loop,
    deferred-DMA lr/health bookkeeping, the final metrics flush — runs
    under the transfer sentry. The sanctioned cadence fetch point
    (``Trainer._flush_metrics``) uses explicit ``jax.device_get``, so
    steady-state training performs zero implicit device->host
    transfers."""
    from building_llm_from_scratch_tpu.analysis.runtime import (
        no_implicit_device_to_host,
    )
    from building_llm_from_scratch_tpu.data.pretrain import PretrainLoader
    from building_llm_from_scratch_tpu.data.tokenizers import ByteTokenizer
    from building_llm_from_scratch_tpu.training.trainer import Trainer

    cfg = tiny_cfg(ctx=32, vocab_size=256, eos_id=0, name="sentry-train")
    tok = ByteTokenizer()
    datafile = tmp_path / "corpus.txt"
    datafile.write_text("steady state corpus " * 60)
    loader = PretrainLoader(tok, batch_size=4, max_length=cfg.context_length)
    trainer = Trainer(cfg, init_params(cfg, jax.random.PRNGKey(0)), tok,
                      loader, output_dir=str(tmp_path / "out"),
                      eval_freq=10**6, print_sample_iter=10**6,
                      save_ckpt_freq=10**6, warmup_steps=2, log_every=0,
                      show_progress=False)
    with no_implicit_device_to_host():
        trainer.train_model([str(datafile)], 1, start_context="the ")
    assert trainer.global_step >= 4
    # the deferred fetches DID land (explicitly) at the final flush
    assert len(trainer.track_lrs) == trainer.global_step


# ---------------------------------------------------------------------------
# /metrics + structured /healthz over HTTP
# ---------------------------------------------------------------------------

def _parse_exposition(text):
    """Tiny Prometheus text-format parser: {series_name: [(labels, value)]}
    — raises on any malformed line, which IS the format assertion."""
    series = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        name_part, value = line.rsplit(" ", 1)
        if "{" in name_part:
            name, labels = name_part.split("{", 1)
            assert labels.endswith("}")
            labels = labels[:-1]
        else:
            name, labels = name_part, ""
        series.setdefault(name, []).append((labels, float(value)))
    return series


def test_metrics_endpoint_exposition_and_structured_healthz(model):
    cfg, params = model
    from building_llm_from_scratch_tpu.serving.frontend import (
        make_http_server,
    )

    eng = DecodeEngine(cfg, params, n_slots=2, max_len=64)
    eng.warmup()
    eng.start()
    server = make_http_server(eng, 0, host="127.0.0.1")
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        for i in range(3):
            body = json.dumps({"prompt_ids": [5, 6, 7],
                               "max_new_tokens": 4, "ignore_eos": True,
                               "seed": i, "deadline_s": 60.0})
            conn.request("POST", "/generate", body=body)
            assert conn.getresponse().status == 200

        conn.request("GET", "/metrics")
        resp = conn.getresponse()
        assert resp.status == 200
        assert resp.getheader("Content-Type").startswith("text/plain")
        series = _parse_exposition(resp.read().decode())

        pre = "bllm_serve_"
        assert series[pre + "requests_finished_total"][0][1] == 3
        # histogram bucket counts match the number of finished requests
        for h in ("ttft_seconds", "e2e_seconds", "queue_wait_seconds"):
            buckets = dict(series[pre + h + "_bucket"])
            assert buckets['le="+Inf"'] == 3, (h, buckets)
            assert series[pre + h + "_count"][0][1] == 3
            # cumulative and monotone in `le`
            counts = [v for _, v in series[pre + h + "_bucket"]]
            assert counts == sorted(counts)
        # key gauges for the replica router
        assert pre + "slot_occupancy" in series
        assert pre + "queue_depth" in series
        assert pre + "engine_up" in series
        assert series[pre + "uptime_seconds"][0][1] > 0
        # deadline-carrying requests all finished in time -> burn 0.0
        assert series[pre + "slo_miss_ratio"][0][1] == 0.0
        # per-phase tick time is exported as counters
        assert pre + "tick_decode_dispatch_seconds_total" in series

        conn.request("GET", "/healthz")
        health = json.loads(conn.getresponse().read())
        assert health["status"] == "serving"
        assert health["slots"] == 2                 # compat fields intact
        assert health["uptime_s"] > 0
        assert health["n_ticks"] >= 1
        assert 0.0 <= health["occupancy"] <= 1.0
        assert health["counters"]["requests_finished"] == 3
        conn.close()
    finally:
        server.shutdown()
        server.server_close()
        eng.shutdown()
