"""Fault-injection harness: kill a real training process with SIGTERM
mid-epoch, relaunch with ``--resume auto``, and assert the run continues
from the checkpointed step with a loss trajectory identical to an
uninterrupted run (the ISSUE's preemption acceptance test).

The killed run happens in a subprocess (delivering SIGTERM to the pytest
process would stop pytest); the uninterrupted reference and the resumed
relaunch run in-process on the same forced-CPU platform, so the loss
comparison is bit-for-bit.
"""

import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from building_llm_from_scratch_tpu.args import get_args
from building_llm_from_scratch_tpu.main import main
from building_llm_from_scratch_tpu.training.resilience import CKPT_PREFIX

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "_fault_worker.py")

# long enough that the kill lands mid-epoch with wide margin (~180 steps at
# --debug size), short enough that the resumed run finishes quickly
TEXT = "Every effort moves you closer to mastery. " * 300


def _args(data_dir, out_dir, overlap=False):
    """``overlap=True`` turns on the host-overlap stack (batch prefetch +
    async checkpoint writes); the uninterrupted reference runs the strict
    synchronous path, so the bit-for-bit comparison at the bottom also
    proves the overlap machinery changes NOTHING about training."""
    extra = (["--prefetch", "2", "--async_ckpt", "on"] if overlap
             else ["--prefetch", "0"])
    return get_args([
        "--data_dir", data_dir, "--output_dir", out_dir,
        "--debug", "--byte_tokenizer", "--n_epochs", "1",
        "--batch_size", "4", "--eval_freq", "10",
        "--print_sample_iter", "100000", "--save_ckpt_freq", "5",
        "--warmup_steps", "2", "--keep_ckpts", "2", *extra,
    ])


def _step_tagged(out_dir):
    if not os.path.isdir(out_dir):
        return []
    return sorted(
        name for name in os.listdir(out_dir)
        if name.startswith(CKPT_PREFIX)
        and name[len(CKPT_PREFIX):].isdigit()
        and os.path.isfile(os.path.join(out_dir, name, "manifest.json")))


@pytest.mark.slow
def test_sigterm_preemption_then_auto_resume_matches_uninterrupted(tmp_path):
    data_dir = tmp_path / "data"
    data_dir.mkdir()
    (data_dir / "corpus.txt").write_text(TEXT)
    out_ref = str(tmp_path / "out_ref")
    out_kill = str(tmp_path / "out_kill")

    # 1. uninterrupted reference run (in-process)
    ref = main(_args(str(data_dir), out_ref))
    assert ref.global_step > 20 and len(ref.train_losses) >= 4

    # 2. killed run: subprocess, SIGTERM as soon as the first periodic
    #    checkpoint commits
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)          # worker sets its own device count
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [sys.executable, WORKER, str(data_dir), out_kill],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd=REPO, env=env)
    try:
        deadline = time.monotonic() + 300
        while not _step_tagged(out_kill):
            if proc.poll() is not None:
                pytest.fail("worker exited before its first checkpoint:\n"
                            + proc.communicate()[0])
            if time.monotonic() > deadline:
                pytest.fail("worker wrote no checkpoint within 300s")
            time.sleep(0.05)
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=180)
    finally:
        if proc.poll() is None:
            proc.kill()
    # graceful stop: checkpoint written, exit code 0 (not 143)
    assert proc.returncode == 0, f"worker rc={proc.returncode}:\n{out}"
    assert "preempted=True" in out, out
    interrupted = os.path.join(out_kill, "model_pg_interrupted")
    assert os.path.isfile(os.path.join(interrupted, "manifest.json")), out
    # retention GC ran in the worker too
    assert len(_step_tagged(out_kill)) <= 2, _step_tagged(out_kill)
    # the resilience actions left a structured event trail in the metrics
    # sink (obs/): the SIGTERM signal, the step-boundary stop, and the
    # interrupted checkpoint's save — with the header as the first row
    import json as _json

    with open(os.path.join(out_kill, "metrics.jsonl")) as f:
        rows = [_json.loads(line) for line in f if line.strip()]
    assert rows[0]["type"] == "header"
    events = {r["event"] for r in rows if r["type"] == "event"}
    assert {"preemption_signal", "preemption_stop",
            "checkpoint_save"} <= events, events
    assert any(r.get("event") == "checkpoint_save"
               and r["path"].endswith("model_pg_interrupted")
               for r in rows), events

    # 3. relaunch with the SAME command: --resume auto (the default) must
    #    discover the interrupted checkpoint, fast-forward the data cursor,
    #    and finish the epoch — WITH the overlap stack on (prefetch + async
    #    saves, matching the killed worker's flags), against the
    #    synchronous reference
    resumed = main(_args(str(data_dir), out_kill, overlap=True))
    assert not resumed.preempted
    assert resumed.global_step == ref.global_step
    assert resumed.tokens_seen == ref.tokens_seen

    # 4. the post-resume eval-loss trajectory is IDENTICAL to the
    #    uninterrupted run's (deterministic data order via the cursor,
    #    restored optimizer/rng state): bit-for-bit, not approximately
    n = len(resumed.train_losses)
    assert n >= 1
    np.testing.assert_array_equal(
        np.asarray(resumed.train_losses),
        np.asarray(ref.train_losses[-n:]))
    np.testing.assert_array_equal(
        np.asarray(resumed.val_losses),
        np.asarray(ref.val_losses[-n:]))
