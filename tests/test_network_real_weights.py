"""Opt-in real-checkpoint smoke tests (round-3 VERDICT weakness #7/#8).

These exercise the PRODUCTION hub tables end-to-end with real downloads:
GPT-2 124M weights through the torch-free safetensors reader, and a known
greedy continuation checked against transformers' reference output. They
run only when the network is reachable:

  python -m pytest tests/test_network_real_weights.py -m network -q

and guard the repo/filename tables in weights/fetch.py:238-256 that offline
tests can only cover with mocks.
"""

import socket

import numpy as np
import pytest


def _online(host="huggingface.co", timeout=5) -> bool:
    try:
        socket.create_connection((host, 443), timeout=timeout).close()
        return True
    except OSError:
        return False


pytestmark = [
    pytest.mark.network,
    pytest.mark.skipif(not _online(), reason="no network: real-download "
                       "smoke tests need huggingface.co"),
]


def test_real_gpt2_weights_greedy_continuation(tmp_path):
    """Download real GPT-2 124M, load through the torch-free path, and
    check a greedy continuation matches transformers' GPT2LMHeadModel."""
    import torch
    from transformers import GPT2LMHeadModel, GPT2TokenizerFast

    from building_llm_from_scratch_tpu.configs import get_config
    from building_llm_from_scratch_tpu.generate import generate
    from building_llm_from_scratch_tpu.weights.fetch import load_hf_weights

    cache = str(tmp_path / "hf")
    # qkv_bias=True matches HF GPT-2 (reference build_components.py:69-70)
    cfg = get_config("GPT2", "124M", qkv_bias=True)
    params = load_hf_weights("GPT2", "124M", cfg, cache_dir=cache)

    tok = GPT2TokenizerFast.from_pretrained("gpt2", cache_dir=cache)
    prompt = "The capital of France is"
    ids = np.asarray([tok.encode(prompt)], np.int32)

    ours = generate(params, cfg, ids, max_new_tokens=8,
                    context_size=cfg.context_length, temperature=0.0)
    ours_text = tok.decode(np.asarray(ours)[0])

    ref = GPT2LMHeadModel.from_pretrained("gpt2", cache_dir=cache).eval()
    with torch.no_grad():
        ref_out = ref.generate(torch.tensor(ids, dtype=torch.long),
                               max_new_tokens=8, do_sample=False)
    ref_text = tok.decode(ref_out[0])
    assert ours_text == ref_text


def test_real_llama32_tokenizer_roundtrip():
    """Download Meta's real tokenizer.model via the auto-fetch table and
    check the documented special-token layout."""
    from building_llm_from_scratch_tpu.data.tokenizers import build_tokenizer

    tk = build_tokenizer("llama3_2", None)
    assert tk.vocab_size == 128_256
    assert tk.eos_id == 128_001
    text = "Hello, TPU world!"
    assert tk.decode(tk.encode(text)) == text
