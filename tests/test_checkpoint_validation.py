"""Checkpoint validation + dtype fidelity (ADVICE round-1 items)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from building_llm_from_scratch_tpu.training.checkpoint import (
    export_params,
    load_checkpoint,
    load_exported_params,
    save_checkpoint,
)


def test_load_rejects_wrong_shape(tmp_path):
    state = {"w": jnp.ones((4, 4)), "b": jnp.zeros((4,))}
    save_checkpoint(str(tmp_path / "ck"), state)
    bigger = {"w": jnp.ones((8, 8)), "b": jnp.zeros((8,))}
    with pytest.raises(ValueError, match="shape"):
        load_checkpoint(str(tmp_path / "ck"), bigger)


def test_load_rejects_wrong_dtype(tmp_path):
    state = {"w": jnp.ones((4, 4), jnp.float32)}
    save_checkpoint(str(tmp_path / "ck"), state)
    with pytest.raises(ValueError, match="dtype"):
        load_checkpoint(str(tmp_path / "ck"),
                        {"w": jnp.ones((4, 4), jnp.bfloat16)})


def test_bf16_checkpoint_roundtrip(tmp_path):
    state = {"w": (jnp.arange(12, dtype=jnp.float32) / 7.0
                   ).astype(jnp.bfloat16).reshape(3, 4),
             "n": jnp.asarray(3, jnp.int32)}
    save_checkpoint(str(tmp_path / "ck"), state)
    got = load_checkpoint(str(tmp_path / "ck"), jax.tree_util.tree_map(
        jnp.zeros_like, state))
    assert got["w"].dtype == jnp.bfloat16
    np.testing.assert_array_equal(np.asarray(got["w"], np.float32),
                                  np.asarray(state["w"], np.float32))
    assert int(got["n"]) == 3


def test_bf16_export_roundtrip(tmp_path):
    params = {"head": {"weight": (jnp.arange(8, dtype=jnp.float32)
                                  ).astype(jnp.bfloat16).reshape(2, 4)}}
    p = str(tmp_path / "m.npz")
    export_params(p, params)
    got = load_exported_params(p, jax.tree_util.tree_map(jnp.zeros_like,
                                                         params))
    assert got["head"]["weight"].dtype == jnp.bfloat16
    np.testing.assert_array_equal(
        np.asarray(got["head"]["weight"], np.float32),
        np.asarray(params["head"]["weight"], np.float32))


def test_rng_impl_change_keeps_fresh_key(tmp_path):
    """A checkpoint written under a different default PRNG impl (threefry
    (2,) vs rbg (4,) keys) must resume with a fresh rng + warning, not brick
    the run on the shape cross-check (round-3 review finding)."""
    state = {"w": jnp.ones((4, 4)),
             "rng": jnp.zeros((4,), jnp.uint32)}       # rbg-shaped key
    save_checkpoint(str(tmp_path / "ck"), state)
    template = {"w": jnp.zeros((4, 4)),
                "rng": jnp.asarray([7, 9], jnp.uint32)}  # threefry-shaped
    got = load_checkpoint(str(tmp_path / "ck"), template)
    np.testing.assert_array_equal(np.asarray(got["w"]), np.ones((4, 4)))
    # the template's key survives untouched
    np.testing.assert_array_equal(np.asarray(got["rng"]), [7, 9])
