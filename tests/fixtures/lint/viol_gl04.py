"""Seeded GL04x violations: telemetry-schema drift.

NOT importable production code — a fixture the analyzer tests run the
checkers over. Line positions matter to the tests; edit with care.
"""

from building_llm_from_scratch_tpu.obs.metrics import emit_event, get_metrics

# line 10: GL044 — private copy of a schema table
TICK_PHASES = ("admit", "prefill", "decode_dispatch")


def emit_everything(sink):
    emit_event("totally_unknown_event", foo=1)        # line 15: GL041
    emit_event("checkpoint_save", path="/x",
               made_up_field=3)                       # line 17: GL042
    emit_event("checkpoint_save", seconds=1.0)        # line 18: GL043 (no path)
    sink.event("retry", describe="fetch", attempt=1)  # fine
    get_metrics().event("request_failed",
                        request_id=1, reason="x")     # fine
