"""Seeded GL02x violations: impurity / recompile hazards under jit.

NOT importable production code — a fixture the analyzer tests run the
checkers over. Line positions matter to the tests; edit with care.
"""

import random
import time

import jax
import jax.numpy as jnp


def impure_step(state, batch, flag):
    print("tracing impure_step")            # line 15: GL021
    t0 = time.perf_counter()                # line 16: GL022
    noise = random.random()                 # line 17: GL023
    if flag:                                # line 18: GL024 (traced arg)
        state = state + noise
    return state + batch.sum() + t0


jitted = jax.jit(impure_step)


class Holder:
    def jit_method(self, x):
        self.last_x = x                     # line 28: GL025 (self write)
        return jnp.tanh(x)

    def build(self):
        self._fn = jax.jit(self.jit_method)
        return self._fn


def fresh_jit_every_call(params, x):
    # line 37: GL026 — fresh lambda jitted per call defeats the jit cache
    fwd = jax.jit(lambda p, t: (p * t).sum())
    return fwd(params, x)
