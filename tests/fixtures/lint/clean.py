"""A clean fixture: hot path, jitted function, guarded fields and event
emissions all conforming — the analyzers must report ZERO findings here.
"""

import threading

import jax

from building_llm_from_scratch_tpu.obs.metrics import emit_event


# graft: hot-path
def hot_loop(stream):
    total = 0.0
    for step_out in stream:
        host = jax.device_get(step_out)     # explicit: sanctioned
        total += float(host)                # host-typed via device_get
    return total


def pure_step(state, batch):
    return state + batch.sum()


jitted = jax.jit(pure_step)


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self.hits = 0                        # guarded-by: _lock

    def bump(self):
        with self._lock:
            self.hits += 1

    def snapshot(self):
        lock = self._lock
        with lock:                           # alias resolution
            return self.hits


def emit(step):
    emit_event("checkpoint_save", path="/tmp/x", seconds=0.5, step=step)
