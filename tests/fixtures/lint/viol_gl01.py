"""Seeded GL01x violations: implicit device->host syncs in a hot path.

NOT importable production code — a fixture the analyzer tests run the
checkers over. Line positions matter to the tests; edit with care.
"""

import numpy as np


# graft: hot-path
def hot_loop(stream, device_value):
    total = 0.0
    for step_out in stream:
        total += float(step_out)            # line 14: GL011
        arr = np.asarray(device_value)      # line 15: GL012
        scalar = device_value.item()        # line 16: GL013
        listed = device_value.tolist()      # line 17: GL012
        suppressed = int(step_out)          # graft-ok: GL011 host counter
        del arr, scalar, listed, suppressed
    return total


def cold_path(device_value):
    # same constructs OUTSIDE a registered/marked hot path: not flagged
    return float(device_value) + np.asarray(device_value).sum()
