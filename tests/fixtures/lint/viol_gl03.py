"""Seeded GL03x violations: lock-discipline breaches + an order cycle.

NOT importable production code — a fixture the analyzer tests run the
checkers over. Line positions matter to the tests; edit with care.
"""

import threading


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self.hits = 0                        # guarded-by: _lock
        self.phantom = 0                     # guarded-by: _no_such_lock

    def locked_bump(self):
        with self._lock:
            self.hits += 1                   # fine: under the lock

    def racy_bump(self):
        self.hits += 1                       # line 21: GL031 (write)

    def racy_read(self):
        return self.hits                     # line 24: GL031 (read)

    def suppressed_read(self):
        return self.hits                     # graft-ok: GL031 display only

    # holds: _lock
    def documented_helper(self):
        self.hits += 1                       # fine: caller holds it


class AB:
    """Acquires lock_a, then calls into BA (which takes lock_b)."""

    def __init__(self, other):
        self.lock_a = threading.Lock()
        self.other = other

    def forward(self):
        with self.lock_a:
            self.other.take_b()             # edge: AB.lock_a -> BA.lock_b

    def take_a(self):
        with self.lock_a:
            pass


class BA:
    """Acquires lock_b, then calls into AB (which takes lock_a) —
    closing the cycle AB.lock_a -> BA.lock_b -> AB.lock_a (GL032)."""

    def __init__(self, other):
        self.lock_b = threading.Lock()
        self.other = other

    def take_b(self):
        with self.lock_b:
            pass

    def backward(self):
        with self.lock_b:
            self.other.take_a()             # edge: BA.lock_b -> AB.lock_a
