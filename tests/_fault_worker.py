"""Worker for the SIGTERM fault-injection test (spawned by
tests/test_fault_injection.py — not collected by pytest).

Runs the real CLI ``main()`` on the forced-CPU platform so the parent test
can deliver a genuine SIGTERM mid-epoch: the GracefulStopper installed by
main() must checkpoint at the next step boundary and exit 0. The final
line reports whether the run observed the preemption and at which step.
"""

import os
import sys

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def main():
    data_dir, out_dir = sys.argv[1], sys.argv[2]
    from building_llm_from_scratch_tpu.args import get_args
    from building_llm_from_scratch_tpu.main import main as run_main

    args = get_args([
        "--data_dir", data_dir, "--output_dir", out_dir,
        "--debug", "--byte_tokenizer", "--n_epochs", "1",
        "--batch_size", "4", "--eval_freq", "10",
        "--print_sample_iter", "100000", "--save_ckpt_freq", "5",
        "--warmup_steps", "2", "--keep_ckpts", "2",
        # host-overlap round: the killed run exercises the FULL overlap
        # stack — batch prefetching and async checkpoint writes — so the
        # SIGTERM lands while a prefetch worker is staging batches and
        # periodic saves are committing on a background thread. The
        # graceful stop must still tear both down cleanly and leave a
        # durable interrupted checkpoint.
        "--prefetch", "2", "--async_ckpt", "on",
        # structured telemetry: the parent test asserts the preemption +
        # checkpoint events landed in the sink (rows flush per write, so
        # the file is complete even though this process gets SIGTERMed)
        "--metrics_jsonl", os.path.join(out_dir, "metrics.jsonl"),
    ])
    trainer = run_main(args)
    print(f"WORKER_EXIT preempted={trainer.preempted} "
          f"step={trainer.global_step}", flush=True)


if __name__ == "__main__":
    main()
