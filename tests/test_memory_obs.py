"""Memory observatory tests (obs/memory.py + engine/trainer/trace
integration): byte-exact ledger components vs live pytree ``nbytes``
(fp32 / bf16 / int8+sidecar), reconcile/growth/probe/pressure
detectors (injected pinned-pane leak fires ``memory_drift`` naming the
component), per-namespace and per-tenant attribution, request_done
``kv_bytes_peak``/``prefix_bytes_saved``, zero recompiles + zero
implicit transfers with the ledger armed at tick cadence, and
byte-deterministic Perfetto memory counter tracks.
"""

import json

import jax
import numpy as np
import pytest

from building_llm_from_scratch_tpu.configs import ModelConfig
from building_llm_from_scratch_tpu.models import init_params
from building_llm_from_scratch_tpu.obs.memory import (
    MemoryLedger,
    pytree_nbytes,
)
from building_llm_from_scratch_tpu.obs.metrics import configure_metrics
from building_llm_from_scratch_tpu.serving import (
    DecodeEngine,
    KVCachePolicy,
    SamplingParams,
)
from building_llm_from_scratch_tpu.serving.kvcache import cache_nbytes


def tiny_cfg(ctx=256, **kw):
    base = dict(name="mem-tiny", vocab_size=96, context_length=ctx,
                emb_dim=32, n_heads=2, n_layers=2, hidden_dim=64,
                n_kv_groups=2, norm="layernorm", positional="learned",
                activation="gelu", drop_rate=0.0, eos_id=1)
    base.update(kw)
    return ModelConfig(**base)


@pytest.fixture(scope="module")
def model():
    cfg = tiny_cfg()
    return cfg, init_params(cfg, jax.random.PRNGKey(0))


def shared_prefix_prompts(cfg, n, prefix_len=40, seed=0):
    rng = np.random.default_rng(seed)
    prefix = rng.integers(2, cfg.vocab_size, (prefix_len,)).astype(np.int32)
    return [np.concatenate([prefix, rng.integers(
        2, cfg.vocab_size, (2 + i % 3,)).astype(np.int32)])
        for i in range(n)]


def capture_ledger(**kw):
    """A ledger whose emitted events land in a plain list (no metrics
    sink), with device polling stubbed off unless a test injects it."""
    events = []

    def emit(kind, **fields):
        events.append((kind, fields))

    kw.setdefault("poll_device", False)
    kw.setdefault("auto_capacity", False)
    return MemoryLedger(emit=emit, **kw), events


# ---------------------------------------------------------------------------
# MemoryLedger units: measurement, watermarks, detectors
# ---------------------------------------------------------------------------

def test_snapshot_watermarks_and_totals():
    led, events = capture_ledger(source="unit")
    sizes = {"a": 100, "b": 7}
    led.register("a", lambda: sizes["a"])
    led.register("b", lambda: sizes["b"], device=False)
    led.observe()
    assert led.device_bytes() == 100 and led.host_bytes() == 7
    assert led.total_bytes() == 107
    assert led.headroom_bytes() is None        # CPU: capacity unknown
    sizes["a"] = 60                            # shrink: watermark sticks
    led.observe()
    assert led.sizes["a"] == 60 and led.watermarks["a"] == 100
    snaps = [f for k, f in events if k == "memory_snapshot"]
    assert len(snaps) == 2
    assert snaps[-1]["components"] == {"a": 60, "b": 7}
    assert snaps[-1]["source"] == "unit"
    assert "capacity_bytes" not in snaps[-1]   # n/a-safe by absence
    assert not [k for k, _ in events if k == "memory_drift"]


def test_reconcile_drift_is_byte_exact():
    led, events = capture_ledger()
    measured = {"n": 4096}
    led.register("slot_kv", lambda: measured["n"], expected=lambda: 4096)
    led.observe()
    assert not [k for k, _ in events if k == "memory_drift"]
    measured["n"] = 4097                       # off by ONE byte -> drift
    led.observe()
    drifts = [f for k, f in events if k == "memory_drift"]
    assert len(drifts) == 1
    d = drifts[0]
    assert d["component"] == "slot_kv" and d["reason"] == "reconcile"
    assert d["expected_bytes"] == 4096 and d["measured_bytes"] == 4097
    assert d["delta_bytes"] == 1
    assert led.n_drift_events == 1


def test_monotonic_growth_leak_detector_fires_once_and_rearms():
    led, events = capture_ledger(growth_streak=3)
    sizes = {"pool": 10}
    led.register("pool", lambda: sizes["pool"])
    for _ in range(4):                         # 3 consecutive grows
        led.observe()
        sizes["pool"] += 5
    drifts = [f for k, f in events if k == "memory_drift"]
    assert len(drifts) == 1
    assert drifts[0]["component"] == "pool"
    assert drifts[0]["reason"] == "monotonic_growth"
    assert drifts[0]["streak"] == 3
    led.observe()                              # still growing: fired once
    sizes["pool"] += 5
    led.observe()
    assert len([f for k, f in events if k == "memory_drift"]) == 1
    sizes["pool"] = 10                         # shrink: re-arm
    led.observe()
    for _ in range(4):
        led.observe()
        sizes["pool"] += 5
    assert len([f for k, f in events if k == "memory_drift"]) == 2


def test_pressure_flight_recorder_and_hysteresis():
    led, events = capture_ledger(capacity_bytes=1000, pressure_frac=0.9)
    sizes = {"kv": 500}
    led.register("kv", lambda: sizes["kv"])
    led.register_labeled("kv_live_bytes", "tenant",
                         lambda: {"base": sizes["kv"]})
    led.observe()
    assert not [k for k, _ in events if k == "memory_pressure"]
    sizes["kv"] = 950                          # upward crossing
    led.observe()
    led.observe()                              # still above: no re-fire
    press = [f for k, f in events if k == "memory_pressure"]
    assert len(press) == 1
    p = press[0]
    # the near-OOM dump: the FULL breakdown rides the event
    assert p["components"] == {"kv": 950}
    assert p["labeled"] == {"kv_live_bytes": {"base": 950}}
    assert p["capacity_bytes"] == 1000 and p["headroom_bytes"] == 50
    assert p["used_frac"] == 0.95
    sizes["kv"] = 500                          # fall below: re-arm
    led.observe()
    sizes["kv"] = 990
    led.observe()
    assert len([f for k, f in events if k == "memory_pressure"]) == 2
    assert led.n_pressure_events == 2


def test_labeled_attribution_peaks_and_gauges():
    led, _ = capture_ledger()
    live = {"ta": 10, "tb": 30}
    led.register("kv", lambda: sum(live.values()))
    led.register_labeled("kv_live_bytes", "tenant", lambda: dict(live))
    led.observe()
    live["ta"], live["tb"] = 50, 5             # ta peaks later, tb earlier
    led.observe()
    g = led.gauges()
    assert g['kv_live_bytes{tenant="ta"}'] == 50
    assert g['kv_live_bytes_peak{tenant="ta"}'] == 50
    assert g['kv_live_bytes{tenant="tb"}'] == 5
    assert g['kv_live_bytes_peak{tenant="tb"}'] == 30
    assert g['mem_component_bytes{component="kv"}'] == 55
    assert g["mem_total_bytes"] == 55


def test_probe_violation_fires_drift_with_custom_reason():
    led, events = capture_ledger()
    led.register("store", lambda: 64)
    state = {"pinned": 0}
    led.register_probe(
        "store",
        lambda: ({"reason": "pinned_orphan",
                  "pinned_bytes": state["pinned"]}
                 if state["pinned"] else None))
    led.observe()
    assert not [k for k, _ in events if k == "memory_drift"]
    state["pinned"] = 32
    led.observe()
    drifts = [f for k, f in events if k == "memory_drift"]
    assert len(drifts) == 1
    assert drifts[0]["component"] == "store"
    assert drifts[0]["reason"] == "pinned_orphan"
    assert drifts[0]["pinned_bytes"] == 32


def test_device_divergence_vs_runtime_accounting():
    led, events = capture_ledger(
        poll_device=True, device_drift_min_bytes=100,
        device_stats_fn=lambda: {"bytes_in_use": 10_000,
                                 "peak_bytes_in_use": 12_000},
        rss_fn=lambda: None)
    led.register("kv", lambda: 500)            # ledger knows 500 of 10000
    led.observe()
    drifts = [f for k, f in events if k == "memory_drift"]
    assert len(drifts) == 1
    assert drifts[0]["component"] == "device"
    assert drifts[0]["reason"] == "device_divergence"
    assert drifts[0]["device_bytes"] == 10_000
    assert drifts[0]["ledger_bytes"] == 500
    # the POLLED numbers stay out of the deterministic snapshot event...
    snap = [f for k, f in events if k == "memory_snapshot"][0]
    assert "hbm_bytes_in_use" not in snap
    assert snap["components"] == {"kv": 500}
    # ...and surface in the gauges instead
    g = led.gauges()
    assert g["hbm_bytes_in_use"] == 10_000
    assert g["hbm_peak_bytes"] == 12_000


def test_pytree_nbytes_matches_manual_sum(model):
    _cfg, params = model
    manual = sum(leaf.nbytes for leaf in jax.tree_util.tree_leaves(params))
    assert pytree_nbytes(params) == manual > 0


# ---------------------------------------------------------------------------
# Engine integration: byte-exact components across KV dtypes
# ---------------------------------------------------------------------------

def _slot_kv_sum(snap):
    return snap["slot_kv"] + snap.get("kv_scales", 0)


@pytest.mark.parametrize("dtype,policy", [
    ("fp32", KVCachePolicy()),
    ("bf16", KVCachePolicy()),
    ("fp32", KVCachePolicy(kv_quant="int8")),
], ids=["fp32", "bf16", "int8"])
def test_engine_slot_kv_byte_exact_vs_pytree(dtype, policy):
    """The acceptance invariant: the ledger's slot-KV (+ int8 sidecar)
    component equals BOTH the live cache pytree's nbytes sum and the
    policy's ``bytes_per_slot x n_slots`` — measured, expected, and
    actual all byte-identical, per KV dtype."""
    cfg = tiny_cfg(dtype=dtype)
    params = init_params(cfg, jax.random.PRNGKey(0))
    eng = DecodeEngine(cfg, params, n_slots=3, max_len=64,
                       warmup_prompt_cap=32, kv_policy=policy,
                       watch_compiles=False)
    snap = eng.memory_ledger.snapshot()
    bps = policy.bytes_per_slot(cfg, 64)
    assert snap["slot_kv"] == bps["kv_bytes"] * 3
    assert _slot_kv_sum(snap) == cache_nbytes(eng.cache)
    assert _slot_kv_sum(snap) == bps["total_bytes"] * 3
    if policy.quantized:
        assert snap["kv_scales"] == bps["scale_bytes"] * 3 > 0
    else:
        assert "kv_scales" not in snap
    assert snap["model_params"] == pytree_nbytes(eng.params)
    eng.shutdown()


def test_engine_spec_headroom_component(model):
    """With speculative decoding the cache rows are ``max_len + k`` long;
    the ledger carves the +k tail into its own component so slot_kv
    still reconciles byte-exactly against bytes_per_slot(max_len)."""
    cfg, params = model
    eng = DecodeEngine(cfg, params, n_slots=2, max_len=64,
                       warmup_prompt_cap=32, spec_k=4,
                       watch_compiles=False)
    snap = eng.memory_ledger.snapshot()
    bps = eng.kv_policy.bytes_per_slot(cfg, 64)
    assert snap["slot_kv"] == bps["kv_bytes"] * 2
    assert snap["spec_headroom"] > 0
    assert (snap["slot_kv"] + snap["spec_headroom"]
            == cache_nbytes(eng.cache))
    # no drift: expected callables cover the carve-out exactly
    events = []
    eng.memory_ledger._emit = lambda kind, **f: events.append(kind)
    eng.memory_ledger.observe()
    assert "memory_drift" not in events
    eng.shutdown()


def test_prefix_attribution_and_request_done_fields(model, tmp_path):
    """Live attribution end-to-end: per-namespace prefix-store bytes,
    per-tenant live-KV series, and the new ``request_done`` fields —
    ``kv_bytes_peak`` on every request, ``prefix_bytes_saved`` on the
    sharers whose prefix arrived by pane copy."""
    cfg, params = model
    mj = str(tmp_path / "m.jsonl")
    sink = configure_metrics(mj)
    sink.write_header(test="memory_obs_attribution")
    try:
        eng = DecodeEngine(cfg, params, n_slots=3, max_len=128,
                           warmup_prompt_cap=64, metrics_every=2,
                           kv_policy=KVCachePolicy(prefill_chunk=16,
                                                   prefix_cache=True))
        eng.warmup()
        prompts = shared_prefix_prompts(cfg, 3)
        sp = SamplingParams(max_new_tokens=4, ignore_eos=True, seed=0)
        eng.submit(prompts[0], sp)
        eng.run_until_idle()                  # donor stores the prefix
        for p in prompts[1:]:
            eng.submit(p, sp)
        eng.run_until_idle()
        snap = eng.memory_ledger.snapshot()
        assert snap["prefix_store"] == eng.prefix_store.bytes_total > 0
        assert (eng.prefix_store.bytes_by_tag()
                == {"base": eng.prefix_store.bytes_total})
        g = eng.memory_ledger.gauges()
        assert g['prefix_store_bytes{namespace="base"}'] > 0
        assert g['kv_live_bytes_peak{tenant="base"}'] > 0
        eng.shutdown()
    finally:
        sink.close()
        configure_metrics(None)
    rows = [json.loads(line) for line in open(mj)]
    done = [r for r in rows if r.get("event") == "request_done"]
    assert len(done) == 3
    kv_tok = eng._kv_bytes_per_token
    for r in done:
        # committed length x bytes/token, a host-math byte count
        assert r["kv_bytes_peak"] > 0
        assert r["kv_bytes_peak"] % kv_tok == 0
    saved = [r["prefix_bytes_saved"] for r in done
             if r.get("prefix_bytes_saved")]
    assert len(saved) == 2                    # both sharers hit
    assert all(s % kv_tok == 0 for s in saved)
    snaps = [r for r in rows if r.get("event") == "memory_snapshot"]
    assert snaps and all(r["source"] == "engine" for r in snaps)
    assert not [r for r in rows if r.get("event") == "memory_drift"]


def test_pinned_pane_leak_fires_drift_naming_component(model):
    """The injected leak of the acceptance criteria: a prefix pane still
    pinned at cadence (match without release — the pinned-forever bug)
    fires ``memory_drift`` naming ``prefix_store``."""
    cfg, params = model
    eng = DecodeEngine(cfg, params, n_slots=2, max_len=128,
                       warmup_prompt_cap=64,
                       kv_policy=KVCachePolicy(prefill_chunk=16,
                                               prefix_cache=True))
    eng.warmup()
    prompts = shared_prefix_prompts(cfg, 1)
    eng.submit(prompts[0],
               SamplingParams(max_new_tokens=2, ignore_eos=True))
    eng.run_until_idle()
    events = []
    eng.memory_ledger._emit = (
        lambda kind, **f: events.append((kind, f)))
    eng.memory_ledger.observe()               # healthy: pins transient
    assert not [k for k, _ in events if k == "memory_drift"]
    span, entry = eng.prefix_store.match(prompts[0], "base")  # pin, no rel
    assert span > 0 and entry is not None and entry.pins == 1
    eng.memory_ledger.observe()
    drifts = [f for k, f in events if k == "memory_drift"]
    assert len(drifts) == 1
    assert drifts[0]["component"] == "prefix_store"
    assert drifts[0]["reason"] == "pinned_orphan"
    assert drifts[0]["pinned_bytes"] == entry.nbytes
    eng.prefix_store.release(entry)           # fix the leak: drift stops
    events.clear()
    eng.memory_ledger.observe()
    assert not [k for k, _ in events if k == "memory_drift"]
    eng.shutdown()


def test_ledger_armed_zero_recompiles_zero_implicit_transfers(model):
    """With the ledger observing at EVERY tick (metrics_every=1) a
    serving burst still runs with zero implicit device->host transfers
    (the ledger is nbytes metadata math) and zero recompiles — the
    observatory must not perturb the engine's invariants."""
    from building_llm_from_scratch_tpu.analysis.runtime import (
        no_implicit_device_to_host,
    )

    cfg, params = model
    eng = DecodeEngine(cfg, params, n_slots=2, max_len=64,
                       metrics_every=1, watch_compiles=False)
    eng.warmup()
    handles = [eng.submit(np.array([3, 4 + i], np.int32),
                          SamplingParams(max_new_tokens=6,
                                         ignore_eos=True, seed=i))
               for i in range(3)]
    with no_implicit_device_to_host():
        eng.run_until_idle()
    for h in handles:
        h.result(timeout=10)
    assert eng.memory_ledger.n_snapshots >= eng.n_ticks >= 3
    assert eng.n_recompiles == 0
    # the scrape path is metadata-only too
    with no_implicit_device_to_host():
        eng.memory_ledger.snapshot()
        eng.memory_ledger.gauges()
    eng.shutdown()


# ---------------------------------------------------------------------------
# Trace + trainer + schema integration
# ---------------------------------------------------------------------------

def _run_traced_engine(model, mj):
    cfg, params = model
    sink = configure_metrics(mj)
    sink.write_header(test="memory_obs_trace")
    try:
        eng = DecodeEngine(cfg, params, n_slots=2, max_len=128,
                           warmup_prompt_cap=64, metrics_every=2,
                           kv_policy=KVCachePolicy(prefill_chunk=16,
                                                   prefix_cache=True))
        eng.warmup()
        for p in shared_prefix_prompts(cfg, 2):
            eng.submit(p, SamplingParams(max_new_tokens=4,
                                         ignore_eos=True, seed=1))
            eng.run_until_idle()
        eng.shutdown()
    finally:
        sink.close()
        configure_metrics(None)


def test_memory_counter_tracks_byte_deterministic(model, tmp_path):
    """Two identical runs -> byte-identical Perfetto memory counter
    tracks: the snapshot event carries only deterministic nbytes math,
    and the polled ``host_rss`` component stays OFF the device
    composition track."""
    from building_llm_from_scratch_tpu.obs.trace import (
        export_chrome_trace,
    )

    counters = []
    for tag in ("a", "b"):
        mj = str(tmp_path / f"{tag}.jsonl")
        _run_traced_engine(model, mj)
        tr = str(tmp_path / f"{tag}_trace.json")
        export_chrome_trace(mj, tr)
        evs = json.load(open(tr))["traceEvents"]
        counters.append([e["args"] for e in evs
                         if e.get("ph") == "C"
                         and e.get("name") == "memory (bytes)"])
    assert counters[0], "no memory counter samples in the trace"
    assert counters[0] == counters[1]
    assert all("host_rss" not in args for args in counters[0])
    assert all(args["slot_kv"] > 0 for args in counters[0])


def test_trainer_ledger_and_legacy_row_keys(tmp_path):
    """The trainer's ad-hoc HBM/RSS gauges now read FROM the ledger:
    cadence rows keep the historical ``host_rss_bytes`` key (renderer /
    plot compatibility) and ``memory_snapshot`` events with
    source=trainer carry params + optimizer state measured from the
    live train state."""
    from building_llm_from_scratch_tpu.data.pretrain import PretrainLoader
    from building_llm_from_scratch_tpu.data.tokenizers import ByteTokenizer
    from building_llm_from_scratch_tpu.training.trainer import Trainer

    cfg = tiny_cfg(ctx=32, vocab_size=256, eos_id=0, name="mem-train")
    tok = ByteTokenizer()
    datafile = tmp_path / "corpus.txt"
    datafile.write_text("memory ledger corpus " * 40)
    mj = str(tmp_path / "train_metrics.jsonl")
    sink = configure_metrics(mj)
    sink.write_header(test="memory_obs_trainer")
    try:
        loader = PretrainLoader(tok, batch_size=4,
                                max_length=cfg.context_length)
        trainer = Trainer(cfg, init_params(cfg, jax.random.PRNGKey(0)),
                          tok, loader, output_dir=str(tmp_path / "out"),
                          eval_freq=10**6, print_sample_iter=10**6,
                          save_ckpt_freq=10**6, warmup_steps=2,
                          log_every=2, show_progress=False)
        trainer.train_model([str(datafile)], 1)
        assert trainer.global_step >= 4
        state = trainer.state
        expected_params = (pytree_nbytes(state["trainable"])
                           + pytree_nbytes(state["frozen"]))
        expected_opt = pytree_nbytes(state["opt_state"])
    finally:
        sink.close()
        configure_metrics(None)
    rows = [json.loads(line) for line in open(mj)]
    cadence = [r for r in rows if r.get("type") == "metrics"
               and "host_rss_bytes" in r]
    assert cadence, "cadence rows lost the legacy host_rss_bytes key"
    snaps = [r for r in rows if r.get("event") == "memory_snapshot"
             and r.get("source") == "trainer"]
    assert snaps
    last = snaps[-1]["components"]
    assert last["model_params"] == expected_params
    assert last["optimizer_state"] == expected_opt > 0
    assert last["host_rss"] > 0
    assert not [r for r in rows if r.get("event") == "memory_drift"]


def test_schema_v11_registers_memory_events():
    from building_llm_from_scratch_tpu.obs import schema as S

    assert S.SCHEMA_VERSION >= 11   # v11 added the memory events; later
    # versions (v12 paged-KV page_* events, ...) must keep them registered
    assert "memory_drift" in S.INCIDENT_EVENTS
    assert "memory_pressure" in S.INCIDENT_EVENTS
    # snapshots are counter-track cadence data, not incidents
    assert "memory_snapshot" not in S.INCIDENT_EVENTS
    assert S.validate_event("memory_snapshot",
                            {"source": "engine",
                             "components": {"slot_kv": 1},
                             "total_bytes": 1, "device_bytes": 1}) == []
    assert S.validate_event("memory_drift",
                            {"component": "prefix_store",
                             "reason": "pinned_orphan",
                             "pinned_bytes": 9}) == []
    assert S.validate_event("memory_pressure",
                            {"headroom_bytes": 5, "capacity_bytes": 100,
                             "used_frac": 0.95,
                             "components": {"kv": 95}}) == []
    # missing required fields are caught
    assert S.validate_event("memory_drift", {"component": "x"})
    # request_done accepts the new attribution fields
    spec = S.EVENTS["request_done"]
    assert "kv_bytes_peak" in spec.optional
    assert "prefix_bytes_saved" in spec.optional
