"""Sharded checkpoint format (training/checkpoint.py sharded-v1).

SURVEY.md §5 target: sharded, resumable checkpoints — each process writes
its addressable shards, restore streams shards onto target shardings (which
may differ from save-time), peak memory bounded by one shard. The reference
has neither resume nor sharding (train.py:244-249 gathers everything).
Runs on the 8-device CPU mesh.
"""

import glob
import json
import os

import jax
import jax.numpy as jnp
import numpy as np

from building_llm_from_scratch_tpu.configs import ModelConfig
from building_llm_from_scratch_tpu.models import init_params
from building_llm_from_scratch_tpu.parallel import build_mesh_plan
from building_llm_from_scratch_tpu.training import (
    build_optimizer,
    init_train_state,
    load_checkpoint,
    save_checkpoint,
    save_checkpoint_gathered,
)


def _small_cfg():
    return ModelConfig(
        name="t", vocab_size=128, context_length=64, emb_dim=64, n_heads=4,
        n_layers=2, hidden_dim=128, n_kv_groups=4, norm="layernorm",
        positional="learned", activation="gelu", drop_rate=0.0, dtype="fp32")


def _state(plan=None):
    cfg = _small_cfg()
    params = init_params(cfg, jax.random.PRNGKey(0))
    opt = build_optimizer(total_steps=10)
    state = init_train_state(params, opt, jax.random.PRNGKey(1))
    if plan is not None:
        state = plan.shard_state(state)
    return state


def _assert_tree_equal(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_sharded_roundtrip_fsdp(tmp_path):
    plan = build_mesh_plan("fsdp")
    state = _state(plan)
    ck = str(tmp_path / "ck")
    save_checkpoint(ck, state, extra_metadata={"global_step": 7})
    manifest = json.load(open(os.path.join(ck, "manifest.json")))
    assert manifest["format"] == "sharded-v1"
    assert manifest["metadata"]["global_step"] == 7

    template = _state(plan)
    restored = load_checkpoint(ck, template,
                               shardings=jax.tree_util.tree_map(
                                   lambda x: x.sharding, template))
    _assert_tree_equal(state, restored)
    # restored leaves keep the target sharding
    for t, r in zip(jax.tree_util.tree_leaves(template),
                    jax.tree_util.tree_leaves(restored)):
        assert r.sharding.is_equivalent_to(t.sharding, t.ndim)


def test_sharded_leaf_files_are_shards_not_full(tmp_path):
    """fsdp-sharded leaves must be written as multiple per-shard files,
    each smaller than the full leaf; replicated leaves exactly once."""
    plan = build_mesh_plan("fsdp")
    state = _state(plan)
    ck = str(tmp_path / "ck")
    save_checkpoint(ck, state)
    manifest = json.load(open(os.path.join(ck, "manifest.json")))
    n_multi = 0
    for meta in manifest["leaves"]:
        nbytes = int(np.prod(meta["shape"]) or 1)
        files = glob.glob(os.path.join(ck, f"leaf_{meta['index']:05d}.*"))
        assert len(files) == len(meta["shards"])
        if len(meta["shards"]) > 1:
            n_multi += 1
            for sh in meta["shards"]:
                box = np.prod([b[1] - b[0] for b in sh["index"]])
                assert box < nbytes  # a real shard, not a full copy
    assert n_multi > 0  # fsdp actually sharded something


def test_sharded_restore_onto_different_sharding(tmp_path):
    """Save under fsdp, restore under dp (replicated params) — values
    must assemble correctly from shard files."""
    fsdp = build_mesh_plan("fsdp")
    state = _state(fsdp)
    ck = str(tmp_path / "ck")
    save_checkpoint(ck, state)

    dp = build_mesh_plan("dp")
    template = _state(dp)
    restored = load_checkpoint(ck, template,
                               shardings=jax.tree_util.tree_map(
                                   lambda x: x.sharding, template))
    _assert_tree_equal(state, restored)


def test_sharded_restore_without_shardings(tmp_path):
    plan = build_mesh_plan("fsdp")
    state = _state(plan)
    ck = str(tmp_path / "ck")
    save_checkpoint(ck, state)
    restored = load_checkpoint(ck, _state())
    _assert_tree_equal(state, restored)


def test_gathered_format_backward_compat(tmp_path):
    """A round-3 (gathered) checkpoint still loads."""
    state = _state()
    ck = str(tmp_path / "ck")
    save_checkpoint_gathered(ck, state, extra_metadata={"global_step": 3})
    manifest = json.load(open(os.path.join(ck, "manifest.json")))
    assert "format" not in manifest
    restored = load_checkpoint(ck, _state())
    _assert_tree_equal(state, restored)


def test_zero1_opt_state_sharding_roundtrip(tmp_path):
    """zero1: only optimizer state is sharded; save + restore onto the
    same plan keeps values and placements."""
    plan = build_mesh_plan("zero1")
    state = _state(plan)
    ck = str(tmp_path / "ck")
    save_checkpoint(ck, state)
    template = _state(plan)
    restored = load_checkpoint(ck, template,
                               shardings=jax.tree_util.tree_map(
                                   lambda x: x.sharding, template))
    _assert_tree_equal(state, restored)
